# Entry points for builders and reviewers.  `make check` is the one
# gate: lint + static verifier + tier-1 tests (see scripts/check.sh).

.PHONY: lint verify test check

lint:
	bash scripts/lint.sh

verify:
	JAX_PLATFORMS=cpu python -m gol_tpu.analysis

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider \
	    -p no:xdist -p no:randomly

check:
	bash scripts/check.sh
