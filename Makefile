# Entry points for builders and reviewers.  `make check` is the one
# gate: lint + static verifier + telemetry smoke + stats smoke +
# resilience drill + batch smoke + sparse smoke + obs smoke + reshard
# smoke + halo smoke + chaos smoke + serve smoke + elastic smoke +
# lockcheck + trace smoke + tier-1 tests + postmortem smoke + fleet
# smoke + ooc smoke (see
# scripts/check.sh).

.PHONY: lint verify lockcheck test check telemetry-smoke stats-smoke \
	resilience-drill batch-smoke batchbench sparse-smoke sparsebench \
	obs-smoke ledger-check reshard-smoke halo-smoke halobench-sweep \
	chaos-smoke chaos-matrix serve-smoke servebench elastic-smoke \
	trace-smoke postmortem-smoke fleet-smoke ooc-smoke oocbench

lint:
	bash scripts/lint.sh

verify:
	JAX_PLATFORMS=cpu python -m gol_tpu.analysis

# Host-plane concurrency passes only (lockcheck + spmdcheck): pure-AST,
# never initializes a jax backend, so it is check.sh's cheapest stage
# (docs/ANALYSIS.md "The concurrency matrix").
lockcheck:
	python -m gol_tpu.analysis --concurrency

test:
	JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
	    --continue-on-collection-errors -p no:cacheprovider \
	    -p no:xdist -p no:randomly

# Tiny run with --telemetry, then `summarize` must schema-validate the
# stream and exit 0 (docs/OBSERVABILITY.md).
telemetry-smoke:
	@tdir=$$(mktemp -d); trap 'rm -rf "$$tdir"' EXIT; \
	JAX_PLATFORMS=cpu python -m gol_tpu 0 64 8 512 0 \
	    --telemetry "$$tdir" --run-id smoke > /dev/null && \
	JAX_PLATFORMS=cpu python -m gol_tpu.telemetry summarize "$$tdir"

# Tiny CPU run with --stats --telemetry; `summarize` must exit 0 and
# render the per-chunk population (stats) table.
stats-smoke:
	@sdir=$$(mktemp -d); trap 'rm -rf "$$sdir"' EXIT; \
	JAX_PLATFORMS=cpu python -m gol_tpu 6 64 8 512 0 \
	    --telemetry "$$sdir" --run-id statsmoke --stats > /dev/null && \
	JAX_PLATFORMS=cpu python -m gol_tpu.telemetry summarize "$$sdir" \
	    | grep "stats     gen"

# Supervised preempt/auto-resume smoke: SIGTERM a supervised child once
# and assert the resumed run's final-grid hash matches an uninterrupted
# run (docs/RESILIENCE.md; the kill-9 chaos matrix is `-m slow`).
resilience-drill:
	JAX_PLATFORMS=cpu python scripts/resilience_drill.py

# Batched multi-world smoke (docs/BATCHING.md): mixed-size batch
# bit-equal to sequential single-world runs, and a second process hits
# the persistent compilation cache (zero new entries).
batch-smoke:
	JAX_PLATFORMS=cpu python scripts/batch_smoke.py

# Per-world-throughput-vs-B amortization curve -> BATCH_r{N}.json
# (CPU: curve shape; the TPU headline is --size 256 --iters 1024).
batchbench:
	python benchmarks/batchbench.py --round 6

# Activity-gated smoke (docs/SPARSE.md): glider-gun run bit-equal to
# the dense bitpack tier while skipping most tile-generations.
sparse-smoke:
	JAX_PLATFORMS=cpu python scripts/sparse_smoke.py

# Dense-vs-gated speedup curve over live-cell fraction ->
# SPARSE_r{N}.json (CPU: curve shape; the TPU headline is
# --size 65536 --iters 256).
sparsebench:
	python benchmarks/sparsebench.py --tile 128 --capacity 0.125 --round 7

# Continuous-observability smoke (docs/OBSERVABILITY.md): live run with
# --metrics-port scraped mid-run + reconciled with the JSONL, v6 spans
# on every chunk, summarize's span table, and the ledger gate.
obs-smoke:
	JAX_PLATFORMS=cpu python scripts/obs_smoke.py

# The cross-run perf regression gate alone: newest record per config
# fingerprint vs the best on the committed PERF_LEDGER.jsonl.
ledger-check:
	JAX_PLATFORMS=cpu python -m gol_tpu.telemetry ledger check \
	    --ledger PERF_LEDGER.jsonl

# Elastic-mesh smoke (docs/RESILIENCE.md): a 2-D-block sharded snapshot
# resumed on a 1-D ring bit-equal to a straight run, with a
# non-identity plan and the schema-v7 reshard event stamped.
reshard-smoke:
	JAX_PLATFORMS=cpu python scripts/reshard_smoke.py

# Pipelined-halo smoke (docs/DESIGN.md): 512² glider, pipeline k=4 on a
# 1-D mesh bit-equal to explicit k=1, v8 halo blocks on every chunk.
halo-smoke:
	JAX_PLATFORMS=cpu python scripts/halo_smoke.py

# The k-vs-MFU depth sweep (HALO_r07.json's command; curve shape only
# on CPU — the TPU headline geometry is pinned in the artifact's note).
halobench-sweep:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
	    python -m gol_tpu.utils.halobench 1024 16 1d:4 \
	    dense,bitpack,pallas --halo-depth-sweep 1,2,4,8,16

# Unified-fault-plane smoke (docs/RESILIENCE.md): one plan file driving
# bit-flip + torn-write + ENOSPC through a small guarded batch run —
# detected, contained, recovered byte-equal, v9 records on the stream.
chaos-smoke:
	JAX_PLATFORMS=cpu python scripts/chaos_smoke.py

# The full committed scenario × tier × mesh grid (minutes on CPU; also
# `pytest -m slow tests/test_chaos_matrix.py`).
chaos-matrix:
	JAX_PLATFORMS=cpu python -m gol_tpu.resilience chaos \
	    --plan tests/data/fault_plans/chaos_matrix.json

# Serving-tier smoke (docs/SERVING.md): a supervised server crashed
# mid-batch completes every accepted request exactly once from the
# journal, byte-equal to the sequential oracle; then a SIGTERM drain.
serve-smoke:
	JAX_PLATFORMS=cpu python scripts/serve_smoke.py

# Live-elasticity smoke (docs/RESILIENCE.md "Live elasticity"): a
# --mesh-devices server loses a device mid-serve, live-reshards at the
# chunk boundary, regrows on restore, and hedges a straggler — every
# request byte-equal, no restart, v11 verdicts on the stream.  The
# script forces its own 8-device virtual CPU ring.
elastic-smoke:
	python scripts/elastic_smoke.py

# Request-tracing smoke (docs/OBSERVABILITY.md "Request tracing &
# SLOs"): the committed v12 fixture round-trips through `telemetry
# trace --perfetto` and the export validates against the committed
# docs/schemas/perfetto_trace.schema.json contract.
trace-smoke:
	JAX_PLATFORMS=cpu python -m gol_tpu.telemetry trace \
	    tests/data/telemetry_v12 --perfetto /tmp/_trace_export.json
	python scripts/validate_trace_export.py /tmp/_trace_export.json \
	    docs/schemas/perfetto_trace.schema.json

# Black-box postmortem smoke (docs/OBSERVABILITY.md "Black box &
# postmortems"): crash a REAL server via the fault plane, validate the
# *.blackbox.jsonl dump, run `telemetry postmortem` and assert the
# verdict names the open request; the supervised replay then keeps the
# verdict's promise; a graceful drain leaves no dump; a future-schema
# dump refuses with exit 2.
postmortem-smoke:
	JAX_PLATFORMS=cpu python scripts/postmortem_smoke.py

# Serving-fleet smoke (docs/SERVING.md "The fleet"): 3 supervised
# replicas behind the replicated front tier, kill -9 one mid-flight —
# journaled handoff to survivors, ownership fencing on the restart,
# every request exactly-once and byte-equal, /readyz degraded and
# recovered, graceful drain exit 0.
fleet-smoke:
	JAX_PLATFORMS=cpu python scripts/fleet_smoke.py

# Open-loop serving load curve -> SERVE_r{N}.json (CPU: admission /
# queue dynamics; the TPU headline command is pinned in the note).
servebench:
	JAX_PLATFORMS=cpu python benchmarks/servebench.py \
	    --rates 4,16,64,400,2000 --requests 48 --generations 24 --round 1

# Out-of-core streaming smoke (docs/STREAMING.md): a Gosper gun on a
# board >=4x the rotation's device footprint, streamed through
# --engine ooc — bit-equal to the in-core bitpack tier, dead bands
# skipped, v15 ooc blocks with measured overlap_fraction on every chunk.
ooc-smoke:
	JAX_PLATFORMS=cpu python scripts/ooc_smoke.py

# Streaming-efficiency curve over board/budget ratios -> OOC_r{N}.json
# (CPU: curve shape; the TPU headline is --height 1048576
# --width 1048576 --budget-mb 4096 --iters 64).
oocbench:
	python benchmarks/oocbench.py --round 1

check:
	bash scripts/check.sh
