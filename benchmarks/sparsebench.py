"""sparsebench: dense-vs-gated speedup over live-cell fraction
(SPARSE_r{N}.json).

The activity tier's reason to exist: every dense tier pays O(area) per
generation regardless of how much of the board is alive, while real
Life workloads (gliders, guns, methuselahs in huge arenas) are ~all
dead space.  This harness measures exactly that curve:

- **scenarios** sweep live-cell fraction downward: random soups at
  decreasing seed densities (high-density soups stay chaotic — the
  gated tier honestly loses there to its own gating overhead and
  fallbacks) down to single-object seeds from the sparse pattern zoo
  (:data:`gol_tpu.models.patterns.SPARSE_OBJECTS`) whose live fraction
  at a big extent is ~1e-4;
- for each scenario both programs are timed under the same discipline
  (best-of-N, fresh donated buffers, ``force_ready`` fenced): the dense
  bitpack tier (:func:`gol_tpu.ops.bitlife.evolve_dense_io` — the
  repo's fastest non-Pallas O(area) engine, and the tier the acceptance
  pin compares against) vs the activity worklist
  (:func:`gol_tpu.sparse.engine.evolve_gated_packed` /
  ``_dense``, matching the board's word alignment);
- ``speedup`` is the headline: ``dense_wall / gated_wall`` per
  scenario, alongside the run's measured active fraction and fallback
  count so a reader can see *why* a row wins or loses.

On the CPU backend this captures curve *shape* only (like every
cpu_mesh artifact — the absolute walls mean nothing); the TPU headline
capture for the ≥10× acceptance number on a 65536² board at <1% live is
pinned in the note::

    python benchmarks/sparsebench.py --size 65536 --iters 256 \
        --round 7   # TPU

Usage::

    python benchmarks/sparsebench.py --round 7            # defaults
    python benchmarks/sparsebench.py --size 2048 --iters 64
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Optional, Sequence

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct-script invocation from anywhere
    sys.path.insert(0, str(REPO))


def scenarios(size: int):
    """(name, board-factory) pairs, densest first."""
    import numpy as np

    from gol_tpu.models import patterns

    def soup(density, seed=42):
        def make():
            rng = np.random.default_rng(seed)
            return (rng.random((size, size)) < density).astype(np.uint8)

        return make

    def obj(name):
        # Center-ish offset: nothing special about it (torus), but it
        # keeps the object clear of the seam visualizations in dumps.
        return lambda: patterns.init_sparse_world(
            name, size, size, (size // 3, size // 3)
        )

    rows = [
        ("soup_0.100", soup(0.100)),
        ("soup_0.030", soup(0.030)),
        ("soup_0.010", soup(0.010)),
        ("soup_0.003", soup(0.003)),
        ("soup_0.001", soup(0.001)),
        ("acorn", obj("acorn")),
        ("gosper_gun", obj("gosper_gun")),
        ("lwss", obj("lwss")),
    ]
    return rows


def measure(name, make_board, size: int, iters: int, tile: int,
            capacity_frac: float, repeats: int) -> dict:
    import jax
    import numpy as np

    from gol_tpu.ops import bitlife
    from gol_tpu.sparse import engine as sparse_engine
    from gol_tpu.sparse import mask as sparse_mask
    from gol_tpu.utils.timing import time_best

    board_np = make_board()
    packed = size % bitlife.BITS == 0 and tile % bitlife.BITS == 0
    th, tw = sparse_mask.grid_shape(size, size, tile)
    capacity = sparse_engine.default_capacity(th, tw, capacity_frac)

    def fresh_board():
        return jax.device_put(board_np)

    dense_wall = time_best(
        lambda b: bitlife.evolve_dense_io(b, iters), fresh_board,
        repeats=repeats,
    )

    gated = (
        sparse_engine.evolve_gated_packed
        if packed
        else sparse_engine.evolve_gated_dense
    )

    def fresh_pair():
        return (
            jax.device_put(board_np),
            sparse_mask.full_mask(th, tw),
        )

    def run_gated(args):
        b, m = args
        out, _, act = gated(b, m, iters, tile, capacity)
        return out, act

    gated_wall = time_best(run_gated, fresh_pair, repeats=repeats)

    # One more (untimed) run for the bit-equality receipt + counters.
    ref = np.asarray(bitlife.evolve_dense_io(fresh_board(), iters))
    out, act = run_gated(fresh_pair())
    if not np.array_equal(np.asarray(out), ref):
        raise AssertionError(
            f"scenario {name!r}: gated result diverges from dense — "
            "refusing to write a benchmark row for a wrong program"
        )
    tile_gens = th * tw * iters
    computed = int(act["computed_tile_gens"])
    return dict(
        scenario=name,
        live_fraction_t0=float(board_np.mean()),
        live_fraction_final=float(ref.mean()),
        repr="packed" if packed else "dense",
        tile=tile,
        capacity=capacity,
        dense_wall_s=dense_wall,
        gated_wall_s=gated_wall,
        speedup=dense_wall / gated_wall if gated_wall > 0 else None,
        active_fraction=int(act["active_tile_gens"]) / tile_gens,
        computed_fraction=computed / tile_gens,
        fallback_gens=int(act["fallback_gens"]),
        bit_equal=True,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="sparsebench", description=__doc__)
    ap.add_argument("--size", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=256)
    ap.add_argument("--tile", type=int, default=0, metavar="T")
    ap.add_argument("--capacity", type=float, default=0.25, metavar="FRAC")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--out", default=None)
    ns = ap.parse_args(list(sys.argv[1:] if argv is None else argv))

    import jax

    from gol_tpu.sparse import mask as sparse_mask

    tile = ns.tile or sparse_mask.pick_tile(ns.size, ns.size, packed=True)
    rows = [
        measure(name, make, ns.size, ns.iters, tile, ns.capacity,
                ns.repeats)
        for name, make in scenarios(ns.size)
    ]
    from gol_tpu.telemetry import ledger as ledger_mod

    payload = dict(
        # Common artifact header (docs/OBSERVABILITY.md): the perf
        # ledger routes ingestion by header.tool, no filename sniffing.
        header=ledger_mod.artifact_header("sparsebench"),
        note=(
            "dense-vs-gated speedup curve over live-cell fraction "
            "(docs/SPARSE.md). dense_wall_s = best-of-N fenced wall of "
            "the bitpack tier's compiled O(area) loop; gated_wall_s = "
            "the activity worklist on the same board from the all-ones "
            "mask; speedup = dense/gated, growing as the live fraction "
            "drops (dense soups honestly lose to gating overhead + "
            "fallbacks). Every row is written only after a bit-equality "
            "check of the two final grids. CPU-backend captures are "
            "curve shape only; the TPU headline (>=10x at <1% live) is "
            "--size 65536 --iters 256."
        ),
        backend=jax.default_backend(),
        size=ns.size,
        iters=ns.iters,
        tile=tile,
        rows=rows,
        command=(
            f"python benchmarks/sparsebench.py --size {ns.size} "
            f"--iters {ns.iters} --tile {tile} "
            f"--capacity {ns.capacity} --round {ns.round}"
        ),
    )
    out = ns.out or str(REPO / f"SPARSE_r{ns.round:02d}.json")
    pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")
    for row in rows:
        print(
            f"  {row['scenario']:>11}  live {row['live_fraction_t0']:.4f}"
            f"  dense {row['dense_wall_s']:.4f}s  gated "
            f"{row['gated_wall_s']:.4f}s  x{row['speedup']:.2f}"
            f"  (active {100 * row['active_fraction']:.1f}%"
            + (f", fb={row['fallback_gens']}" if row["fallback_gens"]
               else "")
            + ")"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
