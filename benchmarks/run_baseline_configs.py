"""Run the BASELINE.md target-config table and emit one JSON line per config.

BASELINE.md defines five working-target configurations (the reference
publishes no numbers of its own).  This runner executes each one scaled to
the hardware it finds — the full sizes on a real chip, proportionally
smaller ones via ``--scale`` for quick checks — and reports correctness
and/or throughput per config:

1. 256² × 100, single shard: bit-exact vs the NumPy oracle.
2. 4096² × 1000, 4-way row blocks: sharded result == single-device result.
3. 16384² × 10,240 generations on TPU at full scale (shorter loops when
   scaled down or on CPU): headline cell-updates/sec/chip, best engine.
4. weak scaling: per-chip efficiency across the visible device counts
   (the v5e-256 pod point requires a pod; the same harness runs there
   unchanged — see gol_tpu/utils/scalebench.py).
5. 3-D Life (stretch): fused Pallas kernel throughput.

Usage: ``python benchmarks/run_baseline_configs.py [--scale N]``
(scale divides the linear sizes by N; step counts shrink likewise).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# Runnable as a plain script: python puts benchmarks/ (not the repo root)
# on sys.path, so gol_tpu and tests.oracle would not import (ADVICE r1).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _force(x):
    from gol_tpu.utils.timing import force_ready

    force_ready(x)


def _emit(row):
    print(json.dumps(row), flush=True)


def config1(scale: int):
    import jax.numpy as jnp

    from gol_tpu.ops import stencil
    from tests.oracle import run_torus

    size, steps = max(64, 256 // scale), max(10, 100 // scale)
    rng = np.random.default_rng(0)
    board = (rng.random((size, size)) < 0.35).astype(np.uint8)
    got = np.asarray(stencil.run(jnp.asarray(board), steps))
    ok = bool((got == run_torus(board, steps)).all())
    _emit({"config": 1, "size": size, "steps": steps, "oracle_exact": ok})
    return ok


def config2(scale: int):
    import jax.numpy as jnp

    from gol_tpu.ops import stencil
    from gol_tpu.parallel import mesh as mesh_mod, sharded

    size, steps = max(128, 4096 // scale), max(20, 1000 // scale)
    n = min(4, len(__import__("jax").devices()))
    mesh = mesh_mod.make_mesh_1d(n)
    rng = np.random.default_rng(1)
    board = (rng.random((size, size)) < 0.35).astype(np.uint8)
    got = np.asarray(sharded.evolve_sharded(jnp.asarray(board), steps, mesh))
    ref = np.asarray(stencil.run(jnp.asarray(board), steps))
    ok = bool((got == ref).all())
    _emit(
        {"config": 2, "size": size, "steps": steps, "ring": n,
         "sharded_equals_single": ok}
    )
    return ok


def config3(scale: int):
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import pallas_bitlife
    from gol_tpu.ops import bitlife

    on_tpu = jax.devices()[0].platform == "tpu"
    size = max(1024, 16384 // scale)
    # A full-scale TPU run uses config 3's own 10k-generation count: one
    # tunneled program invocation costs ~130 ms of RPC, which at 1024
    # steps was still ~46% of wall time and halved the reported rate.
    # Scaled-down / CPU quick checks have no tunnel to amortize and keep
    # the short loop.
    steps = max(32, (10240 if on_tpu and scale == 1 else 1024) // scale)
    rng = np.random.default_rng(2)
    board = jnp.asarray((rng.random((size, size)) < 0.35).astype(np.uint8))
    evolve = (
        (lambda b: pallas_bitlife.evolve(b, steps, 512))
        if on_tpu
        else (lambda b: bitlife.evolve_dense_io(b, steps))
    )
    work = jnp.array(board, copy=True)
    _force(evolve(work))  # warm
    best = float("inf")
    for _ in range(3):
        work = jnp.array(board, copy=True)
        _force(work)
        t0 = time.perf_counter()
        _force(evolve(work))
        best = min(best, time.perf_counter() - t0)
    rate = size * size * steps / best
    _emit(
        {"config": 3, "size": size, "steps": steps,
         "engine": "pallas_bitpack" if on_tpu else "bitpack",
         "cell_updates_per_sec_per_chip": rate,
         "per_chip_target": 1e11 / 256,
         "vs_target": rate / (1e11 / 256)}
    )
    return True


def config4(scale: int):
    from gol_tpu.utils import scalebench

    size = max(128, 1024 // scale)
    rows = scalebench.measure_weak_scaling(size, steps=max(8, 64 // scale))
    _emit({"config": 4, "size_per_chip": size, "rows": rows})
    return True


def config5(scale: int):
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import bitlife3d, pallas_bitlife3d

    on_tpu = jax.devices()[0].platform == "tpu"
    size = max(128, 1024 // scale) if on_tpu else 64
    steps = max(8, 64 // scale)
    rng = np.random.default_rng(3)
    vol = jnp.asarray((rng.random((size,) * 3) < 0.3).astype(np.uint8))
    evolve = (
        (lambda v: pallas_bitlife3d.evolve3d(v, steps))
        if on_tpu
        else (lambda v: bitlife3d.evolve3d_dense_io(v, steps))
    )
    work = jnp.array(vol, copy=True)
    _force(evolve(work))
    best = float("inf")
    for _ in range(2):
        work = jnp.array(vol, copy=True)
        _force(work)
        t0 = time.perf_counter()
        _force(evolve(work))
        best = min(best, time.perf_counter() - t0)
    _emit(
        {"config": 5, "size": size, "steps": steps,
         # evolve3d auto-selects: fused Pallas when the plane window fits
         # scoped VMEM, else the XLA packed path (e.g. at 1024³).
         "engine": "evolve3d(auto)" if on_tpu else "bitpack3d",
         "cell_updates_per_sec_per_chip": size**3 * steps / best}
    )
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=int, default=1)
    ap.add_argument(
        "--configs", default="1,2,3,4,5",
        help="comma-separated subset of configs to run",
    )
    ns = ap.parse_args(argv)
    fns = {"1": config1, "2": config2, "3": config3, "4": config4,
           "5": config5}
    ok = True
    for key in ns.configs.split(","):
        ok = fns[key.strip()](ns.scale) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
