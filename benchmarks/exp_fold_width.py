"""Which axis of "small board" costs the rate: row width or row count?

exp_folded_gap found the bare torus kernel at the fold=4 layout's
[4096, 128-word] geometry runs at ~1.27e12 cell-updates/s (43.5% MFU)
vs the 16384^2 flagship's ~1.98e12 — so most of the folded pod-shard
gap is the *geometry*, not the ring.  A 16384x1024 shard can be folded
deeper than the minimal fold=4: fold=8 gives [2048, 256w], fold=16
gives [1024, 512w] — the flagship's exact row width.  This script
measures the bare torus kernel (no ring, no groups — pure geometry)
at each equivalent board shape, same-session with the 16384^2
reference, to find whether deeper folding can recover the issue rate.

Usage: ``python benchmarks/exp_fold_width.py [steps] [reps]`` on TPU.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

CELLS = 16384 * 1024  # the pod shard's cell count


def main() -> None:
    import jax.numpy as jnp

    from gol_tpu.ops import pallas_bitlife
    from gol_tpu.utils.timing import force_ready

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    rng = np.random.default_rng(2)
    shapes = {
        "fold4_equiv_4096x4096": (4096, 4096),
        "fold8_equiv_2048x8192": (2048, 8192),
        "fold16_equiv_1024x16384": (1024, 16384),
        "fold32_equiv_512x32768": (512, 32768),
        "flagship_16384sq_ref": (16384, 16384),
    }
    boards, best = {}, {}
    for name, shape in shapes.items():
        esteps = steps if shape[0] * shape[1] == CELLS else steps // 16
        fn = lambda b, n=esteps: pallas_bitlife.evolve(b, n)
        b = jnp.asarray((rng.random(shape) < 0.35).astype(np.uint8))
        t0 = time.perf_counter()
        b = fn(b)
        force_ready(b)
        print(f"# warm {name}: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr, flush=True)
        boards[name] = (b, fn, esteps, shape)
        best[name] = float("inf")

    for _ in range(reps):
        for name in shapes:
            b, fn, esteps, shape = boards[name]
            t0 = time.perf_counter()
            b = fn(b)
            force_ready(b)
            best[name] = min(best[name], time.perf_counter() - t0)
            boards[name] = (b, fn, esteps, shape)

    for name in shapes:
        _, _, esteps, shape = boards[name]
        rate = shape[0] * shape[1] * esteps / best[name]
        print(json.dumps({
            "config": name,
            "shape": list(shape),
            "cells_per_s": float(f"{rate:.4g}"),
            "best_s": round(best[name], 4),
            "steps": esteps,
        }), flush=True)


if __name__ == "__main__":
    main()
