"""Capture the per-round north-star metric artifacts (VERDICT r3 #4).

BASELINE.json names three north-star metrics; BENCH_r{N}.json pins only
the first.  This driver captures the other two into committed artifacts:

- ``HALO_r{N}.json`` — halobench's exchange-vs-compute attribution
  (seconds/gen for exchange-only, full step, pure stencil, exposed
  exchange), for the flagship engine's serial AND overlap forms on the
  chip's 1-ring, plus the 8-device CPU mesh's multi-device attribution
  (curve *shape* only — absolute CPU numbers are not chip numbers).
- ``SCALE_r{N}.json`` — scalebench's weak-scaling efficiency curve on
  the 8-device CPU mesh plus the real-chip 1-device throughput point.

Usage: ``python benchmarks/capture_artifacts.py <round>`` with the TPU
visible (the CPU-mesh parts run in subprocesses pinned to the virtual
CPU mesh; the TPU parts run in-process).  Each artifact records the
command that produced every section so the judge can re-run any line.
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent

CPU_ENV = {
    **os.environ,
    "PYTHONPATH": str(REPO),
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}


def _cpu_json(args: list) -> dict:
    out = subprocess.run(
        [sys.executable, "-m", *args],
        env=CPU_ENV,
        capture_output=True,
        text=True,
        check=True,
        cwd=REPO,
    ).stdout
    payload = json.loads(out.strip().splitlines()[-1])
    payload["command"] = "JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 python -m " + " ".join(args)
    return payload


def _cpu_json_2proc(
    args: list, devices_per_proc: int = 4, timeout_per_worker: float = 900.0
) -> dict:
    """Run a module across two real coordinator-connected OS processes
    (Gloo over localhost, 2×4 = 8 global CPU devices); process 0 prints
    the report."""
    import socket
    import time

    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    env = {
        **CPU_ENV,
        "XLA_FLAGS": (
            f"--xla_force_host_platform_device_count={devices_per_proc}"
        ),
    }
    trio = ["--coordinator", coord, "--num-processes", "2"]
    procs = []
    deadlines = []
    for i in range(2):
        procs.append(
            subprocess.Popen(
                [sys.executable, "-m", *args, *trio, "--process-id", str(i)],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
                cwd=REPO,
            )
        )
        # Each worker gets the full budget from its own start — not one
        # shared window the slower worker's wait eats into.
        deadlines.append(time.monotonic() + timeout_per_worker)
    # Drain both processes concurrently (a thread per pipe pair, so
    # neither can deadlock on a full pipe) and fail FAST on the first
    # nonzero exit: if one worker dies during coordinator startup the
    # other blocks in jax.distributed forever, and a sequential
    # communicate() would time out 900 s later with the dead worker's
    # stderr (the actual root cause) never surfaced.
    import concurrent.futures as cf

    def _drain(p):
        out, err = p.communicate()
        return p.returncode, out, err

    outs = [None, None]
    with cf.ThreadPoolExecutor(max_workers=2) as ex:
        futs = {ex.submit(_drain, p): i for i, p in enumerate(procs)}
        try:
            pending = set(futs)
            while pending:
                now = time.monotonic()
                expired = [
                    futs[f] for f in pending if now >= deadlines[futs[f]]
                ]
                if expired:
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    # The kills EOF the pipes, so every pending _drain
                    # returns promptly; join them to recover what each
                    # worker managed to say before dying — the drained
                    # output IS the diagnostic, never discard it.
                    drained = {i: f.result() for f, i in futs.items()}
                    raise RuntimeError(
                        f"2-process worker(s) {expired} exceeded "
                        f"{timeout_per_worker:.0f}s\n"
                        + "\n".join(
                            f"worker {i}: rc={rc}\nstdout:{out}\nstderr:{err}"
                            for i, (rc, out, err) in sorted(drained.items())
                        )
                    )
                wait_s = min(deadlines[futs[f]] for f in pending) - now
                done, pending = cf.wait(
                    pending, timeout=max(wait_s, 0.0),
                    return_when=cf.FIRST_COMPLETED,
                )
                for fut in done:
                    i = futs[fut]
                    rc, out, err = fut.result()
                    outs[i] = (rc, out, err)
                    if rc != 0:
                        raise RuntimeError(
                            f"2-process worker {i} failed rc={rc}\n"
                            f"stdout:{out}\nstderr:{err}"
                        )
        finally:
            # Killing the survivors EOFs their pipes, so the remaining
            # _drain threads (and the executor shutdown) return promptly.
            for p in procs:
                if p.poll() is None:
                    p.kill()
    payload = json.loads(outs[0][1].strip().splitlines()[-1])
    payload["command"] = (
        "2 processes x "
        f"{devices_per_proc} CPU devices: JAX_PLATFORMS=cpu "
        f"XLA_FLAGS=--xla_force_host_platform_device_count={devices_per_proc} "
        "python -m " + " ".join(args)
        + " --coordinator HOST:PORT --num-processes 2 --process-id {0,1}"
    )
    return payload


def main() -> None:
    rnd = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"

    halo = {"note": (
        "seconds per generation. exchange_s = the ppermute exchange loop "
        "alone (received halos folded into boundary faces/accumulators "
        "only — O(boundary) anti-DCE, r5; 3-D sections ship the fused "
        "engine's own quanta: one packed band plane + one packed ghost "
        "word column per side per generation, a tight upper bound on "
        "its per-generation wire); step_s = full sharded program; "
        "stencil_s = single-device compute ceiling; exposed_exchange_s "
        "= step - stencil (what latency hiding can win). TPU sections "
        "are real-chip; every per-generation column still carries "
        "~overhead/steps of tunnel cost (common-mode across columns, "
        "cancelling in the subtraction; see BASELINE.md r5 fits). "
        "cpu_mesh sections are 8-virtual-device curve shape only."
    )}
    scale = {"note": (
        "weak scaling: fixed size_per_chip^2 cells per device; 1-D ring "
        "or the 2-D pod decomposition (near-square mesh, the config-3 "
        "16x16 shape scaled to n devices). efficiency = per-chip rate / "
        "1-device per-chip rate. cpu_mesh = 8-virtual-device curve "
        "shape; tpu_1chip = the real per-chip throughput the curve "
        "hangs off; 2proc sections run two real coordinator-connected "
        "OS processes (Gloo, 2x4 devices). Virtual CPU devices "
        "timeshare the host's cores, so aggregate throughput is flat and "
        "per-chip efficiency falls ~1/n by construction — the CPU curve "
        "validates the comm structure and regression-tests the programs; "
        "real efficiency curves need real chips (the harness runs "
        "unchanged on a pod)."
    )}

    if on_tpu:
        from gol_tpu.utils import halobench, scalebench
        from gol_tpu.parallel import mesh as mesh_mod

        ring = mesh_mod.make_mesh_1d(1)
        # Square headline board, plus the lane-folded pod shard (BASELINE
        # config 3 on a 16x16 mesh: 16384x1024 cells = 32 words) — the
        # geometry whose exchange exposure the folded overlap (r4)
        # exists to hide.
        # Loop lengths sized so the ~0.2-0.26 s/invocation tunnel
        # overhead (BASELINE.md r5 fits) stays a small fraction of every
        # per-generation column: at x1024 the ~0.2 ms/gen overhead floor
        # swamped the folded shard's ~8 us/gen device cost and let
        # exchange_s/step_s orderings flip on noise.
        for engine in ("pallas", "pallas_overlap"):
            for size, steps, suffix in (
                (16384, 8192, ""),
                ((16384, 1024), 65536, "_folded_pod_shard"),
            ):
                size_str = (
                    str(size) if isinstance(size, int)
                    else f"{size[0]}x{size[1]}"
                )
                halo[f"tpu_1ring_{engine}{suffix}"] = {
                    **halobench.measure(ring, size, steps, engine),
                    "size": size if isinstance(size, int) else list(size),
                    "steps": steps,
                    "devices": 1,
                    "command": (
                        f"python -m gol_tpu.utils.halobench {size_str} "
                        f"{steps} 1d {engine}"
                    ),
                }
        rows = scalebench.measure_weak_scaling(
            4096, 16384, "pallas", counts=[1]
        )
        scale["tpu_1chip"] = {
            "size_per_chip": 4096,
            "steps": 16384,
            "engine": "pallas",
            "rows": rows,
            "command": "scalebench.measure_weak_scaling(4096, 16384, 'pallas', counts=[1])",
        }
        # 3-D flagship attribution on the real chip's one-device ring
        # (VERDICT r4 #4); the non-degenerate rings are the cpu_mesh 3-D
        # sections below.
        halo["tpu_1ring_pallas3d"] = {
            **halobench.measure3d(
                # Explicit one-device list: devices=None means ALL
                # visible devices, which on a multi-chip host fails
                # make_mesh_3d's shape==count validation and would abort
                # the capture after the expensive sections above ran.
                mesh_mod.make_mesh_3d((1, 1, 1), devices=jax.devices()[:1]),
                512, 2048
            ),
            "size": 512,
            "steps": 2048,
            "devices": 1,
            "command": "python -m gol_tpu.utils.halobench 512x512x512 2048 3d",
        }
    else:
        print("capture_artifacts: no TPU visible; TPU sections skipped",
              file=sys.stderr)

    halo["cpu_mesh_dense_1d"] = _cpu_json(
        ["gol_tpu.utils.halobench", "1024", "32", "1d", "dense"]
    )
    halo["cpu_mesh_bitpack_1d"] = _cpu_json(
        ["gol_tpu.utils.halobench", "1024", "32", "1d", "bitpack"]
    )
    halo["cpu_mesh_dense_2d"] = _cpu_json(
        ["gol_tpu.utils.halobench", "1024", "32", "2d", "dense"]
    )
    # 3-D flagship attribution over real (virtual-device) rings, both
    # band orientations, x sharded so the ghost-word-column second phase
    # runs (wide 17-word shards keep the ghosted rolling kernel in
    # dispatch, matching the Hypothesis sweep's wide draw).
    halo["cpu_mesh_pallas3d_planes_banded"] = _cpu_json(
        ["gol_tpu.utils.halobench", "32x16x1088", "16", "3d:4,1,2"]
    )
    halo["cpu_mesh_pallas3d_rows_banded"] = _cpu_json(
        ["gol_tpu.utils.halobench", "16x32x1088", "16", "3d:1,4,2"]
    )
    scale["cpu_mesh_dense"] = _cpu_json(
        ["gol_tpu.utils.scalebench", "512", "32", "dense"]
    )
    scale["cpu_mesh_bitpack"] = _cpu_json(
        ["gol_tpu.utils.scalebench", "512", "32", "bitpack"]
    )
    # The pod decomposition (VERDICT r4 #3): 2-D near-square meshes, all
    # four engines including the flagship fused-kernel forms (interpret
    # mode on CPU — curve shape and program validation, not chip rates).
    scale["cpu_mesh_dense_2d"] = _cpu_json(
        ["gol_tpu.utils.scalebench", "512", "32", "dense", "2d"]
    )
    scale["cpu_mesh_bitpack_2d"] = _cpu_json(
        ["gol_tpu.utils.scalebench", "512", "32", "bitpack", "2d"]
    )
    scale["cpu_mesh_pallas_2d"] = _cpu_json(
        ["gol_tpu.utils.scalebench", "256", "16", "pallas", "2d"]
    )
    scale["cpu_mesh_pallas_overlap_2d"] = _cpu_json(
        ["gol_tpu.utils.scalebench", "256", "16", "pallas_overlap", "2d"]
    )
    # One real multi-process curve: two coordinator-connected OS
    # processes (Gloo), rows 1-4 measured by process 0 alone, row 8
    # spanning the process boundary — the config-4 pod shape in miniature.
    scale["cpu_mesh_dense_2proc"] = _cpu_json_2proc(
        ["gol_tpu.utils.scalebench", "512", "32", "dense"]
    )

    # Common artifact header (docs/OBSERVABILITY.md): the perf ledger
    # routes ingestion by header.tool — the committed legacy files keep
    # their structural sniffers.  Sections still carry their own
    # tpu_/cpu_ backend prefixes (a capture mixes both).
    from gol_tpu.telemetry import ledger as ledger_mod

    halo["header"] = ledger_mod.artifact_header("halobench")
    scale["header"] = ledger_mod.artifact_header("scalebench")
    for name, payload in (("HALO", halo), ("SCALE", scale)):
        path = REPO / f"{name}_r{rnd:02d}.json"
        path.write_text(json.dumps(payload, indent=1) + "\n")
        print(f"wrote {path}")


if __name__ == "__main__":
    main()
