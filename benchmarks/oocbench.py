"""oocbench: streaming-efficiency curve of the out-of-core tier
(OOC_r{N}.json).

The ooc tier's reason to exist: a board bigger than device memory
cannot run the in-core tiers at all, so the honest question is not "is
streaming faster" (it is not — it pays PCIe/DMA per band) but "how much
of in-core throughput survives when the board only fits in host RAM".
This harness measures exactly that curve:

- **ratio sweep**: the same soup board streamed under simulated device
  budgets of board/4 .. board/32 — the planner inverts each budget into
  a band height, so the sweep walks band-count (and therefore
  transfer:compute ratio) while the work stays constant.  Each row
  reports ``efficiency`` = in-core wall / streamed wall (the fraction
  of in-core throughput retained), the measured ``overlap_fraction``
  (how much of the transfer wall the three-deep rotation hid behind
  compute), and the chunk's H2D/D2H byte volume;
- **sparse row**: a Gosper gun in the same arena at one budget — dead
  bands are never fetched, so its ``bytes_h2d`` collapses relative to
  the soup row at the same ratio (transfer scales with *active* bands,
  not area);
- every row is written only after a **bit-equality receipt**: the
  streamed board must match the in-core bitpack tier
  (:func:`gol_tpu.ops.bitlife.evolve_dense_io`) on the full grid at
  these sizes (on the TPU headline geometry the receipt runs on a
  cropped replica — stepping the full board twice would double the
  run).

On the CPU backend this captures curve *shape* only (host↔host copies
stand in for PCIe; the absolute walls mean nothing).  The TPU headline
— a 2^20 × 2^20 board, ~128 GiB packed, streamed through one chip's
HBM budget — is pinned in the note::

    python benchmarks/oocbench.py --height 1048576 --width 1048576 \
        --budget-mb 4096 --iters 64 --round 2   # TPU

Usage::

    python benchmarks/oocbench.py --round 1             # defaults
    python benchmarks/oocbench.py --height 4096 --iters 64
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time
from typing import Optional, Sequence

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct-script invocation from anywhere
    sys.path.insert(0, str(REPO))


def _best(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def measure(
    name: str,
    board_np,
    depth: int,
    iters: int,
    budget_bytes: int,
    repeats: int,
) -> dict:
    import jax
    import numpy as np

    from gol_tpu.ooc import OocScheduler, plan_bands
    from gol_tpu.ops import bitlife
    from gol_tpu.utils.timing import force_ready

    h, w = board_np.shape
    plan = plan_bands(h, w, depth, budget_bytes=budget_bytes)

    # In-core oracle wall (the tier a board this size could NOT run if
    # the budget were real) + the bit-equality receipt reference.
    ref = None

    def incore():
        nonlocal ref
        b = jax.device_put(board_np)
        out = bitlife.evolve_dense_io(b, iters)
        force_ready(out)
        ref = out

    incore_wall = _best(incore, repeats)
    ref_np = np.asarray(ref)

    # Streamed wall: board reload is setup, the chunk is the measurement.
    sched = OocScheduler(plan)
    rep = None

    def streamed():
        nonlocal rep
        sched.load_dense(board_np)
        rep = sched.run_chunk(iters, 0)

    ooc_wall = _best(streamed, repeats)

    if not np.array_equal(sched.dense(), ref_np):
        raise AssertionError(
            f"scenario {name!r}: streamed result diverges from the "
            "in-core bitpack tier — refusing to write a benchmark row "
            "for a wrong program"
        )
    cells = h * w * iters
    return dict(
        scenario=name,
        height=h,
        width=w,
        depth=depth,
        iters=iters,
        budget_bytes=budget_bytes,
        board_bytes=plan.board_bytes,
        board_over_budget=(
            plan.board_bytes / budget_bytes if budget_bytes else None
        ),
        bands=plan.num_bands,
        band_rows=plan.band_rows,
        device_bytes=plan.device_bytes(),
        incore_wall_s=incore_wall,
        ooc_wall_s=ooc_wall,
        efficiency=incore_wall / ooc_wall if ooc_wall > 0 else None,
        updates_per_sec=cells / ooc_wall if ooc_wall > 0 else None,
        overlap_fraction=rep["overlap_fraction"],
        bytes_h2d=rep["bytes_h2d"],
        bytes_d2h=rep["bytes_d2h"],
        skipped_bands=rep["skipped_bands"],
        visits=rep["visits"],
        bit_equal=True,
    )


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="oocbench", description=__doc__)
    ap.add_argument("--height", type=int, default=2048)
    ap.add_argument("--width", type=int, default=512)
    ap.add_argument("--depth", type=int, default=4, metavar="K")
    ap.add_argument("--iters", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument(
        "--ratios", default="4,8,16,32",
        help="board-bytes / simulated-device-budget sweep",
    )
    ap.add_argument("--budget-mb", type=int, default=0,
                    help="explicit budget (MiB) instead of the ratio sweep "
                    "(the TPU headline form)")
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--out", default=None)
    ns = ap.parse_args(list(sys.argv[1:] if argv is None else argv))

    import jax
    import numpy as np

    from gol_tpu.models import patterns
    from gol_tpu.ops import bitlife

    h, w = ns.height, ns.width
    board_bytes = h * (w // bitlife.BITS) * 4
    rng = np.random.default_rng(907)
    soup = (rng.random((h, w)) < 0.33).astype(np.uint8)

    rows = []
    if ns.budget_mb:
        budgets = [("soup_0.330", soup, ns.budget_mb << 20)]
    else:
        budgets = [
            ("soup_0.330", soup, max(1, board_bytes // int(r)))
            for r in ns.ratios.split(",")
            if r
        ]
    for name, board, budget in budgets:
        rows.append(
            measure(name, board, ns.depth, ns.iters, budget, ns.repeats)
        )
    # The sparse row: same arena, a single gun — dead bands move zero
    # bytes, so transfer collapses to the active neighborhood.
    gun = patterns.init_sparse_world(
        "gosper_gun", h, w, (h // 3, w // 3)
    )
    rows.append(
        measure(
            "gosper_gun", gun, ns.depth, ns.iters,
            budgets[min(1, len(budgets) - 1)][2], ns.repeats,
        )
    )

    from gol_tpu.telemetry import ledger as ledger_mod

    payload = dict(
        header=ledger_mod.artifact_header("oocbench"),
        note=(
            "streaming-efficiency curve of the out-of-core tier "
            "(docs/STREAMING.md). efficiency = in-core bitpack wall / "
            "streamed wall on the same board under a simulated device "
            "budget of board/N bytes; overlap_fraction = measured "
            "fraction of host-side transfer wall hidden behind "
            "in-flight compute by the three-deep rotation; the "
            "gosper_gun row shows dead-band skipping collapsing "
            "bytes_h2d relative to the soup row at the same budget. "
            "Every row is written only after a bit-equality receipt "
            "against the in-core tier. CPU-backend captures are curve "
            "shape only (host-to-host copies stand in for PCIe); the "
            "TPU headline is --height 1048576 --width 1048576 "
            "--budget-mb 4096 --iters 64 (~128 GiB packed through one "
            "chip)."
        ),
        backend=jax.default_backend(),
        height=h,
        width=w,
        depth=ns.depth,
        iters=ns.iters,
        rows=rows,
        command=(
            f"python benchmarks/oocbench.py --height {h} --width {w} "
            f"--depth {ns.depth} --iters {ns.iters} --ratios "
            f"{ns.ratios} --round {ns.round}"
        ),
    )
    out = ns.out or str(REPO / f"OOC_r{ns.round:02d}.json")
    pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")
    for row in rows:
        ratio = row["board_over_budget"]
        print(
            f"  {row['scenario']:>11}  board/budget "
            f"{ratio:.1f}x  bands {row['bands']:>3}  "
            f"eff {row['efficiency']:.3f}  "
            f"ovl {100 * row['overlap_fraction']:.0f}%  "
            f"h2d {row['bytes_h2d']}B"
            + (f"  skip {row['skipped_bands']}" if row["skipped_bands"]
               else "")
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
