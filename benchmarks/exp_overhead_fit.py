"""Separate per-invocation tunnel overhead from true device rate.

Wall time of one invocation of an n-generation loop through the axon
tunnel is ``T(n) = a + b*n``: ``a`` is the per-invocation overhead (RPC,
dispatch, readback fence) and ``b`` the device's per-generation time.
Single-interval wall rates conflate the two — r4's headline intervals
(0.4-1.4 s) carry *different* overhead fractions per config, and the
overhead itself drifts session to session, so cross-config ratios read
off walls are biased toward long-interval configs.

This script times each config at two loop lengths (n, 8n), best-of-N
interleaved, and reports the fitted overhead and the *device* rate
``cells/b`` — the number a pod chip would actually deliver inside one
program, and the honest basis for the folded-shard gap attribution.

Usage: ``python benchmarks/exp_overhead_fit.py [reps]`` on the TPU.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

FH, FW = 16384, 1024


def report_fits(points) -> None:
    """Shared fit-and-print tail of the two-point experiment scripts
    (``exp_tile_fit`` imports this; the fit arithmetic itself lives in
    :func:`gol_tpu.utils.timing.fit_overhead` so the committed artifacts
    cannot disagree with ``bench.py``'s device_fit field).

    ``points`` rows: ``[name, shape, n, fn, board, wall_samples]``.
    """
    from gol_tpu.utils.timing import fit_overhead

    by_name = {}
    for name, shape, n, _, _, ts in points:
        by_name.setdefault(name, {"shape": shape})[n] = min(ts)
    for name, d in by_name.items():
        shape = d.pop("shape")
        a, b = fit_overhead(d)
        cells = int(np.prod(shape))
        print(json.dumps({
            "config": name,
            "shape": list(shape),
            "walls_s": {str(n): round(t, 4) for n, t in sorted(d.items())},
            "overhead_s_per_invocation": round(a, 4),
            "device_cells_per_s": float(f"{cells / b:.4g}"),
        }), flush=True)


def main() -> None:
    import jax.numpy as jnp

    from gol_tpu.ops import pallas_bitlife
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import packed as packed_mod
    from gol_tpu.utils.timing import force_ready

    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3

    rng = np.random.default_rng(3)
    ring = mesh_mod.make_mesh_1d(1)

    # (name, shape, short_n, builder(steps) -> evolve)
    def bare(shape):
        return lambda n: (lambda b: pallas_bitlife.evolve(b, n))

    def ring_eng(k, t):
        return lambda n: packed_mod.compiled_evolve_packed_pallas(
            ring, n, halo_depth=k, tile_hint=t
        )

    configs = [
        ("bare_4096sq", (4096, 4096), 8192, bare((4096, 4096))),
        ("bare_1024x16384", (1024, 16384), 8192, bare((1024, 16384))),
        ("flagship_16384sq", (16384, 16384), 2048, bare((16384, 16384))),
        ("ring_k8_t128", (FH, FW), 8192, ring_eng(8, 128)),
        ("ring_k8_t512", (FH, FW), 8192, ring_eng(8, 512)),
        ("ring_k32_t512", (FH, FW), 8192, ring_eng(32, 512)),
    ]

    points = []  # (name, shape, n, fn, board, [times])
    for name, shape, n_short, build in configs:
        for n in (n_short, 8 * n_short):
            fn = build(n)
            b = jnp.asarray((rng.random(shape) < 0.35).astype(np.uint8))
            t0 = time.perf_counter()
            b = fn(b)
            force_ready(b)
            print(f"# warm {name} n={n}: {time.perf_counter() - t0:.1f}s",
                  file=sys.stderr, flush=True)
            points.append([name, shape, n, fn, b, []])

    for _ in range(reps):
        for p in points:
            t0 = time.perf_counter()
            p[4] = p[3](p[4])
            force_ready(p[4])
            p[5].append(time.perf_counter() - t0)

    report_fits(points)


if __name__ == "__main__":
    main()
