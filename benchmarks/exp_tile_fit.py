"""Device-rate (overhead-fitted) tile sweep for the ring engines + 3-D.

exp_overhead_fit showed the session's per-invocation tunnel overhead is
0.19-0.26 s — large enough that r4's wall-based tile conclusions ("tiles
64-128 measure ~2-5% above 256") are suspect, and that the folded pod
shard at tile 512 actually runs at 1.98e12 device-side (88% of the
flagship).  This script fits T(n) = a + b*n per config and reports
device rates for:

- the full 16384^2 board on the 1-ring at tile hints 128/256/512
  (does the tile-512 win generalize, i.e. should the engine default
  change?),
- the folded pod shard at hints 512 vs 1024 (is there more), and the
  folded overlap form at 512,
- the sharded 3-D flagship at 1024^3 (is the r4 6.93e11 wall also
  overhead-diluted).

Usage: ``python benchmarks/exp_tile_fit.py [reps]`` on the TPU.
"""

from __future__ import annotations

import sys
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import packed as packed_mod
    from gol_tpu.parallel import sharded3d
    from gol_tpu.parallel.mesh import place_private
    from gol_tpu.parallel.sharded3d import volume_sharding
    from gol_tpu.utils.timing import force_ready

    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 3

    rng = np.random.default_rng(4)
    ring = mesh_mod.make_mesh_1d(1)

    def ring_eng(shape, k, t, overlap=False):
        def build(n):
            fn = packed_mod.compiled_evolve_packed_pallas(
                ring, n, halo_depth=k, tile_hint=t, overlap=overlap
            )
            return fn
        return shape, build

    mesh3 = mesh_mod.make_mesh_3d((1, 1, 1), devices=jax.devices()[:1])

    def vol3(shape):
        def build(n):
            # Donating compiled fn; the caller places the volume once and
            # chains outputs (re-placing per repeat would re-ship data).
            return sharded3d.compiled_evolve3d_pallas(mesh3, n)
        return shape, build

    configs = {
        "ring16384sq_k8_t128": (*ring_eng((16384, 16384), 8, 128), 2048),
        "ring16384sq_k8_t256": (*ring_eng((16384, 16384), 8, 256), 2048),
        "ring16384sq_k8_t512": (*ring_eng((16384, 16384), 8, 512), 2048),
        "foldshard_k8_t512": (*ring_eng((16384, 1024), 8, 512), 8192),
        "foldshard_k8_t1024": (*ring_eng((16384, 1024), 8, 1024), 8192),
        "foldshard_overlap_k8_t512": (
            *ring_eng((16384, 1024), 8, 512, overlap=True), 8192
        ),
        "sharded3d_1024cube": (*vol3((1024, 1024, 1024)), 256),
    }

    points = []
    for name, (shape, build, n_short) in configs.items():
        for n in (n_short, 8 * n_short):
            fn = build(n)
            arr_np = (rng.random(shape) < 0.33).astype(np.uint8)
            if name.startswith("sharded3d"):
                b = place_private(
                    jnp.asarray(arr_np), volume_sharding(mesh3)
                )
            else:
                b = jnp.asarray(arr_np)
            t0 = time.perf_counter()
            b = fn(b)
            force_ready(b)
            print(f"# warm {name} n={n}: {time.perf_counter() - t0:.1f}s",
                  file=sys.stderr, flush=True)
            points.append([name, shape, n, fn, b, []])

    for _ in range(reps):
        for p in points:
            t0 = time.perf_counter()
            p[4] = p[3](p[4])
            force_ready(p[4])
            p[5].append(time.perf_counter() - t0)

    # Shared fit-and-print tail (sys.path[0] is benchmarks/ when run as
    # a script, so the sibling module imports directly).
    from exp_overhead_fit import report_fits

    report_fits(points)


if __name__ == "__main__":
    main()
