"""A/B the rolling-plane 3-D kernel against the r3 kernels on the chip.

Interleaved best-of-N samples in ONE process (BASELINE.md measurement
discipline): per contender, jit a steps-long fori_loop over the kernel,
warm it, then time reps fenced with force_ready.

Usage: python benchmarks/bench_roll3d.py [size] [steps] [reps]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from gol_tpu.ops import bitlife3d, pallas_bitlife3d as p3
from gol_tpu.utils.timing import force_ready


def main() -> None:
    size = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    reps = int(sys.argv[3]) if len(sys.argv) > 3 else 4
    # Every contender runs whole k=8 chunks; count the generations that
    # actually execute or the reported rate is inflated.
    steps -= steps % 8
    if steps < 8:
        raise SystemExit("steps must be >= 8 (one temporal block)")
    d = h = w = size
    nw = w // 32
    rng = np.random.default_rng(0)
    vol = (rng.random((d, h, w)) < 0.3).astype(np.uint8)
    packed = bitlife3d.pack3d(jnp.asarray(vol))
    pt = jax.lax.bitcast_convert_type(packed, jnp.int32).transpose(0, 2, 1)
    pw = jax.lax.bitcast_convert_type(packed, jnp.int32).transpose(2, 0, 1)
    cells = float(d) * h * w * steps

    contenders = {}

    wt = p3.pick_tile3d_wt(d, nw, h)
    if wt is not None:
        td, tw = wt

        def run_wt(x):
            return jax.lax.fori_loop(
                0,
                steps // 8,
                lambda _, p: p3.multi_step_pallas_packed3d_wt(p, td, tw, 8),
                x,
            )

        contenders[f"wt({td},{tw})k8"] = (jax.jit(run_wt), pw)

    plane = p3.pick_tile3d(d, nw, h)
    if plane:

        def run_plane(x):
            return jax.lax.fori_loop(
                0,
                steps // 8,
                lambda _, p: p3.multi_step_pallas_packed3d(p, plane, 8),
                x,
            )

        contenders[f"plane({plane})k8"] = (jax.jit(run_plane), pt)

    for tile in (t for t in dict.fromkeys(
        int(x) for x in (sys.argv[4].split(",") if len(sys.argv) > 4
                         else ["32", "64", "96", "128", "256"])
    ) if d % t == 0):
        window_mb = (tile + 16) * nw * h * 4 / 2**20
        if window_mb > 15:
            continue

        def run_roll(x, t=tile):
            return jax.lax.fori_loop(
                0,
                steps // 8,
                lambda _, p: p3.multi_step_pallas_packed3d_roll(p, t, 8),
                x,
            )

        contenders[f"roll({tile})k8"] = (jax.jit(run_roll), pt)

    timed = {}
    fns = {}
    for name, (fn, x) in contenders.items():
        t0 = time.perf_counter()
        try:
            force_ready(fn(x))
        except Exception as e:  # noqa: BLE001 — report compile failures
            print(f"{name}: FAILED {type(e).__name__}: {str(e)[:200]}")
            continue
        print(f"{name}: warm+compile {time.perf_counter() - t0:.1f}s")
        timed[name] = []
        fns[name] = (fn, x)

    for _ in range(reps):
        for name, (fn, x) in fns.items():
            t0 = time.perf_counter()
            force_ready(fn(x))
            timed[name].append(time.perf_counter() - t0)

    for name, ts in timed.items():
        best = min(ts)
        print(
            f"{name}: best {best:.3f}s -> {cells / best:.3e} cell-updates/s "
            f"(all: {['%.3f' % t for t in ts]})"
        )


if __name__ == "__main__":
    main()
