"""Attribute the folded pod-shard throughput gap (VERDICT r4 #1).

The folded 16384x1024 config-3 shard (lane-folded [4096, 128] layout,
fold=4) measured 1.02e12 cell-updates/s at 39.4% MFU in r4, against the
16384^2 flagship's 1.98e12 at 67.9% — the folded engine *issued* at ~58%
of the flagship's rate at near-equal ops/word, and nothing attributed the
loss.  This script decomposes it, same-session and interleaved (the only
comparison discipline that survives the tunnel's +-10-20% noise):

- ``bare``: :func:`gol_tpu.ops.pallas_bitlife.evolve` on a 4096^2 board —
  the plain torus kernel at the folded layout's exact [4096, 128] packed
  geometry with NO ring, NO band assembly, NO group rolls (the geometry
  ceiling: if this is already slow, the loss is the small-board launch
  regime, not the fold or the ring).
- ``ring k=K t=T``: the sharded engine
  (:func:`gol_tpu.parallel.packed.compiled_evolve_packed_pallas`) on this
  chip's 1-ring at halo_depth K and tile_hint T, serial chunks.  The r4
  claim ran the defaults (k=8, t=128 -> folded tile 128, 32 chunk
  launches per 8 generations of 4096 folded rows).  Chunk-fixed costs
  (launch + band assembly + 2 ppermutes) amortize over k*h rows, so if
  they dominate, deeper k and larger tiles claw the rate back — and the
  recompute tax *shrinks* as tiles grow ((tile + k + 1)/tile).
- ``overlap k=K t=T``: the comm/compute-overlap chunk form at the same
  points (three launches per chunk instead of one; measures what the
  pod's latency-hiding form costs in the launch-bound regime).

k <= 32 keeps the configuration valid for the real pod decomposition
(config 3's 16x16 mesh is 2-D, whose column-band light cone caps
halo_depth at 32); the k=64 point is attribution-only.

Usage: ``python benchmarks/exp_folded_gap.py [steps] [reps]`` on the TPU.
Prints one JSON line per configuration plus a summary ranking.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

FH, FW = 16384, 1024  # BASELINE config 3's shard on the 16x16 pod mesh


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import bitlife, pallas_bitlife
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import packed as packed_mod
    from gol_tpu.utils import roofline
    from gol_tpu.utils.timing import force_ready

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 3

    rng = np.random.default_rng(1)
    ring = mesh_mod.make_mesh_1d(1)
    fold = pallas_bitlife.fold_factor(bitlife.packed_width(FW))  # 4

    def ring_fn(k, t, overlap):
        fn = packed_mod.compiled_evolve_packed_pallas(
            ring, steps, halo_depth=k, tile_hint=t, overlap=overlap
        )
        return fn, (FH, FW)

    def bare_fn():
        side = int(np.sqrt(FH * FW))  # 4096: same cells, same packed rows
        return (lambda b: pallas_bitlife.evolve(b, steps)), (side, side)

    configs = {"bare_4096sq_torus": bare_fn()}
    for k, t in ((8, 128), (8, 256), (8, 512), (16, 256), (16, 512),
                 (32, 512), (32, 1024), (64, 1024)):
        configs[f"ring k={k} t={t}"] = ring_fn(k, t, False)
    for k, t in ((8, 128), (32, 512)):
        configs[f"overlap k={k} t={t}"] = ring_fn(k, t, True)

    # Warm (compile) everything first, then interleave measurements so
    # drift hits every config equally.  Boards stay device-resident:
    # donation chains each config's output back in as its next input.
    boards, best = {}, {}
    for name, (fn, shape) in configs.items():
        b = jnp.asarray((rng.random(shape) < 0.35).astype(np.uint8))
        t0 = time.perf_counter()
        b = fn(b)
        force_ready(b)
        print(
            f"# warm {name}: compile+run {time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
            flush=True,
        )
        boards[name] = b
        best[name] = float("inf")

    for _ in range(reps):
        for name, (fn, shape) in configs.items():
            t0 = time.perf_counter()
            boards[name] = fn(boards[name])
            force_ready(boards[name])
            best[name] = min(best[name], time.perf_counter() - t0)

    cells = FH * FW
    out = []
    for name, (fn, shape) in configs.items():
        rate = cells * steps / best[name]
        rec = {"config": name, "cells_per_s": float(f"{rate:.4g}"),
               "best_s": round(best[name], 4), "steps": steps}
        if name.startswith(("ring", "overlap")):
            k = int(name.split("k=")[1].split()[0])
            t = int(name.split("t=")[1])
            folded_h = FH // fold
            interior = folded_h - (2 * k if name.startswith("overlap") else 0)
            tile = pallas_bitlife.pick_tile(
                interior, fold * bitlife.packed_width(FW), t
            )
            rl = roofline.roofline_2d(rate, tile, k, folded=True)
            rec["tile"] = tile
            rec["mfu_vpu"] = rl.as_dict()
        else:
            tile, kk = pallas_bitlife.blocking_plan(
                4096, 4096 // bitlife.BITS, steps, 1024
            )
            rl = roofline.roofline_2d(rate, tile, kk)
            rec["tile"], rec["k"] = tile, kk
            rec["mfu_vpu"] = rl.as_dict()
        out.append(rec)
        print(json.dumps(rec), flush=True)

    ranked = sorted(out, key=lambda r: -r["cells_per_s"])
    print(json.dumps({"ranking": [r["config"] for r in ranked]}))


if __name__ == "__main__":
    main()
