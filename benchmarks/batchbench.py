"""batchbench: per-world throughput vs batch size B (BATCH_r{N}.json).

The batched engine's reason to exist is launch-overhead amortization:
BENCH_r05's device-fit decomposition pins ~0.17–0.26 s of per-invocation
overhead ``a``, so a small board's sequential wall is ``a + c`` with
``c`` (device compute) tiny — and stepping B worlds in one launch costs
``a + B·c`` instead of ``B·(a + c)``.  This harness measures exactly
that curve:

- for each B it times one compiled batched program (fresh donated
  stacks, best-of-N, ``force_ready`` fenced) at two loop lengths so the
  r5 measurement discipline applies: ``T(n) = a + b·n`` separates the
  per-invocation overhead from the device rate;
- ``per_world_speedup_vs_sequential`` is the headline:
  ``B · wall(B=1) / wall(B)`` — how much faster each world's work
  completes than dispatching the same worlds one launch at a time.
  On a TPU with 256²×1024 worlds this is the ≥10× acceptance number;
  on the CPU backend compute dominates and the curve honestly flattens
  toward 1× (curve shape only, like every cpu_mesh artifact).

Usage::

    python benchmarks/batchbench.py --round 6                  # defaults
    python benchmarks/batchbench.py --size 256 --iters 1024 --bs 1,8,64

The TPU headline capture is ``--size 256 --iters 1024 --bs 1,64``.
Writes ``BATCH_r{round:02d}.json`` (or ``--out PATH``) with the command
pinned per row, per repo artifact convention.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
from typing import Optional, Sequence

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct-script invocation from anywhere
    sys.path.insert(0, str(REPO))


def measure(
    size: int,
    iters: int,
    batch: int,
    engine: str = "auto",
    repeats: int = 3,
) -> dict:
    """One row: walls at ``iters`` and ``iters // 4`` + the overhead fit."""
    import jax
    import numpy as np

    from gol_tpu.batch import engines as batch_engines
    from gol_tpu.batch.runtime import Bucket, resolve_bucket_engine
    from gol_tpu.utils.timing import fit_overhead, time_best

    shapes = [(size, size)] * batch
    bucket = Bucket(shape=(size, size), indices=tuple(range(batch)), masked=False)
    name = resolve_bucket_engine(engine, bucket, shapes)
    rng = np.random.default_rng(42)
    stack_np = (rng.random((batch, size, size)) < 0.33).astype(np.uint8)

    def fresh():
        return jax.device_put(stack_np)

    walls = {}
    for n in sorted({max(1, iters // 4), iters}):
        fn = batch_engines.compiled_batch_evolver(name, n, False, 1024, None)
        walls[n] = time_best(fn, fresh, repeats=repeats)
    wall = walls[iters]
    world_updates = size * size * iters
    row = dict(
        B=batch,
        engine=name,
        wall_s=wall,
        walls={str(n): w for n, w in walls.items()},
        aggregate_updates_per_sec=batch * world_updates / wall,
        per_world_updates_per_sec=world_updates / wall,
    )
    if len(walls) > 1:
        a, b = fit_overhead(walls)
        row["device_fit"] = dict(
            overhead_s=a,
            per_step_s=b,
            aggregate_updates_per_sec_device=(
                batch * size * size / b if b > 0 else None
            ),
        )
    return row


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="batchbench", description=__doc__)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--iters", type=int, default=1024)
    ap.add_argument("--bs", default="1,8,64", metavar="B1,B2,...")
    ap.add_argument(
        "--engine", default="auto",
        choices=["auto", "dense", "bitpack", "pallas_bitpack"],
    )
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--round", type=int, default=0)
    ap.add_argument("--out", default=None)
    ns = ap.parse_args(list(sys.argv[1:] if argv is None else argv))

    import jax

    batches = [int(b) for b in ns.bs.split(",") if b]
    rows = [
        measure(ns.size, ns.iters, b, ns.engine, ns.repeats) for b in batches
    ]
    base = rows[0]["wall_s"]
    base_b = rows[0]["B"]
    for row in rows:
        # B·wall(B0)/ (B0·wall(B)) — worlds completed per second, batched
        # vs one-launch-at-a-time dispatch of the same worlds.
        row["per_world_speedup_vs_sequential"] = (
            row["B"] * base / (base_b * row["wall_s"])
        )
    from gol_tpu.telemetry import ledger as ledger_mod

    payload = dict(
        # Common artifact header (docs/OBSERVABILITY.md): the perf
        # ledger routes ingestion by header.tool, no filename sniffing.
        header=ledger_mod.artifact_header("batchbench"),
        note=(
            "batched multi-world amortization curve (docs/BATCHING.md). "
            "wall_s = best-of-N fenced wall of one compiled batched "
            "launch stepping all B worlds `iters` generations; "
            "per_world_speedup_vs_sequential = B*wall(B_min)/"
            "(B_min*wall(B)) — the launch-overhead amortization factor. "
            "device_fit separates per-invocation overhead from device "
            "rate (r5 discipline: never compare wall rates across "
            "configs). CPU-backend captures are curve shape only; the "
            "TPU headline config is --size 256 --iters 1024 --bs 1,64."
        ),
        backend=jax.default_backend(),
        size=ns.size,
        iters=ns.iters,
        rows=rows,
        command=(
            f"python benchmarks/batchbench.py --size {ns.size} "
            f"--iters {ns.iters} --bs {ns.bs} --engine {ns.engine} "
            f"--round {ns.round}"
        ),
    )
    out = ns.out or str(REPO / f"BATCH_r{ns.round:02d}.json")
    pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")
    for row in rows:
        print(
            f"  B={row['B']:>4}  wall {row['wall_s']:.4f}s  "
            f"per-world speedup x{row['per_world_speedup_vs_sequential']:.2f}"
            f"  ({row['engine']})"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
