"""One targeted experiment at the 2-D kernel's MFU residue (VERDICT r4 #7).

The roofline attributes ~32% of the VPU issue peak to "Mosaic
scheduling/roll-port effects".  One concrete candidate: the temporal
blocking's shrinking in-place window.  Generation ``j`` of
:func:`gol_tpu.ops.pallas_bitlife._kernel_ext` reads
``scratch[j : tile+2k-j]`` and writes ``scratch[j+1 : tile+2k-j-1]`` —
both at *odd sublane offsets* for most ``j``, which Mosaic must realign
(the (8,128) tile rule) with shift/copy traffic around every generation.

The variant here ping-pongs between two VMEM buffers instead: generation
``j`` reads buffer ``j%2`` rows ``[0, w)`` and writes buffer ``(j+1)%2``
rows ``[0, w-2)`` — every load AND store starts at sublane 0, the
aligned case, at the cost of one extra window-sized VMEM buffer per slot
(the double-buffered DMA protocol is unchanged).  After ``k``
generations the surviving rows ``[0, tile)`` of the final buffer are
exactly the body tile.

If the aligned form wins >= 3% same-session it graduates into
``pallas_bitlife``; either way the number is recorded in BASELINE.md r5.

Usage: ``python benchmarks/exp_pingpong.py [steps] [reps]`` on the TPU.
"""

from __future__ import annotations

import functools
import json
import sys
import time

import numpy as np

SIZE = 16384


def _build_pingpong(ext_i32, tile: int, k: int):
    """multi_step_pallas_packed_ext with aligned ping-pong generation
    buffers (experiment-only copy; contract identical, rule=None,
    groups=1)."""
    import jax
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from gol_tpu.ops import pallas_bitlife as pb

    height = ext_i32.shape[0] - 2 * k
    nw = ext_i32.shape[1]

    def kernel(ext_hbm, out_ref, scratch, sems):
        i = pl.program_id(0)
        nt = pl.num_programs(0)
        slot = jax.lax.rem(i, 2)

        def copies(j, s):
            start = pl.multiple_of(j * tile, 8)
            return (
                pltpu.make_async_copy(
                    ext_hbm.at[pl.ds(start, tile + 2 * k)],
                    # Window lands in ping buffer 0 of slot s.
                    scratch.at[s, 0],
                    sems.at[s],
                ),
            )

        from gol_tpu.ops.pallas_common import load_window_double_buffered

        load_window_double_buffered(
            copies, i, i + 1, slot, i == 0, i + 1 < nt
        )
        for j in range(k):
            w = tile + 2 * k - 2 * j
            src = j % 2
            dst = 1 - src
            scratch[slot, dst, 0 : w - 2] = pb._one_generation(
                scratch[slot, src, 0:w]
            )
        out_ref[:] = scratch[slot, k % 2, 0:tile]

    return pl.pallas_call(
        kernel,
        grid=(height // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tile, nw), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((height, nw), ext_i32.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, 2, tile + 2 * k, nw), ext_i32.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
    )(ext_i32)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gol_tpu.ops import bitlife, pallas_bitlife
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import packed as packed_mod
    from gol_tpu.utils.timing import force_ready

    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 10240
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    k, tile = 8, 256  # the flagship blocking plan at 16384^2

    rng = np.random.default_rng(0)
    board = jnp.asarray(
        (rng.random((SIZE, SIZE)) < 0.35).astype(np.uint8)
    )

    # Both contenders run the identical ring-engine chunk structure: one
    # band exchange per k generations feeding a k-ext window; only the
    # kernel body differs.  Build via the ext form directly so the
    # ping-pong variant slots in.
    nw = bitlife.packed_width(SIZE)

    @functools.partial(jax.jit, donate_argnums=0)
    def run_inplace(b):
        p = lax.bitcast_convert_type(bitlife.pack(b), jnp.int32)
        def chunk(_, p):
            ext = jnp.concatenate([p[-k:], p, p[:k]])
            return pallas_bitlife.multi_step_pallas_packed_ext(
                ext, tile, k
            )
        p = lax.fori_loop(0, steps // k, chunk, p)
        return bitlife.unpack(lax.bitcast_convert_type(p, jnp.uint32))

    @functools.partial(jax.jit, donate_argnums=0)
    def run_pingpong(b):
        p = lax.bitcast_convert_type(bitlife.pack(b), jnp.int32)
        def chunk(_, p):
            ext = jnp.concatenate([p[-k:], p, p[:k]])
            return _build_pingpong(ext, tile, k)
        p = lax.fori_loop(0, steps // k, chunk, p)
        return bitlife.unpack(lax.bitcast_convert_type(p, jnp.uint32))

    contenders = {"inplace_shrink": run_inplace, "pingpong_aligned": run_pingpong}
    boards, best = {}, {}
    for name, fn in contenders.items():
        b = jnp.array(board, copy=True)
        t0 = time.perf_counter()
        b = fn(b)
        force_ready(b)
        print(f"# warm {name}: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr, flush=True)
        boards[name] = b
        best[name] = []

    for _ in range(reps):
        for name, fn in contenders.items():
            t0 = time.perf_counter()
            boards[name] = fn(boards[name])
            force_ready(boards[name])
            best[name].append(time.perf_counter() - t0)

    # Equality check: both must compute the same board.
    bye = {n: np.asarray(b) for n, b in boards.items()}
    same = bool(
        (bye["inplace_shrink"] == bye["pingpong_aligned"]).all()
    )
    for name, ts in best.items():
        rate = SIZE * SIZE * steps / min(ts)
        print(json.dumps({
            "config": name,
            "cells_per_s": float(f"{rate:.4g}"),
            "samples_s": [round(t, 4) for t in sorted(ts)],
            "steps": steps,
            "boards_equal": same,
        }), flush=True)


if __name__ == "__main__":
    main()
