"""servebench — open-loop load curve for the serving tier.

Method (docs/SERVING.md "Measuring the tier"): start a REAL server — the
HTTP front end, the continuous-batching scheduler, the journal — on an
ephemeral localhost port, then for each offered request rate submit N
small worlds open-loop (fixed spacing, never waiting for completions —
the honest way to expose queueing) and record what the tier actually
did: how many were admitted vs explicitly rejected (429 backpressure is
a *feature* being measured, not an error), the achieved completion
rate, and the p50/p99 end-to-end latency from the server's own
``latency_s`` stamps.  The queue-depth trace is sampled during the
submission window; its max shows how deep the bounded buffer actually
ran.

Since schema v12 each per-rate server runs with a telemetry stream
attached, and the rows carry what the trace plane reconstructs from it:
the p50/p99 **latency decomposition** (queue / compute / stall /
interference / hedge, from every committed request's result payload —
docs/OBSERVABILITY.md "Request tracing & SLOs") and the **SLO
evaluation** (burn rate per objective, :mod:`gol_tpu.telemetry.slo`),
so a rate row says not just how fast the tier went but *where the time
went* and whether the objectives held.

The committed artifact (SERVE_rNN.json at the repo root) carries the
ledger header so ``python -m gol_tpu.telemetry ledger ingest`` routes it
(tool=servebench): each row lands as one throughput record (req/s,
higher-is-better), latency records (p99 and queue-wait p99 seconds,
lower-is-better), and one ``slo`` burn-rate record per objective — so
``ledger check`` gates the tier on its objectives, not just its rate.

CPU rounds pin the curve SHAPE (admission behavior, queue dynamics);
the TPU headline row is the note's pinned command.

``--fleet N1,N2,...`` switches to the replicated front tier
(docs/SERVING.md "The fleet"): each row runs a REAL fleet — N
supervised replica processes behind an in-process
:class:`~gol_tpu.serve.fleet.FleetFront` — at one fixed offered rate,
so the rows answer "what does adding a replica buy" in achieved req/s.
The final row repeats the largest N with a ``kill -9`` of the
busiest replica mid-run: its p99 prices a journaled handoff (detection
+ migration + replay on a survivor), the fleet's headline robustness
number.  The artifact (FLEET_rNN.json) ingests as ``tool=fleetbench``.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time
from typing import Optional, Sequence

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct-script invocation from anywhere
    sys.path.insert(0, str(REPO))


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def run_curve(
    rates: Sequence[float],
    n_requests: int,
    size: int,
    generations: int,
    slots: int,
    queue_depth: int,
    chunk: int,
    workdir: str,
    slo_commit_s: float = 30.0,
) -> list:
    from gol_tpu.serve.client import Backpressure, SimClient
    from gol_tpu.serve.scheduler import ServeScheduler
    from gol_tpu.serve.server import ServeServer
    from gol_tpu.telemetry import slo as slo_mod
    from gol_tpu.telemetry import trace as trace_mod

    rows = []
    for r_i, rate in enumerate(rates):
        state = str(pathlib.Path(workdir) / f"rate{r_i}")
        sched = ServeScheduler(
            state, slots=slots, queue_depth=queue_depth, chunk=chunk,
            telemetry_dir=str(pathlib.Path(workdir) / f"tel{r_i}"),
            run_id=f"rate{r_i}",
        )
        srv = ServeServer(sched, 0)
        stop = threading.Event()

        def loop():
            while not (stop.is_set() and sched.outstanding() == 0):
                if not sched.run_once():
                    time.sleep(0.001)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        client = SimClient(f"http://127.0.0.1:{srv.port}")
        gap = 1.0 / rate
        accepted, rejected = [], 0
        max_queue = 0
        stats_lock = threading.Lock()
        t0 = time.perf_counter()

        def submit_one(i: int) -> None:
            # Open loop: the schedule, not the server, decides when each
            # request goes out.  A pool of submitters keeps that true
            # past the point where one client's HTTP round-trip would
            # silently turn the bench closed-loop.
            nonlocal rejected, max_queue
            target = t0 + i * gap
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            rid = f"b{r_i}-{i}"
            try:
                client.submit(
                    {"id": rid, "pattern": 4, "size": size,
                     "generations": generations}
                )
                with stats_lock:
                    accepted.append(rid)
            except Backpressure:
                with stats_lock:
                    rejected += 1
            depth = sched._depths()["queue_depth"]
            with stats_lock:
                max_queue = max(max_queue, depth)

        pool = min(16, max(1, int(rate * 0.05) or 1))
        idx = iter(range(n_requests))

        def worker():
            for i in idx:  # shared iterator: each index submits once
                submit_one(i)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(pool)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        submit_wall = time.perf_counter() - t0
        for rid in accepted:
            client.wait_for(rid, timeout_s=300.0)
        wall = time.perf_counter() - t0
        stop.set()
        t.join(timeout=30.0)
        srv.close()
        sched.close()
        lats = sorted(
            sched.get_result(rid).result["latency_s"] for rid in accepted
        )
        # The decomposition rides every result payload (same numbers
        # the span tree reconstructs — one source of truth), so the row
        # says where each rate's latency went, and the SLO engine turns
        # the set into burn rates the ledger gates on.
        decomps = [
            sched.get_result(rid).result["decomposition"]
            for rid in accepted
        ]
        slos = [
            slo_mod.SLO(
                name="commit_p99", metric="commit_latency_s",
                target=slo_commit_s, budget=0.01,
            ),
            slo_mod.SLO(
                name="queue_frac_p50", metric="queue_fraction",
                target=0.5, budget=0.05, percentile=0.50,
            ),
        ]
        slo_rows = slo_mod.evaluate(slos, decomps)
        rows.append(
            {
                "offered_rps": rate,
                "submitted": n_requests,
                "completed": len(accepted),
                "rejected": rejected,
                "achieved_rps": len(accepted) / wall if wall > 0 else 0.0,
                "submit_window_s": round(submit_wall, 4),
                "wall_s": round(wall, 4),
                "p50_s": _percentile(lats, 0.50),
                "p99_s": _percentile(lats, 0.99),
                "max_queue_depth": max_queue,
                "decomposition": trace_mod.decomposition_percentiles(
                    decomps
                ),
                "slo": slo_rows,
            }
        )
        burn = max((s["burn_rate"] for s in slo_rows), default=0.0)
        print(
            f"  offered {rate:>6.1f}/s  completed {len(accepted):>3} "
            f"rejected {rejected:>3}  achieved "
            f"{rows[-1]['achieved_rps']:.1f}/s  "
            f"p50 {rows[-1]['p50_s']:.3f}s p99 {rows[-1]['p99_s']:.3f}s "
            f"maxq {max_queue}  worst-burn {burn:.2f}"
        )
    return rows


def run_fleet_curve(
    replica_counts: Sequence[int],
    rate: float,
    n_requests: int,
    generations: int,
    slots: int,
    queue_depth: int,
    chunk: int,
    workdir: str,
) -> list:
    """One row per replica count at a fixed offered rate, plus a final
    row repeating the largest count with a mid-run ``kill -9`` of the
    busiest replica — the p99 of that row prices a journaled handoff.

    Requests cycle four bucket keys (32/96 x auto/dense) so the ring
    actually spreads load; every fleet runs REAL supervised replica
    subprocesses (compile caches and all), which is what makes the
    scaling honest on CPU too."""
    import os
    import signal as signal_mod
    import types

    from gol_tpu.serve import fleet as fleet_mod
    from gol_tpu.serve.client import Backpressure, SimClient

    sizes = [(32, "auto"), (96, "auto"), (32, "dense"), (96, "dense")]
    rows = []
    runs = [(n, False) for n in replica_counts]
    runs.append((max(replica_counts), True))
    for run_i, (n_replicas, kill) in enumerate(runs):
        state = str(pathlib.Path(workdir) / f"fleet{run_i}")
        ns = types.SimpleNamespace(
            replicas=n_replicas, max_restarts=3, slots=slots,
            queue_depth=queue_depth, chunk=chunk, bucket_quantum=64,
            engine="auto",
        )
        replicas = fleet_mod.spawn_replicas(ns, state)
        front = server = None
        poll_stop = threading.Event()
        poller = None
        try:
            fleet_mod.wait_replicas_healthy(replicas, timeout_s=180.0)
            front = fleet_mod.FleetFront(replicas, state)
            server = fleet_mod.FleetServer(front, 0)

            def poll_loop():
                while not poll_stop.is_set():
                    front.poll()
                    time.sleep(0.1)

            poller = threading.Thread(target=poll_loop, daemon=True)
            poller.start()
            client = SimClient(f"http://127.0.0.1:{server.port}")
            gap = 1.0 / rate
            accepted, rejected = [], 0
            # The victim owns request 0's bucket — guaranteed routed
            # work when the kill fires at the halfway mark.
            ring = fleet_mod.HashRing([r.name for r in replicas])
            victim = ring.lookup(fleet_mod.bucket_key(sizes[0][0], sizes[0][1], 64))
            t0 = time.perf_counter()
            for i in range(n_requests):
                target = t0 + i * gap
                now = time.perf_counter()
                if target > now:
                    time.sleep(target - now)
                if kill and i == n_requests // 2:
                    try:
                        with open(
                            os.path.join(state, victim, "manifest.json")
                        ) as f:
                            pid = json.load(f)["attempts"][-1]["pid"]
                        os.kill(pid, signal_mod.SIGKILL)
                    except (OSError, KeyError, IndexError,
                            json.JSONDecodeError):
                        pass
                size, engine = sizes[i % len(sizes)]
                rid = f"fl{run_i}-{i}"
                try:
                    client.submit(
                        {"id": rid, "pattern": 4, "size": size,
                         "generations": generations, "engine": engine}
                    )
                    accepted.append(rid)
                except Backpressure:
                    rejected += 1
            results = {
                rid: client.wait_for(rid, timeout_s=300.0)
                for rid in accepted
            }
            wall = time.perf_counter() - t0
            lats = sorted(
                r["latency_s"] for r in results.values()
                if r.get("latency_s") is not None
            )
            rows.append(
                {
                    "replicas": n_replicas,
                    "kill": kill,
                    "offered_rps": rate,
                    "submitted": n_requests,
                    "completed": len(accepted),
                    "rejected": rejected,
                    "achieved_rps": (
                        len(accepted) / wall if wall > 0 else 0.0
                    ),
                    "wall_s": round(wall, 4),
                    "p50_s": _percentile(lats, 0.50),
                    "p99_s": _percentile(lats, 0.99),
                    "handoffs": front.handoffs_total,
                    "routing_epoch": front.epoch,
                }
            )
            print(
                f"  fleet n={n_replicas}{' +kill' if kill else '     '}"
                f"  completed {len(accepted):>3} rejected {rejected:>3}"
                f"  achieved {rows[-1]['achieved_rps']:.1f}/s  "
                f"p50 {rows[-1]['p50_s']:.3f}s "
                f"p99 {rows[-1]['p99_s']:.3f}s  "
                f"handoffs {front.handoffs_total}"
            )
        finally:
            poll_stop.set()
            if poller is not None:
                poller.join(timeout=5.0)
            if front is not None:
                front.drain(timeout_s=60.0)
            if server is not None:
                server.close()
            if front is not None:
                front.close()
            for r in replicas:
                if r.proc is not None and r.proc.poll() is None:
                    r.proc.kill()
                    r.proc.wait(timeout=10.0)
    return rows


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="servebench", description=__doc__)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--generations", type=int, default=8)
    ap.add_argument(
        "--rates", default="4,16,64", metavar="R1,R2,...",
        help="offered request rates (req/s), one row each",
    )
    ap.add_argument("--requests", type=int, default=24,
                    help="requests submitted per rate row")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument(
        "--slo-commit-s", type=float, default=30.0, metavar="SECONDS",
        help="commit-latency SLO target evaluated per row "
        "(p99 over the trace decompositions, 1%% error budget)",
    )
    ap.add_argument("--out", default=None)
    ap.add_argument(
        "--fleet", default=None, metavar="N1,N2,...",
        help="fleet mode: one row per replica count at --fleet-rate, "
        "plus a mid-run-kill row at the largest count "
        "(writes FLEET_r{round}.json, tool=fleetbench)",
    )
    ap.add_argument(
        "--fleet-rate", type=float, default=8.0, metavar="RPS",
        help="offered request rate for every fleet row (default 8)",
    )
    ns = ap.parse_args(argv)

    import tempfile

    from gol_tpu.telemetry import ledger as ledger_mod

    if ns.fleet:
        counts = [int(c) for c in ns.fleet.split(",") if c]
        workdir = tempfile.mkdtemp(prefix="fleetbench_")
        rows = run_fleet_curve(
            counts, ns.fleet_rate, ns.requests, ns.generations,
            ns.slots, ns.queue_depth, ns.chunk, workdir,
        )
        payload = dict(
            header=ledger_mod.artifact_header("fleetbench"),
            note=(
                "open-loop serving-fleet scaling curve (docs/SERVING.md"
                ' "The fleet"). One row per replica count at a fixed '
                "offered rate — real supervised replica subprocesses "
                "behind the replicated front tier, requests cycling "
                "four bucket keys so the consistent-hash ring spreads "
                "load — plus a final row repeating the largest count "
                "with a kill -9 of the busiest replica mid-run: its "
                "p99 prices a journaled ownership handoff (detection, "
                "migration, replay on a survivor). CPU rounds pin the "
                "scaling shape; the TPU headline is: python "
                "benchmarks/servebench.py --fleet 1,2,4 --fleet-rate 64 "
                "--requests 96 --size 256 --generations 64"
            ),
            generations=ns.generations,
            slots=ns.slots,
            queue_depth=ns.queue_depth,
            chunk=ns.chunk,
            requests_per_row=ns.requests,
            offered_rps=ns.fleet_rate,
            rows=rows,
        )
        out = ns.out or str(REPO / f"FLEET_r{ns.round:02d}.json")
        pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
        print(f"wrote {out}")
        return 0

    rates = [float(r) for r in ns.rates.split(",") if r]
    workdir = tempfile.mkdtemp(prefix="servebench_")
    rows = run_curve(
        rates, ns.requests, ns.size, ns.generations, ns.slots,
        ns.queue_depth, ns.chunk, workdir,
        slo_commit_s=ns.slo_commit_s,
    )
    payload = dict(
        header=ledger_mod.artifact_header("servebench"),
        note=(
            "open-loop serving-tier load curve (docs/SERVING.md). "
            "Each row: N small worlds offered at a fixed rate to a real "
            "HTTP server (ephemeral port, journal on tmpfs); completed "
            "vs 429-rejected counts, achieved req/s over the full "
            "drain, p50/p99 end-to-end latency from the server's "
            "latency_s stamps, the p50/p99 latency decomposition "
            "(queue/compute/stall/interference/hedge) from the v12 "
            "trace plane, and per-objective SLO burn rates. "
            "CPU rounds pin the curve shape "
            "(admission + queue dynamics); the TPU headline is: "
            "python benchmarks/servebench.py --size 256 "
            "--generations 64 --rates 16,64,256 --requests 96 "
            "--slots 8 --queue-depth 16"
        ),
        size=ns.size,
        generations=ns.generations,
        slots=ns.slots,
        queue_depth=ns.queue_depth,
        chunk=ns.chunk,
        requests_per_rate=ns.requests,
        rows=rows,
    )
    out = ns.out or str(REPO / f"SERVE_r{ns.round:02d}.json")
    pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
