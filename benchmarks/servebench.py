"""servebench — open-loop load curve for the serving tier.

Method (docs/SERVING.md "Measuring the tier"): start a REAL server — the
HTTP front end, the continuous-batching scheduler, the journal — on an
ephemeral localhost port, then for each offered request rate submit N
small worlds open-loop (fixed spacing, never waiting for completions —
the honest way to expose queueing) and record what the tier actually
did: how many were admitted vs explicitly rejected (429 backpressure is
a *feature* being measured, not an error), the achieved completion
rate, and the p50/p99 end-to-end latency from the server's own
``latency_s`` stamps.  The queue-depth trace is sampled during the
submission window; its max shows how deep the bounded buffer actually
ran.

Since schema v12 each per-rate server runs with a telemetry stream
attached, and the rows carry what the trace plane reconstructs from it:
the p50/p99 **latency decomposition** (queue / compute / stall /
interference / hedge, from every committed request's result payload —
docs/OBSERVABILITY.md "Request tracing & SLOs") and the **SLO
evaluation** (burn rate per objective, :mod:`gol_tpu.telemetry.slo`),
so a rate row says not just how fast the tier went but *where the time
went* and whether the objectives held.

The committed artifact (SERVE_rNN.json at the repo root) carries the
ledger header so ``python -m gol_tpu.telemetry ledger ingest`` routes it
(tool=servebench): each row lands as one throughput record (req/s,
higher-is-better), latency records (p99 and queue-wait p99 seconds,
lower-is-better), and one ``slo`` burn-rate record per objective — so
``ledger check`` gates the tier on its objectives, not just its rate.

CPU rounds pin the curve SHAPE (admission behavior, queue dynamics);
the TPU headline row is the note's pinned command.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time
from typing import Optional, Sequence

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct-script invocation from anywhere
    sys.path.insert(0, str(REPO))


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def run_curve(
    rates: Sequence[float],
    n_requests: int,
    size: int,
    generations: int,
    slots: int,
    queue_depth: int,
    chunk: int,
    workdir: str,
    slo_commit_s: float = 30.0,
) -> list:
    from gol_tpu.serve.client import Backpressure, SimClient
    from gol_tpu.serve.scheduler import ServeScheduler
    from gol_tpu.serve.server import ServeServer
    from gol_tpu.telemetry import slo as slo_mod
    from gol_tpu.telemetry import trace as trace_mod

    rows = []
    for r_i, rate in enumerate(rates):
        state = str(pathlib.Path(workdir) / f"rate{r_i}")
        sched = ServeScheduler(
            state, slots=slots, queue_depth=queue_depth, chunk=chunk,
            telemetry_dir=str(pathlib.Path(workdir) / f"tel{r_i}"),
            run_id=f"rate{r_i}",
        )
        srv = ServeServer(sched, 0)
        stop = threading.Event()

        def loop():
            while not (stop.is_set() and sched.outstanding() == 0):
                if not sched.run_once():
                    time.sleep(0.001)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        client = SimClient(f"http://127.0.0.1:{srv.port}")
        gap = 1.0 / rate
        accepted, rejected = [], 0
        max_queue = 0
        stats_lock = threading.Lock()
        t0 = time.perf_counter()

        def submit_one(i: int) -> None:
            # Open loop: the schedule, not the server, decides when each
            # request goes out.  A pool of submitters keeps that true
            # past the point where one client's HTTP round-trip would
            # silently turn the bench closed-loop.
            nonlocal rejected, max_queue
            target = t0 + i * gap
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            rid = f"b{r_i}-{i}"
            try:
                client.submit(
                    {"id": rid, "pattern": 4, "size": size,
                     "generations": generations}
                )
                with stats_lock:
                    accepted.append(rid)
            except Backpressure:
                with stats_lock:
                    rejected += 1
            depth = sched._depths()["queue_depth"]
            with stats_lock:
                max_queue = max(max_queue, depth)

        pool = min(16, max(1, int(rate * 0.05) or 1))
        idx = iter(range(n_requests))

        def worker():
            for i in idx:  # shared iterator: each index submits once
                submit_one(i)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(pool)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        submit_wall = time.perf_counter() - t0
        for rid in accepted:
            client.wait_for(rid, timeout_s=300.0)
        wall = time.perf_counter() - t0
        stop.set()
        t.join(timeout=30.0)
        srv.close()
        sched.close()
        lats = sorted(
            sched.get_result(rid).result["latency_s"] for rid in accepted
        )
        # The decomposition rides every result payload (same numbers
        # the span tree reconstructs — one source of truth), so the row
        # says where each rate's latency went, and the SLO engine turns
        # the set into burn rates the ledger gates on.
        decomps = [
            sched.get_result(rid).result["decomposition"]
            for rid in accepted
        ]
        slos = [
            slo_mod.SLO(
                name="commit_p99", metric="commit_latency_s",
                target=slo_commit_s, budget=0.01,
            ),
            slo_mod.SLO(
                name="queue_frac_p50", metric="queue_fraction",
                target=0.5, budget=0.05, percentile=0.50,
            ),
        ]
        slo_rows = slo_mod.evaluate(slos, decomps)
        rows.append(
            {
                "offered_rps": rate,
                "submitted": n_requests,
                "completed": len(accepted),
                "rejected": rejected,
                "achieved_rps": len(accepted) / wall if wall > 0 else 0.0,
                "submit_window_s": round(submit_wall, 4),
                "wall_s": round(wall, 4),
                "p50_s": _percentile(lats, 0.50),
                "p99_s": _percentile(lats, 0.99),
                "max_queue_depth": max_queue,
                "decomposition": trace_mod.decomposition_percentiles(
                    decomps
                ),
                "slo": slo_rows,
            }
        )
        burn = max((s["burn_rate"] for s in slo_rows), default=0.0)
        print(
            f"  offered {rate:>6.1f}/s  completed {len(accepted):>3} "
            f"rejected {rejected:>3}  achieved "
            f"{rows[-1]['achieved_rps']:.1f}/s  "
            f"p50 {rows[-1]['p50_s']:.3f}s p99 {rows[-1]['p99_s']:.3f}s "
            f"maxq {max_queue}  worst-burn {burn:.2f}"
        )
    return rows


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="servebench", description=__doc__)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--generations", type=int, default=8)
    ap.add_argument(
        "--rates", default="4,16,64", metavar="R1,R2,...",
        help="offered request rates (req/s), one row each",
    )
    ap.add_argument("--requests", type=int, default=24,
                    help="requests submitted per rate row")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument(
        "--slo-commit-s", type=float, default=30.0, metavar="SECONDS",
        help="commit-latency SLO target evaluated per row "
        "(p99 over the trace decompositions, 1%% error budget)",
    )
    ap.add_argument("--out", default=None)
    ns = ap.parse_args(argv)

    import tempfile

    from gol_tpu.telemetry import ledger as ledger_mod

    rates = [float(r) for r in ns.rates.split(",") if r]
    workdir = tempfile.mkdtemp(prefix="servebench_")
    rows = run_curve(
        rates, ns.requests, ns.size, ns.generations, ns.slots,
        ns.queue_depth, ns.chunk, workdir,
        slo_commit_s=ns.slo_commit_s,
    )
    payload = dict(
        header=ledger_mod.artifact_header("servebench"),
        note=(
            "open-loop serving-tier load curve (docs/SERVING.md). "
            "Each row: N small worlds offered at a fixed rate to a real "
            "HTTP server (ephemeral port, journal on tmpfs); completed "
            "vs 429-rejected counts, achieved req/s over the full "
            "drain, p50/p99 end-to-end latency from the server's "
            "latency_s stamps, the p50/p99 latency decomposition "
            "(queue/compute/stall/interference/hedge) from the v12 "
            "trace plane, and per-objective SLO burn rates. "
            "CPU rounds pin the curve shape "
            "(admission + queue dynamics); the TPU headline is: "
            "python benchmarks/servebench.py --size 256 "
            "--generations 64 --rates 16,64,256 --requests 96 "
            "--slots 8 --queue-depth 16"
        ),
        size=ns.size,
        generations=ns.generations,
        slots=ns.slots,
        queue_depth=ns.queue_depth,
        chunk=ns.chunk,
        requests_per_rate=ns.requests,
        rows=rows,
    )
    out = ns.out or str(REPO / f"SERVE_r{ns.round:02d}.json")
    pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
