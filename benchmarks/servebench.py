"""servebench — open-loop load curve for the serving tier.

Method (docs/SERVING.md "Measuring the tier"): start a REAL server — the
HTTP front end, the continuous-batching scheduler, the journal — on an
ephemeral localhost port, then for each offered request rate submit N
small worlds open-loop (fixed spacing, never waiting for completions —
the honest way to expose queueing) and record what the tier actually
did: how many were admitted vs explicitly rejected (429 backpressure is
a *feature* being measured, not an error), the achieved completion
rate, and the p50/p99 end-to-end latency from the server's own
``latency_s`` stamps.  The queue-depth trace is sampled during the
submission window; its max shows how deep the bounded buffer actually
ran.

The committed artifact (SERVE_rNN.json at the repo root) carries the
ledger header so ``python -m gol_tpu.telemetry ledger ingest`` routes it
(tool=servebench): each row lands as one throughput record (req/s,
higher-is-better) and one latency record (p99 seconds,
lower-is-better), so ``ledger check`` gates p99 regressions on TPU
rounds the same way it gates cell rates.

CPU rounds pin the curve SHAPE (admission behavior, queue dynamics);
the TPU headline row is the note's pinned command.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys
import threading
import time
from typing import Optional, Sequence

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct-script invocation from anywhere
    sys.path.insert(0, str(REPO))


def _percentile(sorted_vals, q: float) -> Optional[float]:
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def run_curve(
    rates: Sequence[float],
    n_requests: int,
    size: int,
    generations: int,
    slots: int,
    queue_depth: int,
    chunk: int,
    workdir: str,
) -> list:
    from gol_tpu.serve.client import Backpressure, SimClient
    from gol_tpu.serve.scheduler import ServeScheduler
    from gol_tpu.serve.server import ServeServer

    rows = []
    for r_i, rate in enumerate(rates):
        state = str(pathlib.Path(workdir) / f"rate{r_i}")
        sched = ServeScheduler(
            state, slots=slots, queue_depth=queue_depth, chunk=chunk,
        )
        srv = ServeServer(sched, 0)
        stop = threading.Event()

        def loop():
            while not (stop.is_set() and sched.outstanding() == 0):
                if not sched.run_once():
                    time.sleep(0.001)

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        client = SimClient(f"http://127.0.0.1:{srv.port}")
        gap = 1.0 / rate
        accepted, rejected = [], 0
        max_queue = 0
        stats_lock = threading.Lock()
        t0 = time.perf_counter()

        def submit_one(i: int) -> None:
            # Open loop: the schedule, not the server, decides when each
            # request goes out.  A pool of submitters keeps that true
            # past the point where one client's HTTP round-trip would
            # silently turn the bench closed-loop.
            nonlocal rejected, max_queue
            target = t0 + i * gap
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            rid = f"b{r_i}-{i}"
            try:
                client.submit(
                    {"id": rid, "pattern": 4, "size": size,
                     "generations": generations}
                )
                with stats_lock:
                    accepted.append(rid)
            except Backpressure:
                with stats_lock:
                    rejected += 1
            depth = sched._depths()["queue_depth"]
            with stats_lock:
                max_queue = max(max_queue, depth)

        pool = min(16, max(1, int(rate * 0.05) or 1))
        idx = iter(range(n_requests))

        def worker():
            for i in idx:  # shared iterator: each index submits once
                submit_one(i)

        threads = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(pool)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        submit_wall = time.perf_counter() - t0
        for rid in accepted:
            client.wait_for(rid, timeout_s=300.0)
        wall = time.perf_counter() - t0
        stop.set()
        t.join(timeout=30.0)
        srv.close()
        sched.close()
        lats = sorted(
            sched.get_result(rid).result["latency_s"] for rid in accepted
        )
        rows.append(
            {
                "offered_rps": rate,
                "submitted": n_requests,
                "completed": len(accepted),
                "rejected": rejected,
                "achieved_rps": len(accepted) / wall if wall > 0 else 0.0,
                "submit_window_s": round(submit_wall, 4),
                "wall_s": round(wall, 4),
                "p50_s": _percentile(lats, 0.50),
                "p99_s": _percentile(lats, 0.99),
                "max_queue_depth": max_queue,
            }
        )
        print(
            f"  offered {rate:>6.1f}/s  completed {len(accepted):>3} "
            f"rejected {rejected:>3}  achieved "
            f"{rows[-1]['achieved_rps']:.1f}/s  "
            f"p50 {rows[-1]['p50_s']:.3f}s p99 {rows[-1]['p99_s']:.3f}s "
            f"maxq {max_queue}"
        )
    return rows


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="servebench", description=__doc__)
    ap.add_argument("--size", type=int, default=32)
    ap.add_argument("--generations", type=int, default=8)
    ap.add_argument(
        "--rates", default="4,16,64", metavar="R1,R2,...",
        help="offered request rates (req/s), one row each",
    )
    ap.add_argument("--requests", type=int, default=24,
                    help="requests submitted per rate row")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--queue-depth", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=4)
    ap.add_argument("--round", type=int, default=1)
    ap.add_argument("--out", default=None)
    ns = ap.parse_args(argv)

    import tempfile

    from gol_tpu.telemetry import ledger as ledger_mod

    rates = [float(r) for r in ns.rates.split(",") if r]
    workdir = tempfile.mkdtemp(prefix="servebench_")
    rows = run_curve(
        rates, ns.requests, ns.size, ns.generations, ns.slots,
        ns.queue_depth, ns.chunk, workdir,
    )
    payload = dict(
        header=ledger_mod.artifact_header("servebench"),
        note=(
            "open-loop serving-tier load curve (docs/SERVING.md). "
            "Each row: N small worlds offered at a fixed rate to a real "
            "HTTP server (ephemeral port, journal on tmpfs); completed "
            "vs 429-rejected counts, achieved req/s over the full "
            "drain, and p50/p99 end-to-end latency from the server's "
            "latency_s stamps. CPU rounds pin the curve shape "
            "(admission + queue dynamics); the TPU headline is: "
            "python benchmarks/servebench.py --size 256 "
            "--generations 64 --rates 16,64,256 --requests 96 "
            "--slots 8 --queue-depth 16"
        ),
        size=ns.size,
        generations=ns.generations,
        slots=ns.slots,
        queue_depth=ns.queue_depth,
        chunk=ns.chunk,
        requests_per_rate=ns.requests,
        rows=rows,
    )
    out = ns.out or str(REPO / f"SERVE_r{ns.round:02d}.json")
    pathlib.Path(out).write_text(json.dumps(payload, indent=1) + "\n")
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
