// gol: native CLI driver for the tpu-life framework.
//
// Plays the role of the reference's C driver entrypoint (main,
// gol-main.c:30-146): owns the process surface — argument count check with
// the usage message and exit(-1) (gol-main.c:43-47) — then hands the run to
// the TPU runtime.  Where the reference driver then calls MPI + CUDA
// directly, this one exec's the Python/JAX runtime (`python -m gol_tpu`),
// which performs the mesh setup, compiled generation loop, reporting and
// dumps; argument *values* are forwarded verbatim so atoi-equivalent
// parsing (gol-main.c:49-53) happens in one place, the runtime.
//
// Build: `make -C native gol`.  Usage identical to the reference:
//   ./gol <pattern> <worldSize> <iterations> <threadsPerBlock> <on_off> [--flags]

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <unistd.h>
#include <vector>

int main(int argc, char** argv) {
  // Count positionals (extension --flags and their values are passed through;
  // a value belonging to a --flag is not a positional).
  int positionals = 0;
  for (int i = 1; i < argc; ++i) {
    if (std::strncmp(argv[i], "--", 2) == 0) {
      // Flags with separate values: skip the value token when present.
      if (i + 1 < argc && std::strncmp(argv[i + 1], "--", 2) != 0 &&
          std::strcmp(argv[i], "--compat-banner") != 0)
        ++i;
      continue;
    }
    ++positionals;
  }
  if (positionals != 5) {
    std::printf(
        "GOL requires 5 arguments: pattern number, sq size of the world and "
        "the number of itterations, threads per block and output-on-off "
        "e.g. ./gol 0 32 2 512 0 \n");
    return -1;
  }

  const char* python = std::getenv("GOL_PYTHON");
  if (!python) python = "python3";

  std::vector<char*> args;
  args.push_back(const_cast<char*>(python));
  args.push_back(const_cast<char*>("-m"));
  args.push_back(const_cast<char*>("gol_tpu"));
  for (int i = 1; i < argc; ++i) args.push_back(argv[i]);
  args.push_back(nullptr);

  execvp(python, args.data());
  std::perror("gol: failed to exec python runtime");
  return 127;
}
