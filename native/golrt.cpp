// golrt: native host-runtime helpers for the tpu-life framework.
//
// The reference's host runtime is native C/CUDA; here the TPU compute path
// is XLA-compiled, and this library covers the *host-side* hot spots:
//
//  - world-dump formatting/writing, byte-identical to gol_printWorld
//    (gol-main.c:17-28: "Row %2d: " prefix with a globalized label, "%u "
//    per cell, banner line from gol-main.c:136).  Formatting a 65536^2
//    board is ~8.6 GB of text; the pure-Python renderer is the correctness
//    arbiter and this is the fast path.
//  - bit-pack/unpack between the dense uint8 board and the bit-packed
//    engine's uint32 words (bit i of word j = cell j*32 + i).
//
// Exposed with C linkage and called from Python via ctypes
// (gol_tpu/utils/native.py); no pybind11 dependency.

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

// Digits needed for a non-negative row label (%2d pads to >= 2 chars).
inline size_t label_width(int64_t v) {
  size_t w = 1;
  while (v >= 10) {
    v /= 10;
    ++w;
  }
  return w < 2 ? 2 : w;
}

// Renders "Row %2d: " into out; returns bytes written.
inline size_t render_prefix(int64_t label, char* out) {
  return static_cast<size_t>(std::sprintf(out, "Row %2ld: ", (long)label));
}

}  // namespace

extern "C" {

// Upper bound on the rendered size of a block (cells assumed single digit,
// which holds for 0/1 boards; multi-digit cells fall back to Python).
size_t golrt_format_world_size(int64_t h, int64_t w, int64_t row0) {
  size_t total = 0;
  for (int64_t i = 0; i < h; ++i) {
    total += 4 + 1 + label_width(row0 + i) + 2;  // "Row " + pad/label + ": "
    total += static_cast<size_t>(2 * w) + 1;     // "d " per cell + "\n"
  }
  return total;
}

// Renders the block; returns bytes written (<= golrt_format_world_size).
size_t golrt_format_world(const uint8_t* cells, int64_t h, int64_t w,
                          int64_t row0, char* out) {
  char* p = out;
  for (int64_t i = 0; i < h; ++i) {
    p += render_prefix(row0 + i, p);
    const uint8_t* row = cells + i * w;
    for (int64_t j = 0; j < w; ++j) {
      *p++ = static_cast<char>('0' + row[j]);
      *p++ = ' ';
    }
    *p++ = '\n';
  }
  return static_cast<size_t>(p - out);
}

// Writes banner + world to path. Returns 0 on success.
int golrt_write_rank_file(const char* path, const uint8_t* cells, int64_t h,
                          int64_t w, int64_t rank) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return 1;
  if (std::fprintf(f,
                   "######################### FINAL WORLD IN RANK %ld IS "
                   "###############################\n",
                   (long)rank) < 0) {
    std::fclose(f);
    return 2;
  }
  // Stream row by row to keep memory flat for multi-GB worlds.
  const size_t line_cap = 32 + static_cast<size_t>(2 * w) + 2;
  char* line = new char[line_cap];
  int rc = 0;
  const int64_t row0 = h * rank;
  for (int64_t i = 0; i < h && rc == 0; ++i) {
    char* p = line;
    p += render_prefix(row0 + i, p);
    const uint8_t* row = cells + i * w;
    for (int64_t j = 0; j < w; ++j) {
      *p++ = static_cast<char>('0' + row[j]);
      *p++ = ' ';
    }
    *p++ = '\n';
    if (std::fwrite(line, 1, static_cast<size_t>(p - line), f) !=
        static_cast<size_t>(p - line))
      rc = 3;
  }
  delete[] line;
  if (std::fclose(f) != 0 && rc == 0) rc = 4;
  return rc;
}

// uint8[n] 0/1 cells -> uint32[n/32] words; bit i of word j = cell j*32+i.
void golrt_pack_bits(const uint8_t* cells, int64_t n, uint32_t* words) {
  const int64_t nw = n / 32;
  for (int64_t j = 0; j < nw; ++j) {
    uint32_t word = 0;
    const uint8_t* c = cells + j * 32;
    for (int b = 0; b < 32; ++b) word |= static_cast<uint32_t>(c[b] & 1u) << b;
    words[j] = word;
  }
}

void golrt_unpack_bits(const uint32_t* words, int64_t nw, uint8_t* cells) {
  for (int64_t j = 0; j < nw; ++j) {
    const uint32_t word = words[j];
    uint8_t* c = cells + j * 32;
    for (int b = 0; b < 32; ++b) c[b] = static_cast<uint8_t>((word >> b) & 1u);
  }
}

}  // extern "C"
