"""Benchmark: cell-updates/sec on the local accelerator. Prints ONE JSON line.

Headline metric (BASELINE.md): cell-updates/sec/chip at 16384².  The
baseline target is 1e11 aggregate on a 256-chip v5e pod == 3.90625e8 per
chip; ``vs_baseline`` is measured-per-chip / per-chip-target, so 1.0 means
pod-parity pro-rated to this chip and bigger is better.

Runs every available engine on the real device (TPU under the driver; CPU
fallback works too), warm-compiled, timing only steady-state execution of a
multi-generation fori_loop.  The step count for the fast engines is 10240 —
BASELINE config 3's own generation count — because the whole loop is ONE
device program and each invocation pays ~130 ms of tunnel RPC: at 1024
steps that RPC was still ~46% of the wall time and the reported rate half
the chip's real one (measured 1.89e12 at 10240 steps vs 9.8e11 at 1024 in
the same session).  The slower contenders run shorter loops — their rates
only set the baseline bar, and per-second rates don't depend on the step
count beyond RPC dilution.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

from gol_tpu.utils.timing import fit_overhead, force_ready as _force

SIZE = 16384
STEPS = 10240
SLOW_STEPS = 1024
PER_CHIP_TARGET = 1e11 / 256.0


def _measure(evolve, board, repeats: int = 3) -> float:
    """Best-of-N wall of one chained invocation: the board stays
    device-resident through donation, so each repeat times exactly one
    program execution + readback fence.  The ONE timing discipline in
    this file — the wall claims and the overhead fits both go through
    it, so the methodology cannot drift between them."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        board = evolve(board)
        _force(board)
        best = min(best, time.perf_counter() - t0)
    return best


def _device_fit(build, board, long_n: int, repeats: int = 2,
                long_wall=None):
    """Two-point overhead fit (r5): wall time of one invocation through
    the tunnel is T(n) = a + b*n, with ``a`` the per-invocation overhead
    (0.13-0.26 s depending on session) and ``b`` the device's
    per-generation time.  Timing at (n/8, n) and fitting separates the
    chip's true rate from the tunnel — single-interval wall rates
    under-report by the overhead fraction, *differently per config*
    (see BASELINE.md r5).  ``build(n)`` returns an evolve closure for an
    n-step loop; boards chain device-resident through donation.
    ``long_wall`` reuses a wall the caller already measured at
    ``long_n`` (same compiled program), so only the short point costs
    new tunnel invocations.
    """
    import jax.numpy as jnp

    short_n = max(8, long_n // 8)
    walls = {} if long_wall is None else {long_n: long_wall}
    for n in (short_n,) if long_wall is not None else (short_n, long_n):
        fn = build(n)
        b = fn(jnp.array(board, copy=True))
        _force(b)  # warm (compile) outside timing
        walls[n] = _measure(fn, b, repeats)
    overhead, slope = fit_overhead(walls)
    return {
        "overhead_s_per_invocation": round(overhead, 4),
        "cells_per_s_device": float(f"{board.size / slope:.5g}"),
    }


def main() -> None:
    import jax
    import jax.numpy as jnp

    from gol_tpu.ops import stencil

    on_tpu = jax.devices()[0].platform == "tpu"
    size, steps, slow_steps = (
        (SIZE, STEPS, SLOW_STEPS) if on_tpu else (2048, 8, 8)
    )

    rng = np.random.default_rng(0)
    board = jnp.asarray((rng.random((size, size)) < 0.35).astype(np.uint8))

    # Each entry: (evolve, steps), built by ``entry`` from one step count
    # so the closure and the rate formula cannot drift — the fused-kernel
    # contenders run the full config-3 generation count, the slower tiers
    # a shorter loop.
    def entry(fn, n):
        return (lambda b: fn(b, n)), n

    engines = {}
    try:
        from gol_tpu.ops import bitlife

        engines["bitpack"] = entry(bitlife.evolve_dense_io, slow_steps)
    except ImportError:
        pass
    if on_tpu:
        # Pallas interpreter mode (non-TPU) is far too slow to bench.
        try:
            from gol_tpu.ops import pallas_bitlife

            engines["pallas_bitpack"] = entry(
                lambda b, s: pallas_bitlife.evolve(b, s, 1024), steps
            )
        except ImportError:
            pass
        try:
            from gol_tpu.ops import pallas_step

            engines["pallas"] = entry(
                lambda b, s: pallas_step.evolve(b, s, 512), slow_steps
            )
        except ImportError:
            pass
        try:
            # The flagship multi-chip program on this chip's 1-ring: the
            # fused kernel per shard behind an 8-deep ppermute exchange.
            from gol_tpu.parallel import mesh as mesh_mod
            from gol_tpu.parallel import packed as packed_mod

            ring = mesh_mod.make_mesh_1d(1)
            engines["pallas_ring"] = entry(
                lambda b, s: (
                    packed_mod.compiled_evolve_packed_pallas(ring, s)(b)
                ),
                steps,
            )
        except ImportError:
            pass
    engines["dense"] = entry(stencil.run, slow_steps)

    results = {}
    for name, (evolve, esteps) in engines.items():
        # Warm-up: compile + one full execution outside timing. Work on a
        # private copy since the engines donate their input.
        try:
            warm = jnp.array(board, copy=True)
            _force(evolve(warm))
        except Exception as e:  # noqa: BLE001 — report, never hide, a dropped engine
            print(f"bench: skipping engine {name!r}: {e!r}", file=sys.stderr)
            continue
        # The slow engines only contend for the baseline; don't spend
        # minutes on losers once a fast engine has set the bar.
        repeats = 3 if not results or name.startswith("pallas") else 2
        work = jnp.array(board, copy=True)
        dt = _measure(evolve, work, repeats)
        results[name] = ((size * size * esteps) / dt, esteps)

    if not results:
        print("bench: every engine failed; see stderr above", file=sys.stderr)
        raise SystemExit(1)
    best_name = max(results, key=lambda n: results[n][0])
    value, best_steps = results[best_name]
    line = {
        "metric": f"cell_updates_per_sec_per_chip@{size}^2x{best_steps}({best_name})",
        "value": value,
        "unit": "cell-updates/s",
        "vs_baseline": value / PER_CHIP_TARGET,
    }
    if best_name in ("pallas_bitpack", "pallas_ring"):
        # Roofline attribution (utils/roofline.py): emitted lane-ops/s —
        # including the temporal blocking's recomputed halo bands —
        # against the v5e VPU issue-peak model.  The kernel is
        # VPU-issue-bound: its HBM traffic at this shape is ~30 GB/s
        # against ~819 GB/s peak, two orders below the bandwidth roof.
        from gol_tpu.utils import roofline

        rl = (
            roofline.bench_roofline_2d(value, size, size, best_steps)
            if best_name == "pallas_bitpack"
            else roofline.bench_roofline_2d_ring(value, size, size)
        )
        line["mfu_vpu"] = rl.as_dict()
    if on_tpu:
        line["claims"] = _claims(results, size, board)
    print(json.dumps(line))


def _claims(results, size, board) -> list:
    """Pin EVERY headline perf claim in the driver artifact (VERDICT r3
    #3): 2-D flagship, flagship ring, lane-folded 32-word shard, and the
    sharded 3-D flagship — each with its roofline attribution — so no
    perf record exists only as BASELINE.md prose."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.utils import roofline

    claims = []

    def add(name, metric, value, rl, fit=None):
        rec = {
            "name": name,
            "metric": metric,
            "value": value,
            "unit": "cell-updates/s",
            "roofline": rl.as_dict(),
        }
        if fit is not None:
            # r5: the chip's overhead-fitted device rate alongside the
            # wall rate (the wall `value` stays the cross-round
            # comparable number; the fit is what a pod chip delivers
            # inside one program — see BASELINE.md r5).  MFU is linear
            # in the rate, so scaling the wall MFU by device/wall keeps
            # the formula in roofline.py alone.
            rec["device_fit"] = dict(fit)
            rec["device_fit"]["mfu_vpu_device"] = round(
                rl.mfu * fit["cells_per_s_device"] / value, 3
            )
        claims.append(rec)

    for name, key in (("flagship_2d", "pallas_bitpack"),
                      ("flagship_ring", "pallas_ring")):
        if key in results:
            value, esteps = results[key]
            rl = (
                roofline.bench_roofline_2d(value, size, size, esteps)
                if key == "pallas_bitpack"
                else roofline.bench_roofline_2d_ring(value, size, size)
            )
            try:
                if key == "pallas_bitpack":
                    from gol_tpu.ops import pallas_bitlife

                    build = lambda n: (
                        lambda b: pallas_bitlife.evolve(b, n, 1024)
                    )
                else:
                    from gol_tpu.parallel import mesh as mesh_mod
                    from gol_tpu.parallel import packed as packed_mod

                    ring1 = mesh_mod.make_mesh_1d(1)
                    build = lambda n: packed_mod.compiled_evolve_packed_pallas(
                        ring1, n
                    )
                # The long point is the wall _measure already produced
                # for this exact lru-cached program — only the short
                # point costs new tunnel invocations.
                fit = _device_fit(
                    build, board, esteps,
                    long_wall=size * size * esteps / value,
                )
            except Exception as e:  # noqa: BLE001 — report, never hide
                print(f"bench: {name} fit failed: {e!r}", file=sys.stderr)
                fit = None
            add(name, f"{size}^2x{esteps}", value, rl, fit)

    rng = np.random.default_rng(1)
    # Lane-folded narrow shards: BASELINE config 3's 16x16-pod shard
    # (16384 rows x 1024 cells = 32 packed words), on this chip's 1-ring,
    # in BOTH chunk forms — serial and comm/compute overlap (the form a
    # pod would actually run; VERDICT r4 #5: no headline configuration
    # may exist only as BASELINE prose).  Steps chosen so the session's
    # 0.2-0.26 s per-invocation tunnel overhead (r5 fits) stays under
    # ~20% of the ~1.3 s measured interval; the device_fit field removes
    # the rest.
    try:
        from gol_tpu.parallel import mesh as mesh_mod
        from gol_tpu.parallel import packed as packed_mod

        fh, fw, fsteps = 16384, 1024, 131072
        fboard = jnp.asarray((rng.random((fh, fw)) < 0.35).astype(np.uint8))
        ring = mesh_mod.make_mesh_1d(1)
    except Exception as e:  # noqa: BLE001 — degrade to missing claims,
        # never crash main after its measurements (the headline line
        # must still print).
        print(f"bench: folded claims unavailable: {e!r}", file=sys.stderr)
        ring = None
    for cname, overlap in () if ring is None else (
        ("folded_32word_shard", False),
        ("folded_32word_shard_overlap", True),
    ):
        try:
            fn = packed_mod.compiled_evolve_packed_pallas(
                ring, fsteps, overlap=overlap
            )
            _force(fn(jnp.array(fboard, copy=True)))
            dt = _measure(fn, jnp.array(fboard, copy=True))
            value = fh * fw * fsteps / dt
            # The fit gets its own guard: a transient tunnel error in its
            # extra invocations must not discard the measured wall claim.
            fit = None
            try:
                build = (
                    lambda n, o=overlap:
                    packed_mod.compiled_evolve_packed_pallas(
                        ring, n, overlap=o
                    )
                )
                fit = _device_fit(build, fboard, fsteps, long_wall=dt)
            except Exception as e:  # noqa: BLE001
                print(f"bench: {cname} fit failed: {e!r}", file=sys.stderr)
            add(
                cname,
                f"{fh}x{fw}x{fsteps}",
                value,
                roofline.bench_roofline_2d_ring(value, fh, fw),
                fit,
            )
        except Exception as e:  # noqa: BLE001 — report, never hide
            print(f"bench: {cname} claim failed: {e!r}", file=sys.stderr)

    try:
        # Sharded 3-D flagship at the config-5 headline size, full
        # exchange structure on this chip's degenerate rings.
        from gol_tpu.parallel import mesh as mesh_mod
        from gol_tpu.parallel import sharded3d
        from gol_tpu.parallel.mesh import place_private
        from gol_tpu.parallel.sharded3d import volume_sharding

        # x4096: the session-dependent 0.2-0.26 s per-invocation tunnel
        # overhead (r5 fits) is ~5% of the ~5.5 s measured interval at
        # this length (at x1024 it was ~17% and read 6.9e11 for a chip
        # doing 8.2e11); the device_fit field removes the rest.
        vsize, vsteps = 1024, 4096
        vol = jnp.asarray(
            (rng.random((vsize, vsize, vsize)) < 0.3).astype(np.uint8)
        )
        mesh3 = mesh_mod.make_mesh_3d((1, 1, 1), devices=jax.devices()[:1])
        fn3 = sharded3d.compiled_evolve3d_pallas(mesh3, vsteps)

        def run3(v):
            return fn3(place_private(v, volume_sharding(mesh3)))

        _force(run3(vol))
        dt = _measure(run3, vol)
        value = float(vsize) ** 3 * vsteps / dt
        fit3 = None
        try:
            # The fit chains donated device-resident volumes; on the
            # one-device mesh the engine accepts the committed array
            # without an explicit re-place.
            build3 = lambda n: sharded3d.compiled_evolve3d_pallas(mesh3, n)
            fit3 = _device_fit(
                build3,
                place_private(vol, volume_sharding(mesh3)),
                vsteps,
                long_wall=dt,
            )
        except Exception as e:  # noqa: BLE001
            print(f"bench: 3-D fit failed: {e!r}", file=sys.stderr)
        add(
            "sharded3d_flagship",
            f"{vsize}^3x{vsteps}",
            value,
            roofline.bench_roofline_3d_sharded(value, vsize),
            fit3,
        )
    except Exception as e:  # noqa: BLE001
        print(f"bench: 3-D claim failed: {e!r}", file=sys.stderr)
    return claims


if __name__ == "__main__":
    main()
