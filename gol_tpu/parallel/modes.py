"""The (engine, shard-mode, halo-depth) legality matrix — one authority.

Before PR 9 the answer to "is this combo legal?" lived in four places
that had already drifted: ``runtime.__post_init__`` grew an ad-hoc
if-chain per rule (the stale ``halo_depth > 1 requires shard_mode
'explicit'`` message survived two releases after overlap learned deep
bands), the engine builders each re-validated their own subset with
slightly different text, and the CLI and verifier re-derived the matrix
by hand.  This module is now the single source of truth: the runtime
validates every sharded configuration through :func:`check_combo` +
:func:`check_depth`, and the per-combo error messages are pinned by
``tests/test_mode_plan.py`` so a future mode can't resurrect the drift.

The positive matrix (``ENGINE_MODES``):

====================  ========  =======  ====  ========
engine                explicit  overlap  auto  pipeline
====================  ========  =======  ====  ========
dense                 any k     any k    k=1   any k
bitpack               any k     any k*   --    any k
pallas_bitpack        k%8       k%8      --    k%8
activity              k=1       --       --    --
ooc                   --        --       --    --
====================  ========  =======  ====  ========

``ooc`` (the out-of-core streaming tier, docs/STREAMING.md) is
host-driven and single-process by construction — the board lives in
host RAM and bands stream through one device, so there is no sharded
ring program to pick a mode for; every (ooc, mode) cell rejects with a
message naming the legal alternatives (mesh-none ooc, or a sharded
engine).

(*) the packed depth-1 overlap keeps its hand-written 1-D program;
depth-1 2-D and every deeper form run the generic interior/boundary
split in :mod:`gol_tpu.parallel.halo`.  ``pipeline`` is the cross-chunk
double buffer: the loop carries ``(block, bands)`` and ships chunk
N+1's ghost band while chunk N's interior computes.  Depth limits
against shard extents (the ghost shell must come from the immediate
ring neighbor; packed engines count the width axis in 32-cell words)
are geometry checks, kept separate in :func:`check_depth`.
"""

from __future__ import annotations

from typing import Optional

SHARD_MODES = ("explicit", "overlap", "auto", "pipeline")

#: Shard modes with a built program per (resolved) engine.
ENGINE_MODES = {
    "dense": ("explicit", "overlap", "auto", "pipeline"),
    "bitpack": ("explicit", "overlap", "pipeline"),
    "pallas_bitpack": ("explicit", "overlap", "pipeline"),
    "activity": ("explicit",),
}

#: Modes whose exchange ships a deeper-than-one-generation ghost band.
DEEP_BAND_MODES = ("explicit", "overlap", "pipeline")


def mode_rejection(engine: str, shard_mode: str) -> Optional[str]:
    """The canonical rejection message for an (engine, mode) cell that
    has no program, or ``None`` when the combination is supported."""
    if shard_mode not in SHARD_MODES:
        return (
            f"unknown shard_mode {shard_mode!r}; expected one of "
            f"{SHARD_MODES}"
        )
    if engine == "ooc":
        return (
            "the out-of-core streaming engine is host-driven and has no "
            f"sharded ring program (got shard_mode {shard_mode!r}); run "
            "--engine ooc without a mesh (it streams bands through one "
            "device), or pick a sharded engine ('dense', 'bitpack', "
            "'pallas_bitpack', 'activity') for mesh runs"
        )
    allowed = ENGINE_MODES.get(engine)
    if allowed is None or shard_mode in allowed:
        return None
    if engine == "bitpack" and shard_mode == "auto":
        return (
            "the bit-packed sharded engine has no auto-SPMD program; "
            "shard_mode 'auto' applies to engine 'dense'"
        )
    if engine == "pallas_bitpack":
        return (
            "the sharded Pallas engine has the explicit, overlap and "
            "pipeline ring programs only (got shard_mode "
            f"{shard_mode!r})"
        )
    if engine == "activity":
        return (
            "the sharded activity engine has the explicit ring program "
            f"only (got shard_mode {shard_mode!r})"
        )
    return (
        f"engine {engine!r} has no {shard_mode!r} program; supported "
        f"modes: {allowed}"
    )


def check_combo(engine: str, shard_mode: str, halo_depth: int) -> None:
    """Raise the canonical ``ValueError`` for an illegal (engine, mode,
    depth) combination — mesh-independent legality only."""
    reason = mode_rejection(engine, shard_mode)
    if reason is not None:
        raise ValueError(reason)
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")
    if halo_depth > 1 and shard_mode not in DEEP_BAND_MODES:
        # Only 'auto' survives the matrix to reach this rule today, but
        # the check is written against DEEP_BAND_MODES so a future mode
        # states its band policy instead of inheriting one silently.
        raise ValueError(
            "halo_depth > 1 (temporal blocking) requires shard_mode "
            "'explicit', 'overlap' or 'pipeline' (got "
            f"{shard_mode!r}): auto-SPMD derives its own per-generation "
            "exchanges, so there is no band to deepen"
        )
    if engine == "pallas_bitpack" and halo_depth > 1 and halo_depth % 8:
        raise ValueError(
            "the sharded Pallas engine needs halo_depth to be a "
            f"multiple of 8 (DMA row alignment), got {halo_depth}"
        )
    if engine == "activity" and halo_depth != 1:
        raise ValueError(
            "engine 'activity' exchanges one-tile mask halos per "
            f"generation; halo_depth must be 1, got {halo_depth}"
        )


def check_depth(
    halo_depth: int,
    shard_h: int,
    shard_w: int,
    two_d: bool,
    units: str = "cells",
) -> None:
    """Depth-vs-shard-extent limit: the ghost shell must come entirely
    from the immediate ring neighbor.

    ``shard_h``/``shard_w`` are the per-shard extents in each axis's
    exchange quantum — rows vertically, 32-cell words horizontally for
    the packed engines (``units`` names them for the message).  A 2-D
    mesh extends the width axis even when its cols ring has size 1 (the
    ring degenerates to the local wrap), so the width limit applies
    whenever ``two_d`` is set.
    """
    limit = min(shard_h, shard_w) if two_d else shard_h
    if halo_depth > limit:
        raise ValueError(
            f"halo_depth {halo_depth} exceeds the shard extent "
            f"({shard_h}×{shard_w} rows×{units}); the ghost shell must "
            "come from the immediate ring neighbor"
        )
