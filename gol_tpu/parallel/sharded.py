"""Sharded generation engines: ``shard_map`` + ``lax.ppermute`` halo rings.

TPU-native replacement for the reference's L2 distributed layer.  The
reference exchanges one ghost row with each ring neighbor per step via
nonblocking MPI point-to-point (2×``MPI_Irecv`` gol-main.c:97-100,
2×``MPI_Isend`` gol-main.c:104-107, ``MPI_Wait`` gol-main.c:110-111) with
mod-ring neighbor ids (gol-main.c:86-87) — and, due to bug B1, actually
ships stale t=0 rows forever.  Here each step's halos are sliced from the
*live* block and shifted with ``lax.ppermute`` ring permutations inside one
compiled program: fresh by construction, no tags, no request management,
ordering owned by the XLA scheduler, traffic riding ICI.

Two decompositions (SURVEY §7 steps 4 and 6):

- **1-D rows** (the reference's own layout): two ppermutes/step deliver the
  up/down ghost rows; columns wrap locally since the width axis is
  unsharded.
- **2-D blocks** (BASELINE config 3): two-phase exchange — vertical edge
  rows first, then the *halo-extended* blocks' edge columns horizontally,
  which carries the four corner cells for free (the part with no reference
  analog: MPI codes typically need 8 messages or a diagonal phase; the
  ordered two-phase does it in 4).

Three program modes:

- ``"explicit"`` — hand-placed ppermutes (the analog of the reference's
  explicit messaging), halo-extend then stencil.
- ``"overlap"`` — same exchange, but the stencil is split interior/boundary
  so the interior (the bulk) has no data dependency on the ppermutes and
  XLA's latency-hiding scheduler runs exchange and compute concurrently —
  the interior-first overlap the reference attempted with nonblocking MPI
  but forfeited by calling ``MPI_Wait`` before the kernel
  (gol-main.c:110-114).
- ``"auto"`` — XLA's SPMD partitioner derives collective-permutes from the
  sharded torus rolls: the "annotate shardings, let the compiler insert
  collectives" recipe.

The whole multi-generation loop runs inside one jitted program
(``lax.fori_loop``), so there is no per-step host round-trip — the
reference pays ``cudaDeviceSynchronize`` per step (gol-with-cuda.cu:277).
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.ops import stencil
from gol_tpu.parallel.halo import (
    halo_extend,
    overlap_local_loop,
    pipelined_local_loop,
    ring,
)
from gol_tpu.parallel.mesh import COLS, ROWS, board_sharding, validate_geometry
from gol_tpu.parallel.mesh import place_private as mesh_place_private

MODES = ("explicit", "overlap", "auto", "pipeline")


def exchange_row_halos(block: jax.Array, num_rows: int):
    """Fresh up/down ghost rows for a row-sharded block.

    One up-shift and one down-shift ppermute — the ``previous_last_row`` /
    ``next_first_row`` of the reference (gol-main.c:11), except re-sliced
    from the live board every step (fixing B1 by construction).
    Returns (top_row[W], bottom_row[W]).
    """
    top = lax.ppermute(block[-1:], ROWS, ring(num_rows, 1))
    bottom = lax.ppermute(block[:1], ROWS, ring(num_rows, -1))
    return top[0], bottom[0]


def exchange_block_halos(block: jax.Array, num_rows: int, num_cols: int):
    """Halo-extend a 2-D-sharded block to [h+2, w+2] via two-phase ppermute.

    Phase 1 ships edge *rows* vertically; phase 2 ships the edge *columns of
    the already row-extended block* horizontally, so each corner cell makes
    two hops (vertical then horizontal) and lands correctly — no diagonal
    messages needed.  Implemented by the generic N-phase extension in
    :mod:`gol_tpu.parallel.halo` (shared with the 3-D engine).
    """
    return halo_extend(block, ((0, ROWS, num_rows), (1, COLS, num_cols)))


@functools.lru_cache(maxsize=64)
def compiled_evolve(mesh: Mesh, steps: int, mode: str, halo_depth: int = 1):
    """Build + jit the sharded evolve for (mesh, steps, mode, halo_depth).

    ``halo_depth=k > 1`` is temporal blocking (modes "explicit",
    "overlap" and "pipeline"): each exchange ships a k-deep ghost band
    and the shard then steps k generations locally, consuming one ghost
    layer per generation — 2 ppermutes per axis per k generations
    instead of per generation, at the cost of a k-wide band of redundant
    compute at shard edges (negligible for big shards, a large win when
    exchange latency dominates).  "overlap" splits each chunk
    interior/boundary so the exchange hides under the interior stencil
    (the depth-1 split generalized by
    :func:`gol_tpu.parallel.halo.overlap_local_loop`); "pipeline"
    additionally double-buffers ACROSS chunks — the loop carries
    ``(block, bands)`` and ships chunk N+1's band from chunk N's
    boundary slabs while chunk N's interior computes
    (:func:`gol_tpu.parallel.halo.pipelined_local_loop`), so no chunk
    ever starts by waiting on the ring.

    The returned function donates its input buffer (the framework's double
    buffer); callers who need the input afterwards must pass a copy.
    """
    if mode not in MODES:
        raise ValueError(f"unknown mode {mode!r}; expected one of {MODES}")
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")
    if halo_depth > 1 and mode == "auto":
        raise ValueError(
            f"halo_depth > 1 requires mode 'explicit', 'overlap' or "
            f"'pipeline' (got mode {mode!r}): auto-SPMD derives its own "
            "per-generation exchanges, so there is no band to deepen"
        )
    if mode == "auto":
        # XLA SPMD derives collective-permutes from the sharded torus rolls.
        return jax.jit(
            lambda b: lax.fori_loop(0, steps, lambda _, x: stencil.step(x), b),
            in_shardings=board_sharding(mesh),
            out_shardings=board_sharding(mesh),
            donate_argnums=0,
        )

    two_d = COLS in mesh.axis_names
    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)
    overlap = mode == "overlap"

    if two_d:
        phases = ((0, ROWS, num_rows), (1, COLS, num_cols))
        shrink_step = stencil.step_halo_full

        def chunk(blk, k):
            ext = halo_extend(blk, phases, depth=k)
            for _ in range(k):  # each valid-mode step consumes one layer
                ext = stencil.step_halo_full(ext)
            return ext

        def overlap_body(_, blk):
            ext = halo_extend(blk, phases)
            return stencil.step_halo_full_overlap(blk, ext)

        spec = P(ROWS, COLS)
    else:
        phases = ((0, ROWS, num_rows),)
        shrink_step = lambda ext: stencil.step_halo_rows(
            ext[1:-1], ext[0], ext[-1]
        )

        def chunk(blk, k):
            ext = halo_extend(blk, phases, depth=k)
            for _ in range(k):
                ext = stencil.step_halo_rows(ext[1:-1], ext[0], ext[-1])
            return ext

        def overlap_body(_, blk):
            top, bottom = exchange_row_halos(blk, num_rows)
            return stencil.step_halo_rows_overlap(blk, top, bottom)

        spec = P(ROWS, None)

    if mode == "pipeline":
        # Cross-chunk double buffer: the loop carries (block, bands);
        # chunk N+1's band ships from chunk N's boundary slabs while
        # chunk N's interior computes (gol_tpu.parallel.halo).
        local_loop = pipelined_local_loop(shrink_step, phases, steps, halo_depth)
    elif overlap and halo_depth > 1:
        # Depth-k interior/boundary split: the depth-1 restriction lifted
        # — the interior launch still carries no ppermute dependency.
        local_loop = overlap_local_loop(shrink_step, phases, steps, halo_depth)
    else:
        # Depth-1 explicit mode IS a one-generation chunk; depth-1
        # overlap keeps its hand-written split (byte-identical program).
        body = overlap_body if overlap else (lambda _, blk: chunk(blk, 1))

        if halo_depth == 1:
            local_loop = lambda b: lax.fori_loop(0, steps, body, b)
        else:
            full, rem = divmod(steps, halo_depth)

            def local_loop(b):
                if full:
                    b = lax.fori_loop(
                        0, full, lambda _, x: chunk(x, halo_depth), b
                    )
                if rem:
                    b = chunk(b, rem)
                return b

    local = compat.shard_map(
        local_loop,
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
    )
    return jax.jit(local, donate_argnums=0)


def place_private(board: jax.Array, mesh: Mesh) -> jax.Array:
    """Canonically shard ``board`` in a buffer safe to donate.

    See :func:`gol_tpu.parallel.mesh.place_private` for the aliasing
    rationale.
    """
    return mesh_place_private(board, board_sharding(mesh))


def evolve_sharded(
    board: jax.Array,
    steps: int,
    mesh: Mesh,
    mode: str = "explicit",
    halo_depth: int = 1,
) -> jax.Array:
    """Evolve a board sharded over ``mesh`` for ``steps`` generations.

    The board is placed with the canonical sharding if it isn't already, and
    the caller's array is never consumed (see :func:`place_private`).
    Performance-critical callers that *want* the donation manage placement
    themselves and call :func:`compiled_evolve`.  Semantics are the correct
    torus (fresh halos) in every mode and at every ``halo_depth``.
    """
    if mode not in MODES:
        raise ValueError(f"unknown mode {mode!r}; expected one of {MODES}")
    validate_geometry(board.shape, mesh)
    return compiled_evolve(mesh, steps, mode, halo_depth)(
        place_private(board, mesh)
    )


def lower_sharded(
    shape,
    dtype,
    steps: int,
    mesh: Mesh,
    mode: str = "explicit",
    halo_depth: int = 1,
):
    """AOT-lower the sharded evolve for compile-cost inspection / warmup."""
    spec = jax.ShapeDtypeStruct(shape, dtype, sharding=board_sharding(mesh))
    return compiled_evolve(mesh, steps, mode, halo_depth).lower(spec)
