"""Bit-packed sharded engine: 32-cells/word Life under shard_map halo rings.

The two perf tiers of SURVEY §7 composed: the carry-save bit-packed step
(:mod:`gol_tpu.ops.bitlife`, 8× less HBM traffic than dense uint8) runs
per-shard under ``shard_map``, with ``lax.ppermute`` ring exchanges shipping
*packed* halos — so the wire traffic of the reference's ghost-row messages
(``MPI_UNSIGNED_CHAR`` × width, gol-main.c:97-107) also drops 8×: one
uint32 word per 32 cells of boundary instead of 32 bytes.

Decompositions mirror :mod:`gol_tpu.parallel.sharded`:

- **1-D rows**: two ppermutes/step deliver packed up/down ghost rows;
  columns wrap locally (width axis unsharded) via the lane-carry roll inside
  the packed step.
- **2-D blocks**: two-phase exchange — edge *rows* of packed words
  vertically, then edge *word columns* of the row-extended block
  horizontally, which carries the four corner words for free.  The
  horizontal halo quantum is a full 32-cell word even though only 1
  boundary bit is consumed; a word is the cheapest addressable unit and
  the traffic is still ≤ the dense engine's 1-byte column halo.

Pack/unpack happen once per evolve call, per shard, inside the compiled
program — dense uint8 in, dense uint8 out, cost amortized over the whole
``fori_loop`` (same contract as :func:`gol_tpu.ops.bitlife.evolve_dense_io`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu.ops import bitlife
from gol_tpu.parallel.halo import build_ring_engine
from gol_tpu.parallel.mesh import COLS, ROWS, validate_geometry
from gol_tpu.parallel.sharded import (
    exchange_block_halos,
    exchange_row_halos,
    place_private,
)


def validate_packed_geometry(shape, mesh: Mesh) -> None:
    """Packed sharding needs each shard's width to pack into whole words."""
    validate_geometry(shape, mesh)
    cols = mesh.shape.get(COLS, 1)
    shard_w = shape[1] // cols
    if shard_w % bitlife.BITS != 0:
        raise ValueError(
            f"bit-packed sharded engine needs shard width divisible by "
            f"{bitlife.BITS}; board width {shape[1]} over {cols} mesh cols "
            f"gives shard width {shard_w}"
        )


def step_packed_halo_rows(block: jax.Array, num_rows: int) -> jax.Array:
    """One packed generation of a row-sharded shard with fresh ring halos.

    ``block`` is the shard's packed words ``uint32[h, W/32]``.  The dense
    engine's ring exchange (:func:`~gol_tpu.parallel.sharded.
    exchange_row_halos`, dtype-agnostic) ships the packed boundary rows —
    the ``previous_last_row``/``next_first_row`` of gol-main.c:11, re-sliced
    live each step (B1 fixed by construction), at 1/8th the bytes.
    """
    top, bottom = exchange_row_halos(block, num_rows)
    ext = jnp.concatenate([top[None], block, bottom[None]], axis=0)
    return bitlife.step_packed_vext(ext)


def step_packed_halo_blocks(
    block: jax.Array, num_rows: int, num_cols: int
) -> jax.Array:
    """One packed generation of a 2-D-sharded shard with fresh ring halos.

    The same two-phase edge exchange as the dense engine
    (:func:`gol_tpu.parallel.sharded.exchange_block_halos` is dtype-agnostic
    and reused directly), but the halo quantum is a packed word: phase 2
    ships the edge word-columns of the already row-extended block, so the
    corner *words* make two hops and land with their boundary bits intact.
    """
    ext = exchange_block_halos(block, num_rows, num_cols)  # [h+2, nw+2]
    return bitlife.step_packed_halo_full(ext)


@functools.lru_cache(maxsize=64)
def compiled_evolve_packed_overlap(mesh: Mesh, steps: int):
    """Packed 1-D ring evolve in comm/compute-overlap form.

    Counterpart of the dense engine's ``--shard-mode overlap``
    (:func:`gol_tpu.parallel.sharded.compiled_evolve`): interior rows
    never wait on the halo ppermutes.  1-D row meshes only — the 2-D
    packed boundary ring needs word-carry edge columns whose overlap form
    has no payoff at word granularity.  Single-layer halos (overlap's
    interior/boundary split assumes depth 1).
    """
    if COLS in mesh.axis_names:
        raise ValueError(
            "packed overlap mode is 1-D (row-ring) only; use shard_mode "
            "'explicit' on 2-D meshes"
        )
    num_rows = mesh.shape[ROWS]

    def body(_, blk):
        top, bottom = exchange_row_halos(blk, num_rows)
        return bitlife.step_packed_overlap_rows(blk, top, bottom)

    def local(board):
        packed = bitlife.pack(board)
        packed = lax.fori_loop(0, steps, body, packed)
        return bitlife.unpack(packed)

    shmapped = jax.shard_map(
        local, mesh=mesh, in_specs=P(ROWS, None), out_specs=P(ROWS, None)
    )
    return jax.jit(shmapped, donate_argnums=0)


@functools.lru_cache(maxsize=64)
def compiled_evolve_packed(mesh: Mesh, steps: int, halo_depth: int = 1):
    """Build + jit the packed sharded evolve for (mesh, steps, halo_depth).

    Dense uint8 board in/out with the canonical mesh sharding; pack /
    ``fori_loop`` over packed steps / unpack all run per-shard inside one
    compiled program.  The input buffer is donated (the double buffer).

    ``halo_depth=k > 1`` is temporal blocking on the packed words: one
    exchange ships a k-deep ghost band and the shard steps k generations
    locally, consuming one ghost layer per step.  The consumption quantum
    matches the exchange quantum — a packed *word* column (32 cells)
    horizontally on 2-D meshes, a packed row vertically — so the 2-D wire
    cost per k generations is ``2k`` ghost rows + ``2k`` ghost word-columns
    against ``2k`` rows + ``2k`` single-cell columns for the dense engine;
    still ~8× fewer bytes on the row axis, break-even on the word axis at
    k=1, and k× fewer ppermute latencies either way.
    """
    return build_ring_engine(
        mesh,
        steps,
        halo_depth,
        step_1d=bitlife.step_packed_vext,  # consumes a row layer
        step_2d=bitlife.step_packed_halo_full,  # row + word-column layer
        pack=bitlife.pack,
        unpack=bitlife.unpack,
    )


@functools.lru_cache(maxsize=64)
def compiled_evolve_packed_pallas(
    mesh: Mesh, steps: int, halo_depth: int = 8, tile_hint: int = 256,
    rule=None,
):
    """Sharded evolve running the fused Pallas kernel per shard.

    The flagship multi-chip configuration: per chunk, one ``halo_extend``
    ring exchange ships a ``halo_depth``-deep packed ghost band
    (``lax.ppermute`` over ICI), then the shard steps ``halo_depth``
    generations inside a single Pallas launch
    (:func:`gol_tpu.ops.pallas_bitlife.multi_step_pallas_packed_ext` — the
    no-wrap variant; the exchanged band replaces the torus DMA).  1-D row
    meshes only (the kernel's lane word-ring assumes the width axis is
    unsharded); ``halo_depth`` must be a multiple of 8 (DMA row
    alignment).  A non-multiple remainder of ``steps`` runs on the jnp
    packed step.  Optional ``rule`` switches the kernel tail to the
    generic plane matcher.
    """
    from gol_tpu.ops import pallas_bitlife

    if COLS in mesh.axis_names:
        raise ValueError(
            "the sharded Pallas engine is 1-D (row-ring) only; use engine "
            "'bitpack' on 2-D meshes"
        )
    if halo_depth < 8 or halo_depth % 8:
        raise ValueError(
            f"the sharded Pallas engine needs halo_depth to be a multiple "
            f"of 8 (DMA row alignment), got {halo_depth}"
        )
    from gol_tpu.parallel.halo import halo_extend

    num_rows = mesh.shape[ROWS]
    phases = ((0, ROWS, num_rows),)
    full, rem = divmod(steps, halo_depth)

    def chunk(p_u32, tile):
        # Bit-identical int32 view only around the kernel; the jnp packed
        # ops stay on uint32 (their right-shifts must be logical).
        ext = lax.bitcast_convert_type(
            halo_extend(p_u32, phases, depth=halo_depth), jnp.int32
        )
        out = pallas_bitlife.multi_step_pallas_packed_ext(
            ext, tile, halo_depth, rule
        )
        return lax.bitcast_convert_type(out, jnp.uint32)

    def jnp_step(ext):
        if rule is None:
            return bitlife.step_packed_vext(ext)
        from gol_tpu.ops import rules as rules_mod

        return rules_mod.step_rule_packed_vext(ext, rule)

    def local(board):
        h, w = board.shape  # per-shard block (static under shard_map)
        if jax.default_backend() == "tpu" and (w // bitlife.BITS) % 128:
            raise ValueError(
                "the sharded Pallas engine needs each shard's packed width "
                "to fill whole 128-lane tiles on TPU: shard width must be "
                f"a multiple of {128 * bitlife.BITS}, got {w}"
            )
        if h % 8 or h < halo_depth:
            raise ValueError(
                f"the sharded Pallas engine needs shard height (got {h}) "
                f"to be a multiple of 8 and >= the exchanged band depth "
                f"{halo_depth}"
            )
        packed = bitlife.pack(board)
        tile = pallas_bitlife.pick_tile(
            packed.shape[0], packed.shape[1], tile_hint
        )
        if full:
            packed = lax.fori_loop(
                0, full, lambda _, p: chunk(p, tile), packed
            )
        if rem:
            # One depth-rem exchange feeds all leftover generations (the
            # blocked-chunk pattern of halo.blocked_local_loop), instead of
            # rem separate ppermute pairs.
            ext = halo_extend(packed, phases, depth=rem)
            for _ in range(rem):  # each step consumes one ghost layer
                ext = jnp_step(ext)
            packed = ext
        return bitlife.unpack(packed)

    # check_vma=False: pallas_call's out ShapeDtypeStruct carries no
    # varying-mesh-axes annotation, and the kernel is already per-shard.
    shmapped = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=P(ROWS, None),
        out_specs=P(ROWS, None),
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=0)


def evolve_sharded_packed(board: jax.Array, steps: int, mesh: Mesh) -> jax.Array:
    """Evolve a dense board over ``mesh`` with the bit-packed engine.

    Placement/copy contract matches
    :func:`gol_tpu.parallel.sharded.evolve_sharded`: the caller's array is
    never consumed (see :func:`gol_tpu.parallel.sharded.place_private`).
    """
    validate_packed_geometry(board.shape, mesh)
    return compiled_evolve_packed(mesh, steps)(place_private(board, mesh))
