"""Bit-packed sharded engine: 32-cells/word Life under shard_map halo rings.

The two perf tiers of SURVEY §7 composed: the carry-save bit-packed step
(:mod:`gol_tpu.ops.bitlife`, 8× less HBM traffic than dense uint8) runs
per-shard under ``shard_map``, with ``lax.ppermute`` ring exchanges shipping
*packed* halos — so the wire traffic of the reference's ghost-row messages
(``MPI_UNSIGNED_CHAR`` × width, gol-main.c:97-107) also drops 8×: one
uint32 word per 32 cells of boundary instead of 32 bytes.

Decompositions mirror :mod:`gol_tpu.parallel.sharded`:

- **1-D rows**: two ppermutes/step deliver packed up/down ghost rows;
  columns wrap locally (width axis unsharded) via the lane-carry roll inside
  the packed step.
- **2-D blocks**: two-phase exchange — edge *rows* of packed words
  vertically, then edge *word columns* of the row-extended block
  horizontally, which carries the four corner words for free.  The
  horizontal halo quantum is a full 32-cell word even though only 1
  boundary bit is consumed; a word is the cheapest addressable unit and
  the traffic is still ≤ the dense engine's 1-byte column halo.

Pack/unpack happen once per evolve call, per shard, inside the compiled
program — dense uint8 in, dense uint8 out, cost amortized over the whole
``fori_loop`` (same contract as :func:`gol_tpu.ops.bitlife.evolve_dense_io`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.ops import bitlife
from gol_tpu.parallel.halo import build_ring_engine, ring
from gol_tpu.parallel.mesh import COLS, ROWS, validate_geometry
from gol_tpu.parallel.sharded import (
    exchange_block_halos,
    exchange_row_halos,
    place_private,
)


def validate_packed_geometry(shape, mesh: Mesh) -> None:
    """Packed sharding needs each shard's width to pack into whole words."""
    validate_geometry(shape, mesh)
    cols = mesh.shape.get(COLS, 1)
    shard_w = shape[1] // cols
    if shard_w % bitlife.BITS != 0:
        raise ValueError(
            f"bit-packed sharded engine needs shard width divisible by "
            f"{bitlife.BITS}; board width {shape[1]} over {cols} mesh cols "
            f"gives shard width {shard_w}"
        )


def fold_rows(x: jax.Array, f: int) -> jax.Array:
    """``[h, nw] -> [h/f, f*nw]``: row group ``g`` (shard rows
    ``[g*h/f, (g+1)*h/f)``) occupies lanes ``[g*nw, (g+1)*nw)``.

    The narrow-shard layout of the sharded Pallas engine: vertical
    neighbors stay vertically adjacent *within* each group, so the fused
    kernel's row-window stencil is untouched; the group seams (vertical at
    the band rows, horizontal at the lane wrap) are repaired by the band
    construction and the exact-edge overwrite.
    """
    h, nw = x.shape
    return x.reshape(f, h // f, nw).transpose(1, 0, 2).reshape(h // f, f * nw)


def unfold_rows(x: jax.Array, f: int) -> jax.Array:
    """Inverse of :func:`fold_rows`."""
    hg, fnw = x.shape
    nw = fnw // f
    return x.reshape(hg, f, nw).transpose(1, 0, 2).reshape(f * hg, nw)


def step_packed_halo_rows(block: jax.Array, num_rows: int) -> jax.Array:
    """One packed generation of a row-sharded shard with fresh ring halos.

    ``block`` is the shard's packed words ``uint32[h, W/32]``.  The dense
    engine's ring exchange (:func:`~gol_tpu.parallel.sharded.
    exchange_row_halos`, dtype-agnostic) ships the packed boundary rows —
    the ``previous_last_row``/``next_first_row`` of gol-main.c:11, re-sliced
    live each step (B1 fixed by construction), at 1/8th the bytes.
    """
    top, bottom = exchange_row_halos(block, num_rows)
    ext = jnp.concatenate([top[None], block, bottom[None]], axis=0)
    return bitlife.step_packed_vext(ext)


def step_packed_halo_blocks(
    block: jax.Array, num_rows: int, num_cols: int
) -> jax.Array:
    """One packed generation of a 2-D-sharded shard with fresh ring halos.

    The same two-phase edge exchange as the dense engine
    (:func:`gol_tpu.parallel.sharded.exchange_block_halos` is dtype-agnostic
    and reused directly), but the halo quantum is a packed word: phase 2
    ships the edge word-columns of the already row-extended block, so the
    corner *words* make two hops and land with their boundary bits intact.
    """
    ext = exchange_block_halos(block, num_rows, num_cols)  # [h+2, nw+2]
    return bitlife.step_packed_halo_full(ext)


@functools.lru_cache(maxsize=64)
def compiled_evolve_packed_overlap(mesh: Mesh, steps: int):
    """Packed 1-D ring evolve in comm/compute-overlap form.

    Counterpart of the dense engine's ``--shard-mode overlap``
    (:func:`gol_tpu.parallel.sharded.compiled_evolve`): interior rows
    never wait on the halo ppermutes.  1-D row meshes only — the 2-D
    packed boundary ring needs word-carry edge columns whose overlap form
    has no payoff at word granularity.  Single-layer halos (overlap's
    interior/boundary split assumes depth 1).
    """
    if COLS in mesh.axis_names:
        raise ValueError(
            "packed overlap mode is 1-D (row-ring) only; use shard_mode "
            "'explicit' on 2-D meshes"
        )
    num_rows = mesh.shape[ROWS]

    def body(_, blk):
        top, bottom = exchange_row_halos(blk, num_rows)
        return bitlife.step_packed_overlap_rows(blk, top, bottom)

    def local(board):
        packed = bitlife.pack(board)
        packed = lax.fori_loop(0, steps, body, packed)
        return bitlife.unpack(packed)

    shmapped = compat.shard_map(
        local, mesh=mesh, in_specs=P(ROWS, None), out_specs=P(ROWS, None)
    )
    return jax.jit(shmapped, donate_argnums=0)


@functools.lru_cache(maxsize=64)
def compiled_evolve_packed(
    mesh: Mesh, steps: int, halo_depth: int = 1, mode: str = "explicit"
):
    """Build + jit the packed sharded evolve for (mesh, steps, halo_depth).

    Dense uint8 board in/out with the canonical mesh sharding; pack /
    ``fori_loop`` over packed steps / unpack all run per-shard inside one
    compiled program.  The input buffer is donated (the double buffer).

    ``halo_depth=k > 1`` is temporal blocking on the packed words: one
    exchange ships a k-deep ghost band and the shard steps k generations
    locally, consuming one ghost layer per step.  The consumption quantum
    matches the exchange quantum — a packed *word* column (32 cells)
    horizontally on 2-D meshes, a packed row vertically — so the 2-D wire
    cost per k generations is ``2k`` ghost rows + ``2k`` ghost word-columns
    against ``2k`` rows + ``2k`` single-cell columns for the dense engine;
    still ~8× fewer bytes on the row axis, break-even on the word axis at
    k=1, and k× fewer ppermute latencies either way.

    ``mode`` picks the chunk loop (:data:`gol_tpu.parallel.halo.
    LOCAL_LOOPS`): "explicit" (serial blocked chunks), "overlap" (depth-k
    interior/boundary split — the packed counterpart of the dense
    engine's lifted overlap mode, now on 1-D AND 2-D meshes at any k),
    or "pipeline" (the cross-chunk double buffer: chunk N+1's packed
    band ships while chunk N's interior computes).  All modes are pinned
    bit-identical to explicit.
    """
    return build_ring_engine(
        mesh,
        steps,
        halo_depth,
        step_1d=bitlife.step_packed_vext,  # consumes a row layer
        step_2d=bitlife.step_packed_halo_full,  # row + word-column layer
        pack=bitlife.pack,
        unpack=bitlife.unpack,
        mode=mode,
    )


@functools.lru_cache(maxsize=64)
def compiled_evolve_packed_pallas(
    mesh: Mesh, steps: int, halo_depth: int = 8, tile_hint: int = 1024,
    rule=None, overlap: bool = False, pipeline: bool = False,
):
    """Sharded evolve running the fused Pallas kernel per shard.

    The flagship multi-chip configuration: per chunk, one ring exchange
    ships a ``halo_depth``-deep packed ghost band (``lax.ppermute`` over
    ICI), then the shard steps ``halo_depth`` generations inside a single
    Pallas launch.  The band rides its *own* kernel operand
    (:func:`gol_tpu.ops.pallas_bitlife.multi_step_pallas_packed_bands`),
    so the shard's rows are never re-copied into an extended array — the
    halo_extend concat was a full-board HBM round trip per chunk, worth
    ~4% of end-to-end throughput at 16384² (1.81e12 vs 1.73e12
    cell-updates/s at ×10240).  Tiles smaller than the band depth fall
    back to the pre-extended kernel
    (:func:`~gol_tpu.ops.pallas_bitlife.multi_step_pallas_packed_ext`),
    whose windows may span several neighbor tiles.
    ``halo_depth`` must be a multiple of 8 (DMA row alignment).  A
    non-multiple remainder of ``steps`` runs on the jnp packed step.
    Defaults: band depth 8 (deeper bands measured at parity or slightly
    behind in r5 overhead-fitted sweeps — k=32 within noise of k=8 —
    and k=8 stays inside the 2-D column-band light cone) and row tile
    hint 1024, which lets :func:`~gol_tpu.ops.pallas_bitlife.pick_tile`'s
    VMEM budget set the real cap per geometry: wide boards cap at 256
    (nw=512's budget), narrow lane-folded shards reach 1024.  Earlier
    rounds defaulted the hint to 128 off wall-clock sweeps; r5's
    two-point overhead fits (benchmarks/exp_tile_fit.py, BASELINE.md r5)
    showed those walls were tunnel-overhead artifacts — device-side, the
    folded 16384×1024 pod shard runs 2.01e12 cell-updates/s at tile 1024
    vs 1.49e12 at tile 128 (+35%), and the full 16384² board gains ~4%
    at its 256 cap.  Taller tiles amortize per-tile fixed costs over
    more rows AND shrink the temporal blocking's recompute factor
    ((tile + k + 1)/tile); the VMEM budget is the only true ceiling.
    Optional ``rule`` switches the kernel tail to the generic plane
    matcher.

    ``overlap=True`` restructures each chunk for comm/compute overlap —
    the interior-first split the reference attempted with nonblocking MPI
    but forfeited by calling ``MPI_Wait`` before the kernel
    (gol-main.c:110-114).  The shard's interior rows ``[k, h-k)`` depend
    only on local data, so their (bulk) kernel launch carries no data
    dependency on the ring ppermutes and XLA's latency-hiding scheduler
    can run the band exchange underneath it; only two k-row boundary
    kernels wait for the band.  The price is reassembling the output from
    the three pieces (one board copy per chunk, ~1/(22·k/8) of the kernel's
    bitwise work) — hence a mode, not the default: serial wins single-chip,
    overlap wins when exchange latency is exposed (multi-chip, DCN).

    ``pipeline=True`` is the cross-chunk double buffer (``--shard-mode
    pipeline``): the chunk loop carries ``(block, bands)`` — each chunk
    consumes the k-row ghost bands exchanged DURING the previous chunk's
    compute, and ships the next chunk's bands from its own just-computed
    k-row boundary kernels (whose outputs are exactly the rows the ring
    must carry), so the ring ppermutes for chunk N+1 are in flight while
    chunk N's interior kernel — which reads only carried state — still
    runs.  Where overlap hides the exchange under the *same* chunk's
    interior (the band must still arrive before the boundary kernels),
    pipeline removes the arrival deadline entirely: the band has a full
    chunk of interior compute to cross the wire.  One exchange per chunk
    exactly (prologue + one per loop chunk; a remainder chunk consumes
    the final carried band sliced to its depth, and with no remainder
    the last chunk runs consume-only).  The carried band is one chunk
    "stale" only in wall-clock — its contents are the neighbor's
    boundary rows at this chunk's start generation, which is precisely
    what the ghost shell must hold.  Geometry constraints match overlap
    (the interior tile must clear both bands).

    **Narrow shards** (packed width not a multiple of 128 lanes — e.g.
    BASELINE config 3 on a 16×16 mesh: 1024-cell = 32-word shards) are
    evolved **lane-folded**: ``f = 128/gcd(nw, 128)`` row groups side by
    side in lanes (``[h, nw] -> [h/f, f*nw]``, :func:`fold_rows`), with
    the kernel's word ring made *group-local* (two masked rolls,
    ``pallas_bitlife._one_generation(groups=f)``) so the fold introduces
    no seam wrongness at all.  The board stays folded across the whole
    chunk loop; each chunk's ghost bands are lane-shifted slices of the
    folded block plus the two ring ppermutes.  Measured on v5e: a folded
    16384×1024 board runs within 1% of an equal-cell 4096² unfolded board
    (7.56e11 vs 7.60e11 cell-updates/s at ×16384) — the engine's fastest
    kernel now composes with pod-scale 2-D decompositions at any shard
    width >= 2 words.  Requires shard height divisible by ``8f``.
    ``overlap=True`` composes with the fold (r4): in the folded layout
    every interior group seam's band is a lane-shifted slice of the block
    itself, so the only ppermute-dependent inputs are the two ring ghosts
    — the interior kernel (folded rows ``[k, h/f - k)``, all lane groups)
    reads the folded block alone, and only two k-row boundary kernels
    wait for the exchanged band; folded overlap additionally needs
    ``h/f >= 2*halo_depth + 8`` (an aligned interior tile clear of both
    bands, same constraint as unfolded overlap one fold down).

    On **2-D block meshes** (BASELINE config 3's decomposition) the
    exchange grows a second phase: the k-row temporal band vertically, then
    a single ghost *word* column of the row-extended block horizontally
    (corner words ride the second hop).  The kernel itself still runs at
    the lane-aligned shard width with its local column wrap — wrong at the
    shard's vertical seams, but the wrongness is confined by the stencil
    light cone to the outer ``k`` bits of the two edge words (k <= 32 = one
    word).  Those two word columns are then recomputed exactly from 3-word
    strips (96-bit no-wrap windows: every edge-word bit sits >= 32 bits
    from both window boundaries) and spliced over the kernel's output.  The
    strips are O(rows) work that XLA can schedule concurrently with the
    kernel — the whole horizontal fix-up costs ~3/nw of the kernel's
    compute and none of its latency.
    """
    from gol_tpu.ops import pallas_bitlife

    two_d = COLS in mesh.axis_names
    if overlap and pipeline:
        raise ValueError(
            "overlap and pipeline are distinct chunk forms; pick one"
        )
    if halo_depth < 8 or halo_depth % 8:
        raise ValueError(
            f"the sharded Pallas engine needs halo_depth to be a multiple "
            f"of 8 (DMA row alignment), got {halo_depth}"
        )
    if two_d and halo_depth > bitlife.BITS:
        raise ValueError(
            f"on a 2-D mesh the sharded Pallas engine ships a 1-word "
            f"column band whose bit light cone supports halo_depth <= "
            f"{bitlife.BITS}, got {halo_depth}"
        )
    from gol_tpu.parallel.halo import halo_extend

    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)
    phases = ((0, ROWS, num_rows),)
    phases2d = ((0, ROWS, num_rows), (1, COLS, num_cols))
    full, rem = divmod(steps, halo_depth)
    # A 2-D mesh with a size-1 column ring shards only the rows: the shard
    # owns the full width, its local column wrap IS the torus, and the
    # strip/edge machinery would compute what the kernel already has — so
    # degenerate column rings take the 1-D bodies.
    strip_fix = two_d and num_cols > 1

    def kernel(ext_u32, tile, k, edges_u32=None, groups=1):
        # Bit-identical int32 view only around the kernel; the jnp packed
        # ops stay on uint32 (their right-shifts must be logical).
        out = pallas_bitlife.multi_step_pallas_packed_ext(
            lax.bitcast_convert_type(ext_u32, jnp.int32),
            tile,
            k,
            rule,
            None
            if edges_u32 is None
            else lax.bitcast_convert_type(edges_u32, jnp.int32),
            groups,
        )
        return lax.bitcast_convert_type(out, jnp.uint32)

    def kernel_bands(blk_u32, bands_u32, tile, k, edges_u32=None, groups=1):
        out = pallas_bitlife.multi_step_pallas_packed_bands(
            lax.bitcast_convert_type(blk_u32, jnp.int32),
            lax.bitcast_convert_type(bands_u32, jnp.int32),
            tile,
            k,
            rule,
            None
            if edges_u32 is None
            else lax.bitcast_convert_type(edges_u32, jnp.int32),
            groups,
        )
        return lax.bitcast_convert_type(out, jnp.uint32)

    def bands_for(p_u32):
        """The chunk's k-row ghost bands, fresh off the ring."""
        k = halo_depth
        top_ghost = lax.ppermute(p_u32[-k:], ROWS, ring(num_rows, 1))
        bottom_ghost = lax.ppermute(p_u32[:k], ROWS, ring(num_rows, -1))
        return top_ghost, bottom_ghost

    def four(a):
        """A block's four boundary word-columns, lane-packed."""
        return jnp.concatenate([a[:, :2], a[:, -2:]], axis=1)

    def edge_strips(top_ghost, middle4, bottom_ghost):
        """Exact post-chunk edge words from the three row pieces' boundary
        columns (ghost bands around the shard's own four() columns) — the
        one assembly behind every strip-repair site."""
        return exact_edges(
            jnp.concatenate(
                [four(top_ghost), middle4, four(bottom_ghost)], axis=0
            ).T
        )

    def jnp_step(ext):
        if rule is None:
            return bitlife.step_packed_vext(ext)
        from gol_tpu.ops import rules as rules_mod

        return rules_mod.step_rule_packed_vext(ext, rule)

    def jnp_step_nowrap(ext):
        if rule is None:
            return bitlife.step_packed_vext_nowrap(ext)
        from gol_tpu.ops import rules as rules_mod

        return rules_mod.step_rule_packed_vext_nowrap(ext, rule)

    def jnp_step_nowrap_t(ext_t):
        if rule is None:
            return bitlife.step_packed_vext_nowrap_t(ext_t)
        from gol_tpu.ops import rules as rules_mod

        return rules_mod.step_rule_packed_vext_nowrap_t(ext_t, rule)

    def chunk(p_u32, tile):
        # Band as its own kernel operand: the exchange ships 2k rows and
        # the shard's own rows are never re-copied into an extended array
        # (halo_extend's concat cost a full-board HBM round trip per
        # chunk — ~1/9 of chunk traffic at k=8).
        top_ghost, bottom_ghost = bands_for(p_u32)
        bands = jnp.concatenate([top_ghost, bottom_ghost])
        return kernel_bands(p_u32, bands, tile, halo_depth)

    def exact_edges(edges_t):
        """Exact post-chunk edge word-columns from the row-extended block's
        four boundary columns (transposed, ``[4, h + 2k]``).

        The horizontal phase of the two-phase exchange: ppermute a ghost
        word-column per side (corner words ride this second hop), then
        step 3-word strips (ghost + edge + 1 interior — 96-bit no-wrap
        windows: every edge-word bit sits >= 32 bits from both window
        boundaries, so k <= 32 steps stay exact), stacked so both sides
        share one op chain.  Transposed layout throughout: the long row
        axis fills the 128-wide lanes a ``[rows, 1]`` column would waste.
        Returns the ``[h, 2]`` left/right edge words after ``halo_depth``
        generations.
        """
        left_ghost_t = lax.ppermute(edges_t[3:4], COLS, ring(num_cols, 1))
        right_ghost_t = lax.ppermute(edges_t[0:1], COLS, ring(num_cols, -1))
        strips = jnp.stack(
            [
                jnp.concatenate([left_ghost_t, edges_t[0:2]], axis=0),
                jnp.concatenate([edges_t[2:4], right_ghost_t], axis=0),
            ]
        )  # [2 sides, 3 words, h + 2k rows]
        for _ in range(halo_depth):  # each step consumes one ghost row layer
            strips = jnp_step_nowrap_t(strips)
        return jnp.stack([strips[0, 1], strips[1, 1]], axis=1)  # [h, 2]

    def chunk_ext(p_u32, tile):
        # tile < halo_depth fallback: the banded kernel's one-descriptor
        # halo segments can't span multiple neighbor tiles, so small
        # tiles take the pre-extended form (one extra board copy/chunk).
        return kernel(
            halo_extend(p_u32, phases, depth=halo_depth), tile, halo_depth
        )

    def chunk2d_ext(p_u32, tile):
        ext = halo_extend(p_u32, phases, depth=halo_depth)  # rows only
        edges = exact_edges(
            jnp.concatenate([ext[:, :2], ext[:, -2:]], axis=1).T
        )
        return kernel(ext, tile, halo_depth, edges)

    def chunk2d(p_u32, tile):
        top_ghost, bottom_ghost = bands_for(p_u32)
        # One transpose pulls all four boundary columns into lane-major
        # layout up front, sliced from the pieces (no row-extended array
        # is ever materialized — the band rides its own kernel operand).
        edges = edge_strips(top_ghost, four(p_u32), bottom_ghost)
        bands = jnp.concatenate([top_ghost, bottom_ghost])
        # Kernel at the lane-aligned shard width; its local column wrap is
        # wrong at the vertical seams, confined by the light cone to the
        # outer halo_depth bits of the two edge words — which the kernel
        # overwrites with `edges` during its own output store.
        return kernel_bands(p_u32, bands, tile, halo_depth, edges)

    def bands_folded(fp, f):
        """Ring bands in the folded-lane layout (k <= hg — the banded
        path's own tile >= k constraint guarantees it).

        Row group ``g``'s vertical neighbors are shard rows
        ``[g*hg - k, g*hg)`` above and ``[(g+1)*hg, (g+1)*hg + k)`` below:
        every interior group seam's band is a lane-shifted slice of the
        folded block itself; only the outer two ride the ROWS ring.  The
        board therefore stays folded across the whole chunk loop — no
        per-chunk transpose.  Returns ``(bands, top_ghost, bottom_ghost)``
        with the ghosts in unfolded ``[k, nw]`` layout (the 2-D edge
        strips want them that way).
        """
        k = halo_depth
        hg, fnw = fp.shape
        nw = fnw // f
        top_ghost = lax.ppermute(
            fp[hg - k :, (f - 1) * nw :], ROWS, ring(num_rows, 1)
        )
        bottom_ghost = lax.ppermute(fp[:k, :nw], ROWS, ring(num_rows, -1))
        top_band = jnp.concatenate(
            [top_ghost, fp[hg - k :, : (f - 1) * nw]], axis=1
        )
        bot_band = jnp.concatenate([fp[:k, nw:], bottom_ghost], axis=1)
        return jnp.concatenate([top_band, bot_band]), top_ghost, bottom_ghost

    def four_folded(fp, f):
        """``[hg, f*nw] -> [h, 4]``: the unfolded shard's four boundary
        word columns, gathered from each group's edge lanes."""
        hg, fnw = fp.shape
        nw = fnw // f
        idx = [g * nw + j for j in (0, 1, nw - 2, nw - 1) for g in range(f)]
        cols = fp[:, jnp.asarray(idx)]  # [hg, 4f], column-kind major
        return cols.reshape(hg, 4, f).transpose(2, 0, 1).reshape(hg * f, 4)

    def folded_edges(fp, top_ghost, bottom_ghost, f):
        """Exact post-chunk edge pairs of a folded shard, in the kernel's
        folded edges layout ``[hg, 2f]`` — the one strip-repair assembly
        behind both folded chunk bodies."""
        return fold_rows(
            edge_strips(top_ghost, four_folded(fp, f), bottom_ghost), f
        )

    def chunk_folded(fp, tile, f):
        # The kernel's group-local lane rolls (groups=f) make the fold
        # seams exact by construction, so a row-sharded (1-D) narrow shard
        # needs no repair at all; a column-sharded one needs only the same
        # two exact edge columns as the unfolded 2-D path, folded to one
        # (left, right) pair per group.
        bands, top_ghost, bottom_ghost = bands_folded(fp, f)
        edges_f = None
        if strip_fix:
            edges_f = folded_edges(fp, top_ghost, bottom_ghost, f)
        return kernel_bands(fp, bands, tile, halo_depth, edges_f, f)

    def folded_band_slices(p_u32, top_ghost, bottom_ghost, f):
        """Band construction valid for any k (k > hg included): in
        ``concat([ring_ghost, shard_rows])`` coordinates every group's
        band is the contiguous slice ``[g*hg, g*hg + k)``, whatever mix
        of ghost and local rows it spans."""
        k = halo_depth
        h, nw = p_u32.shape
        hg = h // f
        ext_top = jnp.concatenate([top_ghost, p_u32[: (f - 1) * hg]])
        ext_bot = jnp.concatenate([p_u32[hg:], bottom_ghost])
        top_band = jnp.stack(
            [ext_top[g * hg : g * hg + k] for g in range(f)], axis=1
        ).reshape(k, f * nw)
        bot_band = jnp.stack(
            [ext_bot[g * hg : g * hg + k] for g in range(f)], axis=1
        ).reshape(k, f * nw)
        return jnp.concatenate([top_band, bot_band])

    def chunk_folded_ext(p_u32, tile, f):
        # tile < halo_depth fallback (the banded kernel's one-descriptor
        # segments need tile >= k; k may even exceed hg here): assemble
        # the extended folded window from unfolded-resident slices.
        top_ghost, bottom_ghost = bands_for(p_u32)
        bands = folded_band_slices(p_u32, top_ghost, bottom_ghost, f)
        k = halo_depth
        ext = jnp.concatenate([bands[:k], fold_rows(p_u32, f), bands[k:]])
        edges_f = None
        if strip_fix:
            edges_f = fold_rows(
                edge_strips(top_ghost, four(p_u32), bottom_ghost), f
            )
        return unfold_rows(kernel(ext, tile, halo_depth, edges_f, f), f)

    def _boundary_pieces(p_u32, tile_int):
        """Interior kernel (ppermute-independent) + band-gated edge kernels.

        Returns the three row pieces of the stepped shard.  The interior
        launch reads only local rows, so XLA schedules the ring ppermutes
        concurrently with it; the two k-row boundary kernels consume the
        arrived band plus a 2k-row local margin (their windows span rows
        ``[-k, 2k)`` and ``[h-2k, h+k)``).
        """
        k = halo_depth
        top_ghost, bottom_ghost = bands_for(p_u32)
        interior = kernel(p_u32, tile_int, k)  # output rows [k, h-k)
        top = kernel(jnp.concatenate([top_ghost, p_u32[: 2 * k]]), k, k)
        bottom = kernel(
            jnp.concatenate([p_u32[-2 * k :], bottom_ghost]), k, k
        )
        return top, interior, bottom, top_ghost, bottom_ghost

    def chunk_overlap(p_u32, tile_int):
        top, interior, bottom, _, _ = _boundary_pieces(p_u32, tile_int)
        return jnp.concatenate([top, interior, bottom], axis=0)

    def chunk_folded_overlap(fp, tile_int, f):
        # Folded counterpart of chunk_overlap / chunk2d_overlap.  In the
        # folded layout the interior group seams' bands are lane-shifted
        # slices of the block itself (see bands_folded), so the ONLY
        # ppermute-dependent inputs are the two ring ghosts: the interior
        # kernel (folded rows [k, hg-k), every lane group) reads fp alone
        # and XLA schedules the ring exchange underneath it; the two
        # k-row boundary kernels consume the arrived band plus a 2k-row
        # local margin, exactly as in _boundary_pieces one fold down.
        k = halo_depth
        bands, top_ghost, bottom_ghost = bands_folded(fp, f)
        interior = kernel(fp, tile_int, k, groups=f)  # folded rows [k, hg-k)
        top = kernel(
            jnp.concatenate([bands[:k], fp[: 2 * k]]), k, k, groups=f
        )
        bottom = kernel(
            jnp.concatenate([fp[-2 * k :], bands[k:]]), k, k, groups=f
        )
        rows_out = jnp.concatenate([top, interior, bottom], axis=0)
        if strip_fix:
            # Same strip repair as chunk_folded, spliced by lane concat
            # (the interior kernel must not take the edges operand — the
            # strips depend on both exchange phases).  Group g's exact
            # (left, right) pair sits at edges_f columns 2g, 2g+1; its
            # words at lanes g*nw and (g+1)*nw - 1.
            edges_f = folded_edges(fp, top_ghost, bottom_ghost, f)
            nw = fp.shape[1] // f
            rows_out = jnp.concatenate(
                [
                    piece
                    for g in range(f)
                    for piece in (
                        edges_f[:, 2 * g : 2 * g + 1],
                        rows_out[:, g * nw + 1 : (g + 1) * nw - 1],
                        edges_f[:, 2 * g + 1 : 2 * g + 2],
                    )
                ],
                axis=1,
            )
        return rows_out

    def chunk2d_overlap(p_u32, tile_int):
        top, interior, bottom, top_ghost, bottom_ghost = _boundary_pieces(
            p_u32, tile_int
        )
        rows_out = jnp.concatenate([top, interior, bottom], axis=0)
        # Same strip repair as chunk2d, with the row-extended block's four
        # boundary columns sliced from the pieces instead of a
        # materialized extension.  The kernels above could not take an
        # ``edges`` input (the strips depend on both exchange phases,
        # which the interior launch must not), so the exact edge words are
        # spliced by a lane concat instead of the kernel's own output
        # store — the serial form's advantage this mode trades away for
        # the overlap.
        edges = edge_strips(top_ghost, four(p_u32), bottom_ghost)
        return jnp.concatenate(
            [edges[:, :1], rows_out[:, 1:-1], edges[:, 1:]], axis=1
        )

    def chunk_pipe_pieces(p_u32, bt, bb, tile_int):
        """The three row pieces of one pipelined chunk, consuming the
        CARRIED bands ``bt``/``bb`` (exchanged during the previous
        chunk's compute).  Strip repair included, so the pieces are the
        exact rows the next exchange ships."""
        k = halo_depth
        interior = kernel(p_u32, tile_int, k)  # carried state only
        top = kernel(jnp.concatenate([bt, p_u32[: 2 * k]]), k, k)
        bottom = kernel(jnp.concatenate([p_u32[-2 * k :], bb]), k, k)
        if strip_fix:
            # Same repair as chunk2d_overlap, spliced per piece (concat
            # of spliced pieces == splice of the concat); the COLS
            # ppermutes inside edge_strips read only carried state, so
            # they too are in flight before the interior kernel.
            edges = edge_strips(bt, four(p_u32), bb)
            top = jnp.concatenate(
                [edges[:k, :1], top[:, 1:-1], edges[:k, 1:]], axis=1
            )
            interior = jnp.concatenate(
                [edges[k:-k, :1], interior[:, 1:-1], edges[k:-k, 1:]],
                axis=1,
            )
            bottom = jnp.concatenate(
                [edges[-k:, :1], bottom[:, 1:-1], edges[-k:, 1:]], axis=1
            )
        return top, interior, bottom

    def chunk_folded_pipe_pieces(fp, tg, bg, tile_int, f):
        """Folded counterpart: carried state is ``(fp, tg, bg)`` with the
        two RING ghosts in unfolded ``[k, nw]`` layout; the interior
        group seams' band parts are lane-shifted slices of ``fp`` itself
        (carried state, no wire), exactly as in bands_folded."""
        k = halo_depth
        hg, fnw = fp.shape
        nw = fnw // f
        top_band = jnp.concatenate([tg, fp[hg - k :, : (f - 1) * nw]], axis=1)
        bot_band = jnp.concatenate([fp[:k, nw:], bg], axis=1)
        interior = kernel(fp, tile_int, k, groups=f)  # folded [k, hg-k)
        top = kernel(
            jnp.concatenate([top_band, fp[: 2 * k]]), k, k, groups=f
        )
        bottom = kernel(
            jnp.concatenate([fp[-2 * k :], bot_band]), k, k, groups=f
        )
        if strip_fix:
            edges_f = folded_edges(fp, tg, bg, f)

            def splice(piece, rows):
                return jnp.concatenate(
                    [
                        part
                        for g in range(f)
                        for part in (
                            edges_f[rows, 2 * g : 2 * g + 1],
                            piece[:, g * nw + 1 : (g + 1) * nw - 1],
                            edges_f[rows, 2 * g + 1 : 2 * g + 2],
                        )
                    ],
                    axis=1,
                )

            top = splice(top, slice(None, k))
            interior = splice(interior, slice(k, hg - k))
            bottom = splice(bottom, slice(hg - k, None))
        return top, interior, bottom

    def tail_consume(p_u32, bt, bb):
        """The remainder chunk of a pipelined run: consume the carried
        bands (sliced to depth rem) instead of exchanging again — same
        values the serial tails' halo_extend would ship."""
        ext_rows = jnp.concatenate([bt[-rem:], p_u32, bb[:rem]])
        if strip_fix:
            left = lax.ppermute(ext_rows[:, -1:], COLS, ring(num_cols, 1))
            right = lax.ppermute(ext_rows[:, :1], COLS, ring(num_cols, -1))
            ext = jnp.concatenate([left, ext_rows, right], axis=1)
            for _ in range(rem):
                ext = jnp_step_nowrap(ext)
            return ext[:, 1:-1]
        ext = ext_rows
        for _ in range(rem):  # each step consumes one ghost layer
            ext = jnp_step(ext)
        return ext

    def local_pipeline(packed, fold):
        """The pipelined chunk loop: prologue exchange, carried
        ``(block, bands)`` iterations each shipping the next chunk's
        bands from its boundary pieces, and a band-consuming tail."""
        k = halo_depth
        if full == 0:
            # steps < band depth: a single serial-tail chunk.
            return (tail2d if strip_fix else tail)(packed)
        n_loop = full if rem else full - 1
        if fold > 1:
            hg = packed.shape[0] // fold
            nw = packed.shape[1]
            tile = pallas_bitlife.pick_tile(
                hg - 2 * k, fold * nw, tile_hint
            )
            fp = fold_rows(packed, fold)
            tg = lax.ppermute(
                fp[hg - k :, (fold - 1) * nw :], ROWS, ring(num_rows, 1)
            )
            bg = lax.ppermute(fp[:k, :nw], ROWS, ring(num_rows, -1))

            def body_f(_, carry):
                q, t, b = carry
                top, inter, bottom = chunk_folded_pipe_pieces(
                    q, t, b, tile, fold
                )
                nq = jnp.concatenate([top, inter, bottom])
                nt = lax.ppermute(
                    bottom[:, (fold - 1) * nw :], ROWS, ring(num_rows, 1)
                )
                nb = lax.ppermute(top[:, :nw], ROWS, ring(num_rows, -1))
                return nq, nt, nb

            if n_loop:
                fp, tg, bg = lax.fori_loop(
                    0, n_loop, body_f, (fp, tg, bg)
                )
            if rem:
                return tail_consume(unfold_rows(fp, fold), tg, bg)
            top, inter, bottom = chunk_folded_pipe_pieces(
                fp, tg, bg, tile, fold
            )
            return unfold_rows(
                jnp.concatenate([top, inter, bottom]), fold
            )
        tile = pallas_bitlife.pick_tile(
            packed.shape[0] - 2 * k, packed.shape[1], tile_hint
        )
        bt, bb = bands_for(packed)  # prologue

        def body(_, carry):
            q, t, b = carry
            top, inter, bottom = chunk_pipe_pieces(q, t, b, tile)
            nq = jnp.concatenate([top, inter, bottom])
            nt = lax.ppermute(bottom, ROWS, ring(num_rows, 1))
            nb = lax.ppermute(top, ROWS, ring(num_rows, -1))
            return nq, nt, nb

        if n_loop:
            packed, bt, bb = lax.fori_loop(
                0, n_loop, body, (packed, bt, bb)
            )
        if rem:
            return tail_consume(packed, bt, bb)
        top, inter, bottom = chunk_pipe_pieces(packed, bt, bb, tile)
        return jnp.concatenate([top, inter, bottom])

    def tail(p_u32):
        # One depth-rem exchange feeds all leftover generations (the
        # blocked-chunk pattern of halo.blocked_local_loop), instead of
        # rem separate ppermute pairs.
        ext = halo_extend(p_u32, phases, depth=rem)
        for _ in range(rem):  # each step consumes one ghost layer
            ext = jnp_step(ext)
        return ext

    def tail2d(p_u32):
        # rem < halo_depth <= BITS, so the no-wrap step's bit-level garbage
        # stays inside the single ghost word per side; the interior crop is
        # exact.
        ext = halo_extend(p_u32, phases2d, depth=(rem, 1))
        for _ in range(rem):
            ext = jnp_step_nowrap(ext)
        return ext[:, 1:-1]

    def local(board):
        h, w = board.shape  # per-shard block (static under shard_map)
        nw = w // bitlife.BITS
        fold = pallas_bitlife.fold_factor(nw)
        # Overlap and pipeline share the split geometry: the interior
        # kernel needs an aligned row tile clear of both k-row bands.
        split = overlap or pipeline
        split_name = "pipeline" if pipeline else "overlap"
        if fold > 1:
            # Narrow shard: evolve in the lane-folded [h/f, f*nw] layout
            # (see fold_rows) so the kernel still fills whole 128-lane
            # tiles — the fix for BASELINE config 3's 16x16-mesh shard
            # width, where nw = 32.  The kernel's group-local lane rolls
            # keep the fold exact, so the only constraints are geometric.
            feasible = pallas_bitlife.fold_feasible(
                h, fold, split, halo_depth
            )
            if not feasible:
                if jax.default_backend() == "tpu":
                    raise ValueError(
                        f"shard width {w} = {nw} packed words does not "
                        f"fill whole 128-lane tiles; lane-folding x{fold} "
                        f"lifts that but needs shard height divisible by "
                        f"{fold * 8} (got {h})"
                        + (
                            f" and, in {split_name} mode, folded height "
                            f"h/f >= 2*halo_depth + 8 = "
                            f"{2 * halo_depth + 8} (got {h // fold})"
                            if split
                            else ""
                        )
                    )
                fold = 1  # interpret mode has no lane-tiling constraint
        if h % 8 or h < halo_depth:
            raise ValueError(
                f"the sharded Pallas engine needs shard height (got {h}) "
                f"to be a multiple of 8 and >= the exchanged band depth "
                f"{halo_depth}"
            )
        if two_d and num_cols > 1 and nw < 2:
            raise ValueError(
                f"the 2-D sharded Pallas engine needs >= 2 packed words "
                f"per shard (edge-word strips), got shard width {w}"
            )
        if split and h < 2 * halo_depth + 8:
            raise ValueError(
                f"{split_name} mode needs shard height (got {h}) >= "
                f"2*halo_depth + 8 = {2 * halo_depth + 8}: the interior "
                "kernel must keep at least one aligned row tile that does "
                "not touch the exchanged band"
            )
        packed = bitlife.pack(board)
        if pipeline:
            return bitlife.unpack(local_pipeline(packed, fold))
        if fold > 1 and overlap:
            # Interior tile lives clear of both exchanged bands, so the
            # tileable extent is the folded height minus the 2k margin.
            tile = pallas_bitlife.pick_tile(
                h // fold - 2 * halo_depth, fold * nw, tile_hint
            )
            if full:
                fp = fold_rows(packed, fold)
                fp = lax.fori_loop(
                    0,
                    full,
                    lambda _, q: chunk_folded_overlap(q, tile, fold),
                    fp,
                )
                packed = unfold_rows(fp, fold)
        elif fold > 1:
            tile = pallas_bitlife.pick_tile(h // fold, fold * nw, tile_hint)
            if full:
                if tile >= halo_depth:
                    # Folded-resident loop: fold once, chunk on the folded
                    # layout (bands are lane-shifted slices of it), unfold
                    # once — no per-chunk transpose.
                    fp = fold_rows(packed, fold)
                    fp = lax.fori_loop(
                        0, full, lambda _, q: chunk_folded(q, tile, fold), fp
                    )
                    packed = unfold_rows(fp, fold)
                else:
                    packed = lax.fori_loop(
                        0,
                        full,
                        lambda _, p: chunk_folded_ext(p, tile, fold),
                        packed,
                    )
        else:
            tile = pallas_bitlife.pick_tile(
                packed.shape[0] - (2 * halo_depth if overlap else 0),
                packed.shape[1],
                tile_hint,
            )
            if overlap:
                body = chunk2d_overlap if strip_fix else chunk_overlap
            elif tile >= halo_depth:
                body = chunk2d if strip_fix else chunk
            else:
                body = chunk2d_ext if strip_fix else chunk_ext
            if full:
                packed = lax.fori_loop(
                    0, full, lambda _, p: body(p, tile), packed
                )
        if rem:
            packed = (tail2d if strip_fix else tail)(packed)
        return bitlife.unpack(packed)

    # check_vma=False: pallas_call's out ShapeDtypeStruct carries no
    # varying-mesh-axes annotation, and the kernel is already per-shard.
    spec = P(ROWS, COLS) if two_d else P(ROWS, None)
    shmapped = compat.shard_map(
        local,
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(shmapped, donate_argnums=0)


def evolve_sharded_packed(board: jax.Array, steps: int, mesh: Mesh) -> jax.Array:
    """Evolve a dense board over ``mesh`` with the bit-packed engine.

    Placement/copy contract matches
    :func:`gol_tpu.parallel.sharded.evolve_sharded`: the caller's array is
    never consumed (see :func:`gol_tpu.parallel.sharded.place_private`).
    """
    validate_packed_geometry(board.shape, mesh)
    return compiled_evolve_packed(mesh, steps)(place_private(board, mesh))
