"""Multi-host process topology + per-host sharded I/O (the DCN tier).

The reference scales across nodes with ``mpirun`` process spawning
(``MPI_Init``/``Comm_rank``/``Comm_size``, gol-main.c:58-62), binds each
process to a local GPU (``cudaSetDevice(myRank % deviceCount)``,
gol-with-cuda.cu:296), and has every rank write its own output file
(gol-main.c:64-73,135-139).  The TPU-native equivalent:

- ``jax.distributed.initialize`` connects the processes (coordinator +
  process id — the ``mpirun`` analog).  After it, ``jax.devices()`` is the
  *global* device list, so the same ``Mesh`` constructors in
  :mod:`gol_tpu.parallel.mesh` span the whole pod; ``lax.ppermute`` hops
  between co-located chips ride ICI and inter-host hops ride DCN, chosen by
  XLA — no NCCL/MPI plumbing in user code.
- Per-host I/O: each process writes the ``Rank_<r>_of_<n>.txt`` files whose
  data already lives in its addressable shards.  No cross-host gather — the
  exact I/O pattern of the reference, where each rank dumps its local block.
  The writer assignment is computed *deterministically on every host* from
  the sharding's device→index map (``Sharding.devices_indices_map``), so no
  coordination traffic is needed to agree who writes what.
- Logical ranks whose rows no single host fully owns (e.g. a 2-D mesh whose
  column axis crosses hosts) fall back to an XLA replication gather
  (``jit`` identity with fully-replicated out-sharding — a real collective
  over ICI/DCN), written by process 0.

Tested for real in ``tests/test_multihost.py``: two OS processes, Gloo
collectives between them, byte-compared against the single-process run.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec

from gol_tpu.utils import io as gol_io


@dataclasses.dataclass(frozen=True)
class HostTopology:
    """This process's place in the job — the ``myRank``/``numRank`` analog."""

    process_index: int
    process_count: int
    local_device_count: int
    global_device_count: int

    @property
    def is_coordinator(self) -> bool:
        """Process 0 — the reference's reporting rank (gol-main.c:121)."""
        return self.process_index == 0


def topology() -> HostTopology:
    return HostTopology(
        process_index=jax.process_index(),
        process_count=jax.process_count(),
        local_device_count=len(jax.local_devices()),
        global_device_count=len(jax.devices()),
    )


def add_multihost_args(parser) -> None:
    """Install the multi-host CLI trio (the ``mpirun -np N`` analog).

    One definition shared by every entry point (`gol_tpu.cli`,
    ``scalebench``), so the multi-host surface cannot drift between them;
    the parsed ``coordinator``/``num_processes``/``process_id`` feed
    :func:`init_multihost`.
    """
    parser.add_argument("--coordinator", default=None, metavar="HOST:PORT")
    parser.add_argument("--num-processes", type=int, default=None, metavar="N")
    parser.add_argument("--process-id", type=int, default=None, metavar="I")


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
) -> HostTopology:
    """Connect this process to the job (the ``MPI_Init`` analog).

    A no-op returning the current (single-process) topology when no
    multi-host argument is given — so single-host code paths never pay for
    this.  Partial flag combinations are rejected rather than silently run
    as a single-process job: a worker that forgot ``--coordinator`` would
    otherwise evolve its own private world and clobber the real job's
    output files.  (On cloud TPU pods, calling with no arguments at all and
    using ``jax.distributed.initialize()``'s environment auto-detection is
    still available directly.)
    """
    trio = (coordinator_address, num_processes, process_id)
    if all(v is None for v in trio) and local_device_ids is None:
        return topology()
    if any(v is None for v in trio):
        # Includes local_device_ids given alone: device pinning only means
        # anything inside a multi-process job, so dropping it silently
        # (process grabs every local device) would betray the caller.
        raise ValueError(
            "multi-host init needs coordinator_address, num_processes, and "
            f"process_id together; got coordinator={coordinator_address!r}, "
            f"num_processes={num_processes!r}, process_id={process_id!r}, "
            f"local_device_ids={local_device_ids!r}"
        )
    if num_processes and num_processes > 1:
        from gol_tpu import compat

        compat.enable_cpu_cross_process_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    return topology()


# -- writer planning ---------------------------------------------------------


def _rect(idx, shape) -> Tuple[int, int, int, int]:
    """Decode a shard's index (tuple of slices) into (r0, r1, c0, c1)."""
    h, w = shape[0], shape[1] if len(shape) > 1 else 1
    r = idx[0] if len(idx) > 0 else slice(None)
    c = idx[1] if len(idx) > 1 else slice(None)
    return (
        0 if r.start is None else r.start,
        h if r.stop is None else r.stop,
        0 if c.start is None else c.start,
        w if c.stop is None else c.stop,
    )


def _index_rects(
    sharding, shape: Tuple[int, ...]
) -> Dict[int, set]:
    """Per-process set of (r0, r1, c0, c1) global rectangles it can read.

    Replicated shards dedupe via the set; the remaining rectangles are a
    disjoint partition of the array (regular grid sharding), so coverage
    checks reduce to area sums.
    """
    rects: Dict[int, set] = defaultdict(set)
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        rects[dev.process_index].add(_rect(idx, shape))
    return rects


def plan_rank_writers(
    sharding, shape: Tuple[int, int], num_ranks: int
) -> Tuple[Dict[int, int], List[int]]:
    """Assign each logical rank's dump file to a writer process.

    Returns ``(writers, gather_ranks)``: ``writers[rank] = process`` for
    every rank some single process fully covers from its addressable shards
    (lowest such process index wins, so the assignment is identical on all
    hosts with zero communication); ``gather_ranks`` lists ranks nobody
    covers alone (they need a collective gather).
    """
    h, w = shape
    if num_ranks < 1:
        raise ValueError(f"num_ranks must be positive, got {num_ranks}")
    if h % num_ranks:
        raise ValueError(f"global height {h} not divisible by {num_ranks} ranks")
    s = h // num_ranks
    rects = _index_rects(sharding, shape)
    writers: Dict[int, int] = {}
    gather: List[int] = []
    for rank in range(num_ranks):
        lo, hi = rank * s, (rank + 1) * s
        need = (hi - lo) * w
        writer = None
        for proc in sorted(rects):
            area = sum(
                max(0, min(r1, hi) - max(r0, lo)) * (c1 - c0)
                for (r0, r1, c0, c1) in rects[proc]
            )
            if area == need:
                writer = proc
                break
        if writer is None:
            gather.append(rank)
        else:
            writers[rank] = writer
    return writers, gather


def _assemble_rank_block(arr, rank: int, block_h: int) -> np.ndarray:
    """Stitch one rank's rows from this host's addressable shards."""
    h, w = arr.shape
    lo = rank * block_h
    block = np.empty((block_h, w), dtype=arr.dtype)
    for shard in arr.addressable_shards:
        r0, r1, c0, c1 = _rect(shard.index, arr.shape)
        i0, i1 = max(r0, lo), min(r1, lo + block_h)
        if i0 >= i1:
            continue
        data = np.asarray(shard.data)
        block[i0 - lo : i1 - lo, c0:c1] = data[i0 - r0 : i1 - r0, :]
    return block


def fetch_global(arr) -> np.ndarray:
    """Full array on every host, via an XLA replication collective.

    ``jit`` identity with a fully-replicated out-sharding makes XLA insert
    the all-gather (ICI/DCN as the mesh dictates); afterwards every host
    holds an addressable copy.  Single-process arrays short-circuit to a
    plain host transfer.
    """
    sharding = getattr(arr, "sharding", None)
    if jax.process_count() == 1 or sharding is None:
        return np.asarray(arr)
    if not isinstance(sharding, NamedSharding):
        raise ValueError(
            f"fetch_global needs a NamedSharding to replicate over, got "
            f"{type(sharding).__name__}"
        )
    out = NamedSharding(sharding.mesh, PartitionSpec())
    replicated = jax.jit(lambda x: x, out_shardings=out)(arr)
    return np.asarray(replicated.addressable_shards[0].data)


def allgather_host_ints(value: int) -> List[int]:
    """One host integer from every process, on every process.

    The resilience tier's agreement primitive (``--auto-resume``: all
    ranks take min(newest valid snapshot generation) so no rank resumes
    ahead of another).  Rides the same replication-collective machinery
    as :func:`fetch_global` — works over gloo on CPU test jobs and
    ICI/DCN on pods; identity on single-process jobs.
    """
    if jax.process_count() == 1:
        return [int(value)]
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray([int(value)], np.int64)
    )
    return [int(v) for v in np.asarray(gathered).ravel()]


def precreate_host_dump_files(
    mesh, shape: Tuple[int, int], num_ranks: int, directory: str = "."
) -> List[str]:
    """Create (truncating) at startup the dump files this process will write.

    The reference opens every rank's file right after ``MPI_Init``, before
    world init (gol-main.c:64-73).  With sharded output the writer plan is
    known deterministically from the prospective board sharding, so each
    process pre-creates exactly the files :func:`write_host_dumps` will
    later fill (process 0 additionally owns any gathered ranks).  Raises
    :class:`gol_tpu.utils.io.RankFileError` on open failure, like the
    single-process path.
    """
    from gol_tpu.parallel import mesh as mesh_mod

    writers, gather_ranks = plan_rank_writers(
        mesh_mod.board_sharding(mesh), shape, num_ranks
    )
    me = jax.process_index()
    ranks = sorted(
        [r for r, p in writers.items() if p == me]
        + (gather_ranks if me == 0 else [])
    )
    return gol_io.create_rank_files(ranks, num_ranks, directory)


def write_host_dumps(
    global_array,
    num_ranks: int,
    directory: str = ".",
    use_native: bool = True,
    allow_gather: bool = True,
) -> List[str]:
    """Write this host's share of the ``Rank_<r>_of_<n>.txt`` dump files.

    The multi-host equivalent of every MPI rank executing
    gol-main.c:135-139: each process writes exactly the files whose rows it
    owns, from addressable shards, with no cross-host traffic.  Ranks nobody
    fully owns (column axis split across hosts) are gathered collectively —
    *all* processes must keep calling in that case — and written by
    process 0.  Returns the paths this process wrote.
    """
    h, _ = global_array.shape
    if h % num_ranks:
        raise ValueError(f"global height {h} not divisible by {num_ranks} ranks")
    s = h // num_ranks
    sharding = getattr(global_array, "sharding", None)
    me = jax.process_index()
    written: List[str] = []
    if sharding is None:
        if me == 0:
            return gol_io.write_world_dumps(
                np.asarray(global_array), num_ranks, directory, use_native
            )
        return written
    writers, gather_ranks = plan_rank_writers(
        sharding, global_array.shape, num_ranks
    )
    for rank, proc in writers.items():
        if proc != me:
            continue
        block = _assemble_rank_block(global_array, rank, s)
        written.append(
            gol_io.write_rank_file(block, rank, num_ranks, directory, use_native)
        )
    if gather_ranks:
        if not allow_gather:
            raise ValueError(
                f"ranks {gather_ranks} are split across hosts; re-shard, or "
                "pass allow_gather=True to fetch them collectively"
            )
        for rank in gather_ranks:
            # Collective — every process executes the same gather sequence.
            full = fetch_global(global_array[rank * s : (rank + 1) * s])
            if me == 0:
                written.append(
                    gol_io.write_rank_file(
                        full, rank, num_ranks, directory, use_native
                    )
                )
    return written
