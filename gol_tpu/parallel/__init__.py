"""Distributed layer: device meshes, ppermute halo exchange, sharded engines."""
