"""Generic N-phase halo extension over an N-D device mesh.

Generalizes the two-phase edge+corner exchange of
:func:`gol_tpu.parallel.sharded.exchange_block_halos` to any rank: extend
one array axis at a time with ``lax.ppermute`` ring shifts, each later
phase shipping boundary slices of the *already-extended* array.  After
phase k, a halo cell that must cross k mesh axes (an edge or corner of the
decomposition) has made its k hops — so faces, edges, and corners all land
without diagonal messages, in 2 ppermutes per axis.

The same code path expresses the local torus wrap: on a mesh axis of size
1 the ring permutation ``[(0, 0)]`` delivers the shard its *own* boundary
slice, which is exactly the periodic wrap.  Axes the caller leaves
unsharded therefore just use size-1 rings — there is one program shape for
every decomposition of the torus (the property the reference's hand-rolled
1-D MPI exchange, gol-main.c:86-111, could not scale to).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu import compat


def ring(n: int, shift: int):
    """Permutation delivering each shard the slice from its ring ±1 neighbor.

    ``shift=+1`` receives from the ring predecessor (the reference's
    ``prevRank``, gol-main.c:86), ``shift=-1`` from the successor.
    """
    return [(i, (i + shift) % n) for i in range(n)]


def halo_extend(
    block: jax.Array,
    mesh_axes: Sequence[Tuple[int, str, int]],
    depth=1,
) -> jax.Array:
    """Extend ``block`` by ``depth`` ghost layers on both sides of each axis.

    ``mesh_axes`` is a sequence of ``(array_axis, mesh_axis_name, ring_size)``
    — one entry per array axis to extend, in phase order.  Must be called
    inside ``shard_map`` over a mesh carrying the named axes.  Returns the
    block grown by ``2*depth`` along every listed axis.  ``depth`` may also
    be a sequence, one depth per listed axis — engines whose halo quantum
    differs per axis (the 2-D sharded Pallas engine ships a k-row temporal
    band but a 1-word column band) exchange both in one call.

    ``depth > 1`` is the temporal-blocking exchange: a ``depth``-deep ghost
    shell shipped once supplies ``depth`` generations of local stepping
    (each consuming one layer), so the ring pays 2 ppermutes per axis per
    ``depth`` generations instead of per generation.  A ghost shell must
    come entirely from the immediate ring neighbor, so ``depth`` may not
    exceed the shard's extent along any extended axis.
    """
    depths = (
        (depth,) * len(mesh_axes)
        if isinstance(depth, int)
        else tuple(depth)
    )
    if len(depths) != len(mesh_axes):
        raise ValueError(
            f"{len(depths)} depths for {len(mesh_axes)} extended axes"
        )
    ext = block
    for (axis, name, n), depth in zip(mesh_axes, depths):
        if block.shape[axis] < depth:
            raise ValueError(
                f"halo depth {depth} exceeds shard extent "
                f"{block.shape[axis]} along axis {axis} ({name}); the ghost "
                "shell would need cells from beyond the ring neighbor"
            )
        last = tuple(
            slice(-depth, None) if a == axis else slice(None)
            for a in range(ext.ndim)
        )
        first = tuple(
            slice(None, depth) if a == axis else slice(None)
            for a in range(ext.ndim)
        )
        # Receive the ring-predecessor's last slice (our "low" ghost) and the
        # ring-successor's first slice (our "high" ghost).
        lo = lax.ppermute(ext[last], name, ring(n, 1))
        hi = lax.ppermute(ext[first], name, ring(n, -1))
        ext = jnp.concatenate([lo, ext, hi], axis=axis)
    return ext


def blocked_local_loop(
    step: Callable,
    phases,
    steps: int,
    halo_depth: int,
    pack: Optional[Callable] = None,
    unpack: Optional[Callable] = None,
) -> Callable:
    """Per-shard generation loop with depth-k temporal blocking.

    ``step`` consumes one ghost layer per call (shrink-by-one on every
    extended axis); each chunk halo-extends by ``k`` and applies ``step``
    ``k`` times, so the ring pays one exchange per ``k`` generations.
    ``steps`` is split into full ``halo_depth`` chunks plus one remainder
    chunk.  Optional ``pack``/``unpack`` convert the shard representation
    once around the whole loop (the bit-packed engines' dense-in/dense-out
    contract).  The returned callable is the body for ``shard_map`` —
    shared by the 2-D and 3-D packed engines so their blocking logic
    cannot diverge.
    """
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")

    def chunk(x, k):
        ext = halo_extend(x, phases, depth=k)
        for _ in range(k):  # each generation consumes one ghost layer
            ext = step(ext)
        return ext

    full, rem = divmod(steps, halo_depth)

    def local(x):
        if pack is not None:
            x = pack(x)
        if full:
            x = lax.fori_loop(0, full, lambda _, y: chunk(y, halo_depth), x)
        if rem:
            x = chunk(x, rem)
        if unpack is not None:
            x = unpack(x)
        return x

    return local


def build_ring_engine(
    mesh,
    steps: int,
    halo_depth: int,
    step_1d: Callable,
    step_2d: Callable,
    pack: Optional[Callable] = None,
    unpack: Optional[Callable] = None,
):
    """jit'ed shard_map ring engine over a 1-D or 2-D board mesh.

    The one builder behind the packed Conway engine and the generic-rule
    engines: picks the row-only or row+column phase list from the mesh's
    axes, wires the matching shrink-by-one ``step`` through
    :func:`blocked_local_loop`, and returns the donated-input jitted
    program.  Keeping this in one place means a change to the mesh-phase
    or donation conventions cannot diverge between engines.
    """
    from gol_tpu.parallel.mesh import COLS, ROWS
    from jax.sharding import PartitionSpec as P

    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)
    if COLS in mesh.axis_names:
        phases = ((0, ROWS, num_rows), (1, COLS, num_cols))
        step, spec = step_2d, P(ROWS, COLS)
    else:
        phases = ((0, ROWS, num_rows),)
        step, spec = step_1d, P(ROWS, None)

    local = blocked_local_loop(
        step, phases, steps, halo_depth, pack=pack, unpack=unpack
    )
    shmapped = compat.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(shmapped, donate_argnums=0)
