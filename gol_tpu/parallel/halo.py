"""Generic N-phase halo extension over an N-D device mesh.

Generalizes the two-phase edge+corner exchange of
:func:`gol_tpu.parallel.sharded.exchange_block_halos` to any rank: extend
one array axis at a time with ``lax.ppermute`` ring shifts, each later
phase shipping boundary slices of the *already-extended* array.  After
phase k, a halo cell that must cross k mesh axes (an edge or corner of the
decomposition) has made its k hops — so faces, edges, and corners all land
without diagonal messages, in 2 ppermutes per axis.

The same code path expresses the local torus wrap: on a mesh axis of size
1 the ring permutation ``[(0, 0)]`` delivers the shard its *own* boundary
slice, which is exactly the periodic wrap.  Axes the caller leaves
unsharded therefore just use size-1 rings — there is one program shape for
every decomposition of the torus (the property the reference's hand-rolled
1-D MPI exchange, gol-main.c:86-111, could not scale to).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu import compat


def ring(n: int, shift: int):
    """Permutation delivering each shard the slice from its ring ±1 neighbor.

    ``shift=+1`` receives from the ring predecessor (the reference's
    ``prevRank``, gol-main.c:86), ``shift=-1`` from the successor.
    """
    return [(i, (i + shift) % n) for i in range(n)]


def halo_extend(
    block: jax.Array,
    mesh_axes: Sequence[Tuple[int, str, int]],
    depth=1,
) -> jax.Array:
    """Extend ``block`` by ``depth`` ghost layers on both sides of each axis.

    ``mesh_axes`` is a sequence of ``(array_axis, mesh_axis_name, ring_size)``
    — one entry per array axis to extend, in phase order.  Must be called
    inside ``shard_map`` over a mesh carrying the named axes.  Returns the
    block grown by ``2*depth`` along every listed axis.  ``depth`` may also
    be a sequence, one depth per listed axis — engines whose halo quantum
    differs per axis (the 2-D sharded Pallas engine ships a k-row temporal
    band but a 1-word column band) exchange both in one call.

    ``depth > 1`` is the temporal-blocking exchange: a ``depth``-deep ghost
    shell shipped once supplies ``depth`` generations of local stepping
    (each consuming one layer), so the ring pays 2 ppermutes per axis per
    ``depth`` generations instead of per generation.  A ghost shell must
    come entirely from the immediate ring neighbor, so ``depth`` may not
    exceed the shard's extent along any extended axis.
    """
    depths = (
        (depth,) * len(mesh_axes)
        if isinstance(depth, int)
        else tuple(depth)
    )
    if len(depths) != len(mesh_axes):
        raise ValueError(
            f"{len(depths)} depths for {len(mesh_axes)} extended axes"
        )
    ext = block
    for (axis, name, n), depth in zip(mesh_axes, depths):
        if block.shape[axis] < depth:
            raise ValueError(
                f"halo depth {depth} exceeds shard extent "
                f"{block.shape[axis]} along axis {axis} ({name}); the ghost "
                "shell would need cells from beyond the ring neighbor"
            )
        last = tuple(
            slice(-depth, None) if a == axis else slice(None)
            for a in range(ext.ndim)
        )
        first = tuple(
            slice(None, depth) if a == axis else slice(None)
            for a in range(ext.ndim)
        )
        # Receive the ring-predecessor's last slice (our "low" ghost) and the
        # ring-successor's first slice (our "high" ghost).
        lo = lax.ppermute(ext[last], name, ring(n, 1))
        hi = lax.ppermute(ext[first], name, ring(n, -1))
        ext = jnp.concatenate([lo, ext, hi], axis=axis)
    return ext


def blocked_local_loop(
    step: Callable,
    phases,
    steps: int,
    halo_depth: int,
    pack: Optional[Callable] = None,
    unpack: Optional[Callable] = None,
) -> Callable:
    """Per-shard generation loop with depth-k temporal blocking.

    ``step`` consumes one ghost layer per call (shrink-by-one on every
    extended axis); each chunk halo-extends by ``k`` and applies ``step``
    ``k`` times, so the ring pays one exchange per ``k`` generations.
    ``steps`` is split into full ``halo_depth`` chunks plus one remainder
    chunk.  Optional ``pack``/``unpack`` convert the shard representation
    once around the whole loop (the bit-packed engines' dense-in/dense-out
    contract).  The returned callable is the body for ``shard_map`` —
    shared by the 2-D and 3-D packed engines so their blocking logic
    cannot diverge.
    """
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")

    def chunk(x, k):
        ext = halo_extend(x, phases, depth=k)
        for _ in range(k):  # each generation consumes one ghost layer
            ext = step(ext)
        return ext

    full, rem = divmod(steps, halo_depth)

    def local(x):
        if pack is not None:
            x = pack(x)
        if full:
            x = lax.fori_loop(0, full, lambda _, y: chunk(y, halo_depth), x)
        if rem:
            x = chunk(x, rem)
        if unpack is not None:
            x = unpack(x)
        return x

    return local


# ---------------------------------------------------------------------------
# Depth-k interior/boundary split + the pipelined double-buffer
# ---------------------------------------------------------------------------
#
# The explicit blocked chunk (halo_extend then k shrinking steps) serializes
# every chunk on its exchange: nothing computes until the ring delivers the
# band.  The two forms below break that dependency.
#
# - ``overlap_local_loop``: the SAME per-chunk exchange, but the chunk is
#   computed as interior + boundary slabs — the interior (rows [k, h-k) on
#   every extended axis, the bulk) reads only local data, so XLA's
#   latency-hiding scheduler runs the ring ppermutes underneath it; only
#   the 2k boundary layers per axis wait for the band.  This lifts the
#   depth-1 restriction of the hand-written overlap steps in ops/stencil.py
#   to any k.
#
# - ``pipelined_local_loop``: the cross-chunk double buffer.  The loop
#   carries ``(block, bands)``; each iteration consumes the band exchanged
#   DURING the previous chunk's compute, and ships the next chunk's band
#   from the just-computed boundary slabs — operands that never depend on
#   the interior kernel, so the exchange for chunk N+1 is in flight while
#   chunk N's interior still computes and its latency hides entirely.  The
#   carried band is "one chunk stale" only in wall-clock: its contents are
#   the neighbor's boundary at this chunk's start generation, which is
#   exactly what the ghost shell must hold — correctness is unchanged, and
#   every form below is pinned bit-identical to the explicit path.
#
# Both forms pay the same redundant boundary recompute as any temporal
# block (each 3k-deep slab re-steps its overlap with the interior).  The
# split is exact for the integer stencils here: stepping a slab yields
# bit-identical cells to stepping the whole extended array, because every
# step is a pure elementwise window op (wraps only on axes both forms keep
# at full extent).


def _axis_slice(ndim: int, axis: int, s: slice):
    return tuple(s if a == axis else slice(None) for a in range(ndim))


def _shrink(step: Callable, x: jax.Array, n: int) -> jax.Array:
    for _ in range(n):  # each generation consumes one ghost layer
        x = step(x)
    return x


def exchange_bands(block: jax.Array, phases, depth: int):
    """The ``depth``-deep ghost bands of ``block``, in phase order.

    Exactly the slices :func:`halo_extend` ships — phase i's bands carry
    the earlier phases' ghost layers on their corner regions — returned
    as ``((lo_0, hi_0), ...)`` instead of concatenated, so a pipelined
    loop can carry them across chunks.
    """
    bands = []
    ext = block
    for axis, name, n in phases:
        if block.shape[axis] < depth:
            raise ValueError(
                f"halo depth {depth} exceeds shard extent "
                f"{block.shape[axis]} along axis {axis} ({name}); the ghost "
                "shell would need cells from beyond the ring neighbor"
            )
        lo = lax.ppermute(
            ext[_axis_slice(ext.ndim, axis, slice(-depth, None))],
            name,
            ring(n, 1),
        )
        hi = lax.ppermute(
            ext[_axis_slice(ext.ndim, axis, slice(None, depth))],
            name,
            ring(n, -1),
        )
        bands.append((lo, hi))
        ext = jnp.concatenate([lo, ext, hi], axis=axis)
    return tuple(bands)


def assemble_ext(block: jax.Array, bands, phases) -> jax.Array:
    """Rebuild the halo-extended array from a block and its bands —
    bit-identical to :func:`halo_extend`'s output for the same depth."""
    ext = block
    for (axis, _, _), (lo, hi) in zip(phases, bands):
        ext = jnp.concatenate([lo, ext, hi], axis=axis)
    return ext


def trim_bands(bands, phases, k: int, kk: int):
    """Slice ``k``-deep bands down to the ``kk`` layers adjacent to the
    block (the remainder chunk's consumption of a full-depth band)."""
    if kk == k:
        return bands
    out = []
    for i, (axis_i, _, _) in enumerate(phases):
        lo, hi = bands[i]
        nd = lo.ndim
        sl_lo = [slice(None)] * nd
        sl_hi = [slice(None)] * nd
        sl_lo[axis_i] = slice(-kk, None)  # layers nearest the block
        sl_hi[axis_i] = slice(None, kk)
        for j in range(i):  # corner regions shrink with the depth
            axis_j = phases[j][0]
            sl_lo[axis_j] = slice(k - kk, -(k - kk))
            sl_hi[axis_j] = slice(k - kk, -(k - kk))
        out.append((lo[tuple(sl_lo)], hi[tuple(sl_hi)]))
    return tuple(out)


def can_split(shape, phases, kk: int) -> bool:
    """Whether the interior/boundary split has a nonempty interior at
    depth ``kk`` (tiny shards fall back to the whole-array chunk)."""
    return all(shape[axis] > 2 * kk for axis, _, _ in phases)


def split_chunk(step: Callable, phases, block: jax.Array, bands, kk: int):
    """One interior/boundary-split chunk of ``kk`` generations.

    Returns ``(next_block, slabs)``: ``slabs[i] = (lo, hi)`` are the
    untrimmed ``kk``-deep boundary slabs of ``next_block`` along each
    phase axis at full extent on every other axis — exactly the operands
    a pipelined exchange ships, computed without touching the interior.
    The interior itself is stepped from ``block`` alone, so it carries no
    data dependency on the bands (the overlap property).
    """
    nd = block.ndim
    ext = assemble_ext(block, bands, phases)
    interior = _shrink(step, block, kk)
    slabs = []
    for axis, _, _ in phases:
        # A 3kk-deep slab of ext along this axis covers the kk-deep
        # output boundary at full extent on every other axis (those stay
        # ghost-extended in ext, and each step consumes one layer of
        # every extended axis).
        lo = _shrink(step, ext[_axis_slice(nd, axis, slice(None, 3 * kk))], kk)
        hi = _shrink(step, ext[_axis_slice(nd, axis, slice(-3 * kk, None))], kk)
        slabs.append((lo, hi))
    out = interior
    for i in range(len(phases) - 1, -1, -1):
        axis = phases[i][0]
        lo, hi = slabs[i]
        sl = [slice(None)] * nd
        for j in range(i):  # earlier-phase slabs own the corners
            sl[phases[j][0]] = slice(kk, -kk)
        out = jnp.concatenate([lo[tuple(sl)], out, hi[tuple(sl)]], axis=axis)
    return out, tuple(slabs)


def exchange_from_slabs(slabs, phases, k: int):
    """Ship the next chunk's bands from boundary slabs alone.

    Phase i's operands are the first/last ``k`` layers of the
    phase-(<i)-extended next block along axis i — assembled from the
    untrimmed slabs plus the NEW bands of earlier phases (the corner
    two-hop), so no ppermute operand ever depends on the interior
    kernel.  This is the property the pipeline exists for: the exchange
    is already in flight while the interior computes.
    """
    bands = []
    for i, (axis, name, n) in enumerate(phases):
        lo_shell, hi_shell = slabs[i]
        nd = lo_shell.ndim
        for j in range(i):
            axis_j = phases[j][0]
            new_lo_j, new_hi_j = bands[j]
            first = _axis_slice(nd, axis, slice(None, k))
            last = _axis_slice(nd, axis, slice(-k, None))
            lo_shell = jnp.concatenate(
                [new_lo_j[first], lo_shell, new_hi_j[first]], axis=axis_j
            )
            hi_shell = jnp.concatenate(
                [new_lo_j[last], hi_shell, new_hi_j[last]], axis=axis_j
            )
        lo = lax.ppermute(hi_shell, name, ring(n, 1))
        hi = lax.ppermute(lo_shell, name, ring(n, -1))
        bands.append((lo, hi))
    return tuple(bands)


def _consume_chunk(step: Callable, phases, block: jax.Array, bands, kk: int):
    """One chunk from an already-exchanged band: split form where the
    interior is nonempty, whole-extended-array form on tiny shards."""
    if can_split(block.shape, phases, kk):
        out, _ = split_chunk(step, phases, block, bands, kk)
        return out
    return _shrink(step, assemble_ext(block, bands, phases), kk)


def overlap_local_loop(
    step: Callable,
    phases,
    steps: int,
    halo_depth: int,
    pack: Optional[Callable] = None,
    unpack: Optional[Callable] = None,
) -> Callable:
    """Depth-k comm/compute-overlap loop (the depth-1 restriction lifted).

    Per chunk: exchange the k-deep bands, then compute the chunk as
    interior + boundary slabs — the interior launch carries no data
    dependency on the ppermutes.  Same exchange count and bit-identical
    results as :func:`blocked_local_loop`.
    """
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")

    def chunk(x, kk):
        return _consume_chunk(step, phases, x, exchange_bands(x, phases, kk), kk)

    full, rem = divmod(steps, halo_depth)

    def local(x):
        if pack is not None:
            x = pack(x)
        if full:
            x = lax.fori_loop(0, full, lambda _, y: chunk(y, halo_depth), x)
        if rem:
            x = chunk(x, rem)
        if unpack is not None:
            x = unpack(x)
        return x

    return local


def pipelined_local_loop(
    step: Callable,
    phases,
    steps: int,
    halo_depth: int,
    pack: Optional[Callable] = None,
    unpack: Optional[Callable] = None,
) -> Callable:
    """Cross-chunk double-buffered loop (``shard_mode "pipeline"``).

    The loop carries ``(block, bands)``: each iteration consumes the band
    exchanged during the PREVIOUS chunk's compute and ships the next
    chunk's band from its just-computed boundary slabs, so exchange
    latency hides under interior compute entirely.  Exactly one exchange
    per chunk: one prologue exchange, one per loop iteration, and a
    remainder chunk that consumes the final band (sliced to its depth)
    instead of exchanging again; with no remainder the last chunk runs
    consume-only.  Bit-identical to the explicit blocked loop.
    """
    if halo_depth < 1:
        raise ValueError(f"halo_depth must be >= 1, got {halo_depth}")
    k = halo_depth
    full, rem = divmod(steps, k)

    def body(carry):
        x, bands = carry
        if can_split(x.shape, phases, k):
            nx, slabs = split_chunk(step, phases, x, bands, k)
        else:
            # Tiny shard: every layer is boundary — compute whole, ship
            # slices (correct; there is no interior to hide behind).
            nx = _shrink(step, assemble_ext(x, bands, phases), k)
            nd = nx.ndim
            slabs = tuple(
                (
                    nx[_axis_slice(nd, axis, slice(None, k))],
                    nx[_axis_slice(nd, axis, slice(-k, None))],
                )
                for axis, _, _ in phases
            )
        return nx, exchange_from_slabs(slabs, phases, k)

    def local(x):
        if pack is not None:
            x = pack(x)
        if steps:
            if full == 0:
                # Remainder only: one exchange at the remainder's depth.
                x = _consume_chunk(
                    step, phases, x, exchange_bands(x, phases, rem), rem
                )
            else:
                bands = exchange_bands(x, phases, k)  # prologue
                n_loop = full if rem else full - 1
                if n_loop:
                    x, bands = lax.fori_loop(
                        0, n_loop, lambda _, c: body(c), (x, bands)
                    )
                if rem:
                    x = _consume_chunk(
                        step, phases, x, trim_bands(bands, phases, k, rem), rem
                    )
                else:
                    # Final chunk consume-only — no wasted exchange.
                    x = _consume_chunk(step, phases, x, bands, k)
        if unpack is not None:
            x = unpack(x)
        return x

    return local


LOCAL_LOOPS = {
    "explicit": blocked_local_loop,
    "overlap": overlap_local_loop,
    "pipeline": pipelined_local_loop,
}


def build_ring_engine(
    mesh,
    steps: int,
    halo_depth: int,
    step_1d: Callable,
    step_2d: Callable,
    pack: Optional[Callable] = None,
    unpack: Optional[Callable] = None,
    mode: str = "explicit",
):
    """jit'ed shard_map ring engine over a 1-D or 2-D board mesh.

    The one builder behind the packed Conway engine and the generic-rule
    engines: picks the row-only or row+column phase list from the mesh's
    axes, wires the matching shrink-by-one ``step`` through the ``mode``'s
    chunk loop (:data:`LOCAL_LOOPS`: explicit blocked / depth-k overlap /
    pipelined double-buffer), and returns the donated-input jitted
    program.  Keeping this in one place means a change to the mesh-phase
    or donation conventions cannot diverge between engines.
    """
    from gol_tpu.parallel.mesh import COLS, ROWS
    from jax.sharding import PartitionSpec as P

    if mode not in LOCAL_LOOPS:
        raise ValueError(
            f"unknown ring-engine mode {mode!r}; expected one of "
            f"{tuple(LOCAL_LOOPS)}"
        )
    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)
    if COLS in mesh.axis_names:
        phases = ((0, ROWS, num_rows), (1, COLS, num_cols))
        step, spec = step_2d, P(ROWS, COLS)
    else:
        phases = ((0, ROWS, num_rows),)
        step, spec = step_1d, P(ROWS, None)

    local = LOCAL_LOOPS[mode](
        step, phases, steps, halo_depth, pack=pack, unpack=unpack
    )
    shmapped = compat.shard_map(local, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(shmapped, donate_argnums=0)
