"""Generation-loop engines over the whole (possibly multi-rank) world.

Two semantics are offered, per the bug-register decision in SURVEY §5 (B1):

- **fresh** (default): correct torus Game of Life.  The halo rows a block
  sees are always the neighbors' *current* boundary rows — on a sharded mesh
  they are delivered by ``lax.ppermute`` every step
  (:mod:`gol_tpu.parallel.sharded`); on a single device the plain torus
  stencil is equivalent.
- **stale_t0** (reference-compat): the reference fills its halo send buffers
  once at t=0 and never refreshes them (``init_Ghost_rows``,
  gol-with-cuda.cu:40-47; no re-copy anywhere in the loop,
  gol-main.c:94-116), so every step each rank receives its ring neighbors'
  t=0 boundary rows.  After t=0 the rank blocks evolve independently — which
  is exactly how we implement it: the frozen halos are computed once from
  the initial board and the per-rank evolution is a ``vmap`` over the rank
  axis, the whole multi-generation loop one compiled ``fori_loop``.

Both keep all generations on-device in a single compiled program — no
per-step host round-trip (the reference pays ``cudaDeviceSynchronize`` +
2×``MPI_Wait`` per generation, gol-with-cuda.cu:277 / gol-main.c:110-111).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.ops import stencil

HALO_MODES = ("fresh", "stale_t0")


def split_ranks(global_board: jax.Array, num_ranks: int) -> jax.Array:
    """[R*S, W] -> [R, S, W] stack of per-rank blocks."""
    height, width = global_board.shape
    if height % num_ranks:
        raise ValueError(f"height {height} not divisible by {num_ranks} ranks")
    return global_board.reshape(num_ranks, height // num_ranks, width)


@functools.partial(jax.jit, static_argnums=(1,), donate_argnums=(0,))
def evolve_fresh(global_board: jax.Array, steps: int) -> jax.Array:
    """Correct torus semantics on one device (halos implicit in the wrap)."""
    return lax.fori_loop(0, steps, lambda _, b: stencil.step(b), global_board)


def frozen_halos(
    global_board: jax.Array, num_ranks: int
) -> tuple[jax.Array, jax.Array]:
    """The t=0 ghost rows every rank keeps receiving under bug B1.

    Rank r's top ghost row is rank (r-1)%R's t=0 last row, its bottom ghost
    row is rank (r+1)%R's t=0 first row (ring neighbor ids as in
    gol-main.c:86-87).  Shapes: ([R, W], [R, W]).
    """
    blocks = split_ranks(global_board, num_ranks)
    top0 = jnp.roll(blocks[:, -1, :], 1, axis=0)
    bottom0 = jnp.roll(blocks[:, 0, :], -1, axis=0)
    return top0, bottom0


@functools.partial(jax.jit, static_argnums=(3, 4), donate_argnums=(0,))
def evolve_stale_with_halos(
    global_board: jax.Array,
    top0: jax.Array,
    bottom0: jax.Array,
    num_ranks: int,
    steps: int,
) -> jax.Array:
    """Reference-compat evolution given explicitly frozen halos.

    Split out from :func:`evolve_stale_t0` so chunked/checkpointed/resumed
    runs keep the *original* t=0 halos instead of re-freezing from the
    current board (which would silently change the semantics mid-run).
    """
    blocks = split_ranks(global_board, num_ranks)  # [R, S, W]
    step_all = jax.vmap(stencil.step_halo_rows)
    out = lax.fori_loop(0, steps, lambda _, b: step_all(b, top0, bottom0), blocks)
    return out.reshape(global_board.shape)


def evolve_stale_t0(global_board: jax.Array, num_ranks: int, steps: int) -> jax.Array:
    """Reference-compat (bug B1) semantics, halos frozen from this board."""
    top0, bottom0 = frozen_halos(global_board, num_ranks)
    return evolve_stale_with_halos(global_board, top0, bottom0, num_ranks, steps)


def evolve(
    global_board: jax.Array, steps: int, num_ranks: int = 1, halo_mode: str = "fresh"
) -> jax.Array:
    """Dispatch on halo semantics. ``num_ranks`` only matters for stale_t0."""
    if halo_mode == "fresh":
        return evolve_fresh(global_board, steps)
    if halo_mode == "stale_t0":
        return evolve_stale_t0(global_board, num_ranks, steps)
    raise ValueError(f"unknown halo_mode {halo_mode!r}; expected one of {HALO_MODES}")
