"""Sharded engines for generalized B/S rules (dense and bit-packed).

The Conway engines (:mod:`gol_tpu.parallel.sharded`, :mod:`~.packed`) own
the hard-wired fast paths; this module is their rule-parameterized twin,
built from the same pieces — :func:`gol_tpu.parallel.halo.halo_extend`
ring exchanges and the :func:`~gol_tpu.parallel.halo.blocked_local_loop`
temporal-blocking driver — with the generic shrink-by-one step functions
of :mod:`gol_tpu.ops.rules`.  One program shape per (mesh, rule, depth),
identical placement/donation contract.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import Mesh

from gol_tpu.ops import bitlife, rules as rules_mod
from gol_tpu.parallel.halo import build_ring_engine
from gol_tpu.parallel.mesh import validate_geometry
from gol_tpu.parallel.packed import validate_packed_geometry
from gol_tpu.parallel.sharded import place_private


@functools.lru_cache(maxsize=64)
def compiled_evolve_rule(
    mesh: Mesh,
    steps: int,
    rule: rules_mod.Rule2D,
    packed: bool = False,
    halo_depth: int = 1,
):
    """Build + jit the sharded generic-rule evolve.

    ``packed=True`` runs the bit-plane evaluator on 32-cell words (packed
    row halos; word-quantum ghost columns on 2-D meshes), ``False`` the
    dense one.  ``halo_depth=k`` is temporal blocking exactly as in the
    Conway engines.  The input buffer is donated.
    """
    if packed:
        step_1d = lambda ext: rules_mod.step_rule_packed_vext(ext, rule)
        step_2d = lambda ext: rules_mod.step_rule_packed_halo_full(ext, rule)
    else:
        step_1d = lambda ext: rules_mod.step_rule_halo_rows(ext, rule)
        step_2d = lambda ext: rules_mod.step_rule_halo_full(ext, rule)
    return build_ring_engine(
        mesh,
        steps,
        halo_depth,
        step_1d,
        step_2d,
        pack=bitlife.pack if packed else None,
        unpack=bitlife.unpack if packed else None,
    )


def evolve_sharded_rule(
    board: jax.Array,
    steps: int,
    mesh: Mesh,
    rule: rules_mod.Rule2D,
    packed: bool = False,
    halo_depth: int = 1,
) -> jax.Array:
    """Evolve a dense board over ``mesh`` under ``rule``.

    Placement/copy contract matches the Conway engines: the caller's array
    is never consumed by the donated buffer.
    """
    if packed:
        validate_packed_geometry(board.shape, mesh)
    else:
        validate_geometry(board.shape, mesh)
    return compiled_evolve_rule(mesh, steps, rule, packed, halo_depth)(
        place_private(board, mesh)
    )
