"""Device-side resharding: the live-elasticity collective (ROADMAP 4b).

``resilience/reshard.py`` makes snapshots portable across topologies,
but its transport is the host: pieces are read, repacked, and re-placed
through ``make_array_from_callback``.  That is the right shape for a
*restart* event; a *live* mesh change (device loss, capacity return —
docs/RESILIENCE.md, "Live elasticity") cannot afford the device→host
round trip.  This module executes the SAME validated
:class:`~gol_tpu.resilience.reshard.ReshardPlan` move table as a
``shard_map`` program of ``lax.ppermute`` phases over bit-packed words,
so a board (or a batch-tier world stack) moves from mesh A to mesh B
without the cells ever leaving device memory:

- **pack** — a ``shard_map`` over the source mesh packs each shard
  in-graph (:mod:`gol_tpu.ops.bitlife` layout, 32 cells per uint32
  word), stacking the pieces along a leading axis.
- **exchange** — a flat 1-D transfer mesh over the union of source and
  destination devices runs one ``ppermute`` ring-shift phase per
  distinct (src device → dst device) offset in the move table — the
  portable all-to-all of the redistribution paper (PAPERS.md), as a
  persistent schedule rebuilt only when the plan changes (the
  persistent-collective framing of the partitioned-MPI paper).  Each
  device then assembles its destination shard with a
  ``lax.switch`` over statically unrolled per-device move lists; column
  seams that cut a source word mid-bit are realigned with the same
  logical-shift pair the host path uses (``w >> s | w' << 32-s``), in
  the graph.
- **land** — a ``shard_map`` over the destination mesh unpacks the
  assembled words into the canonical board sharding.

The executor is pinned bit-equal to the host-side ``load_resharded``
path on every none/1d/2d grow+shrink pair (tests/test_redistribute.py)
and its static program is re-verified by
``gol_tpu/analysis/redistcheck.py`` (exactly-once coverage derived from
the branch tables themselves, plus broken-plan TEETH).  Transport is
destination-major: the union device list starts with the destination
mesh so landing is a prefix slice and the exchange output already sits
on the devices that keep it.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu.compat import shard_map
from gol_tpu.ops import bitlife
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.resilience.reshard import (
    Box,
    MeshLayout,
    ReshardError,
    ReshardPlan,
    WORD_BITS,
    plan_reshard,
    validate_plan,
)

XFER = "xfer"  # the flat union-mesh axis the exchange phases ride


# -- packed helpers (arbitrary widths; bitlife.pack wants 32-multiples) -------


def _packed_words(width: int) -> int:
    return -(-width // WORD_BITS)


def _pack_cells(cells: jax.Array) -> jax.Array:
    """uint8[h, w] -> uint32[h, ceil(w/32)], padding the tail word."""
    h, w = cells.shape
    pad = (-w) % WORD_BITS
    if pad:
        cells = jnp.pad(cells, ((0, 0), (0, pad)))
    return bitlife.pack(cells)


def _unpack_cells(words: jax.Array, width: int) -> jax.Array:
    return bitlife.unpack(words)[:, :width]


def _extract_cols(words: jax.Array, c0: int, c1: int) -> jax.Array:
    """Packed cells ``[c0, c1)`` realigned so bit 0 is column ``c0``.

    The in-graph twin of ``reshard.slice_packed_cols``: a shift pair
    (``w[k] >> s | w[k+1] << 32-s``) when the seam cuts mid-word, a
    plain word slice when it does not.  The tail word is masked so the
    result ORs cleanly into a destination canvas.
    """
    nb = c1 - c0
    q, s = divmod(c0, WORD_BITS)
    now = _packed_words(nb)
    need = now + (1 if s else 0)
    w = words[:, q : q + need]
    if w.shape[1] < need:
        w = jnp.pad(w, ((0, 0), (0, need - w.shape[1])))
    if s:
        out = (w[:, :now] >> np.uint32(s)) | (
            w[:, 1 : now + 1] << np.uint32(WORD_BITS - s)
        )
    else:
        out = w
    tail = nb % WORD_BITS
    if tail:
        out = out.at[:, now - 1].set(
            out[:, now - 1] & np.uint32((1 << tail) - 1)
        )
    return out


def _deposit_cols(
    canvas: jax.Array,
    r0: int,
    r1: int,
    bits: jax.Array,
    c0: int,
    nb: int,
) -> jax.Array:
    """OR ``bits`` (bit 0 = dst column ``c0``) into the canvas rows."""
    q, s = divmod(c0, WORD_BITS)
    if s:
        lo = bits << np.uint32(s)
        hi = bits >> np.uint32(WORD_BITS - s)
        shifted = jnp.concatenate(
            [lo, jnp.zeros_like(bits[:, :1])], axis=1
        )
        shifted = shifted.at[:, 1:].set(shifted[:, 1:] | hi)
    else:
        shifted = bits
    # The carry word can poke past the canvas only when its content is
    # already zero (the tail was masked at extraction) — clip it.
    span = min(shifted.shape[1], canvas.shape[1] - q)
    region = canvas[r0:r1, q : q + span]
    return canvas.at[r0:r1, q : q + span].set(region | shifted[:, :span])


# -- the static exchange schedule --------------------------------------------


class _Schedule:
    """Everything the SPMD program needs, derived once per plan.

    ``branch_moves[p]`` lists, for the device at union position ``p``,
    the statically-resolved moves that build its destination piece:
    ``(phase, src_box, dst_box, inter)`` — which received buffer to
    read and which rectangle to cut and paste.  ``redistcheck`` paints
    its coverage canvas from THESE tables (not from the plan), so a bug
    in the phase assignment — not just in the geometry — fails the
    verify gate.
    """

    def __init__(
        self,
        plan: ReshardPlan,
        src_devices: Sequence,
        dst_devices: Sequence,
    ) -> None:
        validate_plan(plan)
        self.plan = plan
        self.src_boxes: List[Box] = plan.src.boxes(plan.shape)
        self.dst_boxes: List[Box] = [d for d, _ in plan.moves]
        if len(self.src_boxes) != len(src_devices):
            raise ReshardError(
                f"plan has {len(self.src_boxes)} source pieces but the "
                f"source mesh holds {len(src_devices)} devices"
            )
        if len(self.dst_boxes) != len(dst_devices):
            raise ReshardError(
                f"plan has {len(self.dst_boxes)} destination shards but "
                f"the destination mesh holds {len(dst_devices)} devices"
            )
        # Destination-major union: landing is a prefix slice.
        self.union = list(dict.fromkeys(list(dst_devices) + list(src_devices)))
        self.n = len(self.union)
        upos = {d: p for p, d in enumerate(self.union)}
        self.pos_src = [upos[d] for d in src_devices]
        self.pos_dst = [upos[d] for d in dst_devices]
        src_index = {b: i for i, b in enumerate(self.src_boxes)}
        shifts = sorted(
            {
                (self.pos_dst[j] - self.pos_src[src_index[sbox]]) % self.n
                for j, (_, srcs) in enumerate(plan.moves)
                for sbox, _ in srcs
            }
        )
        self.shifts: List[int] = shifts
        phase_of = {s: k for k, s in enumerate(shifts)}
        self.branch_moves: List[List[Tuple[int, Box, Box, Box]]] = [
            [] for _ in range(self.n)
        ]
        for j, (dbox, srcs) in enumerate(plan.moves):
            p = self.pos_dst[j]
            for sbox, inter in srcs:
                q = self.pos_src[src_index[sbox]]
                self.branch_moves[p].append(
                    (phase_of[(p - q) % self.n], sbox, dbox, inter)
                )


def schedule_coverage(sched: "_Schedule") -> np.ndarray:
    """Per-cell write counts implied by the *compiled* branch tables.

    Exactly-once on-device means this canvas is all-ones.  It is
    deliberately derived from :attr:`_Schedule.branch_moves` — the
    structures the traced program actually unrolls — rather than from
    the plan, so the verifier re-proves the phase assignment, not just
    the geometry ``validate_plan`` already covered.
    """
    h, w = sched.plan.shape
    canvas = np.zeros((h, w), np.int64)
    for p, moves in enumerate(sched.branch_moves):
        for _, _, dbox, inter in moves:
            if _boxpos(sched, dbox) != p:
                raise ReshardError(
                    f"branch {p} writes into foreign dst box {dbox}"
                )
            canvas[inter[0] : inter[1], inter[2] : inter[3]] += 1
    return canvas


def _boxpos(sched: "_Schedule", dbox: Box) -> int:
    return sched.pos_dst[sched.dst_boxes.index(dbox)]


# -- program construction -----------------------------------------------------


def _xfer_mesh(sched: _Schedule) -> Mesh:
    return Mesh(np.asarray(sched.union), (XFER,))


def _exchange_fn(sched: _Schedule, piece_shape, canvas_shape):
    """The per-device exchange+assemble program over the union mesh.

    ``piece_shape``/``canvas_shape`` are the (rows, words) blocks of one
    packed source piece / destination piece.  Rectangles and shifts are
    baked in; the traced graph is identical for identical plans, and no
    host state (fault plane, health plane) is consulted — the
    trace-identity pin in tests/test_redistribute.py holds the program
    to that.
    """
    n = sched.n
    shifts = sched.shifts

    def _branch(p: int):
        moves = sched.branch_moves[p]

        def build(recv):
            canvas = jnp.zeros(canvas_shape, jnp.uint32)
            for phase, sbox, dbox, inter in moves:
                r0, r1, c0, c1 = inter
                piece = recv[phase]
                rows = piece[r0 - sbox[0] : r1 - sbox[0]]
                bits = _extract_cols(rows, c0 - sbox[2], c1 - sbox[2])
                canvas = _deposit_cols(
                    canvas,
                    r0 - dbox[0],
                    r1 - dbox[0],
                    bits,
                    c0 - dbox[2],
                    c1 - c0,
                )
            return canvas

        return build

    branches = [_branch(p) for p in range(n)]

    def exchange(stacked):
        piece = stacked[0]
        recvs = []
        for s in shifts:
            if s == 0:
                recvs.append(piece)
            else:
                perm = [(q, (q + s) % n) for q in range(n)]
                recvs.append(lax.ppermute(piece, XFER, perm))
        recv = jnp.stack(recvs)
        idx = lax.axis_index(XFER)
        return lax.switch(idx, branches, recv)[None]

    return exchange


@functools.lru_cache(maxsize=32)
def _board_program(
    plan: ReshardPlan,
    src_mesh: Optional[Mesh],
    dst_mesh: Optional[Mesh],
):
    """(pack, exchange, land) jitted callables for one board reshard."""
    h, w = plan.shape
    src_devs = (
        list(src_mesh.devices.flat) if src_mesh is not None
        else [jax.devices()[0]]
    )
    dst_devs = (
        list(dst_mesh.devices.flat) if dst_mesh is not None
        else [jax.devices()[0]]
    )
    sched = _Schedule(plan, src_devs, dst_devs)
    sb0 = sched.src_boxes[0]
    db0 = sched.dst_boxes[0]
    piece_shape = (sb0[1] - sb0[0], _packed_words(sb0[3] - sb0[2]))
    canvas_shape = (db0[1] - db0[0], _packed_words(db0[3] - db0[2]))
    xmesh = _xfer_mesh(sched)
    xspec = NamedSharding(xmesh, P(XFER, None, None))

    if src_mesh is None:
        pack = jax.jit(lambda b: _pack_cells(b)[None])
    else:
        axes = (
            (mesh_mod.ROWS, mesh_mod.COLS)
            if mesh_mod.COLS in src_mesh.axis_names
            else mesh_mod.ROWS
        )
        pack = jax.jit(
            shard_map(
                lambda b: _pack_cells(b)[None],
                mesh=src_mesh,
                in_specs=mesh_mod.board_sharding(src_mesh).spec,
                out_specs=P(axes, None, None),
                check_vma=False,
            )
        )

    exchange = jax.jit(
        shard_map(
            _exchange_fn(sched, piece_shape, canvas_shape),
            mesh=xmesh,
            in_specs=P(XFER, None, None),
            out_specs=P(XFER, None, None),
            check_vma=False,
        )
    )

    dw = db0[3] - db0[2]
    if dst_mesh is None:
        land = jax.jit(lambda st: _unpack_cells(st[0], dw))
    else:
        daxes = (
            (mesh_mod.ROWS, mesh_mod.COLS)
            if mesh_mod.COLS in dst_mesh.axis_names
            else mesh_mod.ROWS
        )
        land = jax.jit(
            shard_map(
                lambda st: _unpack_cells(st[0], dw),
                mesh=dst_mesh,
                in_specs=P(daxes, None, None),
                out_specs=mesh_mod.board_sharding(dst_mesh).spec,
                check_vma=False,
            )
        )
    return sched, pack, exchange, land, xspec


def device_reshard(
    board: jax.Array,
    src_mesh: Optional[Mesh],
    dst_mesh: Optional[Mesh],
    plan: Optional[ReshardPlan] = None,
) -> jax.Array:
    """Move ``board`` from ``src_mesh``'s sharding to ``dst_mesh``'s.

    The plan defaults to :func:`plan_reshard` for the two layouts; an
    explicit plan is re-validated first (the broken-fixture TEETH in
    ``redistcheck`` rides this), so an overlapping or gapped move table
    can never reach the device program.  Returns the board under the
    destination mesh's canonical sharding, bit-equal to the host-side
    ``load_resharded`` placement of the same cells.
    """
    h, w = int(board.shape[0]), int(board.shape[1])
    src_layout = MeshLayout.from_mesh(src_mesh)
    dst_layout = MeshLayout.from_mesh(dst_mesh)
    if plan is None:
        plan = plan_reshard(
            (h, w), src_layout.boxes((h, w)), src_layout, dst_layout
        )
    else:
        validate_plan(plan)
    if plan.shape != (h, w):
        raise ReshardError(
            f"plan is for a {plan.shape} board, got {h}x{w}"
        )
    if (plan.src, plan.dst) != (src_layout, dst_layout):
        raise ReshardError(
            f"plan maps {plan.src.describe()} -> {plan.dst.describe()}, "
            f"but the live meshes are {src_layout.describe()} -> "
            f"{dst_layout.describe()}"
        )
    sched, pack, exchange, land, xspec = _board_program(
        plan, src_mesh, dst_mesh
    )
    dtype = board.dtype
    stacked = pack(board.astype(jnp.uint8))
    stacked = _to_union(stacked, sched, xspec)
    out = exchange(stacked)
    landed = _from_union(out, sched, dst_mesh)
    return land(landed).astype(dtype)


def _to_union(stacked, sched: _Schedule, xspec) -> jax.Array:
    """Route the packed src-piece stack onto its union-mesh positions.

    Union ordering is destination-major, so source piece ``i`` belongs
    at position ``pos_src[i]`` — a permutation (plus zero slots for
    devices that only receive).  The heavy all-to-all is the exchange
    program; this step only relabels buffers (and is a same-device
    no-op when the meshes overlap).
    """
    n_src = len(sched.pos_src)
    take = np.full((sched.n,), n_src, np.int32)
    for i, p in enumerate(sched.pos_src):
        take[p] = i
    padded = jnp.concatenate(
        [stacked, jnp.zeros_like(stacked[:1])], axis=0
    )
    return jax.device_put(jnp.take(padded, take, axis=0), xspec)


def _from_union(out, sched: _Schedule, dst_mesh) -> jax.Array:
    """Prefix-slice the exchange output back to the destination stack."""
    n_dst = len(sched.pos_dst)
    sliced = out[:n_dst]
    if dst_mesh is None:
        return jax.device_put(sliced, sched.union[0])
    daxes = (
        (mesh_mod.ROWS, mesh_mod.COLS)
        if mesh_mod.COLS in dst_mesh.axis_names
        else mesh_mod.ROWS
    )
    return jax.device_put(
        sliced, NamedSharding(dst_mesh, P(daxes, None, None))
    )


# -- batch-tier world stacks --------------------------------------------------


def plan_worlds(batch: int, n_src: int, n_dst: int) -> ReshardPlan:
    """A move table over the worlds axis of a ``[B, H, W]`` stack.

    Worlds reshard as whole rows of a ``(B, 32)`` pseudo-board — the
    column range is always one full word, so the exchange ships whole
    packed worlds and never touches the seam repack.  ``B`` must divide
    both device counts (the serve tier enforces slots % devices == 0).
    """
    src = MeshLayout("none") if n_src == 1 else MeshLayout("1d", rows=n_src)
    dst = MeshLayout("none") if n_dst == 1 else MeshLayout("1d", rows=n_dst)
    shape = (batch, WORD_BITS)
    return plan_reshard(shape, src.boxes(shape), src, dst)


@functools.lru_cache(maxsize=32)
def _worlds_program(
    plan: ReshardPlan,
    hw: Tuple[int, int],
    src_mesh: Optional[Mesh],
    dst_mesh: Optional[Mesh],
):
    from gol_tpu.batch import engines as batch_engines

    h, w = hw
    src_devs = (
        list(src_mesh.devices.flat) if src_mesh is not None
        else [jax.devices()[0]]
    )
    dst_devs = (
        list(dst_mesh.devices.flat) if dst_mesh is not None
        else [jax.devices()[0]]
    )
    sched = _Schedule(plan, src_devs, dst_devs)
    b_src = sched.src_boxes[0][1] - sched.src_boxes[0][0]
    b_dst = sched.dst_boxes[0][1] - sched.dst_boxes[0][0]
    ww = _packed_words(w)
    xmesh = _xfer_mesh(sched)
    xspec = NamedSharding(xmesh, P(XFER, None, None, None))
    W = batch_engines.WORLDS

    def _pack_block(block):  # [b, h, w] -> [b, h, ww]
        return jax.vmap(_pack_cells)(block)

    def _unpack_block(block):  # [b, h, ww] -> [b, h, w]
        return jax.vmap(lambda ws: _unpack_cells(ws, w))(block)

    if src_mesh is None:
        pack = jax.jit(lambda st: _pack_block(st)[None])
    else:
        pack = jax.jit(
            shard_map(
                lambda st: _pack_block(st)[None],
                mesh=src_mesh,
                in_specs=P(W, None, None),
                out_specs=P(W, None, None, None),
                check_vma=False,
            )
        )

    def _branch(p: int):
        moves = sched.branch_moves[p]

        def build(recv):
            canvas = jnp.zeros((b_dst, h, ww), jnp.uint32)
            for phase, sbox, dbox, inter in moves:
                a0, a1 = inter[0] - sbox[0], inter[1] - sbox[0]
                d0, d1 = inter[0] - dbox[0], inter[1] - dbox[0]
                canvas = canvas.at[d0:d1].set(recv[phase][a0:a1])
            return canvas

        return build

    branches = [_branch(p) for p in range(sched.n)]
    shifts = sched.shifts
    n = sched.n

    def exchange_body(stacked):
        piece = stacked[0]
        recvs = []
        for s in shifts:
            if s == 0:
                recvs.append(piece)
            else:
                perm = [(q, (q + s) % n) for q in range(n)]
                recvs.append(lax.ppermute(piece, XFER, perm))
        recv = jnp.stack(recvs)
        return lax.switch(lax.axis_index(XFER), branches, recv)[None]

    exchange = jax.jit(
        shard_map(
            exchange_body,
            mesh=xmesh,
            in_specs=P(XFER, None, None, None),
            out_specs=P(XFER, None, None, None),
            check_vma=False,
        )
    )

    if dst_mesh is None:
        land = jax.jit(lambda st: _unpack_block(st[0]))
    else:
        land = jax.jit(
            shard_map(
                lambda st: _unpack_block(st[0]),
                mesh=dst_mesh,
                in_specs=P(W, None, None, None),
                out_specs=P(W, None, None),
                check_vma=False,
            )
        )
    return sched, pack, exchange, land, xspec, b_src


def device_reshard_worlds(
    stack: jax.Array,
    src_mesh: Optional[Mesh],
    dst_mesh: Optional[Mesh],
    plan: Optional[ReshardPlan] = None,
) -> jax.Array:
    """Move a ``[B, H, W]`` world stack between worlds meshes, on device.

    The serve tier's live-elasticity hook: bucket-group stacks ride this
    at a chunk boundary when the health plane shrinks or regrows the
    mesh (docs/SERVING.md).  Same contract as :func:`device_reshard`:
    plan re-validated, result bit-equal to a host round trip.
    """
    b, h, w = (int(x) for x in stack.shape)
    n_src = 1 if src_mesh is None else src_mesh.devices.size
    n_dst = 1 if dst_mesh is None else dst_mesh.devices.size
    if plan is None:
        plan = plan_worlds(b, n_src, n_dst)
    else:
        validate_plan(plan)
    if plan.shape[0] != b:
        raise ReshardError(
            f"worlds plan is for {plan.shape[0]} worlds, stack holds {b}"
        )
    sched, pack, exchange, land, xspec, _ = _worlds_program(
        plan, (h, w), src_mesh, dst_mesh
    )
    dtype = stack.dtype
    packed = pack(stack.astype(jnp.uint8))
    packed = _to_union(packed, sched, xspec)
    out = exchange(packed)
    n_dst_slots = len(sched.pos_dst)
    sliced = out[:n_dst_slots]
    if dst_mesh is None:
        sliced = jax.device_put(sliced, sched.union[0])
    else:
        from gol_tpu.batch import engines as batch_engines

        sliced = jax.device_put(
            sliced,
            NamedSharding(
                dst_mesh, P(batch_engines.WORLDS, None, None, None)
            ),
        )
    return land(sliced).astype(dtype)


# -- verifier surface ---------------------------------------------------------


def board_schedule(
    plan: ReshardPlan,
    src_mesh: Optional[Mesh],
    dst_mesh: Optional[Mesh],
) -> _Schedule:
    """The static exchange schedule ``redistcheck`` audits (no tracing)."""
    src_devs = (
        list(src_mesh.devices.flat) if src_mesh is not None
        else [jax.devices()[0]]
    )
    dst_devs = (
        list(dst_mesh.devices.flat) if dst_mesh is not None
        else [jax.devices()[0]]
    )
    return _Schedule(plan, src_devs, dst_devs)


def lowered_exchange_text(
    plan: ReshardPlan,
    src_mesh: Optional[Mesh],
    dst_mesh: Optional[Mesh],
) -> str:
    """Lowered text of the exchange program (the trace-identity pin).

    The health plane and fault plane are host-side by construction;
    arming either must leave this string byte-identical.
    """
    sched, _, exchange, _, xspec = _board_program(plan, src_mesh, dst_mesh)
    sb0 = sched.src_boxes[0]
    shape = (
        sched.n,
        sb0[1] - sb0[0],
        _packed_words(sb0[3] - sb0[2]),
    )
    arg = jax.ShapeDtypeStruct(shape, jnp.uint32, sharding=xspec)
    return str(exchange.lower(arg).as_text())
