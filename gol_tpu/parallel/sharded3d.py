"""Sharded 3-D Life: volume decomposition with three-phase halo rings.

BASELINE.md config 5 (stretch): the 26-neighbor stencil over a
``(planes, rows, cols)`` device mesh.  Each step halo-extends the shard by
one ghost shell via :func:`gol_tpu.parallel.halo.halo_extend` — three
ppermute phases whose later phases ship slices of the already-extended
block, so the 12 edge and 8 corner regions of the 3-D decomposition land
without diagonal messages (6 ppermutes total; an MPI code would need up to
26 point-to-point messages per shard, cf. the reference's 4 for 1-D,
gol-main.c:97-107).

Mesh axes of size 1 degenerate to the local torus wrap (see halo.py), so
the same compiled program shape covers every decomposition from fully
local (1×1×1) to fully sharded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.ops.life3d import BAYS_4555, Rule3D, step3d_halo_full
from gol_tpu.parallel.halo import LOCAL_LOOPS, blocked_local_loop, halo_extend
from gol_tpu.parallel.mesh import COLS, PLANES, ROWS, place_private


def _phases(mesh: Mesh):
    """(array_axis, mesh_axis, ring_size) per volume axis, in phase order."""
    return tuple(
        (axis, name, mesh.shape.get(name, 1))
        for axis, name in enumerate((PLANES, ROWS, COLS))
    )


def volume_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical volume sharding: (planes, rows, cols) split over the mesh."""
    return NamedSharding(mesh, P(PLANES, ROWS, COLS))


def validate_geometry3d(shape, mesh: Mesh) -> None:
    for dim, name in zip(shape, (PLANES, ROWS, COLS)):
        n = mesh.shape.get(name, 1)
        if dim % n:
            raise ValueError(
                f"volume axis {name} of size {dim} not divisible by its "
                f"mesh axis of size {n}"
            )


@functools.lru_cache(maxsize=64)
def compiled_evolve3d(mesh: Mesh, steps: int, rule: Rule3D):
    """Build + jit the sharded 3-D evolve for (mesh, steps, rule).

    The whole generation loop runs inside one program; the input volume
    buffer is donated (the double buffer).
    """
    phases = _phases(mesh)

    def body(_, vol):
        return step3d_halo_full(halo_extend(vol, phases), rule)

    spec = P(PLANES, ROWS, COLS)
    local = compat.shard_map(
        lambda v: lax.fori_loop(0, steps, body, v),
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
    )
    return jax.jit(local, donate_argnums=0)


def evolve_sharded3d(
    vol: jax.Array, steps: int, mesh: Mesh, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """Evolve a 3-torus volume sharded over ``mesh`` for ``steps`` gens.

    Placement/copy contract matches the 2-D engines: the caller's array is
    never consumed by the donated buffer.
    """
    validate_geometry3d(vol.shape, mesh)
    return compiled_evolve3d(mesh, steps, rule)(
        place_private(vol, volume_sharding(mesh))
    )


def validate_geometry3d_packed(shape, mesh: Mesh) -> None:
    """Packed sharding additionally needs whole words per x-shard."""
    from gol_tpu.ops import bitlife

    validate_geometry3d(shape, mesh)
    cols = mesh.shape.get(COLS, 1)
    if (shape[2] // cols) % bitlife.BITS != 0:
        raise ValueError(
            f"bit-packed 3-D engine needs shard width divisible by "
            f"{bitlife.BITS}; volume width {shape[2]} over {cols} mesh cols "
            f"gives shard width {shape[2] // cols}"
        )


@functools.lru_cache(maxsize=64)
def compiled_evolve3d_packed(
    mesh: Mesh, steps: int, rule: Rule3D, halo_depth: int = 1,
    mode: str = "explicit",
):
    """Packed sharded 3-D evolve: word halos over three ppermute phases.

    Same program shape as :func:`compiled_evolve3d` but on 32-cell packed
    words — 8× less halo wire on the plane/row faces, word-quantum ghost
    columns along x.  ``halo_depth=k`` is temporal blocking exactly as in
    :func:`gol_tpu.parallel.packed.compiled_evolve_packed`: one 6-ppermute
    exchange per k generations.  ``mode`` picks the chunk loop
    (:data:`gol_tpu.parallel.halo.LOCAL_LOOPS`): "explicit" serial
    chunks, "overlap" the depth-k interior/boundary split (the interior
    volume reads no exchanged shell), or "pipeline" the cross-chunk
    double buffer — the next chunk's three-phase ghost shell ships from
    the current chunk's boundary slabs while its interior computes.  All
    three are pinned bit-identical.
    """
    from gol_tpu.ops import bitlife3d

    if mode not in LOCAL_LOOPS:
        raise ValueError(
            f"unknown 3-D ring mode {mode!r}; expected one of "
            f"{tuple(LOCAL_LOOPS)}"
        )
    local = LOCAL_LOOPS[mode](
        lambda ext: bitlife3d.step3d_packed_halo_full(ext, rule),
        _phases(mesh),
        steps,
        halo_depth,
        pack=bitlife3d.pack3d,
        unpack=bitlife3d.unpack3d,
    )
    spec = P(PLANES, ROWS, COLS)
    local_sharded = compat.shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec
    )
    return jax.jit(local_sharded, donate_argnums=0)


def evolve_sharded3d_packed(
    vol: jax.Array,
    steps: int,
    mesh: Mesh,
    rule: Rule3D = BAYS_4555,
    halo_depth: int = 1,
    mode: str = "explicit",
) -> jax.Array:
    """Packed-engine counterpart of :func:`evolve_sharded3d`."""
    validate_geometry3d_packed(vol.shape, mesh)
    return compiled_evolve3d_packed(mesh, steps, rule, halo_depth, mode)(
        place_private(vol, volume_sharding(mesh))
    )


def kernel_plan3d(
    band_extent: int, nw: int, lane_extent: int, pad: int, ghosted: bool
):
    """Which fused kernel :func:`compiled_evolve3d_pallas` dispatches for
    one shard, and at what tile — factored out of the engine so tests can
    assert the dispatch choice directly (the Hypothesis kernel-matrix
    sweep uses it to prove it reaches the ghosted rolling regime).

    Dispatch is by halo-recompute score
    (:func:`gol_tpu.ops.pallas_bitlife3d.recompute_score`, the
    shrinking-window per-generation mean), exactly like the single-device
    evolve3d.  On x-unsharded meshes (``ghosted=False``) the rolling
    kernel carries NO word ghosts at all (the shard's local x wrap is the
    torus); on x-sharded meshes its ghost-word form pays only
    ``(nw+2)/nw`` — the two ghost columns ride a separate
    8-sublane-aligned operand, sidestepping Mosaic's tiled-HBM slicing
    constraint — vs the wt kernel's ``(tw+2)/tw`` at its VMEM-bound
    ``tw``.  wt remains the fallback where the rolling window cannot fit.

    Returns ``("roll_g" | "roll", tile)`` or ``("wt", (tile_d, tile_w))``;
    raises when no fused window fits scoped VMEM.
    """
    from gol_tpu.ops import pallas_bitlife3d

    wt = pallas_bitlife3d.pick_tile3d_wt(band_extent, nw, lane_extent, pad)
    if wt is not None and wt[0] < pad:
        # The kernels need tile >= pad (the window shrink must stay
        # inside one tile's halo); the pickers optimize recompute under
        # the VMEM budget and can return smaller — such a candidate is
        # infeasible here, not merely worse.
        wt = None
    budget_words = nw + pallas_bitlife3d.GHOST_SLOTS if ghosted else nw
    roll_tile = (
        pallas_bitlife3d.pick_tile3d_roll(
            band_extent, budget_words, lane_extent, pad
        )
        if band_extent % 8 == 0
        else 0
    )
    if roll_tile < pad:
        roll_tile = 0
    if wt is None and not roll_tile:
        raise ValueError(
            f"no fused kernel window fits scoped VMEM for a shard with "
            f"banded extent {band_extent}, {nw} packed words, lane extent "
            f"{lane_extent} at band depth {pad}"
        )
    use_roll = roll_tile and (
        wt is None
        or pallas_bitlife3d.recompute_score(
            roll_tile, nw if ghosted else 0, pad
        )
        < pallas_bitlife3d.recompute_score(wt[0], wt[1], pad)
    )
    if use_roll:
        return ("roll_g" if ghosted else "roll", roll_tile)
    return ("wt", wt)


@functools.lru_cache(maxsize=64)
def compiled_evolve3d_pallas(
    mesh: Mesh, steps: int, rule: Rule3D = BAYS_4555, halo_depth: int = 8
):
    """Sharded 3-D evolve running the fused word-tiled Pallas kernel per
    shard — config 5's fastest kernel composed with its decomposition
    (VERDICT r2 #2).

    Per chunk, a two-phase ring exchange mirrors the 2-D flagship's
    corner handling one dimension up: (1) a ``halo_depth``-deep ghost
    *plane* band rides the PLANES ring; (2) one ghost word *column* per
    side of the already plane-extended volume rides the COLS ring, so the
    x/d corner words make two hops.  The extended volume feeds whichever
    fused kernel scores the lower halo recompute — the rolling-plane
    forms (:func:`gol_tpu.ops.pallas_bitlife3d.
    multi_step_pallas_packed3d_roll_ext` on x-unsharded meshes, its
    ghost-word sibling ``..._roll_ext_g`` on x-sharded ones; usual
    winners, r4: the one-window VMEM model fits plane tiles the others
    cannot and the word tax is at most (nw+2)/nw) or the word-tiled
    fallback (:func:`gol_tpu.ops.pallas_bitlife3d.
    multi_step_pallas_packed3d_wt_ext`) — the same kernels the
    single-device path runs, whose zero-filled outer-ghost light cones
    already support exactly this 1-word x halo for k <= 32 generations.

    **Mesh constraint**: at least one of the PLANES/ROWS axes must have
    size 1.  The kernel's two non-word spatial axes are geometrically
    interchangeable: its *sublane* axis carries the exchanged band
    (slices, shrink-per-generation) and its *lane* axis wraps with a
    local roll — so the lane axis must be the volume axis the mesh does
    NOT shard.  ``rows == 1`` runs the natural ``[nw, D, H]`` layout
    (band over the PLANES ring, lanes = H); ``planes == 1`` transposes
    to ``[nw, H, D]`` (band over the ROWS ring, lanes = D).  Meshes
    sharding *both* D and H (e.g. (2,2,2)) are rejected — every device
    count factors as (P,1,C) or (1,R,C) instead.  A non-multiple-of-
    ``halo_depth`` remainder of ``steps`` runs on the XLA packed step.
    """
    from gol_tpu.ops import bitlife, bitlife3d, pallas_bitlife3d
    from gol_tpu.parallel.halo import ring

    num_planes = mesh.shape.get(PLANES, 1)
    num_rows = mesh.shape.get(ROWS, 1)
    num_cols = mesh.shape.get(COLS, 1)
    if num_planes != 1 and num_rows != 1:
        raise ValueError(
            "the sharded 3-D Pallas engine needs an H-unsharded or "
            "D-unsharded mesh (planes or rows axis of size 1): the "
            "kernel's lane wrap is a local roll, true only when the "
            f"shard owns that full axis; got mesh {dict(mesh.shape)} — "
            "factor the devices as (P,1,C) or (1,R,C) instead. The "
            "relabeling is free: measured at equal shard volumes and "
            "lane extents (8-device CPU mesh, r4), the (1,R,C) "
            "transposed layout runs at per-chunk parity with (P,1,C) — "
            "only a one-time pack/unpack transpose differs, amortized "
            "over the run (step-scaling ratio 1.87x at 16 steps -> "
            "1.07x at 32) — so no device count loses a decomposition"
        )
    # Band rides whichever of the two spatial axes the mesh shards; the
    # other becomes the kernel's lane axis.
    band_over_planes = num_rows == 1
    band_axis_name = PLANES if band_over_planes else ROWS
    band_ring = num_planes if band_over_planes else num_rows
    if halo_depth < 8 or halo_depth % 8:
        raise ValueError(
            f"the sharded 3-D Pallas engine needs halo_depth to be a "
            f"multiple of 8 (DMA plane alignment), got {halo_depth}"
        )
    from gol_tpu.ops.bitlife import BITS

    if halo_depth > BITS:
        raise ValueError(
            f"the sharded 3-D Pallas engine ships one ghost word column "
            f"whose bit light cone supports halo_depth <= {BITS}, got "
            f"{halo_depth}"
        )
    pad = halo_depth
    full, rem = divmod(steps, halo_depth)
    phases = _phases(mesh)

    def chunk(pw, tile_d, tile_w):
        # Two-phase exchange; x ghost words sliced from the already
        # band-extended array carry the x/band corner data for free.
        # ``pw``'s middle axis is whichever spatial axis the mesh shards
        # (D in the natural layout, H in the transposed one).
        top = lax.ppermute(pw[:, -pad:], band_axis_name, ring(band_ring, 1))
        bot = lax.ppermute(pw[:, :pad], band_axis_name, ring(band_ring, -1))
        ext_d = jnp.concatenate([top, pw, bot], axis=1)
        left = lax.ppermute(ext_d[-1:], COLS, ring(num_cols, 1))
        right = lax.ppermute(ext_d[:1], COLS, ring(num_cols, -1))
        ext = jnp.concatenate([left, ext_d, right], axis=0)
        return pallas_bitlife3d.multi_step_pallas_packed3d_wt_ext(
            ext, tile_d, tile_w, halo_depth, rule
        )

    def chunk_roll(pp, tile):
        # Band exchange only, in the rolling kernel's plane-leading
        # layout [band, nw, lanes]: this path runs exclusively on
        # x-unsharded meshes (the dispatch below), where the shard's
        # local x wrap IS the torus — no ghost word columns.  (A
        # word-extended variant was a measured dead end: nw + 2 on the
        # sublane axis is an unaligned tiled-HBM extent Mosaic cannot
        # slice — r4.)
        top = lax.ppermute(pp[-pad:], band_axis_name, ring(band_ring, 1))
        bot = lax.ppermute(pp[:pad], band_axis_name, ring(band_ring, -1))
        ext = jnp.concatenate([top, pp, bot], axis=0)
        return pallas_bitlife3d.multi_step_pallas_packed3d_roll_ext(
            ext, tile, halo_depth, rule
        )

    def chunk_roll_g(pp, tile):
        # x-sharded rolling form (r4): same band exchange, plus one ghost
        # word column per side riding the COLS ring as a separate
        # 8-sublane-aligned operand (slots 0/1 real; the corner words
        # ride the second hop because the columns are sliced from the
        # already band-extended array, exactly like chunk()).
        top = lax.ppermute(pp[-pad:], band_axis_name, ring(band_ring, 1))
        bot = lax.ppermute(pp[:pad], band_axis_name, ring(band_ring, -1))
        ext = jnp.concatenate([top, pp, bot], axis=0)
        left = lax.ppermute(ext[:, -1:], COLS, ring(num_cols, 1))
        right = lax.ppermute(ext[:, :1], COLS, ring(num_cols, -1))
        zeros = jnp.zeros(
            (
                ext.shape[0],
                pallas_bitlife3d.GHOST_SLOTS - 2,
                ext.shape[2],
            ),
            ext.dtype,
        )
        ghosts = jnp.concatenate([left, right, zeros], axis=1)
        return pallas_bitlife3d.multi_step_pallas_packed3d_roll_ext_g(
            ext, ghosts, tile, halo_depth, rule
        )

    def local(vol):
        d, h, w = vol.shape  # per-shard block (static under shard_map)
        nw = w // bitlife.BITS
        # Kernel-axis mapping: band = the sharded spatial axis, lanes =
        # the unsharded one (see the mesh-constraint note above).
        band_extent, lane_extent = (d, h) if band_over_planes else (h, d)
        if jax.default_backend() == "tpu" and lane_extent % 128:
            raise ValueError(
                "the sharded 3-D Pallas engine needs the unsharded "
                f"{'H' if band_over_planes else 'D'} axis to fill whole "
                f"128-lane tiles on TPU, got {lane_extent}"
            )
        if band_extent < pad:
            raise ValueError(
                f"shard extent {band_extent} on the banded axis < "
                f"exchanged band {pad}: the ghost band would need layers "
                "from beyond the ring neighbor"
            )
        # Kernel dispatch by halo-recompute score (see kernel_plan3d —
        # module-level so tests can assert the choice directly).
        ghosted = num_cols > 1
        kind, tile_info = kernel_plan3d(
            band_extent, nw, lane_extent, pad, ghosted
        )
        use_roll = kind != "wt"
        roll_tile = tile_info if use_roll else 0
        wt = None if use_roll else tile_info
        packed3 = lax.bitcast_convert_type(
            bitlife3d.pack3d(vol), jnp.int32
        )  # [d, h, nw]
        if use_roll:
            # Plane-leading: [band, nw, lanes].
            packed = packed3.transpose(
                (0, 2, 1) if band_over_planes else (1, 2, 0)
            )
            roll_body = chunk_roll_g if ghosted else chunk_roll
            if full:
                packed = lax.fori_loop(
                    0, full, lambda _, p: roll_body(p, roll_tile), packed
                )
            p3 = lax.bitcast_convert_type(
                packed.transpose(
                    (0, 2, 1) if band_over_planes else (2, 0, 1)
                ),
                jnp.uint32,
            )
        else:
            tile_d, tile_w = wt
            # Natural: [nw, d, h] (band=d, lanes=h); transposed: [nw, h, d].
            packed = packed3.transpose(
                (2, 0, 1) if band_over_planes else (2, 1, 0)
            )
            if full:
                packed = lax.fori_loop(
                    0, full, lambda _, p: chunk(p, tile_d, tile_w), packed
                )
            p3 = lax.bitcast_convert_type(
                packed.transpose(
                    (1, 2, 0) if band_over_planes else (2, 1, 0)
                ),
                jnp.uint32,
            )
        if rem:
            # Leftover generations on the XLA packed step, one exchange
            # each: a depth-rem blocked exchange would ship rem ghost
            # *words* along x, which narrow (few-word) shards can't
            # source from one ring neighbor; rem < halo_depth <= 32, so
            # the per-step ppermute cost is bounded and tiny.
            for _ in range(rem):
                p3 = bitlife3d.step3d_packed_halo_full(
                    halo_extend(p3, phases, depth=1), rule
                )
        return bitlife3d.unpack3d(p3)

    spec = P(PLANES, ROWS, COLS)
    # check_vma=False: pallas_call's out ShapeDtypeStruct carries no
    # varying-mesh-axes annotation (same note as the 2-D flagship).
    local_sharded = compat.shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec, check_vma=False
    )
    return jax.jit(local_sharded, donate_argnums=0)


def evolve_sharded3d_pallas(
    vol: jax.Array,
    steps: int,
    mesh: Mesh,
    rule: Rule3D = BAYS_4555,
    halo_depth: int = 8,
) -> jax.Array:
    """Fused-kernel counterpart of :func:`evolve_sharded3d`."""
    validate_geometry3d_packed(vol.shape, mesh)
    return compiled_evolve3d_pallas(mesh, steps, rule, halo_depth)(
        place_private(vol, volume_sharding(mesh))
    )
