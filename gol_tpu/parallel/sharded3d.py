"""Sharded 3-D Life: volume decomposition with three-phase halo rings.

BASELINE.md config 5 (stretch): the 26-neighbor stencil over a
``(planes, rows, cols)`` device mesh.  Each step halo-extends the shard by
one ghost shell via :func:`gol_tpu.parallel.halo.halo_extend` — three
ppermute phases whose later phases ship slices of the already-extended
block, so the 12 edge and 8 corner regions of the 3-D decomposition land
without diagonal messages (6 ppermutes total; an MPI code would need up to
26 point-to-point messages per shard, cf. the reference's 4 for 1-D,
gol-main.c:97-107).

Mesh axes of size 1 degenerate to the local torus wrap (see halo.py), so
the same compiled program shape covers every decomposition from fully
local (1×1×1) to fully sharded.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu.ops.life3d import BAYS_4555, Rule3D, step3d_halo_full
from gol_tpu.parallel.halo import halo_extend
from gol_tpu.parallel.mesh import COLS, PLANES, ROWS, place_private


def volume_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical volume sharding: (planes, rows, cols) split over the mesh."""
    return NamedSharding(mesh, P(PLANES, ROWS, COLS))


def validate_geometry3d(shape, mesh: Mesh) -> None:
    for dim, name in zip(shape, (PLANES, ROWS, COLS)):
        n = mesh.shape.get(name, 1)
        if dim % n:
            raise ValueError(
                f"volume axis {name} of size {dim} not divisible by its "
                f"mesh axis of size {n}"
            )


@functools.lru_cache(maxsize=64)
def compiled_evolve3d(mesh: Mesh, steps: int, rule: Rule3D):
    """Build + jit the sharded 3-D evolve for (mesh, steps, rule).

    The whole generation loop runs inside one program; the input volume
    buffer is donated (the double buffer).
    """
    phases = tuple(
        (axis, name, mesh.shape.get(name, 1))
        for axis, name in enumerate((PLANES, ROWS, COLS))
    )

    def body(_, vol):
        return step3d_halo_full(halo_extend(vol, phases), rule)

    spec = P(PLANES, ROWS, COLS)
    local = jax.shard_map(
        lambda v: lax.fori_loop(0, steps, body, v),
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
    )
    return jax.jit(local, donate_argnums=0)


def evolve_sharded3d(
    vol: jax.Array, steps: int, mesh: Mesh, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """Evolve a 3-torus volume sharded over ``mesh`` for ``steps`` gens.

    Placement/copy contract matches the 2-D engines: the caller's array is
    never consumed by the donated buffer.
    """
    validate_geometry3d(vol.shape, mesh)
    return compiled_evolve3d(mesh, steps, rule)(
        place_private(vol, volume_sharding(mesh))
    )
