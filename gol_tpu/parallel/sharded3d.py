"""Sharded 3-D Life: volume decomposition with three-phase halo rings.

BASELINE.md config 5 (stretch): the 26-neighbor stencil over a
``(planes, rows, cols)`` device mesh.  Each step halo-extends the shard by
one ghost shell via :func:`gol_tpu.parallel.halo.halo_extend` — three
ppermute phases whose later phases ship slices of the already-extended
block, so the 12 edge and 8 corner regions of the 3-D decomposition land
without diagonal messages (6 ppermutes total; an MPI code would need up to
26 point-to-point messages per shard, cf. the reference's 4 for 1-D,
gol-main.c:97-107).

Mesh axes of size 1 degenerate to the local torus wrap (see halo.py), so
the same compiled program shape covers every decomposition from fully
local (1×1×1) to fully sharded.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu.ops.life3d import BAYS_4555, Rule3D, step3d_halo_full
from gol_tpu.parallel.halo import blocked_local_loop, halo_extend
from gol_tpu.parallel.mesh import COLS, PLANES, ROWS, place_private


def _phases(mesh: Mesh):
    """(array_axis, mesh_axis, ring_size) per volume axis, in phase order."""
    return tuple(
        (axis, name, mesh.shape.get(name, 1))
        for axis, name in enumerate((PLANES, ROWS, COLS))
    )


def volume_sharding(mesh: Mesh) -> NamedSharding:
    """Canonical volume sharding: (planes, rows, cols) split over the mesh."""
    return NamedSharding(mesh, P(PLANES, ROWS, COLS))


def validate_geometry3d(shape, mesh: Mesh) -> None:
    for dim, name in zip(shape, (PLANES, ROWS, COLS)):
        n = mesh.shape.get(name, 1)
        if dim % n:
            raise ValueError(
                f"volume axis {name} of size {dim} not divisible by its "
                f"mesh axis of size {n}"
            )


@functools.lru_cache(maxsize=64)
def compiled_evolve3d(mesh: Mesh, steps: int, rule: Rule3D):
    """Build + jit the sharded 3-D evolve for (mesh, steps, rule).

    The whole generation loop runs inside one program; the input volume
    buffer is donated (the double buffer).
    """
    phases = _phases(mesh)

    def body(_, vol):
        return step3d_halo_full(halo_extend(vol, phases), rule)

    spec = P(PLANES, ROWS, COLS)
    local = jax.shard_map(
        lambda v: lax.fori_loop(0, steps, body, v),
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
    )
    return jax.jit(local, donate_argnums=0)


def evolve_sharded3d(
    vol: jax.Array, steps: int, mesh: Mesh, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """Evolve a 3-torus volume sharded over ``mesh`` for ``steps`` gens.

    Placement/copy contract matches the 2-D engines: the caller's array is
    never consumed by the donated buffer.
    """
    validate_geometry3d(vol.shape, mesh)
    return compiled_evolve3d(mesh, steps, rule)(
        place_private(vol, volume_sharding(mesh))
    )


def validate_geometry3d_packed(shape, mesh: Mesh) -> None:
    """Packed sharding additionally needs whole words per x-shard."""
    from gol_tpu.ops import bitlife

    validate_geometry3d(shape, mesh)
    cols = mesh.shape.get(COLS, 1)
    if (shape[2] // cols) % bitlife.BITS != 0:
        raise ValueError(
            f"bit-packed 3-D engine needs shard width divisible by "
            f"{bitlife.BITS}; volume width {shape[2]} over {cols} mesh cols "
            f"gives shard width {shape[2] // cols}"
        )


@functools.lru_cache(maxsize=64)
def compiled_evolve3d_packed(
    mesh: Mesh, steps: int, rule: Rule3D, halo_depth: int = 1
):
    """Packed sharded 3-D evolve: word halos over three ppermute phases.

    Same program shape as :func:`compiled_evolve3d` but on 32-cell packed
    words — 8× less halo wire on the plane/row faces, word-quantum ghost
    columns along x.  ``halo_depth=k`` is temporal blocking exactly as in
    :func:`gol_tpu.parallel.packed.compiled_evolve_packed`: one 6-ppermute
    exchange per k generations.
    """
    from gol_tpu.ops import bitlife3d

    local = blocked_local_loop(
        lambda ext: bitlife3d.step3d_packed_halo_full(ext, rule),
        _phases(mesh),
        steps,
        halo_depth,
        pack=bitlife3d.pack3d,
        unpack=bitlife3d.unpack3d,
    )
    spec = P(PLANES, ROWS, COLS)
    local_sharded = jax.shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec
    )
    return jax.jit(local_sharded, donate_argnums=0)


def evolve_sharded3d_packed(
    vol: jax.Array,
    steps: int,
    mesh: Mesh,
    rule: Rule3D = BAYS_4555,
    halo_depth: int = 1,
) -> jax.Array:
    """Packed-engine counterpart of :func:`evolve_sharded3d`."""
    validate_geometry3d_packed(vol.shape, mesh)
    return compiled_evolve3d_packed(mesh, steps, rule, halo_depth)(
        place_private(vol, volume_sharding(mesh))
    )
