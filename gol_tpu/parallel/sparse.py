"""Sharded activity-gated engine: mask exchange across the mesh ring.

The mesh form of :mod:`gol_tpu.sparse.engine`.  Each shard owns its
block of the board *and* the matching block of the changed-tile mask
(same ``P(rows[, cols])`` sharding, one mask cell per tile).  Per
generation, inside one ``shard_map`` program:

1. **exchange** — the board ships its one-cell halo ring and the mask
   its one-*tile* halo ring over the same ppermute phases
   (:func:`gol_tpu.parallel.halo.halo_extend`; on a 1-D mesh the width
   axis wraps locally).  The mask exchange is the seam-correctness
   move: a glider leaving shard r's edge tile sets that tile's changed
   bit, the ppermute delivers it as shard r+1's ghost mask entry, and
   the dilation activates r+1's edge tiles *before* the glider's cells
   arrive — no live-region tile on any shard is ever skipped
   (the analysis activity matrix and the seam-crossing tests pin this).
2. **gate** — ``dilate_ext`` over the extended mask, then the same
   static-capacity worklist gather/step/scatter as the single-device
   engine (capacity is per *shard* here), with the ``lax.cond`` dense
   fallback stepping the whole extended block.
3. **byproduct mask** — changed tiles from the step's flip planes.

The activity counters psum to replicated global values (the telemetry
contract: every rank reports the same number), exactly like
:mod:`gol_tpu.parallel.stats`.  Wire cost per generation: the board
halo (unavoidable) plus ``perimeter/tile`` mask bytes — the mask ring
is ~``tile×`` smaller than the board ring it rides next to.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.ops import stencil
from gol_tpu.parallel.halo import halo_extend
from gol_tpu.parallel.mesh import COLS, ROWS
from gol_tpu.sparse import engine as sparse_engine
from gol_tpu.sparse import mask as mask_mod


def mask_sharding(mesh: Mesh):
    """The changed-mask sharding: one mask cell per tile, split like the
    board."""
    from jax.sharding import NamedSharding

    if COLS in mesh.axis_names:
        return NamedSharding(mesh, P(ROWS, COLS))
    return NamedSharding(mesh, P(ROWS, None))


def validate_activity_geometry(
    shape, mesh: Mesh, tile: int
) -> None:
    """The activity tile must divide every shard's extents (each shard
    owns whole tiles, so a mask cell never straddles a seam)."""
    h, w = shape
    rows = mesh.shape[ROWS]
    cols = mesh.shape.get(COLS, 1)
    if (h // rows) % tile or (w // cols) % tile:
        raise ValueError(
            f"activity tile {tile} must divide the shard extents "
            f"({h // rows}x{w // cols} for board {shape} on mesh "
            f"{dict(mesh.shape)})"
        )


@functools.lru_cache(maxsize=32)
def compiled_evolve_activity(
    mesh: Mesh, steps: int, tile: int, capacity: int
):
    """Build + jit the sharded activity evolver for (mesh, steps, tile,
    capacity).  The jitted call is ``fn(board, changed) -> (board,
    changed, activity)`` with replicated global activity counters;
    both inputs are donated (the double buffers).
    """
    two_d = COLS in mesh.axis_names
    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)
    phases = (
        ((0, ROWS, num_rows), (1, COLS, num_cols))
        if two_d
        else ((0, ROWS, num_rows),)
    )
    axes = tuple(mesh.axis_names)
    spec = P(ROWS, COLS) if two_d else P(ROWS, None)

    def extend(x):
        ext = halo_extend(x, phases)
        if not two_d:
            # Width is unsharded on the 1-D row mesh: the column wrap is
            # local, exactly as in the single-device engines.
            ext = jnp.pad(ext, ((0, 0), (1, 1)), mode="wrap")
        return ext

    def gen(board, changed):
        board_ext = extend(board)
        # Collectives carry the mask as bytes (bool is not a wire dtype
        # everywhere); one tiny convert per side.
        mask_ext = extend(changed.astype(jnp.uint8)).astype(jnp.bool_)
        active = mask_mod.dilate_ext(mask_ext)
        count = jnp.sum(active, dtype=jnp.uint32)
        fits = count <= jnp.uint32(capacity)

        def worklist(b):
            coords = jnp.nonzero(active, size=capacity, fill_value=0)
            return sparse_engine._worklist_pass(
                board_ext, b, changed.shape, coords, tile, tile,
                stencil.step_halo_full,
            )

        def dense_fallback(b):
            new = stencil.step_halo_full(board_ext)
            return new, mask_mod.changed_tiles_dense(b, new, tile)

        board, changed = lax.cond(fits, worklist, dense_fallback, board)
        return board, changed, count, ~fits

    def local(board, changed):
        zero = jnp.uint32(0)
        shard_tiles = jnp.uint32(
            (board.shape[0] // tile) * (board.shape[1] // tile)
        )

        def body(_, carry):
            board, changed, agens, cgens, fgens = carry
            board, changed, count, fell = gen(board, changed)
            computed = jnp.where(fell, shard_tiles, count)
            return (
                board,
                changed,
                agens + count,
                cgens + computed,
                fgens + fell.astype(jnp.uint32),
            )

        board, changed, agens, cgens, fgens = lax.fori_loop(
            0, steps, body, (board, changed, zero, zero, zero)
        )
        # Replicated global counters, like gol_tpu.parallel.stats:
        # active/computed tile-gens sum over shards; fallback counts
        # shard-gens that overflowed (each shard gates independently).
        return board, changed, {
            "active_tile_gens": lax.psum(agens, axes),
            "computed_tile_gens": lax.psum(cgens, axes),
            "fallback_gens": lax.psum(fgens, axes),
        }

    shmapped = compat.shard_map(
        local,
        mesh=mesh,
        in_specs=(spec, spec),
        out_specs=(
            spec,
            spec,
            {
                "active_tile_gens": P(),
                "computed_tile_gens": P(),
                "fallback_gens": P(),
            },
        ),
    )
    return jax.jit(shmapped, donate_argnums=(0, 1))
