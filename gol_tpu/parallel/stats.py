"""Global chunk statistics over a device mesh: shard-local reduce + psum.

The sharded engines never materialize the global board anywhere (the
no-gather discipline of the checkpoint and telemetry formats), so
"population of the world" has to be computed the same way the world is
computed: each shard reduces its own block
(:mod:`gol_tpu.ops.stats`) and a ``lax.psum`` over every mesh axis turns
the shard partials into the global value — replicated, so **every rank
of a multi-host run reports the identical number** with no extra
communication (the property the cross-rank population watchdog in
``summarize`` then verifies for free).

Face bands need one extra step: the global top band lives only on the
shards in mesh row 0, so each shard's face contribution is gated by its
``lax.axis_index`` before the psum (a 1-D row mesh leaves the width
unsharded — every shard holds a piece of the global left/right bands and
contributes unconditionally).

The psum pairs are :func:`gol_tpu.ops.stats.sum_pair` split
accumulators; summing pairs across R shards keeps the exactness bound of
the single-shard case (the per-shard hi/lo are already collapsed, so the
psum adds R words ≤ 2¹⁶ apart from the documented 65536-row bound).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.parallel.mesh import COLS, ROWS


def global_stats_fn(mesh: Mesh, local_stats, band: int):
    """``fn(prev, new) -> stats`` with globally-psummed split accumulators.

    ``local_stats(prev, new, band)`` is one of the shard-local reducers
    in :mod:`gol_tpu.ops.stats` (dense or popcount, matching the engine
    tier).  Inputs carry the canonical board sharding; outputs are
    replicated ``uint32[2]`` pairs.
    """
    two_d = COLS in mesh.axis_names
    axes = tuple(mesh.axis_names)
    spec = P(ROWS, COLS) if two_d else P(ROWS, None)

    def shardwise(prev, new):
        s = local_stats(prev, new, band)
        r = lax.axis_index(ROWS)
        gates = {
            "face_top": r == 0,
            "face_bottom": r == mesh.shape[ROWS] - 1,
        }
        if two_d:
            c = lax.axis_index(COLS)
            gates["face_left"] = c == 0
            gates["face_right"] = c == mesh.shape[COLS] - 1
        out = {}
        for name, pair in s.items():
            gate = gates.get(name)
            if gate is not None:
                pair = jnp.where(gate, pair, jnp.zeros_like(pair))
            out[name] = lax.psum(pair, axes)
        return out

    return compat.shard_map(
        shardwise, mesh=mesh, in_specs=(spec, spec), out_specs=P()
    )
