"""Device-mesh construction: the TPU-native replacement for MPI topology.

The reference's topology layer is ``MPI_Init``/``Comm_rank``/``Comm_size``
(gol-main.c:58-62) plus mod-ring neighbor ids (gol-main.c:86-87) and a
rank→GPU binding ``cudaSetDevice(myRank % deviceCount)``
(gol-with-cuda.cu:296).  On TPU none of that is explicit: a
``jax.sharding.Mesh`` names the axes, ``shard_map`` places the per-shard
program, and ring neighborhoods are expressed as ``lax.ppermute``
permutations over the mesh axis — XLA routes them over ICI (and pjit over
DCN for multi-slice).

Axis conventions:
  - 1-D row decomposition: ``('rows',)`` — the reference's own layout
    (each rank owns a horizontal stripe).
  - 2-D block decomposition: ``('rows', 'cols')`` — BASELINE.md config 3.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

ROWS = "rows"
COLS = "cols"
PLANES = "planes"  # leading axis of the 3-D Life volume decomposition


def make_mesh_1d(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """Ring of devices over the row axis."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (ROWS,))


def make_mesh_2d(
    shape: Optional[Tuple[int, int]] = None, devices=None
) -> Mesh:
    """Grid of devices over (rows, cols).

    Without an explicit shape, picks the most square factorization of the
    device count (halo bytes scale with the shard perimeter, so squarer is
    cheaper).
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        r = int(np.sqrt(n))
        while n % r:
            r -= 1
        shape = (r, n // r)
    rows, cols = shape
    if rows * cols != len(devices):
        raise ValueError(f"mesh shape {shape} != device count {len(devices)}")
    return Mesh(np.asarray(devices).reshape(rows, cols), (ROWS, COLS))


def make_mesh_3d(
    shape: Optional[Tuple[int, int, int]] = None, devices=None
) -> Mesh:
    """Grid of devices over (planes, rows, cols) for 3-D Life volumes.

    Axes may have size 1 (unsharded volume axes use size-1 halo rings, which
    degenerate to the local torus wrap).  Without an explicit shape, picks
    the most cube-like factorization of the device count.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        p = int(round(n ** (1 / 3)))
        while p > 1 and n % p:
            p -= 1
        rest = n // p
        r = int(np.sqrt(rest))
        while rest % r:
            r -= 1
        shape = (p, r, rest // r)
    planes, rows, cols = shape
    if planes * rows * cols != n:
        raise ValueError(f"mesh shape {shape} != device count {n}")
    return Mesh(
        np.asarray(devices).reshape(planes, rows, cols), (PLANES, ROWS, COLS)
    )


def board_sharding(mesh: Mesh) -> NamedSharding:
    """The canonical board sharding for a mesh: rows (and cols) split."""
    if COLS in mesh.axis_names:
        return NamedSharding(mesh, PartitionSpec(ROWS, COLS))
    return NamedSharding(mesh, PartitionSpec(ROWS, None))


def shard_board(board, mesh: Mesh):
    """Place a board onto the mesh with the canonical sharding.

    Works on multi-host meshes too: when the mesh spans devices this process
    cannot address, each host contributes its local shards from its (full)
    host copy of the board via ``make_array_from_callback`` — the standard
    multi-process placement path (every host runs the same deterministic
    init, so the copies agree).
    """
    sharding = board_sharding(mesh)
    current = getattr(board, "sharding", None)
    if current is not None and sharding.is_equivalent_to(current, board.ndim):
        # Already placed (e.g. a sharded-checkpoint resume assembled the
        # global array directly); np.asarray below would gather — or fail
        # outright on a non-fully-addressable multi-host array.
        return board
    if any(d.process_index != jax.process_index() for d in mesh.devices.flat):
        board_np = np.asarray(board)
        return jax.make_array_from_callback(
            board_np.shape, sharding, lambda idx: board_np[idx]
        )
    return jax.device_put(board, sharding)


def place_private(arr, sharding: NamedSharding):
    """Place ``arr`` with ``sharding`` in a buffer safe to donate.

    The sharded evolvers donate their input (the framework's double
    buffer), so the caller's array must never be the donated buffer: when
    ``device_put`` would be a no-op (equivalent-sharding fast path, which
    aliases), hand the evolver a private copy instead.
    """
    import jax.numpy as jnp

    current = getattr(arr, "sharding", None)
    if current is not None and sharding.is_equivalent_to(current, arr.ndim):
        return jnp.array(arr, copy=True)
    return jax.device_put(arr, sharding)


def validate_geometry(shape: Sequence[int], mesh: Mesh) -> None:
    h, w = shape
    rows = mesh.shape[ROWS]
    cols = mesh.shape.get(COLS, 1)
    if h % rows:
        raise ValueError(f"board height {h} not divisible by mesh rows {rows}")
    if w % cols:
        raise ValueError(f"board width {w} not divisible by mesh cols {cols}")
    if h // rows < 1 or w // cols < 1:
        raise ValueError(f"empty shards for board {shape} on mesh {mesh.shape}")
