"""Mask-gated Pallas grid: early-out row-band programs.

The gated-grid alternative to the worklist engine
(:mod:`gol_tpu.sparse.engine`): the grid still covers the whole packed
board in row bands, but the per-band activity gate rides in as a
**scalar-prefetch operand** (SMEM, available before the body runs) and
an inactive band's program early-outs under ``pl.when`` — it copies its
input block to the output instead of running the ~22-op carry-save
adder tree.  Work skipped is the VPU compute; the band's HBM round trip
still happens (the BlockSpec machinery DMAs every block), which is the
structural tradeoff against the worklist form:

- **worklist** (the runtime's form): O(active) gather/scatter traffic
  *and* compute, but per-generation ``nonzero`` + scatter indexing
  overhead and a static capacity with a dense fallback;
- **gated grid** (this form): O(area) traffic at O(active) compute, no
  capacity cliff, no indexing overhead — the right shape when the
  kernel is VPU-bound (the fused tier is, see ops/pallas_bitlife.py) and
  activity is moderately dense.

Gating granularity is one row *band* of tiles (= ``tile`` board rows):
band i is live iff any tile in mask row i is dilated-active
(:func:`gol_tpu.sparse.mask.band_mask`).  The three shifted input views
(band above / center / below, torus-wrapped block index maps) give the
kernel its ±1 ghost rows, so an active band next to an inactive one
still reads fresh neighbor rows — the same one-generation-per-call
contract as :func:`gol_tpu.ops.bitlife.step_packed`.

Like every Pallas tier, bit-identity is the contract: interpret mode
(any backend) pins this kernel against the jnp packed step in
tests/test_sparse.py; on TPU the same program compiles via Mosaic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import bitlife
from gol_tpu.ops.pallas_bitlife import _ALIGN, _one_generation
from gol_tpu.sparse import mask as mask_mod


def _kernel(mask_ref, above, center, below, out_ref):
    i = pl.program_id(0)

    @pl.when(mask_ref[i] != 0)
    def _():
        band = center.shape[0]
        ext = jnp.concatenate(
            [above[band - 1 : band], center[:], below[0:1]], axis=0
        )
        out_ref[:] = _one_generation(ext)

    @pl.when(mask_ref[i] == 0)
    def _():
        out_ref[:] = center[:]


def step_gated_pallas(
    packed_i32: jax.Array, band_active: jax.Array, band: int
) -> jax.Array:
    """One gated torus generation on an int32-bitcast packed board.

    ``band_active`` is int32[H // band]; bands with a zero gate are
    copied through (exact by the dilation invariant — their mask row and
    both neighbors saw no change last generation).
    """
    height, nw = packed_i32.shape
    if band < 1 or height % band or band % _ALIGN:
        raise ValueError(
            f"gated band {band} must divide the height ({height}) and "
            f"the {_ALIGN}-row DMA alignment"
        )
    nbands = height // band
    spec = functools.partial(pl.BlockSpec, (band, nw))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nbands,),
        in_specs=[
            # Index maps under scalar prefetch receive the gate ref too.
            spec(lambda i, m: ((i + nbands - 1) % nbands, 0)),
            spec(lambda i, m: (i, 0)),
            spec(lambda i, m: ((i + 1) % nbands, 0)),
        ],
        out_specs=spec(lambda i, m: (i, 0)),
    )
    return pl.pallas_call(
        _kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(packed_i32.shape, packed_i32.dtype),
        interpret=jax.default_backend() != "tpu",
    )(band_active, packed_i32, packed_i32, packed_i32)


@functools.partial(jax.jit, static_argnums=(2, 3), donate_argnums=(0, 1))
def evolve_gated_pallas(
    board: jax.Array, changed: jax.Array, steps: int, tile: int
):
    """``steps`` gated generations, dense-in/dense-out, Pallas grid.

    Same ``(board, changed, activity)`` contract as the worklist
    engines; ``fallback_gens`` is always 0 (the gated grid has no
    capacity cliff).  Mask maintenance (dilate + per-tile flip
    reduction) runs as fused jnp over the packed words — O(area/32)
    word traffic per generation, the documented cost of this form.
    ``tile`` is word-quantized like the packed worklist's (a multiple
    of 32, so mask tiles stay square over whole words).
    """
    mask_mod.validate_tile(board.shape[0], board.shape[1], tile, packed=True)
    packed = lax.bitcast_convert_type(bitlife.pack(board), jnp.int32)

    tw = jnp.uint32(changed.shape[1])  # tiles per row band

    def body(_, carry):
        packed, changed, agens, cgens = carry
        active = mask_mod.dilate(changed)
        bands = mask_mod.band_mask(active)
        new = step_gated_pallas(packed, bands, tile)
        changed = mask_mod.tile_any_packed(packed ^ new, tile)
        agens = agens + jnp.sum(active, dtype=jnp.uint32)
        # The grid computes whole live row bands (band granularity).
        cgens = cgens + jnp.sum(bands, dtype=jnp.uint32) * tw
        return new, changed, agens, cgens

    packed, changed, agens, cgens = lax.fori_loop(
        0, steps, body, (packed, changed, jnp.uint32(0), jnp.uint32(0))
    )
    board = bitlife.unpack(lax.bitcast_convert_type(packed, jnp.uint32))
    return board, changed, {
        "active_tile_gens": agens,
        "computed_tile_gens": cgens,
        "fallback_gens": jnp.uint32(0),
    }
