"""Per-tile activity mask: lifecycle and the dilation invariant.

The board is partitioned into ``tile × tile`` cell tiles; the engine
carries a boolean **changed mask** ``C[th, tw]`` — tile (i, j) is set iff
some cell in it flipped during the *last* generation.  One generation of
the gated step is then:

1. **dilate**: ``A = dilate3x3(C)`` (torus-wrapped one-tile
   neighborhood).  Life's light cone is one cell per generation, and a
   tile plus its 8 neighbors covers every cell within ``tile`` cells of
   a changed cell, so any cell whose 3×3 neighborhood saw a change last
   generation lives in a tile of ``A``.
2. **step only A**: cells outside ``A`` had a statically-quiet
   neighborhood, and a cell whose 3×3 neighborhood did not change
   between t-1 and t has the same state at t+1 as at t — skipping them
   is exact, not approximate.
3. **byproduct mask**: the new ``C`` comes from the same flip planes
   (:func:`gol_tpu.ops.stats.flip_planes_dense` /
   :func:`~gol_tpu.ops.stats.flip_planes_packed`) the ``--stats``
   reducers consume — tiles outside ``A`` are 0 by the invariant, so
   only stepped tiles need the reduction.

Soundness (no live-region tile ever skipped) is exactly the dilation:
the analysis suite's activity matrix proves a deliberately-broken
under-dilating step diverges from the dense oracle on a moving glider
(``gol_tpu.analysis.sparsecheck``), and the Hypothesis soundness family
in tests/test_property.py checks the invariant on random soups.

At t=0 (and after any resume — the mask is not checkpointed, it is
cheaply reconstructed) the mask is **all ones**: a superset of the true
changed set is always sound, and one generation later it has collapsed
to the real activity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gol_tpu.ops import bitlife
from gol_tpu.ops import stats as ops_stats

#: Candidate tile edges for auto-selection, largest first.  Bigger tiles
#: amortize the gather/scatter indexing and keep the mask grid tiny;
#: smaller tiles track activity more precisely.  64 is the measured
#: sweet spot on both backends (see docs/SPARSE.md).
TILE_CANDIDATES = (64, 32, 16, 8, 4, 2, 1)


#: Auto-pick wants at least this many tiles per axis: a coarse mask
#: grid can't gate — one object plus its 3×3 dilation already covers
#: most of a 4×4 grid, so every generation would overflow the worklist
#: (measured on the 256² gun: a 4×4 grid falls back 64/64 generations,
#: an 8×8 grid skips 83%).
_MIN_GRID = 8


def pick_tile(height: int, width: int, packed: bool = False) -> int:
    """Largest candidate edge dividing both extents (and, packed, the
    32-cell word quantum) that still yields a ≥8×8 mask grid; when the
    board is too small for that, the smallest dividing candidate (the
    finest grid available)."""
    divisors = [
        t
        for t in TILE_CANDIDATES
        if height % t == 0
        and width % t == 0
        and (not packed or t % bitlife.BITS == 0)
    ]
    if not divisors:
        raise ValueError(
            f"no activity tile divides board {height}x{width}"
            + (" at the 32-cell packed quantum" if packed else "")
        )
    for t in divisors:  # largest first
        if height // t >= _MIN_GRID and width // t >= _MIN_GRID:
            return t
    return divisors[-1]


def validate_tile(height: int, width: int, tile: int, packed: bool) -> None:
    if tile < 1:
        raise ValueError(f"activity tile must be >= 1, got {tile}")
    if height % tile or width % tile:
        raise ValueError(
            f"activity tile {tile} must divide the board ({height}x{width})"
        )
    if packed and tile % bitlife.BITS:
        raise ValueError(
            f"packed activity tiles are word-quantized: tile {tile} must "
            f"be a multiple of {bitlife.BITS}"
        )


def grid_shape(height: int, width: int, tile: int):
    """The mask grid ``(th, tw)`` for a board."""
    return height // tile, width // tile


def full_mask(th: int, tw: int) -> jax.Array:
    """The all-active mask: the sound start/resume state."""
    return jnp.ones((th, tw), jnp.bool_)


def dilate(changed: jax.Array) -> jax.Array:
    """Torus 3×3 OR — one tile-neighborhood of dilation (separable)."""
    v = (
        changed
        | jnp.roll(changed, 1, axis=0)
        | jnp.roll(changed, -1, axis=0)
    )
    return v | jnp.roll(v, 1, axis=1) | jnp.roll(v, -1, axis=1)


def dilate_ext(changed_ext: jax.Array) -> jax.Array:
    """3×3 OR over a halo-extended mask ``[th+2, tw+2]`` → ``[th, tw]``.

    The sharded form: the one-tile halo ring (delivered by the mask
    ppermute exchange, or a local wrap pad) carries all periodicity, so
    a glider crossing a shard seam reactivates the neighbor shard's
    edge tiles through its ghost mask entries.
    """
    v = changed_ext[:-2] | changed_ext[1:-1] | changed_ext[2:]
    return v[:, :-2] | v[:, 1:-1] | v[:, 2:]


def tile_any_dense(plane: jax.Array, tile: int) -> jax.Array:
    """Per-tile any-nonzero of a cell plane ``[h, w]`` → bool ``[th, tw]``."""
    h, w = plane.shape
    return (
        plane.reshape(h // tile, tile, w // tile, tile)
        .astype(jnp.bool_)
        .any(axis=(1, 3))
    )


def tile_any_packed(words: jax.Array, tile: int) -> jax.Array:
    """Per-tile any-set-bit of a packed word plane ``[h, nw]``.

    Tile width in words is ``tile // 32`` (validated); the reduce tree
    sees words, 32× fewer elements than the dense form — the packed
    tiers' native idiom.
    """
    h, nw = words.shape
    tw_words = tile // bitlife.BITS
    return (
        words.reshape(h // tile, tile, nw // tw_words, tw_words) != 0
    ).any(axis=(1, 3))


def changed_tiles_dense(prev: jax.Array, new: jax.Array, tile: int) -> jax.Array:
    """Changed-tile mask as a byproduct of the stats flip planes."""
    flips, _, _ = ops_stats.flip_planes_dense(prev, new)
    return tile_any_dense(flips, tile)


def changed_tiles_packed(p: jax.Array, n: jax.Array, tile: int) -> jax.Array:
    """Packed counterpart (``p``/``n`` already in word layout)."""
    born, died = ops_stats.flip_planes_packed(p, n)
    return tile_any_packed(born | died, tile)


def band_mask(active: jax.Array) -> jax.Array:
    """Row-band gate for the Pallas gated-grid form: band i is live iff
    any tile in mask row i is active.  int32 (SMEM scalar-prefetch
    operands are word-sized)."""
    return active.any(axis=1).astype(jnp.int32)
