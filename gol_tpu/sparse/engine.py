"""Single-device activity-gated engines: compact worklists of live tiles.

The structural move (the dataflow-locality framing of the Cerebras and
Tenstorrent stencil papers in PAPERS.md): work follows the data that
*changes*, not the allocated array.  Per generation, entirely inside the
compiled program:

1. ``active = dilate(changed)`` (:mod:`gol_tpu.sparse.mask`).
2. ``jnp.nonzero(active, size=K)`` builds a **static-capacity worklist**
   of active tile coordinates (K is fixed at compile time — the JAX
   answer to dynamic shapes; slack entries are filled with tile (0, 0),
   whose redundant step is the identity on a quiet tile and whose
   duplicate scatter writes identical values, so padding is
   semantically free).
3. One mod-indexed gather pulls each listed tile *with its one-cell
   halo* straight from the board (the wrap costs O(K · tile) index
   arithmetic, never an O(area) pad copy); a vmapped halo-full step
   (:func:`gol_tpu.ops.stencil.step_halo_full` /
   :func:`gol_tpu.ops.bitlife.step_packed_halo_full`) advances all K
   tiles; a loop of ``dynamic_update_slice`` writes the interiors back
   in place (:func:`_scatter_tiles` — XLA's generic scatter walks
   elements and costs more than the dense step it replaces).
4. The new changed mask is scattered from per-tile flip flags — the
   byproduct of the same flip planes the ``--stats`` reducers use.

If a generation's true active count exceeds K, ``lax.cond`` runs the
plain dense step for that generation instead (both branches are traced,
one executes): the tier is **never wrong and never asymptotically worse
than the dense tier** — overflow costs one dense generation, not
correctness.  The wall-clock win is the executed branch: at <1% live
cells the worklist touches O(K · tile²) cells instead of O(H · W).

Two representations, both bit-identical to their dense oracles (pinned
by tests/test_sparse.py and the analysis activity matrix):

- **dense-jnp** — uint8 cells; the reference form and the oracle for
  the masking machinery itself;
- **bitpack** — 32 cells/word (:mod:`gol_tpu.ops.bitlife`); tiles are
  gathered as word blocks with one ghost word per side, so the gather
  moves 8× fewer bytes and the per-tile step is the carry-save adder.

The Pallas gated-grid alternative lives in :mod:`gol_tpu.sparse.pallas`;
the sharded (mesh) form in :mod:`gol_tpu.parallel.sparse`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.ops import bitlife, stencil
from gol_tpu.sparse import mask as mask_mod

#: Names of the per-chunk activity counters every activity program
#: returns (uint32 scalars, reset each chunk — a chunk's tile-gens stay
#: far below 2³² for every geometry the repo runs).  ``active`` is the
#: dilated mask population per generation; ``computed`` what actually
#: ran (= active on worklist generations, the full grid on fallback
#: generations), so ``tiles*gens - computed`` is the honest skip count.
ACTIVITY_FIELDS = ("active_tile_gens", "computed_tile_gens", "fallback_gens")


def default_capacity(th: int, tw: int, fraction: float) -> int:
    """The worklist capacity K for a mask grid: ``fraction`` of the
    tiles, at least one, never more than all of them."""
    if not 0.0 < fraction <= 1.0:
        raise ValueError(
            f"activity capacity fraction must be in (0, 1], got {fraction}"
        )
    return max(1, min(th * tw, int(np.ceil(th * tw * fraction))))


def _tile_spans(coords, tile: int, halo: int):
    """Row/col index planes of each listed tile's haloed window.

    ``coords = (r, c)`` int32[K]; returns ``rows[K, tile+2*halo]`` and
    ``cols[K, tile+2*halo]`` into an array whose origin is shifted by
    ``halo`` (the wrap/exchange padding), so no per-index mod is needed.
    """
    r, c = coords
    span = jnp.arange(tile + 2 * halo, dtype=jnp.int32)
    return r[:, None] * tile + span[None], c[:, None] * tile + span[None]


def _scatter_tiles(board, stepped, r, c, tile_h: int, tile_w: int):
    """Write K stepped tile interiors back at their grid slots.

    A ``fori_loop`` of ``dynamic_update_slice`` writes, NOT one big
    ``.at[...].set`` scatter: XLA's generic scatter walks elements
    (measured ~0.4 ms/generation on CPU for a 64-tile worklist — 2× the
    whole dense step it was supposed to skip), while contiguous DUS
    windows are memcpy-shaped and update the donated carry in place.
    Duplicate slots (the worklist's fill padding) rewrite identical
    values sequentially — deterministic by construction.
    """

    def write_one(k, b):
        return lax.dynamic_update_slice(
            b, stepped[k], (r[k] * tile_h, c[k] * tile_w)
        )

    return lax.fori_loop(0, r.shape[0], write_one, board)


def _worklist_pass_mod(board, changed_shape, coords, tile_h, tile_w, step1):
    """Gather → step → scatter one worklist of tiles, torus wrap via
    mod-indexed gathers.

    The single-device form: each listed tile's haloed window is gathered
    straight from ``board`` with per-tile mod-H/W index planes — the
    wrap costs O(K · tile) index arithmetic instead of the O(area)
    wrap-pad copy a padded gather would pay per generation (measured:
    the pad alone costs as much as the dense step it was supposed to
    skip; a tile-major blocked layout was measured too and loses to
    this form end-to-end — its fallback pays two full-board transposes
    per overflow generation).  ``step1`` maps one haloed tile
    ``[tile_h+2, tile_w+2]`` to its stepped interior.  Returns
    ``(new_board, new_changed)``.
    """
    h, w = board.shape
    r, c = coords
    span = jnp.arange(-1, max(tile_h, tile_w) + 1, dtype=jnp.int32)
    rows = (r[:, None] * tile_h + span[: tile_h + 2][None]) % h
    cols = (c[:, None] * tile_w + span[: tile_w + 2][None]) % w
    tiles = board[rows[:, :, None], cols[:, None, :]]  # [K, th+2, tw+2]
    stepped = jax.vmap(step1)(tiles)  # [K, tile_h, tile_w]
    orig = tiles[:, 1:-1, 1:-1]
    flags = jnp.any(stepped != orig, axis=(1, 2))
    new_board = _scatter_tiles(board, stepped, r, c, tile_h, tile_w)
    new_changed = (
        jnp.zeros(changed_shape, jnp.bool_).at[r, c].set(flags)
    )
    return new_board, new_changed


def _worklist_pass(ext, board, changed_shape, coords, tile_h, tile_w, step1):
    """Gather → step → scatter one worklist of tiles.

    ``ext`` is the board padded/halo-extended by one (rows) and one
    column quantum (cells dense, words packed); ``tile_h``/``tile_w``
    are the tile extents in ``board``'s own units.  ``step1`` maps one
    haloed tile ``[tile_h+2, tile_w+2]`` to its stepped interior.
    Returns ``(new_board, new_changed, flags)``.
    """
    r, c = coords
    span_r = jnp.arange(tile_h + 2, dtype=jnp.int32)
    span_c = jnp.arange(tile_w + 2, dtype=jnp.int32)
    rows = r[:, None] * tile_h + span_r[None]
    cols = c[:, None] * tile_w + span_c[None]
    tiles = ext[rows[:, :, None], cols[:, None, :]]  # [K, th+2, tw+2]
    stepped = jax.vmap(step1)(tiles)  # [K, tile_h, tile_w]
    orig = tiles[:, 1:-1, 1:-1]
    flags = jnp.any(stepped != orig, axis=(1, 2))
    new_board = _scatter_tiles(board, stepped, r, c, tile_h, tile_w)
    new_changed = (
        jnp.zeros(changed_shape, jnp.bool_).at[r, c].set(flags)
    )
    return new_board, new_changed


def _gen_dense(board, changed, tile: int, capacity: int):
    """One activity-gated dense generation.  Returns
    ``(board, changed, active_count, fell_back)``."""
    active = mask_mod.dilate(changed)
    count = jnp.sum(active, dtype=jnp.uint32)
    fits = count <= jnp.uint32(capacity)

    def worklist(b):
        coords = jnp.nonzero(active, size=capacity, fill_value=0)
        return _worklist_pass_mod(
            b, changed.shape, coords, tile, tile,
            stencil.step_halo_full,
        )

    def dense_fallback(b):
        new = stencil.step(b)
        return new, mask_mod.changed_tiles_dense(b, new, tile)

    board, changed = lax.cond(fits, worklist, dense_fallback, board)
    return board, changed, count, ~fits


def _gen_packed(packed, changed, tile: int, capacity: int):
    """One activity-gated packed generation (word-quantized tiles).

    The worklist steps its windows **transposed** — ``[K, words, rows]``
    via :func:`gol_tpu.ops.bitlife.step_packed_vext_nowrap_t` — because
    a packed tile is only ``tile/32 + 2`` words wide: in the natural
    ``[rows, words]`` layout the minor axis is a handful of words and
    the adder tree runs at a fraction of SIMD width, while transposed
    the ``tile+2``-long row axis fills the vector lanes (the same
    narrow-strip argument that motivated the transposed step for the
    2-D-mesh edge strips).  The gathered ghost *words* make the no-wrap
    step's edge-bit shrinkage irrelevant: the garbage bits live in the
    ghost words, which only ever feed carries inward — the interior
    words are exact, and the ghost rows/words are discarded.
    """
    active = mask_mod.dilate(changed)
    count = jnp.sum(active, dtype=jnp.uint32)
    fits = count <= jnp.uint32(capacity)
    tw_words = tile // bitlife.BITS

    def worklist(p):
        h, nw = p.shape
        r, c = jnp.nonzero(active, size=capacity, fill_value=0)
        span_r = jnp.arange(-1, tile + 1, dtype=jnp.int32)
        span_c = jnp.arange(-1, tw_words + 1, dtype=jnp.int32)
        rows = (r[:, None] * tile + span_r[None]) % h
        cols = (c[:, None] * tw_words + span_c[None]) % nw
        # [K, tww+2, tile+2]: words on the (short) middle axis, rows on
        # the (long) minor axis.
        tiles_t = p[rows[:, None, :], cols[:, :, None]]
        stepped_t = jax.vmap(bitlife.step_packed_vext_nowrap_t)(tiles_t)
        interior_t = stepped_t[:, 1:-1, :]  # [K, tww, tile]
        orig_t = tiles_t[:, 1:-1, 1:-1]
        flags = jnp.any(interior_t != orig_t, axis=(1, 2))
        stepped = jnp.swapaxes(interior_t, 1, 2)  # [K, tile, tww]
        new_board = _scatter_tiles(p, stepped, r, c, tile, tw_words)
        new_changed = (
            jnp.zeros(changed.shape, jnp.bool_).at[r, c].set(flags)
        )
        return new_board, new_changed

    def dense_fallback(p):
        new = bitlife.step_packed(p)
        return new, mask_mod.changed_tiles_packed(p, new, tile)

    packed, changed = lax.cond(fits, worklist, dense_fallback, packed)
    return packed, changed, count, ~fits


def _evolve_loop(rep, changed, steps: int, gen):
    zero = jnp.uint32(0)
    ntiles = jnp.uint32(changed.shape[0] * changed.shape[1])

    def body(_, carry):
        rep, changed, agens, cgens, fgens = carry
        rep, changed, count, fell = gen(rep, changed)
        computed = jnp.where(fell, ntiles, count)
        return (
            rep,
            changed,
            agens + count,
            cgens + computed,
            fgens + fell.astype(jnp.uint32),
        )

    rep, changed, agens, cgens, fgens = lax.fori_loop(
        0, steps, body, (rep, changed, zero, zero, zero)
    )
    return rep, changed, {
        "active_tile_gens": agens,
        "computed_tile_gens": cgens,
        "fallback_gens": fgens,
    }


@functools.partial(
    jax.jit, static_argnums=(2, 3, 4), donate_argnums=(0, 1)
)
def evolve_gated_dense(
    board: jax.Array,
    changed: jax.Array,
    steps: int,
    tile: int,
    capacity: int,
):
    """``steps`` gated generations, dense cells.  Returns
    ``(board, changed, activity)`` — the activity dict holds the
    :data:`ACTIVITY_FIELDS` uint32 counters for this chunk."""
    gen = functools.partial(_gen_dense, tile=tile, capacity=capacity)
    return _evolve_loop(board, changed, steps, gen)


@functools.partial(
    jax.jit, static_argnums=(2, 3, 4), donate_argnums=(0, 1)
)
def evolve_gated_packed(
    board: jax.Array,
    changed: jax.Array,
    steps: int,
    tile: int,
    capacity: int,
):
    """Dense-in/dense-out packed form: pack once, run the gated word
    worklist, unpack — the activity twin of
    :func:`gol_tpu.ops.bitlife.evolve_dense_io`."""
    packed = bitlife.pack(board)
    gen = functools.partial(_gen_packed, tile=tile, capacity=capacity)
    packed, changed, act = _evolve_loop(packed, changed, steps, gen)
    return bitlife.unpack(packed), changed, act
