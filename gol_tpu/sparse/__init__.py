"""Activity-gated sparse tier: skip the dead universe.

Every dense tier does O(area) work per generation even when 99% of the
board is static or dead — and real Life workloads (gliders, guns,
methuselahs in huge arenas) are exactly that sparse.  This package adds
the activity-tracking tier (``--engine activity``, docs/SPARSE.md):

- :mod:`gol_tpu.sparse.mask` — the per-tile changed mask lifecycle:
  changed tiles are a *byproduct* of the step's flip planes (the same
  :func:`gol_tpu.ops.stats.flip_planes_dense` /
  :func:`~gol_tpu.ops.stats.flip_planes_packed` expressions the
  ``--stats`` reducers consume), dilated one tile-neighborhood per
  generation (the light-cone invariant that makes skipping sound).
- :mod:`gol_tpu.sparse.engine` — the single-device engines: a compact
  worklist of active tiles + halos gathered/scattered inside the
  compiled program (static capacity; `lax.cond` falls back to the dense
  step when the worklist would overflow, so the tier is never wrong and
  never worse than O(area)), in dense-jnp and bit-packed forms.
- :mod:`gol_tpu.sparse.pallas` — the mask-gated grid form: a Pallas TPU
  kernel whose row-band programs early-out (``pl.when``) on the
  prefetched band mask.

The sharded form (mask ppermute exchange so a glider crossing a shard
seam reactivates the neighbor's edge tiles) lives in
:mod:`gol_tpu.parallel.sparse`; the runtime dispatch in
:class:`gol_tpu.runtime.GolRuntime`.  Every form is pinned bit-identical
to the dense tiers (tests/test_sparse.py and the analysis suite's
activity matrix).
"""

from gol_tpu.sparse import engine, mask  # noqa: F401
