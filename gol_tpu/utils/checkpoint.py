"""Checkpoint / resume — a capability addition over the reference.

The reference's only persistence is the write-only final dump
(gol-main.c:135-139); there is no loader and no mid-run snapshot (SURVEY §5).
Here a run can periodically snapshot the board + generation counter and
resume from any snapshot.  Format: a single ``.npz`` with the board, the
generation, the geometry, and — for reference-compat (stale-halo, bug B1)
runs — the frozen t=0 ghost rows, so a resumed compat run keeps the
*original* halos rather than re-freezing from the resumed board.  Portable
and readable without JAX.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import List, Optional, Tuple

import numpy as np

CKPT_SUFFIX = ".gol.npz"
# Batched multi-world snapshots (gol_tpu/batch): one archive holding every
# world of a batch run, each with its own fingerprint.  Single-file only —
# the batch runtime is single-process (its mesh spans local devices), so
# there is no sharded batch format; the kind's sharded suffix below exists
# solely so the generic kind plumbing has a never-matching value.
BCKPT_SUFFIX = ".golb.npz"
BCKPT_SHARD_DIR_SUFFIX = ".golb.shards.d"  # reserved; never written


@dataclasses.dataclass(frozen=True)
class Snapshot:
    board: np.ndarray
    generation: int
    num_ranks: int
    top0: Optional[np.ndarray] = None  # frozen halos, stale_t0 runs only
    bottom0: Optional[np.ndarray] = None
    rule: Optional[str] = None  # B/S rulestring for custom-rule runs


class CorruptSnapshotError(ValueError):
    """The snapshot's stored fingerprint does not match its board."""


def _archive_errors() -> tuple:
    """Every exception a flipped byte can surface while READING an npz.

    A single corrupted byte can land in zip structure
    (``BadZipFile``/``struct.error``), a compressed stream
    (``zlib.error``), a member header (numpy's header parse raises
    ``ValueError``/``SyntaxError``/``tokenize.TokenError`` — the chaos
    matrix's ``snapshot.bitflip`` site found the latter two for real),
    or truncate the payload (``EOFError``/``KeyError``).  All of them
    mean "this snapshot is corrupt", never a traceback.
    """
    import struct
    import tokenize
    import zipfile
    import zlib

    return (
        zipfile.BadZipFile,
        zlib.error,
        struct.error,
        tokenize.TokenError,
        SyntaxError,
        KeyError,
        ValueError,
        EOFError,
    )


def _tmp_rename_gap() -> None:
    """Chaos-drill hook: widen the window between the ``.tmp`` write and
    the atomic rename.

    The kill-9 drill (tests/test_resilience_drill.py) must land SIGKILL
    *inside* a checkpoint write to prove a torn ``.tmp`` file is never
    resumed from; real writes close that window in microseconds.  Now a
    site of the declarative fault plane
    (``{"site": "checkpoint.rename_delay", "delay_s": S}``,
    :mod:`gol_tpu.resilience.faults`); the original
    ``GOL_CKPT_TEST_WRITE_DELAY`` env var keeps working as a documented
    alias.  With neither set (production), this is a no-op.
    """
    from gol_tpu.resilience import faults

    faults.rename_gap()


def _write_fault(tmp: str, generation) -> None:
    """Fault-plane site for the snapshot ``.tmp`` write (io_error /
    torn_tmp / disk_full; no-op without an armed plan)."""
    from gol_tpu.resilience import faults

    if faults.active() is not None:
        faults.checkpoint_write_fault(tmp, int(generation))


def _post_rename_fault(path: str, generation) -> None:
    """Fault-plane site for on-disk rot of a just-renamed snapshot."""
    from gol_tpu.resilience import faults

    if faults.active() is not None:
        faults.corrupt_snapshot_file(path, int(generation))


class AsyncSnapshotWriter:
    """Background checkpoint writer: overlap file I/O with device compute.

    VERDICT r3 #6: the runtime's synchronous snapshot stalled the device
    loop for a multi-GB compressed write per checkpoint.  The split that
    makes async safe under buffer donation: the *device→host fetch*
    (``np.asarray``) stays on the caller's thread — it completes before
    the next chunk donates the device buffer — and only the *file write*
    (compression + atomic tmp+rename, which this module's save functions
    already implement) moves to the writer thread.

    Single-process only: the multi-host sharded save ends in a global
    device barrier, and collectives must never be issued from two
    threads of one process.  A bounded queue (depth 2) backpressures a
    checkpoint cadence faster than the disk instead of accumulating
    host copies; a writer failure is sticky and re-raised on the next
    ``submit``/``flush`` so a run cannot silently finish with missing
    snapshots.  Crash safety is unchanged from the sync path: the
    snapshot being written when the process dies is a ``.tmp`` file,
    never a clobbered previous snapshot.
    """

    def __init__(self, depth: int = 2):
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._err: Optional[BaseException] = None
        self._err_raised = False  # surfaced via submit/flush already?
        self._thread = threading.Thread(
            target=self._loop, name="gol-ckpt-writer", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            try:
                if item is None:
                    return
                fn, args, kwargs = item
                if self._err is None:  # don't pile writes onto a failure
                    fn(*args, **kwargs)
            except BaseException as e:  # noqa: BLE001 — surfaced at submit/flush
                # Note attached once, here — _raise_pending may re-raise
                # the same object multiple times (sticky error).
                if isinstance(e, (OSError, ValueError)) and hasattr(
                    e, "add_note"
                ):
                    e.add_note(
                        "(raised by the async checkpoint writer; the "
                        "run's snapshots are incomplete)"
                    )
                self._err = e
            finally:
                self._q.task_done()

    def _raise_pending(self) -> None:
        if self._err is not None:
            err = self._err
            self._err_raised = True
            if isinstance(err, (OSError, ValueError)):
                # Preserve the type: the CLIs' clean-exit handlers catch
                # (ValueError, OSError) — an unwritable dir or full disk
                # must print its message and exit 255 exactly as the
                # synchronous save path did, not become a traceback.
                # (The writer-thread loop attached the context note.)
                raise err
            raise RuntimeError(
                "async checkpoint writer failed; the run's snapshots are "
                "incomplete"
            ) from err

    def submit(self, fn, *args, **kwargs) -> None:
        """Queue one write (blocks only when ``depth`` writes are pending)."""
        self._raise_pending()
        self._q.put((fn, args, kwargs))

    def flush(self) -> None:
        """Wait for every queued write; re-raise any writer failure."""
        self._q.join()
        self._raise_pending()

    def close(self) -> None:
        """Drain and stop the thread (does not raise; call flush first
        when completion must be verified).

        A sticky writer failure that was never surfaced through
        ``submit``/``flush`` is *printed* to stderr here: the
        abnormal-exit paths (cli3d's ``finally``, ``run_guarded`` after a
        GuardError) call close() without a prior flush, and a failed
        mid-run snapshot — exactly what a post-crash resume needs — must
        leave a trace on the failing run's stderr rather than vanish.
        (Already-raised errors are not re-printed: the normal
        flush-then-close path reports once, via the raise.)
        """
        self._q.put(None)
        self._thread.join()
        if self._err is not None and not self._err_raised:
            import sys

            print(
                "gol: async checkpoint writer failed; the run's snapshots "
                f"are incomplete: {self._err!r}",
                file=sys.stderr,
            )


def _halo_plane(top0: np.ndarray, bottom0: np.ndarray) -> np.ndarray:
    """Canonical 2-row plane for fingerprinting the frozen halo pair
    (halos may arrive as (W,) or (1, W))."""
    return np.stack([np.ravel(top0), np.ravel(bottom0)])


def checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(directory, f"ckpt_{generation:012d}{CKPT_SUFFIX}")


def save(
    path: str,
    board: np.ndarray,
    generation: int,
    num_ranks: int,
    top0: Optional[np.ndarray] = None,
    bottom0: Optional[np.ndarray] = None,
    fingerprint: Optional[int] = None,
    rule: Optional[str] = None,
) -> str:
    """Write a snapshot atomically, stamped with a content fingerprint.

    The fingerprint (:func:`gol_tpu.utils.guard.fingerprint_np`) makes the
    file tamper-evident: :func:`load` recomputes and verifies it, so a
    corrupted snapshot fails loudly instead of silently resuming a wrong
    world (failure-detection tier 2, SURVEY §5's missing subsystem).
    Callers that already computed the board's fingerprint on device (the
    guard audit) pass it in to skip the host-side O(H·W) recompute — it is
    bit-identical to ``fingerprint_np`` by design.
    """
    from gol_tpu.utils.guard import fingerprint_np

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    board = np.asarray(board, np.uint8)
    arrays = dict(
        board=board,
        generation=np.int64(generation),
        num_ranks=np.int64(num_ranks),
        fingerprint=np.uint32(
            fingerprint_np(board) if fingerprint is None else fingerprint
        ),
    )
    if rule is not None:
        # Like the frozen halos, the rule changes the semantics of every
        # resumed generation; record it so resume can refuse a mismatch.
        arrays["rule"] = np.asarray(rule)
    if top0 is not None:
        arrays["top0"] = np.asarray(top0, np.uint8)
        arrays["bottom0"] = np.asarray(bottom0, np.uint8)
        # The frozen halos evolve the resumed world every generation, so
        # they need the same tamper evidence as the board itself.
        arrays["halo_fingerprint"] = np.uint32(
            fingerprint_np(_halo_plane(arrays["top0"], arrays["bottom0"]))
        )
    tmp = path + ".tmp.npz"
    _write_fault(tmp, generation)
    np.savez_compressed(tmp, **arrays)
    _tmp_rename_gap()
    os.replace(tmp, path)
    _post_rename_fault(path, generation)
    return path


def load(path: str) -> Snapshot:
    """Read a snapshot, verifying its fingerprint when present.

    (Snapshots written before fingerprints existed load without the check.)
    Truncated or otherwise unreadable archives raise
    :class:`CorruptSnapshotError` like a bad fingerprint does — the
    auto-resume walk treats every malformation as "skip this candidate".
    """
    try:
        data = np.load(path)
    except _archive_errors() as e:
        raise CorruptSnapshotError(
            f"{path}: not a readable snapshot archive ({e})"
        ) from e
    with data:
        try:
            return _read_snapshot(path, data)
        except CorruptSnapshotError:
            raise
        except _archive_errors() as e:
            # A flipped byte can land in zip structure, a compressed
            # stream, or a member header — all of them are "this snapshot
            # is corrupt", never a traceback.
            raise CorruptSnapshotError(
                f"{path}: snapshot archive is corrupt ({e})"
            ) from e


def _read_snapshot(path: str, data) -> Snapshot:
    board = data["board"].astype(np.uint8)
    top0 = data["top0"].astype(np.uint8) if "top0" in data else None
    bottom0 = (
        data["bottom0"].astype(np.uint8) if "bottom0" in data else None
    )
    if "fingerprint" in data:
        from gol_tpu.utils.guard import fingerprint_np

        stored = int(data["fingerprint"])
        actual = fingerprint_np(board)
        if stored != actual:
            raise CorruptSnapshotError(
                f"{path}: stored fingerprint {stored:#010x} != computed "
                f"{actual:#010x}; the snapshot is corrupt"
            )
        if "halo_fingerprint" in data:
            stored_h = int(data["halo_fingerprint"])
            actual_h = fingerprint_np(_halo_plane(top0, bottom0))
            if stored_h != actual_h:
                raise CorruptSnapshotError(
                    f"{path}: halo fingerprint {stored_h:#010x} != "
                    f"computed {actual_h:#010x}; the frozen halos are "
                    "corrupt"
                )
    return Snapshot(
        board=board,
        generation=int(data["generation"]),
        num_ranks=int(data["num_ranks"]),
        top0=top0,
        bottom0=bottom0,
        rule=str(data["rule"]) if "rule" in data else None,
    )


@dataclasses.dataclass(frozen=True)
class BatchSnapshot:
    """One batched multi-world snapshot: every world at one generation."""

    boards: List[np.ndarray]  # per-world uint8 grids, heterogeneous shapes
    generation: int


def batch_checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(directory, f"bckpt_{generation:012d}{BCKPT_SUFFIX}")


def save_batch(
    path: str,
    boards,
    generation: int,
    fingerprints=None,
) -> str:
    """Write a batched snapshot atomically: all worlds, one archive.

    Each world carries its own content fingerprint (the same
    ``fingerprint_np`` the single-world format stamps), so :func:`load_batch`
    verifies every world independently — one flipped byte corrupts the
    whole snapshot loudly, exactly like the 2-D format.  ``fingerprints``
    (device-computed, optional) skips the host-side recompute per world.
    """
    from gol_tpu.utils.guard import fingerprint_np

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    boards = [np.asarray(b, np.uint8) for b in boards]
    fps = (
        [fingerprint_np(b) for b in boards]
        if fingerprints is None
        else [int(f) for f in fingerprints]
    )
    if len(fps) != len(boards):
        raise ValueError(
            f"{len(fps)} fingerprints for {len(boards)} worlds"
        )
    arrays = dict(
        generation=np.int64(generation),
        num_worlds=np.int64(len(boards)),
        fingerprints=np.asarray(fps, np.uint32),
    )
    for i, b in enumerate(boards):
        arrays[f"world_{i:05d}"] = b
    tmp = path + ".tmp.npz"
    _write_fault(tmp, generation)
    np.savez_compressed(tmp, **arrays)
    _tmp_rename_gap()
    os.replace(tmp, path)
    _post_rename_fault(path, generation)
    return path


def load_batch(path: str) -> BatchSnapshot:
    """Read a batched snapshot, verifying every world's fingerprint.

    Any malformation — unreadable archive, missing world, fingerprint
    mismatch — raises :class:`CorruptSnapshotError`, so the validated
    auto-resume walk (``kind='batch'``) falls back past it exactly as it
    does for the single-world formats.
    """
    from gol_tpu.utils.guard import fingerprint_np

    try:
        data = np.load(path)
    except _archive_errors() as e:
        raise CorruptSnapshotError(
            f"{path}: not a readable batch snapshot archive ({e})"
        ) from e
    with data:
        try:
            n = int(data["num_worlds"])
            fps = data["fingerprints"]
            if len(fps) != n:
                raise CorruptSnapshotError(
                    f"{path}: {len(fps)} fingerprints for {n} worlds"
                )
            boards = []
            for i in range(n):
                board = data[f"world_{i:05d}"].astype(np.uint8)
                actual = fingerprint_np(board)
                if int(fps[i]) != actual:
                    raise CorruptSnapshotError(
                        f"{path}: world {i} fingerprint {actual:#010x} != "
                        f"stored {int(fps[i]):#010x}; the snapshot is "
                        "corrupt"
                    )
                boards.append(board)
            return BatchSnapshot(
                boards=boards, generation=int(data["generation"])
            )
        except CorruptSnapshotError:
            raise
        except _archive_errors() as e:
            raise CorruptSnapshotError(
                f"{path}: batch snapshot archive is corrupt ({e})"
            ) from e


def _sharded_complete(dirpath: str) -> bool:
    """True when the manifest and every shard file it references exist.

    A sharded checkpoint directory is not created atomically (each host
    lands its own file, the barrier comes after), so a crash mid-save can
    leave a torn directory; :func:`latest` must never prefer one over an
    older complete snapshot.
    """
    try:
        with np.load(os.path.join(dirpath, _MANIFEST)) as data:
            procs = set(int(p) for p in data["procs"])
    except (OSError, KeyError, ValueError):
        return False
    return all(
        os.path.exists(os.path.join(dirpath, f"shards_{p:05d}.npz"))
        for p in procs
    )


def latest(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        f
        for f in os.listdir(directory)
        if f.startswith("ckpt_")
        and (
            f.endswith(CKPT_SUFFIX)
            or (
                f.endswith(SHARD_DIR_SUFFIX)
                and _sharded_complete(os.path.join(directory, f))
            )
        )
    )
    return os.path.join(directory, ckpts[-1]) if ckpts else None


# -- 3-D volume snapshots (the cli3d driver's persistence) -------------------


CKPT3D_SUFFIX = ".gol3d.npz"


def checkpoint3d_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"ckpt3d_{generation:012d}{CKPT3D_SUFFIX}"
    )


@dataclasses.dataclass(frozen=True)
class Snapshot3D:
    volume: np.ndarray
    generation: int
    rule: str  # 3-D rulestring (e.g. "B4/S4,5" / named form's expansion)


def _vol_fingerprint(vol: np.ndarray) -> int:
    """Volume integrity stamp: the 2-D position-weighted fingerprint over
    the ``[D*H, W]`` flattening (deterministic, shape-free)."""
    from gol_tpu.utils.guard import fingerprint_np

    d, h, w = vol.shape
    return fingerprint_np(vol.reshape(d * h, w))


def save3d(
    path: str,
    vol: np.ndarray,
    generation: int,
    rule: str,
    fingerprint: Optional[int] = None,
) -> str:
    """Atomic fingerprint-stamped 3-D snapshot (same contract as
    :func:`save`, volume-shaped).  A caller-supplied ``fingerprint`` (the
    guard audit's device stamp — bit-identical to ``_vol_fingerprint`` by
    construction) skips the host-side recompute pass over the volume."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    vol = np.asarray(vol, np.uint8)
    if fingerprint is None:
        fingerprint = _vol_fingerprint(vol)
    tmp = path + ".tmp.npz"
    _write_fault(tmp, generation)
    np.savez_compressed(
        tmp,
        volume=vol,
        generation=np.int64(generation),
        rule=np.asarray(rule),
        fingerprint=np.uint32(fingerprint),
    )
    _tmp_rename_gap()
    os.replace(tmp, path)
    _post_rename_fault(path, generation)
    return path


def load3d(path: str) -> Snapshot3D:
    """Read + fingerprint-verify a 3-D snapshot.

    Every malformation fails as :class:`CorruptSnapshotError` (a
    ValueError), so the CLI's clean-error handling covers truncated
    files and wrong-format archives too — not just bad fingerprints.
    """
    try:
        data = np.load(path)
    except _archive_errors() as e:
        raise CorruptSnapshotError(
            f"{path}: not a readable snapshot archive ({e})"
        ) from e
    with data:
        missing = {"volume", "generation", "rule", "fingerprint"} - set(
            data.files
        )
        if missing:
            raise CorruptSnapshotError(
                f"{path}: not a 3-D snapshot (missing "
                f"{sorted(missing)}; a 2-D {CKPT_SUFFIX} checkpoint "
                "belongs to the 2-D driver)"
            )
        try:
            vol = data["volume"].astype(np.uint8)
            generation = int(data["generation"])
            rule = str(data["rule"])
            stored = int(data["fingerprint"])
        except _archive_errors() as e:
            raise CorruptSnapshotError(
                f"{path}: snapshot archive is corrupt ({e})"
            ) from e
        actual = _vol_fingerprint(vol)
        if stored != actual:
            raise CorruptSnapshotError(
                f"{path}: stored fingerprint {stored:#010x} != computed "
                f"{actual:#010x}; the snapshot is corrupt"
            )
        return Snapshot3D(volume=vol, generation=generation, rule=rule)


# -- sharded checkpoints (multi-host: no host materializes the board) --------
#
# Layout of a sharded checkpoint directory (2-D ``ckpt_<gen>.gol.d/`` and 3-D
# ``ckpt3d_<gen>.gol3d.d/`` share it):
#   manifest.npz          — geometry + the full piece table (box -> writer
#                           process), identical on every host by construction
#   shards_<proc>.npz     — that process's pieces: one array per box of the
#                           board/volume it owns, each stamped with a
#                           global-offset fingerprint
#
# The piece table is computed deterministically on every process from
# ``Sharding.devices_indices_map`` (the writer-planning idea of
# ``multihost.write_host_dumps``), so save needs zero coordination traffic;
# the only collective is the caller's barrier after the files land.  Because
# the fingerprint is a position-weighted sum mod 2^32
# (:func:`gol_tpu.utils.guard.fingerprint_np`; 3-D volumes under their
# ``[D*H, W]`` flattening), the per-piece stamps of the disjoint cover add
# up to the whole array's fingerprint — so a global audit stamp can be
# verified at load without assembling the data.
#
# Everything dimension-independent lives in the ``_nd`` helpers below; the
# 2-D and 3-D formats are thin wrappers differing only in box arity
# (``(r0, r1, c0, c1)`` vs ``(d0, d1, r0, r1, c0, c1)``), piece fingerprint
# offsets, and manifest fields.

SHARD_DIR_SUFFIX = ".gol.d"
SHARD3D_DIR_SUFFIX = ".gol3d.d"
_MANIFEST = "manifest.npz"


def sharded_checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"ckpt_{generation:012d}{SHARD_DIR_SUFFIX}"
    )


def sharded_checkpoint3d_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"ckpt3d_{generation:012d}{SHARD3D_DIR_SUFFIX}"
    )


def is_sharded(path: str) -> bool:
    return os.path.isdir(path)


@dataclasses.dataclass(frozen=True)
class ShardedMeta:
    """The 2-D manifest: everything except the board data itself."""

    shape: tuple
    generation: int
    num_ranks: int
    rule: Optional[str]
    rects: np.ndarray  # [n, 4] (r0, r1, c0, c1) disjoint cover
    procs: np.ndarray  # [n] writer process per rect
    fingerprint: Optional[int]  # global stamp (guard audit), if known
    # Elastic-mesh stamp (docs/RESILIENCE.md): the mesh topology that
    # wrote the snapshot ({kind, rows, cols}) and the writing job's
    # process count.  ``None`` on pre-stamp (legacy) manifests — the
    # reshard planner then infers the layout from the rect table and
    # flags the source ``legacy``.
    layout: Optional[dict] = None
    process_count: Optional[int] = None

    @property
    def legacy(self) -> bool:
        return self.layout is None


@dataclasses.dataclass(frozen=True)
class Sharded3DMeta:
    """The 3-D manifest: everything except the volume data itself."""

    shape: tuple
    generation: int
    rule: str
    boxes: np.ndarray  # [n, 6] (d0, d1, r0, r1, c0, c1) disjoint cover
    procs: np.ndarray  # [n] writer process per box
    fingerprint: Optional[int]
    # Writing job's process count (the elastic-mesh stamp, shared with
    # the 2-D manifest writer); None on pre-stamp manifests.  3-D
    # volumes have no reshard path — the stamp feeds the topology
    # diagnosis, not a planner.
    process_count: Optional[int] = None


def fingerprint3d_np(
    piece: np.ndarray, d0: int, r0: int, c0: int, global_h: int
) -> int:
    """Additive stamp of a 3-D piece at global offset ``(d0, r0, c0)``.

    Computed under the volume's ``[D*H, W]`` flattening (plane ``d`` row
    ``r`` lands at flattened row ``d*H + r``), so the stamps of a disjoint
    box cover sum mod 2^32 to :func:`_vol_fingerprint` of the whole
    volume.
    """
    from gol_tpu.utils.guard import fingerprint_np

    total = np.uint32(0)
    with np.errstate(over="ignore"):
        for di in range(piece.shape[0]):
            total = total + np.uint32(
                fingerprint_np(piece[di], (d0 + di) * global_h + r0, c0)
            )
    return int(total)


def _piece_fp(piece: np.ndarray, box, shape) -> int:
    """Global-offset fingerprint of one piece, 2-D or 3-D by arity."""
    from gol_tpu.utils.guard import fingerprint_np

    if len(box) == 4:
        return fingerprint_np(piece, box[0], box[2])
    return fingerprint3d_np(piece, box[0], box[2], box[4], shape[1])


def _box_nd(idx, shape):
    """Decode a shard index (tuple of slices) into a flat 2*ndim box."""
    out = []
    sl = list(idx) + [slice(None)] * (len(shape) - len(idx))
    for s, dim in zip(sl, shape):
        out.append(0 if s.start is None else s.start)
        out.append(dim if s.stop is None else s.stop)
    return tuple(out)


def _piece_table_nd(sharding, shape):
    """Deterministic (box -> lowest owning process) map, same on all hosts."""
    owner = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        b = _box_nd(idx, shape)
        p = dev.process_index
        if b not in owner or p < owner[b]:
            owner[b] = p
    return owner


def _save_sharded_nd(dirpath: str, arr, box_key: str, manifest_fields):
    """Write this process's pieces + (process 0) the manifest.

    The dimension-independent core of :func:`save_sharded` /
    :func:`save_sharded3d`: every process writes one ``shards_<proc>.npz``
    holding exactly the boxes assigned to it (lowest process index owning
    a box writes it — replicas dedupe), and process 0 additionally writes
    the manifest.  No process ever holds more than its own addressable
    shards; the caller is responsible for a barrier before using the
    checkpoint.  Returns the paths this process wrote.
    """
    import jax

    os.makedirs(dirpath, exist_ok=True)
    # Topology stamp (elastic meshes, docs/RESILIENCE.md): every
    # manifest records the writing job's process count, so a resume on
    # a different job size can tell "topology changed" from "pieces
    # missing" and verify accordingly.
    manifest_fields = dict(
        manifest_fields, process_count=np.int64(jax.process_count())
    )
    shape = tuple(arr.shape)
    owner = _piece_table_nd(arr.sharding, shape)
    me = jax.process_index()
    written = []
    pieces, seen = [], set()
    for shard in arr.addressable_shards:
        b = _box_nd(shard.index, shape)
        if owner[b] != me or b in seen:
            continue
        seen.add(b)
        pieces.append((b, np.asarray(shard.data, np.uint8)))
    arity = 2 * len(shape)
    arrays = {
        box_key: np.asarray(
            [b for b, _ in pieces], np.int64
        ).reshape(-1, arity),
        "fps": np.asarray(
            [_piece_fp(data, b, shape) for b, data in pieces], np.uint32
        ),
    }
    for i, (_, data) in enumerate(pieces):
        arrays[f"piece_{i}"] = data
    path = os.path.join(dirpath, f"shards_{me:05d}.npz")
    tmp = path + ".tmp.npz"
    _write_fault(tmp, manifest_fields["generation"])
    np.savez_compressed(tmp, **arrays)
    _tmp_rename_gap()
    os.replace(tmp, path)
    _post_rename_fault(path, manifest_fields["generation"])
    written.append(path)
    if me == 0:
        table = sorted(owner.items())
        manifest = dict(
            shape=np.asarray(shape, np.int64),
            **manifest_fields,
        )
        manifest[box_key] = np.asarray(
            [b for b, _ in table], np.int64
        ).reshape(-1, arity)
        manifest["procs"] = np.asarray([p for _, p in table], np.int64)
        mpath = os.path.join(dirpath, _MANIFEST)
        tmp = mpath + ".tmp.npz"
        np.savez_compressed(tmp, **manifest)
        _tmp_rename_gap()
        os.replace(tmp, mpath)
        written.append(mpath)
    return written


def save_sharded(
    dirpath: str,
    arr,
    generation: int,
    num_ranks: int,
    rule: Optional[str] = None,
    fingerprint: Optional[int] = None,
    mesh_layout: Optional[dict] = None,
) -> list:
    """Write this process's pieces of a sharded board (collective call).

    See :func:`_save_sharded_nd` for the write protocol; the caller fences
    with a barrier before relying on the checkpoint
    (``runtime._save_snapshot`` uses ``sync_global_devices``).
    ``mesh_layout`` (``{kind, rows, cols}``, see
    :class:`gol_tpu.resilience.reshard.MeshLayout`) stamps the writing
    topology into the manifest so a cross-topology resume can name the
    mismatch instead of inferring it.
    """
    fields = dict(
        generation=np.int64(generation), num_ranks=np.int64(num_ranks)
    )
    if rule is not None:
        fields["rule"] = np.asarray(rule)
    if fingerprint is not None:
        fields["fingerprint"] = np.uint32(fingerprint)
    if mesh_layout is not None:
        fields["mesh_kind"] = np.asarray(str(mesh_layout["kind"]))
        fields["mesh_rows"] = np.int64(mesh_layout.get("rows", 1))
        fields["mesh_cols"] = np.int64(mesh_layout.get("cols", 1))
    return _save_sharded_nd(dirpath, arr, "rects", fields)


def save_sharded3d(
    dirpath: str,
    arr,
    generation: int,
    rule: str,
    fingerprint: Optional[int] = None,
) -> list:
    """3-D counterpart of :func:`save_sharded` (same write protocol)."""
    fields = dict(generation=np.int64(generation), rule=np.asarray(rule))
    if fingerprint is not None:
        fields["fingerprint"] = np.uint32(fingerprint)
    return _save_sharded_nd(dirpath, arr, "boxes", fields)


def _validate_box_cover(dirpath: str, shape, boxes) -> list:
    """Bounds + exact-measure + pairwise-disjointness of a box cover.

    In-bounds + exact total measure only proves a tiling if the boxes are
    also pairwise disjoint; overlapping boxes that happen to sum to the
    array's size would otherwise let a region read double-count coverage
    and return ``np.empty`` garbage in the genuinely uncovered cells.
    Piece counts are O(hosts), so the quadratic sweep is cheap.  Returns
    the boxes as sorted int tuples.
    """
    ndim = len(shape)
    measure_total = 0
    out = []
    for row in boxes:
        b = tuple(int(x) for x in row)
        ok = all(
            0 <= b[2 * a] < b[2 * a + 1] <= shape[a] for a in range(ndim)
        )
        if not ok:
            raise CorruptSnapshotError(
                f"{dirpath}: piece box {b} falls outside the "
                f"{'x'.join(map(str, shape))} array; the manifest is corrupt"
            )
        m = 1
        for a in range(ndim):
            m *= b[2 * a + 1] - b[2 * a]
        measure_total += m
        out.append(b)
    total = 1
    for dim in shape:
        total *= dim
    if measure_total != total:
        raise CorruptSnapshotError(
            f"{dirpath}: piece table covers {measure_total} cells of "
            f"{total}; the manifest is corrupt or incomplete"
        )
    out.sort()
    for i, a in enumerate(out):
        for b in out[i + 1 :]:
            if b[0] >= a[1]:
                break  # sorted by the leading axis: no later overlap
            if all(
                b[2 * ax] < a[2 * ax + 1] and b[2 * ax + 1] > a[2 * ax]
                for ax in range(1, ndim)
            ):
                raise CorruptSnapshotError(
                    f"{dirpath}: piece boxes {a} and {b} overlap; the "
                    "manifest is corrupt"
                )
    return out


def _verify_global_stamp(dirpath: str, procs, stamp: int) -> None:
    """sum(per-piece fingerprints) must equal the stamped global hash."""
    total = np.uint32(0)
    with np.errstate(over="ignore"):
        for proc in sorted(set(int(p) for p in procs)):
            with np.load(
                os.path.join(dirpath, f"shards_{proc:05d}.npz")
            ) as sf:
                total = total + np.sum(
                    sf["fps"].astype(np.uint32), dtype=np.uint32
                )
    if int(total) != stamp:
        raise CorruptSnapshotError(
            f"{dirpath}: piece fingerprints sum to {int(total):#010x} "
            f"!= stamped {stamp:#010x}; some shard file is corrupt"
        )


def load_sharded_meta(dirpath: str, verify_stamp: bool = True) -> ShardedMeta:
    """Read + validate the 2-D manifest: the cover must tile the board
    exactly, and (when a global stamp is present) the per-piece
    fingerprints must add up to it — both checked without assembling any
    board data.  ``verify_stamp=False`` skips the global-stamp sweep (it
    reads every shard file — a multi-host auto-resume validates only its
    own process's pieces instead, see :func:`verify_snapshot`)."""
    try:
        with np.load(os.path.join(dirpath, _MANIFEST)) as data:
            layout = None
            if "mesh_kind" in data:
                layout = dict(
                    kind=str(data["mesh_kind"]),
                    rows=int(data["mesh_rows"]),
                    cols=int(data["mesh_cols"]),
                )
            meta = ShardedMeta(
                shape=tuple(int(x) for x in data["shape"]),
                generation=int(data["generation"]),
                num_ranks=int(data["num_ranks"]),
                rule=str(data["rule"]) if "rule" in data else None,
                rects=data["rects"].copy(),
                procs=data["procs"].copy(),
                fingerprint=(
                    int(data["fingerprint"]) if "fingerprint" in data else None
                ),
                layout=layout,
                process_count=(
                    int(data["process_count"])
                    if "process_count" in data
                    else None
                ),
            )
    except _archive_errors() as e:
        raise CorruptSnapshotError(
            f"{dirpath}: not a 2-D sharded checkpoint manifest ({e}); a "
            f"3-D {SHARD3D_DIR_SUFFIX} directory belongs to the 3-D driver"
        ) from e
    if len(meta.shape) != 2 or meta.rects.ndim != 2 or meta.rects.shape[1] != 4:
        raise CorruptSnapshotError(
            f"{dirpath}: malformed 2-D manifest geometry "
            f"(shape {meta.shape}, rect table {meta.rects.shape})"
        )
    _validate_box_cover(dirpath, meta.shape, meta.rects)
    if meta.fingerprint is not None and verify_stamp:
        _verify_global_stamp(dirpath, meta.procs, meta.fingerprint)
    return meta


def load_sharded3d_meta(
    dirpath: str, verify_stamp: bool = True
) -> Sharded3DMeta:
    """3-D counterpart of :func:`load_sharded_meta` (same validation)."""
    try:
        with np.load(os.path.join(dirpath, _MANIFEST)) as data:
            meta = Sharded3DMeta(
                shape=tuple(int(x) for x in data["shape"]),
                generation=int(data["generation"]),
                rule=str(data["rule"]),
                boxes=data["boxes"].copy(),
                procs=data["procs"].copy(),
                fingerprint=(
                    int(data["fingerprint"]) if "fingerprint" in data else None
                ),
                process_count=(
                    int(data["process_count"])
                    if "process_count" in data
                    else None
                ),
            )
    except _archive_errors() as e:
        raise CorruptSnapshotError(
            f"{dirpath}: not a 3-D sharded checkpoint manifest ({e}); a "
            f"2-D {SHARD_DIR_SUFFIX} directory belongs to the 2-D driver"
        ) from e
    if len(meta.shape) != 3 or meta.boxes.ndim != 2 or meta.boxes.shape[1] != 6:
        raise CorruptSnapshotError(
            f"{dirpath}: malformed 3-D manifest geometry "
            f"(shape {meta.shape}, box table {meta.boxes.shape})"
        )
    _validate_box_cover(dirpath, meta.shape, meta.boxes)
    if meta.fingerprint is not None and verify_stamp:
        _verify_global_stamp(dirpath, meta.procs, meta.fingerprint)
    return meta


def _read_region_nd(
    dirpath: str, shape, boxes, procs, box_key: str, index
) -> np.ndarray:
    """Assemble one box-shaped region from the piece files (any rank).

    ``index`` is a tuple of slices over the global array (the contract of
    ``jax.make_array_from_callback``, so a resuming host reads *only* the
    region its devices own).  Each piece consulted is fingerprint-verified
    once per call; pieces that don't intersect the region are never read.
    """
    ndim = len(shape)
    sl = list(index) + [slice(None)] * (ndim - len(index))
    lo = [0 if s.start is None else s.start for s in sl]
    hi = [shape[a] if sl[a].stop is None else sl[a].stop for a in range(ndim)]
    out = np.empty(tuple(hi[a] - lo[a] for a in range(ndim)), np.uint8)
    filled = 0
    by_proc = {}
    try:
        for row, proc in zip(boxes, procs):
            box = tuple(int(x) for x in row)
            inter = [
                (max(box[2 * a], lo[a]), min(box[2 * a + 1], hi[a]))
                for a in range(ndim)
            ]
            if any(i0 >= i1 for i0, i1 in inter):
                continue
            proc = int(proc)
            if proc not in by_proc:
                by_proc[proc] = np.load(
                    os.path.join(dirpath, f"shards_{proc:05d}.npz")
                )
            sf = by_proc[proc]
            hit = np.nonzero(
                np.all(sf[box_key] == np.asarray(box, np.int64), axis=1)
            )[0]
            if hit.size != 1:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece {box} missing from "
                    f"shards_{proc:05d}.npz"
                )
            k = int(hit[0])
            data = sf[f"piece_{k}"].astype(np.uint8)
            want = tuple(box[2 * a + 1] - box[2 * a] for a in range(ndim))
            if data.shape != want:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece {box} has shape {data.shape}, "
                    f"expected {want}"
                )
            stored = int(sf["fps"][k])
            actual = _piece_fp(data, box, shape)
            if stored != actual:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece {box} fingerprint {actual:#010x} "
                    f"!= stored {stored:#010x}; the shard file is corrupt"
                )
            dst = tuple(
                slice(inter[a][0] - lo[a], inter[a][1] - lo[a])
                for a in range(ndim)
            )
            src = tuple(
                slice(inter[a][0] - box[2 * a], inter[a][1] - box[2 * a])
                for a in range(ndim)
            )
            out[dst] = data[src]
            m = 1
            for i0, i1 in inter:
                m *= i1 - i0
            filled += m
    finally:
        for sf in by_proc.values():
            sf.close()
    if filled != out.size:
        raise CorruptSnapshotError(
            f"{dirpath}: region {index} only covered {filled} of "
            f"{out.size} cells"
        )
    return out


def read_sharded_region(
    dirpath: str, meta: ShardedMeta, index
) -> np.ndarray:
    """Assemble one rectangular region from the 2-D piece files."""
    return _read_region_nd(
        dirpath, meta.shape, meta.rects, meta.procs, "rects", index
    )


def read_sharded3d_region(
    dirpath: str, meta: Sharded3DMeta, index
) -> np.ndarray:
    """Assemble one box-shaped region from the 3-D piece files."""
    return _read_region_nd(
        dirpath, meta.shape, meta.boxes, meta.procs, "boxes", index
    )


# -- validated snapshot discovery (the resilience tier's read side) ----------
#
# `latest()` answers "what is the newest complete-looking snapshot" with a
# directory listing; the resilience layer needs the stronger question
# "what is the newest snapshot that would actually LOAD" — a preempted or
# kill-9'd run must fall back past a corrupt/torn newest candidate instead
# of dying on CorruptSnapshotError at resume time.  `latest_valid` walks
# newest→oldest, fully verifying each candidate (fingerprints included),
# and reports what it skipped so the fallback is loggable.

_GEN_RE = re.compile(r"^b?ckpt(?:3d)?_(\d+)\.")


def snapshot_generation(path: str) -> Optional[int]:
    """Generation encoded in a snapshot filename, or None."""
    m = _GEN_RE.match(os.path.basename(path))
    return int(m.group(1)) if m else None


def _kind_suffixes(kind: str) -> Tuple[str, str, str]:
    """(prefix, single-file suffix, sharded-dir suffix) for a driver kind."""
    if kind == "2d":
        return "ckpt_", CKPT_SUFFIX, SHARD_DIR_SUFFIX
    if kind == "3d":
        return "ckpt3d_", CKPT3D_SUFFIX, SHARD3D_DIR_SUFFIX
    if kind == "batch":
        return "bckpt_", BCKPT_SUFFIX, BCKPT_SHARD_DIR_SUFFIX
    raise ValueError(
        f"unknown snapshot kind {kind!r}; expected '2d'/'3d'/'batch'"
    )


def list_snapshots(directory: str, kind: str = "2d") -> List[str]:
    """Every snapshot *candidate* in ``directory``, oldest→newest.

    Includes torn sharded directories and corrupt files — validation is
    the walk's job, not the listing's.  Leftover ``.tmp.npz`` files from
    a killed writer never match (their names don't end in a snapshot
    suffix), so they are invisible here exactly as they are to
    :func:`latest`.
    """
    prefix, single, sharded = _kind_suffixes(kind)
    if not os.path.isdir(directory):
        return []
    names = sorted(
        f
        for f in os.listdir(directory)
        if f.startswith(prefix) and (f.endswith(single) or f.endswith(sharded))
    )
    return [os.path.join(directory, f) for f in names]


def _verify_pieces_nd(
    dirpath: str, shape, boxes, procs, box_key: str, only_process=None
) -> None:
    """Fingerprint-verify shard pieces without assembling the array.

    ``only_process`` restricts the sweep to one writer process's file —
    the multi-host auto-resume contract: each rank vouches for the pieces
    *it* wrote, and the ranks then agree on min(newest valid) so nobody
    resumes ahead of a rank whose pieces failed.
    """
    per_proc: dict = {}
    for row, proc in zip(boxes, procs):
        proc = int(proc)
        if only_process is not None and proc != only_process:
            continue
        per_proc.setdefault(proc, []).append(tuple(int(x) for x in row))
    for proc, pboxes in sorted(per_proc.items()):
        fpath = os.path.join(dirpath, f"shards_{proc:05d}.npz")
        try:
            sf = np.load(fpath)
        except _archive_errors() as e:
            raise CorruptSnapshotError(
                f"{fpath}: not a readable shard archive ({e})"
            ) from e
        with sf:
            try:
                table = sf[box_key]
                fps = sf["fps"]
                for box in pboxes:
                    hit = np.nonzero(
                        np.all(table == np.asarray(box, np.int64), axis=1)
                    )[0]
                    if hit.size != 1:
                        raise CorruptSnapshotError(
                            f"{dirpath}: piece {box} missing from "
                            f"shards_{proc:05d}.npz"
                        )
                    k = int(hit[0])
                    data = sf[f"piece_{k}"].astype(np.uint8)
                    ndim = len(shape)
                    want = tuple(
                        box[2 * a + 1] - box[2 * a] for a in range(ndim)
                    )
                    if data.shape != want:
                        raise CorruptSnapshotError(
                            f"{dirpath}: piece {box} has shape "
                            f"{data.shape}, expected {want}"
                        )
                    stored = int(fps[k])
                    actual = _piece_fp(data, box, shape)
                    if stored != actual:
                        raise CorruptSnapshotError(
                            f"{dirpath}: piece {box} fingerprint "
                            f"{actual:#010x} != stored {stored:#010x}; the "
                            "shard file is corrupt"
                        )
            except CorruptSnapshotError:
                raise
            except _archive_errors() as e:
                raise CorruptSnapshotError(
                    f"{fpath}: shard archive is corrupt ({e})"
                ) from e


def verify_snapshot(
    path: str,
    only_process: Optional[int] = None,
    expect_processes: Optional[int] = None,
) -> int:
    """Fully validate one snapshot (any format); return its generation.

    Single-file snapshots load + fingerprint-verify end to end; sharded
    directories validate the manifest (cover + global stamp) and
    fingerprint-verify every piece — or, with ``only_process``, only that
    process's pieces and no global stamp (each rank vouches for its own
    writes; cross-rank agreement happens at the resume-generation min).
    ``expect_processes`` (the resuming job's process count) arms the
    topology check: when the manifest was stamped by a *different* job
    size, the own-pieces shortcut is unsound — a shrunk job would leave
    the vanished ranks' pieces vouched for by nobody — so the sweep
    silently widens to every piece plus the global stamp (the
    shared-storage degraded-resume path).  Raises
    :class:`CorruptSnapshotError` (or ``OSError`` for a vanished file)
    when the snapshot cannot be trusted.
    """
    name = os.path.basename(path)
    if name.endswith(SHARD_DIR_SUFFIX) or name.endswith(SHARD3D_DIR_SUFFIX):
        if not _sharded_complete(path):
            raise CorruptSnapshotError(
                f"{path}: torn sharded checkpoint (manifest or shard "
                "files missing)"
            )
        if name.endswith(SHARD3D_DIR_SUFFIX):
            meta3 = load_sharded3d_meta(path, verify_stamp=False)
            only3 = _effective_only_process(
                only_process, expect_processes, meta3.process_count,
                meta3.procs,
            )
            if only3 is None and meta3.fingerprint is not None:
                _verify_global_stamp(path, meta3.procs, meta3.fingerprint)
            _verify_pieces_nd(
                path, meta3.shape, meta3.boxes, meta3.procs, "boxes", only3
            )
            return meta3.generation
        meta = load_sharded_meta(path, verify_stamp=False)
        only = _effective_only_process(
            only_process, expect_processes, meta.process_count, meta.procs
        )
        if only is None and meta.fingerprint is not None:
            _verify_global_stamp(path, meta.procs, meta.fingerprint)
        _verify_pieces_nd(
            path, meta.shape, meta.rects, meta.procs, "rects", only
        )
        return meta.generation
    if name.endswith(BCKPT_SUFFIX):
        return load_batch(path).generation
    if name.endswith(CKPT3D_SUFFIX):
        return load3d(path).generation
    if name.endswith(CKPT_SUFFIX):
        return load(path).generation
    raise CorruptSnapshotError(f"{path}: not a snapshot path")


def _effective_only_process(
    only_process: Optional[int],
    expect_processes: Optional[int],
    stamped: Optional[int],
    procs,
) -> Optional[int]:
    """Resolve the per-rank verification shortcut against the topology.

    The shortcut is only sound when the resuming job has the same shape
    as the writing job; on a mismatch (stamped process count differs, or
    a legacy manifest's piece table implies one) every rank verifies
    every piece.  With ``expect_processes`` unset (plain
    :func:`verify_snapshot` callers) the shortcut is honored as before.
    """
    if only_process is None or expect_processes is None:
        return only_process
    if stamped is None:
        # Legacy manifest: the writer count is whatever the piece table
        # references (process ids are dense from 0 by construction).
        stamped = max((int(p) for p in procs), default=0) + 1
    return only_process if stamped == expect_processes else None


def latest_valid(
    directory: str,
    kind: str = "2d",
    only_process: Optional[int] = None,
    expect_processes: Optional[int] = None,
) -> Tuple[Optional[str], List[str]]:
    """Newest snapshot that fully verifies, walking newest→oldest.

    Returns ``(path_or_None, skipped)`` where ``skipped`` lists the
    *newer* candidates rejected as corrupt/torn (in the order they were
    rejected) — a nonempty list is the "fallback happened" signal the
    resume telemetry event records.
    """
    skipped: List[str] = []
    for path in reversed(list_snapshots(directory, kind)):
        try:
            verify_snapshot(
                path,
                only_process=only_process,
                expect_processes=expect_processes,
            )
            return path, skipped
        except (CorruptSnapshotError, OSError):
            skipped.append(path)
    return None, skipped
