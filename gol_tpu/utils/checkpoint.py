"""Checkpoint / resume — a capability addition over the reference.

The reference's only persistence is the write-only final dump
(gol-main.c:135-139); there is no loader and no mid-run snapshot (SURVEY §5).
Here a run can periodically snapshot the board + generation counter and
resume from any snapshot.  Format: a single ``.npz`` with the board, the
generation, the geometry, and — for reference-compat (stale-halo, bug B1)
runs — the frozen t=0 ghost rows, so a resumed compat run keeps the
*original* halos rather than re-freezing from the resumed board.  Portable
and readable without JAX.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

CKPT_SUFFIX = ".gol.npz"


@dataclasses.dataclass(frozen=True)
class Snapshot:
    board: np.ndarray
    generation: int
    num_ranks: int
    top0: Optional[np.ndarray] = None  # frozen halos, stale_t0 runs only
    bottom0: Optional[np.ndarray] = None
    rule: Optional[str] = None  # B/S rulestring for custom-rule runs


class CorruptSnapshotError(ValueError):
    """The snapshot's stored fingerprint does not match its board."""


def _halo_plane(top0: np.ndarray, bottom0: np.ndarray) -> np.ndarray:
    """Canonical 2-row plane for fingerprinting the frozen halo pair
    (halos may arrive as (W,) or (1, W))."""
    return np.stack([np.ravel(top0), np.ravel(bottom0)])


def checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(directory, f"ckpt_{generation:012d}{CKPT_SUFFIX}")


def save(
    path: str,
    board: np.ndarray,
    generation: int,
    num_ranks: int,
    top0: Optional[np.ndarray] = None,
    bottom0: Optional[np.ndarray] = None,
    fingerprint: Optional[int] = None,
    rule: Optional[str] = None,
) -> str:
    """Write a snapshot atomically, stamped with a content fingerprint.

    The fingerprint (:func:`gol_tpu.utils.guard.fingerprint_np`) makes the
    file tamper-evident: :func:`load` recomputes and verifies it, so a
    corrupted snapshot fails loudly instead of silently resuming a wrong
    world (failure-detection tier 2, SURVEY §5's missing subsystem).
    Callers that already computed the board's fingerprint on device (the
    guard audit) pass it in to skip the host-side O(H·W) recompute — it is
    bit-identical to ``fingerprint_np`` by design.
    """
    from gol_tpu.utils.guard import fingerprint_np

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    board = np.asarray(board, np.uint8)
    arrays = dict(
        board=board,
        generation=np.int64(generation),
        num_ranks=np.int64(num_ranks),
        fingerprint=np.uint32(
            fingerprint_np(board) if fingerprint is None else fingerprint
        ),
    )
    if rule is not None:
        # Like the frozen halos, the rule changes the semantics of every
        # resumed generation; record it so resume can refuse a mismatch.
        arrays["rule"] = np.asarray(rule)
    if top0 is not None:
        arrays["top0"] = np.asarray(top0, np.uint8)
        arrays["bottom0"] = np.asarray(bottom0, np.uint8)
        # The frozen halos evolve the resumed world every generation, so
        # they need the same tamper evidence as the board itself.
        arrays["halo_fingerprint"] = np.uint32(
            fingerprint_np(_halo_plane(arrays["top0"], arrays["bottom0"]))
        )
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)
    return path


def load(path: str) -> Snapshot:
    """Read a snapshot, verifying its fingerprint when present.

    (Snapshots written before fingerprints existed load without the check.)
    """
    with np.load(path) as data:
        board = data["board"].astype(np.uint8)
        top0 = data["top0"].astype(np.uint8) if "top0" in data else None
        bottom0 = (
            data["bottom0"].astype(np.uint8) if "bottom0" in data else None
        )
        if "fingerprint" in data:
            from gol_tpu.utils.guard import fingerprint_np

            stored = int(data["fingerprint"])
            actual = fingerprint_np(board)
            if stored != actual:
                raise CorruptSnapshotError(
                    f"{path}: stored fingerprint {stored:#010x} != computed "
                    f"{actual:#010x}; the snapshot is corrupt"
                )
            if "halo_fingerprint" in data:
                stored_h = int(data["halo_fingerprint"])
                actual_h = fingerprint_np(_halo_plane(top0, bottom0))
                if stored_h != actual_h:
                    raise CorruptSnapshotError(
                        f"{path}: halo fingerprint {stored_h:#010x} != "
                        f"computed {actual_h:#010x}; the frozen halos are "
                        "corrupt"
                    )
        return Snapshot(
            board=board,
            generation=int(data["generation"]),
            num_ranks=int(data["num_ranks"]),
            top0=top0,
            bottom0=bottom0,
            rule=str(data["rule"]) if "rule" in data else None,
        )


def latest(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        f
        for f in os.listdir(directory)
        if f.startswith("ckpt_") and f.endswith(CKPT_SUFFIX)
    )
    return os.path.join(directory, ckpts[-1]) if ckpts else None
