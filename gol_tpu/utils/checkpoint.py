"""Checkpoint / resume — a capability addition over the reference.

The reference's only persistence is the write-only final dump
(gol-main.c:135-139); there is no loader and no mid-run snapshot (SURVEY §5).
Here a run can periodically snapshot the board + generation counter and
resume from any snapshot.  Format: a single ``.npz`` with the board, the
generation, the geometry, and — for reference-compat (stale-halo, bug B1)
runs — the frozen t=0 ghost rows, so a resumed compat run keeps the
*original* halos rather than re-freezing from the resumed board.  Portable
and readable without JAX.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

CKPT_SUFFIX = ".gol.npz"


@dataclasses.dataclass(frozen=True)
class Snapshot:
    board: np.ndarray
    generation: int
    num_ranks: int
    top0: Optional[np.ndarray] = None  # frozen halos, stale_t0 runs only
    bottom0: Optional[np.ndarray] = None
    rule: Optional[str] = None  # B/S rulestring for custom-rule runs


class CorruptSnapshotError(ValueError):
    """The snapshot's stored fingerprint does not match its board."""


def _halo_plane(top0: np.ndarray, bottom0: np.ndarray) -> np.ndarray:
    """Canonical 2-row plane for fingerprinting the frozen halo pair
    (halos may arrive as (W,) or (1, W))."""
    return np.stack([np.ravel(top0), np.ravel(bottom0)])


def checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(directory, f"ckpt_{generation:012d}{CKPT_SUFFIX}")


def save(
    path: str,
    board: np.ndarray,
    generation: int,
    num_ranks: int,
    top0: Optional[np.ndarray] = None,
    bottom0: Optional[np.ndarray] = None,
    fingerprint: Optional[int] = None,
    rule: Optional[str] = None,
) -> str:
    """Write a snapshot atomically, stamped with a content fingerprint.

    The fingerprint (:func:`gol_tpu.utils.guard.fingerprint_np`) makes the
    file tamper-evident: :func:`load` recomputes and verifies it, so a
    corrupted snapshot fails loudly instead of silently resuming a wrong
    world (failure-detection tier 2, SURVEY §5's missing subsystem).
    Callers that already computed the board's fingerprint on device (the
    guard audit) pass it in to skip the host-side O(H·W) recompute — it is
    bit-identical to ``fingerprint_np`` by design.
    """
    from gol_tpu.utils.guard import fingerprint_np

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    board = np.asarray(board, np.uint8)
    arrays = dict(
        board=board,
        generation=np.int64(generation),
        num_ranks=np.int64(num_ranks),
        fingerprint=np.uint32(
            fingerprint_np(board) if fingerprint is None else fingerprint
        ),
    )
    if rule is not None:
        # Like the frozen halos, the rule changes the semantics of every
        # resumed generation; record it so resume can refuse a mismatch.
        arrays["rule"] = np.asarray(rule)
    if top0 is not None:
        arrays["top0"] = np.asarray(top0, np.uint8)
        arrays["bottom0"] = np.asarray(bottom0, np.uint8)
        # The frozen halos evolve the resumed world every generation, so
        # they need the same tamper evidence as the board itself.
        arrays["halo_fingerprint"] = np.uint32(
            fingerprint_np(_halo_plane(arrays["top0"], arrays["bottom0"]))
        )
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)
    return path


def load(path: str) -> Snapshot:
    """Read a snapshot, verifying its fingerprint when present.

    (Snapshots written before fingerprints existed load without the check.)
    """
    with np.load(path) as data:
        board = data["board"].astype(np.uint8)
        top0 = data["top0"].astype(np.uint8) if "top0" in data else None
        bottom0 = (
            data["bottom0"].astype(np.uint8) if "bottom0" in data else None
        )
        if "fingerprint" in data:
            from gol_tpu.utils.guard import fingerprint_np

            stored = int(data["fingerprint"])
            actual = fingerprint_np(board)
            if stored != actual:
                raise CorruptSnapshotError(
                    f"{path}: stored fingerprint {stored:#010x} != computed "
                    f"{actual:#010x}; the snapshot is corrupt"
                )
            if "halo_fingerprint" in data:
                stored_h = int(data["halo_fingerprint"])
                actual_h = fingerprint_np(_halo_plane(top0, bottom0))
                if stored_h != actual_h:
                    raise CorruptSnapshotError(
                        f"{path}: halo fingerprint {stored_h:#010x} != "
                        f"computed {actual_h:#010x}; the frozen halos are "
                        "corrupt"
                    )
        return Snapshot(
            board=board,
            generation=int(data["generation"]),
            num_ranks=int(data["num_ranks"]),
            top0=top0,
            bottom0=bottom0,
            rule=str(data["rule"]) if "rule" in data else None,
        )


def _sharded_complete(dirpath: str) -> bool:
    """True when the manifest and every shard file it references exist.

    A sharded checkpoint directory is not created atomically (each host
    lands its own file, the barrier comes after), so a crash mid-save can
    leave a torn directory; :func:`latest` must never prefer one over an
    older complete snapshot.
    """
    try:
        with np.load(os.path.join(dirpath, _MANIFEST)) as data:
            procs = set(int(p) for p in data["procs"])
    except (OSError, KeyError, ValueError):
        return False
    return all(
        os.path.exists(os.path.join(dirpath, f"shards_{p:05d}.npz"))
        for p in procs
    )


def latest(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        f
        for f in os.listdir(directory)
        if f.startswith("ckpt_")
        and (
            f.endswith(CKPT_SUFFIX)
            or (
                f.endswith(SHARD_DIR_SUFFIX)
                and _sharded_complete(os.path.join(directory, f))
            )
        )
    )
    return os.path.join(directory, ckpts[-1]) if ckpts else None


# -- 3-D volume snapshots (the cli3d driver's persistence) -------------------


CKPT3D_SUFFIX = ".gol3d.npz"


def checkpoint3d_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"ckpt3d_{generation:012d}{CKPT3D_SUFFIX}"
    )


@dataclasses.dataclass(frozen=True)
class Snapshot3D:
    volume: np.ndarray
    generation: int
    rule: str  # 3-D rulestring (e.g. "B4/S4,5" / named form's expansion)


def _vol_fingerprint(vol: np.ndarray) -> int:
    """Volume integrity stamp: the 2-D position-weighted fingerprint over
    the ``[D*H, W]`` flattening (deterministic, shape-free)."""
    from gol_tpu.utils.guard import fingerprint_np

    d, h, w = vol.shape
    return fingerprint_np(vol.reshape(d * h, w))


def save3d(
    path: str,
    vol: np.ndarray,
    generation: int,
    rule: str,
    fingerprint: Optional[int] = None,
) -> str:
    """Atomic fingerprint-stamped 3-D snapshot (same contract as
    :func:`save`, volume-shaped).  A caller-supplied ``fingerprint`` (the
    guard audit's device stamp — bit-identical to ``_vol_fingerprint`` by
    construction) skips the host-side recompute pass over the volume."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    vol = np.asarray(vol, np.uint8)
    if fingerprint is None:
        fingerprint = _vol_fingerprint(vol)
    tmp = path + ".tmp.npz"
    np.savez_compressed(
        tmp,
        volume=vol,
        generation=np.int64(generation),
        rule=np.asarray(rule),
        fingerprint=np.uint32(fingerprint),
    )
    os.replace(tmp, path)
    return path


def load3d(path: str) -> Snapshot3D:
    """Read + fingerprint-verify a 3-D snapshot.

    Every malformation fails as :class:`CorruptSnapshotError` (a
    ValueError), so the CLI's clean-error handling covers truncated
    files and wrong-format archives too — not just bad fingerprints.
    """
    import zipfile

    try:
        data = np.load(path)
    except (zipfile.BadZipFile, ValueError) as e:
        raise CorruptSnapshotError(
            f"{path}: not a readable snapshot archive ({e})"
        ) from e
    with data:
        missing = {"volume", "generation", "rule", "fingerprint"} - set(
            data.files
        )
        if missing:
            raise CorruptSnapshotError(
                f"{path}: not a 3-D snapshot (missing "
                f"{sorted(missing)}; a 2-D {CKPT_SUFFIX} checkpoint "
                "belongs to the 2-D driver)"
            )
        vol = data["volume"].astype(np.uint8)
        stored = int(data["fingerprint"])
        actual = _vol_fingerprint(vol)
        if stored != actual:
            raise CorruptSnapshotError(
                f"{path}: stored fingerprint {stored:#010x} != computed "
                f"{actual:#010x}; the snapshot is corrupt"
            )
        return Snapshot3D(
            volume=vol,
            generation=int(data["generation"]),
            rule=str(data["rule"]),
        )


# -- sharded checkpoints (multi-host: no host materializes the board) --------
#
# Layout of a ``ckpt_<gen>.gol.d/`` directory:
#   manifest.npz          — geometry + the full piece table (rect -> writer
#                           process), identical on every host by construction
#   shards_<proc>.npz     — that process's pieces: one array per rectangle of
#                           the board it owns, each stamped with a
#                           global-offset fingerprint
#
# The piece table is computed deterministically on every process from
# ``Sharding.devices_indices_map`` (the writer-planning idea of
# ``multihost.write_host_dumps``), so save needs zero coordination traffic;
# the only collective is the caller's barrier after the files land.  Because
# the fingerprint is a position-weighted sum mod 2^32
# (:func:`gol_tpu.utils.guard.fingerprint_np`), the per-piece stamps of the
# disjoint cover add up to the whole board's fingerprint — so a global
# audit stamp can be verified at load without assembling the board.

SHARD_DIR_SUFFIX = ".gol.d"
_MANIFEST = "manifest.npz"


def sharded_checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"ckpt_{generation:012d}{SHARD_DIR_SUFFIX}"
    )


def is_sharded(path: str) -> bool:
    return os.path.isdir(path)


@dataclasses.dataclass(frozen=True)
class ShardedMeta:
    """The manifest: everything except the board data itself."""

    shape: tuple
    generation: int
    num_ranks: int
    rule: Optional[str]
    rects: np.ndarray  # [n, 4] (r0, r1, c0, c1) disjoint cover
    procs: np.ndarray  # [n] writer process per rect
    fingerprint: Optional[int]  # global stamp (guard audit), if known


def _piece_table(sharding, shape):
    """Deterministic (rect -> lowest owning process) map, same on all hosts."""
    from gol_tpu.parallel.multihost import _rect

    owner = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        r = _rect(idx, shape)
        p = dev.process_index
        if r not in owner or p < owner[r]:
            owner[r] = p
    return owner


def save_sharded(
    dirpath: str,
    arr,
    generation: int,
    num_ranks: int,
    rule: Optional[str] = None,
    fingerprint: Optional[int] = None,
) -> list:
    """Write this process's pieces of a sharded board (collective call).

    Every process calls this; each writes one ``shards_<proc>.npz`` holding
    exactly the rectangles assigned to it (lowest process index owning a
    rect writes it — replicas dedupe), and process 0 additionally writes
    the manifest.  No process ever holds more than its own addressable
    shards.  The caller is responsible for a barrier before using the
    checkpoint (``runtime._save_snapshot`` fences with
    ``sync_global_devices``).  Returns the paths this process wrote.
    """
    import jax

    from gol_tpu.parallel.multihost import _rect
    from gol_tpu.utils.guard import fingerprint_np

    os.makedirs(dirpath, exist_ok=True)
    sharding = arr.sharding
    shape = tuple(arr.shape)
    owner = _piece_table(sharding, shape)
    me = jax.process_index()
    written = []
    pieces, seen = [], set()
    for shard in arr.addressable_shards:
        r = _rect(shard.index, shape)
        if owner[r] != me or r in seen:
            continue
        seen.add(r)
        pieces.append((r, np.asarray(shard.data, np.uint8)))
    arrays = dict(
        rects=np.asarray([r for r, _ in pieces], np.int64).reshape(-1, 4),
        fps=np.asarray(
            [
                fingerprint_np(data, r0, c0)
                for (r0, _, c0, _), data in pieces
            ],
            np.uint32,
        ),
    )
    for i, (_, data) in enumerate(pieces):
        arrays[f"piece_{i}"] = data
    path = os.path.join(dirpath, f"shards_{me:05d}.npz")
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)
    written.append(path)
    if me == 0:
        table = sorted(owner.items())
        manifest = dict(
            shape=np.asarray(shape, np.int64),
            generation=np.int64(generation),
            num_ranks=np.int64(num_ranks),
            rects=np.asarray([r for r, _ in table], np.int64).reshape(-1, 4),
            procs=np.asarray([p for _, p in table], np.int64),
        )
        if rule is not None:
            manifest["rule"] = np.asarray(rule)
        if fingerprint is not None:
            manifest["fingerprint"] = np.uint32(fingerprint)
        mpath = os.path.join(dirpath, _MANIFEST)
        tmp = mpath + ".tmp.npz"
        np.savez_compressed(tmp, **manifest)
        os.replace(tmp, mpath)
        written.append(mpath)
    return written


def load_sharded_meta(dirpath: str) -> ShardedMeta:
    """Read + validate the manifest: the cover must tile the board exactly,
    and (when a global stamp is present) the per-piece fingerprints must
    add up to it — both checked without assembling any board data."""
    import zipfile

    try:
        with np.load(os.path.join(dirpath, _MANIFEST)) as data:
            meta = ShardedMeta(
                shape=tuple(int(x) for x in data["shape"]),
                generation=int(data["generation"]),
                num_ranks=int(data["num_ranks"]),
                rule=str(data["rule"]) if "rule" in data else None,
                rects=data["rects"].copy(),
                procs=data["procs"].copy(),
                fingerprint=(
                    int(data["fingerprint"]) if "fingerprint" in data else None
                ),
            )
    except (KeyError, ValueError, zipfile.BadZipFile) as e:
        raise CorruptSnapshotError(
            f"{dirpath}: not a 2-D sharded checkpoint manifest ({e}); a "
            f"3-D {SHARD3D_DIR_SUFFIX} directory belongs to the 3-D driver"
        ) from e
    if len(meta.shape) != 2 or meta.rects.ndim != 2 or meta.rects.shape[1] != 4:
        raise CorruptSnapshotError(
            f"{dirpath}: malformed 2-D manifest geometry "
            f"(shape {meta.shape}, rect table {meta.rects.shape})"
        )
    h, w = meta.shape
    area = 0
    rects = []
    for r0, r1, c0, c1 in meta.rects:
        r0, r1, c0, c1 = int(r0), int(r1), int(c0), int(c1)
        if not (0 <= r0 < r1 <= h and 0 <= c0 < c1 <= w):
            raise CorruptSnapshotError(
                f"{dirpath}: piece rect ({r0},{r1},{c0},{c1}) falls outside "
                f"the {h}x{w} board; the manifest is corrupt"
            )
        area += (r1 - r0) * (c1 - c0)
        rects.append((r0, r1, c0, c1))
    if area != h * w:
        raise CorruptSnapshotError(
            f"{dirpath}: piece table covers {area} cells of {h * w}; the "
            "manifest is corrupt or incomplete"
        )
    # In-bounds + exact total area only proves a tiling if the rects are
    # also pairwise disjoint; overlapping rects that happen to sum to h*w
    # would otherwise let read_sharded_region double-count coverage and
    # return np.empty garbage in the genuinely uncovered cells.  Piece
    # counts are O(hosts), so the quadratic check is cheap.
    rects.sort()
    for i, (r0, r1, c0, c1) in enumerate(rects):
        for q0, q1, s0, s1 in rects[i + 1 :]:
            if q0 >= r1:
                break  # sorted by r0: no later rect can overlap rows
            # rows overlap (r0 <= q0 < r1); overlap iff columns intersect
            if s1 > c0 and s0 < c1:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece rects ({r0},{r1},{c0},{c1}) and "
                    f"({q0},{q1},{s0},{s1}) overlap; the manifest is corrupt"
                )
    if meta.fingerprint is not None:
        total = np.uint32(0)
        with np.errstate(over="ignore"):
            for proc in sorted(set(int(p) for p in meta.procs)):
                with np.load(
                    os.path.join(dirpath, f"shards_{proc:05d}.npz")
                ) as sf:
                    total = total + np.sum(
                        sf["fps"].astype(np.uint32), dtype=np.uint32
                    )
        if int(total) != meta.fingerprint:
            raise CorruptSnapshotError(
                f"{dirpath}: piece fingerprints sum to {int(total):#010x} "
                f"!= stamped {meta.fingerprint:#010x}; some shard file is "
                "corrupt"
            )
    return meta


def read_sharded_region(
    dirpath: str, meta: ShardedMeta, index
) -> np.ndarray:
    """Assemble one rectangular region from the piece files.

    ``index`` is a tuple of slices over the global board (the contract of
    ``jax.make_array_from_callback``, so a resuming host reads *only* the
    rows its devices own).  Each piece consulted is fingerprint-verified
    once per call; pieces that don't intersect the region are never read.
    """
    h, w = meta.shape
    rs, cs = index[0], index[1] if len(index) > 1 else slice(None)
    lo_r = 0 if rs.start is None else rs.start
    hi_r = h if rs.stop is None else rs.stop
    lo_c = 0 if cs.start is None else cs.start
    hi_c = w if cs.stop is None else cs.stop
    out = np.empty((hi_r - lo_r, hi_c - lo_c), np.uint8)
    filled = 0
    by_proc = {}
    try:
        filled = _fill_region(
            dirpath, meta, out, lo_r, hi_r, lo_c, hi_c, by_proc
        )
    finally:
        for sf in by_proc.values():
            sf.close()
    if filled != out.size:
        raise CorruptSnapshotError(
            f"{dirpath}: region {index} only covered {filled} of "
            f"{out.size} cells"
        )
    return out


def _fill_region(dirpath, meta, out, lo_r, hi_r, lo_c, hi_c, by_proc):
    """Copy every intersecting, fingerprint-verified piece into ``out``;
    opened shard files land in ``by_proc`` for the caller to close."""
    from gol_tpu.utils.guard import fingerprint_np

    filled = 0
    for (r0, r1, c0, c1), proc in zip(meta.rects, meta.procs):
        r0, r1, c0, c1 = int(r0), int(r1), int(c0), int(c1)
        i0, i1 = max(r0, lo_r), min(r1, hi_r)
        j0, j1 = max(c0, lo_c), min(c1, hi_c)
        if i0 >= i1 or j0 >= j1:
            continue
        proc = int(proc)
        if proc not in by_proc:
            by_proc[proc] = np.load(
                os.path.join(dirpath, f"shards_{proc:05d}.npz")
            )
        sf = by_proc[proc]
        rects = sf["rects"]
        hit = np.nonzero(
            (rects[:, 0] == r0)
            & (rects[:, 1] == r1)
            & (rects[:, 2] == c0)
            & (rects[:, 3] == c1)
        )[0]
        if hit.size != 1:
            raise CorruptSnapshotError(
                f"{dirpath}: piece ({r0},{r1},{c0},{c1}) missing from "
                f"shards_{proc:05d}.npz"
            )
        k = int(hit[0])
        data = sf[f"piece_{k}"].astype(np.uint8)
        if data.shape != (r1 - r0, c1 - c0):
            raise CorruptSnapshotError(
                f"{dirpath}: piece ({r0},{r1},{c0},{c1}) has shape "
                f"{data.shape}"
            )
        stored = int(sf["fps"][k])
        actual = fingerprint_np(data, r0, c0)
        if stored != actual:
            raise CorruptSnapshotError(
                f"{dirpath}: piece ({r0},{r1},{c0},{c1}) fingerprint "
                f"{actual:#010x} != stored {stored:#010x}; the shard file "
                "is corrupt"
            )
        out[i0 - lo_r : i1 - lo_r, j0 - lo_c : j1 - lo_c] = data[
            i0 - r0 : i1 - r0, j0 - c0 : j1 - c0
        ]
        filled += (i1 - i0) * (j1 - j0)
    return filled


# -- sharded 3-D checkpoints (the 3-D driver's multi-host persistence) -------
#
# Same design as the 2-D sharded format: per-process piece files + a
# deterministic manifest, position-weighted additive fingerprints under the
# volume's [D*H, W] flattening (matching ``_vol_fingerprint``), so a global
# stamp verifies without any host assembling the volume.  Pieces are 3-D
# boxes ``(d0, d1, r0, r1, c0, c1)``.

SHARD3D_DIR_SUFFIX = ".gol3d.d"


def sharded_checkpoint3d_path(directory: str, generation: int) -> str:
    return os.path.join(
        directory, f"ckpt3d_{generation:012d}{SHARD3D_DIR_SUFFIX}"
    )


@dataclasses.dataclass(frozen=True)
class Sharded3DMeta:
    """The 3-D manifest: everything except the volume data itself."""

    shape: tuple
    generation: int
    rule: str
    boxes: np.ndarray  # [n, 6] (d0, d1, r0, r1, c0, c1) disjoint cover
    procs: np.ndarray  # [n] writer process per box
    fingerprint: Optional[int]


def _box(idx, shape):
    """Decode a 3-D shard index (tuple of slices) into a 6-tuple box."""
    out = []
    sl = list(idx) + [slice(None)] * (3 - len(idx))
    for s, dim in zip(sl, shape):
        out.append(0 if s.start is None else s.start)
        out.append(dim if s.stop is None else s.stop)
    return tuple(out)


def fingerprint3d_np(
    piece: np.ndarray, d0: int, r0: int, c0: int, global_h: int
) -> int:
    """Additive stamp of a 3-D piece at global offset ``(d0, r0, c0)``.

    Computed under the volume's ``[D*H, W]`` flattening (plane ``d`` row
    ``r`` lands at flattened row ``d*H + r``), so the stamps of a disjoint
    box cover sum mod 2^32 to :func:`_vol_fingerprint` of the whole
    volume.
    """
    from gol_tpu.utils.guard import fingerprint_np

    total = np.uint32(0)
    with np.errstate(over="ignore"):
        for di in range(piece.shape[0]):
            total = total + np.uint32(
                fingerprint_np(piece[di], (d0 + di) * global_h + r0, c0)
            )
    return int(total)


def _piece_table3d(sharding, shape):
    """Deterministic (box -> lowest owning process) map, same on all hosts."""
    owner = {}
    for dev, idx in sharding.devices_indices_map(tuple(shape)).items():
        b = _box(idx, shape)
        p = dev.process_index
        if b not in owner or p < owner[b]:
            owner[b] = p
    return owner


def save_sharded3d(
    dirpath: str,
    arr,
    generation: int,
    rule: str,
    fingerprint: Optional[int] = None,
) -> list:
    """Write this process's pieces of a sharded volume (collective call).

    Contract matches :func:`save_sharded`: every process writes exactly
    the boxes assigned to it, process 0 additionally writes the manifest,
    no process ever holds more than its own addressable shards, and the
    caller fences with a barrier before relying on the checkpoint.
    """
    import jax

    os.makedirs(dirpath, exist_ok=True)
    shape = tuple(arr.shape)
    owner = _piece_table3d(arr.sharding, shape)
    me = jax.process_index()
    written = []
    pieces, seen = [], set()
    for shard in arr.addressable_shards:
        b = _box(shard.index, shape)
        if owner[b] != me or b in seen:
            continue
        seen.add(b)
        pieces.append((b, np.asarray(shard.data, np.uint8)))
    arrays = dict(
        boxes=np.asarray([b for b, _ in pieces], np.int64).reshape(-1, 6),
        fps=np.asarray(
            [
                fingerprint3d_np(data, b[0], b[2], b[4], shape[1])
                for b, data in pieces
            ],
            np.uint32,
        ),
    )
    for i, (_, data) in enumerate(pieces):
        arrays[f"piece_{i}"] = data
    path = os.path.join(dirpath, f"shards_{me:05d}.npz")
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)
    written.append(path)
    if me == 0:
        table = sorted(owner.items())
        manifest = dict(
            shape=np.asarray(shape, np.int64),
            generation=np.int64(generation),
            rule=np.asarray(rule),
            boxes=np.asarray(
                [b for b, _ in table], np.int64
            ).reshape(-1, 6),
            procs=np.asarray([p for _, p in table], np.int64),
        )
        if fingerprint is not None:
            manifest["fingerprint"] = np.uint32(fingerprint)
        mpath = os.path.join(dirpath, _MANIFEST)
        tmp = mpath + ".tmp.npz"
        np.savez_compressed(tmp, **manifest)
        os.replace(tmp, mpath)
        written.append(mpath)
    return written


def load_sharded3d_meta(dirpath: str) -> Sharded3DMeta:
    """Read + validate the 3-D manifest: the box cover must tile the
    volume exactly (bounds, total volume, pairwise disjointness), and a
    global stamp must equal the sum of the piece stamps — all without
    assembling any volume data."""
    import zipfile

    try:
        with np.load(os.path.join(dirpath, _MANIFEST)) as data:
            meta = Sharded3DMeta(
                shape=tuple(int(x) for x in data["shape"]),
                generation=int(data["generation"]),
                rule=str(data["rule"]),
                boxes=data["boxes"].copy(),
                procs=data["procs"].copy(),
                fingerprint=(
                    int(data["fingerprint"]) if "fingerprint" in data else None
                ),
            )
    except (KeyError, ValueError, zipfile.BadZipFile) as e:
        raise CorruptSnapshotError(
            f"{dirpath}: not a 3-D sharded checkpoint manifest ({e}); a "
            f"2-D {SHARD_DIR_SUFFIX} directory belongs to the 2-D driver"
        ) from e
    if len(meta.shape) != 3 or meta.boxes.ndim != 2 or meta.boxes.shape[1] != 6:
        raise CorruptSnapshotError(
            f"{dirpath}: malformed 3-D manifest geometry "
            f"(shape {meta.shape}, box table {meta.boxes.shape})"
        )
    d, h, w = meta.shape
    vol = 0
    boxes = []
    for row in meta.boxes:
        d0, d1, r0, r1, c0, c1 = (int(x) for x in row)
        if not (
            0 <= d0 < d1 <= d
            and 0 <= r0 < r1 <= h
            and 0 <= c0 < c1 <= w
        ):
            raise CorruptSnapshotError(
                f"{dirpath}: piece box ({d0},{d1},{r0},{r1},{c0},{c1}) "
                f"falls outside the {d}x{h}x{w} volume; the manifest is "
                "corrupt"
            )
        vol += (d1 - d0) * (r1 - r0) * (c1 - c0)
        boxes.append((d0, d1, r0, r1, c0, c1))
    if vol != d * h * w:
        raise CorruptSnapshotError(
            f"{dirpath}: piece table covers {vol} cells of {d * h * w}; "
            "the manifest is corrupt or incomplete"
        )
    boxes.sort()
    for i, a in enumerate(boxes):
        for b in boxes[i + 1 :]:
            if b[0] >= a[1]:
                break  # sorted by d0: no later box can overlap planes
            if b[2] < a[3] and b[3] > a[2] and b[4] < a[5] and b[5] > a[4]:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece boxes {a} and {b} overlap; the "
                    "manifest is corrupt"
                )
    if meta.fingerprint is not None:
        total = np.uint32(0)
        with np.errstate(over="ignore"):
            for proc in sorted(set(int(p) for p in meta.procs)):
                with np.load(
                    os.path.join(dirpath, f"shards_{proc:05d}.npz")
                ) as sf:
                    total = total + np.sum(
                        sf["fps"].astype(np.uint32), dtype=np.uint32
                    )
        if int(total) != meta.fingerprint:
            raise CorruptSnapshotError(
                f"{dirpath}: piece fingerprints sum to {int(total):#010x} "
                f"!= stamped {meta.fingerprint:#010x}; some shard file is "
                "corrupt"
            )
    return meta


def read_sharded3d_region(
    dirpath: str, meta: Sharded3DMeta, index
) -> np.ndarray:
    """Assemble one box-shaped region from the 3-D piece files.

    ``index`` is a tuple of slices over the global volume (the
    ``jax.make_array_from_callback`` contract); each consulted piece is
    fingerprint-verified once, pieces outside the region never read.
    """
    from gol_tpu.utils.guard import fingerprint_np

    d, h, w = meta.shape
    sl = list(index) + [slice(None)] * (3 - len(index))
    lo = [s.start or 0 for s in sl]
    hi = [
        dim if s.stop is None else s.stop for s, dim in zip(sl, (d, h, w))
    ]
    out = np.empty(
        (hi[0] - lo[0], hi[1] - lo[1], hi[2] - lo[2]), np.uint8
    )
    filled = 0
    by_proc = {}
    try:
        for row, proc in zip(meta.boxes, meta.procs):
            box = tuple(int(x) for x in row)
            inter = [
                (max(box[2 * a], lo[a]), min(box[2 * a + 1], hi[a]))
                for a in range(3)
            ]
            if any(i0 >= i1 for i0, i1 in inter):
                continue
            proc = int(proc)
            if proc not in by_proc:
                by_proc[proc] = np.load(
                    os.path.join(dirpath, f"shards_{proc:05d}.npz")
                )
            sf = by_proc[proc]
            hit = np.nonzero(
                np.all(sf["boxes"] == np.asarray(box, np.int64), axis=1)
            )[0]
            if hit.size != 1:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece {box} missing from "
                    f"shards_{proc:05d}.npz"
                )
            k = int(hit[0])
            data = sf[f"piece_{k}"].astype(np.uint8)
            want = tuple(box[2 * a + 1] - box[2 * a] for a in range(3))
            if data.shape != want:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece {box} has shape {data.shape}, "
                    f"expected {want}"
                )
            stored = int(sf["fps"][k])
            actual = fingerprint3d_np(data, box[0], box[2], box[4], h)
            if stored != actual:
                raise CorruptSnapshotError(
                    f"{dirpath}: piece {box} fingerprint {actual:#010x} "
                    f"!= stored {stored:#010x}; the shard file is corrupt"
                )
            (i0, i1), (j0, j1), (k0, k1) = inter
            out[
                i0 - lo[0] : i1 - lo[0],
                j0 - lo[1] : j1 - lo[1],
                k0 - lo[2] : k1 - lo[2],
            ] = data[
                i0 - box[0] : i1 - box[0],
                j0 - box[2] : j1 - box[2],
                k0 - box[4] : k1 - box[4],
            ]
            filled += (i1 - i0) * (j1 - j0) * (k1 - k0)
    finally:
        for sf in by_proc.values():
            sf.close()
    if filled != out.size:
        raise CorruptSnapshotError(
            f"{dirpath}: region {index} only covered {filled} of "
            f"{out.size} cells"
        )
    return out
