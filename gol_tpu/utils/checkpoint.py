"""Checkpoint / resume — a capability addition over the reference.

The reference's only persistence is the write-only final dump
(gol-main.c:135-139); there is no loader and no mid-run snapshot (SURVEY §5).
Here a run can periodically snapshot the board + generation counter and
resume from any snapshot.  Format: a single ``.npz`` with the board, the
generation, the geometry, and — for reference-compat (stale-halo, bug B1)
runs — the frozen t=0 ghost rows, so a resumed compat run keeps the
*original* halos rather than re-freezing from the resumed board.  Portable
and readable without JAX.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

CKPT_SUFFIX = ".gol.npz"


@dataclasses.dataclass(frozen=True)
class Snapshot:
    board: np.ndarray
    generation: int
    num_ranks: int
    top0: Optional[np.ndarray] = None  # frozen halos, stale_t0 runs only
    bottom0: Optional[np.ndarray] = None


def checkpoint_path(directory: str, generation: int) -> str:
    return os.path.join(directory, f"ckpt_{generation:012d}{CKPT_SUFFIX}")


def save(
    path: str,
    board: np.ndarray,
    generation: int,
    num_ranks: int,
    top0: Optional[np.ndarray] = None,
    bottom0: Optional[np.ndarray] = None,
) -> str:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    arrays = dict(
        board=np.asarray(board, np.uint8),
        generation=np.int64(generation),
        num_ranks=np.int64(num_ranks),
    )
    if top0 is not None:
        arrays["top0"] = np.asarray(top0, np.uint8)
        arrays["bottom0"] = np.asarray(bottom0, np.uint8)
    tmp = path + ".tmp.npz"
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)
    return path


def load(path: str) -> Snapshot:
    with np.load(path) as data:
        return Snapshot(
            board=data["board"].astype(np.uint8),
            generation=int(data["generation"]),
            num_ranks=int(data["num_ranks"]),
            top0=data["top0"].astype(np.uint8) if "top0" in data else None,
            bottom0=data["bottom0"].astype(np.uint8) if "bottom0" in data else None,
        )


def latest(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    ckpts = sorted(
        f
        for f in os.listdir(directory)
        if f.startswith("ckpt_") and f.endswith(CKPT_SUFFIX)
    )
    return os.path.join(directory, ckpts[-1]) if ckpts else None
