"""Utilities: I/O, timing/observability, config validation, native bindings."""
