"""Roofline/MFU attribution for the fused Pallas kernels (VERDICT r2 #4).

The flagship kernels are bitwise VPU programs, so the meaningful
"model-FLOPs-utilization" analog is **lane-ops/s against the VPU's vector
issue peak**: every op processes one int32 word = 32 cells.  This module
owns the arithmetic the benchmarks report: audited per-word op counts for
each kernel, the halo-recompute multiplier of the temporal blocking, and
the peak model.

**Peak model** (documented assumption, not vendor-published): a TPU v5e
TensorCore's VPU is an (8 sublanes × 128 lanes) vector unit with 4
independent ALUs issuing per cycle at the public 940 MHz clock:

    8 * 128 * 4 * 0.94e9 = 3.85e12 int32 lane-ops/s per chip.

Counts treat every emitted vector op (roll/shift/and/or/xor/not/select)
as one issue slot; XLA/Mosaic may fuse some (e.g. and-not) or add
copies, so reported MFU is an estimate good to ~±15%, meant to answer
"which resource binds, and how far from it are we" — not to be a cycle
count.

**Audited op counts** (per 32-cell word, per generation):

2-D B3/S23 kernel (:func:`gol_tpu.ops.pallas_bitlife._one_generation`):
  - horizontal stage, per *extended* row: 2 lane rolls + west (shift,
    shift, mask, or = 4) + east (4) + full-adder (2 xor + 2 and + 1 or
    = 5) = **15**;
  - rule tail, per *output* row: ``_sum3_2bit`` (2 full adders + 4 = 14)
    + ``eq3`` (4) + ``eq4`` (6) + combine (2) = **26**;
  - lane-folded variant (``groups > 1``): + 2 rolls + 2 selects per
    extended row = **19**/15.

3-D Bays-4555 word-tiled kernel
(:func:`gol_tpu.ops.pallas_bitlife3d._one_generation_wt`), per window
word: x stage (2 shifts + 4 + 4 + 5) = 15; count-of-9 (4 lane rolls +
14) = 18; count-of-27 (``_sum3_planes`` width 5: 4 full adders = 20 +
zero-folded ripple ~19) = 39; count-of-26 (``_sub_bit`` over 5 planes,
zero folds) = 13; rule match (B={5}: ~8, S={4,5}: ~17) = 25; combine 5 —
**115** total.

The temporal blocking recomputes halo bands: a tile of ``t`` rows stepped
``k`` generations computes windows of ``t + 2(k-j)`` rows at step ``j``,
so useful output pays a ``(t + k + 1)/t``-ish multiplier (exact sums
below); the 3-D word tiling additionally pays ``(tw + 2)/tw`` on the word
axis and ``(td + 2*pad)/td`` on the plane axis.
"""

from __future__ import annotations

import dataclasses

V5E_VPU_LANE_OPS = 8 * 128 * 4 * 0.94e9  # ~3.85e12 int32 lane-ops/s

# -- XLA-HLO cost model (the static verifier's cross-check) -----------------
#
# ``Compiled.cost_analysis()`` counts elementwise HLO ops as FLOPs, counts
# every ``while`` *body* exactly once (trip counts are dynamic to XLA), and
# counts fusion recompute.  For the depth-1 XLA engines the per-generation
# count is therefore exact and auditable:
#
# - dense step (stencil.step / step_halo_rows): 4 adds (separable 3-row +
#   3-col sums) + 1 subtract + rule (==3, ==2, ==1/alive, and, or, select)
#   = 11 ops/cell (measured exactly: 45056 flops for a 4096-cell shard).
# - packed step (bitlife docstring audit): ~22 bitwise ops per 32-cell
#   word (measured exactly: 11264 flops for 512 words).
# - pack+unpack (byte-staged, counted once per evolve, not per step):
#   ~6.2 ops/cell measured on XLA CPU (weighted byte sums both ways).
#
# Deep-unrolled chunks (halo_depth > 1) and interpret-mode Pallas programs
# are NOT gateable against this model: XLA fuses the unrolled generations
# and its cost analysis counts the recompute inside each fusion, growing
# superlinearly in the unroll factor.  The verifier gates only where the
# model is exact and reports attribution elsewhere.
XLA_DENSE_FLOPS_PER_CELL = 11.0
XLA_PACKED_FLOPS_PER_WORD = 22.0
XLA_PACK_UNPACK_FLOPS_PER_CELL = 6.2
XLA_COST_DRIFT = 2.0  # flagged when measured/model leaves [1/2, 2]


def xla_flops_model(
    engine: str,
    shard_cells: int,
    take: int,
    halo_depth: int,
    sharded: bool = False,
) -> float:
    """Predicted ``cost_analysis()`` FLOPs for one compiled evolve.

    Mirrors XLA's body-counted-once accounting: generations counted =
    one loop body (``halo_depth`` unrolled generations for the blocked
    sharded engines, one for depth-1 loops) plus any remainder tail, all
    over one shard.  Naive-linear in the unroll factor — see the module
    comment for why deeper unrolls under-predict (fusion recompute) and
    are attribution-only.
    """
    if engine in ("bitpack", "pallas_bitpack"):
        words = shard_cells / BITS
        if engine == "pallas_bitpack":
            depth = 8 if halo_depth == 1 else halo_depth
            gens = min(take, depth) + (take % depth if take > depth else 0)
        else:
            gens = min(take, halo_depth) + (
                take % halo_depth if take > halo_depth else 0
            )
        per_word = (
            OPS_2D_HSUM_PER_EXT_ROW + OPS_2D_RULE_PER_OUT_ROW
            if engine == "pallas_bitpack"
            else XLA_PACKED_FLOPS_PER_WORD
        )
        return per_word * words * gens + (
            XLA_PACK_UNPACK_FLOPS_PER_CELL * shard_cells
        )
    # dense tiers (incl. the Pallas dense kernel's interpret mode)
    gens = min(take, halo_depth) + (
        take % halo_depth if take > halo_depth else 0
    )
    if not sharded:
        gens = 1  # single-device fori body is one generation
    return XLA_DENSE_FLOPS_PER_CELL * shard_cells * gens

def xla_bytes_model(engine: str, shard_cells: int) -> float:
    """Predicted I/O bytes of one compiled evolve (argument + output).

    Every engine tier keeps the dense-uint8-in/dense-uint8-out contract,
    so the compiled program's argument+output residency is 2 bytes per
    shard cell regardless of the packed interior (whose word double
    buffer is a *temp*, not an I/O argument).  This is the byte-side
    twin of :func:`xla_flops_model`: ``Compiled.memory_analysis()``'s
    argument/output sizes are gated against it within
    :data:`XLA_COST_DRIFT` (2×) for the dense tier — slack for XLA's
    padding/bookkeeping buffers, tight enough that a dropped donation or
    an accidental widening (uint8 → int32 quadruples it) cannot hide.
    """
    del engine  # one I/O contract across tiers; kept for symmetry
    return 2.0 * shard_cells


# 2-D B3/S23 fused kernel, per word (see module docstring for the audit).
OPS_2D_HSUM_PER_EXT_ROW = 15
OPS_2D_HSUM_PER_EXT_ROW_FOLDED = 19
OPS_2D_RULE_PER_OUT_ROW = 26
# 3-D Bays-4555 word-tiled kernel, per window word per generation.
OPS_3D_WT_PER_WORD = 115

BITS = 32  # cells per packed word


@dataclasses.dataclass(frozen=True)
class Roofline:
    """One kernel configuration's attribution."""

    ops_per_useful_word: float  # incl. halo recompute
    recompute_factor: float  # total windowed work / useful work
    lane_ops_per_sec: float  # at the measured cell rate
    mfu: float  # fraction of V5E_VPU_LANE_OPS

    def as_dict(self) -> dict:
        return {
            "ops_per_useful_word": round(self.ops_per_useful_word, 2),
            "recompute_factor": round(self.recompute_factor, 3),
            "lane_ops_per_sec": float(f"{self.lane_ops_per_sec:.4g}"),
            "mfu": round(self.mfu, 3),
        }


def ops_2d_per_useful_word(tile: int, k: int, folded: bool = False) -> float:
    """Mean emitted ops per useful output word of the 2-D fused kernel.

    A ``tile``-row window stepped ``k`` generations runs the horizontal
    stage over ``tile + 2(k-j)`` rows and the rule tail over two fewer, at
    step ``j``; useful output is ``tile * k`` word-rows.
    """
    h_ops = (
        OPS_2D_HSUM_PER_EXT_ROW_FOLDED if folded else OPS_2D_HSUM_PER_EXT_ROW
    )
    total = 0.0
    for j in range(k):
        window = tile + 2 * (k - j)
        total += window * h_ops + (window - 2) * OPS_2D_RULE_PER_OUT_ROW
    return total / (tile * k)


def recompute_2d(tile: int, k: int) -> float:
    """Windowed rows / useful rows for the 2-D temporal blocking."""
    return sum(tile + 2 * (k - j) for j in range(k)) / (tile * k)


def roofline_2d(
    cells_per_sec: float, tile: int, k: int, folded: bool = False
) -> Roofline:
    ops_word = ops_2d_per_useful_word(tile, k, folded)
    lane_ops = cells_per_sec / BITS * ops_word
    # Same per-row basis as the numerator, so the factor isolates the
    # temporal-blocking recompute and never conflates fold overhead.
    flat = (
        OPS_2D_HSUM_PER_EXT_ROW_FOLDED
        if folded
        else OPS_2D_HSUM_PER_EXT_ROW
    ) + OPS_2D_RULE_PER_OUT_ROW
    return Roofline(
        ops_per_useful_word=ops_word,
        recompute_factor=ops_word / flat,
        lane_ops_per_sec=lane_ops,
        mfu=lane_ops / V5E_VPU_LANE_OPS,
    )


def ops_3d_wt_per_useful_word(tile_d: int, tile_w: int, k: int) -> float:
    """Mean ops per useful word of the 3-D word-tiled kernel.

    Window at step ``j``: ``(tile_w + 2)`` words × ``tile_d + 2(k-j)``
    planes (the shrink runs on the plane axis; the ghost words are carried
    the whole way); useful output ``tile_w * tile_d * k``.
    """
    total = 0.0
    for j in range(k):
        total += (tile_w + 2) * (tile_d + 2 * (k - j)) * OPS_3D_WT_PER_WORD
    return total / (tile_w * tile_d * k)


def roofline_3d_wt(
    cells_per_sec: float, tile_d: int, tile_w: int, k: int
) -> Roofline:
    ops_word = ops_3d_wt_per_useful_word(tile_d, tile_w, k)
    lane_ops = cells_per_sec / BITS * ops_word
    return Roofline(
        ops_per_useful_word=ops_word,
        recompute_factor=ops_word / OPS_3D_WT_PER_WORD,
        lane_ops_per_sec=lane_ops,
        mfu=lane_ops / V5E_VPU_LANE_OPS,
    )


def ops_3d_roll_per_useful_word(tile_d: int, k: int) -> float:
    """Mean ops per useful word of the rolling-plane 3-D kernel.

    Plane-axis windows shrink per generation exactly like the 2-D
    temporal blocking; there is NO word-ghost term — both forms of the
    kernel (torus and band-extended) run at the shard's full x width
    with a local word wrap, which is the whole point of the r4
    restructure (the wt kernel paid ``(tw+2)/tw`` = ×1.5 at 1024³).
    """
    total = 0.0
    for j in range(k):
        total += (tile_d + 2 * (k - j)) * OPS_3D_WT_PER_WORD
    return total / (tile_d * k)


def roofline_3d_roll(
    cells_per_sec: float, tile_d: int, k: int
) -> Roofline:
    ops_word = ops_3d_roll_per_useful_word(tile_d, k)
    lane_ops = cells_per_sec / BITS * ops_word
    return Roofline(
        ops_per_useful_word=ops_word,
        recompute_factor=ops_word / OPS_3D_WT_PER_WORD,
        lane_ops_per_sec=lane_ops,
        mfu=lane_ops / V5E_VPU_LANE_OPS,
    )


def bench_roofline_3d_sharded(cells_per_sec: float, size: int) -> Roofline:
    """Attribution for the sharded 3-D flagship at a cubic volume,
    mirroring the engine's own kernel dispatch and tile derivation
    (``sharded3d.compiled_evolve3d_pallas``'s ``local``)."""
    import inspect

    from gol_tpu.ops import pallas_bitlife3d as p3
    from gol_tpu.parallel import sharded3d

    nw = size // BITS
    # The engine's default halo_depth, read off its signature (like
    # bench_roofline_2d_ring) so the attribution cannot drift from the
    # executed configuration if the default changes.
    pad = inspect.signature(
        sharded3d.compiled_evolve3d_pallas
    ).parameters["halo_depth"].default
    # x-unsharded dispatch (the cubic single-chip/(P,1,1) case this
    # bench claim measures): the rolling kernel with NO word ghosts.
    # (x-sharded shards run the ghost-word rolling form or wt — their
    # attribution is per-shard, not this cubic helper's job.)
    roll = p3.pick_tile3d_roll(size, nw, size, pad)
    if roll >= pad:  # mirror the engine's tile >= pad feasibility gate
        return roofline_3d_roll(cells_per_sec, roll, pad)
    wt = p3.pick_tile3d_wt(size, nw, size, pad)
    if wt is None:
        raise ValueError(
            f"no fused 3-D kernel window at size {size} — nothing to "
            "attribute"
        )
    return roofline_3d_wt(cells_per_sec, wt[0], wt[1], pad)


def bench_roofline_2d(
    cells_per_sec: float, height: int, width: int, steps: int,
    tile_hint: int = 1024,
) -> Roofline:
    """Attribution for ``pallas_bitlife.evolve`` exactly as the benchmark
    runs it, via the engine's own :func:`~gol_tpu.ops.pallas_bitlife.
    blocking_plan` — the reported configuration is the executed one."""
    from gol_tpu.ops import bitlife, pallas_bitlife

    tile, k = pallas_bitlife.blocking_plan(
        height, bitlife.packed_width(width), steps, tile_hint
    )
    return roofline_2d(cells_per_sec, tile, k)


def bench_roofline_2d_ring(
    cells_per_sec: float, height: int, width: int, num_devices: int = 1
) -> Roofline:
    """Attribution for the 1-D sharded ring engine
    (``packed.compiled_evolve_packed_pallas``) at its defaults, read off
    the engine's own signature, with the engine's shard-height and
    lane-fold tile derivation mirrored (packed.py ``local``)."""
    import inspect

    from gol_tpu.ops import bitlife, pallas_bitlife
    from gol_tpu.parallel import packed

    sig = inspect.signature(packed.compiled_evolve_packed_pallas)
    k = sig.parameters["halo_depth"].default
    hint = sig.parameters["tile_hint"].default
    nw = bitlife.packed_width(width)  # 1-D ring: width unsharded
    shard_h = height // num_devices
    fold = pallas_bitlife.fold_factor(nw)
    folded = fold > 1
    if folded and shard_h % (fold * 8):
        # Mirror the engine's rejection: attributing an unfoldable
        # geometry would describe a configuration that cannot run.
        raise ValueError(
            f"shard height {shard_h} is not divisible by {fold * 8}; the "
            f"ring engine cannot lane-fold this geometry (nw={nw})"
        )
    if folded:
        tile = pallas_bitlife.pick_tile(shard_h // fold, fold * nw, hint)
    else:
        tile = pallas_bitlife.pick_tile(shard_h, nw, hint)
    return roofline_2d(cells_per_sec, tile, k, folded)
