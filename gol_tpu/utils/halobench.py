"""Halo-exchange latency measurement (SURVEY §5 observability).

The reference's only performance artifact is one end-to-end wall-clock
line (gol-main.c:121-125); it cannot attribute time to communication vs
compute.  This tool times three compiled programs on the live mesh:

- ``exchange``: ``steps`` back-to-back halo exchanges alone (the ppermute
  ring traffic, nothing else) — the TPU analog of timing the reference's
  ``MPI_Irecv``/``Isend``/``Wait`` block;
- ``step``: the full exchange+stencil generation loop;
- ``stencil``: the halo-free torus stencil loop (pure compute ceiling).

``step - stencil`` estimates the *exposed* (non-overlapped) exchange cost
per generation; ``exchange`` bounds the raw ring latency.  All loops run
inside single compiled programs so host round-trips don't pollute the
numbers.

Run as a module for a JSON report:
``python -m gol_tpu.utils.halobench [size] [steps] [mesh {1d,2d}]
[engine {dense,bitpack,pallas,pallas_overlap}]``.  The sharded 3-D
flagship has its own mode (:func:`measure3d`):
``python -m gol_tpu.utils.halobench DxHxW steps 3d:P,R,C``.
"""

from __future__ import annotations

import functools
import json
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.ops import stencil
from gol_tpu.parallel import sharded
from gol_tpu.parallel.mesh import COLS, PLANES, ROWS, board_sharding
from gol_tpu.utils.timing import time_best


@functools.lru_cache(maxsize=32)
def _exchange_only(mesh: Mesh, steps: int):
    """jit: `steps` chained halo exchanges, no stencil.

    Each iteration folds the received halos back into the block's
    *boundary rows/columns only* — O(boundary) work, so the loop has a
    genuine data dependency (the next exchange ships the just-modified
    edges, XLA cannot elide the ppermutes) while ``exchange_s`` measures
    ring traffic + launch and nothing else.  The previous fold added the
    halos across the whole block, a full-board HBM pass per iteration
    that at 16384² made "exchange alone" read 3× the full step.
    """
    from gol_tpu.parallel.halo import ring

    two_d = COLS in mesh.axis_names
    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)

    if two_d:

        def body(_, blk):
            # Two-phase edge exchange hand-rolled at O(boundary): phase 2
            # ships the *phase-1-folded* edge columns, so the corner
            # two-hop chain is live and none of the four ppermutes is
            # dead code.  (exchange_block_halos itself concatenates a
            # full [h+2, w+2] extension — a whole-board copy the real
            # engines amortize over a k-deep chunk, which an
            # exchange-ONLY loop must not pay per iteration.)
            top, bottom = sharded.exchange_row_halos(blk, num_rows)
            blk = blk.at[0].add(top).at[-1].add(bottom)
            left = lax.ppermute(blk[:, -1:], COLS, ring(num_cols, 1))
            right = lax.ppermute(blk[:, :1], COLS, ring(num_cols, -1))
            return blk.at[:, :1].add(left).at[:, -1:].add(right)

        spec = P(ROWS, COLS)
    else:

        def body(_, blk):
            top, bottom = sharded.exchange_row_halos(blk, num_rows)
            return blk.at[0].add(top).at[-1].add(bottom)

        spec = P(ROWS, None)

    local = compat.shard_map(
        lambda b: lax.fori_loop(0, steps, body, b),
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
    )
    return jax.jit(local)


def _time(fn, arg, repeats: int = 3) -> float:
    """Shared warm best-of-N timer; the lambdas passed here copy their own
    donated inputs, so the same ``arg`` is safe for every repeat."""
    return time_best(fn, lambda: arg, repeats)


ENGINES = ("dense", "bitpack", "pallas", "pallas_overlap")


def measure(
    mesh: Mesh, size, steps: int = 100, engine: str = "dense"
) -> Dict[str, float]:
    """Per-generation seconds for exchange-only / full step / pure stencil.

    ``size`` is a square side or an ``(h, w)`` pair — rectangular boards
    reach the lane-folded narrow-shard geometries (e.g. the 16×16-pod
    config-3 shard, 16384×1024) whose exchange-vs-compute split is
    exactly where the folded overlap story lives.

    ``stencil_s`` is the pure-compute ceiling: the torus stencil on an
    *unsharded single-device* board of one shard's dimensions (what each
    device computes per generation, minus all communication).  Handing the
    sharded global board to ``stencil.run`` would instead compile an
    auto-SPMD program whose rolls insert their own collectives.

    ``engine="bitpack"`` attributes the packed ring engine instead: the
    full step is :func:`gol_tpu.parallel.packed.compiled_evolve_packed`
    (packed-word halos — 8× less wire) and the compute ceiling the packed
    single-device evolve; ``exchange_s`` still times dense-row ppermutes,
    an upper bound on the packed exchange's wire time.

    ``engine="pallas"`` / ``"pallas_overlap"`` attribute the flagship
    sharded Pallas engine's serial and comm/compute-overlap forms
    (:func:`gol_tpu.parallel.packed.compiled_evolve_packed_pallas`); the
    compute ceiling is the single-device fused-kernel evolve.  Comparing
    the two engines' ``exposed_exchange_s`` (same mesh, same size) measures
    exactly what the overlap form hides.  ``steps`` should be a multiple of
    8 (the band depth) so no jnp remainder tail pollutes the attribution.

    Returns ``{"exchange_s": ..., "step_s": ..., "stencil_s": ...,
    "exposed_exchange_s": ...}``, all per generation.
    """
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; expected {ENGINES}")
    h, w = (size, size) if isinstance(size, int) else size
    rng = np.random.default_rng(0)
    board_np = (rng.random((h, w)) < 0.35).astype(np.uint8)
    board = jax.device_put(jnp.asarray(board_np), board_sharding(mesh))
    t_exch = _time(_exchange_only(mesh, steps), board) / steps
    if engine in ("pallas", "pallas_overlap"):
        from gol_tpu.parallel import packed as packed_mod

        packed_mod.validate_packed_geometry(board.shape, mesh)
        step_fn = packed_mod.compiled_evolve_packed_pallas(
            mesh, steps, overlap=engine == "pallas_overlap"
        )
    elif engine == "bitpack":
        from gol_tpu.parallel import packed as packed_mod

        packed_mod.validate_packed_geometry(board.shape, mesh)
        step_fn = packed_mod.compiled_evolve_packed(mesh, steps)
    else:
        step_fn = sharded.compiled_evolve(mesh, steps, "explicit", 1)
    t_step = (
        _time(lambda b: step_fn(jnp.array(b, copy=True)), board) / steps
    )
    local_h = h // mesh.shape[ROWS]
    local_w = w // mesh.shape.get(COLS, 1)
    shard = jax.device_put(
        jnp.asarray(board_np[:local_h, :local_w]),
        mesh.devices.ravel()[0],
    )
    ceiling_note = None
    if engine in ("pallas", "pallas_overlap"):
        from gol_tpu.ops import bitlife, pallas_bitlife

        fold = pallas_bitlife.fold_factor(bitlife.packed_width(local_w))
        if fold == 1 or jax.default_backend() != "tpu":
            sten_fn = lambda b: pallas_bitlife.evolve(b, steps)
        else:
            # Narrow (lane-folded) shard: no bare-kernel program exists
            # at this width (folding is the whole point), so the compute
            # ceiling is the serial folded engine on a 1-ring — the
            # closest pure-compute proxy.  Degenerate caveat, flagged in
            # the output: when the measurement mesh IS that 1-ring, the
            # serial proxy is the identical compiled program and the
            # subtraction reads noise, not exchange exposure (for the
            # overlap engine it reads overlap-over-serial overhead).
            from gol_tpu.parallel import mesh as mesh_mod
            from gol_tpu.parallel import packed as packed_mod

            ring1 = mesh_mod.make_mesh_1d(
                devices=[mesh.devices.ravel()[0]]
            )
            fold_fn = packed_mod.compiled_evolve_packed_pallas(
                ring1, steps
            )
            sten_fn = lambda b: fold_fn(b)
            if mesh.devices.size == 1:
                # Any one-device mesh (1-D 1-ring OR a (1,1) 2-D mesh) is
                # equally degenerate: the proxy is the same program.
                ceiling_note = (
                    "folded 1-ring proxy equals the measured step "
                    "program on a 1-device mesh: exposed_exchange_s is "
                    "definitional noise (serial engine) or "
                    "overlap-over-serial overhead (overlap engine), NOT "
                    "exchange exposure"
                )
            elif engine == "pallas_overlap":
                ceiling_note = (
                    "ceiling is the SERIAL folded 1-ring engine; "
                    "exposed_exchange_s mixes exchange exposure with the "
                    "overlap form's reassembly overhead"
                )
    elif engine == "bitpack":
        from gol_tpu.ops import bitlife

        sten_fn = lambda b: bitlife.evolve_dense_io(b, steps)
    else:
        sten_fn = lambda b: stencil.run(b, steps)
    t_sten = (
        _time(lambda b: sten_fn(jnp.array(b, copy=True)), shard) / steps
    )
    out = {
        "exchange_s": t_exch,
        "step_s": t_step,
        "stencil_s": t_sten,
        "exposed_exchange_s": max(0.0, t_step - t_sten),
    }
    if ceiling_note is not None:
        out["ceiling_note"] = ceiling_note
    return out


SWEEP_MODES = ("explicit", "overlap", "pipeline")


def measure_depth_sweep(
    mesh: Mesh,
    size,
    steps: int,
    engine: str,
    depths,
    modes=SWEEP_MODES,
) -> Dict[str, dict]:
    """The k-vs-MFU curve the pipelined halo engine exists for (PR 9).

    For every (shard mode, halo depth k) cell, times the FULL sharded
    chunk program — explicit serial chunks, the depth-k interior/boundary
    overlap split, or the cross-chunk pipelined double buffer — and
    reports per-generation seconds, cell-updates/s, and the VPU-roofline
    fraction (``telemetry.roofline_utilization`` over the same
    ``xla_flops_model`` the chunk telemetry uses, so the sweep's MFU
    column and a run's v8 ``halo`` block share one model).  Every row is
    written only after a bit-equality receipt against the explicit
    depth-1 program on the same board (the sparsebench discipline: a
    fast wrong program must not enter an artifact).  Cells the engine
    rejects (k beyond the shard extent, non-8-multiple Pallas depths)
    become ``{"skipped": reason}`` rows — visible, never silently
    dropped.

    ``engine``: ``dense`` | ``bitpack`` | ``pallas`` (the fused sharded
    Pallas engine; its depth quantum is 8, so k=1 measures the default
    8-deep band and non-multiples of 8 skip).
    """
    from gol_tpu import telemetry as telemetry_mod
    from gol_tpu.parallel import packed as packed_mod

    if engine not in ("dense", "bitpack", "pallas"):
        raise ValueError(
            f"sweep engine {engine!r}: expected dense/bitpack/pallas"
        )
    h, w = (size, size) if isinstance(size, int) else size
    rng = np.random.default_rng(0)
    board_np = (rng.random((h, w)) < 0.35).astype(np.uint8)
    place = lambda: jax.device_put(
        jnp.asarray(board_np), board_sharding(mesh)
    )
    devices = mesh.devices.size
    shard_cells = (h * w) // devices
    model_engine = {"pallas": "pallas_bitpack"}.get(engine, engine)

    def build(mode: str, k: int):
        if engine == "pallas":
            depth = 8 if k == 1 else k
            if depth % 8:
                raise ValueError(
                    "the sharded Pallas engine needs halo_depth to be a "
                    f"multiple of 8, got {k}"
                )
            packed_mod.validate_packed_geometry((h, w), mesh)
            return depth, packed_mod.compiled_evolve_packed_pallas(
                mesh,
                steps,
                halo_depth=depth,
                overlap=mode == "overlap",
                pipeline=mode == "pipeline",
            )
        if engine == "bitpack":
            packed_mod.validate_packed_geometry((h, w), mesh)
            return k, packed_mod.compiled_evolve_packed(
                mesh, steps, k, mode=mode
            )
        return k, sharded.compiled_evolve(mesh, steps, mode, k)

    _, ref_fn = build("explicit", 1)
    ref = np.asarray(ref_fn(place()))
    out: Dict[str, dict] = {}
    for mode in modes:
        for k in depths:
            name = f"{engine}_{mode}_k{k}"
            try:
                depth, fn = build(mode, k)
                got = np.asarray(fn(place()))
                if not np.array_equal(got, ref):
                    raise AssertionError(
                        "bit-equality receipt FAILED vs explicit depth-1"
                    )
            except (ValueError, AssertionError) as e:
                out[name] = {"skipped": str(e).splitlines()[0]}
                continue
            t_gen = _time(lambda b: fn(jnp.array(b, copy=True)), place()) / steps
            mfu = telemetry_mod.roofline_utilization(
                model_engine, shard_cells, steps, depth, True,
                t_gen * steps,
            )
            out[name] = {
                "step_s": t_gen,
                "updates_per_sec": (h * w) / t_gen,
                "mfu": mfu,
                "halo_depth": depth,
                "shard_mode": mode,
                "bit_equal_explicit_k1": True,
            }
    return out


@functools.lru_cache(maxsize=32)
def _exchange_only_3d(mesh: Mesh, steps: int):
    """jit: ``steps`` chained exchanges of the 3-D flagship's own wire
    quanta, no stencil, O(face) per iteration.

    Mirrors :func:`gol_tpu.parallel.sharded3d.compiled_evolve3d_pallas`'s
    two-ring structure in its packed plane-leading ``[band, nw, lanes]``
    layout: per iteration, one packed *band plane* rides the banded
    spatial ring and one packed *ghost word column* per side rides the
    COLS ring (4 ppermutes; the third volume axis is the kernel's lane
    axis, which the engine's mesh constraint leaves unsharded — there is
    nothing to exchange on it).  This is a tight upper bound on the
    engine's per-generation wire: the engine ships ``pad``-deep bands
    once per ``pad`` generations (same band bytes/generation) and its
    ghost columns only once per chunk.

    Anti-DCE state is four *face accumulators* — the packed volume stays
    loop-invariant, each shipped face mixes in the previously received
    one (and the column phase mixes a sliver of the just-received band
    plane, sequencing the phases like the real corner two-hop), and the
    accumulators fold into the output's boundary once after the loop.
    Two measured dead ends this loop must not repeat (r5, real chip at
    512³): in-loop ``vol.at[...].add`` chains — XLA copies the volume,
    2.1 ms/gen — and *dense* uint8 faces, whose minor-axis slicing
    relayouts at ~0.94 ms/gen; the packed-layout faces cost ~34 µs/gen.
    """
    from gol_tpu.ops import bitlife3d
    from gol_tpu.parallel.halo import ring

    np_ = mesh.shape.get(PLANES, 1)
    nr = mesh.shape.get(ROWS, 1)
    nc = mesh.shape.get(COLS, 1)
    if np_ != 1 and nr != 1:
        raise ValueError(
            "the 3-D exchange harness mirrors the fused engine's mesh "
            "constraint: planes or rows axis must be size 1, got "
            f"{dict(mesh.shape)}"
        )
    band_over_planes = nr == 1
    band_axis_name = PLANES if band_over_planes else ROWS
    band_ring = np_ if band_over_planes else nr

    def local(vol):
        p3 = bitlife3d.pack3d(vol)  # [d, h, nw]
        p = p3.transpose((0, 2, 1) if band_over_planes else (1, 2, 0))

        def body(_, c):
            ctop, cbot, cw, ce = c
            top = lax.ppermute(
                p[-1] + ctop, band_axis_name, ring(band_ring, 1)
            )
            bot = lax.ppermute(
                p[0] + cbot, band_axis_name, ring(band_ring, -1)
            )
            west = lax.ppermute(
                p[:, -1] + cw + top[-1:, :], COLS, ring(nc, 1)
            )
            east = lax.ppermute(
                p[:, 0] + ce + bot[:1, :], COLS, ring(nc, -1)
            )
            return (top, bot, west, east)

        c0 = (p[-1] * 0, p[0] * 0, p[:, -1] * 0, p[:, 0] * 0)
        ctop, cbot, cw, ce = lax.fori_loop(0, steps, body, c0)
        # One post-loop boundary fold keeps every accumulator live.
        p = p.at[0].add(ctop).at[-1].add(cbot)
        p = p.at[:, 0].add(cw).at[:, -1].add(ce)
        p3 = p.transpose((0, 2, 1) if band_over_planes else (2, 0, 1))
        return bitlife3d.unpack3d(p3)

    spec = P(PLANES, ROWS, COLS)
    local_sharded = compat.shard_map(
        local, mesh=mesh, in_specs=spec, out_specs=spec
    )
    return jax.jit(local_sharded)


def measure3d(mesh: Mesh, size, steps: int = 64) -> Dict[str, float]:
    """Per-generation attribution for the sharded 3-D flagship
    (:func:`gol_tpu.parallel.sharded3d.compiled_evolve3d_pallas`) — the
    band + ghost-word-column exchange structure the 2-D sections cannot
    see (VERDICT r4 #4).

    ``size`` is a cube side or a ``(d, h, w)`` triple.  Columns mirror
    :func:`measure`: ``exchange_s`` times the engine's own exchange
    quanta — one packed band plane on the banded ring + one packed ghost
    word column per side on the COLS ring, per generation (4 ppermutes,
    O(face) accumulator folds; a tight upper bound on the fused engine's
    per-generation wire, see :func:`_exchange_only_3d`); ``step_s`` the
    full fused sharded program; ``stencil_s`` the single-device
    fused-kernel evolve at one shard's dimensions (pure compute ceiling,
    no exchange, whatever kernel form the dispatch picks there);
    ``exposed_exchange_s`` their difference.  ``steps`` should be a multiple of 8 (the band depth) so
    no per-step jnp remainder tail pollutes the attribution.  On a
    one-device mesh the subtraction reads the chunk/ring machinery's
    overhead, not exchange exposure — flagged in ``ceiling_note``.
    """
    from gol_tpu.ops import pallas_bitlife3d
    from gol_tpu.parallel import sharded3d
    from gol_tpu.parallel.sharded3d import volume_sharding

    d, h, w = (size, size, size) if isinstance(size, int) else size
    rng = np.random.default_rng(0)
    vol_np = (rng.random((d, h, w)) < 0.3).astype(np.uint8)
    vol = jax.device_put(jnp.asarray(vol_np), volume_sharding(mesh))
    t_exch = _time(_exchange_only_3d(mesh, steps), vol) / steps
    step_fn = sharded3d.compiled_evolve3d_pallas(mesh, steps)
    t_step = (
        _time(lambda v: step_fn(jnp.array(v, copy=True)), vol) / steps
    )
    ld = d // mesh.shape.get(PLANES, 1)
    lh = h // mesh.shape.get(ROWS, 1)
    lw = w // mesh.shape.get(COLS, 1)
    shard = jax.device_put(
        jnp.asarray(vol_np[:ld, :lh, :lw]), mesh.devices.ravel()[0]
    )
    sten_fn = lambda v: pallas_bitlife3d.evolve3d(v, steps)
    t_sten = (
        _time(lambda v: sten_fn(jnp.array(v, copy=True)), shard) / steps
    )
    out = {
        "exchange_s": t_exch,
        "step_s": t_step,
        "stencil_s": t_sten,
        "exposed_exchange_s": max(0.0, t_step - t_sten),
    }
    if mesh.devices.size == 1:
        out["ceiling_note"] = (
            "one-device mesh: every ppermute is a self-copy, so "
            "exposed_exchange_s reads the sharded program's chunk/ring "
            "machinery overhead over the bare kernel, NOT exchange "
            "exposure"
        )
    return out


def main(argv=None) -> None:
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    # Optional flags, peeled off before the positional surface so the
    # published CLI is unchanged: the structured-telemetry sink
    # (docs/OBSERVABILITY.md) and the depth sweep (PR 9): a comma list of
    # halo depths swept per shard mode, emitting the k-vs-MFU curve.
    telemetry_dir = run_id = sweep_depths = None
    for flag in ("--telemetry", "--run-id", "--halo-depth-sweep"):
        if flag in args:
            k = args.index(flag)
            value = args[k + 1]
            del args[k : k + 2]
            if flag == "--telemetry":
                telemetry_dir = value
            elif flag == "--run-id":
                run_id = value
            else:
                sweep_depths = [int(v) for v in value.split(",")]
    if len(args) > 0 and "x" in args[0]:
        parts = tuple(int(v) for v in args[0].split("x"))
        size = parts if len(parts) > 1 else parts[0]
    else:
        size = int(args[0]) if len(args) > 0 else 4096
    steps = int(args[1]) if len(args) > 1 else 100
    kind = args[2] if len(args) > 2 else "1d"
    engine = args[3] if len(args) > 3 else "dense"

    from gol_tpu.parallel import mesh as mesh_mod

    if kind.startswith("3d"):
        # 3-D flagship attribution: mesh shape after a colon selects the
        # decomposition AND band orientation ("3d:4,1,2" bands over the
        # PLANES ring, "3d:1,4,2" the transposed ROWS-banded layout);
        # bare "3d" is the one-device ring.
        pshape = (
            tuple(int(v) for v in kind.split(":", 1)[1].split(","))
            if ":" in kind
            else (1, 1, 1)
        )
        n = pshape[0] * pshape[1] * pshape[2]
        mesh = mesh_mod.make_mesh_3d(pshape, devices=jax.devices()[:n])
        out = measure3d(mesh, size, steps)
        engine = "pallas3d"
    else:
        # "1d:N" / "2d:R,C" pin the device count (a sweep wants shard
        # extents that admit its deepest band); bare kinds keep the
        # all-devices default.
        if ":" in kind:
            base, spec_s = kind.split(":", 1)
            if base == "1d":
                n = int(spec_s)
                mesh = mesh_mod.make_mesh_1d(n, devices=jax.devices()[:n])
            else:
                r, c = (int(v) for v in spec_s.split(","))
                mesh = mesh_mod.make_mesh_2d(
                    (r, c), devices=jax.devices()[: r * c]
                )
        else:
            mesh = (
                mesh_mod.make_mesh_2d()
                if kind == "2d"
                else mesh_mod.make_mesh_1d()
            )
        if sweep_depths is not None:
            from gol_tpu.telemetry import ledger as ledger_mod

            out = {
                "header": ledger_mod.artifact_header("halobench"),
                "note": (
                    "k-vs-MFU sweep of the ring chunk forms (PR 9): "
                    "step_s/updates_per_sec/mfu per (shard_mode, "
                    "halo_depth), every row bit-equality-receipted "
                    "against the explicit depth-1 program on the same "
                    "board before timing; rejected cells appear as "
                    "skipped rows. mfu shares xla_flops_model with the "
                    "v8 chunk telemetry."
                    + (
                        " THIS CAPTURE IS CPU (virtual-device ring): "
                        "curve SHAPE only — CPU cores timeshare, so "
                        "exchange latency is not the bottleneck the "
                        "pipeline hides and absolute MFU is "
                        "meaningless. TPU headline command: python -m "
                        "gol_tpu.utils.halobench 16384 8192 1d "
                        "dense,bitpack,pallas --halo-depth-sweep "
                        "1,2,4,8,16 (and 2d:4,2 for the pod "
                        "decomposition)."
                        if jax.default_backend() != "tpu"
                        else ""
                    )
                ),
            }
            # The engine positional accepts a comma list here so one
            # invocation (one reproducible argv) captures the whole
            # artifact.
            kind_key = kind.replace(":", "x").replace(",", "x")
            for eng in engine.split(","):
                out.update(
                    {
                        f"{jax.default_backend()}_mesh{kind_key}_{key}": body
                        for key, body in measure_depth_sweep(
                            mesh, size, steps, eng, sweep_depths
                        ).items()
                    }
                )
            out.update(
                {
                    "size": list(size) if isinstance(size, tuple) else size,
                    "steps": steps,
                    "mesh": dict(mesh.shape),
                    "devices": len(mesh.devices.ravel()),
                    "depths": sweep_depths,
                }
            )
            print(json.dumps(out, indent=1))
            if telemetry_dir:
                from gol_tpu import telemetry as telemetry_mod

                with telemetry_mod.EventLog(
                    telemetry_dir, run_id=run_id
                ) as ev:
                    ev.run_header(
                        dict(tool="halobench", sweep=True, kind=kind)
                    )
                    for key, body in out.items():
                        if isinstance(body, dict) and "step_s" in body:
                            ev.bench_row("halobench", {**body, "name": key})
            return
        out = measure(mesh, size, steps, engine)
    from gol_tpu.telemetry import ledger as ledger_mod

    out.update(
        {
            "size": list(size) if isinstance(size, tuple) else size,
            "steps": steps,
            "mesh": dict(mesh.shape),
            "devices": len(mesh.devices.ravel()),
            "engine": engine,
            # Satellite (PR 9): the module emitter stamps the common
            # header too, so a bare `python -m gol_tpu.utils.halobench`
            # capture ingests with zero sniffing like capture_artifacts'.
            "header": ledger_mod.artifact_header("halobench"),
        }
    )
    print(json.dumps(out))
    if telemetry_dir:
        from gol_tpu import telemetry as telemetry_mod

        with telemetry_mod.EventLog(telemetry_dir, run_id=run_id) as ev:
            ev.run_header(dict(tool="halobench", engine=engine, kind=kind))
            ev.bench_row("halobench", out)


if __name__ == "__main__":
    main()
