"""Halo-exchange latency measurement (SURVEY §5 observability).

The reference's only performance artifact is one end-to-end wall-clock
line (gol-main.c:121-125); it cannot attribute time to communication vs
compute.  This tool times three compiled programs on the live mesh:

- ``exchange``: ``steps`` back-to-back halo exchanges alone (the ppermute
  ring traffic, nothing else) — the TPU analog of timing the reference's
  ``MPI_Irecv``/``Isend``/``Wait`` block;
- ``step``: the full exchange+stencil generation loop;
- ``stencil``: the halo-free torus stencil loop (pure compute ceiling).

``step - stencil`` estimates the *exposed* (non-overlapped) exchange cost
per generation; ``exchange`` bounds the raw ring latency.  All loops run
inside single compiled programs so host round-trips don't pollute the
numbers.

Run as a module for a JSON report:
``python -m gol_tpu.utils.halobench [size] [steps] [mesh {1d,2d}]
[engine {dense,bitpack,pallas,pallas_overlap}]``.
"""

from __future__ import annotations

import functools
import json
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from gol_tpu.ops import stencil
from gol_tpu.parallel import sharded
from gol_tpu.parallel.mesh import COLS, ROWS, board_sharding
from gol_tpu.utils.timing import time_best


@functools.lru_cache(maxsize=32)
def _exchange_only(mesh: Mesh, steps: int):
    """jit: `steps` chained halo exchanges, no stencil.

    Each iteration folds the received halos back into the block (one add)
    so the loop has a genuine data dependency and XLA cannot elide the
    ppermutes.
    """
    two_d = COLS in mesh.axis_names
    num_rows = mesh.shape[ROWS]
    num_cols = mesh.shape.get(COLS, 1)

    if two_d:

        def body(_, blk):
            ext = sharded.exchange_block_halos(blk, num_rows, num_cols)
            # Fold in all four ghost sides so none of the four ppermutes
            # (both phases) is dead code.
            return (
                blk
                + ext[0, 1:-1]
                + ext[-1, 1:-1]
                + ext[1:-1, 0][:, None]
                + ext[1:-1, -1][:, None]
            )

        spec = P(ROWS, COLS)
    else:

        def body(_, blk):
            top, bottom = sharded.exchange_row_halos(blk, num_rows)
            return blk + top + bottom

        spec = P(ROWS, None)

    local = jax.shard_map(
        lambda b: lax.fori_loop(0, steps, body, b),
        mesh=mesh,
        in_specs=spec,
        out_specs=spec,
    )
    return jax.jit(local)


def _time(fn, arg, repeats: int = 3) -> float:
    """Shared warm best-of-N timer; the lambdas passed here copy their own
    donated inputs, so the same ``arg`` is safe for every repeat."""
    return time_best(fn, lambda: arg, repeats)


ENGINES = ("dense", "bitpack", "pallas", "pallas_overlap")


def measure(
    mesh: Mesh, size, steps: int = 100, engine: str = "dense"
) -> Dict[str, float]:
    """Per-generation seconds for exchange-only / full step / pure stencil.

    ``size`` is a square side or an ``(h, w)`` pair — rectangular boards
    reach the lane-folded narrow-shard geometries (e.g. the 16×16-pod
    config-3 shard, 16384×1024) whose exchange-vs-compute split is
    exactly where the folded overlap story lives.

    ``stencil_s`` is the pure-compute ceiling: the torus stencil on an
    *unsharded single-device* board of one shard's dimensions (what each
    device computes per generation, minus all communication).  Handing the
    sharded global board to ``stencil.run`` would instead compile an
    auto-SPMD program whose rolls insert their own collectives.

    ``engine="bitpack"`` attributes the packed ring engine instead: the
    full step is :func:`gol_tpu.parallel.packed.compiled_evolve_packed`
    (packed-word halos — 8× less wire) and the compute ceiling the packed
    single-device evolve; ``exchange_s`` still times dense-row ppermutes,
    an upper bound on the packed exchange's wire time.

    ``engine="pallas"`` / ``"pallas_overlap"`` attribute the flagship
    sharded Pallas engine's serial and comm/compute-overlap forms
    (:func:`gol_tpu.parallel.packed.compiled_evolve_packed_pallas`); the
    compute ceiling is the single-device fused-kernel evolve.  Comparing
    the two engines' ``exposed_exchange_s`` (same mesh, same size) measures
    exactly what the overlap form hides.  ``steps`` should be a multiple of
    8 (the band depth) so no jnp remainder tail pollutes the attribution.

    Returns ``{"exchange_s": ..., "step_s": ..., "stencil_s": ...,
    "exposed_exchange_s": ...}``, all per generation.
    """
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; expected {ENGINES}")
    h, w = (size, size) if isinstance(size, int) else size
    rng = np.random.default_rng(0)
    board_np = (rng.random((h, w)) < 0.35).astype(np.uint8)
    board = jax.device_put(jnp.asarray(board_np), board_sharding(mesh))
    t_exch = _time(_exchange_only(mesh, steps), board) / steps
    if engine in ("pallas", "pallas_overlap"):
        from gol_tpu.parallel import packed as packed_mod

        packed_mod.validate_packed_geometry(board.shape, mesh)
        step_fn = packed_mod.compiled_evolve_packed_pallas(
            mesh, steps, overlap=engine == "pallas_overlap"
        )
    elif engine == "bitpack":
        from gol_tpu.parallel import packed as packed_mod

        packed_mod.validate_packed_geometry(board.shape, mesh)
        step_fn = packed_mod.compiled_evolve_packed(mesh, steps)
    else:
        step_fn = sharded.compiled_evolve(mesh, steps, "explicit", 1)
    t_step = (
        _time(lambda b: step_fn(jnp.array(b, copy=True)), board) / steps
    )
    local_h = h // mesh.shape[ROWS]
    local_w = w // mesh.shape.get(COLS, 1)
    shard = jax.device_put(
        jnp.asarray(board_np[:local_h, :local_w]),
        mesh.devices.ravel()[0],
    )
    ceiling_note = None
    if engine in ("pallas", "pallas_overlap"):
        from gol_tpu.ops import bitlife, pallas_bitlife

        fold = pallas_bitlife.fold_factor(bitlife.packed_width(local_w))
        if fold == 1 or jax.default_backend() != "tpu":
            sten_fn = lambda b: pallas_bitlife.evolve(b, steps)
        else:
            # Narrow (lane-folded) shard: no bare-kernel program exists
            # at this width (folding is the whole point), so the compute
            # ceiling is the serial folded engine on a 1-ring — the
            # closest pure-compute proxy.  Degenerate caveat, flagged in
            # the output: when the measurement mesh IS that 1-ring, the
            # serial proxy is the identical compiled program and the
            # subtraction reads noise, not exchange exposure (for the
            # overlap engine it reads overlap-over-serial overhead).
            from gol_tpu.parallel import mesh as mesh_mod
            from gol_tpu.parallel import packed as packed_mod

            ring1 = mesh_mod.make_mesh_1d(
                devices=[mesh.devices.ravel()[0]]
            )
            fold_fn = packed_mod.compiled_evolve_packed_pallas(
                ring1, steps
            )
            sten_fn = lambda b: fold_fn(b)
            if ring1 == mesh:
                ceiling_note = (
                    "folded 1-ring proxy equals the measured step "
                    "program on a 1-device mesh: exposed_exchange_s is "
                    "definitional noise (serial engine) or "
                    "overlap-over-serial overhead (overlap engine), NOT "
                    "exchange exposure"
                )
            elif engine == "pallas_overlap":
                ceiling_note = (
                    "ceiling is the SERIAL folded 1-ring engine; "
                    "exposed_exchange_s mixes exchange exposure with the "
                    "overlap form's reassembly overhead"
                )
    elif engine == "bitpack":
        from gol_tpu.ops import bitlife

        sten_fn = lambda b: bitlife.evolve_dense_io(b, steps)
    else:
        sten_fn = lambda b: stencil.run(b, steps)
    t_sten = (
        _time(lambda b: sten_fn(jnp.array(b, copy=True)), shard) / steps
    )
    out = {
        "exchange_s": t_exch,
        "step_s": t_step,
        "stencil_s": t_sten,
        "exposed_exchange_s": max(0.0, t_step - t_sten),
    }
    if ceiling_note is not None:
        out["ceiling_note"] = ceiling_note
    return out


def main(argv=None) -> None:
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    if len(args) > 0 and "x" in args[0]:
        hh, ww = args[0].split("x")
        size = (int(hh), int(ww))
    else:
        size = int(args[0]) if len(args) > 0 else 4096
    steps = int(args[1]) if len(args) > 1 else 100
    kind = args[2] if len(args) > 2 else "1d"
    engine = args[3] if len(args) > 3 else "dense"

    from gol_tpu.parallel import mesh as mesh_mod

    mesh = (
        mesh_mod.make_mesh_2d() if kind == "2d" else mesh_mod.make_mesh_1d()
    )
    out = measure(mesh, size, steps, engine)
    out.update(
        {
            "size": size,
            "steps": steps,
            "mesh": dict(mesh.shape),
            "devices": len(mesh.devices.ravel()),
            "engine": engine,
        }
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
