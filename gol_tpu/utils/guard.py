"""Failure detection + elastic recovery — a capability addition (SURVEY §5).

The reference has no failure handling at all: errors exit the process, and
silent data corruption (SDC — a real failure mode on large accelerator
fleets) would go entirely unnoticed because nothing ever validates the
evolving board.  This module adds the three standard tiers:

1. **Detection** — a cheap on-device audit of the live board.  The live
   detector is the cell-value invariant: every cell must be 0/1 (the B3/S23
   rule can only produce 0/1, so any other value proves corruption in
   place).  Alongside it the audit records telemetry that external harness
   checks can compare — the population count and a deterministic content
   fingerprint (order-independent mod-2^32 mixing, so XLA reduce order
   cannot change it).  The plain fingerprint has no in-run oracle (the
   evolved board's correct hash isn't known in advance); its job is
   cross-run / cross-replica determinism comparison and checkpoint
   integrity (tier 2).  The **redundancy audit** (``GuardConfig.redundant``
   / ``--guard-redundant``) builds that oracle in-run: every audited chunk
   is recomputed on a *second* bit-exact engine (dense vs bit-packed — the
   framework's tiers are mutually bit-exact, pinned by the equivalence
   suite) and the two device fingerprints must match, which catches the
   in-range flip (1->0 / 0->1) the 0/1 invariant passes, at the price of
   doubling the audited compute.  The audit is one small jitted reduce fused
   over the board — negligible next to a generation chunk — and its scalars
   are replicated across hosts, so every process takes the same recovery
   decision with no extra communication.
2. **Integrity** — the same fingerprint, computed bit-identically in NumPy,
   rides inside checkpoint files and is re-verified on load, turning the
   write-only dump culture of the reference into tamper-evident snapshots.
3. **Elastic recovery** — :func:`run_guarded` evolves in audit-sized chunks,
   keeps the last known-good state resident on device (sharded like the
   board, so no per-chunk host fetch or cross-host gather), and on a failed
   audit rolls back and replays instead of dying; a bounded restore budget
   converts persistent faults into a clean :class:`GuardError`.

Fault injection for tests/drills is a first-class hook (``fault_hook``),
because a recovery path that has never fired is a recovery path that does
not work.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gol_tpu.models.state import GolState
from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.utils import checkpoint as ckpt_mod
from gol_tpu.utils.timing import RunReport, Stopwatch, force_ready

# Odd constants -> invertible multiplies mod 2^32; distinct per axis so
# transposed/rolled boards fingerprint differently.
_ROW_MIX = np.uint32(0x9E3779B1)
_COL_MIX = np.uint32(0x85EBCA77)
_VAL_MIX = np.uint32(0xC2B2AE35)


def fingerprint_np(
    board: np.ndarray, row0: int = 0, col0: int = 0
) -> int:
    """Reference NumPy fingerprint (mod 2^32), bit-identical to the device one.

    Each cell contributes ``value * (1 + mix(i) * mix(j))``; contributions
    are summed mod 2^32.  Addition mod 2^32 is associative and commutative,
    so any reduction order — NumPy's, XLA's on one chip, or a cross-host
    psum — produces the same 32-bit result.

    ``row0``/``col0`` offset the cell coordinates into a larger global
    board: because the hash is a position-weighted *sum*, the fingerprints
    of a disjoint rectangle cover computed with global offsets add up
    (mod 2^32) to the whole board's fingerprint — the property the sharded
    checkpoint format uses to verify a global stamp from per-piece stamps
    without any host ever assembling the board.
    """
    board = np.asarray(board)
    h, w = board.shape
    total = np.uint32(0)
    with np.errstate(over="ignore"):
        cj = (
            np.arange(col0, col0 + w, dtype=np.uint32) * _COL_MIX
            + np.uint32(1)
        )[None, :]
        # Row-chunked so the uint32 weight plane never exceeds ~64 MB even
        # for 65536-wide boards (the device version is fused by XLA and
        # never materializes weights at all).
        step = max(1, (16 << 20) // max(w, 1))
        for r0 in range(0, h, step):
            r1 = min(h, r0 + step)
            ri = (
                np.arange(row0 + r0, row0 + r1, dtype=np.uint32) * _ROW_MIX
                + np.uint32(1)
            )[:, None]
            weights = np.uint32(1) + ri * cj * _VAL_MIX
            total = total + np.sum(
                board[r0:r1].astype(np.uint32) * weights, dtype=np.uint32
            )
    return int(total)


def _audit_device(board: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(max_cell, population, fingerprint) — one fused on-device reduce."""
    h, w = board.shape
    ri = (jnp.arange(h, dtype=jnp.uint32) * _ROW_MIX + jnp.uint32(1))[:, None]
    cj = (jnp.arange(w, dtype=jnp.uint32) * _COL_MIX + jnp.uint32(1))[None, :]
    weights = jnp.uint32(1) + ri * cj * _VAL_MIX
    cells = board.astype(jnp.uint32)
    return (
        jnp.max(board),
        jnp.sum(cells, dtype=jnp.uint32),
        jnp.sum(cells * weights, dtype=jnp.uint32),
    )


def _audit_device3(vol: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """3-D twin of :func:`_audit_device`, weighted under the volume's
    ``[D*H, W]`` flattening so the fingerprint is bit-identical to
    ``checkpoint._vol_fingerprint`` — one audit convention per driver.
    All elementwise: shard-local under any volume sharding."""
    d, h, w = vol.shape
    ri = (
        jnp.arange(d, dtype=jnp.uint32)[:, None, None] * jnp.uint32(h)
        + jnp.arange(h, dtype=jnp.uint32)[None, :, None]
    ) * _ROW_MIX + jnp.uint32(1)
    cj = jnp.arange(w, dtype=jnp.uint32)[None, None, :] * _COL_MIX + jnp.uint32(1)
    weights = jnp.uint32(1) + ri * cj * _VAL_MIX
    cells = vol.astype(jnp.uint32)
    return (
        jnp.max(vol),
        jnp.sum(cells, dtype=jnp.uint32),
        jnp.sum(cells * weights, dtype=jnp.uint32),
    )


_audit_jit = jax.jit(_audit_device)
_audit3_jit = jax.jit(_audit_device3)
# Batched audit: one fused reduce per world of a [B, H, W] stack (the
# batch runtime's guarded loop).  vmap keeps it a single launch; the
# per-world scalars are tiny.  Padded bucket cells are forced dead every
# generation by the masked engines, so a padded world's fingerprint
# equals its cropped board's (zero cells contribute nothing to the
# position-weighted sum).
_audit_batch_jit = jax.jit(jax.vmap(_audit_device))


@dataclasses.dataclass(frozen=True)
class Audit:
    """One detection pass over the live board.

    ``redundant_fingerprint`` is filled by the cross-engine redundancy
    audit (``GuardConfig.redundant``): the same chunk recomputed on a
    second bit-exact engine.  ``ok`` then also requires the fingerprints
    to match — the in-run oracle the plain invariant lacks (an in-range
    1<->0 flip passes the 0/1 check but cannot survive a fingerprint
    comparison against an independent recompute).
    """

    generation: int
    ok: bool
    max_cell: int
    population: int
    fingerprint: int
    redundant_fingerprint: Optional[int] = None


def audit_board(board, generation: int = 0) -> Audit:
    """Run the on-device detector; scalars replicate to every host.

    Accepts 2-D boards and 3-D volumes (the latter fingerprinted under
    the ``[D*H, W]`` flattening the 3-D checkpoint format stamps)."""
    max_cell, pop, fp = (
        _audit_jit(board) if board.ndim == 2 else _audit3_jit(board)
    )
    max_cell = int(max_cell)
    return Audit(
        generation=generation,
        ok=max_cell <= 1,
        max_cell=max_cell,
        population=int(pop),
        fingerprint=int(fp),
    )


def audit_worlds(stack, generation: int) -> List["Audit"]:
    """Per-world detection pass over a batched ``[B, H, W]`` stack.

    One vmapped fused reduce; returns one :class:`Audit` per world so
    the batch guard can name (and roll back) exactly the corrupted
    world's bucket.
    """
    max_cells, pops, fps = _audit_batch_jit(stack)
    max_cells = np.asarray(max_cells)
    pops = np.asarray(pops)
    fps = np.asarray(fps)
    return [
        Audit(
            generation=generation,
            ok=int(max_cells[i]) <= 1,
            max_cell=int(max_cells[i]),
            population=int(pops[i]),
            fingerprint=int(fps[i]),
        )
        for i in range(len(max_cells))
    ]


def inject_bitflip(board, row: int, col: int, value: int = 0xA5):
    """Fault-injection drill: corrupt one cell (device-side functional update).

    ``value`` defaults to an out-of-range byte — the signature of a real
    bit-flip in uint8 storage, exactly what the invariant detects.
    """
    return board.at[row, col].set(jnp.uint8(value))


class GuardError(ValueError):
    """Raised when the restore budget is exhausted (persistent fault).

    A ``ValueError`` subclass so the CLI's existing clean-error handling
    catches it (same convention as ``CorruptSnapshotError``).
    """


@dataclasses.dataclass(frozen=True)
class GuardConfig:
    check_every: int  # generations between audits (the chunk size)
    max_restores: int = 3
    # Test/drill hook: (board, generation_after_chunk) -> board, applied
    # after each chunk *before* the audit, simulating in-flight corruption.
    fault_hook: Optional[Callable[[jax.Array, int], jax.Array]] = None
    # Cross-engine redundancy audit: recompute every audited chunk on a
    # second bit-exact engine and require matching fingerprints.  Doubles
    # the audited compute; the only in-run detector for in-range flips.
    redundant: bool = False
    # Sampling for the redundancy audit: recompute only every Nth audited
    # chunk (starting with the first).  Overhead drops from 2x to
    # ~(1 + 1/N)x of the guarded path; the trade is *coverage*, not
    # latency — a flip landing in an unsampled chunk is carried into the
    # recompute baseline and never caught, so per-corrupted-chunk
    # detection probability is 1/N and a recurring fault source is caught
    # within ~N audits in expectation.  (Catching every single flip
    # fundamentally requires an unbroken independent recompute chain —
    # i.e. N=1's full 2x.)  A replay forced by a redundant mismatch is
    # always re-verified redundantly, whatever the sampling phase.
    redundant_every: int = 1

    def __post_init__(self) -> None:
        if self.check_every < 1:
            raise ValueError(f"check_every must be >= 1, got {self.check_every}")
        if self.max_restores < 0:
            raise ValueError(
                f"max_restores must be >= 0, got {self.max_restores}"
            )
        if self.redundant_every < 1:
            raise ValueError(
                f"redundant_every must be >= 1, got {self.redundant_every}"
            )
        if self.redundant_every != 1 and not self.redundant:
            raise ValueError(
                "redundant_every samples the redundancy audit, so it "
                "requires redundant=True"
            )


@dataclasses.dataclass
class GuardReport:
    """What the guard saw: every audit, plus the recovery ledger."""

    audits: List[Audit] = dataclasses.field(default_factory=list)
    failures: int = 0
    restores: int = 0

    @property
    def checks(self) -> int:
        return len(self.audits)

    def summary_line(self) -> str:
        return (
            f"GUARD          : {self.checks} checks, {self.failures} failures, "
            f"{self.restores} restores"
        )


def _checker_runtime(rt):
    """A sibling runtime on a *different* bit-exact engine — the redundant
    auditor.  dense checks the packed tiers (different data layout and
    program); bitpack checks dense.  A random hardware flip cannot
    reproduce across two independent programs, so matching fingerprints
    certify the chunk; the engines' mutual bit-exactness is pinned by the
    equivalence test suite.
    """
    import dataclasses as dc

    if rt.halo_mode != "fresh":
        raise ValueError(
            "the redundant audit needs a second bit-exact engine; stale_t0 "
            "(reference-compat) runs exist only on the dense engine"
        )
    if rt._resolved == "dense":
        geom = (rt.geometry.global_height, rt.geometry.global_width)
        try:
            if rt.mesh is not None:
                from gol_tpu.parallel import packed as packed_mod

                packed_mod.validate_packed_geometry(geom, rt.mesh)
            else:
                from gol_tpu.ops import bitlife

                bitlife.packed_width(geom[1])
        except ValueError as e:
            raise ValueError(
                f"the redundant audit needs a second engine, and the only "
                f"check for a dense run is bit-packed: {e}"
            ) from e
        checker = "bitpack"
    else:
        checker = "dense"
    return dc.replace(
        rt,
        engine=checker,
        shard_mode="explicit",
        halo_depth=1,
        checkpoint_every=0,
        checkpoint_dir=None,
    )


# Device-to-device snapshot of the (possibly sharded) board: the last-good
# buffer stays resident with the board's own sharding, so the per-chunk
# cost is one on-device copy — not the host fetch (a full cross-host
# all-gather on multi-host runs, ADVICE r1) the first version paid.  jit
# re-specializes per shape/sharding; all hosts call it in lockstep.
_device_copy = jax.jit(jnp.copy)


def run_guarded(
    rt,
    pattern: int,
    iterations: int,
    config: GuardConfig,
    resume: Optional[str] = None,
) -> Tuple[RunReport, GolState, GuardReport]:
    """Evolve with failure detection and rollback-replay recovery.

    Drop-in sibling of :meth:`gol_tpu.runtime.GolRuntime.run`: same engine
    dispatch and AOT compile phase, but the generation loop is chopped into
    ``config.check_every``-sized chunks, each followed by an on-device
    audit.  A failed audit rolls the board back to the last good snapshot
    — kept *on device* with the board's own sharding, so multi-host runs
    never pay a per-chunk all-gather — and replays the chunk; more than
    ``config.max_restores`` consecutive failures raises
    :class:`GuardError` (the fault is persistent — retrying cannot help).
    With no faults the result is identical to ``rt.run`` — pinned by tests
    against the unguarded path.

    When the runtime also has ``checkpoint_every`` set, a verified snapshot
    is persisted at the first audit boundary at or after each interval, so
    a run killed past its restore budget can still be resumed on fresh
    hardware from the last audited-good state (only audited boards are ever
    written — a snapshot can't capture corruption the guard would catch).
    """
    from gol_tpu import telemetry as telemetry_mod

    if getattr(rt, "stats", False):
        raise ValueError(
            "--stats applies to unguarded runs: the guard's audit already "
            "reports population/fingerprint per chunk, and its rollback "
            "replay consumes the evolvers' donated buffers that stats "
            "mode must keep alive"
        )
    sw = Stopwatch()
    guard = GuardReport()
    with sw.phase("init"):
        state = rt.initial_state(pattern, resume)
        board = state.board
        if rt.mesh is not None:
            board = mesh_mod.shard_board(board, rt.mesh)

    schedule: List[int] = rt.chunk_schedule(iterations, config.check_every)

    events = rt.open_event_log()
    # The containment policies need the live stream: the disk-full shed
    # sacrifices telemetry before checkpoints (docs/RESILIENCE.md).
    rt._ckpt_shed = False
    rt._live_events = events
    try:
        with sw.phase("compile"):
            evolvers = rt.compile_evolvers(board, schedule, events)
            checker_evolvers = None
            if config.redundant:
                checker_evolvers = _checker_runtime(rt).compile_evolvers(
                    board, schedule
                )
        on_restore = None
        if getattr(rt, "_resolved", None) == "activity":
            # Activity tier under guard (docs/SPARSE.md): the chunk
            # programs carry the changed-tile mask — fn(board, mask) ->
            # (board, mask, activity).  The adapter threads the mask
            # outside the guarded loop's view (the audit rides the
            # board the worklist produced), and a rollback reconstructs
            # it all-active — the same sound superset rule as resume,
            # collapsing to the true activity after one generation, so
            # the replayed board stays bit-identical to the dense tiers.
            mask_holder = [rt._initial_activity_mask()]

            def _wrap_activity(compiled):
                def call(b):
                    nb, nm, _act = compiled(b, mask_holder[0])
                    mask_holder[0] = nm
                    return nb

                return call

            evolvers = {
                take: (_wrap_activity(c), dynamic)
                for take, (c, dynamic) in evolvers.items()
            }

            def on_restore():
                mask_holder[0] = rt._initial_activity_mask()

        generation = int(state.generation)
        writer = None
        if rt.checkpoint_every > 0 and jax.process_count() == 1:
            # Same async overlap + final-flush contract as GolRuntime.run.
            writer = ckpt_mod.AsyncSnapshotWriter()
        rt._ckpt_writer = writer
        try:
            with telemetry_mod.trace_annotation("gol.guard.run"):
                board, generation = guarded_loop(
                    sw,
                    guard,
                    board,
                    generation,
                    schedule,
                    evolvers,
                    checker_evolvers,
                    config,
                    save_snapshot=lambda b, g, fp: rt._save_snapshot(
                        GolState.create(b, g), fingerprint=fp
                    ),
                    checkpoint_every=rt.checkpoint_every,
                    events=events,
                    chunk_utilization=rt.chunk_utilization,
                    checkpoint_overlapped=writer is not None,
                    # Audited boards only ever reach the hook — a
                    # preemption snapshot can't capture corruption the
                    # guard would catch.
                    preempt_hook=lambda b, g, fp, saved: rt._preempt(
                        GolState.create(b, g),
                        sw,
                        writer,
                        events,
                        fingerprint=fp,
                        already_saved=saved,
                    ),
                    on_restore=on_restore,
                )
            if writer is not None:
                with sw.phase("checkpoint"):
                    writer.flush()
        finally:
            rt._ckpt_writer = None
            if writer is not None:
                writer.close()

        report = sw.report(rt.geometry.cell_updates(iterations))
        if events is not None:
            events.summary(report)
    finally:
        rt._live_events = None
        if events is not None:
            events.close()
    return report, GolState.create(board, generation), guard


def guarded_loop(
    sw: Stopwatch,
    guard: GuardReport,
    board,
    generation: int,
    schedule,
    evolvers,
    checker_evolvers,
    config: GuardConfig,
    save_snapshot=None,
    checkpoint_every: int = 0,
    events=None,
    chunk_utilization=None,
    checkpoint_overlapped: bool = False,
    preempt_hook=None,
    on_restore=None,
):
    """The chunk/audit/rollback core, shared by the 2-D and 3-D drivers.

    ``evolvers[take]`` is ``(compiled, dynamic_args)``; the compiled
    program donates its input.  ``save_snapshot(board, generation,
    fingerprint)`` persists an audited-good state (the audit's device
    fingerprint rides along so no host-side recompute happens).  Returns
    the final ``(board, generation)``; the caller owns reporting.

    ``events`` (a :class:`gol_tpu.telemetry.EventLog`) receives one
    ``chunk`` record per *executed* chunk — replays included, so the
    stream shows recovery work the phase totals hide — plus one
    ``guard_audit`` record per audit and one ``checkpoint`` record per
    snapshot.  Chunk records carry a schema-v6 ``spans`` block:
    dispatch/ready for the chunk itself plus the guard's boundary
    phases (audit/redundant/snapshot/restore/checkpoint/telemetry/
    preempt_poll) since the previous chunk event.  ``chunk_utilization(take, wall_s)`` maps a chunk to its
    roofline fraction (``None`` skips the column).  All emission is
    host-side, after the ``force_ready`` fences.

    ``preempt_hook(board, generation, fingerprint, just_checkpointed)``
    is the cooperative-preemption exit (gol_tpu/resilience/): called at
    a chunk boundary — after the audit certified the board and any due
    checkpoint landed — when a preemption was requested and work
    remains.  The hook persists/fences a final snapshot and raises
    ``Preempted``; only audited-good boards ever reach it.

    ``on_restore`` (optional) runs after every rollback, before the
    replay — the activity tier resets its carried changed-tile mask to
    the all-active superset here.  The pipelined shard mode needs no
    analog: its ``(block, bands)`` double buffer lives entirely inside
    one compiled chunk program (each chunk re-exchanges its prologue
    band from the board it is given), so restoring the board restores
    the carried pair by construction — pinned by the guard×pipeline
    rollback tests.

    An active fault plan (:mod:`gol_tpu.resilience.faults`) composes
    with ``config.fault_hook``: plan ``board.bitflip`` entries apply
    after the hook, ``crash.exit``/``rank.stall`` fire at the chunk
    boundary, and fired injections / containment decisions drain into
    schema-v9 ``fault``/``degraded`` events when telemetry is on.
    """
    import time as time_mod

    from gol_tpu import telemetry as telemetry_mod
    from gol_tpu.resilience import degrade as degrade_mod
    from gol_tpu.resilience import faults as faults_mod

    plan_on = faults_mod.active() is not None

    def _drain_plane():
        # Fault-plane ledgers -> v9 telemetry (no-ops when empty; fired
        # checkpoint faults accumulate on the writer thread and surface
        # at the next boundary here).
        if events is None:
            return
        for f in faults_mod.drain_fired():
            events.fault_event(**f)
        for d in degrade_mod.drain_reports():
            events.degraded_event(**d)
    # The rollback base lives on device (in the same fault domain as the
    # board — the price of not all-gathering per chunk), so its audit
    # fingerprint is recorded at snapshot time and re-verified before any
    # replay: a fault landing in the base itself must fail the restore
    # loudly, never silently replay-and-certify corruption.
    last_good = (_device_copy(board), generation, audit_board(board).fingerprint)
    next_ckpt = (
        generation + checkpoint_every if checkpoint_every > 0 else None
    )
    # Span attribution (schema v6): the guard adds its own phases
    # (audit/redundant/snapshot/restore) to the chunk-loop spans; off
    # (no events) the clock is never built.
    import contextlib

    sc = telemetry_mod.SpanClock() if events is not None else None

    def _span(phase):
        return sc.span(phase) if sc is not None else contextlib.nullcontext()
    i = 0
    restores_this_chunk = 0
    while i < len(schedule):
        take = schedule[i]
        compiled, dynamic = evolvers[take]
        with telemetry_mod.step_annotation("gol.guard.chunk", i):
            with sw.phase("total"):
                t0 = time_mod.perf_counter()
                candidate = compiled(board, *dynamic)
                t1 = time_mod.perf_counter()
                force_ready(candidate)
                chunk_dt = time_mod.perf_counter() - t0
        if events is not None:
            sc.add("dispatch", t1 - t0)
            sc.add("ready", chunk_dt - (t1 - t0))
            spans = sc.take()
            with sc.span("telemetry"):
                events.chunk_event(
                    i,
                    take,
                    generation + take,
                    chunk_dt,
                    int(candidate.size) * take,
                    None
                    if chunk_utilization is None
                    else chunk_utilization(take, chunk_dt),
                    restores_this_chunk=restores_this_chunk,
                    spans=spans,
                )
        if config.fault_hook is not None:
            candidate = config.fault_hook(candidate, generation + take)
        if plan_on:
            candidate = faults_mod.apply_board_faults(
                candidate, generation + take
            )
        with telemetry_mod.trace_annotation("gol.guard.audit"):
            with sw.phase("audit"), _span("audit"):
                audit = audit_board(candidate, generation + take)
        # Sampling keys on the stable chunk index, so a sampled chunk's
        # replays — after either a cheap-audit or a recompute failure —
        # are re-verified redundantly, and failures cannot drift the
        # sampling phase onto different chunks.
        sampled = i % config.redundant_every == 0
        if checker_evolvers is not None and audit.ok and sampled:
            # Redundant recompute of the same chunk from the same input
            # (last_good still holds it — it only advances below) on the
            # second engine; fingerprints of two independent programs can
            # only agree if neither run was corrupted.
            comp2, dyn2 = checker_evolvers[take]
            with telemetry_mod.trace_annotation("gol.guard.redundant"):
                with sw.phase("redundant"), _span("redundant"):
                    reference = comp2(_device_copy(last_good[0]), *dyn2)
                    audit2 = audit_board(reference, generation + take)
            audit = dataclasses.replace(
                audit,
                ok=audit2.fingerprint == audit.fingerprint,
                redundant_fingerprint=audit2.fingerprint,
            )
        guard.audits.append(audit)
        if events is not None:
            with _span("telemetry"):
                events.guard_event(audit)
        if not audit.ok:
            guard.failures += 1
            restores_this_chunk += 1
            if restores_this_chunk > config.max_restores:
                detail = (
                    f"max cell {audit.max_cell}"
                    if audit.max_cell > 1
                    else (
                        f"fingerprint {audit.fingerprint:#010x} != redundant "
                        f"recompute {audit.redundant_fingerprint:#010x}"
                    )
                )
                raise GuardError(
                    f"audit failed at generation {audit.generation} "
                    f"({detail}) and the restore budget "
                    f"({config.max_restores}) is exhausted — persistent fault"
                )
            guard.restores += 1
            with telemetry_mod.trace_annotation(
                "gol.guard.restore"
            ), sw.phase("restore"), _span("restore"):
                # Copy again: the replayed chunk donates its input, and
                # the last-good buffer must survive for further replays.
                board = _device_copy(last_good[0])
                generation = last_good[1]
                base = audit_board(board, generation)
                if not base.ok or base.fingerprint != last_good[2]:
                    raise GuardError(
                        f"the rollback base itself is corrupt at generation "
                        f"{generation} (fingerprint {base.fingerprint:#010x} "
                        f"!= recorded {last_good[2]:#010x}); in-run recovery "
                        "is impossible — resume from the last checkpoint"
                    )
                if on_restore is not None:
                    on_restore()
            continue  # replay the same chunk
        restores_this_chunk = 0
        board = candidate
        generation += take
        with sw.phase("snapshot"), _span("snapshot"):
            # audit.fingerprint is this exact board's stamp (just computed
            # on device) — recorded for the base-integrity check above.
            last_good = (_device_copy(board), generation, audit.fingerprint)
        just_checkpointed = False
        if next_ckpt is not None and generation >= next_ckpt:
            with telemetry_mod.trace_annotation("gol.checkpoint.save"):
                with sw.phase("checkpoint"):
                    # The audit already fingerprinted this exact board on
                    # device — no host-side fingerprint pass; multi-host
                    # runs write sharded pieces with no gather at all.
                    t0 = time_mod.perf_counter()
                    save_snapshot(board, generation, audit.fingerprint)
                    ckpt_dt = time_mod.perf_counter() - t0
            if sc is not None:
                sc.add("checkpoint", ckpt_dt)
            if events is not None:
                with _span("telemetry"):
                    events.checkpoint_event(
                        generation,
                        ckpt_dt,
                        int(board.size),
                        overlapped=checkpoint_overlapped,
                    )
            next_ckpt = generation + checkpoint_every
            just_checkpointed = True
        if plan_on:
            faults_mod.crash_or_stall(generation)
        _drain_plane()
        if preempt_hook is not None and i < len(schedule) - 1:
            from gol_tpu import resilience

            with _span("preempt_poll"):
                preempt_now = resilience.agreed_preempt_requested()
            if preempt_now:
                preempt_hook(
                    board, generation, audit.fingerprint, just_checkpointed
                )
        i += 1
    return board, generation
