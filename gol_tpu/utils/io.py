"""Per-rank world dumps, byte-compatible with the reference's output files.

The reference writes each rank's final board to ``Rank_<r>_of_<n>.txt``
(filename at gol-main.c:66) consisting of a banner line
(gol-main.c:136) followed by one line per local row in the format
``"Row %2d: "`` + ``"%u "`` per cell + newline (gol_printWorld,
gol-main.c:17-28).  The row label is globalized: ``local_height * rank + i``
(gol-main.c:22).  Note the ``%2d`` minimum field width and the trailing
space after the last cell — both reproduced here byte-for-byte (golden-file
tests pin this).

A native C++ fast path for the hot formatting loop lives in
``native/golrt.cpp`` (loaded lazily via :mod:`gol_tpu.utils.native`); this
module is the always-available pure-Python/NumPy implementation and the
arbiter of correctness.

Reading the files back (:func:`read_rank_file`) is a capability *addition* —
the reference's dump is write-only (SURVEY §5: no loader exists).
"""

from __future__ import annotations

import os
import re
from typing import Optional

import numpy as np

RANK_FILE_TEMPLATE = "Rank_{rank}_of_{num_ranks}.txt"
_HEADER_TEMPLATE = (
    "######################### FINAL WORLD IN RANK {rank} IS "
    "###############################\n"
)


def rank_filename(rank: int, num_ranks: int) -> str:
    return RANK_FILE_TEMPLATE.format(rank=rank, num_ranks=num_ranks)


class RankFileError(OSError):
    """A rank's dump file could not be created at startup.

    Carries the failing logical rank so the driver can print the
    reference's exact diagnostic ``printf("ERROR IN RANK %d", myRank)``
    (gol-main.c:68-71).
    """

    def __init__(self, rank: int, cause: OSError):
        super().__init__(f"ERROR IN RANK {rank}")
        self.rank = rank
        self.cause = cause


def create_rank_files(ranks, num_ranks: int, directory: str = ".") -> list:
    """Create (truncating) each rank's dump file at startup.

    The reference ``fopen(..., "w")``s every rank's ``Rank_<r>_of_<n>.txt``
    right after ``MPI_Init``, *before* world initialization
    (gol-main.c:64-73) — so with output enabled a (possibly empty) file
    exists even if the run later dies, and a pre-existing dump from an
    earlier run is truncated the moment the new run starts.  Raises
    :class:`RankFileError` naming the first rank whose open failed.
    """
    ranks = list(ranks)
    paths = []
    if not ranks:
        return paths
    try:
        os.makedirs(directory, exist_ok=True)
    except OSError as e:
        raise RankFileError(ranks[0], e)
    for rank in ranks:
        path = os.path.join(directory, rank_filename(rank, num_ranks))
        try:
            open(path, "wb").close()
        except OSError as e:
            raise RankFileError(rank, e)
        paths.append(path)
    return paths


def _format_rows_fast(block: np.ndarray, row0: int) -> bytes:
    """Vectorized renderer for the common case: all cells are single digit.

    Builds each data row as ``digit + space`` byte pairs in one NumPy pass;
    only the ``Row %2d: `` prefixes are Python-level.
    """
    h, w = block.shape
    cells = np.empty((h, w, 2), dtype=np.uint8)
    cells[:, :, 0] = block + ord("0")
    cells[:, :, 1] = ord(" ")
    body = cells.reshape(h, 2 * w)
    out = []
    for i in range(h):
        out.append(b"Row %2d: " % (row0 + i))
        out.append(body[i].tobytes())
        out.append(b"\n")
    return b"".join(out)


def format_world(block: np.ndarray, rank: int) -> bytes:
    """Render one rank's block exactly as gol_printWorld (gol-main.c:17-28).

    ``block`` is the rank's local board; row labels are globalized with the
    block's own height (the reference uses the *local* ``g_worldHeight``).
    """
    block = np.asarray(block)
    if block.ndim != 2:
        raise ValueError(f"expected a 2-D block, got shape {block.shape}")
    row0 = block.shape[0] * rank
    if block.size and block.max() > 9:
        # General %u rendering (cells are 0/1 in practice; keep correctness
        # for arbitrary uint8 anyway).
        lines = []
        for i, row in enumerate(block):
            lines.append(
                ("Row %2d: " % (row0 + i))
                + "".join("%u " % v for v in row)
                + "\n"
            )
        return "".join(lines).encode()
    return _format_rows_fast(block.astype(np.uint8, copy=False), row0)


def format_rank_file(block: np.ndarray, rank: int) -> bytes:
    """Banner (gol-main.c:136) + world dump — the full file contents."""
    return _HEADER_TEMPLATE.format(rank=rank).encode() + format_world(block, rank)


def write_rank_file(
    block: np.ndarray,
    rank: int,
    num_ranks: int,
    directory: str = ".",
    use_native: bool = True,
) -> str:
    """Write one rank's ``Rank_<r>_of_<n>.txt``; returns the path."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, rank_filename(rank, num_ranks))
    data: Optional[bytes] = None
    block = np.asarray(block)
    if use_native and (block.size == 0 or block.max() <= 9):
        # The native renderer emits single-digit cells only; multi-digit
        # values take the generic Python '%u ' path so the bytes written
        # never depend on whether the library was built.
        from gol_tpu.utils import native

        if native.available():
            native.write_rank_file(path, np.ascontiguousarray(block), rank)
            return path
    data = format_rank_file(block, rank)
    with open(path, "wb") as f:
        f.write(data)
    return path


def write_world_dumps(
    global_board: np.ndarray,
    num_ranks: int,
    directory: str = ".",
    use_native: bool = True,
) -> list[str]:
    """Write all ranks' dump files from the stacked global board.

    Equivalent to every MPI rank executing gol-main.c:135-139 — but here the
    shards are rows of one (possibly sharded) global array, written per
    logical rank without any gather beyond host transfer of each block.
    """
    height = global_board.shape[0]
    if height % num_ranks:
        raise ValueError(f"global height {height} not divisible by {num_ranks} ranks")
    s = height // num_ranks
    return [
        write_rank_file(
            global_board[r * s : (r + 1) * s], r, num_ranks, directory, use_native
        )
        for r in range(num_ranks)
    ]


_ROW_RE = re.compile(rb"^Row\s*(-?\d+): (.*?) ?$")


def read_rank_file(path: str) -> tuple[int, np.ndarray]:
    """Parse a dump file back into (first_global_row, block array)."""
    with open(path, "rb") as f:
        lines = f.read().split(b"\n")
    if not lines or not lines[0].startswith(b"#"):
        raise ValueError(f"{path}: missing banner line")
    rows = []
    first_label = None
    for line in lines[1:]:
        if not line:
            continue
        m = _ROW_RE.match(line)
        if not m:
            raise ValueError(f"{path}: malformed row line {line[:40]!r}")
        if first_label is None:
            first_label = int(m.group(1))
        rows.append(np.array([int(t) for t in m.group(2).split()], dtype=np.uint8))
    if first_label is None:
        raise ValueError(f"{path}: no data rows")
    return first_label, np.stack(rows)
