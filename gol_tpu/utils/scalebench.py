"""Weak-scaling efficiency harness (BASELINE.md config 4's metric).

The reference's scaling model is weak scaling by construction: every MPI
rank owns a fixed ``S×S`` block, so "scaling the domain" means adding ranks
(global world ``numRank·S × S``, gol-main.c:22,124-125).  This harness
measures the TPU equivalent: for each device count ``n`` it evolves an
``(n·S) × S`` board row-sharded over an ``n``-device ring and reports

- aggregate and per-chip cell-updates/sec, and
- **weak-scaling efficiency**: per-chip throughput at ``n`` devices divided
  by the 1-device throughput (1.0 = perfect scaling; the loss is the
  exposed halo-exchange cost, which :mod:`gol_tpu.utils.halobench`
  attributes in detail).

On this repo's single-real-TPU hosts the sweep runs on the host-local
virtual CPU mesh (``--xla_force_host_platform_device_count``) — valid for
the *shape* of the scaling curve and for regression-testing the comm
structure, not for absolute numbers; on a real pod the same harness runs
unchanged over ICI.

Run as a module for a JSON report:
``python -m gol_tpu.utils.scalebench [size_per_chip] [steps] [engine]
[mesh {1d,2d}]`` (engine ``dense`` | ``bitpack`` | ``pallas`` |
``pallas_overlap`` — the last two are the flagship fused-kernel-per-shard
program in its serial and comm/compute-overlap forms).

``mesh 2d`` sweeps the *pod decomposition* (BASELINE config 3's 16×16
block mesh, scaled to each device count as the near-square factorization
with rows <= cols: 8 devices -> 2×4): every device owns a fixed
``S×S`` block of a ``(rows·S) × (cols·S)`` world, the two-phase
row+word-column exchange replaces the 1-D ring, and narrow shards take
the lane-folded kernel — the engine/mesh combination a real pod would
run, which the 1-D sweep cannot see (VERDICT r4 #3).

**Multi-host sweeps** (the config-4 pod shape): pass the same trio as the
CLI — ``--coordinator HOST:PORT --num-processes N --process-id I`` — on
every participating process.  Device counts then sweep the *global*
device list: rows using only some processes' devices are measured by
those processes while the rest idle at the between-row barrier (the
1-device baseline every efficiency number divides by stays measurable),
and rows spanning processes run the exact cross-host programs a pod
would.  Process 0 prints the report.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gol_tpu.parallel import mesh as mesh_mod
from gol_tpu.parallel import packed as packed_mod
from gol_tpu.parallel import sharded as sharded_mod
from gol_tpu.utils.timing import time_best

ENGINES = ("dense", "bitpack", "pallas", "pallas_overlap")


def device_counts(limit: Optional[int] = None) -> List[int]:
    """Powers of two up to the visible device count (always including 1)."""
    n = len(jax.devices())
    if limit is not None:
        n = min(n, limit)
    counts = [1]
    while counts[-1] * 2 <= n:
        counts.append(counts[-1] * 2)
    return counts


def factor_2d(n: int):
    """Near-square ``(rows, cols)`` with rows <= cols: the config-3 pod
    decomposition (16×16 at 256 devices) scaled to ``n`` (8 -> 2×4)."""
    r = int(np.sqrt(n))
    while n % r:
        r -= 1
    return (min(r, n // r), max(r, n // r))


def _mesh_and_shape(n: int, size_per_chip: int, mesh_kind: str):
    """The row's mesh and world shape: every device owns ``S×S`` cells."""
    if mesh_kind == "2d":
        rows, cols = factor_2d(n)
        mesh = mesh_mod.make_mesh_2d(
            (rows, cols), devices=jax.devices()[:n]
        )
        return mesh, (rows * size_per_chip, cols * size_per_chip)
    mesh = mesh_mod.make_mesh_1d(num_devices=n)
    return mesh, (n * size_per_chip, size_per_chip)


def measure_weak_scaling(
    size_per_chip: int,
    steps: int,
    engine: str = "dense",
    counts: Optional[List[int]] = None,
    mesh_kind: str = "1d",
) -> List[Dict[str, float]]:
    """One weak-scaling sweep; returns a row per device count.

    Multi-process jobs: every process must call this (rows spanning
    processes run cross-host programs; a between-row barrier keeps the
    job in lockstep).  A process only measures rows whose mesh includes
    its devices, so the returned list is complete — and the efficiency
    baseline correct — on process 0, whose devices lead the global device
    list; other processes' partial lists are for their own logging only.
    """
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}; expected {ENGINES}")
    if mesh_kind not in ("1d", "2d"):
        raise ValueError(f"unknown mesh kind {mesh_kind!r}; expected 1d|2d")
    counts = device_counts() if counts is None else list(counts)
    if not counts or counts[0] != 1:
        # Efficiency is defined against the 1-device throughput; a sweep
        # that skips it would silently re-baseline on its first row.
        raise ValueError(f"counts must start at 1, got {counts}")
    pallas_like = engine in ("pallas", "pallas_overlap")
    if (
        pallas_like
        and mesh_kind == "1d"
        and jax.default_backend() == "tpu"
    ):
        # Surface the fused kernel's lane constraint early (it otherwise
        # raises deep inside shard_map tracing).  Loop-invariant: the
        # width axis is unsharded on the 1-D row mesh.  (2-D sweeps
        # lane-fold narrow shards instead; their geometry is validated
        # per row below.)
        from gol_tpu.ops import bitlife, pallas_bitlife

        lane_cells = pallas_bitlife._LANE * bitlife.BITS
        if size_per_chip % lane_cells:
            raise ValueError(
                f"engine {engine!r} on TPU needs size_per_chip to be a "
                f"multiple of {lane_cells} (128-lane packed width); got "
                f"{size_per_chip}"
            )
    multi = jax.process_count() > 1
    me = jax.process_index()
    # Validate every row's geometry up front, on every process: the checks
    # are deterministic, so a bad configuration fails identically
    # everywhere *before* the first row barrier — a participant raising
    # mid-sweep would leave the idle processes deadlocked at theirs.
    for n in counts:
        mesh, shape = _mesh_and_shape(n, size_per_chip, mesh_kind)
        if pallas_like or engine == "bitpack":
            # Packable widths are >= 32, so the square shard also always
            # clears the overlap form's 24-row interior/boundary minimum.
            packed_mod.validate_packed_geometry(shape, mesh)
        else:
            mesh_mod.validate_geometry(shape, mesh)
    rows: List[Dict[str, float]] = []
    base_per_chip: Optional[float] = None
    for n in counts:
        mesh, world = _mesh_and_shape(n, size_per_chip, mesh_kind)
        participating = {d.process_index for d in mesh.devices.flat}
        try:
            if me in participating:
                # Per-row seed: every process that measures row n builds
                # the identical board with no sequential PRNG coupling, so
                # idle processes skip at zero cost.
                rng = np.random.default_rng((0, n))
                board_np = (rng.random(world) < 0.35).astype(np.uint8)
                board = mesh_mod.shard_board(jnp.asarray(board_np), mesh)
                if pallas_like:
                    # The flagship multi-chip program (fused kernel per
                    # shard over the ring), serial or overlap form.
                    # Meaningful curves need a real TPU — interpret mode
                    # is far too slow.
                    evolve = packed_mod.compiled_evolve_packed_pallas(
                        mesh, steps, overlap=engine == "pallas_overlap"
                    )
                elif engine == "bitpack":
                    evolve = packed_mod.compiled_evolve_packed(mesh, steps)
                else:
                    evolve = sharded_mod.compiled_evolve(
                        mesh, steps, "explicit", 1
                    )
                dt = time_best(evolve, lambda b=board: jnp.array(b, copy=True))
                updates = world[0] * world[1] * steps
                per_chip = updates / dt / n
                if base_per_chip is None:
                    base_per_chip = per_chip
                rows.append(
                    {
                        "devices": n,
                        "mesh": dict(mesh.shape),
                        "seconds": dt,
                        "updates_per_s": updates / dt,
                        "per_chip": per_chip,
                        "efficiency": per_chip / base_per_chip,
                    }
                )
        finally:
            # Reached even if a participant's row fails at runtime, so the
            # others' barrier is never left waiting on a dead process.
            if multi:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(f"scalebench_row_{n}")
    return rows


def main(argv=None) -> None:
    import argparse
    import sys

    from gol_tpu.parallel.multihost import add_multihost_args

    p = argparse.ArgumentParser(prog="scalebench")
    p.add_argument("positionals", nargs="*", metavar="ARG")
    # The multi-host trio, same surface as the main CLI: every process of
    # the job runs this module with its own --process-id.
    add_multihost_args(p)
    # Structured-telemetry sink (docs/OBSERVABILITY.md): every process
    # writes its own rank file with the rows it measured.
    p.add_argument("--telemetry", default=None, metavar="DIR")
    p.add_argument("--run-id", default=None, metavar="NAME")
    ns = p.parse_args(list(sys.argv[1:] if argv is None else argv))
    size = int(ns.positionals[0]) if len(ns.positionals) > 0 else 1024
    steps = int(ns.positionals[1]) if len(ns.positionals) > 1 else 64
    engine = ns.positionals[2] if len(ns.positionals) > 2 else "dense"
    mesh_kind = ns.positionals[3] if len(ns.positionals) > 3 else "1d"

    from gol_tpu.parallel import multihost

    topo = multihost.init_multihost(
        ns.coordinator, ns.num_processes, ns.process_id
    )
    rows = measure_weak_scaling(size, steps, engine, mesh_kind=mesh_kind)
    if ns.telemetry:
        from gol_tpu import telemetry as telemetry_mod

        with telemetry_mod.EventLog(ns.telemetry, run_id=ns.run_id) as ev:
            ev.run_header(
                dict(
                    tool="scalebench",
                    engine=engine,
                    mesh_kind=mesh_kind,
                    size_per_chip=size,
                    steps=steps,
                )
            )
            for row in rows:
                ev.bench_row("scalebench", row)
    if topo.is_coordinator:
        # Process 0 owns the full curve (its devices lead the global list,
        # so it participates in every row, including the 1-device
        # baseline); it reports alone, like the reference's rank 0.
        from gol_tpu.telemetry import ledger as ledger_mod

        print(
            json.dumps(
                {
                    "size_per_chip": size,
                    "steps": steps,
                    "engine": engine,
                    "mesh_kind": mesh_kind,
                    "platform": jax.devices()[0].platform,
                    "processes": topo.process_count,
                    "rows": rows,
                    # Satellite (PR 9): the module emitter stamps the
                    # common header (capture_artifacts already does), so
                    # a bare capture ingests with zero sniffing.
                    "header": ledger_mod.artifact_header("scalebench"),
                }
            )
        )


if __name__ == "__main__":
    main()
