"""Timing/observability harness.

The reference's only instrumentation is a rank-0 wall-clock pair around the
whole loop plus one printed line (``MPI_Wtime`` at gol-main.c:81-82,122 and
the report at gol-main.c:124-125).  This module reproduces that headline
metric exactly and extends it (SURVEY §5) with per-phase breakdowns, derived
throughput, and an optional ``jax.profiler`` trace.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator, Optional


@dataclasses.dataclass
class RunReport:
    duration_s: float
    cell_updates: int
    phases: Dict[str, float]

    @property
    def updates_per_sec(self) -> float:
        return self.cell_updates / self.duration_s if self.duration_s > 0 else 0.0

    def duration_line(self) -> str:
        """The reference's exact report line (gol-main.c:124-125)."""
        return (
            f"TOTAL DURATION : {self.duration_s:.5f}, "
            f"number of cell updates = {self.cell_updates}"
        )

    def throughput_line(self) -> str:
        """Extension: derived throughput (the BASELINE.json metric)."""
        return f"THROUGHPUT     : {self.updates_per_sec:.4g} cell-updates/sec"


class Stopwatch:
    """Accumulates named wall-clock phases; the whole-run phase is 'total'."""

    def __init__(self) -> None:
        self.phases: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + time.perf_counter() - t0

    def report(self, cell_updates: int, total_phase: str = "total") -> RunReport:
        return RunReport(
            duration_s=self.phases.get(total_phase, 0.0),
            cell_updates=cell_updates,
            phases=dict(self.phases),
        )


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace when a directory is given (else no-op).

    View with TensorBoard or xprof.  The runtime enters this around the
    timed generation loop only — compilation is warmed beforehand, so the
    trace shows steady-state device execution (the TPU-native upgrade over
    the reference's single wall-clock delta).
    """
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
