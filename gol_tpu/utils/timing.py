"""Timing/observability harness.

The reference's only instrumentation is a rank-0 wall-clock pair around the
whole loop plus one printed line (``MPI_Wtime`` at gol-main.c:81-82,122 and
the report at gol-main.c:124-125).  This module reproduces that headline
metric exactly and extends it (SURVEY §5) with per-phase breakdowns, derived
throughput, and an optional ``jax.profiler`` trace.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Iterator, Optional


@dataclasses.dataclass
class RunReport:
    duration_s: float
    cell_updates: int
    phases: Dict[str, float]

    @property
    def updates_per_sec(self) -> float:
        return self.cell_updates / self.duration_s if self.duration_s > 0 else 0.0

    def duration_line(self) -> str:
        """The reference's exact report line (gol-main.c:124-125)."""
        return (
            f"TOTAL DURATION : {self.duration_s:.5f}, "
            f"number of cell updates = {self.cell_updates}"
        )

    def throughput_line(self) -> str:
        """Extension: derived throughput (the BASELINE.json metric)."""
        return f"THROUGHPUT     : {self.updates_per_sec:.4g} cell-updates/sec"


class Stopwatch:
    """Accumulates named wall-clock phases; the whole-run phase is 'total'."""

    def __init__(self) -> None:
        self.phases: Dict[str, float] = {}

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + time.perf_counter() - t0

    def report(self, cell_updates: int, total_phase: str = "total") -> RunReport:
        return RunReport(
            duration_s=self.phases.get(total_phase, 0.0),
            cell_updates=cell_updates,
            phases=dict(self.phases),
        )


def force_ready(x) -> None:
    """Force device completion of every array in a pytree, robustly.

    ``jax.block_until_ready`` has been observed returning early on the
    tunneled single-TPU platform; a 1-element readback cannot return early
    (the output buffer must fully exist first) and moves only a few bytes.
    Every timed phase must end with this, or the reported ``TOTAL
    DURATION`` measures dispatch instead of execution.
    """
    import jax

    # block_until_ready alone can return early through the tunnel; the
    # readback alone only proves shard (0,...,0) finished on a sharded
    # array.  Both together cover single- and multi-device cases.
    jax.block_until_ready(x)
    leaves = jax.tree_util.tree_leaves(x)
    if jax.process_count() > 1 and any(
        not getattr(leaf, "is_fully_addressable", True) for leaf in leaves
    ):
        # A cross-process array: element (0,...,0) may not be addressable
        # here, so a barrier is the correct fence — mirroring the
        # reference's MPI_Barrier before the timing stop (gol-main.c:118).
        # Fully-addressable arrays fall through to the readback even in
        # multi-process jobs: they belong to a process-local computation
        # (e.g. a scalebench row only some processes run), and a global
        # barrier would deadlock against processes sitting that row out.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("gol_force_ready")
        return
    for leaf in leaves:
        if hasattr(leaf, "ndim"):
            leaf[(0,) * leaf.ndim].item()


def time_best(fn, arg_factory, repeats: int = 3) -> float:
    """Best-of-N wall-clock of ``fn(arg_factory())``, warm-compiled.

    ``arg_factory`` returns a fresh argument per call so donating functions
    never consume a buffer the next repeat needs.  One untimed call warms
    compilation; ``force_ready`` fences every timed call.  Shared by the
    halo-latency and weak-scaling harnesses (bench.py deliberately chains
    donated boards instead — copying its 256 MB boards through the device
    tunnel would dominate the measurement).
    """
    force_ready(fn(arg_factory()))
    best = float("inf")
    for _ in range(repeats):
        arg = arg_factory()
        # Fence the factory's (async) device work — e.g. a board copy —
        # so the timed window measures fn alone, not the copy it depends on.
        force_ready(arg)
        t0 = time.perf_counter()
        out = fn(arg)
        force_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best


def fit_overhead(walls: Dict[int, float]):
    """Two-point per-invocation-overhead fit (r5 measurement discipline).

    Wall time of one invocation of an n-step device loop through the
    tunnel is ``T(n) = a + b*n``: ``a`` the per-invocation overhead
    (RPC, dispatch, readback fence — 0.13-0.26 s depending on session)
    and ``b`` the device's per-step time.  Given best-wall samples at
    two (or more — the fit uses the extremes) loop lengths, returns
    ``(overhead_s, per_step_s)``.  Single-interval wall rates conflate
    the two and under-report the chip *differently per config*, so every
    cross-config conclusion must come from ``b``, never from walls
    (BASELINE.md r5).  One definition shared by ``bench.py`` and the
    ``benchmarks/exp_*_fit.py`` scripts so the artifacts cannot
    disagree on the arithmetic.
    """
    if len(walls) < 2:
        raise ValueError(f"need >= 2 loop lengths to fit, got {walls}")
    (n1, t1), *_, (n2, t2) = sorted(walls.items())
    b = (t2 - t1) / (n2 - n1)
    return t1 - b * n1, b


@contextlib.contextmanager
def maybe_profile(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace when a directory is given (else no-op).

    View with TensorBoard or xprof.  The runtime enters this around the
    timed generation loop only — compilation is warmed beforehand, so the
    trace shows steady-state device execution (the TPU-native upgrade over
    the reference's single wall-clock delta).
    """
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
