"""ctypes bindings to the native C++ runtime helpers (``native/golrt``).

The reference's host runtime is native C/CUDA; our TPU compute path is
XLA-compiled, but the host-side runtime hot spots — formatting multi-GB
world dumps (gol_printWorld, gol-main.c:17-28) and bit-pack/unpack between
the dense and bit-packed engines — are implemented in C++
(``native/golrt.cpp``) and loaded here via ctypes.  Every entry point has a
pure-Python fallback (in :mod:`gol_tpu.utils.io` / :mod:`gol_tpu.ops.bitlife`);
``available()`` gates usage so the framework works before ``make -C native``.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

_LIB_NAMES = ("libgolrt.so",)
_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _candidate_paths():
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    for name in _LIB_NAMES:
        yield os.path.join(here, "native", name)
        yield os.path.join(here, name)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    for path in _candidate_paths():
        if os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            lib.golrt_format_world_size.restype = ctypes.c_size_t
            lib.golrt_format_world_size.argtypes = [
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
            ]
            lib.golrt_format_world.restype = ctypes.c_size_t
            lib.golrt_format_world.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_char),
            ]
            lib.golrt_write_rank_file.restype = ctypes.c_int
            lib.golrt_write_rank_file.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
            ]
            lib.golrt_pack_bits.restype = None
            lib.golrt_pack_bits.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.golrt_unpack_bits.restype = None
            lib.golrt_unpack_bits.argtypes = [
                ctypes.POINTER(ctypes.c_uint32),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8),
            ]
            _lib = lib
            break
    return _lib


def available() -> bool:
    return _load() is not None


def format_world(block: np.ndarray, rank: int) -> bytes:
    """Native renderer; byte-identical to utils.io.format_world."""
    lib = _load()
    assert lib is not None
    block = np.ascontiguousarray(block, dtype=np.uint8)
    h, w = block.shape
    size = lib.golrt_format_world_size(h, w, h * rank)
    buf = ctypes.create_string_buffer(size)
    n = lib.golrt_format_world(
        block.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, h * rank, buf
    )
    return buf.raw[:n]


def write_rank_file(path: str, block: np.ndarray, rank: int) -> None:
    lib = _load()
    assert lib is not None
    block = np.ascontiguousarray(block, dtype=np.uint8)
    h, w = block.shape
    rc = lib.golrt_write_rank_file(
        path.encode(), block.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), h, w, rank
    )
    if rc != 0:
        raise OSError(f"native writer failed for {path} (rc={rc})")


def pack_bits(cells: np.ndarray) -> np.ndarray:
    """uint8[n*32] 0/1 cells -> uint32[n] words, bit i of word j = cell j*32+i."""
    lib = _load()
    assert lib is not None
    cells = np.ascontiguousarray(cells, dtype=np.uint8)
    assert cells.size % 32 == 0
    out = np.empty(cells.size // 32, dtype=np.uint32)
    lib.golrt_pack_bits(
        cells.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        cells.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


def unpack_bits(words: np.ndarray) -> np.ndarray:
    lib = _load()
    assert lib is not None
    words = np.ascontiguousarray(words, dtype=np.uint32)
    out = np.empty(words.size * 32, dtype=np.uint8)
    lib.golrt_unpack_bits(
        words.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        words.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out
