"""The five static checks over a traced engine program.

Each check consumes the jaxpr (or AOT-compiled executable) of one engine
configuration and returns findings; none of them needs a TPU.  What they
pin:

- **comm** — every ``lax.ppermute`` is a valid ±1 ring over the right
  mesh axis, both directions are exchanged at every site, the shipped
  halo slab is deep enough for the temporal-blocking contract
  (slab depth × axis quantum ≥ stencil radius × generations per
  exchange, the :func:`gol_tpu.parallel.halo.halo_extend` contract), and
  single-device programs contain no collectives at all.
- **dtype** — the engines are integer programs end to end: any float
  aval is an upcast leak (8× the HBM bytes for the packed tiers); the
  packed tiers additionally stay inside {uint8, uint32, int32, bool}.
- **purity** — no host callbacks / infeed inside compiled generation
  loops: one ``debug_callback`` would serialize every loop iteration on
  a host round-trip (the per-step ``cudaDeviceSynchronize`` this
  framework exists to delete).
- **donation + cost** — the donated input buffer is actually reused
  (XLA input/output aliasing — the double buffer; a dropped alias
  doubles peak HBM), and the compiled FLOP count matches the audited
  per-cell/per-word op model in :mod:`gol_tpu.utils.roofline` within
  2×.  The strict gate applies where the model is exact (depth-1 XLA
  engines): XLA's HLO cost analysis counts loop *bodies* once (trip
  counts are dynamic) and counts fusion recompute, so deep-unrolled
  chunks and interpret-mode Pallas get attribution findings, not gates.
- **retrace** — a chunk schedule must compile once per distinct chunk
  size, never per chunk: engine builders must return cached programs
  for repeated (mesh, steps) keys, and dispatching the jitted engine
  twice on identical buffers must hit the trace cache.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import List, Sequence, Tuple

import numpy as np

from gol_tpu.analysis import walker
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    WARN,
    CheckResult,
    Finding,
)

STENCIL_RADIUS = 1  # Moore neighborhood: one ghost layer per generation

# Host-interaction primitives that must never appear inside a compiled
# generation loop.
IMPURE_PRIMITIVES = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "debug_print",
        "host_callback",
        "outside_call",
        "infeed",
        "outfeed",
    }
)

# Any collective: single-device programs must have none.
COLLECTIVE_PRIMITIVES = frozenset(
    {"ppermute", "psum", "pmax", "pmin", "all_gather", "all_to_all",
     "reduce_scatter"}
)

ALLOWED_DTYPES_PACKED = ("uint8", "uint32", "int32", "bool")


def ring_perm(n: int, shift: int) -> frozenset:
    """The ±1 ring permutation pairs (mirrors parallel.halo.ring)."""
    return frozenset((i, (i + shift) % n) for i in range(n))


# ---------------------------------------------------------------------------
# comm
# ---------------------------------------------------------------------------


def expected_exchange_plan(
    engine: str, shard_mode: str, halo_depth: int, steps: int
) -> Tuple[int, int]:
    """(generations per full exchange, remainder generations).

    Mirrors the engines' documented chunking: every ring mode —
    explicit, the depth-k overlap split, and the cross-chunk pipeline —
    ships one ``halo_depth``-deep band per ``halo_depth`` generations
    (plus one remainder chunk; the pipeline's remainder *consumes* the
    carried band instead of exchanging, and its prologue exchange rides
    outside the loop at full depth, which ``supplied >= need`` admits);
    the sharded Pallas engine always runs 8-aligned bands.
    """
    if engine == "pallas_bitpack":
        depth = 8 if halo_depth == 1 else halo_depth
        return depth, steps % depth
    return halo_depth, steps % halo_depth


def slab_depth(engine: str, axis_name: str, shape: Sequence[int]) -> int:
    """Exchanged ghost-band depth of one ppermute operand.

    Engine slab conventions (pinned by the engines' own layouts): row and
    plane bands carry their depth on array axis 0 — ``(k, words)`` /
    ``(k, W)`` slices; column bands on axis 1 — the ``(h+2k, k)`` edge
    columns of the row-extended block — except the sharded Pallas
    engine's 1-word column band, which rides transposed ``(words, rows)``
    for the kernel's lane layout.  3-D volume bands (rank-3 operands)
    carry their depth on the phase's own axis: planes 0, rows 1, cols 2.
    """
    if len(shape) == 3:
        return shape[{"planes": 0, "rows": 1, "cols": 2}[axis_name]]
    if axis_name == "cols":
        return shape[0] if engine == "pallas_bitpack" else shape[1]
    return shape[0]


def axis_quantum_cells(engine: str, axis_name: str) -> int:
    """Cells of halo covered per unit of exchanged slab depth.

    The packed engines' horizontal ghost quantum is the 32-cell word
    (one word column serves 32 generations of column light cone); every
    other axis exchanges at cell/row granularity.
    """
    if engine in ("bitpack", "pallas_bitpack") and axis_name == "cols":
        from gol_tpu.ops import bitlife

        return bitlife.BITS
    return 1


def check_comm(jaxpr, cfg, mesh) -> CheckResult:
    """Verify ring permutations and halo-depth sufficiency."""
    findings: List[Finding] = []
    pp = walker.find_eqns(jaxpr, ["ppermute"])

    if mesh is None:
        extra = [
            i.name
            for i in walker.iter_eqns(jaxpr)
            if i.name in COLLECTIVE_PRIMITIVES
        ]
        if extra:
            findings.append(
                Finding(
                    ERROR,
                    "comm",
                    f"single-device program contains collectives: {extra}",
                )
            )
        else:
            findings.append(
                Finding(INFO, "comm", "single-device: no collectives, as required")
            )
        return CheckResult.from_findings("comm", findings)

    if cfg.shard_mode == "auto":
        # XLA SPMD inserts collective-permutes at partition time; the
        # jaxpr legitimately has none.  The compiled-HLO side is covered
        # by check_donation_cost's lowering (see run_config).
        if pp:
            findings.append(
                Finding(
                    WARN,
                    "comm",
                    "auto-SPMD program unexpectedly contains explicit "
                    f"ppermutes ({len(pp)})",
                )
            )
        return CheckResult.from_findings("comm", findings)

    if not pp:
        findings.append(
            Finding(
                ERROR,
                "comm",
                "sharded explicit/overlap/pipeline program contains no "
                "ppermute — "
                "shards would evolve independently (the reference's bug "
                "B1, permanently)",
            )
        )
        return CheckResult.from_findings("comm", findings)

    g_full, g_rem = expected_exchange_plan(
        cfg.engine, cfg.shard_mode, cfg.halo_depth, max(cfg.schedule)
    )

    # Group sites by (mesh axis, in generation loop or remainder tail).
    sites = {}
    for info in pp:
        axis_name = info.eqn.params["axis_name"]
        axis = axis_name[0] if isinstance(axis_name, tuple) else axis_name
        sites.setdefault((axis, info.in_loop), []).append(info)

    for (axis, in_loop), infos in sorted(sites.items(), key=str):
        n = mesh.shape.get(axis)
        if n is None:
            findings.append(
                Finding(
                    ERROR,
                    "comm",
                    f"ppermute over axis {axis!r} which is not a mesh "
                    f"axis of {dict(mesh.shape)}",
                )
            )
            continue
        fwd, bwd = ring_perm(n, 1), ring_perm(n, -1)
        dirs = set()
        for info in infos:
            perm = frozenset(tuple(p) for p in info.eqn.params["perm"])
            if perm == fwd:
                dirs.add(+1)
            elif perm == bwd:
                dirs.add(-1)
            else:
                findings.append(
                    Finding(
                        ERROR,
                        "comm",
                        f"axis {axis!r}: ppermute permutation "
                        f"{sorted(perm)} is not a ±1 ring over {n} "
                        "devices — halos would come from the wrong "
                        "neighbor",
                    )
                )
        if fwd == bwd:
            # n <= 2: the ±1 rings coincide (each shard's neighbor is
            # the same device both ways); direction balance is vacuous.
            dirs = {+1, -1} if dirs else dirs
        if len(infos) >= 2 and dirs and dirs != {+1, -1}:
            findings.append(
                Finding(
                    ERROR,
                    "comm",
                    f"axis {axis!r}: both ring directions must be "
                    f"exchanged per site, saw shifts {sorted(dirs)} only",
                )
            )

        # Halo-depth sufficiency.  The slab rides the smallest dimension
        # of the ppermute operand (boards are sized so shard extents
        # strictly exceed band depths).
        need = g_full if in_loop else g_rem
        if need == 0:
            continue
        quantum = axis_quantum_cells(cfg.engine, axis)
        depth = min(
            slab_depth(cfg.engine, axis, i.eqn.invars[0].aval.shape)
            for i in infos
        )
        supplied = depth * quantum
        if supplied < STENCIL_RADIUS * need:
            findings.append(
                Finding(
                    ERROR,
                    "comm",
                    f"axis {axis!r} ({'loop' if in_loop else 'tail'}): "
                    f"exchanged halo depth {depth} (×{quantum} cells) < "
                    f"stencil radius {STENCIL_RADIUS} × {need} "
                    "generations per exchange — the outermost "
                    "generations would read stale or uninitialized ghost "
                    "cells",
                )
            )
        elif supplied > 4 * STENCIL_RADIUS * max(need, 8, quantum):
            # (quantum in the slack: a word-column axis cannot ship finer
            # than 32 cells, so k-word bands at small k are convention,
            # not waste)
            findings.append(
                Finding(
                    WARN,
                    "comm",
                    f"axis {axis!r}: exchanged depth {supplied} cells is "
                    f">4× the {need} generations it serves — wasted "
                    "ring bandwidth",
                )
            )
        else:
            findings.append(
                Finding(
                    INFO,
                    "comm",
                    f"axis {axis!r} ({'loop' if in_loop else 'tail'}): "
                    f"{len(infos)} ppermutes, slab depth {depth} "
                    f"(quantum {quantum}) serves {need} gens",
                )
            )
    return CheckResult.from_findings("comm", findings)


# ---------------------------------------------------------------------------
# dtype
# ---------------------------------------------------------------------------


def check_dtype(jaxpr, cfg) -> CheckResult:
    """No float avals anywhere; packed tiers stay in the word dtypes."""
    findings: List[Finding] = []
    packed = cfg.engine in ("bitpack", "pallas_bitpack")
    float_hits = {}
    alien_hits = {}
    for info, aval in walker.all_avals(jaxpr):
        dtype = getattr(aval, "dtype", None)
        if dtype is None:
            continue
        # Pallas DMA semaphores and scratch refs are bookkeeping, not
        # cell state; only value avals can leak board dtypes.  (A float
        # VMEM scratch would still surface through the values computed
        # from it.)
        if "Ref" in type(aval).__name__ or "Semaphore" in type(aval).__name__:
            continue
        try:
            name = np.dtype(dtype).name
        except TypeError:  # extended/opaque dtype (pallas internals)
            continue
        if np.issubdtype(dtype, np.floating) or np.issubdtype(
            dtype, np.complexfloating
        ):
            float_hits.setdefault((name, info.name), 0)
            float_hits[(name, info.name)] += 1
        elif packed and name not in ALLOWED_DTYPES_PACKED:
            alien_hits.setdefault((name, info.name), 0)
            alien_hits[(name, info.name)] += 1
    for (name, prim), count in sorted(float_hits.items()):
        findings.append(
            Finding(
                ERROR,
                "dtype",
                f"float leak: {count}× {name} aval(s) at primitive "
                f"{prim!r} — the engines are integer programs; a float "
                "upcast multiplies HBM traffic and breaks bit-exactness",
            )
        )
    for (name, prim), count in sorted(alien_hits.items()):
        findings.append(
            Finding(
                ERROR,
                "dtype",
                f"packed-tier dtype leak: {count}× {name} aval(s) at "
                f"primitive {prim!r}; allowed: {ALLOWED_DTYPES_PACKED}",
            )
        )
    if not findings:
        findings.append(
            Finding(INFO, "dtype", "all avals integer/bool, as required")
        )
    return CheckResult.from_findings("dtype", findings)


# ---------------------------------------------------------------------------
# purity
# ---------------------------------------------------------------------------


def check_purity(jaxpr, cfg) -> CheckResult:
    """No host callbacks / infeed anywhere in the compiled program."""
    findings: List[Finding] = []
    for info in walker.iter_eqns(jaxpr):
        if info.name in IMPURE_PRIMITIVES:
            where = "inside the generation loop" if info.in_loop else (
                "in the compiled program"
            )
            findings.append(
                Finding(
                    ERROR,
                    "purity",
                    f"host-interaction primitive {info.name!r} {where} "
                    f"(path {'/'.join(info.path) or 'top'}) — every "
                    "iteration would pay a host round-trip",
                )
            )
    if not findings:
        findings.append(
            Finding(INFO, "purity", "no host callbacks in the traced program")
        )
    return CheckResult.from_findings("purity", findings)


def check_stats_purity(rt, cfg, spec, take) -> CheckResult:
    """The ``--stats`` wrapper adds reductions only — never callbacks.

    Builds the stats-mode program through the *real* runtime path
    (:func:`gol_tpu.telemetry.stats.build_stats_evolver` on a
    ``stats=True`` sibling of the verified runtime) and re-runs the
    purity scan over its jaxpr: the chunk statistics must stay in-graph
    (fused reductions, psums on a mesh), because one ``debug_callback``
    smuggled in for "just a population print" would serialize every
    chunk on a host round-trip — precisely the failure mode the
    stats subsystem exists to avoid.  ``stale_t0`` configs are skipped
    (their frozen-halo operands are bound at board init, not trace
    time; stats mode is a fresh-run observability feature).
    """
    if cfg.halo_mode != "fresh":
        return CheckResult.skipped(
            "stats-purity", "stale_t0 runs bind frozen halos at init"
        )
    from gol_tpu.telemetry import stats as stats_mod

    findings: List[Finding] = []
    try:
        rt_stats = dataclasses.replace(rt, stats=True)
        sfn, sdyn = stats_mod.build_stats_evolver(rt_stats, take)
        sjaxpr = walker.trace_jaxpr(sfn, spec, *sdyn)
    except Exception as e:
        findings.append(
            Finding(
                ERROR,
                "stats-purity",
                f"stats-mode program failed to build/trace: {e}",
            )
        )
        return CheckResult.from_findings("stats-purity", findings)
    for info in walker.iter_eqns(sjaxpr):
        if info.name in IMPURE_PRIMITIVES:
            findings.append(
                Finding(
                    ERROR,
                    "stats-purity",
                    f"host-interaction primitive {info.name!r} in the "
                    f"stats-mode program (path "
                    f"{'/'.join(info.path) or 'top'}) — chunk statistics "
                    "must be in-graph reductions, never callbacks",
                )
            )
    if not findings:
        findings.append(
            Finding(
                INFO,
                "stats-purity",
                "stats-mode program traced pure (reductions only)",
            )
        )
    return CheckResult.from_findings("stats-purity", findings)


# ---------------------------------------------------------------------------
# donation + cost
# ---------------------------------------------------------------------------


def _cost_dict(compiled) -> dict:
    try:
        ca = compiled.cost_analysis()
    except Exception:  # backend without cost analysis
        return {}
    if isinstance(ca, list):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def check_donation(compiled, cfg, shard_bytes: int, compile_warnings=())\
        -> CheckResult:
    """The donated input buffer must be reused by the executable."""
    findings: List[Finding] = []
    for w in compile_warnings:
        if "donat" in str(w.message).lower():
            findings.append(
                Finding(ERROR, "donation", f"XLA: {w.message}")
            )
    alias = None
    try:
        alias = compiled.memory_analysis().alias_size_in_bytes
    except Exception:
        pass
    if alias is not None:
        if alias >= shard_bytes:
            findings.append(
                Finding(
                    INFO,
                    "donation",
                    f"{alias} bytes aliased (≥ shard {shard_bytes}) — "
                    "double buffer in place",
                )
            )
        else:
            findings.append(
                Finding(
                    ERROR,
                    "donation",
                    f"only {alias} bytes aliased but the donated shard "
                    f"is {shard_bytes} bytes — the double buffer is "
                    "broken and peak HBM doubles",
                )
            )
    elif "input_output_alias" in compiled.as_text():
        findings.append(
            Finding(INFO, "donation", "input_output_alias present in HLO")
        )
    else:
        findings.append(
            Finding(
                ERROR,
                "donation",
                "no input/output aliasing in the compiled executable",
            )
        )
    return CheckResult.from_findings("donation", findings)


def check_cost(compiled, cfg, mesh, num_devices: int) -> CheckResult:
    """Cross-check compiled FLOPs against the roofline op model."""
    from gol_tpu.utils import roofline

    findings: List[Finding] = []
    ca = _cost_dict(compiled)
    flops = ca.get("flops")
    bytes_accessed = ca.get("bytes accessed")
    if not flops:
        return CheckResult.skipped(
            "cost", "backend reported no FLOP count for this executable"
        )

    h, w = cfg.board_shape
    shard_cells = (h * w) // max(num_devices, 1)
    take = max(cfg.schedule)
    model = roofline.xla_flops_model(
        cfg.engine,
        shard_cells,
        take,
        cfg.halo_depth,
        sharded=mesh is not None,
    )
    ratio = flops / model if model else float("nan")
    attribution = (
        f"compiled flops {flops:.0f} vs model {model:.0f} "
        f"(ratio {ratio:.2f}; XLA counts loop bodies once)"
    )
    if cfg.cost_gate and model:
        if ratio > roofline.XLA_COST_DRIFT or ratio < 1 / roofline.XLA_COST_DRIFT:
            findings.append(
                Finding(
                    ERROR,
                    "cost",
                    f"{attribution} — drift exceeds "
                    f"{roofline.XLA_COST_DRIFT}×; the engine is doing "
                    "work the op model does not predict",
                )
            )
        else:
            findings.append(Finding(INFO, "cost", attribution))
    else:
        findings.append(
            Finding(
                INFO,
                "cost",
                attribution
                + " [attribution only: fusion recompute / interpret-mode "
                "Pallas make deep-unrolled counts non-gateable]",
            )
        )
    if bytes_accessed:
        findings.append(
            Finding(
                INFO,
                "cost",
                f"bytes accessed {bytes_accessed:.0f} "
                f"({bytes_accessed / max(shard_cells, 1):.1f}/cell of one "
                "loop body)",
            )
        )
    return CheckResult.from_findings("cost", findings)


# ---------------------------------------------------------------------------
# retrace
# ---------------------------------------------------------------------------


def check_retrace(
    rt,
    cfg,
    make_board,
    execute: bool = True,
) -> CheckResult:
    """A chunk schedule compiles once per distinct size, never per chunk.

    ``make_board`` builds a fresh donated-safe concrete board (called per
    execution because the engines consume their input).
    """
    findings: List[Finding] = []
    schedule = list(cfg.schedule)

    # 1. Builder stability: repeated takes must yield the identical
    # program object (the lru_cache contract of the engine builders).
    seen = {}
    for take in schedule + schedule:
        fn, _, _ = rt._evolve_fn(take)
        seen.setdefault(take, set()).add(id(fn))
    unstable = {t: ids for t, ids in seen.items() if len(ids) > 1}
    if unstable:
        findings.append(
            Finding(
                ERROR,
                "retrace",
                f"engine builder returned a fresh program object for "
                f"repeated chunk sizes {sorted(unstable)} — every chunk "
                "would retrace and recompile",
            )
        )
    else:
        findings.append(
            Finding(
                INFO,
                "retrace",
                f"{len(seen)} distinct programs for "
                f"{len(schedule)}-chunk schedule {schedule}",
            )
        )

    # 2. Dispatch stability: a second call on identical buffers must hit
    # the trace cache.
    if execute and not unstable:
        take = min(schedule)
        fn, dynamic, static = rt._evolve_fn(take)
        size = getattr(fn, "_cache_size", None)
        if size is None:
            findings.append(
                Finding(
                    WARN,
                    "retrace",
                    "jit cache size introspection unavailable; dispatch "
                    "check skipped",
                )
            )
        else:
            fn(make_board(), *dynamic, *static)
            warm = size()
            fn(make_board(), *dynamic, *static)
            if size() > warm:
                findings.append(
                    Finding(
                        ERROR,
                        "retrace",
                        "identical dispatch added a trace-cache entry — "
                        "the engine retraces per call (unstable static "
                        "argument or unhashable key)",
                    )
                )
    return CheckResult.from_findings("retrace", findings)


# ---------------------------------------------------------------------------
# driver: one config end to end
# ---------------------------------------------------------------------------


def run_config(cfg, execute_retrace: bool = True):
    """All checks over one :class:`EngineConfig`; returns EngineReport."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.analysis.report import EngineReport, FAIL, PASS
    from gol_tpu.parallel import mesh as mesh_mod

    report = EngineReport(config_name=cfg.name)

    if cfg.reject_reason is not None:
        try:
            cfg.build_runtime()
        except ValueError as e:
            report.rejected = str(e).splitlines()[0]
            report.checks.append(
                CheckResult("config", PASS, [
                    Finding(INFO, "config", f"rejected: {e}")
                ])
            )
        else:
            report.checks.append(
                CheckResult("config", FAIL, [
                    Finding(
                        ERROR,
                        "config",
                        "runtime accepted a combination it must reject "
                        f"({cfg.reject_reason})",
                    )
                ])
            )
        return report

    try:
        rt = cfg.build_runtime()
    except Exception as e:  # config must build
        report.checks.append(
            CheckResult("config", FAIL, [
                Finding(ERROR, "config", f"runtime failed to build: {e}")
            ])
        )
        return report

    mesh = rt.mesh
    h, w = cfg.board_shape
    if mesh is not None:
        spec = jax.ShapeDtypeStruct(
            (h, w), jnp.uint8, sharding=mesh_mod.board_sharding(mesh)
        )
    else:
        spec = jax.ShapeDtypeStruct((h, w), jnp.uint8)

    if cfg.halo_mode == "stale_t0":
        # Frozen t=0 halos are dynamic inputs; abstract stand-ins trace
        # and lower identically.
        halo = jax.ShapeDtypeStruct((cfg.num_ranks, w), jnp.uint8)
        rt._halos = (halo, halo)

    take = max(cfg.schedule)
    fn, dynamic, static = rt._evolve_fn(take)
    jaxpr = walker.trace_jaxpr(fn, spec, *dynamic, *static)

    report.checks.append(check_comm(jaxpr, cfg, mesh))
    report.checks.append(check_dtype(jaxpr, cfg))
    report.checks.append(check_purity(jaxpr, cfg))
    report.checks.append(check_stats_purity(rt, cfg, spec, take))

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        compiled = fn.lower(spec, *dynamic, *static).compile()
    num_devices = 1 if mesh is None else mesh.devices.size
    shard_bytes = (h * w) // max(num_devices, 1)  # uint8: 1 byte/cell
    report.checks.append(
        check_donation(compiled, cfg, shard_bytes, caught)
    )
    report.checks.append(check_cost(compiled, cfg, mesh, num_devices))

    if cfg.shard_mode == "auto" and mesh is not None:
        # The comm invariant for auto-SPMD lives in the partitioned HLO.
        txt = compiled.as_text()
        ok = "collective-permute" in txt or "all-to-all" in txt
        report.checks.append(
            CheckResult.from_findings("comm-hlo", [
                Finding(
                    INFO if ok else ERROR,
                    "comm-hlo",
                    "partitioned HLO contains collective-permute"
                    if ok
                    else "auto-SPMD compiled program has no collective — "
                    "XLA failed to derive the halo exchange and shards "
                    "evolve independently",
                )
            ])
        )

    def make_board():
        rng = np.random.default_rng(2026)
        board = jnp.asarray(
            (rng.random((h, w)) < 0.33).astype(np.uint8)
        )
        if mesh is not None:
            return mesh_mod.place_private(
                board, mesh_mod.board_sharding(mesh)
            )
        return board

    if cfg.halo_mode == "stale_t0":
        # Execution would need concrete halos; builder stability is the
        # meaningful half here.
        from gol_tpu.parallel import engine as engine_mod

        board0 = make_board()
        rt._halos = engine_mod.frozen_halos(board0, cfg.num_ranks)
        execute_retrace = False
    exec_ok = execute_retrace and cfg.engine not in (
        "pallas",
        "pallas_bitpack",
    )
    report.checks.append(
        check_retrace(rt, cfg, make_board, execute=exec_ok)
    )
    return report
