"""lockwatch — env-gated runtime witness for the static lock graph.

lockcheck (static) proves the lock-order graph acyclic from the AST;
this module records what *actually* happens at runtime so a serve run
can assert the dynamic acquisition order is a subgraph of the static
one.  Off by default and zero-cost when off: ``maybe_wrap`` returns
the raw lock unless ``GOL_LOCKWATCH=1`` is set, so production paths
carry no indirection.

Usage (already wired in the serve scheduler and metrics registry)::

    self._lock = lockwatch.maybe_wrap(
        "ServeScheduler._lock", threading.RLock()
    )

With the env var set, every acquisition records a per-thread held
stack and emits ``(outermost_held, acquired)`` edges into a module
registry; :func:`check` returns the edges that violate a static edge
set and :func:`find_cycle` reuses lockcheck's cycle detector.  The
serve stress test runs with the recorder on and asserts (a) no cycle
and (b) every dynamic edge appears in lockcheck's static graph.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

ENV_VAR = "GOL_LOCKWATCH"

_registry_lock = threading.Lock()
_edges: Set[Tuple[str, str]] = set()
_acquires: Dict[str, int] = {}
_tls = threading.local()


def enabled() -> bool:
    return bool(os.environ.get(ENV_VAR))


def reset() -> None:
    with _registry_lock:
        _edges.clear()
        _acquires.clear()


def edges() -> Set[Tuple[str, str]]:
    with _registry_lock:
        return set(_edges)


def acquire_counts() -> Dict[str, int]:
    with _registry_lock:
        return dict(_acquires)


class WatchedLock:
    """Context-manager/acquire-release proxy that records order."""

    def __init__(self, name: str, lock) -> None:
        self.name = name
        self._lock = lock

    def _held_stack(self) -> List[str]:
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        return stack

    def acquire(self, *args, **kwargs):
        got = self._lock.acquire(*args, **kwargs)
        if got:
            stack = self._held_stack()
            with _registry_lock:
                _acquires[self.name] = _acquires.get(self.name, 0) + 1
                if self.name not in stack:  # reentrancy adds no edge
                    for held in stack:
                        _edges.add((held, self.name))
            stack.append(self.name)
        return got

    def release(self) -> None:
        stack = self._held_stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        elif self.name in stack:  # out-of-order release; stay balanced
            stack.remove(self.name)
        self._lock.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def maybe_wrap(name: str, lock):
    """The one call sites use: free when the recorder is off."""
    if not enabled():
        return lock
    return WatchedLock(name, lock)


def find_cycle() -> Optional[List[str]]:
    from gol_tpu.analysis.lockcheck import find_cycle as _fc

    return _fc({e: ("", 0) for e in edges()})


def check(static_edges: Set[Tuple[str, str]]) -> Set[Tuple[str, str]]:
    """Dynamic edges the static lock-order graph does not predict."""
    return edges() - set(static_edges)
