"""Static checks over the activity-gated programs (docs/SPARSE.md).

The activity matrix — one report per engine form × mesh — proves the
three invariants the sparse tier lives or dies by, the same way the
engine and batch matrices do:

- **activity purity** — the gated chunk programs contain no host
  callbacks (the worklist's ``nonzero``/gather/scatter and the
  ``lax.cond`` fallback are all in-graph; a host round-trip per
  generation would re-create the per-step sync the repo exists to
  avoid).  Sharded forms additionally may contain *only* ppermute/psum
  collectives (the mask/halo exchange and the replicated counters) —
  anything else means the gating grew an unplanned gather.
- **gated equivalence** — executed: an activity run from the all-ones
  mask is bit-identical to the dense reference on a moving-object board
  (a glider, whose translation visits tiles the initial activity has
  long left), *and* actually skips tiles while doing it.
- **mask-soundness teeth** — the reason the equivalence check can be
  trusted: a deliberately-broken gen that **under-dilates** (gates on
  the raw changed mask, skipping the one-tile neighborhood) must
  visibly diverge from the dense oracle on the same board.  If the
  broken fixture ever matches the oracle, the soundness property has
  lost its witness and the check fails — the broken-fixture discipline
  of the verifier applied to the dilation invariant.

Run as part of ``python -m gol_tpu.analysis``; one
:class:`~gol_tpu.analysis.report.EngineReport` per configuration.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from gol_tpu.analysis import walker
from gol_tpu.analysis.checks import (
    COLLECTIVE_PRIMITIVES,
    IMPURE_PRIMITIVES,
    check_dtype,
)
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

STEPS = 24  # generations per executed check: the glider crosses tiles
TILE = 8  # default mask tile edge (packed configs use the 32-cell word)
CAPACITY = 24  # tiles; ample for one dilated glider, small vs the grid

#: Collectives the sharded activity program may legitimately contain.
ALLOWED_COLLECTIVES = frozenset({"ppermute", "psum"})


@dataclasses.dataclass(frozen=True)
class SparseConfig:
    """One cell of the activity verification matrix."""

    name: str
    mesh: str  # none / 1d / 2d
    packed: bool = False
    size: int = 64  # square board edge
    tile: int = TILE  # mask tile edge (word-quantized when packed)
    engine: str = "activity"  # for check_dtype's packed-tier keying


def default_sparse_matrix() -> List[SparseConfig]:
    return [
        SparseConfig("activity/none/dense", "none"),
        SparseConfig("activity/none/packed", "none", packed=True,
                     size=128, tile=32),
        SparseConfig("activity/1d", "1d"),
        SparseConfig("activity/2d", "2d"),
    ]


def _build_mesh(kind: str):
    import jax

    from gol_tpu.parallel import mesh as mesh_mod

    if kind == "none":
        return None
    devices = jax.devices()
    if len(devices) < 4:
        raise RuntimeError(
            f"activity config needs 4 devices, have {len(devices)}"
        )
    if kind == "1d":
        return mesh_mod.make_mesh_1d(4, devices=devices[:4])
    return mesh_mod.make_mesh_2d((2, 2), devices=devices[:4])


def _build(cfg: SparseConfig):
    """(jitted_fn, arg_specs, mesh) exactly as GolRuntime dispatches."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.models.state import CELL_DTYPE
    from gol_tpu.sparse import engine as sparse_engine
    from gol_tpu.sparse import mask as sparse_mask

    mesh = _build_mesh(cfg.mesh)
    th, tw = sparse_mask.grid_shape(cfg.size, cfg.size, cfg.tile)
    if mesh is None:
        fn = (
            sparse_engine.evolve_gated_packed
            if cfg.packed
            else sparse_engine.evolve_gated_dense
        )
        board_spec = jax.ShapeDtypeStruct((cfg.size, cfg.size), CELL_DTYPE)
        mask_spec = jax.ShapeDtypeStruct((th, tw), jnp.bool_)
        statics = (STEPS, cfg.tile, CAPACITY)
        return fn, (board_spec, mask_spec), statics, mesh
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import sparse as par_sparse

    fn = par_sparse.compiled_evolve_activity(mesh, STEPS, cfg.tile, CAPACITY)
    board_spec = jax.ShapeDtypeStruct(
        (cfg.size, cfg.size),
        CELL_DTYPE,
        sharding=mesh_mod.board_sharding(mesh),
    )
    mask_spec = jax.ShapeDtypeStruct(
        (th, tw), jnp.bool_, sharding=par_sparse.mask_sharding(mesh)
    )
    return fn, (board_spec, mask_spec), (), mesh


def check_activity_purity(jaxpr, cfg: SparseConfig) -> CheckResult:
    """No host callbacks; collectives only where the mesh form earns
    them (mask/halo ppermute + counter psum)."""
    findings: List[Finding] = []
    collectives = set()
    for info in walker.iter_eqns(jaxpr):
        if info.name in IMPURE_PRIMITIVES:
            findings.append(
                Finding(
                    ERROR,
                    "activity-purity",
                    f"host-interaction primitive {info.name!r} in the "
                    f"gated program (path {'/'.join(info.path) or 'top'})"
                    " — the worklist must gate in-graph, not per-step on "
                    "host",
                )
            )
        if info.name in COLLECTIVE_PRIMITIVES:
            collectives.add(info.name)
    if cfg.mesh == "none" and collectives:
        findings.append(
            Finding(
                ERROR,
                "activity-purity",
                f"collectives {sorted(collectives)} in the single-device "
                "gated program",
            )
        )
    elif cfg.mesh != "none":
        alien = collectives - ALLOWED_COLLECTIVES
        if alien:
            findings.append(
                Finding(
                    ERROR,
                    "activity-purity",
                    f"unexpected collectives {sorted(alien)}; the sharded "
                    "activity program earns ppermute (mask/halo ring) and "
                    "psum (replicated counters) only",
                )
            )
        if "ppermute" not in collectives:
            findings.append(
                Finding(
                    ERROR,
                    "activity-purity",
                    "no ppermute in the sharded gated program — the mask/"
                    "halo exchange is missing; a glider crossing a shard "
                    "seam would never reactivate the neighbor's tiles",
                )
            )
    if not findings:
        findings.append(
            Finding(
                INFO,
                "activity-purity",
                "gated program traced pure"
                + (
                    f"; collectives: {sorted(collectives)}"
                    if collectives
                    else "; no collectives"
                ),
            )
        )
    return CheckResult.from_findings("activity-purity", findings)


def _glider_board(size: int) -> np.ndarray:
    from gol_tpu.models import patterns

    # Offset so the glider's path crosses tile AND shard seams early.
    return patterns.init_sparse_world(
        "glider", size, size, (size // 2 - 2, size // 2 - 2)
    )


def _run_activity(cfg: SparseConfig, fn, statics, mesh, board_np):
    import jax

    from gol_tpu.sparse import mask as sparse_mask

    th, tw = sparse_mask.grid_shape(cfg.size, cfg.size, cfg.tile)
    mask0 = np.ones((th, tw), bool)
    if mesh is None:
        return fn(board_np, mask0, *statics)
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import sparse as par_sparse

    board = mesh_mod.shard_board(board_np, mesh)
    mask = jax.device_put(mask0, par_sparse.mask_sharding(mesh))
    return fn(board, mask)


def check_gated_equivalence(cfg: SparseConfig, fn, statics, mesh) -> CheckResult:
    """Executed: gated == dense on a translating glider, with skips."""
    import jax.numpy as jnp

    from gol_tpu.ops import stencil
    from gol_tpu.sparse import mask as sparse_mask

    findings: List[Finding] = []
    board_np = _glider_board(cfg.size)
    ref = np.asarray(stencil.run(jnp.asarray(board_np), STEPS))
    out, _, act = _run_activity(cfg, fn, statics, mesh, board_np)
    th, tw = sparse_mask.grid_shape(cfg.size, cfg.size, cfg.tile)
    tile_gens = th * tw * STEPS
    computed = int(act["computed_tile_gens"])
    if not np.array_equal(np.asarray(out), ref):
        findings.append(
            Finding(
                ERROR,
                "gated-equivalence",
                f"activity run diverges from the dense reference after "
                f"{STEPS} generations of a translating glider",
            )
        )
    elif computed >= tile_gens:
        findings.append(
            Finding(
                ERROR,
                "gated-equivalence",
                f"activity run computed {computed}/{tile_gens} tile-gens "
                "— it never skipped anything; the gate is not gating",
            )
        )
    else:
        findings.append(
            Finding(
                INFO,
                "gated-equivalence",
                f"bit-equal to dense over {STEPS} gens; computed "
                f"{computed}/{tile_gens} tile-gens "
                f"({100 * (1 - computed / tile_gens):.0f}% skipped)",
            )
        )
    return CheckResult.from_findings("gated-equivalence", findings)


def check_mask_soundness_teeth(cfg: SparseConfig) -> CheckResult:
    """The deliberately-broken under-dilating step must diverge.

    Runs the single-device gated loop with ``dilate`` replaced by the
    identity (gate on the raw changed mask): the glider's leading edge
    writes into tiles the broken gate never activates, so the boards
    must diverge from the dense oracle within a few generations — the
    proof that the equivalence check above would actually catch an
    under-dilated implementation.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from gol_tpu.ops import stencil
    from gol_tpu.sparse import mask as sparse_mask

    findings: List[Finding] = []
    size = cfg.size
    board_np = _glider_board(size)
    # The broken fixture always gates at the default tile — the
    # soundness witness is about the missing dilation, not the config's
    # tile geometry.

    def broken_gen(carry):
        board, changed = carry
        active = changed  # BROKEN: no dilation — the light cone is cut
        cellmask = jnp.repeat(
            jnp.repeat(active, TILE, axis=0), TILE, axis=1
        )
        stepped = stencil.step(board)
        new = jnp.where(cellmask, stepped, board)
        return new, sparse_mask.changed_tiles_dense(board, new, TILE)

    @jax.jit
    def run_broken(board, changed):
        return lax.fori_loop(
            0, STEPS, lambda _, c: broken_gen(c), (board, changed)
        )

    # Start from the *true* one-generation changed mask (not all-ones —
    # all-ones would hide the missing dilation for a while).
    b1 = stencil.step(jnp.asarray(board_np))
    changed = sparse_mask.changed_tiles_dense(
        jnp.asarray(board_np), b1, TILE
    )
    broken, _ = run_broken(b1, changed)
    ref = np.asarray(stencil.run(jnp.array(b1, copy=True), STEPS))
    if np.array_equal(np.asarray(broken), ref):
        findings.append(
            Finding(
                ERROR,
                "mask-soundness",
                "the under-dilating broken fixture matched the dense "
                "oracle — the soundness property has no witness on this "
                "board; the equivalence check cannot be trusted to catch "
                "a missing dilation",
            )
        )
    else:
        findings.append(
            Finding(
                INFO,
                "mask-soundness",
                "under-dilated gating diverges from the dense oracle "
                f"within {STEPS} generations, as it must — the dilation "
                "invariant has teeth",
            )
        )
    return CheckResult.from_findings("mask-soundness", findings)


def run_sparse_config(cfg: SparseConfig) -> EngineReport:
    report = EngineReport(config_name=cfg.name)
    try:
        fn, specs, statics, mesh = _build(cfg)
        jaxpr = walker.trace_jaxpr(
            fn, *specs, *statics,
            static_argnums=tuple(
                range(len(specs), len(specs) + len(statics))
            ),
        )
    except Exception as e:
        from gol_tpu.analysis.report import FAIL

        report.checks.append(
            CheckResult("config", FAIL, [
                Finding(ERROR, "config", f"gated program failed to build: {e}")
            ])
        )
        return report
    report.checks.append(check_activity_purity(jaxpr, cfg))
    report.checks.append(check_dtype(jaxpr, cfg))
    report.checks.append(check_gated_equivalence(cfg, fn, statics, mesh))
    report.checks.append(check_mask_soundness_teeth(cfg))
    return report


def run_sparse_checks(
    matrix: Optional[List[SparseConfig]] = None,
) -> List[EngineReport]:
    return [run_sparse_config(c) for c in (matrix or default_sparse_matrix())]
