"""Static checks over the batched multi-world programs.

Two invariants the batch subsystem (gol_tpu/batch, docs/BATCHING.md)
lives or dies by, verified the same way the engine matrix is:

- **batch purity** — the batched chunk programs contain no host
  callbacks (the scan of :data:`gol_tpu.analysis.checks.
  IMPURE_PRIMITIVES`) and, crucially, **no collectives at all** — not
  even on the world-axis-sharded shard_map form.  Worlds are
  independent; a single psum/ppermute in a batched program means two
  worlds are coupled, which is the batched analog of the reference's
  bug B1 (wrong halos) in reverse.
- **batch invariance** — a batch of B distinct worlds stepped by the
  batched program is bit-identical per world to B sequential
  single-world runs of the existing engines.  Executed on small boards
  (CPU is enough — every tier is bit-exact across backends), covering
  the exact and the padded+masked program forms.

Run as part of ``python -m gol_tpu.analysis``; one
:class:`~gol_tpu.analysis.report.EngineReport` per batch configuration.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from gol_tpu.analysis import walker
from gol_tpu.analysis.checks import (
    COLLECTIVE_PRIMITIVES,
    IMPURE_PRIMITIVES,
    check_dtype,
)
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

STEPS = 4  # generations per traced/executed chunk


@dataclasses.dataclass(frozen=True)
class BatchConfig:
    """One cell of the batch verification matrix."""

    name: str
    engine: str  # dense / bitpack / pallas_bitpack
    masked: bool
    mesh: bool  # shard the world axis over a 4-device 'worlds' ring?
    batch: int = 4
    shape: Tuple[int, int] = (32, 64)  # bucket (padded) shape


def default_batch_matrix() -> List[BatchConfig]:
    return [
        BatchConfig("batch/dense/exact", "dense", False, False),
        BatchConfig("batch/dense/masked", "dense", True, False),
        BatchConfig("batch/bitpack/exact", "bitpack", False, False),
        BatchConfig("batch/bitpack/masked", "bitpack", True, False),
        BatchConfig(
            "batch/pallas_bitpack/exact", "pallas_bitpack", False, False
        ),
        BatchConfig("batch/dense/worlds-1d", "dense", False, True),
        BatchConfig("batch/bitpack/worlds-1d", "bitpack", False, True),
    ]


def _build(cfg: BatchConfig):
    """(jitted_fn, arg_specs) exactly as GolBatchRuntime dispatches them."""
    import jax

    from gol_tpu.batch import engines as batch_engines
    from gol_tpu.models.state import CELL_DTYPE

    mesh = None
    if cfg.mesh:
        devices = jax.devices()
        if len(devices) < 4:
            raise RuntimeError(
                f"config {cfg.name!r} needs 4 devices, have {len(devices)}"
            )
        mesh = batch_engines.make_batch_mesh(4, devices=devices[:4])
    fn = batch_engines.compiled_batch_evolver(
        cfg.engine, STEPS, cfg.masked, 512, mesh
    )
    B = cfg.batch
    H, W = cfg.shape
    if mesh is not None:
        stack_spec = jax.ShapeDtypeStruct(
            (B, H, W),
            CELL_DTYPE,
            sharding=batch_engines.batch_sharding(mesh),
        )
        vec_sharding = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(batch_engines.WORLDS)
        )
        vec_spec = jax.ShapeDtypeStruct((B,), np.int32, sharding=vec_sharding)
    else:
        stack_spec = jax.ShapeDtypeStruct((B, H, W), CELL_DTYPE)
        vec_spec = jax.ShapeDtypeStruct((B,), np.int32)
    specs = (stack_spec, vec_spec, vec_spec) if cfg.masked else (stack_spec,)
    return fn, specs, mesh


def check_batch_purity(jaxpr, cfg: BatchConfig) -> CheckResult:
    """No host callbacks AND no collectives — worlds must stay decoupled."""
    findings: List[Finding] = []
    for info in walker.iter_eqns(jaxpr):
        if info.name in IMPURE_PRIMITIVES:
            findings.append(
                Finding(
                    ERROR,
                    "batch-purity",
                    f"host-interaction primitive {info.name!r} in the "
                    f"batched program (path {'/'.join(info.path) or 'top'})",
                )
            )
        if info.name in COLLECTIVE_PRIMITIVES:
            findings.append(
                Finding(
                    ERROR,
                    "batch-purity",
                    f"collective {info.name!r} in a batched program — "
                    "worlds are independent; any collective couples them "
                    "(the world-axis shard_map must be embarrassingly "
                    "parallel)",
                )
            )
    if not findings:
        findings.append(
            Finding(
                INFO,
                "batch-purity",
                "batched program traced pure: no callbacks, no collectives",
            )
        )
    return CheckResult.from_findings("batch-purity", findings)


def _reference(engine: str, board, steps: int):
    """The single-world program the batched tier must match bit-for-bit."""
    from gol_tpu.ops import bitlife, stencil

    if engine == "dense":
        return stencil.run(board, steps)
    if engine == "bitpack":
        return bitlife.evolve_dense_io(board, steps)
    from gol_tpu.ops import pallas_bitlife

    return pallas_bitlife.evolve(board, steps, 512)


def check_batch_invariance(cfg: BatchConfig, fn, mesh) -> CheckResult:
    """B distinct worlds, batched == B sequential single-world runs."""
    import jax
    import jax.numpy as jnp

    from gol_tpu.batch import engines as batch_engines

    findings: List[Finding] = []
    H, W = cfg.shape
    rng = np.random.default_rng(2026)
    shapes = []
    for k in range(cfg.batch):
        if cfg.masked and k % 2:
            # Mixed-size members: every second world smaller than the
            # bucket (word-aligned widths so the packed tier applies).
            shapes.append((H - 8, W - 32))
        else:
            shapes.append((H, W))
    worlds = [
        (rng.random(s) < 0.33).astype(np.uint8) for s in shapes
    ]
    stack = np.zeros((cfg.batch, H, W), np.uint8)
    for k, wld in enumerate(worlds):
        stack[k, : wld.shape[0], : wld.shape[1]] = wld
    hs = np.asarray([s[0] for s in shapes], np.int32)
    ws = np.asarray([s[1] for s in shapes], np.int32)
    if mesh is not None:
        sharding = batch_engines.batch_sharding(mesh)
        dev_stack = jax.device_put(stack, sharding)
        vec = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(batch_engines.WORLDS)
        )
        args = (dev_stack, jax.device_put(hs, vec), jax.device_put(ws, vec))
    else:
        args = (jnp.asarray(stack), jnp.asarray(hs), jnp.asarray(ws))
    out = np.asarray(fn(*args[: 3 if cfg.masked else 1]))
    bad = []
    for k, wld in enumerate(worlds):
        ref = np.asarray(_reference(cfg.engine, jnp.asarray(wld), STEPS))
        got = out[k, : wld.shape[0], : wld.shape[1]]
        if not np.array_equal(got, ref):
            bad.append(k)
        pad = out[k].copy()
        pad[: wld.shape[0], : wld.shape[1]] = 0
        if pad.any():
            bad.append(k)
    if bad:
        findings.append(
            Finding(
                ERROR,
                "batch-invariance",
                f"worlds {sorted(set(bad))} diverge from their sequential "
                f"single-world runs (or leak live cells into padding) "
                f"after {STEPS} generations — the batched program is not "
                "a pure stacking of the single-world engines",
            )
        )
    else:
        findings.append(
            Finding(
                INFO,
                "batch-invariance",
                f"{cfg.batch} worlds bit-equal to sequential runs "
                f"({STEPS} gens, shapes {sorted(set(shapes))})",
            )
        )
    return CheckResult.from_findings("batch-invariance", findings)


def run_batch_config(cfg: BatchConfig) -> EngineReport:
    report = EngineReport(config_name=cfg.name)
    try:
        fn, specs, mesh = _build(cfg)
        jaxpr = walker.trace_jaxpr(fn, *specs)
    except Exception as e:
        from gol_tpu.analysis.report import FAIL

        report.checks.append(
            CheckResult("config", FAIL, [
                Finding(ERROR, "config", f"batched program failed to build: {e}")
            ])
        )
        return report
    report.checks.append(check_batch_purity(jaxpr, cfg))
    # Dtype hygiene: the batched tiers inherit the engines' integer-only
    # contract (the checker keys on cfg.engine, which matches).
    report.checks.append(check_dtype(jaxpr, cfg))
    report.checks.append(check_batch_invariance(cfg, fn, mesh))
    return report


def run_batch_checks(
    matrix: Optional[List[BatchConfig]] = None,
) -> List[EngineReport]:
    return [run_batch_config(c) for c in (matrix or default_batch_matrix())]
