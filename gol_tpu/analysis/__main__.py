"""``python -m gol_tpu.analysis`` — the static verification pass.

Traces every engine×mesh configuration in the matrix on abstract inputs
(CPU is enough; no board is ever evolved) and verifies the framework
invariants: ring-permutation comm contracts, integer-only dtypes, no
host callbacks, live buffer donation, cost-model drift, and
trace-cache stability across chunk schedules.  Exits non-zero on any
violated invariant — the correctness gate for perf PRs.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Optional, Sequence


def _ensure_cpu_devices(min_devices: int) -> None:
    """Give the verifier a virtual device ring when run on a bare host.

    Mesh configs need ``min_devices`` devices; on CPU the standard
    ``--xla_force_host_platform_device_count`` flag provides them.  Must
    run before the first backend touch (the flag is read at backend
    init); the site may have pre-imported jax, which is fine as long as
    no computation has happened yet.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={min_devices}"
        ).strip()


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m gol_tpu.analysis",
        description="statically verify engine invariants (no TPU needed)",
    )
    parser.add_argument(
        "--engine",
        action="append",
        choices=["dense", "bitpack", "pallas", "pallas_bitpack"],
        help="restrict to these engines (repeatable; default: all)",
    )
    parser.add_argument(
        "--mesh",
        action="append",
        choices=["none", "1d", "2d"],
        help="restrict to these mesh modes (repeatable; default: all)",
    )
    parser.add_argument(
        "--json", action="store_true", help="emit the report as JSON"
    )
    parser.add_argument(
        "--verbose", "-v", action="store_true",
        help="show info findings, not just violations",
    )
    parser.add_argument(
        "--list", action="store_true", help="list matrix entries and exit"
    )
    parser.add_argument(
        "--native-devices",
        action="store_true",
        help="use the ambient backend/devices as-is (default: force the "
        "CPU backend with a virtual 4-device ring)",
    )
    parser.add_argument(
        "--concurrency",
        action="store_true",
        help="run only the host-plane concurrency passes (lockcheck + "
        "spmdcheck); pure-AST, never touches a jax backend",
    )
    ns = parser.parse_args(list(sys.argv[1:] if argv is None else argv))

    if ns.concurrency:
        from gol_tpu.analysis.lockcheck import (
            default_lock_matrix, run_lock_checks,
        )
        from gol_tpu.analysis.report import AnalysisReport
        from gol_tpu.analysis.spmdcheck import run_spmd_checks

        if ns.list:
            for cell in default_lock_matrix():
                print(cell.name)
            print("lock/teeth")
            print("lock/waivers")
            print("spmd/collectives")
            print("spmd/teeth")
            print("spmd/waivers")
            return 0
        report = AnalysisReport()
        report.engines.extend(run_lock_checks())
        report.engines.extend(run_spmd_checks())
        if ns.json:
            print(report.to_json())
        else:
            print(report.render_text(verbose=ns.verbose))
        return report.exit_code

    if not ns.native_devices:
        from gol_tpu.analysis.configs import MESH_DEVICE_COUNTS

        _ensure_cpu_devices(max(MESH_DEVICE_COUNTS.values()))
        import jax

        jax.config.update("jax_platforms", "cpu")

    from gol_tpu.analysis.configs import default_matrix, select
    from gol_tpu.analysis.report import AnalysisReport

    matrix = select(default_matrix(), ns.engine, ns.mesh)
    # The batched multi-world matrix (gol_tpu/batch) and the activity
    # matrix (gol_tpu/sparse) ride the full run only — engine/mesh
    # filters select single-world engine cells.
    extras_on = not ns.engine and not ns.mesh
    if ns.list:
        for cfg in matrix:
            print(cfg.name)
        if extras_on:
            from gol_tpu.analysis.batchcheck import default_batch_matrix
            from gol_tpu.analysis.guardcheck import default_guard_matrix
            from gol_tpu.analysis.halocheck import default_halo_matrix
            from gol_tpu.analysis.redistcheck import default_redist_matrix
            from gol_tpu.analysis.reshardcheck import default_reshard_matrix
            from gol_tpu.analysis.sparsecheck import default_sparse_matrix

            for bcfg in default_batch_matrix():
                print(bcfg.name)
            for scfg in default_sparse_matrix():
                print(scfg.name)
            for rcfg in default_reshard_matrix():
                print(rcfg.name)
            for dcfg in default_redist_matrix():
                print(dcfg.name)
            print("redist-worlds-stack")
            for hcfg in default_halo_matrix():
                print(hcfg.name)
            from gol_tpu.analysis.ooccheck import default_ooc_matrix

            for ocfg in default_ooc_matrix():
                print(ocfg.name)
            for gcfg in default_guard_matrix():
                print(gcfg.name)
            from gol_tpu.analysis.lockcheck import default_lock_matrix

            for lcfg in default_lock_matrix():
                print(lcfg.name)
            print("lock/teeth")
            print("lock/waivers")
            print("spmd/collectives")
            print("spmd/teeth")
            print("spmd/waivers")
        return 0

    from gol_tpu.analysis.checks import run_config

    report = AnalysisReport()
    for cfg in matrix:
        report.engines.append(run_config(cfg))
    if extras_on:
        from gol_tpu.analysis.batchcheck import run_batch_checks
        from gol_tpu.analysis.guardcheck import run_guard_checks
        from gol_tpu.analysis.halocheck import run_halo_checks
        from gol_tpu.analysis.redistcheck import run_redist_checks
        from gol_tpu.analysis.reshardcheck import run_reshard_checks
        from gol_tpu.analysis.sparsecheck import run_sparse_checks

        report.engines.extend(run_batch_checks())
        report.engines.extend(run_sparse_checks())
        report.engines.extend(run_reshard_checks())
        report.engines.extend(run_redist_checks())
        report.engines.extend(run_halo_checks())
        from gol_tpu.analysis.ooccheck import run_ooc_checks

        report.engines.extend(run_ooc_checks())
        report.engines.extend(run_guard_checks())
        from gol_tpu.analysis.lockcheck import run_lock_checks
        from gol_tpu.analysis.spmdcheck import run_spmd_checks

        report.engines.extend(run_lock_checks())
        report.engines.extend(run_spmd_checks())

    if ns.json:
        print(report.to_json())
    else:
        print(report.render_text(verbose=ns.verbose))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
