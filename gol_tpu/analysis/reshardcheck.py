"""Static checks over the elastic-mesh reshard planner (docs/RESILIENCE.md).

The reshard matrix — one report per (src layout → dst layout) pair over
grow and shrink directions — proves the two invariants cross-topology
resume lives or dies by, with the verifier's broken-fixture discipline:

- **plan soundness** — for every topology pair, the move table
  :func:`gol_tpu.resilience.reshard.plan_reshard` builds covers every
  destination cell **exactly once** (validated), and executing it
  against the packed piece store reproduces a random board bit-exactly,
  including destination seams that cut source pieces mid-word (the
  shift-repack path).
- **validator teeth** — the reason the soundness check can be trusted:
  deliberately broken plans — one with an *overlapping* move (a cell
  written twice), one with a *gapped* move (a cell written never), one
  whose move leaks outside its claimed source piece — must each FAIL
  :func:`~gol_tpu.resilience.reshard.validate_plan`.  A broken fixture
  that validates means the exactly-once property has lost its witness,
  and the check errors.

Pure host-side geometry + numpy — no tracing, no devices — so the
matrix runs anywhere the verifier does.
"""

from __future__ import annotations

import dataclasses
from typing import List, Tuple

import numpy as np

from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)
from gol_tpu.resilience import reshard as rs

# Board sized so every layout below tiles it AND the 2-D column seams
# land sub-word (96 = 3 words of 32; a 3-col split cuts at bit 32 and
# 64 — word-aligned — while the 96/2=48 split cuts mid-word).
SHAPE = (48, 96)


@dataclasses.dataclass(frozen=True)
class ReshardConfig:
    """One src→dst cell of the reshard matrix."""

    name: str
    src: rs.MeshLayout
    dst: rs.MeshLayout


def default_reshard_matrix() -> List[ReshardConfig]:
    """Grow and shrink pairs over none/1d/2d, seam-cutting included."""
    layouts = {
        "none": rs.MeshLayout("none"),
        "1d2": rs.MeshLayout("1d", rows=2),
        "1d4": rs.MeshLayout("1d", rows=4),
        "2d2x2": rs.MeshLayout("2d", rows=2, cols=2),
        "2d4x2": rs.MeshLayout("2d", rows=4, cols=2),
        "2d2x3": rs.MeshLayout("2d", rows=2, cols=3),
    }
    pairs: List[Tuple[str, str]] = [
        ("none", "1d4"),
        ("none", "2d2x2"),
        ("1d4", "none"),     # shrink to one device
        ("1d2", "1d4"),      # grow the ring
        ("1d4", "1d2"),      # shrink the ring
        ("1d4", "2d2x3"),    # ring -> blocks, mid-word column seams
        ("2d2x2", "1d4"),    # blocks -> ring
        ("2d2x3", "2d2x2"),  # both splits mid-word somewhere
        ("2d4x2", "2d2x3"),
    ]
    return [
        ReshardConfig(
            name=f"reshard-{s}-to-{d}", src=layouts[s], dst=layouts[d]
        )
        for s, d in pairs
    ]


def _check_soundness(cfg: ReshardConfig) -> CheckResult:
    """Plan validates + executing it reproduces the board bit-exactly."""
    findings: List[Finding] = []
    src_boxes = cfg.src.boxes(SHAPE)
    try:
        plan = rs.plan_reshard(SHAPE, src_boxes, cfg.src, cfg.dst)
    except rs.ReshardError as e:
        findings.append(
            Finding(ERROR, "reshard-plan", f"planning failed: {e}")
        )
        return CheckResult.from_findings("reshard-plan", findings)
    rng = np.random.default_rng(hash(cfg.name) % (2**32))
    board = (rng.random(SHAPE) < 0.5).astype(np.uint8)
    store = rs.PackedStore()
    for b in src_boxes:
        store.put(b, board[b[0] : b[1], b[2] : b[3]])
    for dbox, _ in plan.moves:
        got = store.region(dbox)
        want = board[dbox[0] : dbox[1], dbox[2] : dbox[3]]
        if not np.array_equal(got, want):
            findings.append(
                Finding(
                    ERROR,
                    "reshard-plan",
                    f"dst shard {dbox} assembled wrong cells from the "
                    "packed store",
                )
            )
    summ = plan.summary()
    findings.append(
        Finding(
            INFO,
            "reshard-plan",
            f"{summ['moves']} moves, {summ['seam_splits']} sub-word seam "
            f"splits, {summ['bytes_moved']} packed bytes",
        )
    )
    return CheckResult.from_findings("reshard-plan", findings)


def _broken_plans(plan: rs.ReshardPlan):
    """(label, broken plan) fixtures validate_plan MUST reject."""
    dbox, srcs = plan.moves[-1]
    overlapping = dataclasses.replace(
        plan, moves=plan.moves[:-1] + ((dbox, srcs + (srcs[0],)),)
    )
    gapped = dataclasses.replace(
        plan, moves=plan.moves[:-1] + ((dbox, srcs[:-1]),)
    )
    sbox, inter = srcs[0]
    # A move whose intersection reaches one row past its claimed source
    # piece: total measure is untouched, so only the src-containment
    # check can catch it.
    leak_box = (sbox[0], inter[1] - 1, sbox[2], sbox[3])
    leaking = dataclasses.replace(
        plan,
        moves=plan.moves[:-1] + ((dbox, ((leak_box, inter),) + srcs[1:]),),
    )
    return [
        ("overlapping move", overlapping),
        ("gapped move", gapped),
        ("src-leaking move", leaking),
    ]


def _check_teeth(cfg: ReshardConfig) -> CheckResult:
    """Each broken-plan fixture must fail validation."""
    findings: List[Finding] = []
    plan = rs.plan_reshard(SHAPE, cfg.src.boxes(SHAPE), cfg.src, cfg.dst)
    if not plan.moves or not plan.moves[-1][1]:
        return CheckResult.skipped(
            "reshard-teeth", "plan has no moves to break"
        )
    for label, bad in _broken_plans(plan):
        try:
            rs.validate_plan(bad)
        except rs.ReshardPlanError as e:
            findings.append(
                Finding(INFO, "reshard-teeth", f"{label} rejected: {e}")
            )
        else:
            findings.append(
                Finding(
                    ERROR,
                    "reshard-teeth",
                    f"broken fixture ({label}) VALIDATED — the "
                    "exactly-once property has no witness",
                )
            )
    return CheckResult.from_findings("reshard-teeth", findings)


def run_reshard_checks() -> List[EngineReport]:
    """One :class:`EngineReport` per src→dst pair of the matrix."""
    reports = []
    for cfg in default_reshard_matrix():
        rep = EngineReport(config_name=cfg.name)
        rep.checks.append(_check_soundness(cfg))
        rep.checks.append(_check_teeth(cfg))
        reports.append(rep)
    return reports
