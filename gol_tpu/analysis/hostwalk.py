"""Shared AST infrastructure for the host-plane concurrency passes.

The engine verifiers (`checks.py`, `batchcheck.py`, ...) prove facts
about *traced jax programs*; the concurrency passes (`lockcheck.py`,
`spmdcheck.py`) prove facts about the *host code that drives them* —
HTTP handler threads, the scheduler drive loop, the async checkpoint
writer, the metrics observer, and the multi-host collective schedule.
Nothing here executes analyzed code: modules are parsed with
:mod:`ast`, never imported, so deliberately-broken fixtures are safe
to analyze.

This module is the shared substrate both passes walk on:

- :class:`Program` — a set of parsed modules with indexes over
  functions (including nested defs, keyed ``mod:Class.method`` /
  ``mod:outer.inner``), classes, per-module import aliases, and
  module-level lock objects.
- attribute/type inference — a deliberately small abstract domain
  (class basenames plus one container level) fed by ``self.x =
  ClassName(...)`` constructor assignments and ``x: ClassName``
  annotations.  Precision here is a *soundness dial*: an access whose
  receiver type cannot be inferred is simply not recorded, so the
  guarded-field check under-reports rather than false-positives.
- lock identity — ``with self._lock:`` in a method of ``C`` canonical-
  izes to ``C._lock``; a module-level ``with _lock:`` to ``mod._lock``;
  lock *kind* (reentrant or not) rides along so self-acquisition of a
  plain ``Lock`` is distinguishable from RLock reentrancy.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

# Constructors that make a field a synchronization primitive: accessing
# the *object* (to .set()/.wait()/.put()) is inherently thread-safe, so
# such fields are exempt from the guarded-field discipline.
SYNC_CTORS = {
    "Event", "Queue", "SimpleQueue", "Semaphore", "BoundedSemaphore",
    "Barrier",
}
# Lock constructors and their reentrancy.  threading.Condition wraps an
# RLock by default, so nested acquisition of the same condition is
# reentrant, not a self-deadlock.
LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "rlock"}

# Container heads whose single known-class type parameter is the
# element type (``Dict[str, RequestState]`` → values are RequestState).
_CONTAINER_HEADS = {"Dict", "dict", "List", "list", "Deque", "deque",
                    "Set", "set", "Tuple", "tuple"}
# Methods on an inferred container attribute that yield its element.
_CONTAINER_ELT_METHODS = {"get", "pop", "popleft"}
# Method calls that mutate the receiver collection in place — a call
# site ``self._requests.clear()`` is a *write* to the field even though
# the attribute node itself is a Load.
MUTATING_METHODS = {
    "append", "appendleft", "add", "clear", "extend", "insert",
    "pop", "popitem", "popleft", "remove", "discard", "update",
    "setdefault", "sort", "reverse",
}


@dataclasses.dataclass
class FuncInfo:
    """One function/method/nested def, keyed for suffix lookup."""

    key: str  # "mod:func" | "mod:Class.method" | "mod:outer.inner"
    mod: str
    cls: Optional[str]  # defining class basename, if a method
    node: ast.AST  # FunctionDef / AsyncFunctionDef
    is_property: bool = False


@dataclasses.dataclass
class ClassInfo:
    name: str
    mod: str
    node: ast.ClassDef
    bases: List[str]
    # attr -> ("plain"|"ctr", class basename) from ctor assigns / annots
    attr_types: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )
    # attr -> "lock" | "rlock" | "sync"
    attr_kinds: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ThreadSite:
    """A ``threading.Thread(target=...)`` construction site."""

    func: Optional[FuncInfo]  # enclosing function (None = module level)
    call: ast.Call
    mod: str
    path: str
    lineno: int


class Program:
    """A parsed, indexed multi-module host program."""

    def __init__(self) -> None:
        self.modules: Dict[str, ast.Module] = {}
        self.paths: Dict[str, str] = {}
        self.functions: Dict[str, FuncInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        # (mod, name) -> "lock"|"rlock" for module-level lock objects
        self.module_locks: Dict[Tuple[str, str], str] = {}
        # mod -> alias -> target module short name
        self.imports: Dict[str, Dict[str, str]] = {}
        self.thread_sites: List[ThreadSite] = []

    # -- construction --------------------------------------------------------
    @classmethod
    def load(cls, modules: Sequence[Tuple[str, str]]) -> "Program":
        """``modules`` is a list of (short module name, file path)."""
        prog = cls()
        for mod, path in modules:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            prog.modules[mod] = tree
            prog.paths[mod] = path
            prog._index_module(mod, tree)
        return prog

    def _index_module(self, mod: str, tree: ast.Module) -> None:
        aliases: Dict[str, str] = {}
        # Imports anywhere in the module — this codebase deliberately
        # defers many imports into function bodies (backend-init
        # ordering), and a lock edge must not vanish because the
        # importing line lives inside the function that uses it.
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name.rsplit(".", 1)[-1]
                    )
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    aliases[a.asname or a.name] = a.name
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                kind = _ctor_kind(node.value)
                if isinstance(t, ast.Name) and kind in ("lock", "rlock"):
                    self.module_locks[(mod, t.id)] = kind
            elif isinstance(node, ast.ClassDef):
                self._index_class(mod, node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(mod, None, node, node.name)
        self.imports[mod] = aliases
        self._scan_thread_sites(mod, tree)

    def _index_class(self, mod: str, node: ast.ClassDef) -> None:
        bases = [_tail_name(b) for b in node.bases]
        info = ClassInfo(node.name, mod, node, [b for b in bases if b])
        self.classes.setdefault(node.name, info)
        for item in node.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                item.target, ast.Name
            ):
                ann = _annotation_type(item.annotation)
                if ann is not None:
                    info.attr_types[item.target.id] = ann
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_func(mod, node.name, item, item.name)
                self._scan_self_assigns(info, item)

    def _index_func(
        self, mod: str, cls: Optional[str], node, qual: str
    ) -> None:
        is_prop = any(
            isinstance(d, ast.Name) and d.id == "property"
            for d in node.decorator_list
        )
        key = f"{mod}:{cls}.{qual}" if cls else f"{mod}:{qual}"
        self.functions[key] = FuncInfo(key, mod, cls, node, is_prop)
        for child in ast.walk(node):
            if (
                isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                and child is not node
            ):
                nkey = f"{key}.{child.name}"
                self.functions[nkey] = FuncInfo(nkey, mod, cls, child)

    def _scan_self_assigns(self, info: ClassInfo, method) -> None:
        """``self.a = <ctor>`` anywhere in a method types the attr."""
        for node in ast.walk(method):
            tgt = val = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt = node.target
                ann = _annotation_type(node.annotation)
                if (
                    _is_self_attr(tgt)
                    and ann is not None
                    and tgt.attr not in info.attr_types
                ):
                    info.attr_types[tgt.attr] = ann
                continue
            if not _is_self_attr(tgt) or val is None:
                continue
            kind = _ctor_kind(val)
            if kind is not None:
                info.attr_kinds.setdefault(tgt.attr, kind)
            else:
                cname = _ctor_class(val)
                if cname is not None:
                    info.attr_types.setdefault(tgt.attr, ("plain", cname))

    def _scan_thread_sites(self, mod: str, tree: ast.Module) -> None:
        # Map every Call node back to its innermost enclosing function
        # so a thread target like ``self._loop`` can be resolved with
        # the right class context later.
        encl: Dict[int, Optional[FuncInfo]] = {}
        for fi in self.functions.values():
            if fi.mod != mod:
                continue
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    encl[id(node)] = fi
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            named_thread = (
                isinstance(fn, ast.Attribute) and fn.attr == "Thread"
            ) or (isinstance(fn, ast.Name) and fn.id == "Thread")
            if named_thread:
                self.thread_sites.append(
                    ThreadSite(
                        encl.get(id(node)), node, mod,
                        self.paths[mod], node.lineno,
                    )
                )

    # -- lookup --------------------------------------------------------------
    def find(self, suffix: str) -> Optional[FuncInfo]:
        """Resolve a config suffix like ``ServeScheduler.run_once`` or
        ``serve.server:_Handler.do_GET`` to the unique matching key."""
        hits = [
            fi for key, fi in self.functions.items()
            if key == suffix
            or key.endswith(":" + suffix)
            or key.endswith("." + suffix)
        ]
        if len(hits) == 1:
            return hits[0]
        # Prefer an exact tail after ':' over nested-def collisions.
        exact = [h for h in hits if h.key.split(":", 1)[-1] == suffix]
        return exact[0] if len(exact) == 1 else None

    def method(self, cls: str, name: str) -> Optional[FuncInfo]:
        info = self.classes.get(cls)
        if info is None:
            return None
        fi = self.functions.get(f"{info.mod}:{cls}.{name}")
        if fi is not None:
            return fi
        for base in info.bases:  # one level of inheritance is enough
            binfo = self.classes.get(base)
            if binfo is not None:
                fi = self.functions.get(f"{binfo.mod}:{base}.{name}")
                if fi is not None:
                    return fi
        return None


# -- small AST helpers -------------------------------------------------------
def _is_self_attr(node) -> bool:
    return (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    )


def _tail_name(node) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _ctor_kind(value) -> Optional[str]:
    """'lock'/'rlock'/'sync' when ``value`` constructs a primitive.

    Sees through ``lockwatch.maybe_wrap("name", threading.RLock())`` —
    the runtime recorder must not hide the lock from the static pass.
    """
    if not isinstance(value, ast.Call):
        return None
    name = _tail_name(value.func)
    if name == "maybe_wrap" and len(value.args) == 2:
        return _ctor_kind(value.args[1])
    if name in LOCK_CTORS:
        return LOCK_CTORS[name]
    if name in SYNC_CTORS:
        return "sync"
    return None


def _ctor_class(value) -> Optional[str]:
    """Class basename when ``value`` looks like ``ClassName(...)``."""
    if isinstance(value, ast.Call):
        name = _tail_name(value.func)
        if name and name[0].isupper():
            return name
    return None


def _annotation_type(ann) -> Optional[Tuple[str, str]]:
    """('plain'|'ctr', ClassName) from an annotation expression.

    ``Dict[str, RequestState]`` → ('ctr', 'RequestState');
    ``Optional[RequestState]`` → ('plain', 'RequestState').
    Unknown shapes → None (the access is simply not typed).
    """
    head = None
    if isinstance(ann, ast.Subscript):
        head = _tail_name(ann.value)
    names = [
        n.id if isinstance(n, ast.Name) else n.attr
        for n in ast.walk(ann)
        if isinstance(n, (ast.Name, ast.Attribute))
    ]
    classish = [
        n for n in names
        if n and n[0].isupper() and n not in _CONTAINER_HEADS
        and n != "Optional"
    ]
    if not classish:
        return None
    kind = "ctr" if head in _CONTAINER_HEADS else "plain"
    return (kind, classish[-1])


# -- type inference ----------------------------------------------------------
@dataclasses.dataclass
class Env:
    """Per-function inference context for one walk."""

    prog: Program
    func: FuncInfo
    # local name -> ("plain"|"ctr", ClassName)
    locals: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict
    )
    # "Class.method" or bare module-function name -> ClassName returned
    # (reviewed modeling table)
    returns: Dict[str, str] = dataclasses.field(default_factory=dict)

    @property
    def mod(self) -> str:
        return self.func.mod

    @property
    def cls(self) -> Optional[str]:
        return self.func.cls


def infer(expr, env: Env) -> Optional[Tuple[str, str]]:
    """Abstract type of ``expr``: ('plain'|'ctr', ClassName) or None."""
    prog = env.prog
    if isinstance(expr, ast.Name):
        if expr.id == "self" and env.cls:
            return ("plain", env.cls)
        return env.locals.get(expr.id)
    if isinstance(expr, ast.Attribute):
        base = infer(expr.value, env)
        if base is not None and base[0] == "plain":
            cinfo = prog.classes.get(base[1])
            if cinfo is not None:
                t = cinfo.attr_types.get(expr.attr)
                if t is not None:
                    return t
        return None
    if isinstance(expr, ast.Subscript):
        base = infer(expr.value, env)
        if base is not None and base[0] == "ctr":
            return ("plain", base[1])
        return None
    if isinstance(expr, ast.Call):
        fn = expr.func
        # list(X) / sorted(X) wrappers keep the element type.
        if isinstance(fn, ast.Name) and fn.id in ("list", "sorted"):
            if expr.args:
                return infer(expr.args[0], env)
            return None
        name = _tail_name(fn)
        if name in prog.classes:
            return ("plain", name)
        if isinstance(fn, ast.Name):
            # Module-function accessor from the modeling table, e.g.
            # blackbox.recorder() -> FlightRecorder.
            ret = env.returns.get(fn.id)
            if ret is not None:
                return ("plain", ret)
        if isinstance(fn, ast.Attribute):
            recv = infer(fn.value, env)
            if recv is None:
                # Same accessor reached through a module alias
                # (blackbox.recorder() from serve.server).
                ret = env.returns.get(name)
                if ret is not None:
                    return ("plain", ret)
            if recv is not None:
                if recv[0] == "ctr" and name in _CONTAINER_ELT_METHODS:
                    return ("plain", recv[1])
                if recv[0] == "ctr" and name == "values":
                    return ("ctr", recv[1])
                if recv[0] == "plain":
                    ret = env.returns.get(f"{recv[1]}.{name}")
                    if ret is not None:
                        return ("plain", ret)
    return None


def iter_elt(expr, env: Env) -> Optional[Tuple[str, str]]:
    """Type of the loop variable in ``for x in <expr>``."""
    t = infer(expr, env)
    if t is not None and t[0] == "ctr":
        return ("plain", t[1])
    return None


# -- lock identity -----------------------------------------------------------
def lock_id(expr, env: Env) -> Optional[Tuple[str, str]]:
    """(canonical id, 'lock'|'rlock') when ``expr`` names a known lock.

    ``self._lock`` in a method of C → ``C._lock``; a module-global
    ``_lock`` → ``mod._lock``; ``degrade_mod._lock`` resolves through
    the importing module's aliases.
    """
    prog = env.prog
    if isinstance(expr, ast.Name):
        k = prog.module_locks.get((env.mod, expr.id))
        if k is not None:
            return (f"{env.mod.rsplit('.', 1)[-1]}.{expr.id}", k)
        t = env.locals.get(expr.id)
        if t is not None and t[0] == "plain":
            # A local bound to a lock-typed object (rare; fixtures).
            cinfo = prog.classes.get(t[1])
            if cinfo is None:
                return None
        return None
    if isinstance(expr, ast.Attribute):
        # module-alias attribute: faults_mod._lock
        if isinstance(expr.value, ast.Name):
            alias = expr.value.id
            target = prog.imports.get(env.mod, {}).get(alias)
            if target is not None:
                for (m, n), k in prog.module_locks.items():
                    if n == expr.attr and (
                        m == target or m.rsplit(".", 1)[-1] == target
                    ):
                        return (f"{m.rsplit('.', 1)[-1]}.{n}", k)
        base = infer(expr.value, env)
        if base is not None and base[0] == "plain":
            cinfo = prog.classes.get(base[1])
            if cinfo is not None:
                k = cinfo.attr_kinds.get(expr.attr)
                if k in ("lock", "rlock"):
                    return (f"{base[1]}.{expr.attr}", k)
    return None


def module_short(mod: str) -> str:
    return mod.rsplit(".", 1)[-1]
