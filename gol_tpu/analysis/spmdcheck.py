"""spmdcheck — rank-symmetry verifier for host collectives.

Every process of a multi-host run executes the same host program; the
collectives it issues (`multihost.allgather_host_ints`, the
`sync_global_devices` save/row barriers, `fetch_global`'s replication
gather) are rendezvous points ALL ranks must reach in the same order.
A collective dominated by a branch only some ranks take — the classic
``if jax.process_index() == 0:`` mistake — deadlocks the job: rank 0
waits in the collective, everyone else is already past it (or vice
versa).  "Persistent and Partitioned MPI for Stencil Communication"
(PAPERS.md) frames the same fact at the MPI layer: the communication
*schedule*, not just the payload, is the correctness surface.

The pass is a whole-package AST scan (nothing is imported):

- **taint** — ``jax.process_index()`` results, names assigned from
  them, and ``.is_coordinator`` reads are *rank-divergent*.
  ``jax.process_count()`` and collective results are uniform by
  construction (every rank computes the same value), so the pervasive
  ``if jax.process_count() == 1: return`` short-circuits stay green.
- **sites** — every call to a collective (directly, or through a
  function this package defines that transitively issues one) is
  enumerated as INFO; a site inside a rank-tainted branch, or after a
  rank-tainted early return in the same function, is an ERROR.
- **waivers** — same committed allowlist as lockcheck
  (``concurrency_waivers.json``, section ``spmdcheck``), keyed by
  ``file:function``; stale entries are errors.

TEETH: ``tests/data/concurrency_fixtures/broken_rank_gated_collective
.py`` MUST produce a divergence ERROR on every run.
"""

from __future__ import annotations

import ast
import glob
import os
from typing import Dict, List, Optional, Set, Tuple

from gol_tpu.analysis.lockcheck import (
    FIXTURE_DIR,
    load_waivers,
)
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)

# The rendezvous primitives of this codebase's host plane.
COLLECTIVES = {
    "allgather_host_ints",
    "fetch_global",
    "sync_global_devices",
    "process_allgather",
    "broadcast_one_to_all",
}
# Calls that *produce* a rank-divergent value.
_TAINT_CALLS = {"process_index"}
_TAINT_ATTRS = {"is_coordinator"}
# Uniform by construction — never taint, even though they mention jax.
_UNIFORM_CALLS = {"process_count", "device_count", "local_device_count"}


def _package_files() -> List[Tuple[str, str]]:
    out = []
    for path in sorted(
        glob.glob(os.path.join(_PKG_DIR, "**", "*.py"), recursive=True)
    ):
        rel = os.path.relpath(path, _PKG_DIR)
        if rel.startswith(("analysis" + os.sep,)):
            continue  # the analyzers themselves name collectives in data
        mod = rel[:-3].replace(os.sep, ".").replace(".__init__", "")
        out.append((mod, path))
    return out


def _rel(path: str) -> str:
    try:
        return os.path.relpath(path, _REPO_ROOT)
    except ValueError:
        return path


class _FnScan(ast.NodeVisitor):
    """Per-function scan: collective sites + their divergence state."""

    def __init__(self, summaries: Set[str]) -> None:
        self.summaries = summaries  # local fn names that issue collectives
        self.tainted: Set[str] = set()
        # (lineno, callee name, divergence reason or None)
        self.sites: List[Tuple[int, str, Optional[str]]] = []
        self.calls: Set[str] = set()
        self._div_depth = 0  # inside a rank-tainted branch
        self._div_after: Optional[str] = None  # past a tainted early return

    # .. taint ..............................................................
    def _expr_tainted(self, e) -> bool:
        for node in ast.walk(e):
            if isinstance(node, ast.Name) and node.id in self.tainted:
                return True
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _TAINT_ATTRS
            ):
                return True
            if isinstance(node, ast.Call):
                name = _callee_name(node)
                if name in _TAINT_CALLS:
                    return True
        return False

    # .. statements .........................................................
    def visit_Assign(self, node) -> None:
        if self._expr_tainted(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.tainted.add(t.id)
        self._scan_calls(node.value)

    def scan_suite(self, stmts) -> None:
        """Walk one statement list with suite-scoped divergence.

        A rank-tainted If whose arm escapes (return/raise/...) makes
        only the *rest of this suite* divergent: if the suite itself
        sits inside e.g. ``if sharding is None:`` where every path
        returns, code after the enclosing block never runs under
        divergence and must stay green (write_host_dumps' shape).
        """
        saved = self._div_after
        for st in stmts:
            self.visit(st)
            if (
                self._div_after is None
                and isinstance(st, ast.If)
                and self._expr_tainted(st.test)
                and _branch_escapes(st)
            ):
                self._div_after = (
                    f"follows a rank-conditional early return at line "
                    f"{st.lineno}"
                )
        self._div_after = saved

    def visit_If(self, node) -> None:
        self._scan_calls(node.test)
        tainted = self._expr_tainted(node.test)
        if tainted:
            self._div_depth += 1
        self.scan_suite(node.body)
        self.scan_suite(node.orelse)
        if tainted:
            self._div_depth -= 1

    def visit_While(self, node) -> None:
        self._scan_calls(node.test)
        tainted = self._expr_tainted(node.test)
        if tainted:
            self._div_depth += 1
        self.scan_suite(node.body)
        self.scan_suite(node.orelse)
        if tainted:
            self._div_depth -= 1

    def visit_For(self, node) -> None:
        self._scan_calls(node.iter)
        self.scan_suite(node.body)
        self.scan_suite(node.orelse)

    def visit_With(self, node) -> None:
        for item in node.items:
            self._scan_calls(item.context_expr)
        self.scan_suite(node.body)

    visit_AsyncWith = visit_With
    visit_AsyncFor = visit_For

    def visit_Try(self, node) -> None:
        self.scan_suite(node.body)
        for h in node.handlers:
            self.scan_suite(h.body)
        self.scan_suite(node.orelse)
        self.scan_suite(node.finalbody)

    def visit_FunctionDef(self, node) -> None:
        # Nested defs inherit the enclosing divergence state only when
        # walked explicitly; treat them as part of this function (they
        # run on the same rank's schedule).
        self.scan_suite(node.body)

    visit_AsyncFunctionDef = visit_FunctionDef

    def generic_visit(self, node) -> None:
        if isinstance(node, ast.expr):
            self._scan_calls(node)
            return
        super().generic_visit(node)

    def visit_Expr(self, node) -> None:
        self._scan_calls(node.value)

    def visit_Return(self, node) -> None:
        if node.value is not None:
            self._scan_calls(node.value)

    # .. collective sites ...................................................
    def _scan_calls(self, e) -> None:
        if e is None:
            return
        for node in ast.walk(e):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node)
            if name is None:
                continue
            self.calls.add(name)
            if name in COLLECTIVES or name in self.summaries:
                reason = None
                if self._div_depth > 0:
                    reason = "inside a rank-conditional branch"
                elif self._div_after is not None:
                    reason = self._div_after
                self.sites.append((node.lineno, name, reason))


def _callee_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _branch_escapes(node: ast.If) -> bool:
    """True when either arm of the If leaves the function."""
    def arm(stmts) -> bool:
        return any(
            isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break))
            for s in stmts
        )
    return arm(node.body) or arm(node.orelse)


def _functions(tree: ast.Module):
    """Module-level functions and class methods.  Nested defs are NOT
    yielded separately — they are scanned as part of their enclosing
    function (sharing its divergence state), so yielding them again
    would double-report every site they contain."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield item


def analyze_files(
    files: List[Tuple[str, str]], waivers: Dict[str, str]
) -> Tuple[List[Finding], Set[str]]:
    """Two fixpoint rounds: first learn which package functions issue
    collectives transitively, then classify every site."""
    trees: Dict[str, ast.Module] = {}
    for mod, path in files:
        with open(path) as f:
            trees[mod] = ast.parse(f.read(), filename=path)

    # round 1: transitive may-issue-collective summaries (by basename;
    # collisions only widen the net, never shrink it)
    issue: Set[str] = set()
    calls_of: Dict[str, Set[str]] = {}
    for mod, tree in trees.items():
        for fn in _functions(tree):
            scan = _FnScan(set())
            scan.scan_suite(fn.body)
            calls_of.setdefault(fn.name, set()).update(scan.calls)
            if scan.sites:
                issue.add(fn.name)
    changed = True
    while changed:
        changed = False
        for name, callees in calls_of.items():
            if name not in issue and callees & issue:
                issue.add(name)
                changed = True

    # round 2: site classification with summaries active
    findings: List[Finding] = []
    used: Set[str] = set()
    path_of = dict(files)
    for mod, tree in trees.items():
        rel = _rel(path_of[mod])
        for fn in _functions(tree):
            scan = _FnScan(issue - {fn.name})
            scan.scan_suite(fn.body)
            for lineno, name, reason in scan.sites:
                direct = name in COLLECTIVES
                kind = "collective" if direct else "collective-caller"
                if reason is None:
                    if direct:
                        findings.append(
                            Finding(
                                INFO, "spmd-sites",
                                f"{kind} {name} at {rel}:{lineno} "
                                f"(in {fn.name}) — all ranks reach it",
                            )
                        )
                    continue
                key = f"{os.path.basename(rel)}:{fn.name}"
                if key in waivers:
                    used.add(key)
                    findings.append(
                        Finding(
                            INFO, "spmd-divergence",
                            f"waived: {kind} {name} at {rel}:{lineno} "
                            f"{reason} — {waivers[key]}",
                        )
                    )
                else:
                    findings.append(
                        Finding(
                            ERROR, "spmd-divergence",
                            f"{kind} {name} at {rel}:{lineno} (in "
                            f"{fn.name}) {reason}: ranks that skip the "
                            f"branch never reach the rendezvous — "
                            f"SPMD divergence deadlock",
                        )
                    )
    return findings, used


def run_spmd_teeth() -> CheckResult:
    path = os.path.join(FIXTURE_DIR, "broken_rank_gated_collective.py")
    if not os.path.exists(path):
        return CheckResult.skipped(
            "teeth-rank-gated", "fixture dir not present"
        )
    findings, _ = analyze_files([("fixture", path)], {})
    errs = [
        f for f in findings
        if f.severity == ERROR and f.check == "spmd-divergence"
    ]
    if errs:
        return CheckResult.from_findings(
            "teeth-rank-gated",
            [
                Finding(
                    INFO, "teeth-rank-gated",
                    f"fixture correctly flagged: {errs[0].message}",
                )
            ],
        )
    return CheckResult.from_findings(
        "teeth-rank-gated",
        [
            Finding(
                ERROR, "teeth-rank-gated",
                "broken_rank_gated_collective.py produced NO divergence "
                "error — the SPMD check lost its witness",
            )
        ],
    )


def run_spmd_checks(
    files: Optional[List[Tuple[str, str]]] = None,
    waiver_path: Optional[str] = None,
) -> List[EngineReport]:
    try:
        waivers = load_waivers("spmdcheck", waiver_path)
        waiver_err = None
    except ValueError as e:
        waivers, waiver_err = {}, str(e)
    findings, used = analyze_files(
        files if files is not None else _package_files(), waivers
    )
    wfindings: List[Finding] = []
    if waiver_err is not None:
        wfindings.append(Finding(ERROR, "waivers", waiver_err))
    for key, why in sorted(waivers.items()):
        if key in used:
            wfindings.append(
                Finding(INFO, "waivers", f"in use: {key} — {why}")
            )
        else:
            wfindings.append(
                Finding(
                    ERROR, "waivers",
                    f"stale waiver {key!r}: no current finding matches "
                    f"it — remove the entry or restore the pattern it "
                    f"documents",
                )
            )
    return [
        EngineReport(
            config_name="spmd/collectives",
            checks=[
                CheckResult.from_findings(
                    "spmd-sites",
                    [f for f in findings if f.check == "spmd-sites"],
                ),
                CheckResult.from_findings(
                    "spmd-divergence",
                    [f for f in findings if f.check == "spmd-divergence"],
                ),
            ],
        ),
        EngineReport(
            config_name="spmd/teeth", checks=[run_spmd_teeth()]
        ),
        EngineReport(
            config_name="spmd/waivers",
            checks=[CheckResult.from_findings("waivers", wfindings)],
        ),
    ]
