"""Guard-coverage matrix: which tier detects which fault — with teeth.

One report per (tier, mesh) cell, proving the three claims the guard's
coverage table (docs/RESILIENCE.md "Guard coverage") makes:

- **invariant-detects** — an injected *out-of-range* cell (the 0xA5
  byte a real storage flip produces in uint8) fails the guard's 0/1
  invariant audit, and the rollback-replay recovers the exact clean
  grid.
- **redundant-detects** — an injected *in-range* flip (0↔1: values the
  rule itself could produce) fails the cross-engine redundancy audit
  (``--guard-redundant``), and the recovery is byte-identical.
- **audit-teeth** (the broken fixture) — the same in-range flip driven
  through (a) an **un-audited** run and (b) a **plain** invariant-only
  guard must be *missed* by both: the unguarded final grid must differ
  from the clean run (the corruption is real and silent), and the plain
  guard must report zero failures (the 0/1 invariant alone cannot see
  an in-range value).  If either path "catches" it, the redundancy
  audit's detection claim has lost its witness — a detector that fires
  on corruption an oracle-free run would also reject is proving
  nothing.

Cells run the REAL runtimes (``run_guarded`` / the batch guard) with the
fault plane (:mod:`gol_tpu.resilience.faults`) armed, on CPU — the same
injection surface production uses, not a test double.

Run as part of ``python -m gol_tpu.analysis``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from gol_tpu.analysis.report import (
    ERROR,
    FAIL,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

_PATTERN = 4  # deterministic soup
_ITER = 6
_EVERY = 2
# The flip lands at the FINAL generation so it provably persists into
# the output of an un-audited run (an earlier isolated flip can be
# extinguished by the rule itself, which would fake "missed" results).
_ROW, _COL = 10, 20


@dataclasses.dataclass(frozen=True)
class GuardCellConfig:
    """One (tier, mesh) cell of the coverage matrix."""

    name: str
    engine: str  # dense / bitpack / pallas / activity / batch
    mesh: str = "none"
    size: int = 64
    shard_mode: str = "explicit"
    halo_depth: int = 1


def default_guard_matrix() -> List[GuardCellConfig]:
    return [
        GuardCellConfig("guard/dense/none", "dense"),
        GuardCellConfig("guard/dense/1d", "dense", "1d", size=128),
        GuardCellConfig("guard/bitpack/none", "bitpack"),
        GuardCellConfig("guard/bitpack/2d", "bitpack", "2d", size=128),
        GuardCellConfig(
            "guard/bitpack/1d/pipeline/k=2", "bitpack", "1d", size=128,
            shard_mode="pipeline", halo_depth=2,
        ),
        GuardCellConfig("guard/activity/none", "activity"),
        GuardCellConfig("guard/batch/none", "batch"),
    ]


def _flip_plan(value: int):
    from gol_tpu.resilience import faults

    return faults.FaultPlan.from_obj(
        [
            {
                "site": "board.bitflip",
                "at": _ITER,
                "world": 1,
                "row": _ROW,
                "col": _COL,
                "value": value,
            }
        ]
    )


def _run(cfg: GuardCellConfig, *, guard: bool, redundant: bool = False,
         plan=None):
    """(final, guard_failures) through the real runtime dispatch."""
    from gol_tpu.resilience import faults

    faults.install(plan)
    try:
        if cfg.engine == "batch":
            from gol_tpu.batch import GolBatchRuntime
            from gol_tpu.models import patterns

            worlds = [
                patterns.init_global(_PATTERN, cfg.size, 1)
                for _ in range(3)
            ]
            brt = GolBatchRuntime(
                worlds=worlds,
                engine="auto",
                guard_every=_EVERY if guard else 0,
                guard_redundant=redundant,
            )
            _, boards = brt.run(_ITER)
            failures = brt.last_guard.failures if brt.last_guard else 0
            return [np.asarray(b) for b in boards], failures
        from gol_tpu.models.state import Geometry
        from gol_tpu.runtime import GolRuntime, build_mesh
        from gol_tpu.utils import guard as guard_mod

        rt = GolRuntime(
            geometry=Geometry(size=cfg.size, num_ranks=1),
            engine=cfg.engine,
            mesh=build_mesh(cfg.mesh),
            shard_mode=cfg.shard_mode,
            halo_depth=cfg.halo_depth,
        )
        if guard:
            _, state, report = guard_mod.run_guarded(
                rt,
                pattern=_PATTERN,
                iterations=_ITER,
                config=guard_mod.GuardConfig(
                    check_every=_EVERY, redundant=redundant
                ),
            )
            return np.asarray(state.board), report.failures
        _, state = rt.run(pattern=_PATTERN, iterations=_ITER)
        return np.asarray(state.board), 0
    finally:
        faults.clear()


def _equal(a, b) -> bool:
    if isinstance(a, list):
        return all(np.array_equal(x, y) for x, y in zip(a, b))
    return np.array_equal(a, b)


def check_invariant_detects(cfg, clean) -> CheckResult:
    findings: List[Finding] = []
    final, failures = _run(cfg, guard=True, plan=_flip_plan(0xA5))
    if failures < 1:
        findings.append(
            Finding(
                ERROR, "invariant-detects",
                "an out-of-range cell (0xA5) passed the 0/1 invariant "
                "audit — detection tier 1 is dead on this cell",
            )
        )
    elif not _equal(final, clean):
        findings.append(
            Finding(
                ERROR, "invariant-detects",
                "the flip was detected but rollback-replay did not "
                "recover the clean grid",
            )
        )
    else:
        findings.append(
            Finding(
                INFO, "invariant-detects",
                f"out-of-range flip detected ({failures} audit "
                "failure(s)) and recovered byte-identically",
            )
        )
    return CheckResult.from_findings("invariant-detects", findings)


def check_redundant_detects(cfg, clean) -> CheckResult:
    findings: List[Finding] = []
    final, failures = _run(
        cfg, guard=True, redundant=True, plan=_flip_plan(-1)
    )
    if failures < 1:
        findings.append(
            Finding(
                ERROR, "redundant-detects",
                "an in-range flip survived the cross-engine redundancy "
                "audit — the only in-run SDC oracle missed it",
            )
        )
    elif not _equal(final, clean):
        findings.append(
            Finding(
                ERROR, "redundant-detects",
                "the in-range flip was detected but rollback-replay did "
                "not recover the clean grid",
            )
        )
    else:
        findings.append(
            Finding(
                INFO, "redundant-detects",
                "in-range flip caught by the redundancy audit and "
                "recovered byte-identically",
            )
        )
    return CheckResult.from_findings("redundant-detects", findings)


def check_audit_teeth(cfg, clean) -> CheckResult:
    """The broken fixture: the in-range flip MUST evade everything weaker."""
    findings: List[Finding] = []
    unaudited, _ = _run(cfg, guard=False, plan=_flip_plan(-1))
    if _equal(unaudited, clean):
        findings.append(
            Finding(
                ERROR, "audit-teeth",
                "the un-audited run's final grid EQUALS the clean run "
                "despite the injected in-range flip — the corruption "
                "never landed, so the redundancy audit's catch proves "
                "nothing on this cell",
            )
        )
    else:
        findings.append(
            Finding(
                INFO, "audit-teeth",
                "the un-audited run silently carries the flip into its "
                "final grid (corruption is real and invisible without "
                "the audit)",
            )
        )
    plain_final, plain_failures = _run(
        cfg, guard=True, redundant=False, plan=_flip_plan(-1)
    )
    if plain_failures != 0:
        findings.append(
            Finding(
                ERROR, "audit-teeth",
                f"the PLAIN (invariant-only) guard reported "
                f"{plain_failures} failure(s) on an in-range flip — the "
                "0/1 invariant cannot legitimately see an in-range "
                "value, so this detection is spurious and the "
                "redundancy audit has no exclusive claim",
            )
        )
    elif _equal(plain_final, clean):
        findings.append(
            Finding(
                ERROR, "audit-teeth",
                "the plain guard's final grid equals clean — the flip "
                "vanished without a detection, witness lost",
            )
        )
    else:
        findings.append(
            Finding(
                INFO, "audit-teeth",
                "the plain 0/1 guard misses the in-range flip (0 "
                "failures, corrupted output) while the redundancy audit "
                "catches it — the audit has teeth",
            )
        )
    return CheckResult.from_findings("audit-teeth", findings)


def run_guard_config(cfg: GuardCellConfig) -> EngineReport:
    report = EngineReport(config_name=cfg.name)
    try:
        clean, _ = _run(cfg, guard=False, plan=None)
    except Exception as e:
        report.checks.append(
            CheckResult("config", FAIL, [
                Finding(
                    ERROR, "config",
                    f"guard cell failed to build/run clean: {e}",
                )
            ])
        )
        return report
    report.checks.append(check_invariant_detects(cfg, clean))
    report.checks.append(check_redundant_detects(cfg, clean))
    report.checks.append(check_audit_teeth(cfg, clean))
    return report


def run_guard_checks(
    matrix: Optional[List[GuardCellConfig]] = None,
) -> List[EngineReport]:
    return [run_guard_config(c) for c in (matrix or default_guard_matrix())]
