"""Static + executed checks over the out-of-core streaming tier.

The ooc matrix — one report per (geometry, depth, banding) cell — proves
the invariants the streaming sweep lives or dies by (docs/STREAMING.md),
the way the engine/halo/activity matrices do (docs/ANALYSIS.md):

- **band-schedule soundness** — the plan's bands partition the board's
  row range exactly once, in order, with no band shorter than the visit
  depth (the one-band light-cone premise every ghost read relies on),
  and the rotation footprint respects the device budget when one is
  configured.
- **ghost depth ≥ k and band locality** — the traced visit program
  consumes exactly ``band + 2k`` rows and produces exactly ``band``
  rows (a program that wanted deeper ghosts than the sweep assembles
  could not typecheck against the real extended band), and contains no
  collective: the meshless reuse of the depth-k halo machinery must not
  drag a ring ``ppermute`` into a single-device program.
- **executed equivalence** — the full scheduler (alternating sweeps,
  deferred drains, wrap buffer, dead-band skip on AND off) is bit-equal
  to the in-core dense oracle over a multi-chunk schedule with a
  remainder sweep.
- **shallow-ghost teeth** — the reason the bit-equality pins can be
  trusted: a deliberately-broken scheduler whose assembled ghost is one
  row too shallow (outermost ghost layer zeroed — depth k-1 data
  dressed as depth k) must visibly diverge from the oracle on the same
  soup, while the real sweep matches it.  If the broken fixture ever
  agrees, the staleness invariant has lost its witness.

Run as part of ``python -m gol_tpu.analysis``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from gol_tpu.analysis import walker
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

#: Collectives that must never appear in a meshless visit program.
_COLLECTIVES = ["ppermute", "psum", "all_gather", "all_to_all"]


@dataclasses.dataclass(frozen=True)
class OocConfig:
    """One cell of the ooc verification matrix."""

    name: str
    height: int
    width: int
    depth: int
    band_rows: int = 0
    budget_bytes: int = 0
    schedule: Tuple[int, ...] = (7, 5)
    teeth: bool = False  # carry the shallow-ghost teeth run


def default_ooc_matrix() -> List[OocConfig]:
    return [
        # Remainder-absorbing last band (50 % 7) at depth 1.
        OocConfig("ooc/k1/remainder", 50, 64, 1, band_rows=7),
        # Deep visits; the teeth carrier (one run witnesses the matrix —
        # the broken fixture is geometry-independent).
        OocConfig("ooc/k3/deep", 128, 64, 3, band_rows=13, teeth=True),
        # Degenerate single-band plan: both ghosts are the wrap seam.
        OocConfig("ooc/k4/single-band", 21, 32, 4, band_rows=21),
        # Budget-derived banding: the planner inverts the footprint.
        OocConfig("ooc/k2/budget", 256, 128, 2, budget_bytes=6528),
    ]


def check_band_schedule(cfg: OocConfig, plan) -> CheckResult:
    """Bands partition [0, H) exactly once; none shorter than depth."""
    findings: List[Finding] = []
    covered = 0
    sound = True
    for r0, r1 in plan.bands:
        if r0 != covered or r1 <= r0:
            sound = False
            findings.append(
                Finding(
                    ERROR,
                    "band-schedule",
                    f"band [{r0}, {r1}) breaks the partition at row "
                    f"{covered}: a row stepped twice or never is a "
                    "silently wrong board",
                )
            )
            break
        covered = r1
    if sound and covered != plan.height:
        sound = False
        findings.append(
            Finding(
                ERROR,
                "band-schedule",
                f"bands cover rows [0, {covered}) of {plan.height}: the "
                "tail would never be stepped",
            )
        )
    short = [b for b in plan.band_heights() if b < plan.depth]
    if short:
        sound = False
        findings.append(
            Finding(
                ERROR,
                "band-schedule",
                f"band height(s) {short} < depth {plan.depth}: a ghost "
                "shell would span past the immediate neighbor band, "
                "voiding the one-band light-cone the skip logic and the "
                "deferred drain both rely on",
            )
        )
    if cfg.budget_bytes and plan.device_bytes() > cfg.budget_bytes:
        sound = False
        findings.append(
            Finding(
                ERROR,
                "band-schedule",
                f"rotation footprint {plan.device_bytes()}B exceeds the "
                f"configured budget {cfg.budget_bytes}B",
            )
        )
    if sound:
        findings.append(
            Finding(
                INFO,
                "band-schedule",
                f"{plan.num_bands} band(s) partition {plan.height} rows "
                f"exactly once (min height {min(plan.band_heights())} >= "
                f"depth {plan.depth}; footprint {plan.device_bytes()}B)",
            )
        )
    return CheckResult.from_findings("band-schedule", findings)


def check_ghost_depth(cfg: OocConfig, plan, sched) -> CheckResult:
    """Every visit program consumes band + 2k rows, emits band rows, and
    contains no collective (band locality of the meshless reuse)."""
    import jax
    import jax.numpy as jnp

    findings: List[Finding] = []
    depths = {plan.depth} | {
        t % plan.depth for t in cfg.schedule if t % plan.depth
    }
    for bh in sorted(set(plan.band_heights())):
        for kk in sorted(depths):
            spec = jax.ShapeDtypeStruct(
                (bh + 2 * kk, plan.words), jnp.uint32
            )
            jaxpr = walker.trace_jaxpr(sched.visit_callable(bh, kk), spec)
            (out_aval,) = [v.aval for v in jaxpr.jaxpr.outvars]
            if out_aval.shape != (bh, plan.words):
                findings.append(
                    Finding(
                        ERROR,
                        "ghost-depth",
                        f"visit (bh={bh}, k={kk}) emits {out_aval.shape}, "
                        f"expected ({bh}, {plan.words}) — the write-back "
                        "would corrupt neighboring bands",
                    )
                )
            colls = list(walker.find_eqns(jaxpr, _COLLECTIVES))
            if colls:
                findings.append(
                    Finding(
                        ERROR,
                        "ghost-depth",
                        f"visit (bh={bh}, k={kk}) contains collectives "
                        f"{sorted({i.eqn.primitive.name for i in colls})}: "
                        "the meshless halo reuse dragged ring code into a "
                        "single-device program",
                    )
                )
    if not findings:
        findings.append(
            Finding(
                INFO,
                "ghost-depth",
                f"every (band, k) visit consumes band + 2k rows and "
                f"emits the band, collective-free (k in {sorted(depths)})",
            )
        )
    return CheckResult.from_findings("ghost-depth", findings)


def _soup(h: int, w: int) -> np.ndarray:
    rng = np.random.default_rng(1511)
    return (rng.random((h, w)) < 0.33).astype(np.uint8)


def _oracle(board: np.ndarray, steps: int) -> np.ndarray:
    import jax.numpy as jnp

    from gol_tpu.ops import bitlife

    return np.asarray(bitlife.evolve_dense_io(jnp.asarray(board), steps))


def check_executed_equivalence(cfg: OocConfig, plan) -> CheckResult:
    """Streamed == in-core oracle, with dead-band skip on and off."""
    from gol_tpu.ooc import OocScheduler

    findings: List[Finding] = []
    steps = sum(cfg.schedule)
    board = _soup(cfg.height, cfg.width)
    ref = _oracle(board, steps)
    for skip in (True, False):
        sched = OocScheduler(plan, skip_dead=skip)
        sched.load_dense(board)
        gen = 0
        for take in cfg.schedule:
            sched.run_chunk(take, gen)
            gen += take
        if np.array_equal(sched.dense(), ref):
            findings.append(
                Finding(
                    INFO,
                    "ooc-equivalence",
                    f"skip_dead={skip}: bit-equal to the in-core oracle "
                    f"over {steps} generations ({len(cfg.schedule)} "
                    "chunks incl. a remainder sweep)",
                )
            )
        else:
            findings.append(
                Finding(
                    ERROR,
                    "ooc-equivalence",
                    f"skip_dead={skip}: diverges from the in-core oracle "
                    f"after {steps} generations",
                )
            )
    return CheckResult.from_findings("ooc-equivalence", findings)


def check_shallow_ghost_teeth(cfg: OocConfig, plan) -> CheckResult:
    """Ghost one row too shallow ⇒ must diverge; the real sweep ⇒ must not.

    The broken fixture zeroes the outermost ghost layer of every
    assembled extended band — depth k-1 data dressed in a depth-k shape,
    exactly the bug a mis-sliced neighbor read or an off-by-one band
    boundary would produce.  Its outermost generation per visit reads
    zeros instead of the neighbor's pre-sweep cells, so it must diverge
    from the oracle; if it doesn't, the staleness invariant has no
    witness on this geometry and the check fails.
    """
    from gol_tpu.ooc import OocScheduler

    class _ShallowGhost(OocScheduler):
        def _build_ext(self, idx, kk, down, wrap):
            ext = super()._build_ext(idx, kk, down, wrap)
            ext[0, :] = 0
            ext[-1, :] = 0
            return ext

    findings: List[Finding] = []
    steps = sum(cfg.schedule)
    board = _soup(cfg.height, cfg.width)
    ref = _oracle(board, steps)

    def run(cls):
        sched = cls(plan, skip_dead=False)
        sched.load_dense(board)
        gen = 0
        for take in cfg.schedule:
            sched.run_chunk(take, gen)
            gen += take
        return sched.dense()

    real = run(OocScheduler)
    broken = run(_ShallowGhost)
    if not np.array_equal(real, ref):
        findings.append(
            Finding(
                ERROR,
                "shallow-ghost",
                f"the REAL sweep at k={plan.depth} diverges from the "
                "oracle — the teeth check has nothing to witness against",
            )
        )
    elif np.array_equal(broken, ref):
        findings.append(
            Finding(
                ERROR,
                "shallow-ghost",
                "the one-row-too-shallow broken fixture matched the "
                f"oracle over {steps} generations — the ghost-staleness "
                "invariant has no witness on this board; the bit-equality "
                "pins cannot be trusted to catch a shallow ghost",
            )
        )
    else:
        findings.append(
            Finding(
                INFO,
                "shallow-ghost",
                f"ghost depth k-1 dressed as k={plan.depth} diverges "
                "from the oracle while the real sweep matches it — the "
                "staleness invariant has teeth",
            )
        )
    return CheckResult.from_findings("shallow-ghost", findings)


def run_ooc_config(cfg: OocConfig) -> EngineReport:
    from gol_tpu.ooc import OocScheduler, plan_bands

    report = EngineReport(config_name=cfg.name)
    try:
        plan = plan_bands(
            cfg.height,
            cfg.width,
            cfg.depth,
            band_rows=cfg.band_rows,
            budget_bytes=cfg.budget_bytes,
        )
        sched = OocScheduler(plan)
    except Exception as e:
        from gol_tpu.analysis.report import FAIL

        report.checks.append(
            CheckResult("config", FAIL, [
                Finding(
                    ERROR, "config",
                    f"ooc plan failed to build: {e}",
                )
            ])
        )
        return report
    report.checks.append(check_band_schedule(cfg, plan))
    report.checks.append(check_ghost_depth(cfg, plan, sched))
    report.checks.append(check_executed_equivalence(cfg, plan))
    if cfg.teeth:
        report.checks.append(check_shallow_ghost_teeth(cfg, plan))
    return report


def run_ooc_checks(
    matrix: Optional[List[OocConfig]] = None,
) -> List[EngineReport]:
    return [run_ooc_config(c) for c in (matrix or default_ooc_matrix())]
