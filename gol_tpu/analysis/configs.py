"""The engine×mesh verification matrix.

Every entry describes one *runtime configuration* exactly as
:class:`gol_tpu.runtime.GolRuntime` would build it — same engine
dispatch, same chunk schedule, same abstract input — so what the verifier
traces is what a pod run executes.  Geometries are sized for CPU tracing
(small boards, virtual-device meshes) but respect every engine's real
constraints (packed widths, Pallas alignment, band depth limits); the
*invariants* checked are size-independent.

Unsupported engine×mesh combinations are first-class entries too: the
runtime must *reject* them with a clean ``ValueError`` (that validation
is itself an invariant — a config silently accepted and mis-executed is
exactly the bug class this subsystem exists to catch).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from gol_tpu.models.state import Geometry

MESH_DEVICE_COUNTS = {"none": 0, "1d": 4, "2d": 4}


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """One engine×mesh cell of the verification matrix."""

    name: str
    engine: str  # dense / bitpack / pallas / pallas_bitpack
    mesh: str  # none / 1d / 2d
    size: int = 64  # per-rank square edge; board is (size*num_ranks, size)
    # Chunk schedule driving the verifier: repeated takes exercise the
    # retrace detector; the largest take is the one traced/compiled.
    schedule: Tuple[int, ...] = (8, 8, 4)
    shard_mode: str = "explicit"
    halo_depth: int = 1
    rule: Optional[str] = None
    halo_mode: str = "fresh"
    num_ranks: int = 1
    tile_hint: int = 512
    # None: combination must build; otherwise a substring the runtime's
    # rejection message must contain (negative check).
    reject_reason: Optional[str] = None
    # Strict 2x cost gate only where the XLA flop model is exact (depth-1
    # XLA engines; fusion recompute and interpret-mode Pallas are
    # attribution-only — see checks.check_cost).
    cost_gate: bool = False

    @property
    def steps(self) -> int:
        return sum(self.schedule)

    @property
    def geometry(self) -> Geometry:
        return Geometry(size=self.size, num_ranks=self.num_ranks)

    @property
    def board_shape(self) -> Tuple[int, int]:
        g = self.geometry
        return (g.global_height, g.global_width)

    def build_mesh(self):
        """The (virtual-)device mesh this config runs on, or None."""
        import jax

        from gol_tpu.parallel import mesh as mesh_mod

        n = MESH_DEVICE_COUNTS[self.mesh]
        if n == 0:
            return None
        devices = jax.devices()
        if len(devices) < n:
            raise RuntimeError(
                f"config {self.name!r} needs {n} devices, have "
                f"{len(devices)}; run under "
                f"--xla_force_host_platform_device_count={n} or more"
            )
        if self.mesh == "1d":
            return mesh_mod.make_mesh_1d(n, devices=devices[:n])
        return mesh_mod.make_mesh_2d((2, 2), devices=devices[:n])

    def build_runtime(self):
        """The GolRuntime for this config (raises for reject entries)."""
        from gol_tpu.runtime import GolRuntime

        return GolRuntime(
            geometry=self.geometry,
            engine=self.engine,
            halo_mode=self.halo_mode,
            tile_hint=self.tile_hint,
            mesh=self.build_mesh(),
            shard_mode=self.shard_mode,
            halo_depth=self.halo_depth,
            rule=self.rule,
        )


def default_matrix() -> List[EngineConfig]:
    """All four engines × mesh modes none/1d (+2d where supported)."""
    cfgs: List[EngineConfig] = []

    # -- mesh none: every single-device tier -------------------------------
    cfgs += [
        EngineConfig(
            name="dense/none", engine="dense", mesh="none", cost_gate=True,
        ),
        EngineConfig(
            name="dense/none/stale_t0", engine="dense", mesh="none",
            size=16, halo_mode="stale_t0", num_ranks=4,
        ),
        EngineConfig(
            name="bitpack/none", engine="bitpack", mesh="none",
            cost_gate=True,
        ),
        EngineConfig(
            name="bitpack/none/rule=B36S23", engine="bitpack", mesh="none",
            rule="B36/S23",
        ),
        EngineConfig(
            name="pallas/none", engine="pallas", mesh="none", tile_hint=32,
        ),
        EngineConfig(
            name="pallas_bitpack/none", engine="pallas_bitpack",
            mesh="none", tile_hint=1024,
        ),
    ]

    # -- mesh 1d (4-device ring) -------------------------------------------
    cfgs += [
        EngineConfig(
            name="dense/1d/explicit", engine="dense", mesh="1d",
            cost_gate=True,
        ),
        EngineConfig(
            name="dense/1d/explicit/k=4", engine="dense", mesh="1d",
            halo_depth=4,
        ),
        EngineConfig(
            name="dense/1d/overlap", engine="dense", mesh="1d",
            shard_mode="overlap",
        ),
        # The depth-1 restriction lifted (PR 9): the overlap split at a
        # deep band, and the cross-chunk pipelined double buffer.
        EngineConfig(
            name="dense/1d/overlap/k=4", engine="dense", mesh="1d",
            shard_mode="overlap", halo_depth=4,
        ),
        EngineConfig(
            name="dense/1d/pipeline/k=4", engine="dense", mesh="1d",
            shard_mode="pipeline", halo_depth=4,
        ),
        EngineConfig(
            name="dense/1d/auto", engine="dense", mesh="1d",
            shard_mode="auto",
        ),
        EngineConfig(
            name="bitpack/1d/explicit/k=2", engine="bitpack", mesh="1d",
            halo_depth=2,
        ),
        EngineConfig(
            name="bitpack/1d/overlap", engine="bitpack", mesh="1d",
            shard_mode="overlap",
        ),
        EngineConfig(
            name="bitpack/1d/pipeline/k=4", engine="bitpack", mesh="1d",
            shard_mode="pipeline", halo_depth=4,
        ),
        EngineConfig(
            name="bitpack/1d/rule=B36S23", engine="bitpack", mesh="1d",
            rule="B36/S23",
        ),
        # The flagship: fused Pallas kernel per shard over the packed ring.
        # Band depth 8; the schedule's 8-multiple takes trace the banded
        # chunk loop and the non-multiple tail traces the jnp remainder.
        EngineConfig(
            name="pallas_bitpack/1d/explicit/k=8", engine="pallas_bitpack",
            mesh="1d", halo_depth=8, schedule=(16, 16, 11),
            tile_hint=1024,
        ),
        # The overlap form: interior kernel independent of the band ring
        # (needs shard height >= 2*depth + 8, hence the larger board).
        EngineConfig(
            name="pallas_bitpack/1d/overlap/k=8", engine="pallas_bitpack",
            mesh="1d", size=128, halo_depth=8, shard_mode="overlap",
            schedule=(16, 16), tile_hint=1024,
        ),
        # The pipelined Pallas form: the ring ppermutes for chunk N+1
        # ride operands computed by chunk N's boundary kernels only.
        EngineConfig(
            name="pallas_bitpack/1d/pipeline/k=8", engine="pallas_bitpack",
            mesh="1d", size=128, halo_depth=8, shard_mode="pipeline",
            schedule=(16, 16), tile_hint=1024,
        ),
        # Negative entries: the runtime must refuse these cleanly.
        EngineConfig(
            name="pallas/1d (must reject)", engine="pallas", mesh="1d",
            reject_reason="no sharded path",
        ),
        EngineConfig(
            name="bitpack/1d/auto (must reject)", engine="bitpack",
            mesh="1d", shard_mode="auto",
            reject_reason="no auto-SPMD",
        ),
        EngineConfig(
            name="dense/1d/auto/k=2 (must reject)", engine="dense",
            mesh="1d", shard_mode="auto", halo_depth=2,
            reject_reason="no band to deepen",
        ),
    ]

    # -- mesh 2d (2x2 grid) --------------------------------------------------
    cfgs += [
        EngineConfig(
            name="dense/2d/explicit", engine="dense", mesh="2d",
            cost_gate=True,
        ),
        EngineConfig(
            name="dense/2d/explicit/k=2", engine="dense", mesh="2d",
            halo_depth=2,
        ),
        EngineConfig(
            name="bitpack/2d/explicit", engine="bitpack", mesh="2d",
        ),
        EngineConfig(
            name="pallas_bitpack/2d/explicit/k=8", engine="pallas_bitpack",
            mesh="2d", size=128, halo_depth=8, schedule=(8, 8),
            tile_hint=1024,
        ),
        # PR 9: the depth-k interior/boundary split covers the packed
        # 2-D decomposition too — the old "1-D (row-ring) only"
        # rejection is gone, and the pipeline rides the same geometry.
        EngineConfig(
            name="bitpack/2d/overlap/k=2", engine="bitpack", mesh="2d",
            size=128, shard_mode="overlap", halo_depth=2,
        ),
        EngineConfig(
            name="bitpack/2d/pipeline/k=2", engine="bitpack", mesh="2d",
            size=128, shard_mode="pipeline", halo_depth=2,
        ),
        EngineConfig(
            name="dense/2d/pipeline/k=2", engine="dense", mesh="2d",
            shard_mode="pipeline", halo_depth=2,
        ),
        EngineConfig(
            name="pallas_bitpack/2d/pipeline/k=8", engine="pallas_bitpack",
            mesh="2d", size=128, halo_depth=8, shard_mode="pipeline",
            schedule=(16, 16), tile_hint=1024,
        ),
    ]
    return cfgs


def select(
    matrix: List[EngineConfig],
    engines: Optional[List[str]] = None,
    meshes: Optional[List[str]] = None,
) -> List[EngineConfig]:
    out = matrix
    if engines:
        out = [c for c in out if c.engine in engines]
    if meshes:
        out = [c for c in out if c.mesh in meshes]
    return out
