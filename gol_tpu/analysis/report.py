"""Findings, per-check results, and the engine×mesh report tree.

The verifier's output contract: every check over every engine
configuration produces one :class:`CheckResult` holding zero or more
:class:`Finding`s.  A finding at severity ``error`` means a framework
invariant is violated in the *traced program itself* — the run would be
wrong (or wasteful) on a pod, and the CLI exits non-zero.  ``warn`` marks
suspicious-but-not-disqualifying facts (e.g. a deeper halo band than the
blocking needs); ``info`` is attribution the other checks computed along
the way (op counts, alias bytes) kept for the report.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

ERROR = "error"
WARN = "warn"
INFO = "info"

PASS = "PASS"
FAIL = "FAIL"
SKIP = "SKIP"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One fact a check established about a traced program."""

    severity: str  # ERROR / WARN / INFO
    check: str  # which check produced it (comm, dtype, purity, ...)
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class CheckResult:
    """Outcome of one check over one engine configuration."""

    check: str
    status: str  # PASS / FAIL / SKIP
    findings: List[Finding] = dataclasses.field(default_factory=list)
    skip_reason: Optional[str] = None

    @classmethod
    def from_findings(
        cls, check: str, findings: List[Finding]
    ) -> "CheckResult":
        status = (
            FAIL if any(f.severity == ERROR for f in findings) else PASS
        )
        return cls(check=check, status=status, findings=list(findings))

    @classmethod
    def skipped(cls, check: str, reason: str) -> "CheckResult":
        return cls(check=check, status=SKIP, skip_reason=reason)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    def as_dict(self) -> dict:
        d = {
            "check": self.check,
            "status": self.status,
            "findings": [f.as_dict() for f in self.findings],
        }
        if self.skip_reason:
            d["skip_reason"] = self.skip_reason
        return d


@dataclasses.dataclass
class EngineReport:
    """All check results for one engine×mesh configuration."""

    config_name: str
    checks: List[CheckResult] = dataclasses.field(default_factory=list)
    # A config the runtime must *reject* (negative check): set when the
    # expected ValueError fired; a config that unexpectedly built instead
    # records a FAIL under the "config" pseudo-check.
    rejected: Optional[str] = None

    @property
    def ok(self) -> bool:
        return all(c.status != FAIL for c in self.checks)

    def as_dict(self) -> dict:
        d = {
            "config": self.config_name,
            "ok": self.ok,
            "checks": [c.as_dict() for c in self.checks],
        }
        if self.rejected is not None:
            d["rejected"] = self.rejected
        return d


@dataclasses.dataclass
class AnalysisReport:
    """The whole verification pass."""

    engines: List[EngineReport] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return all(e.ok for e in self.engines)

    @property
    def exit_code(self) -> int:
        return 0 if self.ok else 1

    def as_dict(self) -> dict:
        return {
            "ok": self.ok,
            "engines": [e.as_dict() for e in self.engines],
        }

    def to_json(self) -> str:
        return json.dumps(self.as_dict(), indent=2)

    def render_text(self, verbose: bool = False) -> str:
        """Human report: one block per config, one line per check."""
        lines = []
        n_fail = 0
        for er in self.engines:
            mark = "ok " if er.ok else "FAIL"
            lines.append(f"[{mark}] {er.config_name}")
            if er.rejected is not None:
                lines.append(f"      rejected as expected: {er.rejected}")
            for c in er.checks:
                if c.status == SKIP:
                    lines.append(f"      - {c.check}: skip ({c.skip_reason})")
                    continue
                lines.append(f"      - {c.check}: {c.status}")
                for f in c.findings:
                    if f.severity == ERROR or verbose:
                        lines.append(f"          {f.severity}: {f.message}")
                n_fail += len(c.errors)
        total = len(self.engines)
        bad = sum(1 for e in self.engines if not e.ok)
        lines.append(
            f"{total} configs verified: {total - bad} ok, {bad} failing, "
            f"{n_fail} invariant violations"
        )
        return "\n".join(lines)
