"""Static verification of engine invariants from traced programs.

The framework's correctness story is dynamic — equivalence tests and the
runtime guard audit boards as they evolve.  This package adds the static
half: trace every engine's evolve program with abstract inputs
(``jax.make_jaxpr`` / AOT ``.lower()``), walk the jaxpr/HLO, and *prove*
the invariants the dynamic checks can only sample — on CPU, at zero pod
cost, before anything runs:

- ``walker``  — recursive jaxpr traversal with loop context;
- ``configs`` — the engine×mesh matrix, built through the real
  :class:`~gol_tpu.runtime.GolRuntime` dispatch;
- ``checks``  — comm rings + halo depth, dtype, purity, donation +
  cost-model drift, retrace detection;
- ``report``  — findings and the per-engine report tree;
- ``__main__`` — the ``python -m gol_tpu.analysis`` gate (also reachable
  as ``python -m gol_tpu verify``).

See ``docs/ANALYSIS.md`` for the invariant each check pins.
"""

from gol_tpu.analysis.configs import EngineConfig, default_matrix, select
from gol_tpu.analysis.checks import run_config
from gol_tpu.analysis.report import (
    AnalysisReport,
    CheckResult,
    EngineReport,
    Finding,
)

__all__ = [
    "AnalysisReport",
    "CheckResult",
    "EngineConfig",
    "EngineReport",
    "Finding",
    "default_matrix",
    "run_config",
    "select",
]
