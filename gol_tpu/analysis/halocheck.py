"""Static + executed checks over the pipelined depth-k halo programs.

The halo-pipeline matrix — one report per (tier, mesh, mode, k) — proves
the three invariants the deep-band chunk forms live or die by, the way
the engine/batch/activity/reshard matrices do (docs/ANALYSIS.md):

- **ring soundness at depth k** — every ppermute in the traced chunk
  program is a ±1 ring over the right mesh axis, both directions
  exchanged, and the shipped band is deep enough for the k generations
  it serves (the main matrix's comm check, re-run here over the deep
  overlap/pipeline forms, including the 3-D packed tier the main matrix
  does not cover).
- **exactly one exchange per chunk** — the whole point of the pipeline:
  each loop-carried chunk performs exactly one bidirectional band
  exchange per mesh axis (2 ppermutes).  A second exchange inside the
  body means the double buffer degenerated to the serial form (latency
  back at the head of every chunk); zero means a chunk is consuming a
  band nobody shipped.
- **shallow-band teeth** — the reason the bit-equality pins can be
  trusted: a deliberately-broken chunk loop whose exchanged band is one
  row too shallow (outermost ghost layer zeroed, i.e. depth k-1 dressed
  as depth k) must visibly diverge from the sequential oracle on the
  same board, while the real pipelined loop matches it.  If the broken
  fixture ever agrees with the oracle, the depth invariant has lost its
  witness and the check fails.

Run as part of ``python -m gol_tpu.analysis``.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from gol_tpu.analysis import walker
from gol_tpu.analysis.checks import check_comm, check_dtype, check_purity
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)


@dataclasses.dataclass(frozen=True)
class HaloConfig:
    """One cell of the halo-pipeline verification matrix.

    2-D cells build through the real :class:`~gol_tpu.runtime.GolRuntime`
    dispatch (via :class:`~gol_tpu.analysis.configs.EngineConfig`); the
    3-D cell goes straight to the packed ring builder — the runtime for
    3-D lives in cli3d, which validates through the same modes matrix.
    """

    name: str
    engine: str  # dense / bitpack / pallas_bitpack / bitpack3d
    mesh: str  # 1d / 2d / 3d
    shard_mode: str = "pipeline"
    halo_depth: int = 4
    size: int = 64
    schedule: Tuple[int, ...] = (12, 9)
    # Pallas tiers trace in interpret mode off-TPU — static checks only;
    # the dense/bitpack cells carry the executed equivalence + teeth.
    execute: bool = True


def default_halo_matrix() -> List[HaloConfig]:
    return [
        HaloConfig("halo/dense/1d/pipeline/k=4", "dense", "1d"),
        HaloConfig("halo/dense/2d/pipeline/k=2", "dense", "2d",
                   halo_depth=2),
        HaloConfig("halo/dense/1d/overlap/k=4", "dense", "1d",
                   shard_mode="overlap"),
        HaloConfig("halo/bitpack/1d/pipeline/k=4", "bitpack", "1d"),
        HaloConfig("halo/bitpack/2d/overlap/k=2", "bitpack", "2d",
                   shard_mode="overlap", halo_depth=2, size=128),
        HaloConfig("halo/bitpack/2d/pipeline/k=2", "bitpack", "2d",
                   halo_depth=2, size=128),
        HaloConfig("halo/pallas_bitpack/1d/pipeline/k=8", "pallas_bitpack",
                   "1d", halo_depth=8, size=128, schedule=(16, 16),
                   execute=False),
        HaloConfig("halo/pallas_bitpack/2d/pipeline/k=8", "pallas_bitpack",
                   "2d", halo_depth=8, size=128, schedule=(16, 16),
                   execute=False),
        HaloConfig("halo/bitpack3d/3d/pipeline/k=2", "bitpack3d", "3d",
                   halo_depth=2, size=64, schedule=(8, 6), execute=False),
    ]


def _build(cfg: HaloConfig):
    """(traceable_fn, arg_spec, comm_cfg, mesh) through the real dispatch."""
    import jax
    import jax.numpy as jnp

    if cfg.engine == "bitpack3d":
        from gol_tpu.ops.life3d import BAYS_4555
        from gol_tpu.parallel import mesh as mesh_mod
        from gol_tpu.parallel import sharded3d

        mesh = mesh_mod.make_mesh_3d((2, 2, 1), devices=jax.devices()[:4])
        fn = sharded3d.compiled_evolve3d_packed(
            mesh, max(cfg.schedule), BAYS_4555, cfg.halo_depth,
            cfg.shard_mode,
        )
        spec = jax.ShapeDtypeStruct(
            (cfg.size,) * 3, jnp.uint8,
            sharding=sharded3d.volume_sharding(mesh),
        )
        # check_comm keys slab quanta off the 2-D packed engine name;
        # the 3-D packed tier shares its word-column convention.
        comm_cfg = dataclasses.replace(cfg, engine="bitpack")
        return fn, spec, comm_cfg, mesh

    from gol_tpu.analysis.configs import EngineConfig
    from gol_tpu.parallel import mesh as mesh_mod

    ecfg = EngineConfig(
        name=cfg.name, engine=cfg.engine, mesh=cfg.mesh, size=cfg.size,
        schedule=cfg.schedule, shard_mode=cfg.shard_mode,
        halo_depth=cfg.halo_depth, tile_hint=1024,
    )
    rt = ecfg.build_runtime()
    fn, dynamic, static = rt._evolve_fn(max(cfg.schedule))
    if dynamic or static:
        raise RuntimeError(
            f"{cfg.name}: ring engines take the board only, got extra "
            f"args {dynamic} / {static}"
        )
    h, w = ecfg.board_shape
    spec = jax.ShapeDtypeStruct(
        (h, w), jnp.uint8, sharding=mesh_mod.board_sharding(rt.mesh)
    )
    return fn, spec, cfg, rt.mesh


def check_one_exchange_per_chunk(jaxpr, cfg: HaloConfig, mesh) -> CheckResult:
    """Each loop-carried chunk exchanges exactly once per mesh axis."""
    findings: List[Finding] = []
    per_axis: dict = {}
    for info in walker.find_eqns(jaxpr, ["ppermute"]):
        if not info.in_loop:
            continue  # prologue / remainder-tail exchanges
        axis = info.eqn.params["axis_name"]
        axis = axis[0] if isinstance(axis, tuple) else axis
        per_axis[axis] = per_axis.get(axis, 0) + 1
    if not per_axis:
        findings.append(
            Finding(
                ERROR,
                "one-exchange",
                "no in-loop ppermute: the chunk loop exchanges nothing — "
                "either the loop unrolled (retrace hazard) or shards "
                "evolve independently",
            )
        )
    for axis, count in sorted(per_axis.items()):
        if count != 2:
            findings.append(
                Finding(
                    ERROR,
                    "one-exchange",
                    f"axis {axis!r}: {count} in-loop ppermutes per chunk; "
                    "exactly 2 (one bidirectional band exchange) expected "
                    "— more means the double buffer degenerated to the "
                    "serial form, fewer means a band nobody ships",
                )
            )
        else:
            findings.append(
                Finding(
                    INFO,
                    "one-exchange",
                    f"axis {axis!r}: one exchange (2 ppermutes) per chunk",
                )
            )
    return CheckResult.from_findings("one-exchange", findings)


def _soup(h: int, w: int) -> np.ndarray:
    rng = np.random.default_rng(907)
    return (rng.random((h, w)) < 0.33).astype(np.uint8)


def check_pipeline_equivalence(
    cfg: HaloConfig, fn, spec, mesh
) -> CheckResult:
    """Executed: the deep-band chunk program == the sequential oracle."""
    import jax.numpy as jnp

    from gol_tpu.ops import stencil
    from gol_tpu.parallel import mesh as mesh_mod

    findings: List[Finding] = []
    steps = max(cfg.schedule)
    board_np = _soup(*spec.shape)
    ref = np.asarray(stencil.run(jnp.asarray(board_np), steps))
    out = fn(
        mesh_mod.place_private(
            jnp.asarray(board_np), mesh_mod.board_sharding(mesh)
        )
    )
    if np.array_equal(np.asarray(out), ref):
        findings.append(
            Finding(
                INFO,
                "pipeline-equivalence",
                f"{cfg.shard_mode} k={cfg.halo_depth} bit-equal to the "
                f"sequential oracle over {steps} generations",
            )
        )
    else:
        findings.append(
            Finding(
                ERROR,
                "pipeline-equivalence",
                f"{cfg.shard_mode} k={cfg.halo_depth} diverges from the "
                f"sequential oracle after {steps} generations",
            )
        )
    return CheckResult.from_findings("pipeline-equivalence", findings)


def check_shallow_band_teeth(cfg: HaloConfig) -> CheckResult:
    """Band one row too shallow ⇒ must diverge; real pipeline ⇒ must not.

    Runs two dense 1-D ring programs on the same soup: the real
    pipelined loop at depth k, and a broken chunk loop whose exchanged
    band has its outermost ghost layer zeroed — depth k-1 data dressed
    in a depth-k shape, exactly the bug a mis-sliced ``ppermute`` operand
    would produce.  The broken run's outermost generation per chunk reads
    zeros instead of the neighbor's cells, so it must diverge from the
    oracle; if it doesn't, the bit-equality pins have no witness on this
    geometry and the check fails.
    """
    import jax
    import jax.numpy as jnp

    from gol_tpu import compat
    from gol_tpu.ops import stencil
    from gol_tpu.parallel import halo
    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel.mesh import ROWS

    findings: List[Finding] = []
    k = cfg.halo_depth
    steps = max(cfg.schedule)
    mesh = mesh_mod.make_mesh_1d(4, devices=jax.devices()[:4])
    phases = ((0, ROWS, 4),)
    step = lambda ext: stencil.step_halo_rows(ext[1:-1], ext[0], ext[-1])

    def shallow(bands):
        out = []
        for (axis, _, _), (lo, hi) in zip(phases, bands):
            nd = lo.ndim
            zero = jnp.zeros_like(
                lo[halo._axis_slice(nd, axis, slice(0, 1))]
            )
            out.append((
                jnp.concatenate(
                    [zero, lo[halo._axis_slice(nd, axis, slice(1, None))]],
                    axis=axis,
                ),
                jnp.concatenate(
                    [hi[halo._axis_slice(nd, axis, slice(None, -1))], zero],
                    axis=axis,
                ),
            ))
        return tuple(out)

    def broken_local(x):
        full, rem = divmod(steps, k)
        for kk in [k] * full + ([rem] if rem else []):
            bands = shallow(halo.exchange_bands(x, phases, kk))
            x = halo._consume_chunk(step, phases, x, bands, kk)
        return x

    from jax.sharding import PartitionSpec as P

    spec = mesh_mod.board_sharding(mesh)
    broken_fn = jax.jit(
        compat.shard_map(
            broken_local, mesh=mesh, in_specs=P(ROWS, None),
            out_specs=P(ROWS, None),
        )
    )
    real_fn = jax.jit(
        compat.shard_map(
            halo.pipelined_local_loop(step, phases, steps, k),
            mesh=mesh, in_specs=P(ROWS, None), out_specs=P(ROWS, None),
        )
    )

    board_np = _soup(64, 64)
    ref = np.asarray(stencil.run(jnp.asarray(board_np), steps))
    place = lambda: mesh_mod.place_private(jnp.asarray(board_np), spec)
    real = np.asarray(real_fn(place()))
    broken = np.asarray(broken_fn(place()))
    if not np.array_equal(real, ref):
        findings.append(
            Finding(
                ERROR,
                "shallow-band",
                f"the REAL pipelined loop at k={k} diverges from the "
                "oracle — the teeth check has nothing to witness against",
            )
        )
    elif np.array_equal(broken, ref):
        findings.append(
            Finding(
                ERROR,
                "shallow-band",
                "the one-row-too-shallow broken fixture matched the "
                f"oracle over {steps} generations — the depth invariant "
                "has no witness on this board; the bit-equality pins "
                "cannot be trusted to catch a shallow band",
            )
        )
    else:
        findings.append(
            Finding(
                INFO,
                "shallow-band",
                f"band depth k-1 dressed as k={k} diverges from the "
                "oracle while the real pipeline matches it — the depth "
                "invariant has teeth",
            )
        )
    return CheckResult.from_findings("shallow-band", findings)


def run_halo_config(cfg: HaloConfig) -> EngineReport:
    report = EngineReport(config_name=cfg.name)
    try:
        fn, spec, comm_cfg, mesh = _build(cfg)
        jaxpr = walker.trace_jaxpr(fn, spec)
    except Exception as e:
        from gol_tpu.analysis.report import FAIL

        report.checks.append(
            CheckResult("config", FAIL, [
                Finding(
                    ERROR, "config",
                    f"halo program failed to build/trace: {e}",
                )
            ])
        )
        return report
    report.checks.append(check_comm(jaxpr, comm_cfg, mesh))
    report.checks.append(check_dtype(jaxpr, comm_cfg))
    report.checks.append(check_purity(jaxpr, comm_cfg))
    report.checks.append(check_one_exchange_per_chunk(jaxpr, cfg, mesh))
    if cfg.execute:
        report.checks.append(
            check_pipeline_equivalence(cfg, fn, spec, mesh)
        )
    if cfg.name == "halo/dense/1d/pipeline/k=4":
        # One teeth run carries the whole matrix: the broken fixture is
        # mode-independent (any ring form consuming a shallow band reads
        # the same zeros).
        report.checks.append(check_shallow_band_teeth(cfg))
    return report


def run_halo_checks(
    matrix: Optional[List[HaloConfig]] = None,
) -> List[EngineReport]:
    return [run_halo_config(c) for c in (matrix or default_halo_matrix())]
