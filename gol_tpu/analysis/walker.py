"""Recursive jaxpr traversal with loop/transform context.

Engine programs nest jaxprs several levels deep — ``pjit`` → ``shard_map``
→ ``while``/``scan`` bodies → more ``pjit`` — and every static check needs
the same two facts about an equation: *what primitive is it* and *is it
inside the generation loop*.  This module owns that traversal so the
checks stay declarative: :func:`iter_eqns` yields every equation in the
tree tagged with its enclosing-loop depth and the path of higher-order
primitives above it, descending into any equation parameter that holds a
``Jaxpr``/``ClosedJaxpr`` (robust to jaxpr parameter naming across JAX
versions).
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, List, Sequence, Tuple, Union

import jax
from jax import core as jax_core

# Primitives whose body executes a data-dependent number of times: an
# equation inside one runs "per loop trip" for invariant purposes.
LOOP_PRIMITIVES = frozenset({"while", "scan"})


@dataclasses.dataclass(frozen=True)
class EqnInfo:
    """One equation plus where in the program tree it sits."""

    eqn: jax_core.JaxprEqn
    path: Tuple[str, ...]  # names of enclosing higher-order primitives
    loop_depth: int  # number of enclosing while/scan bodies

    @property
    def name(self) -> str:
        return self.eqn.primitive.name

    @property
    def in_loop(self) -> bool:
        return self.loop_depth > 0


def _as_jaxpr(value) -> Union[jax_core.Jaxpr, None]:
    if isinstance(value, jax_core.Jaxpr):
        return value
    if isinstance(value, jax_core.ClosedJaxpr):
        return value.jaxpr
    return None


def _sub_jaxprs(eqn: jax_core.JaxprEqn) -> List[jax_core.Jaxpr]:
    subs = []
    for value in eqn.params.values():
        j = _as_jaxpr(value)
        if j is not None:
            subs.append(j)
        elif isinstance(value, (tuple, list)):
            for item in value:
                j = _as_jaxpr(item)
                if j is not None:
                    subs.append(j)
    return subs


def iter_eqns(
    jaxpr: Union[jax_core.Jaxpr, jax_core.ClosedJaxpr],
    _path: Tuple[str, ...] = (),
    _loop_depth: int = 0,
) -> Iterator[EqnInfo]:
    """Depth-first walk of every equation in the jaxpr tree."""
    j = _as_jaxpr(jaxpr)
    if j is None:
        raise TypeError(f"not a jaxpr: {type(jaxpr)!r}")
    for eqn in j.eqns:
        name = eqn.primitive.name
        yield EqnInfo(eqn=eqn, path=_path, loop_depth=_loop_depth)
        inner_depth = _loop_depth + (1 if name in LOOP_PRIMITIVES else 0)
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, _path + (name,), inner_depth)


def primitive_names(jaxpr) -> List[str]:
    """All primitive names in the tree (with duplicates)."""
    return [info.name for info in iter_eqns(jaxpr)]


def find_eqns(jaxpr, names: Sequence[str]) -> List[EqnInfo]:
    """Every equation whose primitive name is in ``names``."""
    wanted = frozenset(names)
    return [info for info in iter_eqns(jaxpr) if info.name in wanted]


def all_avals(jaxpr) -> List[Tuple[EqnInfo, jax_core.AbstractValue]]:
    """(equation, aval) for every input/output of every equation."""
    out = []
    for info in iter_eqns(jaxpr):
        for var in list(info.eqn.invars) + list(info.eqn.outvars):
            aval = getattr(var, "aval", None)
            if aval is not None:
                out.append((info, aval))
    return out


def trace_jaxpr(fn, *args, static_argnums=()):
    """Jaxpr of ``fn`` on abstract ``args`` (ShapeDtypeStructs welcome).

    Prefers the AOT ``.trace`` path for jitted functions (statics already
    bound by ``jax.jit``); falls back to ``jax.make_jaxpr`` with explicit
    ``static_argnums`` for plain callables.
    """
    trace = getattr(fn, "trace", None)
    if trace is not None:
        try:
            return trace(*args).jaxpr
        except (TypeError, AttributeError):
            pass
    return jax.make_jaxpr(fn, static_argnums=static_argnums)(*args)
