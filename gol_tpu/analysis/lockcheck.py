"""lockcheck — thread-safety verifier for the host plane.

The serving tier (PR 12) and the elasticity plane (PR 14) reintroduced
real shared-mutable-state concurrency around the pure step function:
``ThreadingHTTPServer`` handler threads, the ``ServeScheduler`` drive
loop, the async snapshot writer, and the metrics observer all touch the
same objects.  This pass proves, from the AST alone (nothing analyzed
is ever imported or executed), three properties per deployment cell:

- **inventory** — every ``threading.Lock/RLock/Condition`` and every
  thread entry point (configured roots plus discovered
  ``threading.Thread(target=...)`` sites) is enumerated, so a new lock
  or thread cannot appear without the analyzer seeing it.
- **lock-order** — the cross-module lock acquisition graph built from
  nested ``with lock:`` scopes (interprocedurally, through resolved
  calls) must be acyclic; a cycle is a potential deadlock.  Acquiring a
  non-reentrant ``Lock`` already held is a self-deadlock and reported
  directly.  RLock/Condition re-entry is legal and adds no edge.
- **guarded-fields** — fields of the classes in the cell's discipline
  table that are reachable from ≥2 thread labels and mutated anywhere
  must be accessed only while holding their owning lock.  Violations
  are ``file:line`` findings.  Intentional lock-free patterns (e.g. the
  telemetry shed handoff) are *waived*, not silenced: the committed
  ``concurrency_waivers.json`` carries a one-line justification per
  key, waived findings render as INFO, and a waiver that matches no
  finding is itself an ERROR (stale waivers rot the discipline table).

Construction is exempt by design: the walk never descends into
``__init__`` bodies — pre-publication objects are single-threaded, and
treating constructor writes as shared accesses would drown the report.
Sync-primitive-typed fields (Event/Queue) are exempt: touching the
primitive object is the thread-safe operation itself.

TEETH: the committed broken fixtures under
``tests/data/concurrency_fixtures/`` (a real lock inversion and an
unguarded cross-thread write) are analyzed on every run and MUST fail;
a fixture coming back green means the analyzer lost its witness.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from gol_tpu.analysis import hostwalk
from gol_tpu.analysis.hostwalk import Env, FuncInfo, Program
from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_DIR)
WAIVER_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "concurrency_waivers.json"
)
FIXTURE_DIR = os.path.join(
    _REPO_ROOT, "tests", "data", "concurrency_fixtures"
)


@dataclasses.dataclass
class LockCellConfig:
    """One deployment topology: which modules run which threads."""

    name: str
    # (short module name, absolute file path)
    modules: List[Tuple[str, str]]
    # (thread label, function suffix) — see Program.find
    roots: List[Tuple[str, str]]
    # class basename -> owning lock id (None = no lock exists; every
    # shared mutated access needs a waiver)
    guarded: Dict[str, Optional[str]]
    # "Class.method" -> returned class basename (reviewed modeling
    # table for factories the inferencer cannot see through)
    returns: Dict[str, str] = dataclasses.field(default_factory=dict)
    # "Class.attr" -> callee suffixes: calling the attribute invokes
    # these (the EventLog.observer -> MetricsRegistry.observe binding)
    callbacks: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict
    )
    # callee suffix -> thread label: function-valued arguments of this
    # callee run later on that thread (the async-writer submit queue)
    deferred: Dict[str, str] = dataclasses.field(default_factory=dict)
    # caller suffix -> extra callee suffixes the AST cannot resolve
    extra_edges: Dict[str, List[str]] = dataclasses.field(
        default_factory=dict
    )
    # (class, attr) -> class basename: type facts the inferencer
    # cannot derive (plumbed-through constructor results)
    attr_types: Dict[Tuple[str, str], str] = dataclasses.field(
        default_factory=dict
    )


def _pkg(rel: str) -> Tuple[str, str]:
    mod = rel[:-3].replace("/", ".").replace(".__init__", "")
    return (mod, os.path.join(_PKG_DIR, rel))


def default_lock_matrix() -> List[LockCellConfig]:
    serve_modules = [
        _pkg("serve/scheduler.py"),
        _pkg("serve/server.py"),
        _pkg("serve/journal.py"),
        ("serve.main", os.path.join(_PKG_DIR, "serve", "__main__.py")),
        _pkg("telemetry/__init__.py"),
        _pkg("telemetry/metrics.py"),
        _pkg("telemetry/blackbox.py"),
        _pkg("resilience/health.py"),
        _pkg("resilience/degrade.py"),
        _pkg("resilience/faults.py"),
    ]
    runtime_modules = [
        _pkg("runtime.py"),
        _pkg("utils/checkpoint.py"),
        _pkg("telemetry/__init__.py"),
        _pkg("telemetry/metrics.py"),
        _pkg("telemetry/blackbox.py"),
        _pkg("resilience/degrade.py"),
        _pkg("resilience/faults.py"),
    ]
    return [
        LockCellConfig(
            name="lock/serve",
            modules=serve_modules,
            roots=[
                ("http", "serve.server:_Handler.do_GET"),
                ("http", "serve.server:_Handler.do_POST"),
                ("main", "serve.main:main"),
                ("main", "ServeScheduler.run_once"),
                ("main", "ServeScheduler.run_until_drained"),
                ("main", "ServeScheduler.drain"),
                ("main", "ServeScheduler.close"),
            ],
            guarded={
                "ServeScheduler": "ServeScheduler._lock",
                "RequestState": "ServeScheduler._lock",
                "Journal": "ServeScheduler._lock",
                "HealthMonitor": "ServeScheduler._lock",
                "EventLog": "ServeScheduler._lock",
                "MetricsRegistry": "MetricsRegistry._lock",
                "FlightRecorder": "FlightRecorder._lock",
            },
            returns={
                "ServeScheduler.get_result": "RequestState",
                "ServeScheduler.submit": "RequestState",
                # blackbox module accessors: the process-default ring.
                "recorder": "FlightRecorder",
                "install": "FlightRecorder",
            },
            callbacks={
                "EventLog.observer": ["MetricsRegistry.observe"],
            },
        ),
        LockCellConfig(
            name="lock/runtime",
            modules=runtime_modules,
            roots=[
                ("main", "GolRuntime.run"),
                ("metrics-http", "telemetry.metrics:_Handler.do_GET"),
                ("ckpt-writer", "AsyncSnapshotWriter._loop"),
            ],
            guarded={
                "EventLog": None,
                "MetricsRegistry": "MetricsRegistry._lock",
                "AsyncSnapshotWriter": None,
                "FlightRecorder": "FlightRecorder._lock",
            },
            returns={
                "recorder": "FlightRecorder",
                "install": "FlightRecorder",
            },
            callbacks={
                "EventLog.observer": ["MetricsRegistry.observe"],
            },
            deferred={
                "AsyncSnapshotWriter.submit": "ckpt-writer",
            },
            attr_types={
                ("GolRuntime", "_live_events"): "EventLog",
                ("GolRuntime", "_ckpt_writer"): "AsyncSnapshotWriter",
            },
        ),
    ]


# -- waivers -----------------------------------------------------------------
def load_waivers(
    section: str, path: Optional[str] = None
) -> Dict[str, str]:
    """key -> one-line justification for one pass's section."""
    p = path or WAIVER_PATH
    if not os.path.exists(p):
        return {}
    with open(p) as f:
        data = json.load(f)
    known = {"version", "lockcheck", "spmdcheck"}
    unknown = set(data) - known
    if unknown:
        raise ValueError(
            f"unknown sections in {os.path.basename(p)}: {sorted(unknown)}"
        )
    out: Dict[str, str] = {}
    for entry in data.get(section, []):
        if set(entry) != {"key", "why"} or not entry["why"].strip():
            raise ValueError(
                f"waiver entries need exactly 'key' and a non-empty "
                f"'why': {entry!r}"
            )
        out[entry["key"]] = entry["why"]
    return out


# -- the walk ----------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Access:
    cls: str
    attr: str
    path: str
    lineno: int
    label: str
    held: FrozenSet[str]
    is_write: bool

    @property
    def key(self) -> str:
        return f"{self.cls}.{self.attr}"


class _CellWalker:
    def __init__(self, prog: Program, cfg: LockCellConfig) -> None:
        self.prog = prog
        self.cfg = cfg
        self.accesses: Set[Access] = set()
        # (held_lock, acquired_lock) -> (path, lineno)
        self.edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        self.lock_errors: List[Finding] = []
        self.roots_walked: List[Tuple[str, str]] = []
        self._memo: Set[Tuple[str, FrozenSet[str], str]] = set()
        for (c, a), t in cfg.attr_types.items():
            info = prog.classes.get(c)
            if info is not None:
                info.attr_types.setdefault(a, ("plain", t))

    # .. roots ..............................................................
    def run(self) -> None:
        for label, suffix in self.cfg.roots:
            fi = self.prog.find(suffix)
            if fi is None:
                self.lock_errors.append(
                    Finding(
                        ERROR, "inventory",
                        f"configured root {suffix!r} not found — the "
                        f"entry-point table is stale",
                    )
                )
                continue
            self.roots_walked.append((label, fi.key))
            self._visit(fi, frozenset(), label)
        walked_keys = {key for _, key in self.roots_walked}
        for site in self.prog.thread_sites:
            fi, label = self._resolve_thread(site)
            # A function already rooted under a configured label is not
            # re-rooted under its thread-name label (one root per
            # entry-point function; the label is just its display name).
            if fi is not None and fi.key not in walked_keys:
                walked_keys.add(fi.key)
                self.roots_walked.append((label, fi.key))
                self._visit(fi, frozenset(), label)

    def _resolve_thread(self, site) -> Tuple[Optional[FuncInfo], str]:
        target = None
        label = None
        for kw in site.call.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "name" and isinstance(kw.value, ast.Constant):
                label = str(kw.value.value)
        if target is None:
            return None, ""
        env = self._env(site.func) if site.func else None
        fi = self._resolve_callee(target, env) if env else None
        if fi is None and isinstance(target, ast.Name):
            fi = self.prog.functions.get(f"{site.mod}:{target.id}")
        if fi is None:
            return None, ""
        return fi, label or fi.key.rsplit(".", 1)[-1]

    def _env(self, fi: FuncInfo) -> Env:
        env = Env(self.prog, fi, returns=dict(self.cfg.returns))
        node = fi.node
        if hasattr(node, "args"):
            for arg in node.args.args + node.args.kwonlyargs:
                if arg.annotation is not None:
                    t = hostwalk._annotation_type(arg.annotation)
                    if t is not None:
                        env.locals[arg.arg] = t
        return env

    # .. function visit .....................................................
    def _visit(
        self, fi: FuncInfo, held: FrozenSet[str], label: str
    ) -> None:
        memo_key = (fi.key, held, label)
        if memo_key in self._memo:
            return
        self._memo.add(memo_key)
        if fi.key.rsplit(".", 1)[-1] == "__init__":
            return  # construction phase: pre-publication, one thread
        env = self._env(fi)
        self._stmts(list(fi.node.body), env, held, label, fi)
        for suffix in self.cfg.extra_edges.get(
            fi.key.split(":", 1)[-1], []
        ):
            callee = self.prog.find(suffix)
            if callee is not None:
                self._visit(callee, held, label)

    def _stmts(self, stmts, env, held, label, fi) -> FrozenSet[str]:
        for st in stmts:
            held = self._stmt(st, env, held, label, fi)
        return held

    def _stmt(self, st, env, held, label, fi) -> FrozenSet[str]:
        if isinstance(st, ast.With):
            inner = held
            path = _rel(self.prog.paths[env.mod])
            for item in st.items:
                lid = hostwalk.lock_id(item.context_expr, env)
                if lid is not None:
                    inner = self._acquire(lid, inner, st, label, path)
                else:
                    self._expr(item.context_expr, env, held, label, fi)
            self._stmts(st.body, env, inner, label, fi)
            return held
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return held  # nested defs run when called, not when defined
        if isinstance(st, ast.Assign):
            self._expr(st.value, env, held, label, fi)
            t = hostwalk.infer(st.value, env)
            for tgt in st.targets:
                if isinstance(tgt, ast.Name) and t is not None:
                    env.locals[tgt.id] = t
                self._target(tgt, env, held, label, fi)
            return held
        if isinstance(st, ast.AugAssign):
            self._expr(st.value, env, held, label, fi)
            self._record_attr(st.target, env, held, label, fi, write=True)
            return held
        if isinstance(st, ast.AnnAssign):
            if st.value is not None:
                self._expr(st.value, env, held, label, fi)
            if isinstance(st.target, ast.Name):
                t = hostwalk._annotation_type(st.annotation)
                if t is not None:
                    env.locals[st.target.id] = t
            else:
                self._target(st.target, env, held, label, fi)
            return held
        if isinstance(st, (ast.If, ast.While)):
            self._expr(st.test, env, held, label, fi)
            self._stmts(st.body, env, held, label, fi)
            self._stmts(st.orelse, env, held, label, fi)
            return held
        if isinstance(st, ast.For):
            self._expr(st.iter, env, held, label, fi)
            if isinstance(st.target, ast.Name):
                t = hostwalk.iter_elt(st.iter, env)
                if t is not None:
                    env.locals[st.target.id] = t
            self._stmts(st.body, env, held, label, fi)
            self._stmts(st.orelse, env, held, label, fi)
            return held
        if isinstance(st, ast.Try):
            self._stmts(st.body, env, held, label, fi)
            for h in st.handlers:
                self._stmts(h.body, env, held, label, fi)
            self._stmts(st.orelse, env, held, label, fi)
            self._stmts(st.finalbody, env, held, label, fi)
            return held
        if isinstance(st, ast.Expr):
            # Bare acquire()/release() statements adjust the held set
            # for the remainder of the suite.
            if isinstance(st.value, ast.Call):
                fn = st.value.func
                if isinstance(fn, ast.Attribute) and fn.attr in (
                    "acquire", "release"
                ):
                    lid = hostwalk.lock_id(fn.value, env)
                    if lid is not None:
                        if fn.attr == "acquire":
                            return self._acquire(
                                lid, held, st, label,
                                _rel(self.prog.paths[env.mod]),
                            )
                        return held - {lid[0]}
            self._expr(st.value, env, held, label, fi)
            return held
        if isinstance(st, ast.Return) and st.value is not None:
            self._expr(st.value, env, held, label, fi)
            return held
        if isinstance(st, ast.Raise):
            if st.exc is not None:
                self._expr(st.exc, env, held, label, fi)
            return held
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, env, held, label, fi)
        return held

    def _acquire(self, lid, held, node, label, path) -> FrozenSet[str]:
        name, kind = lid
        if name in held:
            if kind == "lock":
                self.lock_errors.append(
                    Finding(
                        ERROR, "lock-order",
                        f"non-reentrant lock {name} re-acquired while "
                        f"already held (self-deadlock) at {path}:"
                        f"{node.lineno} [thread {label!r}]",
                    )
                )
            return held
        for h in held:
            self.edges.setdefault((h, name), (path, node.lineno))
        return held | {name}

    def _target(self, tgt, env, held, label, fi) -> None:
        if isinstance(tgt, ast.Attribute):
            self._record_attr(tgt, env, held, label, fi, write=True)
        elif isinstance(tgt, ast.Subscript):
            # d[k] = v on a guarded attribute mutates the field.
            if isinstance(tgt.value, ast.Attribute):
                self._record_attr(
                    tgt.value, env, held, label, fi, write=True
                )
            self._expr(tgt.slice, env, held, label, fi)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._target(e, env, held, label, fi)

    # .. expressions ........................................................
    def _expr(self, e, env, held, label, fi) -> None:
        if e is None:
            return
        if isinstance(e, ast.Call):
            self._call(e, env, held, label, fi)
            return
        if isinstance(e, ast.Attribute):
            self._record_attr(e, env, held, label, fi, write=False)
            self._expr(e.value, env, held, label, fi)
            return
        if isinstance(e, (ast.Lambda, ast.FunctionDef)):
            return  # deferred bodies run where they are invoked
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, env, held, label, fi)
            elif isinstance(child, ast.comprehension):
                self._expr(child.iter, env, held, label, fi)
                for cond in child.ifs:
                    self._expr(cond, env, held, label, fi)

    def _call(self, call, env, held, label, fi) -> None:
        p = self.prog
        fn = call.func
        # receiver mutation: self._requests.clear() writes the field
        if (
            isinstance(fn, ast.Attribute)
            and fn.attr in hostwalk.MUTATING_METHODS
            and isinstance(fn.value, ast.Attribute)
        ):
            self._record_attr(fn.value, env, held, label, fi, write=True)
        callees: List[FuncInfo] = []
        deferred_label: Optional[str] = None
        callee = self._resolve_callee(fn, env)
        if callee is not None:
            callees.append(callee)
            tail = callee.key.split(":", 1)[-1]
            for suffix, lbl in self.cfg.deferred.items():
                if tail == suffix or tail.endswith("." + suffix):
                    deferred_label = lbl
        # callback attributes: self.observer(rec)
        if isinstance(fn, ast.Attribute) and callee is None:
            recv = hostwalk.infer(fn.value, env)
            if recv is not None and recv[0] == "plain":
                for suffix in self.cfg.callbacks.get(
                    f"{recv[1]}.{fn.attr}", []
                ):
                    cb = p.find(suffix)
                    if cb is not None:
                        callees.append(cb)
        if isinstance(fn, ast.Attribute):
            self._record_attr(fn, env, held, label, fi, write=False)
            self._expr(fn.value, env, held, label, fi)
        for c in callees:
            self._visit(c, held, label)
        # function-valued arguments are invoked (now, or later on the
        # deferred executor's thread with nothing held)
        for arg in list(call.args) + [
            kw.value for kw in call.keywords
        ]:
            target = self._resolve_callee(arg, env)
            if target is not None:
                if deferred_label is not None:
                    self._visit(target, frozenset(), deferred_label)
                else:
                    self._visit(target, held, label)
            else:
                self._expr(arg, env, held, label, fi)

    def _resolve_callee(self, fn, env) -> Optional[FuncInfo]:
        p = self.prog
        if isinstance(fn, ast.Name):
            nested = p.functions.get(f"{env.func.key}.{fn.id}")
            if nested is not None:
                return nested
            mod_fn = p.functions.get(f"{env.mod}:{fn.id}")
            if mod_fn is not None:
                return mod_fn
            return None
        if isinstance(fn, ast.Attribute):
            if fn.attr.startswith("__") and fn.attr != "__call__":
                return None
            if isinstance(fn.value, ast.Name):
                alias = fn.value.id
                target = p.imports.get(env.mod, {}).get(alias)
                if target is not None:
                    short = target.rsplit(".", 1)[-1]
                    for key, info in p.functions.items():
                        m, rest = key.split(":", 1)
                        if rest == fn.attr and (
                            m == target
                            or m.rsplit(".", 1)[-1] == short
                        ):
                            return info
            recv = hostwalk.infer(fn.value, env)
            if recv is not None and recv[0] == "plain":
                m = p.method(recv[1], fn.attr)
                if m is not None and m.key.rsplit(".", 1)[-1] != "__init__":
                    return m
        return None

    def _record_attr(self, node, env, held, label, fi, write) -> None:
        if not isinstance(node, ast.Attribute):
            return
        recv = hostwalk.infer(node.value, env)
        if recv is None or recv[0] != "plain":
            return
        cls = recv[1]
        if cls not in self.cfg.guarded:
            return
        info = self.prog.classes.get(cls)
        if info is not None:
            kind = info.attr_kinds.get(node.attr)
            if kind in ("lock", "rlock", "sync"):
                return  # the primitive itself is the synchronization
        m = self.prog.method(cls, node.attr)
        if m is not None:
            if m.is_property:
                self._visit(m, held, label)
            return  # methods are calls, not field state
        self.accesses.add(
            Access(
                cls, node.attr, _rel(self.prog.paths[env.mod]),
                node.lineno, label, held, write,
            )
        )


def _rel(path: str) -> str:
    try:
        return os.path.relpath(path, _REPO_ROOT)
    except ValueError:
        return path


# -- cycle detection ---------------------------------------------------------
def find_cycle(
    edges: Dict[Tuple[str, str], Tuple[str, int]]
) -> Optional[List[str]]:
    graph: Dict[str, List[str]] = {}
    for a, b in edges:
        graph.setdefault(a, []).append(b)
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for nxt in graph.get(n, []):
            c = color.get(nxt, WHITE)
            if c == GRAY:
                return stack[stack.index(nxt):] + [nxt]
            if c == WHITE:
                got = dfs(nxt)
                if got is not None:
                    return got
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color.get(n, WHITE) == WHITE:
            got = dfs(n)
            if got is not None:
                return got
    return None


# -- per-cell analysis -------------------------------------------------------
def analyze_cell(
    cfg: LockCellConfig, waivers: Dict[str, str]
) -> Tuple[EngineReport, Set[str]]:
    prog = Program.load(cfg.modules)
    walker = _CellWalker(prog, cfg)
    walker.run()

    inventory: List[Finding] = [
        f for f in walker.lock_errors if f.check == "inventory"
    ]
    for cname, info in sorted(prog.classes.items()):
        for attr, kind in sorted(info.attr_kinds.items()):
            if kind in ("lock", "rlock"):
                inventory.append(
                    Finding(
                        INFO, "inventory",
                        f"lock {cname}.{attr} ({kind}) in "
                        f"{_rel(prog.paths[info.mod])}",
                    )
                )
    for (mod, name), kind in sorted(prog.module_locks.items()):
        inventory.append(
            Finding(
                INFO, "inventory",
                f"lock {hostwalk.module_short(mod)}.{name} ({kind}) in "
                f"{_rel(prog.paths[mod])}",
            )
        )
    for label, key in walker.roots_walked:
        inventory.append(
            Finding(INFO, "inventory", f"thread root [{label}] {key}")
        )

    order: List[Finding] = [
        f for f in walker.lock_errors if f.check == "lock-order"
    ]
    for (a, b), (path, lineno) in sorted(walker.edges.items()):
        order.append(
            Finding(
                INFO, "lock-order", f"edge {a} -> {b} ({path}:{lineno})"
            )
        )
    cycle = find_cycle(walker.edges)
    if cycle is not None:
        order.append(
            Finding(
                ERROR, "lock-order",
                "lock-order cycle (potential deadlock): "
                + " -> ".join(cycle),
            )
        )

    guarded: List[Finding] = []
    used_waivers: Set[str] = set()
    by_field: Dict[Tuple[str, str], List[Access]] = {}
    for acc in walker.accesses:
        by_field.setdefault((acc.cls, acc.attr), []).append(acc)
    for (cls, attr), accs in sorted(by_field.items()):
        labels = {a.label for a in accs}
        mutated = any(a.is_write for a in accs)
        if len(labels) < 2 or not mutated:
            continue
        owner = cfg.guarded[cls]
        key = f"{cls}.{attr}"
        for acc in sorted(accs, key=lambda a: (a.path, a.lineno)):
            if owner is not None and owner in acc.held:
                continue
            verb = "written" if acc.is_write else "read"
            need = owner if owner is not None else "any lock (none exists)"
            if key in waivers:
                used_waivers.add(key)
                guarded.append(
                    Finding(
                        INFO, "guarded-fields",
                        f"waived: {key} {verb} without {need} from "
                        f"thread {acc.label!r} at {acc.path}:"
                        f"{acc.lineno} — {waivers[key]}",
                    )
                )
            else:
                guarded.append(
                    Finding(
                        ERROR, "guarded-fields",
                        f"{key} {verb} without {need} from thread "
                        f"{acc.label!r} at {acc.path}:{acc.lineno} "
                        f"(held: {sorted(acc.held) or '{}'}; field is "
                        f"shared by threads {sorted(labels)})",
                    )
                )

    report = EngineReport(
        config_name=cfg.name,
        checks=[
            CheckResult.from_findings("inventory", inventory),
            CheckResult.from_findings("lock-order", order),
            CheckResult.from_findings("guarded-fields", guarded),
        ],
    )
    return report, used_waivers


# -- teeth -------------------------------------------------------------------
def _fixture_cell(name: str) -> Optional[LockCellConfig]:
    path = os.path.join(FIXTURE_DIR, name)
    if not os.path.exists(path):
        return None
    return LockCellConfig(
        name=f"fixture/{name}",
        modules=[(name[:-3], path)],
        roots=[],
        guarded={},
    )


def run_lock_teeth() -> EngineReport:
    """Analyze the committed broken fixtures; they MUST fail."""
    checks: List[CheckResult] = []

    inv = _fixture_cell("broken_lock_inversion.py")
    if inv is None:
        checks.append(
            CheckResult.skipped(
                "teeth-inversion", "fixture dir not present"
            )
        )
    else:
        rep, _ = analyze_cell(inv, {})
        errs = [
            f
            for c in rep.checks
            if c.check == "lock-order"
            for f in c.findings
            if f.severity == ERROR and "cycle" in f.message
        ]
        if errs:
            checks.append(
                CheckResult.from_findings(
                    "teeth-inversion",
                    [
                        Finding(
                            INFO, "teeth-inversion",
                            f"fixture correctly flagged: {errs[0].message}",
                        )
                    ],
                )
            )
        else:
            checks.append(
                CheckResult.from_findings(
                    "teeth-inversion",
                    [
                        Finding(
                            ERROR, "teeth-inversion",
                            "broken_lock_inversion.py produced NO "
                            "lock-order cycle — the deadlock detector "
                            "lost its witness",
                        )
                    ],
                )
            )

    ug = _fixture_cell("broken_unguarded_write.py")
    if ug is None:
        checks.append(
            CheckResult.skipped(
                "teeth-unguarded", "fixture dir not present"
            )
        )
    else:
        ug.guarded = {"Worker": "Worker._lock"}
        rep, _ = analyze_cell(ug, {})
        errs = [
            f
            for c in rep.checks
            if c.check == "guarded-fields"
            for f in c.findings
            if f.severity == ERROR
        ]
        if errs:
            checks.append(
                CheckResult.from_findings(
                    "teeth-unguarded",
                    [
                        Finding(
                            INFO, "teeth-unguarded",
                            f"fixture correctly flagged: {errs[0].message}",
                        )
                    ],
                )
            )
        else:
            checks.append(
                CheckResult.from_findings(
                    "teeth-unguarded",
                    [
                        Finding(
                            ERROR, "teeth-unguarded",
                            "broken_unguarded_write.py produced NO "
                            "guarded-field violation — the discipline "
                            "check lost its witness",
                        )
                    ],
                )
            )
    return EngineReport(config_name="lock/teeth", checks=checks)


# -- entry point -------------------------------------------------------------
def run_lock_checks(
    matrix: Optional[List[LockCellConfig]] = None,
    waiver_path: Optional[str] = None,
) -> List[EngineReport]:
    try:
        waivers = load_waivers("lockcheck", waiver_path)
        waiver_err = None
    except ValueError as e:
        waivers, waiver_err = {}, str(e)
    reports: List[EngineReport] = []
    used: Set[str] = set()
    for cfg in matrix if matrix is not None else default_lock_matrix():
        rep, used_keys = analyze_cell(cfg, waivers)
        used |= used_keys
        reports.append(rep)
    reports.append(run_lock_teeth())

    wfindings: List[Finding] = []
    if waiver_err is not None:
        wfindings.append(Finding(ERROR, "waivers", waiver_err))
    for key, why in sorted(waivers.items()):
        if key in used:
            wfindings.append(
                Finding(INFO, "waivers", f"in use: {key} — {why}")
            )
        else:
            wfindings.append(
                Finding(
                    ERROR, "waivers",
                    f"stale waiver {key!r}: no current finding matches "
                    f"it — remove the entry or restore the pattern it "
                    f"documents",
                )
            )
    reports.append(
        EngineReport(
            config_name="lock/waivers",
            checks=[CheckResult.from_findings("waivers", wfindings)],
        )
    )
    return reports
