"""Checks over the device-side resharding collective (docs/RESILIENCE.md).

``reshardcheck`` proves the *planner* (host geometry, exactly-once move
tables); this matrix proves the *executor* —
:mod:`gol_tpu.parallel.redistribute` — which compiles those tables into
ppermute phases and per-device ``lax.switch`` branch programs.  Three
checks per (src mesh → dst mesh) pair, run on the verifier's virtual
CPU device ring:

- **schedule soundness** — the coverage canvas painted from the
  *compiled branch tables* (:func:`redistribute.schedule_coverage`, not
  the plan) is all-ones: every destination cell is written by exactly
  one (phase, move) of the static exchange program.  A bug in the phase
  assignment or union-position bookkeeping fails here even though
  ``validate_plan`` already blessed the geometry.
- **executed equivalence** — :func:`redistribute.device_reshard` moves
  a random board (seams cutting words mid-bit included) and the landed
  cells are bit-equal to the host-side truth, under the destination
  mesh's canonical sharding; the worlds variant
  (:func:`redistribute.device_reshard_worlds`) is held to the same bar
  over a ``[B, H, W]`` stack.
- **teeth** — deliberately broken plans (an overlapping move, a gapped
  move) handed to ``device_reshard`` explicitly MUST be rejected before
  any device program is built.  A broken fixture that executes means
  the exactly-once property reaches the collective unwitnessed.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import numpy as np

from gol_tpu.analysis.report import (
    ERROR,
    INFO,
    CheckResult,
    EngineReport,
    Finding,
)

# Same seam discipline as reshardcheck: 96 columns = 3 words, so the
# 2-way column split lands mid-word while 1-D splits stay row-only.
SHAPE = (48, 96)
WORLD_HW = (16, 64)  # per-world board of the [B, H, W] stack check
BATCH = 4


@dataclasses.dataclass(frozen=True)
class RedistConfig:
    """One src→dst cell of the device-reshard matrix."""

    name: str
    src: Optional[str]  # mesh spec: None / "1d2" / "1d4" / "2d2x2"
    dst: Optional[str]


def default_redist_matrix() -> List[RedistConfig]:
    """Grow and shrink pairs within the verifier's 4-device ring."""
    pairs: List[Tuple[Optional[str], Optional[str]]] = [
        (None, "1d4"),
        ("1d4", None),       # shrink to one device
        ("1d2", "1d4"),      # grow the ring
        ("1d4", "1d2"),      # shrink the ring
        (None, "2d2x2"),     # blocks, mid-word column seam at 48
        ("2d2x2", None),
        ("1d2", "2d2x2"),    # ring -> blocks
        ("2d2x2", "1d4"),    # blocks -> ring
    ]
    return [
        RedistConfig(
            name=f"redist-{s or 'none'}-to-{d or 'none'}", src=s, dst=d
        )
        for s, d in pairs
    ]


def _mesh(spec: Optional[str]):
    import jax

    from gol_tpu.parallel import mesh as mesh_mod

    if spec is None:
        return None
    if spec.startswith("1d"):
        return mesh_mod.make_mesh_1d(int(spec[2:]))
    rows, cols = int(spec[2]), int(spec[4])
    return mesh_mod.make_mesh_2d(
        (rows, cols), devices=jax.devices()[: rows * cols]
    )


def _check_schedule(cfg: RedistConfig) -> CheckResult:
    """The compiled branch tables cover every cell exactly once."""
    from gol_tpu.parallel import redistribute as rd
    from gol_tpu.resilience import reshard as rs

    findings: List[Finding] = []
    src_mesh, dst_mesh = _mesh(cfg.src), _mesh(cfg.dst)
    src = rs.MeshLayout.from_mesh(src_mesh)
    dst = rs.MeshLayout.from_mesh(dst_mesh)
    plan = rs.plan_reshard(SHAPE, src.boxes(SHAPE), src, dst)
    try:
        sched = rd.board_schedule(plan, src_mesh, dst_mesh)
        canvas = rd.schedule_coverage(sched)
    except rs.ReshardError as e:
        findings.append(
            Finding(ERROR, "redist-schedule", f"schedule build failed: {e}")
        )
        return CheckResult.from_findings("redist-schedule", findings)
    if not (canvas == 1).all():
        over = int((canvas > 1).sum())
        under = int((canvas == 0).sum())
        findings.append(
            Finding(
                ERROR,
                "redist-schedule",
                f"branch tables are not exactly-once: {over} cells "
                f"written more than once, {under} never",
            )
        )
    findings.append(
        Finding(
            INFO,
            "redist-schedule",
            f"{len(sched.shifts)} ppermute phases over a "
            f"{sched.n}-device union",
        )
    )
    return CheckResult.from_findings("redist-schedule", findings)


def _check_executed(cfg: RedistConfig) -> CheckResult:
    """device_reshard lands the same bits the host path would."""
    import jax

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import redistribute as rd

    findings: List[Finding] = []
    src_mesh, dst_mesh = _mesh(cfg.src), _mesh(cfg.dst)
    rng = np.random.default_rng(hash(cfg.name) % (2**32))
    board = (rng.random(SHAPE) < 0.5).astype(np.uint8)
    placed = (
        mesh_mod.shard_board(jax.numpy.asarray(board), src_mesh)
        if src_mesh is not None
        else jax.device_put(jax.numpy.asarray(board))
    )
    out = rd.device_reshard(placed, src_mesh, dst_mesh)
    if not np.array_equal(np.asarray(out), board):
        findings.append(
            Finding(
                ERROR,
                "redist-exec",
                "device reshard changed the board — the collective is "
                "not bit-exact against the host truth",
            )
        )
    if dst_mesh is not None:
        want = mesh_mod.board_sharding(dst_mesh)
        if not out.sharding.is_equivalent_to(want, out.ndim):
            findings.append(
                Finding(
                    ERROR,
                    "redist-exec",
                    "landed board is not under the destination mesh's "
                    "canonical sharding",
                )
            )
    if not findings:
        findings.append(
            Finding(INFO, "redist-exec", "bit-equal under dst sharding")
        )
    return CheckResult.from_findings("redist-exec", findings)


def _check_teeth(cfg: RedistConfig) -> CheckResult:
    """Broken plans must be rejected before any program is built."""
    import jax

    from gol_tpu.parallel import mesh as mesh_mod
    from gol_tpu.parallel import redistribute as rd
    from gol_tpu.resilience import reshard as rs

    findings: List[Finding] = []
    src_mesh, dst_mesh = _mesh(cfg.src), _mesh(cfg.dst)
    src = rs.MeshLayout.from_mesh(src_mesh)
    dst = rs.MeshLayout.from_mesh(dst_mesh)
    plan = rs.plan_reshard(SHAPE, src.boxes(SHAPE), src, dst)
    if not plan.moves or not plan.moves[-1][1]:
        return CheckResult.skipped("redist-teeth", "plan has no moves")
    dbox, srcs = plan.moves[-1]
    broken = [
        (
            "overlapping move",
            dataclasses.replace(
                plan, moves=plan.moves[:-1] + ((dbox, srcs + (srcs[0],)),)
            ),
        ),
        (
            "gapped move",
            dataclasses.replace(
                plan, moves=plan.moves[:-1] + ((dbox, srcs[:-1]),)
            ),
        ),
    ]
    board = np.zeros(SHAPE, np.uint8)
    placed = (
        mesh_mod.shard_board(jax.numpy.asarray(board), src_mesh)
        if src_mesh is not None
        else jax.device_put(jax.numpy.asarray(board))
    )
    for label, bad in broken:
        try:
            rd.device_reshard(placed, src_mesh, dst_mesh, plan=bad)
        except (rs.ReshardError, rs.ReshardPlanError) as e:
            findings.append(
                Finding(INFO, "redist-teeth", f"{label} rejected: {e}")
            )
        else:
            findings.append(
                Finding(
                    ERROR,
                    "redist-teeth",
                    f"broken fixture ({label}) EXECUTED — the device "
                    "collective accepts unvalidated move tables",
                )
            )
    return CheckResult.from_findings("redist-teeth", findings)


def _check_worlds() -> CheckResult:
    """The [B, H, W] stack variant is bit-exact across mesh sizes."""
    import jax

    from gol_tpu.batch import engines as batch_engines
    from gol_tpu.parallel import redistribute as rd

    findings: List[Finding] = []
    rng = np.random.default_rng(7)
    h, w = WORLD_HW
    stack = (rng.random((BATCH, h, w)) < 0.5).astype(np.uint8)
    meshes = {
        1: None,
        2: batch_engines.make_batch_mesh(2),
        4: batch_engines.make_batch_mesh(4),
    }
    for n_src, n_dst in [(1, 4), (4, 1), (2, 4), (4, 2)]:
        src_mesh, dst_mesh = meshes[n_src], meshes[n_dst]
        placed = (
            jax.device_put(
                jax.numpy.asarray(stack),
                batch_engines.batch_sharding(src_mesh),
            )
            if src_mesh is not None
            else jax.device_put(jax.numpy.asarray(stack))
        )
        out = rd.device_reshard_worlds(placed, src_mesh, dst_mesh)
        if not np.array_equal(np.asarray(out), stack):
            findings.append(
                Finding(
                    ERROR,
                    "redist-worlds",
                    f"worlds reshard {n_src}->{n_dst} devices is not "
                    "bit-exact",
                )
            )
    if not findings:
        findings.append(
            Finding(
                INFO, "redist-worlds",
                "stack bit-equal across 1/2/4-device worlds meshes",
            )
        )
    return CheckResult.from_findings("redist-worlds", findings)


def run_redist_checks() -> List[EngineReport]:
    """One :class:`EngineReport` per src→dst pair, plus the worlds cell."""
    reports = []
    for cfg in default_redist_matrix():
        rep = EngineReport(config_name=cfg.name)
        rep.checks.append(_check_schedule(cfg))
        rep.checks.append(_check_executed(cfg))
        rep.checks.append(_check_teeth(cfg))
        reports.append(rep)
    worlds = EngineReport(config_name="redist-worlds-stack")
    worlds.checks.append(_check_worlds())
    reports.append(worlds)
    return reports
