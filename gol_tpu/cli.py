"""Command-line driver preserving the reference's exact 5-argument surface.

The reference driver (``main``, gol-main.c:30-146) takes
``./gol <pattern> <worldSize> <iterations> <threadsPerBlock> <on_off>``
(parse at gol-main.c:43-53), runs the halo-exchange generation loop, prints
rank 0's timing line (gol-main.c:124-125) and a closing banner
(gol-main.c:132), and — when ``on_off == 1`` — dumps each rank's final block
to ``Rank_<r>_of_<n>.txt`` (gol-main.c:64-73,135-139).

This TPU driver keeps that surface verbatim and adds optional flags *after*
the five positionals:

- ``--ranks N``: logical rank count (the reference gets this from
  ``mpirun -np N``; here the world is ``N`` stacked ``S×S`` blocks evolved
  on however many TPU devices exist — logical decomposition is decoupled
  from physical chips).
- ``--halo {fresh,stale_t0}``: correct torus semantics (default) or the
  reference's as-implemented stale-halo semantics (bug B1) for bit-exact
  output parity.
- ``--engine {auto,dense,bitpack,pallas,pallas_bitpack,activity,ooc}``:
  stencil implementation tier (pallas_bitpack: fused carry-save kernel,
  fastest in-core; ooc: host-resident board streamed through a fixed
  device footprint — docs/STREAMING.md).
- ``--outdir DIR``, ``--profile DIR``, ``--compat-banner``,
  ``--checkpoint-every K`` / ``--resume PATH`` (capability additions).

One subcommand rides in front of the reference surface: ``python -m
gol_tpu verify`` runs the static invariant verifier
(:mod:`gol_tpu.analysis`) over the engine×mesh matrix and exits non-zero
on any violation — see ``docs/ANALYSIS.md``.

``threadsPerBlock`` configured the CUDA launch (gol-main.c:52,
gol-with-cuda.cu:272-275); XLA owns tiling here, so the value is validated
(fixing bug B5's silent 0-block no-op) and forwarded as the Pallas tile-size
hint where applicable.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Optional, Sequence

import numpy as np

USAGE = (
    "GOL requires 5 arguments: pattern number, sq size of the world and the "
    "number of itterations, threads per block and output-on-off e.g. "
    "./gol 0 32 2 512 0 \n"
)

_ATOI_RE = re.compile(r"\s*([+-]?\d+)")


def atoi(text: str) -> int:
    """C ``atoi`` semantics (gol-main.c:49-53): leading integer, else 0."""
    m = _ATOI_RE.match(text)
    return int(m.group(1)) if m else 0


def parse_args(argv: Sequence[str]) -> Optional[argparse.Namespace]:
    """Parse the 5 reference positionals + extension flags.

    Returns None (after printing usage) when the positional count is wrong —
    the caller exits with the reference's status (-1 → 255).
    """
    ext = argparse.ArgumentParser(prog="gol", add_help=True)
    ext.add_argument("positionals", nargs="*", metavar="ARG")
    ext.add_argument("--ranks", type=int, default=1)
    ext.add_argument("--halo", choices=["fresh", "stale_t0"], default="fresh")
    ext.add_argument(
        "--engine",
        choices=[
            "auto", "dense", "bitpack", "pallas", "pallas_bitpack",
            "activity", "ooc",
        ],
        default="auto",
    )
    # Activity-gated tier knobs (docs/SPARSE.md): mask tile edge (0 =
    # auto-pick) and worklist capacity as a fraction of the per-shard
    # tile count (overflow generations fall back to one dense step).
    ext.add_argument("--activity-tile", type=int, default=0, metavar="T")
    ext.add_argument(
        "--activity-capacity", type=float, default=0.25, metavar="FRAC"
    )
    # Out-of-core streaming tier knobs (docs/STREAMING.md): device
    # footprint budget the band planner inverts (MiB; the board itself
    # lives in host RAM), an explicit band height override (rows; 0 =
    # derive from the budget), and the dead-band H2D/D2H skip switch.
    ext.add_argument("--ooc-budget-mb", type=int, default=256, metavar="MB")
    ext.add_argument("--ooc-band-rows", type=int, default=0, metavar="R")
    ext.add_argument(
        "--no-ooc-skip-dead", dest="ooc_skip_dead", action="store_false"
    )
    ext.add_argument("--mesh", choices=["none", "1d", "2d"], default="none")
    # Shard-mode matrix (gol_tpu/parallel/modes.py): hand-placed
    # ppermutes / depth-k comm-compute overlap / XLA auto-SPMD /
    # cross-chunk double-buffered pipeline (chunk N+1's ghost band ships
    # while chunk N's interior computes — docs/DESIGN.md).
    ext.add_argument(
        "--shard-mode",
        choices=["explicit", "overlap", "auto", "pipeline"],
        default="explicit",
    )
    ext.add_argument("--halo-depth", type=int, default=1, metavar="K")
    # Capability addition: any totalistic rule, e.g. --rule B36/S23
    # (HighLife). B3/S23 (the reference's hard-wired rule) is the default.
    ext.add_argument("--rule", default=None, metavar="B<d>/S<d>")
    ext.add_argument("--outdir", default=".")
    ext.add_argument("--profile", default=None, metavar="TRACE_DIR")
    # Structured JSONL telemetry (docs/OBSERVABILITY.md): per-process
    # event stream in DIR, summarized/diffed by `python -m
    # gol_tpu.telemetry`.  Multi-host jobs should pass an explicit
    # --run-id so every rank's file shares one prefix.
    ext.add_argument("--telemetry", default=None, metavar="DIR")
    ext.add_argument("--run-id", default=None, metavar="NAME")
    # Live metrics endpoint (docs/OBSERVABILITY.md): rank 0 serves
    # Prometheus text on 127.0.0.1:<P>/metrics (0 = ephemeral port,
    # printed at startup), fed by the same in-process event stream as
    # the JSONL files.  Requires --telemetry.
    ext.add_argument("--metrics-port", type=int, default=None, metavar="P")
    # Batched multi-world mode (gol_tpu/batch, docs/BATCHING.md): evolve
    # B independent worlds in one compiled program per size bucket,
    # amortizing the per-invocation launch overhead B-fold.  --batch-sizes
    # gives per-world square sizes (comma list, cycled over the B worlds;
    # default: every world uses the positional worldSize).  Mixed sizes
    # are padded+masked into buckets — one program per bucket, not per
    # shape.  --mesh 1d shards the world axis across devices.
    ext.add_argument("--batch", type=int, default=0, metavar="B")
    ext.add_argument("--batch-sizes", default=None, metavar="S1,S2,...")
    # XLA persistent compilation cache: repeat invocations load compiled
    # programs from DIR instead of re-running XLA (docs/BATCHING.md).
    # Applies to every mode, not just --batch.
    ext.add_argument("--compile-cache", default=None, metavar="DIR")
    # In-graph simulation statistics: each chunk additionally returns
    # fused device reductions (population, births/deaths, changed,
    # boundary-band populations — global via psum on sharded runs),
    # emitted as schema-v2 `stats` events.  Requires --telemetry (the
    # events are the output) and excludes --guard-every (the guard's
    # audit already reports population, and its rollback replay needs
    # the donated buffers stats mode must keep alive).
    ext.add_argument("--stats", action="store_true")
    ext.add_argument("--compat-banner", action="store_true")
    ext.add_argument("--checkpoint-every", type=int, default=0, metavar="K")
    ext.add_argument("--checkpoint-dir", default=None)
    ext.add_argument("--resume", default=None, metavar="CKPT")
    # Process-tier resilience (docs/RESILIENCE.md): --auto-resume starts
    # from the newest snapshot in the checkpoint dir that fully
    # fingerprint-verifies, falling back past corrupt/torn candidates
    # (multi-host ranks agree on min(newest valid)); `iterations` then
    # means the run's TOTAL generation target, so a preempted job
    # relaunched with identical argv completes exactly the remaining
    # work.  --keep-snapshots K retains only the newest K valid
    # snapshots after each save (0 keeps all).  SIGTERM/SIGINT stop the
    # run at the next chunk boundary with a final checkpoint and exit
    # code 75 (EX_TEMPFAIL: preempted, resumable).
    ext.add_argument("--auto-resume", action="store_true")
    ext.add_argument("--keep-snapshots", type=int, default=3, metavar="K")
    # Elastic meshes (docs/RESILIENCE.md): --allow-shrink lets a run
    # whose board cannot tile every visible device proceed on the
    # largest device count it divides (the degraded-pod relaunch path;
    # supervised children get it via GOL_ALLOW_SHRINK=1).
    # --sharded-snapshots writes the piece-table checkpoint directory
    # format even single-process.  --reshard-at GEN stops at the first
    # chunk boundary reaching GEN, snapshots, and continues the
    # remaining generations on --reshard-mesh — the in-flight reshard
    # drill knob (resume-on-a-new-mesh without leaving the process).
    ext.add_argument("--allow-shrink", action="store_true")
    ext.add_argument("--sharded-snapshots", action="store_true")
    ext.add_argument("--reshard-at", type=int, default=0, metavar="GEN")
    ext.add_argument(
        "--reshard-mesh", choices=["none", "1d", "2d"], default=None
    )
    # Multi-host (the `mpirun -np N` analog): connect this process to the
    # job before any device work; the mesh then spans the whole pod.
    from gol_tpu.parallel.multihost import add_multihost_args

    add_multihost_args(ext)
    # Failure detection + elastic recovery: audit the board every K
    # generations, roll back and replay on corruption (utils/guard.py).
    ext.add_argument("--guard-every", type=int, default=0, metavar="K")
    ext.add_argument("--guard-max-restores", type=int, default=3, metavar="N")
    # Cross-engine redundancy audit: recompute each audited chunk on a
    # second bit-exact engine and require matching fingerprints (catches
    # in-range flips the 0/1 invariant cannot see; ~2x audited compute).
    ext.add_argument("--guard-redundant", action="store_true")
    # Sampling for the redundancy audit: recompute every Nth audited
    # chunk (see utils/guard.py GuardConfig.redundant_every for the
    # coverage trade-off).
    ext.add_argument(
        "--guard-redundant-every", type=int, default=1, metavar="N"
    )
    # Declarative fault injection (docs/RESILIENCE.md "The fault
    # plane"): PATH to a JSON FaultPlan, or inline JSON.  The
    # GOL_FAULT_PLAN env var is the equivalent (supervised children
    # inherit it); legacy GOL_CKPT_TEST_WRITE_DELAY keeps working as a
    # documented alias for a checkpoint.rename_delay entry.
    ext.add_argument("--fault-plan", default=None, metavar="PLAN")
    ns = ext.parse_args(list(argv))
    if len(ns.positionals) != 5:
        sys.stdout.write(USAGE)
        return None
    ns.pattern = atoi(ns.positionals[0])
    ns.world_size = atoi(ns.positionals[1])
    ns.iterations = atoi(ns.positionals[2])
    ns.threads = atoi(ns.positionals[3])
    ns.on_off = atoi(ns.positionals[4])
    return ns


def _run_batch(
    ns, sizes, resume, resume_info, iterations, restart_attempt
) -> int:
    """The ``--batch`` driver: B independent worlds, one launch per bucket.

    Worlds are the CLI pattern at the ``--batch-sizes`` geometries
    (cycled over the B worlds; default: every world at the positional
    ``worldSize``).  Reuses the reference surface end to end — the
    TOTAL DURATION line counts every world's cell updates, ``on_off=1``
    dumps each world's rank files under ``outdir/world_<i>/``, and the
    resilience exit codes (75 = preempted, resumable) are unchanged.
    """
    from gol_tpu import resilience
    from gol_tpu.batch import GolBatchRuntime, make_batch_mesh
    from gol_tpu.models import patterns

    worlds = [
        patterns.init_global(ns.pattern, sizes[i % len(sizes)], ns.ranks)
        for i in range(ns.batch)
    ]
    try:
        brt = GolBatchRuntime(
            worlds=worlds,
            engine=ns.engine,
            mesh=make_batch_mesh() if ns.mesh == "1d" else None,
            tile_hint=ns.threads,
            checkpoint_every=ns.checkpoint_every,
            checkpoint_dir=ns.checkpoint_dir,
            keep_snapshots=ns.keep_snapshots,
            telemetry_dir=ns.telemetry,
            run_id=ns.run_id,
            compile_cache=ns.compile_cache,
            restart_attempt=restart_attempt,
            resume_info=resume_info,
            metrics_port=ns.metrics_port,
            guard_every=ns.guard_every,
            guard_max_restores=ns.guard_max_restores,
            guard_redundant=ns.guard_redundant,
            guard_redundant_every=ns.guard_redundant_every,
        )
        with resilience.preemption_guard():
            report, boards = brt.run(iterations, resume=resume)
    except resilience.Preempted as e:
        print(e)
        return resilience.EX_TEMPFAIL
    except (ValueError, OSError) as e:
        print(e)
        return 255

    print(report.duration_line())
    print(
        f"BATCH          : {ns.batch} worlds in {len(brt.buckets)} "
        f"bucket(s), {report.updates_per_sec / max(ns.batch, 1):.4g} "
        "cell-updates/sec per world"
    )
    if brt.last_guard is not None:
        print(brt.last_guard.summary_line())
    accelerator = "GPU" if ns.compat_banner else "TPU"
    print(
        f"This is the Game of Life running in parallel on a {accelerator} "
        "on multiple ranks."
    )
    if ns.on_off == 1:
        from gol_tpu.utils import io as gol_io

        for i, b in enumerate(boards):
            wdir = os.path.join(ns.outdir, f"world_{i:04d}")
            os.makedirs(wdir, exist_ok=True)
            gol_io.write_world_dumps(np.asarray(b), ns.ranks, wdir)
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "verify":
        # Static verification pass (gol_tpu.analysis): prove engine
        # invariants from traced programs before anything runs on a pod.
        from gol_tpu.analysis.__main__ import main as verify_main

        return verify_main(argv[1:])
    ns = parse_args(argv)
    if ns is None:
        return 255  # exit(-1) in the reference (gol-main.c:46)

    from gol_tpu.resilience import faults as faults_mod

    try:
        if ns.fault_plan:
            faults_mod.install(faults_mod.FaultPlan.load(ns.fault_plan))
        else:
            faults_mod.install_from_env()
    except faults_mod.FaultPlanError as e:
        print(e)
        return 255

    from gol_tpu.models import patterns
    from gol_tpu.models.state import Geometry
    from gol_tpu.parallel import multihost
    from gol_tpu.runtime import GolRuntime, build_mesh

    try:
        topo = multihost.init_multihost(
            coordinator_address=ns.coordinator,
            num_processes=ns.num_processes,
            process_id=ns.process_id,
        )
    except (ValueError, RuntimeError) as e:
        print(e)
        return 255

    if topo.process_count > 1 and ns.mesh == "none":
        # Without a pod-spanning mesh every process would evolve its own
        # private single-device world and race to write the same dump and
        # checkpoint files.
        print(
            f"multi-host run ({topo.process_count} processes) requires a "
            "device mesh; pass --mesh 1d or --mesh 2d"
        )
        return 255

    if ns.on_off == 1 and not ns.batch:
        # Reference lifecycle (gol-main.c:64-73): every rank's dump file is
        # fopen'd "w" right after MPI_Init, BEFORE world init/validation —
        # files exist (truncated) from startup even if the run later dies,
        # and open failure prints the exact "ERROR IN RANK %d" diagnostic.
        from gol_tpu.utils import io as gol_io

        try:
            if topo.process_count > 1:
                try:
                    multihost.precreate_host_dump_files(
                        build_mesh(ns.mesh),
                        (ns.world_size * ns.ranks, ns.world_size),
                        ns.ranks,
                        ns.outdir,
                    )
                except ValueError:
                    pass  # invalid geometry/mesh: validation below reports it
            else:
                gol_io.create_rank_files(
                    range(max(ns.ranks, 0)), ns.ranks, ns.outdir
                )
        except gol_io.RankFileError as e:
            sys.stdout.write(f"ERROR IN RANK {e.rank}")
            return 255  # exit(-1) in the reference (gol-main.c:70)

    try:
        geom = Geometry(size=ns.world_size, num_ranks=ns.ranks)
        patterns.validate_pattern_size(ns.pattern, ns.world_size)
        if ns.threads <= 0:
            raise ValueError(
                f"threads per block must be positive, got {ns.threads} "
                "(the reference silently launched zero blocks here — bug B5)"
            )
        if ns.iterations < 0:
            raise ValueError(f"iterations must be >= 0, got {ns.iterations}")
        if ns.guard_redundant and ns.guard_every <= 0:
            raise ValueError(
                "--guard-redundant audits chunks, so it requires "
                "--guard-every K > 0"
            )
        if ns.guard_redundant_every != 1 and not ns.guard_redundant:
            raise ValueError(
                "--guard-redundant-every samples the redundancy audit, "
                "so it requires --guard-redundant"
            )
        if ns.guard_every < 0:
            raise ValueError(
                f"--guard-every must be >= 0, got {ns.guard_every} "
                "(0 disables the guard)"
            )
        if ns.stats and not ns.telemetry:
            raise ValueError(
                "--stats emits schema-v2 stats events, so it requires "
                "--telemetry DIR"
            )
        if ns.metrics_port is not None and not ns.telemetry:
            raise ValueError(
                "--metrics-port serves the in-process event stream, so "
                "it requires --telemetry DIR"
            )
        if ns.metrics_port is not None and not (
            0 <= ns.metrics_port <= 65535
        ):
            raise ValueError(
                f"--metrics-port must be 0..65535 (0 = ephemeral), got "
                f"{ns.metrics_port}"
            )
        if ns.stats and ns.guard_every > 0:
            raise ValueError(
                "--stats applies to unguarded runs; drop --guard-every "
                "(the guard's audit already reports population per chunk)"
            )
        if (ns.activity_tile or ns.activity_capacity != 0.25) \
                and ns.engine != "activity":
            raise ValueError(
                "--activity-tile/--activity-capacity configure the "
                "activity tier; pass --engine activity"
            )
        if (
            ns.ooc_budget_mb != 256
            or ns.ooc_band_rows
            or not ns.ooc_skip_dead
        ) and ns.engine != "ooc":
            raise ValueError(
                "--ooc-budget-mb/--ooc-band-rows/--no-ooc-skip-dead "
                "configure the out-of-core streaming tier; pass "
                "--engine ooc"
            )
        if ns.engine == "ooc" and ns.guard_every > 0:
            raise ValueError(
                "the checkpoint-restore guard re-executes chunks through "
                "the compiled in-core evolvers; engine 'ooc' streams a "
                "host-resident board, so drop --guard-every (its band "
                "write-backs already run under the retry/containment "
                "plane), or guard an in-core engine ('dense', 'bitpack', "
                "'pallas_bitpack', 'activity')"
            )
        if ns.auto_resume and ns.resume:
            raise ValueError(
                "--auto-resume selects the snapshot itself; pass one of "
                "--resume/--auto-resume, not both"
            )
        if ns.keep_snapshots < 0:
            raise ValueError(
                f"--keep-snapshots must be >= 0, got {ns.keep_snapshots} "
                "(0 keeps every snapshot)"
            )
        if ns.reshard_at < 0:
            raise ValueError(
                f"--reshard-at must be >= 0, got {ns.reshard_at} "
                "(0 disables the in-flight reshard stop)"
            )
        if ns.reshard_at > 0:
            if ns.reshard_mesh is None:
                raise ValueError(
                    "--reshard-at stops to continue on a new topology; "
                    "pass --reshard-mesh {none,1d,2d}"
                )
            if topo.process_count > 1:
                raise ValueError(
                    "--reshard-at is single-process (a multi-host job "
                    "reshapes by relaunching under --auto-resume)"
                )
            if ns.guard_every > 0:
                raise ValueError(
                    "--reshard-at applies to unguarded runs; drop "
                    "--guard-every"
                )
            if ns.batch:
                raise ValueError(
                    "--reshard-at applies to single-world runs; drop "
                    "--batch"
                )
            if ns.halo != "fresh":
                raise ValueError(
                    "--reshard-at runs fresh halos only (stale_t0 worlds "
                    "are single-device by definition)"
                )
            # The stop writes through a snapshot; give it a home.
            ns.checkpoint_dir = ns.checkpoint_dir or "checkpoints"
        elif ns.reshard_mesh is not None:
            raise ValueError(
                "--reshard-mesh names the post-stop topology; pass "
                "--reshard-at GEN"
            )
        if ns.sharded_snapshots and ns.mesh == "none" and not ns.reshard_at:
            raise ValueError(
                "--sharded-snapshots writes the piece-table directory "
                "format, which shards over a mesh; pass --mesh 1d/2d"
            )
        if ns.batch < 0:
            raise ValueError(f"--batch must be >= 0, got {ns.batch}")
        if ns.batch_sizes and not ns.batch:
            raise ValueError(
                "--batch-sizes applies to batched runs; pass --batch B"
            )
        batch_sizes = None
        if ns.batch:
            # Batched multi-world mode: single-process, fresh-halo,
            # Conway-only (the batched tiers are the B3/S23 fast paths);
            # the guard/stats observers are single-world subsystems.
            if topo.process_count > 1:
                raise ValueError(
                    "--batch is single-process (its mesh spans local "
                    "devices); drop the multi-host flags"
                )
            if ns.halo != "fresh":
                raise ValueError("--batch runs fresh halos only")
            if ns.rule:
                raise ValueError(
                    "--batch runs the B3/S23 fast paths; --rule is a "
                    "single-world feature"
                )
            if ns.stats:
                raise ValueError(
                    "--stats is a single-world observer; drop it in "
                    "--batch mode (guarded batch runs report per-world "
                    "audit populations instead)"
                )
            if ns.profile:
                raise ValueError(
                    "--profile applies to single-world runs; drop --batch"
                )
            if ns.mesh == "2d":
                raise ValueError(
                    "--batch shards the world axis (a 1-D ring); use "
                    "--mesh 1d or --mesh none"
                )
            if ns.engine == "ooc":
                raise ValueError(
                    "--batch evolves many in-core worlds in one compiled "
                    "program; engine 'ooc' streams one bigger-than-device "
                    "world through the chip — run it unbatched, or pick a "
                    "batched engine ('auto', 'dense', 'bitpack', "
                    "'pallas_bitpack')"
                )
            if ns.engine in ("pallas", "activity"):
                raise ValueError(
                    f"engine {ns.engine!r} has no batched tier; "
                    "use 'auto'/'dense'/'bitpack'/'pallas_bitpack'"
                )
            sizes_text = ns.batch_sizes or str(ns.world_size)
            batch_sizes = [atoi(s) for s in sizes_text.split(",") if s]
            if not batch_sizes or any(s <= 0 for s in batch_sizes):
                raise ValueError(
                    f"--batch-sizes {sizes_text!r} must be a comma list "
                    "of positive world sizes (parses to no sizes)"
                )
            for s in batch_sizes:
                Geometry(size=s, num_ranks=ns.ranks)
                patterns.validate_pattern_size(ns.pattern, s)
    except ValueError as e:
        print(e)
        return 255

    if ns.compile_cache:
        # Persistent XLA compilation cache (docs/BATCHING.md): wire it
        # before any program compiles so every mode benefits.
        from gol_tpu.batch import cache as cache_mod

        cache_mod.enable_compile_cache(ns.compile_cache)

    from gol_tpu import resilience

    resume = ns.resume
    resume_info = None
    iterations = ns.iterations
    if ns.auto_resume:
        # The walk + (multi-host) min-generation agreement is collective:
        # every process calls it, every process gets the same answer.
        ns.checkpoint_dir = ns.checkpoint_dir or "checkpoints"
        try:
            resume, resume_info = resilience.resolve_auto_resume(
                ns.checkpoint_dir, kind="batch" if ns.batch else "2d"
            )
        except (ValueError, OSError) as e:
            print(e)
            return 255
        if resume is not None:
            # Under auto-resume `iterations` is the TOTAL target: a
            # relaunch with identical argv completes the remaining work.
            iterations = max(0, ns.iterations - resume_info["generation"])
            if topo.is_coordinator:
                print(
                    f"auto-resume: generation "
                    f"{resume_info['generation']} from {resume}"
                    + (
                        "  [fallback: skipped "
                        + ", ".join(resume_info["skipped"])
                        + "]"
                        if resume_info["fallback"] and resume_info["skipped"]
                        else "  [fallback]"
                        if resume_info["fallback"]
                        else ""
                    )
                )
        elif topo.is_coordinator:
            print(
                f"auto-resume: no valid snapshot in {ns.checkpoint_dir}; "
                "starting fresh"
            )

    try:
        restart_attempt = int(os.environ.get("GOL_RESTART_ATTEMPT", "0"))
    except ValueError:
        restart_attempt = 0

    if ns.batch:
        return _run_batch(
            ns, batch_sizes, resume, resume_info, iterations, restart_attempt
        )

    # Elastic shrink policy (docs/RESILIENCE.md): opt in via the flag or
    # the supervisor's environment export, so a supervised relaunch that
    # comes up with a device count the board cannot tile proceeds on a
    # smaller mesh instead of crashing its restart budget.
    allow_shrink = ns.allow_shrink or (
        os.environ.get("GOL_ALLOW_SHRINK") == "1"
    )
    board_shape = (ns.world_size * ns.ranks, ns.world_size)

    def make_runtime(mesh_kind, run_id, reshard_at, rt_resume_info):
        return GolRuntime(
            geometry=geom,
            engine=ns.engine,
            halo_mode=ns.halo,
            tile_hint=ns.threads,
            checkpoint_every=ns.checkpoint_every,
            checkpoint_dir=ns.checkpoint_dir,
            mesh=build_mesh(
                mesh_kind, shape=board_shape, allow_shrink=allow_shrink
            ),
            shard_mode=ns.shard_mode,
            halo_depth=ns.halo_depth,
            rule=ns.rule,
            telemetry_dir=ns.telemetry,
            run_id=run_id,
            stats=ns.stats,
            keep_snapshots=ns.keep_snapshots,
            restart_attempt=restart_attempt,
            resume_info=rt_resume_info,
            activity_tile=ns.activity_tile,
            activity_capacity=ns.activity_capacity,
            ooc_budget_mb=ns.ooc_budget_mb,
            ooc_band_rows=ns.ooc_band_rows,
            ooc_skip_dead=ns.ooc_skip_dead,
            metrics_port=ns.metrics_port,
            reshard_at=reshard_at,
            sharded_snapshots=ns.sharded_snapshots,
        )

    try:
        rt = make_runtime(ns.mesh, ns.run_id, ns.reshard_at, resume_info)
        guard_report = None
        with resilience.preemption_guard():
            if ns.guard_every > 0:
                from gol_tpu.utils import guard as guard_mod

                if ns.profile:
                    raise ValueError(
                        "--profile applies to unguarded runs; drop "
                        "--guard-every"
                    )
                report, final_state, guard_report = guard_mod.run_guarded(
                    rt,
                    pattern=ns.pattern,
                    iterations=iterations,
                    config=guard_mod.GuardConfig(
                        check_every=ns.guard_every,
                        max_restores=ns.guard_max_restores,
                        redundant=ns.guard_redundant,
                        redundant_every=ns.guard_redundant_every,
                    ),
                    resume=resume,
                )
            else:
                try:
                    report, final_state = rt.run(
                        pattern=ns.pattern,
                        iterations=iterations,
                        resume=resume,
                        profile_dir=ns.profile,
                    )
                except resilience.ReshardPoint as rp:
                    # In-flight reshard (--reshard-at): the run stopped
                    # at a chunk boundary through a snapshot; replan and
                    # finish the remaining generations on the new mesh
                    # in this same process.  The resumed runtime detects
                    # the topology change itself and stamps the v7
                    # reshard telemetry event.
                    if topo.is_coordinator:
                        print(
                            f"reshard: generation {rp.generation}, mesh "
                            f"{ns.mesh} -> {ns.reshard_mesh} "
                            f"({rp.remaining} generations remain)"
                        )
                    rt = make_runtime(
                        ns.reshard_mesh,
                        f"{ns.run_id}-reshard" if ns.run_id else None,
                        0,
                        None,
                    )
                    report, final_state = rt.run(
                        pattern=ns.pattern,
                        iterations=rp.remaining,
                        resume=rp.snapshot_path,
                        profile_dir=None,
                    )
    except resilience.Preempted as e:
        # NOT the error path: the run stopped cleanly at a chunk
        # boundary with a resumable snapshot.  EX_TEMPFAIL tells a
        # scheduler/supervisor "relaunch me" — this run relaunched with
        # --auto-resume completes the remaining generations bit-exactly.
        if topo.is_coordinator:
            print(e)
        return resilience.EX_TEMPFAIL
    except (ValueError, OSError) as e:
        # Same clean-error convention as the pre-validation path: bad
        # --resume paths/shapes, unavailable engines, unwritable dirs,
        # corrupt snapshots, exhausted guard restore budgets (both are
        # ValueError subclasses).
        print(e)
        from gol_tpu.utils.checkpoint import CorruptSnapshotError

        if isinstance(e, CorruptSnapshotError) and ns.resume:
            # Satellite fix: a corrupt --resume target is rarely the end
            # of the line — say where the walk would have landed.
            hint = resilience.corrupt_resume_hint(ns.resume, kind="2d")
            if hint:
                print(
                    f"hint: an earlier valid snapshot exists at {hint}; "
                    "resume from it, or rerun with --auto-resume to "
                    "select it (and fall back) automatically"
                )
        elif ns.resume and (
            "not divisible by mesh" in str(e)
            or "does not divide" in str(e)
            or "empty shards" in str(e)
        ):
            # Topology mismatch on a plain --resume: the board in the
            # snapshot cannot tile the requested mesh.  Resharding is
            # automatic on any mesh that CAN tile it — say so instead
            # of leaving the raw divisibility error as the last word.
            hint = resilience.topology_resume_hint(ns.resume, kind="2d")
            if hint:
                print(hint)
        return 255

    # Rank 0's report (gol-main.c:121-128) + closing banner (gol-main.c:132);
    # only the coordinator prints, exactly as only MPI rank 0 did.
    if topo.is_coordinator:
        print(report.duration_line())
        if guard_report is not None:
            print(guard_report.summary_line())
        accelerator = "GPU" if ns.compat_banner else "TPU"
        print(
            f"This is the Game of Life running in parallel on a {accelerator} "
            "on multiple ranks."
        )

    if ns.on_off == 1:
        if topo.process_count > 1:
            # Each host writes the rank files its shards cover (the MPI
            # every-rank-writes-its-own-block I/O pattern, gol-main.c:135-139).
            multihost.write_host_dumps(
                final_state.board, geom.num_ranks, ns.outdir
            )
        else:
            from gol_tpu.utils import io as gol_io

            gol_io.write_world_dumps(
                np.asarray(final_state.board), geom.num_ranks, ns.outdir
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
