"""Model layer: world state pytree and the seed-pattern "model zoo"."""

from gol_tpu.models.state import GolState
from gol_tpu.models import patterns

__all__ = ["GolState", "patterns"]
