"""Seed patterns 0-4: the reference's "model zoo".

The reference implements five init functions dispatched by an integer id
(``gol_initMaster`` switch, ``gol-with-cuda.cu:302-327``).  Each rank holds a
``size × size`` local block; the global world stacks ``num_ranks`` blocks
vertically.  We reproduce each pattern's *effective* cell placement exactly
(including the consequences of the reference's index-math bugs on square
worlds) while replacing its out-of-bounds UB with validation errors:

- pattern 0 ``gol_initAllZeros``       (gol-with-cuda.cu:56-69):  all dead.
- pattern 1 ``gol_initAllOnes``        (gol-with-cuda.cu:72-92):  all alive.
- pattern 2 ``gol_initOnesInMiddle``   (gol-with-cuda.cu:95-120): despite the
  name, every rank sets 10 live cells at flat indices
  ``(H-1)*H + 127 .. +136`` (bug B3 uses height where width belongs; on the
  CLI-enforced square worlds that lands on the *last local row*, columns
  127-136).  Bug B4: the reference heap-overflows when ``size < 137``; we
  raise a ValueError instead (see :func:`validate_pattern_size`).
- pattern 3 ``gol_initOnesAtCorners``  (gol-with-cuda.cu:123-147): rank 0 sets
  the two top corners of its block, the last rank sets its two bottom corners
  (index ``H*(W-1)`` is again square-only math) — i.e. the four corners of the
  global stacked world.
- pattern 4 ``gol_initSpinnerAtCorner`` (gol-with-cuda.cu:150-171): rank 0
  only, live cells at local (0,0), (0,1) and (0, W-1) — a horizontal blinker
  spanning the column wrap; a period-2 oscillator used as the de-facto
  correctness probe.

All constructors are NumPy-free of device work until the caller moves the
board to devices; per-shard constructors exist so a 65536² world never has to
materialize unsharded on one host.
"""

from __future__ import annotations

import numpy as np

from gol_tpu.models.state import Geometry

PATTERN_NAMES = {
    0: "all_zeros",
    1: "all_ones",
    2: "ones_in_middle",  # effective: last local row, cols 127-136 (B3/B4)
    3: "ones_at_corners",  # global corners
    4: "spinner_at_corner",  # wrap-spanning blinker on rank 0
    # Capability additions (the reference exits on ids > 4,
    # gol-with-cuda.cu:324-326): classic Life objects as long-horizon
    # correctness probes — a glider's torus transit and a gun's emission
    # rate catch subtle stencil/wrap bugs that short oscillators cannot.
    5: "glider",  # south-east glider on rank 0; period-4 (+1,+1) translation
    6: "r_pentomino",  # methuselah centered on the global world
    7: "gosper_gun",  # emits one glider every 30 generations
    # Sparse-scenario additions (the activity tier's workload class,
    # docs/SPARSE.md): a fast spaceship and a long-lived methuselah —
    # tiny live populations in arbitrarily large arenas, exactly the
    # boards where O(area) dense work is ~100% waste.
    8: "lwss",  # lightweight spaceship, period 4, speed c/2 eastward
    9: "acorn",  # 7-cell methuselah, stabilizes after ~5200 generations
}

#: (row, col) cells of the capability-addition objects, top-left anchored.
GLIDER_CELLS = ((0, 1), (1, 2), (2, 0), (2, 1), (2, 2))
R_PENTOMINO_CELLS = ((0, 1), (0, 2), (1, 0), (1, 1), (2, 1))
LWSS_CELLS = (
    (0, 0), (0, 3),
    (1, 4),
    (2, 0), (2, 4),
    (3, 1), (3, 2), (3, 3), (3, 4),
)
ACORN_CELLS = (
    (0, 1),
    (1, 3),
    (2, 0), (2, 1), (2, 4), (2, 5), (2, 6),
)
GOSPER_GUN_CELLS = (
    (0, 24),
    (1, 22), (1, 24),
    (2, 12), (2, 13), (2, 20), (2, 21), (2, 34), (2, 35),
    (3, 11), (3, 15), (3, 20), (3, 21), (3, 34), (3, 35),
    (4, 0), (4, 1), (4, 10), (4, 16), (4, 20), (4, 21),
    (5, 0), (5, 1), (5, 10), (5, 14), (5, 16), (5, 17), (5, 22), (5, 24),
    (6, 10), (6, 16), (6, 24),
    (7, 11), (7, 15),
    (8, 12), (8, 13),
)
#: Anchor offset for the object patterns; leaves a margin so the object's
#: first generations don't immediately interact with the wrap.
OBJECT_OFFSET = 1
GOSPER_GUN_MIN_SIZE = OBJECT_OFFSET + 36 + 2  # widest extent + tail margin

#: Pattern 2 writes flat indices (H-1)*H+127 .. +136 (gol-with-cuda.cu:108-114);
#: on a square world that is columns 127..136 of the last row, so any
#: worldSize < 137 overflowed the reference's heap (bug B4).
PATTERN2_COL0 = 127
PATTERN2_NCELLS = 10
PATTERN2_MIN_SIZE = PATTERN2_COL0 + PATTERN2_NCELLS  # 137


def validate_pattern(pattern: int) -> None:
    """Unknown pattern ids exit in the reference (gol-with-cuda.cu:324-326)."""
    if pattern not in PATTERN_NAMES:
        raise ValueError(f"Pattern {pattern} has not been implemented")


def validate_pattern_size(pattern: int, size: int) -> None:
    """Reject geometries that were undefined behavior in the reference (B4)."""
    validate_pattern(pattern)
    if pattern == 2 and size < PATTERN2_MIN_SIZE:
        raise ValueError(
            f"pattern 2 requires worldSize >= {PATTERN2_MIN_SIZE} (the reference "
            f"writes columns {PATTERN2_COL0}..{PATTERN2_COL0 + PATTERN2_NCELLS - 1} "
            f"of the last row and heap-overflows below that; got size={size})"
        )
    if pattern == 5 and size < OBJECT_OFFSET + 3 + 1:
        raise ValueError(
            f"pattern 5 needs worldSize >= {OBJECT_OFFSET + 4} for the "
            f"3×3 glider at its anchor plus margin; got size={size}"
        )
    if pattern == 6 and size < 4:
        # Centered, no anchor offset: a 4×4 world fits the 3×3 pentomino.
        raise ValueError(
            f"pattern 6 needs worldSize >= 4 for the centered 3×3 "
            f"R-pentomino; got size={size}"
        )
    if pattern == 7 and size < GOSPER_GUN_MIN_SIZE:
        raise ValueError(
            f"pattern 7 (Gosper gun) needs worldSize >= {GOSPER_GUN_MIN_SIZE}; "
            f"got size={size}"
        )
    if pattern in (8, 9):
        need = OBJECT_OFFSET + _object_extent(
            LWSS_CELLS if pattern == 8 else ACORN_CELLS
        )[1] + 1
        if size < need:
            raise ValueError(
                f"pattern {pattern} ({PATTERN_NAMES[pattern]}) needs "
                f"worldSize >= {need} for the object at its anchor plus "
                f"margin; got size={size}"
            )


def init_local(pattern: int, size: int, rank: int, num_ranks: int) -> np.ndarray:
    """One rank's ``size × size`` local block at t=0, as uint8.

    Mirrors the per-rank behavior of ``gol_initMaster`` → ``gol_init*``
    (gol-with-cuda.cu:286-328): patterns 0-2 are rank-oblivious, patterns 3-4
    condition on ``myRank``/``numRank``.
    """
    validate_pattern_size(pattern, size)
    if not (0 <= rank < num_ranks):
        raise ValueError(f"rank {rank} out of range for {num_ranks} ranks")

    board = np.zeros((size, size), dtype=np.uint8)
    if pattern == 0:
        pass
    elif pattern == 1:
        board[:] = 1
    elif pattern == 2:
        board[size - 1, PATTERN2_COL0 : PATTERN2_COL0 + PATTERN2_NCELLS] = 1
    elif pattern == 3:
        if rank == 0:
            board[0, 0] = 1
            board[0, size - 1] = 1
        # `else if` in the reference (gol-with-cuda.cu:139): with num_ranks == 1
        # rank 0 takes the first branch only, so the bottom corners stay dead.
        elif rank == num_ranks - 1:
            board[size - 1, 0] = 1
            board[size - 1, size - 1] = 1
    elif pattern == 4:
        if rank == 0:
            board[0, 0] = 1
            board[0, 1] = 1
            board[0, size - 1] = 1
    elif pattern == 5:
        if rank == 0:
            for r, c in GLIDER_CELLS:
                board[OBJECT_OFFSET + r, OBJECT_OFFSET + c] = 1
    elif pattern == 6:
        # Centered on the *global* world: only the rank(s) owning those
        # rows place cells (rank-aware like patterns 3/4).
        gh = size * num_ranks
        r0, c0 = gh // 2 - 1, size // 2 - 1
        for r, c in R_PENTOMINO_CELLS:
            gr = r0 + r
            if rank * size <= gr < (rank + 1) * size:
                board[gr - rank * size, c0 + c] = 1
    elif pattern == 7:
        if rank == 0:
            for r, c in GOSPER_GUN_CELLS:
                board[OBJECT_OFFSET + r, OBJECT_OFFSET + c] = 1
    elif pattern in (8, 9):
        if rank == 0:
            cells = LWSS_CELLS if pattern == 8 else ACORN_CELLS
            for r, c in cells:
                board[OBJECT_OFFSET + r, OBJECT_OFFSET + c] = 1
    return board


#: The named sparse-scenario objects (huge-arena seeds for the activity
#: tier, sparsebench and the seam-crossing tests).  Distinct from the
#: integer pattern ids: these place at *arbitrary* offsets in arbitrary
#: (possibly non-square) extents, torus-wrapped.
SPARSE_OBJECTS = {
    "glider": GLIDER_CELLS,
    "lwss": LWSS_CELLS,
    "r_pentomino": R_PENTOMINO_CELLS,
    "acorn": ACORN_CELLS,
    "gosper_gun": GOSPER_GUN_CELLS,
}


def _object_extent(cells) -> tuple:
    """(height, width) bounding box of a cell list."""
    return (
        max(r for r, _ in cells) + 1,
        max(c for _, c in cells) + 1,
    )


def place_cells(
    board: np.ndarray, cells, row: int, col: int
) -> np.ndarray:
    """Stamp ``cells`` onto ``board`` anchored at ``(row, col)``,
    wrapping both axes (the torus has no special origin — translation
    equivariance is a pinned property, so any offset is legal)."""
    h, w = board.shape
    for r, c in cells:
        board[(row + r) % h, (col + c) % w] = 1
    return board


def init_sparse_world(
    name: str,
    height: int,
    width: int,
    offset=(0, 0),
) -> np.ndarray:
    """A named object alone in an arbitrary extent at an arbitrary offset.

    The sparse scenario class: one :data:`SPARSE_OBJECTS` seed (a few
    live cells) in a ``height × width`` dead arena — gliders/guns/
    methuselahs at huge extents, where the activity tier's skipped
    fraction approaches 1.  Offsets may be negative or past the extent
    (torus wrap), so seeds can be placed straddling shard seams on
    purpose.
    """
    if name not in SPARSE_OBJECTS:
        raise ValueError(
            f"unknown sparse object {name!r}; expected one of "
            f"{sorted(SPARSE_OBJECTS)}"
        )
    cells = SPARSE_OBJECTS[name]
    oh, ow = _object_extent(cells)
    if height < oh or width < ow:
        raise ValueError(
            f"extent {height}x{width} too small for {name!r} ({oh}x{ow})"
        )
    board = np.zeros((height, width), dtype=np.uint8)
    return place_cells(board, cells, int(offset[0]), int(offset[1]))


def init_global(pattern: int, size: int, num_ranks: int) -> np.ndarray:
    """The full ``(num_ranks*size) × size`` world at t=0 (ranks stacked)."""
    geom = Geometry(size=size, num_ranks=num_ranks)
    board = np.empty((geom.global_height, geom.global_width), dtype=np.uint8)
    for rank in range(num_ranks):
        board[rank * size : (rank + 1) * size] = init_local(
            pattern, size, rank, num_ranks
        )
    return board
