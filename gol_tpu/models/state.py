"""World state as an immutable pytree.

The reference keeps its state in C globals shared across two translation
units (``g_data``/``g_resultData``/``g_worldWidth``/``g_worldHeight`` at
``gol-main.c:11-13`` and ``gol-with-cuda.cu:10-30``, ghost-row pointers at
``gol-main.c:11``).  The TPU-native design replaces all of that with a single
immutable dataclass threaded through pure step functions:

- the double buffer (``gol_swap``, ``gol-with-cuda.cu:174-186``) becomes XLA
  input/output aliasing — step functions donate their input board;
- the four ghost-row buffers (``init_Ghost_rows``, ``gol-with-cuda.cu:32-53``)
  have no stored equivalent: fresh halos are produced per step by
  ``lax.ppermute`` (or, in reference-compat mode, frozen t=0 halos are carried
  explicitly in the state — see :mod:`gol_tpu.parallel.engine`).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

CELL_DTYPE = jnp.uint8


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GolState:
    """Immutable Game-of-Life world state.

    Attributes:
      board: uint8[H, W] cell grid (1 = alive, 0 = dead). May be the global
        world or one shard's local block depending on context.
      generation: uint32 scalar — number of steps taken so far.
    """

    board: jax.Array
    generation: jax.Array

    @staticmethod
    def create(board: jax.Array, generation: int = 0) -> "GolState":
        return GolState(
            board=jnp.asarray(board, CELL_DTYPE),
            generation=jnp.asarray(generation, jnp.uint32),
        )

    @property
    def height(self) -> int:
        return self.board.shape[-2]

    @property
    def width(self) -> int:
        return self.board.shape[-1]


@dataclasses.dataclass(frozen=True)
class Geometry:
    """Static world geometry: the TPU-native equivalent of the reference's
    rank bookkeeping (``myRank``/``numRank``/``g_worldWidth``/``g_worldHeight``
    globals, ``gol-main.c:13,55-62``).

    The reference's global world is ``num_ranks`` stacked ``size × size``
    blocks: ``(num_ranks * size)`` rows by ``size`` columns (row labels at
    ``gol-main.c:22``, cell-update count at ``gol-main.c:124-125``).  Both
    axes are periodic (torus): columns wrap mod width inside the kernel
    (``gol-with-cuda.cu:210-211``), rows wrap because the rank ring uses mod
    arithmetic (``gol-main.c:86-87``).
    """

    size: int  # per-rank square edge (CLI `worldSize`)
    num_ranks: int  # logical ranks (= shards of the row axis)

    def __post_init__(self):
        if self.size <= 0:
            raise ValueError(f"worldSize must be positive, got {self.size}")
        if self.num_ranks <= 0:
            raise ValueError(f"num_ranks must be positive, got {self.num_ranks}")

    @property
    def global_height(self) -> int:
        return self.size * self.num_ranks

    @property
    def global_width(self) -> int:
        return self.size

    @property
    def local_height(self) -> int:
        return self.size

    def cell_updates(self, iterations: int) -> int:
        """`numRank * H * W * iterations` (gol-main.c:124-125)."""
        return self.num_ranks * self.size * self.size * iterations
