"""3-D Life driver: the volume counterpart of the 2-D reference CLI.

A capability addition (the reference is strictly 2-D), styled after the
same surface so the two drivers feel like one tool:

    python -m gol_tpu.cli3d <pattern> <size> <iterations> <threads> <on_off>
        [--rule NAME|B../S..] [--engine {auto,dense,bitpack,pallas}]
        [--mesh {none,3d}] [--outdir DIR]
        [--checkpoint-every K] [--checkpoint-dir DIR] [--resume CKPT]

Patterns: 0 all-zeros, 1 all-ones, 2 random (density 0.3, fixed seed 0 —
deterministic across engines and meshes).  ``size`` is the cube edge
D = H = W; ``threads`` is accepted for surface parity with the 2-D driver
and validated (>0, fixing the reference's bug-B5 class) but tiling is
chosen automatically by the engines.
Rules default to Bays 4555 (named: ``bays4555``, ``bays5766``, or any
``B<counts>/S<counts>`` with comma-separated multi-digit counts, e.g.
``B5/S4,5``).  With ``on_off=1`` the final volume is written to
``World3D_of_<n>.npy`` in ``--outdir`` (NumPy format — there is no
reference 3-D dump format to match).

Prints the reference-style duration line plus the live-cell population
(the 3-D analog of eyeballing rank dumps).
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Optional, Sequence

import numpy as np

from gol_tpu import resilience
from gol_tpu.cli import atoi

ENGINES3D = ("auto", "dense", "bitpack", "pallas")

USAGE3D = (
    "gol3d requires 5 arguments: pattern number (0 zeros, 1 ones, 2 "
    "random), cube edge size, iterations, threads per block and "
    "output-on-off e.g. python -m gol_tpu.cli3d 2 64 10 512 0 \n"
)

_RULE3D_RE = re.compile(r"^B([\d,]*)/S([\d,]*)$", re.IGNORECASE)


def parse_rule3d(text: str):
    """Named rule or ``B<counts>/S<counts>`` (comma-separated counts 0-26)."""
    from gol_tpu.ops import life3d

    named = {"bays4555": life3d.BAYS_4555, "bays5766": life3d.BAYS_5766}
    if text.lower() in named:
        return named[text.lower()]
    m = _RULE3D_RE.match(text.strip())
    if not m:
        raise ValueError(
            f"malformed 3-D rule {text!r}; expected a name "
            f"({', '.join(sorted(named))}) or B<counts>/S<counts> with "
            "comma-separated counts, e.g. B5/S4,5"
        )

    def counts(group: str):
        return frozenset(int(t) for t in group.split(",") if t)

    rule = life3d.Rule3D(birth=counts(m.group(1)), survive=counts(m.group(2)))
    if any(c > 26 for c in rule.birth | rule.survive):
        raise ValueError(f"3-D rule {text!r} has counts > 26")
    return rule


def init_volume(pattern: int, size: int) -> np.ndarray:
    if pattern == 0:
        return np.zeros((size, size, size), np.uint8)
    if pattern == 1:
        return np.ones((size, size, size), np.uint8)
    if pattern == 2:
        rng = np.random.default_rng(0)
        return (rng.random((size, size, size)) < 0.3).astype(np.uint8)
    raise ValueError(f"Pattern {pattern} has not been implemented")


def _pallas3d_sharded_fits(mesh, size: int) -> bool:
    """Whether the fused sharded 3-D kernel supports this mesh/geometry —
    mirrors :func:`gol_tpu.parallel.sharded3d.compiled_evolve3d_pallas`'s
    constraints, for ``auto`` resolution (an explicit ``--engine pallas``
    raises the real errors instead)."""
    from gol_tpu.ops import bitlife, pallas_bitlife3d
    from gol_tpu.parallel.mesh import COLS, PLANES, ROWS

    planes = mesh.shape.get(PLANES, 1)
    rows = mesh.shape.get(ROWS, 1)
    if (planes != 1 and rows != 1) or size % 128:
        return False
    band = size // (planes if rows == 1 else rows)
    nw = size // mesh.shape.get(COLS, 1) // bitlife.BITS
    return (
        band >= 8
        and nw >= 1
        and pallas_bitlife3d.pick_tile3d_wt(band, nw, size, 8) is not None
    )


def _halo3d_block(mode: str, k: int, mesh, size: int, take: int) -> dict:
    """One 3-D chunk's schema-v8 ``halo`` block: the packed ring tier's
    exchange depth/count and band traffic (three ppermute phases — plane
    band, row band of the plane-extended shard, word column of both)."""
    from gol_tpu.parallel.mesh import COLS, PLANES, ROWS

    npl = mesh.shape.get(PLANES, 1)
    nr = mesh.shape.get(ROWS, 1)
    nc = mesh.shape.get(COLS, 1)
    d, h, nw = size // npl, size // nr, size // nc // 32

    def band_bytes(dd: int) -> int:
        planes = 2 * dd * h * nw * 4
        rows = 2 * dd * (d + 2 * dd) * nw * 4
        cols = 2 * dd * (d + 2 * dd) * (h + 2 * dd) * 4
        return planes + rows + cols

    full, rem = divmod(take, k)
    chunk_bytes = full * band_bytes(k) + (band_bytes(rem) if rem else 0)
    state = d * h * nw * 4
    payload = chunk_bytes + take * state
    return {
        "depth": k,
        "mode": mode,
        "exchanges": full + (1 if rem else 0),
        "band_bytes": chunk_bytes,
        "exchange_share": chunk_bytes / payload if payload else 0.0,
    }


def _build_evolver(
    engine: str, mesh, steps: int, rule, size: int, stats: bool = False,
    shard_mode: str = "explicit", halo_depth: int = 1,
):
    """(compiled, place) for the chosen engine/mesh.

    ``compiled`` is AOT-lowered from a ShapeDtypeStruct — like
    ``GolRuntime.compile_evolvers``, compilation never executes a throwaway
    evolution — and donates its input; ``place`` puts the host volume on
    device(s) with the sharding the compiled program expects.

    ``stats=True`` wraps the program in the in-graph volume reductions
    (:func:`gol_tpu.telemetry.stats.wrap_evolver_3d`): the compiled
    chunk returns ``(volume, stats)`` — population/births/deaths/changed
    — with sharded volumes reduced at the global-array level (XLA
    derives the collectives; the scalars replicate to every process).
    The chunk-start volume stays live for the diff, so the wrapped form
    forfeits the input donation (one extra volume of HBM).
    """
    import jax

    def finish(fn, static, spec, place):
        if stats:
            from gol_tpu.telemetry import stats as stats_mod

            wrapped = stats_mod.wrap_evolver_3d(fn, static)
            return wrapped.lower(spec).compile(), place
        return fn.lower(spec, *static).compile(), place

    spec_shape = (size, size, size)
    explicit_pallas = engine == "pallas"
    engine = _resolve_engine3d(engine, mesh, size)
    if mesh is not None:
        from gol_tpu.parallel import sharded3d

        packable = True
        try:
            sharded3d.validate_geometry3d_packed(spec_shape, mesh)
        except ValueError:
            packable = False
        if engine in ("bitpack", "pallas") and not packable:
            raise ValueError(
                f"engine {engine!r} needs the x-shard width to pack into "
                f"whole 32-cell words (size {size} over mesh "
                f"{dict(mesh.shape)})"
            )
        if engine == "pallas":
            # The fused word-tiled kernel per shard behind the two-phase
            # ring exchange; an explicit --engine pallas surfaces its
            # geometry constraints (H-unsharded mesh etc.) as clean
            # errors — auto only resolves here when the geometry fits.
            fn = sharded3d.compiled_evolve3d_pallas(mesh, steps, rule)
        elif engine == "bitpack":
            # The packed ring tier carries the temporal-blocking and
            # chunk-form knobs: --halo-depth K ships a k-deep ghost
            # shell per exchange, --shard-mode overlap/pipeline runs the
            # depth-k interior/boundary split / cross-chunk double
            # buffer (gol_tpu.parallel.halo; same forms as the 2-D
            # driver, three ppermute phases instead of two).
            fn = sharded3d.compiled_evolve3d_packed(
                mesh, steps, rule, halo_depth, shard_mode
            )
        else:
            sharded3d.validate_geometry3d(spec_shape, mesh)
            fn = sharded3d.compiled_evolve3d(mesh, steps, rule)
        sharding = sharded3d.volume_sharding(mesh)
        spec = jax.ShapeDtypeStruct(spec_shape, np.uint8, sharding=sharding)
        place = lambda v: jax.device_put(v, sharding)
        return finish(fn, (), spec, place)

    if engine == "pallas":
        from gol_tpu.ops import pallas_bitlife3d

        # strict only for an explicit --engine pallas: a benchmark must
        # never be silently relabeled by the VMEM fallback; 'auto' keeps
        # the silent substitution (it promises the fastest fit, not a
        # specific program).
        fn = pallas_bitlife3d.evolve3d
        static = (steps, rule, explicit_pallas)
    elif engine == "bitpack":
        from gol_tpu.ops import bitlife3d

        fn = bitlife3d.evolve3d_dense_io
        static = (steps, rule)
    else:
        from gol_tpu.ops import life3d

        fn = life3d.run3d
        static = (steps, rule)
    spec = jax.ShapeDtypeStruct(spec_shape, np.uint8)
    return finish(fn, static, spec, jax.device_put)


def _resolve_engine3d(engine: str, mesh, size: int) -> str:
    """Map ``auto`` to the fastest tier this geometry supports (explicit
    choices pass through and surface their own constraint errors).

    The ONE auto policy — ``_build_evolver`` delegates here, so the
    driver's checker-engine selection and the builder cannot drift.
    ``auto`` never resolves to a Pallas configuration that would fall
    back or raise: it promises the fastest *fit*, not a specific program.
    """
    import jax

    if engine != "auto":
        return engine
    if mesh is not None:
        from gol_tpu.parallel import sharded3d

        packable = True
        try:
            sharded3d.validate_geometry3d_packed((size,) * 3, mesh)
        except ValueError:
            packable = False
        if (
            packable
            and jax.default_backend() == "tpu"
            and _pallas3d_sharded_fits(mesh, size)
        ):
            return "pallas"
        return "bitpack" if packable else "dense"
    if jax.default_backend() == "tpu" and size % 128 == 0:
        # % 128 implies the % 32 word packing; still require a kernel
        # window to actually fit scoped VMEM, else auto prefers the tier
        # that runs as asked over one that silently substitutes.
        from gol_tpu.ops import pallas_bitlife3d

        nw = size // 32
        if (
            pallas_bitlife3d.pick_tile3d(size, nw, size)
            or pallas_bitlife3d.pick_tile3d_wt(size, nw, size) is not None
        ):
            return "pallas"
    if size % 32 == 0:
        return "bitpack"
    return "dense"


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    ext = argparse.ArgumentParser(prog="gol3d", add_help=True)
    ext.add_argument("positionals", nargs="*", metavar="ARG")
    ext.add_argument("--rule", default="bays4555")
    ext.add_argument("--engine", choices=ENGINES3D, default="auto")
    ext.add_argument("--mesh", choices=["none", "3d"], default="none")
    # Ring chunk form + temporal blocking for the packed sharded tier
    # (--engine bitpack --mesh 3d): explicit serial chunks, the depth-k
    # interior/boundary overlap split, or the cross-chunk pipelined
    # double buffer — same matrix as the 2-D driver, one dimension up
    # (gol_tpu/parallel/modes.py).
    ext.add_argument(
        "--shard-mode",
        choices=["explicit", "overlap", "pipeline"],
        default="explicit",
    )
    ext.add_argument("--halo-depth", type=int, default=1, metavar="K")
    # Explicit (planes, rows, cols) factorization: the fused sharded
    # kernel needs one of planes/rows to be 1 ((P,1,C) or (1,R,C)),
    # which the default most-cubic factorization of 8 devices (2,2,2)
    # is not.
    ext.add_argument("--mesh-shape", default=None, metavar="P,R,C")
    ext.add_argument("--outdir", default=".")
    # Checkpoint/resume, mirroring the 2-D driver: periodic
    # fingerprint-stamped volume snapshots, verified + rule-checked on
    # resume.  Sharded (mesh) runs write the piece-file directory format —
    # no host ever assembles the volume (utils/checkpoint.py
    # save_sharded3d); single-device runs keep the monolithic npz.
    ext.add_argument("--checkpoint-every", type=int, default=0, metavar="K")
    ext.add_argument("--checkpoint-dir", default="checkpoints3d")
    ext.add_argument("--resume", default=None, metavar="CKPT")
    # Process-tier resilience, exactly the 2-D driver's surface
    # (docs/RESILIENCE.md): validated auto-resume with total-target
    # iteration semantics, keep-last-K snapshot retention, and
    # SIGTERM/SIGINT → chunk-boundary checkpoint + exit 75.
    ext.add_argument("--auto-resume", action="store_true")
    ext.add_argument("--keep-snapshots", type=int, default=3, metavar="K")
    # Multi-host trio + failure detection, exactly the 2-D driver's
    # surface (gol_tpu/cli.py).
    from gol_tpu.parallel import multihost

    multihost.add_multihost_args(ext)
    ext.add_argument("--guard-every", type=int, default=0, metavar="K")
    ext.add_argument("--guard-max-restores", type=int, default=3, metavar="N")
    ext.add_argument("--guard-redundant", action="store_true")
    ext.add_argument(
        "--guard-redundant-every", type=int, default=1, metavar="N"
    )
    # jax.profiler trace of the steady-state loop (2-D driver parity).
    ext.add_argument("--profile", default=None, metavar="TRACE_DIR")
    # Structured JSONL telemetry, same surface and schema as the 2-D
    # driver (docs/OBSERVABILITY.md).
    ext.add_argument("--telemetry", default=None, metavar="DIR")
    ext.add_argument("--run-id", default=None, metavar="NAME")
    # Live metrics endpoint, same surface as the 2-D driver
    # (docs/OBSERVABILITY.md): rank 0 serves Prometheus text fed by the
    # in-process event stream.  Requires --telemetry.
    ext.add_argument("--metrics-port", type=int, default=None, metavar="P")
    # In-graph volume statistics per chunk (schema-v2 `stats` events):
    # population/births/deaths/changed fused onto the chunk program —
    # same surface and constraints as the 2-D driver's --stats.
    ext.add_argument("--stats", action="store_true")
    # Declarative fault injection, same surface as the 2-D driver
    # (docs/RESILIENCE.md): PATH or inline JSON; GOL_FAULT_PLAN is the
    # env equivalent.  3-D board.bitflip entries use plane/row/col.
    ext.add_argument("--fault-plan", default=None, metavar="PLAN")
    ns = ext.parse_args(argv)
    if len(ns.positionals) != 5:
        sys.stdout.write(USAGE3D)
        return 255
    pattern = atoi(ns.positionals[0])
    size = atoi(ns.positionals[1])
    iterations = atoi(ns.positionals[2])
    threads = atoi(ns.positionals[3])
    on_off = atoi(ns.positionals[4])

    from gol_tpu.resilience import degrade as degrade_mod
    from gol_tpu.resilience import faults as faults_mod

    try:
        if ns.fault_plan:
            faults_mod.install(faults_mod.FaultPlan.load(ns.fault_plan))
        else:
            faults_mod.install_from_env()
    except faults_mod.FaultPlanError as e:
        print(e)
        return 255
    plan_on = faults_mod.active() is not None

    try:
        topo = multihost.init_multihost(
            coordinator_address=ns.coordinator,
            num_processes=ns.num_processes,
            process_id=ns.process_id,
        )
    except (ValueError, RuntimeError) as e:
        print(e)
        return 255
    if topo.process_count > 1 and ns.mesh == "none":
        print(
            f"multi-host run ({topo.process_count} processes) requires a "
            "device mesh; pass --mesh 3d"
        )
        return 255

    guard_report = None
    ckpt_writer = None
    events = None
    try:
        if size <= 0:
            raise ValueError(f"size must be positive, got {size}")
        if iterations < 0:
            raise ValueError(f"iterations must be >= 0, got {iterations}")
        if threads <= 0:
            raise ValueError(f"threads per block must be positive, got {threads}")
        if ns.checkpoint_every < 0:
            raise ValueError(
                f"--checkpoint-every must be >= 0, got {ns.checkpoint_every}"
            )
        if ns.guard_every < 0:
            raise ValueError(
                f"--guard-every must be >= 0, got {ns.guard_every} "
                "(0 disables the guard)"
            )
        if ns.guard_redundant and ns.guard_every <= 0:
            raise ValueError(
                "--guard-redundant audits chunks, so it requires "
                "--guard-every K > 0"
            )
        if ns.guard_redundant_every != 1 and not ns.guard_redundant:
            raise ValueError(
                "--guard-redundant-every samples the redundancy audit, "
                "so it requires --guard-redundant"
            )
        if ns.profile and ns.guard_every > 0:
            raise ValueError(
                "--profile applies to unguarded runs; drop --guard-every"
            )
        if ns.stats and not ns.telemetry:
            raise ValueError(
                "--stats emits schema-v2 stats events, so it requires "
                "--telemetry DIR"
            )
        if ns.metrics_port is not None and not ns.telemetry:
            raise ValueError(
                "--metrics-port serves the in-process event stream, so "
                "it requires --telemetry DIR"
            )
        if ns.metrics_port is not None and not (
            0 <= ns.metrics_port <= 65535
        ):
            raise ValueError(
                f"--metrics-port must be 0..65535 (0 = ephemeral), got "
                f"{ns.metrics_port}"
            )
        if ns.stats and ns.guard_every > 0:
            raise ValueError(
                "--stats applies to unguarded runs; drop --guard-every "
                "(the guard's audit already reports population per chunk)"
            )
        if ns.auto_resume and ns.resume:
            raise ValueError(
                "--auto-resume selects the snapshot itself; pass one of "
                "--resume/--auto-resume, not both"
            )
        if ns.keep_snapshots < 0:
            raise ValueError(
                f"--keep-snapshots must be >= 0, got {ns.keep_snapshots} "
                "(0 keeps every snapshot)"
            )
        rule = parse_rule3d(ns.rule)

        import jax

        from gol_tpu.ops.life3d import rulestring3d
        from gol_tpu.utils import checkpoint as ckpt_mod

        rulestr = rulestring3d(rule)

        mesh = None
        if ns.mesh == "3d":
            from gol_tpu.parallel import mesh as mesh_mod

            shape3 = None
            if ns.mesh_shape:
                parts = ns.mesh_shape.split(",")
                if len(parts) != 3 or not all(
                    p.strip().isdigit() for p in parts
                ):
                    raise ValueError(
                        f"--mesh-shape must be P,R,C integers, got "
                        f"{ns.mesh_shape!r}"
                    )
                shape3 = tuple(int(p) for p in parts)
            if shape3 is not None:
                # An explicit factorization may use a subset of the
                # visible devices (e.g. an H-unsharded mesh on a pod
                # whose count doesn't factor as P*1*C).
                n3 = shape3[0] * shape3[1] * shape3[2]
                if n3 > len(jax.devices()):
                    raise ValueError(
                        f"--mesh-shape {ns.mesh_shape} needs {n3} devices, "
                        f"only {len(jax.devices())} visible"
                    )
                mesh = mesh_mod.make_mesh_3d(
                    shape3, devices=jax.devices()[:n3]
                )
            else:
                mesh = mesh_mod.make_mesh_3d()
        elif ns.mesh_shape:
            raise ValueError("--mesh-shape requires --mesh 3d")

        def check_meta(shape, found_rule):
            if tuple(shape) != (size, size, size):
                raise ValueError(
                    f"checkpoint volume {tuple(shape)} != configured "
                    f"{(size, size, size)}"
                )
            if found_rule != rulestr:
                raise ValueError(
                    f"checkpoint was written by a {found_rule} run; this "
                    f"run is configured for {rulestr} — pass the matching "
                    "--rule to resume"
                )

        resume_src = ns.resume
        resume_info = None
        if ns.auto_resume:
            # Collective on multi-host jobs (min-generation agreement).
            resume_src, resume_info = resilience.resolve_auto_resume(
                ns.checkpoint_dir, kind="3d"
            )
            if resume_src is not None:
                # Total-target semantics: identical argv after a
                # preemption completes exactly the remaining generations.
                iterations = max(0, iterations - resume_info["generation"])
                if topo.is_coordinator:
                    print(
                        f"auto-resume: generation "
                        f"{resume_info['generation']} from {resume_src}"
                        + ("  [fallback]" if resume_info["fallback"] else "")
                    )
            elif topo.is_coordinator:
                print(
                    f"auto-resume: no valid snapshot in "
                    f"{ns.checkpoint_dir}; starting fresh"
                )

        generation = 0
        vol = None
        placed = None  # sharded resumes build the device array directly
        if resume_src:
            if ckpt_mod.is_sharded(resume_src):
                meta = ckpt_mod.load_sharded3d_meta(resume_src)
                check_meta(meta.shape, meta.rule)
                generation = meta.generation
                if mesh is not None:
                    from gol_tpu.parallel import sharded3d

                    # Each host reads back only the boxes its devices own.
                    placed = jax.make_array_from_callback(
                        meta.shape,
                        sharded3d.volume_sharding(mesh),
                        lambda idx: ckpt_mod.read_sharded3d_region(
                            resume_src, meta, idx
                        ),
                    )
                else:
                    vol = ckpt_mod.read_sharded3d_region(
                        resume_src,
                        meta,
                        (slice(None), slice(None), slice(None)),
                    )
            else:
                snap = ckpt_mod.load3d(resume_src)
                check_meta(snap.volume.shape, snap.rule)
                vol = snap.volume
                generation = snap.generation
        else:
            vol = init_volume(pattern, size)

        from gol_tpu.utils.timing import Stopwatch, force_ready

        # Evolvers receive the raw choice (auto keeps its silent-fallback
        # contract inside _build_evolver); the resolved name picks the
        # redundant checker's counterpart engine.
        resolved = _resolve_engine3d(ns.engine, mesh, size)

        if ns.halo_depth < 1:
            raise ValueError(
                f"--halo-depth must be >= 1, got {ns.halo_depth}"
            )
        if ns.shard_mode != "explicit" or ns.halo_depth != 1:
            # The chunk-form knobs configure the packed ring tier's
            # exchange; everything else either has no ring (mesh none,
            # dense) or owns its own banding (the fused Pallas engine).
            if mesh is None:
                raise ValueError(
                    "--shard-mode/--halo-depth configure the sharded "
                    "ring exchange; pass --mesh 3d"
                )
            if resolved != "bitpack":
                raise ValueError(
                    f"--shard-mode {ns.shard_mode!r}/--halo-depth "
                    f"{ns.halo_depth} apply to the packed ring tier "
                    f"(engine 'bitpack'); resolved engine is "
                    f"{resolved!r} — pass --engine bitpack (the fused "
                    "3-D Pallas engine keeps its own 8-deep banding)"
                )

        from gol_tpu import telemetry as telemetry_mod

        num_devices = 1 if mesh is None else mesh.devices.size
        shard_cells = size**3 // max(num_devices, 1)
        try:
            restart_attempt = int(os.environ.get("GOL_RESTART_ATTEMPT", "0"))
        except ValueError:
            restart_attempt = 0
        if ns.telemetry:
            events = telemetry_mod.EventLog(ns.telemetry, run_id=ns.run_id)
            if ns.metrics_port is not None and topo.is_coordinator:
                # Rank 0 only: one scrape surface per job, attached
                # before the header emits (main's finally closes the
                # server with the event log).
                from gol_tpu.telemetry import metrics as metrics_mod

                metrics_mod.serve_event_metrics(events, ns.metrics_port)
            events.run_header(
                dict(
                    driver="3d",
                    engine=ns.engine,
                    resolved_engine=resolved,
                    mesh=None if mesh is None else dict(mesh.shape),
                    shard_mode=ns.shard_mode,
                    halo_depth=ns.halo_depth,
                    rule=rulestr,
                    size=size,
                    checkpoint_every=ns.checkpoint_every,
                )
            )
            if restart_attempt > 0:
                events.restart_event(restart_attempt)
            if resume_info is not None and resume_info.get("path"):
                events.resume_event(
                    generation=resume_info["generation"],
                    path=resume_info["path"],
                    fallback=bool(resume_info.get("fallback")),
                    skipped=resume_info.get("skipped") or [],
                )

        def util3d(take, wall_s):
            return telemetry_mod.roofline_utilization_3d(
                resolved, shard_cells, take, wall_s
            )

        # Async writer for the single-device path (same overlap +
        # final-flush contract as GolRuntime.run; the sharded save ends
        # in a device barrier and must stay on the main thread).  The
        # close() in main's finally drains queued writes even when the
        # loop raises — e.g. a guard restore-budget exhaustion, the exact
        # case mid-run snapshots exist for.
        ckpt_writer = (
            ckpt_mod.AsyncSnapshotWriter()
            if ns.checkpoint_every > 0 and mesh is None and iterations > 0
            else None
        )

        def gc_old_snapshots():
            if ns.keep_snapshots > 0:
                resilience.gc_snapshots(
                    ns.checkpoint_dir,
                    ns.keep_snapshots,
                    kind="3d",
                    protect=(resume_src,),
                )

        # Checkpoint containment (docs/RESILIENCE.md "Retry and shed"):
        # transient write errors retry with backoff; persistent ENOSPC
        # sheds telemetry first, then checkpointing — never the run.
        ckpt_state = {"shed": False}

        def shed_telemetry(reason):
            if events is not None:
                events.request_shed("telemetry", reason)

        def save_snapshot(b, g, fp=None):
            if ckpt_state["shed"]:
                return
            if mesh is not None:
                ok = degrade_mod.write_with_retry(
                    lambda: ckpt_mod.save_sharded3d(
                        ckpt_mod.sharded_checkpoint3d_path(
                            ns.checkpoint_dir, g
                        ),
                        b,
                        g,
                        rulestr,
                        fingerprint=fp,
                    ),
                    generation=g,
                    shed_telemetry=shed_telemetry,
                )
                from jax.experimental import multihost_utils

                # The barrier runs even on a shed write: a degraded
                # rank must not strand its peers in the fence.
                multihost_utils.sync_global_devices("gol3d_checkpoint")
                if not ok:
                    ckpt_state["shed"] = True
                    return
                # Retention after the barrier, one process sweeping.
                if jax.process_index() == 0:
                    gc_old_snapshots()
            else:
                path = ckpt_mod.checkpoint3d_path(ns.checkpoint_dir, g)
                # Host fetch on this thread (donation fence — and a
                # background fetch would contend with the next chunk's
                # device execution, see GolRuntime._save_snapshot); the
                # compressed write overlaps.  GC rides behind the save
                # on whichever thread performs it.
                vol_np = np.asarray(b)

                def write(p=path, v=vol_np, g=g, fp=fp):
                    ok = degrade_mod.write_with_retry(
                        lambda: ckpt_mod.save3d(
                            p, v, g, rulestr, fingerprint=fp
                        ),
                        generation=g,
                        shed_telemetry=shed_telemetry,
                    )
                    if not ok:
                        ckpt_state["shed"] = True
                        return
                    gc_old_snapshots()

                if ckpt_writer is not None:
                    ckpt_writer.submit(write)
                else:
                    write()

        # Cooperative-preemption exit (docs/RESILIENCE.md): called at a
        # chunk boundary when SIGTERM/SIGINT arrived and work remains.
        # A final snapshot is persisted when checkpointing is configured
        # (skipped when one just landed at this boundary), the async
        # writer is fenced, and Preempted maps to exit code 75 below.
        preempt_can_save = ns.checkpoint_every > 0 or ns.auto_resume

        def preempt_exit(b, g, fp=None, just_saved=False):
            checkpointed = just_saved
            if preempt_can_save and not just_saved:
                with sw.phase("checkpoint"):
                    save_snapshot(b, g, fp)
                checkpointed = True
            if ckpt_writer is not None and checkpointed:
                with sw.phase("checkpoint"):
                    ckpt_writer.flush()
            if events is not None:
                events.preempt_event(g, checkpointed=checkpointed)
            raise resilience.Preempted(
                g,
                checkpoint_dir=ns.checkpoint_dir if checkpointed else None,
            )

        sw = Stopwatch()
        if iterations > 0:
            # GolRuntime's schedule policy: full audit/checkpoint
            # intervals plus one tail, one AOT evolver per distinct size.
            from gol_tpu.runtime import chunk_schedule

            interval = (
                ns.guard_every
                if ns.guard_every > 0
                else (
                    ns.checkpoint_every
                    if ns.checkpoint_every > 0
                    else iterations
                )
            )
            schedule = chunk_schedule(iterations, interval)
            with sw.phase("compile"):
                import time as time_mod

                from gol_tpu.batch import cache as cache_mod

                evolvers = {}
                for take in set(schedule):
                    probe = cache_mod.CompileCacheProbe()
                    t0 = time_mod.perf_counter()
                    evolvers[take] = _build_evolver(
                        ns.engine, mesh, take, rule, size, stats=ns.stats,
                        shard_mode=ns.shard_mode, halo_depth=ns.halo_depth,
                    )
                    if events is not None:
                        # _build_evolver lowers + compiles in one step;
                        # the record carries the combined duration (and,
                        # schema v2, the compiled memory footprint).
                        from gol_tpu.telemetry import stats as stats_mod

                        cache_hit, cache_key = probe.resolve()
                        events.compile_event(
                            take,
                            0.0,
                            time_mod.perf_counter() - t0,
                            memory=stats_mod.compiled_memory(
                                evolvers[take][0]
                            ),
                            cache_hit=cache_hit,
                            cache_key=cache_key,
                        )
                place = evolvers[schedule[0]][1]
                board = placed if placed is not None else place(vol)
                force_ready(board)
                checker_evolvers = None
                if ns.guard_redundant:
                    # Second bit-exact engine: an independent program a
                    # random flip cannot reproduce (guard._checker_runtime's
                    # reasoning; bitlife3d and life3d are mutually
                    # bit-exact, pinned by the 3-D equivalence tests).
                    checker = "dense" if resolved != "dense" else "bitpack"
                    if checker == "bitpack" and size % 32:
                        raise ValueError(
                            "the redundant audit needs a second bit-exact "
                            "engine, and the only check for a dense run is "
                            f"bit-packed — size {size} does not pack into "
                            "32-cell words"
                        )
                    checker_evolvers = {
                        take: (
                            _build_evolver(checker, mesh, take, rule, size)[0],
                            (),
                        )
                        for take in set(schedule)
                    }
            if ns.guard_every > 0:
                from gol_tpu.utils import guard as guard_mod

                guard_report = guard_mod.GuardReport()
                with resilience.preemption_guard():
                    board, generation = guard_mod.guarded_loop(
                        sw,
                        guard_report,
                        board,
                        generation,
                        schedule,
                        {t: (c, ()) for t, (c, _) in evolvers.items()},
                        checker_evolvers,
                        guard_mod.GuardConfig(
                            check_every=ns.guard_every,
                            max_restores=ns.guard_max_restores,
                            redundant=ns.guard_redundant,
                            redundant_every=ns.guard_redundant_every,
                        ),
                        save_snapshot=save_snapshot,
                        checkpoint_every=ns.checkpoint_every,
                        events=events,
                        chunk_utilization=util3d,
                        checkpoint_overlapped=ckpt_writer is not None,
                        preempt_hook=preempt_exit,
                    )
            else:
                from gol_tpu.utils.timing import maybe_profile

                # Span attribution (schema v6), same shape as the 2-D
                # runtime loop: telemetry-off never builds the clock.
                sc = (
                    telemetry_mod.SpanClock()
                    if events is not None
                    else None
                )
                with resilience.preemption_guard(), maybe_profile(
                    ns.profile
                ), telemetry_mod.trace_annotation(
                    "gol3d.run.evolve"
                ):
                    for i, take in enumerate(schedule):
                        compiled, _ = evolvers[take]
                        dev_stats = None
                        with telemetry_mod.step_annotation("gol.chunk", i):
                            with sw.phase("total"):
                                t0 = time_mod.perf_counter()
                                out3 = compiled(board)
                                t1 = time_mod.perf_counter()
                                if ns.stats:
                                    board, dev_stats = out3
                                else:
                                    board = out3
                                force_ready(board)
                                dt = time_mod.perf_counter() - t0
                        if plan_on:
                            # Fault-plane SDC injection (board.bitflip,
                            # plane/row/col): host-side functional cell
                            # update — the un-audited path takes the
                            # corruption silently by design.
                            board = faults_mod.apply_board_faults(
                                board, generation + take
                            )
                        generation += take
                        if events is not None:
                            sc.add("dispatch", t1 - t0)
                            sc.add("ready", dt - (t1 - t0))
                            spans = sc.take()
                            extra3 = {}
                            if mesh is not None and resolved == "bitpack":
                                # Schema v8: the packed ring tier's
                                # exchange accounting for this chunk.
                                extra3["halo"] = _halo3d_block(
                                    ns.shard_mode, ns.halo_depth,
                                    mesh, size, take,
                                )
                            with sc.span("telemetry"):
                                events.chunk_event(
                                    i,
                                    take,
                                    generation,
                                    dt,
                                    size**3 * take,
                                    util3d(take, dt),
                                    spans=spans,
                                    **extra3,
                                )
                        if dev_stats is not None and events is not None:
                            from gol_tpu.telemetry import (
                                stats as stats_mod,
                            )

                            with sc.span("telemetry"):
                                events.stats_event(
                                    i,
                                    take,
                                    generation,
                                    stats_mod.stats_values(dev_stats),
                                )
                        if ns.checkpoint_every > 0 and not ckpt_state[
                            "shed"
                        ]:
                            with telemetry_mod.trace_annotation(
                                "gol.checkpoint.save"
                            ), sw.phase("checkpoint"):
                                t0 = time_mod.perf_counter()
                                save_snapshot(board, generation)
                                dt = time_mod.perf_counter() - t0
                            if sc is not None:
                                sc.add("checkpoint", dt)
                            if events is not None:
                                with sc.span("telemetry"):
                                    events.checkpoint_event(
                                        generation,
                                        dt,
                                        size**3,
                                        overlapped=ckpt_writer is not None,
                                    )
                        if plan_on:
                            faults_mod.crash_or_stall(generation)
                        if events is not None:
                            for frec in faults_mod.drain_fired():
                                events.fault_event(**frec)
                            for drec in degrade_mod.drain_reports():
                                events.degraded_event(**drec)
                        if i < len(schedule) - 1:
                            if sc is None:
                                preempt_now = (
                                    resilience.agreed_preempt_requested()
                                )
                            else:
                                with sc.span("preempt_poll"):
                                    preempt_now = (
                                        resilience.agreed_preempt_requested()
                                    )
                            if preempt_now:
                                # Chunk-boundary preemption poll (host-
                                # side only; the compiled programs never
                                # see it).
                                preempt_exit(
                                    board,
                                    generation,
                                    just_saved=ns.checkpoint_every > 0,
                                )
            if ckpt_writer is not None:
                # Completion fence only; main's finally owns the close.
                with sw.phase("checkpoint"):
                    ckpt_writer.flush()
            out = board
        else:
            out = placed if placed is not None else vol
        # Population via a device reduce (collective-safe on sharded
        # volumes, and no 1 GB host gather at 1024³ just for the line).
        # Per-plane uint32 counts (each < 2^32: a plane has size² cells)
        # combined in uint64 on host — a single uint32 total would wrap
        # for volumes with >= 2^32 live cells.
        import jax.numpy as jnp

        if hasattr(out, "sharding"):
            reps = None
            if mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec

                reps = NamedSharding(mesh, PartitionSpec())
            plane_pops = jax.jit(
                lambda b: jnp.sum(b.astype(jnp.uint32), axis=(1, 2)),
                out_shardings=reps,
            )(out)
            population = int(
                np.asarray(plane_pops).astype(np.uint64).sum()
            )
        else:
            population = int(np.asarray(out).sum())
        report = sw.report(size**3 * iterations)
        if events is not None:
            events.summary(report)
    except resilience.Preempted as e:
        # Clean chunk-boundary stop, resumable snapshot on disk:
        # EX_TEMPFAIL (75), not the 255 error path.
        if topo.is_coordinator:
            print(e)
        return resilience.EX_TEMPFAIL
    except (ValueError, OSError) as e:
        # Same surface as the 2-D driver (gol_tpu/cli.py): bad --resume
        # paths, corrupt snapshots, unavailable engines, unwritable dirs
        # all exit cleanly with the message, not a traceback.
        print(e)
        from gol_tpu.utils.checkpoint import CorruptSnapshotError

        if isinstance(e, CorruptSnapshotError) and ns.resume:
            hint = resilience.corrupt_resume_hint(ns.resume, kind="3d")
            if hint:
                print(
                    f"hint: an earlier valid snapshot exists at {hint}; "
                    "resume from it, or rerun with --auto-resume to "
                    "select it (and fall back) automatically"
                )
        elif ns.resume and (
            "not divisible" in str(e)
            or "does not divide" in str(e)
            or "divisible by" in str(e)
        ):
            # Topology mismatch on a plain 3-D --resume: unlike the 2-D
            # driver there is no reshard path — the hint names the
            # writing topology instead (docs/RESILIENCE.md).
            hint = resilience.topology_resume_hint(ns.resume, kind="3d")
            if hint:
                print(hint)
        return 255
    finally:
        if ckpt_writer is not None:
            # Drain queued snapshot writes even when the loop raised
            # (e.g. a guard restore-budget exhaustion — the exact case
            # mid-run snapshots exist for); close() never raises.
            ckpt_writer.close()
        if events is not None:
            # The rank file keeps everything emitted before a failure —
            # telemetry exists precisely for runs that die mid-loop.
            events.close()

    if topo.is_coordinator:
        print(report.duration_line())
        if guard_report is not None:
            print(guard_report.summary_line())
        print(f"POPULATION     : {population} live cells of {size**3}")
        print("This is 3-D Life running on a TPU (capability addition).")
    if on_off == 1:
        if topo.process_count > 1:
            # Replication collective; only the coordinator writes.
            full = multihost.fetch_global(out)
            if not topo.is_coordinator:
                return 0
            out_np = full
        else:
            out_np = np.asarray(out)
        os.makedirs(ns.outdir, exist_ok=True)
        path = os.path.join(ns.outdir, "World3D_of_1.npy")
        np.save(path, out_np)
    return 0


if __name__ == "__main__":
    sys.exit(main())
