"""JAX version compatibility shims.

The framework targets the modern public surface (``jax.shard_map``); older
jaxlibs in the image expose the same function as
``jax.experimental.shard_map.shard_map`` with an identical keyword
signature (``f, mesh, in_specs, out_specs``).  Every engine imports the
symbol from here so a version bump is a one-line change and no engine can
drift onto a private path.
"""

from __future__ import annotations

import jax

def set_cpu_device_count(n: int):
    """Request an ``n``-device virtual CPU backend.

    Must run before the backend initializes (or between
    ``clear_backends`` calls).  Modern jax has the ``jax_num_cpu_devices``
    config; older jaxlibs only honor the
    ``--xla_force_host_platform_device_count`` XLA flag, which is read at
    backend init — so the fallback rewrites ``XLA_FLAGS``.  Returns a
    zero-arg callable restoring the previous setting (pair it with a
    backend rebuild, as ``__graft_entry__`` does).
    """
    try:
        prev = jax.config.jax_num_cpu_devices
        jax.config.update("jax_num_cpu_devices", n)
        return lambda: jax.config.update("jax_num_cpu_devices", prev)
    except AttributeError:
        import os
        import re

        prev_flags = os.environ.get("XLA_FLAGS")
        stripped = re.sub(
            r"--xla_force_host_platform_device_count=\S+", "", prev_flags or ""
        )
        os.environ["XLA_FLAGS"] = (
            stripped + f" --xla_force_host_platform_device_count={n}"
        ).strip()

        def restore():
            if prev_flags is None:
                os.environ.pop("XLA_FLAGS", None)
            else:
                os.environ["XLA_FLAGS"] = prev_flags

        return restore


def enable_cpu_cross_process_collectives() -> None:
    """Let the CPU backend run cross-process collectives (via gloo).

    On jaxlibs where the CPU client defaults to single-process-only,
    ``jax_cpu_collectives_implementation`` selects the gloo transport;
    must be set before ``jax.distributed.initialize``.  A no-op where the
    option is gone (newer jax enables CPU collectives by default) or the
    backend is not CPU-bound at init time.
    """
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # jax < 0.5: the pre-graduation home of the same API
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f=None, /, **kwargs):
        # The modern surface renamed check_rep -> check_vma; translate so
        # engines can be written against the current keyword only.
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(f, **kwargs)
