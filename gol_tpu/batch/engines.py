"""Batched multi-world evolvers: B independent boards, one compiled program.

The single-world tiers launch one compiled chunk per world per chunk —
fine when one world fills the chip, but BENCH_r05's device-fit
decomposition pins ~0.17–0.26 s of per-invocation overhead, so a 256²
board runs at a tiny fraction of the hardware's rate.  Stacking B worlds
on a leading ``worlds`` axis amortizes that overhead B-fold in exactly
the way batched inference serving does:

- **dense / bitpack** — ``jax.vmap`` over the existing single-world step
  functions (:mod:`gol_tpu.ops.stencil`, :mod:`gol_tpu.ops.bitlife`);
  the per-world programs are untouched, the batch axis is pure
  data-parallel width for the VPU.
- **pallas_bitpack** — ``jax.vmap`` over the fused kernel's evolve:
  JAX's Pallas batching rule lowers the vmap to an extra leading *grid
  dimension* on the kernel, so all B worlds ride one ``pallas_call``.
- **masked buckets** — worlds smaller than their bucket shape evolve
  under :func:`step_dense_masked` / :func:`step_packed_masked`: the
  torus wrap is taken at each world's true ``(h, w)`` via index
  arithmetic while the padding stays dead, so one compiled program per
  *bucket* serves any mix of world sizes (heights/widths ride in as
  dynamic ``int32[B]`` vectors — no recompile per shape).
- **mesh mode** — ``shard_map`` over a 1-D ``worlds`` device mesh: each
  device evolves its slice of the world axis with the single-device
  batched program.  Worlds are independent, so the sharded program
  contains **no collectives at all** — an invariant the static verifier
  pins (:mod:`gol_tpu.analysis.batchcheck`).

Every tier is pinned bit-identical per world to B sequential
single-world runs (tests/test_batch.py, tests/test_property.py), and
none of this touches the single-world engines — their jaxprs stay
byte-identical (the extended trace-identity pin).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gol_tpu import compat
from gol_tpu.ops import bitlife, stencil

WORLDS = "worlds"  # mesh axis name: the batch (world) axis

BATCH_ENGINES = ("auto", "dense", "bitpack", "pallas_bitpack")


def make_batch_mesh(num_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D device mesh over the ``worlds`` axis (world-axis sharding)."""
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, only {len(devices)} visible"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (WORLDS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """The canonical stack sharding: world axis split, board axes whole."""
    return NamedSharding(mesh, P(WORLDS, None, None))


# ---------------------------------------------------------------------------
# masked steps: the world's torus lives in the top-left (h, w) corner of a
# padded bucket board; wrap indices are taken at the true extent.
# ---------------------------------------------------------------------------


def step_dense_masked(board: jax.Array, h: jax.Array, w: jax.Array):
    """One generation of an ``h×w`` torus padded into ``board[H, W]``.

    ``h``/``w`` are traced scalars, so one compiled program serves every
    world size that fits the bucket.  Wrap neighbors come from gathers at
    ``(i±1) mod h`` / ``(j±1) mod w`` — for valid cells these only ever
    read valid cells, so the padding (masked back to 0 on the way out)
    can never leak into a world.  Bit-identical to
    :func:`gol_tpu.ops.stencil.step` on the cropped board.
    """
    H, W = board.shape
    ri = jnp.arange(H)
    ci = jnp.arange(W)
    up = jnp.where(ri == 0, h - 1, ri - 1)
    down = jnp.where(ri == h - 1, 0, jnp.minimum(ri + 1, H - 1))
    left = jnp.where(ci == 0, w - 1, ci - 1)
    right = jnp.where(ci == w - 1, 0, jnp.minimum(ci + 1, W - 1))
    rows3 = board[up] + board + board[down]
    total = rows3[:, left] + rows3 + rows3[:, right]
    nxt = stencil.life_rule(board, total - board)
    mask = (ri[:, None] < h) & (ci[None, :] < w)
    return jnp.where(mask, nxt, jnp.zeros_like(nxt))


def step_packed_masked(packed: jax.Array, h: jax.Array, nw: jax.Array):
    """Packed counterpart: ``h`` rows × ``nw`` words valid in ``[NH, NW]``.

    World widths must pack into whole 32-bit words (the packed tier's
    standing constraint), so the horizontal wrap is a word-ring at the
    true ``nw``: the west/east carry bits come from gathers at
    ``(j±1) mod nw``, exactly :func:`gol_tpu.ops.bitlife._west_east`
    with the roll taken at the world's width.  Padding words are forced
    back to 0 so they never feed a later generation.
    """
    NH, NW = packed.shape
    ri = jnp.arange(NH)
    wi = jnp.arange(NW)
    up = jnp.where(ri == 0, h - 1, ri - 1)
    down = jnp.where(ri == h - 1, 0, jnp.minimum(ri + 1, NH - 1))
    prev_i = jnp.where(wi == 0, nw - 1, wi - 1)
    next_i = jnp.where(wi == nw - 1, 0, jnp.minimum(wi + 1, NW - 1))
    prev_word = packed[:, prev_i]
    next_word = packed[:, next_i]
    west = (packed << 1) | (prev_word >> (bitlife.BITS - 1))
    east = (packed >> 1) | (next_word << (bitlife.BITS - 1))
    s0, s1 = bitlife._full_add(west, packed, east)
    out = bitlife._rule_from_row_sums(
        packed, (s0[up], s1[up]), (s0, s1), (s0[down], s1[down])
    )
    mask = (ri[:, None] < h) & (wi[None, :] < nw)
    return jnp.where(mask, out, jnp.zeros_like(out))


# ---------------------------------------------------------------------------
# per-tier batched chunk programs
# ---------------------------------------------------------------------------


def _dense_batch(steps: int):
    step = jax.vmap(stencil.step)

    def evolve(stack):
        return lax.fori_loop(0, steps, lambda _, s: step(s), stack)

    return evolve


def _dense_batch_masked(steps: int):
    step = jax.vmap(step_dense_masked)

    def evolve(stack, hs, ws):
        return lax.fori_loop(0, steps, lambda _, s: step(s, hs, ws), stack)

    return evolve


def _bitpack_batch(steps: int):
    pack = jax.vmap(bitlife.pack)
    unpack = jax.vmap(bitlife.unpack)
    step = jax.vmap(bitlife.step_packed)

    def evolve(stack):
        packed = pack(stack)
        packed = lax.fori_loop(0, steps, lambda _, p: step(p), packed)
        return unpack(packed)

    return evolve


def _bitpack_batch_masked(steps: int):
    pack = jax.vmap(bitlife.pack)
    unpack = jax.vmap(bitlife.unpack)
    step = jax.vmap(step_packed_masked)

    def evolve(stack, hs, ws):
        nws = ws // bitlife.BITS
        packed = pack(stack)
        packed = lax.fori_loop(0, steps, lambda _, p: step(p, hs, nws), packed)
        return unpack(packed)

    return evolve


def _pallas_batch(steps: int, tile_hint: int):
    from gol_tpu.ops import pallas_bitlife

    # vmap over the fused kernel: the Pallas batching rule adds a leading
    # grid dimension, so one pallas_call steps every world.
    return jax.vmap(lambda b: pallas_bitlife.evolve(b, steps, tile_hint))


@functools.lru_cache(maxsize=256)
def compiled_batch_evolver(
    engine: str,
    steps: int,
    masked: bool,
    tile_hint: int = 512,
    mesh: Optional[Mesh] = None,
):
    """Build + jit one bucket's batched chunk program.

    The call is ``fn(stack)`` (exact buckets) or ``fn(stack, hs, ws)``
    (masked buckets; ``hs``/``ws`` int32[B] true world extents).  The
    stack is donated (the double buffer); the extent vectors are not.
    With a ``worlds`` mesh the program is the shard_map form — same
    bodies per shard, no collectives.  lru_cached so repeated chunk
    sizes reuse one program object (the retrace contract every engine
    builder honors).
    """
    if engine == "dense":
        local = _dense_batch_masked(steps) if masked else _dense_batch(steps)
    elif engine == "bitpack":
        local = (
            _bitpack_batch_masked(steps) if masked else _bitpack_batch(steps)
        )
    elif engine == "pallas_bitpack":
        if masked:
            raise ValueError(
                "the batched Pallas tier has no masked form; masked "
                "buckets dispatch to the bitpack/dense masked programs "
                "(gol_tpu.batch.runtime.resolve_bucket_engine)"
            )
        local = _pallas_batch(steps, tile_hint)
    else:
        raise ValueError(
            f"unknown batch engine {engine!r}; expected one of "
            f"{BATCH_ENGINES[1:]}"
        )

    if mesh is not None:
        vec = P(WORLDS)
        in_specs = (P(WORLDS, None, None),) + ((vec, vec) if masked else ())
        local = compat.shard_map(
            local,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=P(WORLDS, None, None),
            check_vma=False,
        )
    return jax.jit(local, donate_argnums=0)
