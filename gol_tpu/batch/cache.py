"""XLA persistent compilation cache wiring (``--compile-cache DIR``).

Batched serving recompiles nothing in steady state: every bucket's chunk
program is AOT-compiled at warmup, and with a persistent cache directory
the *second process* skips XLA compilation entirely — the compile events
then report near-zero ``compile_s`` and the cache directory gains no new
entries (the batch-smoke gate asserts exactly that).  The cache is
keyed by XLA on the full (HLO, flags, backend) fingerprint, so it is
safe to share between runs and survives restarts — the compile-time
analog of the PR 4 resume path.

Entries land as ``*-cache`` files; :func:`cache_entries` counts them so
harnesses can assert hit/miss behavior without parsing JAX internals.
:class:`CompileCacheProbe` turns that countable signal into the
per-compile ``cache_hit``/``cache_key`` stamp on schema-v13 ``compile``
events (docs/OBSERVABILITY.md): snapshot the entry set before a
compile, diff after — a new entry means the compile MISSED and its
filename is the persistent key; an unchanged set means XLA read an
existing entry (hit).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple


def enable_compile_cache(directory: str) -> str:
    """Point JAX's persistent compilation cache at ``directory``.

    Also drops the minimum-compile-time/entry-size gates so the small
    chunk programs of CPU smoke runs are cached too — the production win
    is on TPU (seconds of XLA compile per bucket), but the *behavior*
    must be testable on the CPU backend.  Idempotent; returns the
    directory.
    """
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):  # knob absent on this jax
            pass
    try:
        # A compile that ran before the dir was configured latches the
        # cache as checked-and-disabled; reset so the next compile
        # re-reads the config.  No-op when nothing compiled yet.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        pass
    return directory


def cache_entries(directory: str) -> List[str]:
    """The cache's entry files (sorted) — the countable hit/miss signal."""
    if not os.path.isdir(directory):
        return []
    return sorted(f for f in os.listdir(directory) if f.endswith("-cache"))


def active_cache_dir() -> Optional[str]:
    """The configured persistent-cache directory, or None (cache off)."""
    import jax

    try:
        return jax.config.jax_compilation_cache_dir or None
    except AttributeError:  # pragma: no cover - ancient jax
        return None


class CompileCacheProbe:
    """Hit/miss verdict for exactly one compile.

    Construct immediately before ``lowered.compile()``, call
    :meth:`resolve` immediately after: ``(cache_hit, cache_key)`` where
    ``cache_hit`` is None when no cache directory is configured (the
    compile event then omits the stamp entirely), False with the new
    entry's filename as the key when the compile wrote an entry, and
    True (key None — XLA does not say which entry it read; the key is
    stamped by the miss that wrote it) when the entry set is unchanged.
    Entirely filesystem-side: zero effect on the compiled program, so
    probe on/off is trace-identity trivial.
    """

    def __init__(self) -> None:
        self.directory = active_cache_dir()
        self._before = (
            None
            if self.directory is None
            else set(cache_entries(self.directory))
        )

    def resolve(self) -> Tuple[Optional[bool], Optional[str]]:
        if self.directory is None:
            return None, None
        new = [
            e
            for e in cache_entries(self.directory)
            if e not in self._before
        ]
        if new:
            return False, new[0]
        return True, None
