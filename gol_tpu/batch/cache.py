"""XLA persistent compilation cache wiring (``--compile-cache DIR``).

Batched serving recompiles nothing in steady state: every bucket's chunk
program is AOT-compiled at warmup, and with a persistent cache directory
the *second process* skips XLA compilation entirely — the compile events
then report near-zero ``compile_s`` and the cache directory gains no new
entries (the batch-smoke gate asserts exactly that).  The cache is
keyed by XLA on the full (HLO, flags, backend) fingerprint, so it is
safe to share between runs and survives restarts — the compile-time
analog of the PR 4 resume path.

Entries land as ``*-cache`` files; :func:`cache_entries` counts them so
harnesses can assert hit/miss behavior without parsing JAX internals.
"""

from __future__ import annotations

import os
from typing import List


def enable_compile_cache(directory: str) -> str:
    """Point JAX's persistent compilation cache at ``directory``.

    Also drops the minimum-compile-time/entry-size gates so the small
    chunk programs of CPU smoke runs are cached too — the production win
    is on TPU (seconds of XLA compile per bucket), but the *behavior*
    must be testable on the CPU backend.  Idempotent; returns the
    directory.
    """
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    for knob, value in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):  # knob absent on this jax
            pass
    try:
        # A compile that ran before the dir was configured latches the
        # cache as checked-and-disabled; reset so the next compile
        # re-reads the config.  No-op when nothing compiled yet.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except (ImportError, AttributeError):  # pragma: no cover - old jax
        pass
    return directory


def cache_entries(directory: str) -> List[str]:
    """The cache's entry files (sorted) — the countable hit/miss signal."""
    if not os.path.isdir(directory):
        return []
    return sorted(f for f in os.listdir(directory) if f.endswith("-cache"))
