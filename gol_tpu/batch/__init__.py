"""Batched multi-world engine: B independent boards, one compiled launch.

The serving-scale subsystem (docs/BATCHING.md): instead of one compiled
program per world — which pins every small board under the ~0.2 s
per-invocation launch overhead BENCH_r05 measured — B independent worlds
stack on a leading ``worlds`` axis and step together:

- :mod:`gol_tpu.batch.engines` — the batched tiers (vmap on dense /
  bitpack, an extra grid dimension on the fused Pallas kernel, masked
  padded steps for mixed-size buckets, shard_map world-axis sharding);
- :mod:`gol_tpu.batch.runtime` — :class:`GolBatchRuntime`: size
  bucketing, AOT warmup, the chunked loop with checkpoint/preempt/
  telemetry reuse;
- :mod:`gol_tpu.batch.cache` — XLA persistent compilation cache wiring
  (``--compile-cache DIR``), so repeat invocations skip XLA entirely.

CLI surface: ``python -m gol_tpu ... --batch B`` (see ``--batch-sizes``
and ``--compile-cache`` in :mod:`gol_tpu.cli`).
"""

from gol_tpu.batch.cache import cache_entries, enable_compile_cache  # noqa: F401
from gol_tpu.batch.engines import (  # noqa: F401
    BATCH_ENGINES,
    WORLDS,
    batch_sharding,
    compiled_batch_evolver,
    make_batch_mesh,
)
from gol_tpu.batch.runtime import (  # noqa: F401
    Bucket,
    GolBatchRuntime,
    bucket_shape,
    bucketize,
    resolve_bucket_engine,
)
