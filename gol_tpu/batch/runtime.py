"""GolBatchRuntime: the batched multi-world serving loop.

The batched analog of :class:`gol_tpu.runtime.GolRuntime`: it owns B
independent worlds, groups them into size buckets (padded + masked, so a
mixed-size request set compiles **one program per bucket, not per
shape**), AOT-compiles one chunk program per (bucket, chunk size) —
optionally against the XLA persistent compilation cache so repeat
invocations skip compilation entirely — and steps every bucket inside
the same chunked loop the single-world runtime uses: chunk schedule from
:func:`gol_tpu.runtime.chunk_schedule`, fingerprinted checkpoints
(batched format, ``kind='batch'`` on the PR 4 validated-resume path),
cooperative preemption at chunk boundaries, and schema-v4 telemetry
(``chunk`` events carry a ``batch`` block: bucket shape, B, per-world
throughput).

Bit-exactness contract: the batched final grids are pinned bit-identical
per world to B sequential single-world runs, for every tier × mesh
(tests/test_batch.py, tests/test_property.py).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from gol_tpu.batch import engines as batch_engines
from gol_tpu.models.state import CELL_DTYPE
from gol_tpu.ops import bitlife
from gol_tpu.runtime import chunk_schedule
from gol_tpu.utils import checkpoint as ckpt_mod
from gol_tpu.utils.timing import RunReport, Stopwatch, force_ready


def bucket_shape(h: int, w: int, quantum: int) -> Tuple[int, int]:
    """Round a world's extents up to the bucket quantum."""
    if quantum < 1:
        raise ValueError(f"bucket quantum must be >= 1, got {quantum}")
    up = lambda x: -(-x // quantum) * quantum  # noqa: E731
    return (up(h), up(w))


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One padded shape class: the unit of compilation and dispatch."""

    shape: Tuple[int, int]  # padded (H, W) every member world fits
    indices: Tuple[int, ...]  # world ids, in submission order
    masked: bool  # any member smaller than the bucket shape?

    @property
    def batch(self) -> int:
        return len(self.indices)


def stack_worlds(
    boards: Sequence[np.ndarray], shape: Tuple[int, int]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad per-world boards into one host ``[B, H, W]`` stack + the
    true-extent vectors the masked programs take.  Shared by the batch
    runtime's bucket stacks and the serve scheduler's slot stacks
    (``gol_tpu/serve/scheduler.py``), so both tiers pad identically —
    padding cells are dead zeros, which B3/S23 keeps dead, so the masked
    programs are bit-exact regardless of the padding."""
    H, W = shape
    stack = np.zeros((len(boards), H, W), dtype=np.uint8)
    hs = np.empty(len(boards), np.int32)
    ws = np.empty(len(boards), np.int32)
    for k, b in enumerate(boards):
        stack[k, : b.shape[0], : b.shape[1]] = b
        hs[k], ws[k] = b.shape
    return stack, hs, ws


def bucketize(
    shapes: Sequence[Tuple[int, int]], quantum: int
) -> List[Bucket]:
    """Group world shapes into padded buckets (stable within a bucket)."""
    groups: dict = {}
    for i, (h, w) in enumerate(shapes):
        groups.setdefault(bucket_shape(h, w, quantum), []).append(i)
    out = []
    for shape in sorted(groups):
        idx = tuple(groups[shape])
        masked = any(tuple(shapes[i]) != shape for i in idx)
        out.append(Bucket(shape=shape, indices=idx, masked=masked))
    return out


def resolve_bucket_engine(
    engine: str, bucket: Bucket, shapes: Sequence[Tuple[int, int]]
) -> str:
    """Pick the tier one bucket actually runs.

    Mirrors the single-world auto resolution: packed when every member
    width packs into whole 32-bit words, the fused Pallas kernel on TPU
    when the bucket fills whole lane tiles — with the one batched twist
    that masked buckets have no Pallas form and fall back to the masked
    XLA packed program (bit-exact either way; the fallback is a
    performance choice, never a semantics one).
    """
    H, W = bucket.shape
    packable = W % bitlife.BITS == 0 and all(
        shapes[i][1] % bitlife.BITS == 0 for i in bucket.indices
    )
    if engine == "dense":
        return "dense"
    if engine == "bitpack":
        if not packable:
            raise ValueError(
                f"engine 'bitpack' needs every world width in bucket "
                f"{bucket.shape} to pack into {bitlife.BITS}-bit words"
            )
        return "bitpack"
    if engine == "pallas_bitpack":
        if bucket.masked or not packable:
            # Documented fallback: the fused kernel has no masked form.
            return "bitpack" if packable else "dense"
        return "pallas_bitpack"
    # auto
    if not packable:
        return "dense"
    if (
        not bucket.masked
        and jax.default_backend() == "tpu"
    ):
        from gol_tpu.ops import pallas_bitlife

        if (
            W % (pallas_bitlife._LANE * bitlife.BITS) == 0
            and H % pallas_bitlife._ALIGN == 0
        ):
            return "pallas_bitpack"
    return "bitpack"


@dataclasses.dataclass
class GolBatchRuntime:
    """Batched multi-world runtime (see module docstring).

    ``worlds`` are dense uint8 0/1 grids of arbitrary (per-world)
    shapes.  ``mesh`` (a 1-D ``worlds`` mesh from
    :func:`gol_tpu.batch.engines.make_batch_mesh`) shards each bucket's
    world axis across devices when the bucket's B divides the device
    count's requirement (B % devices == 0); buckets that don't divide run
    unsharded — a placement choice, never a semantics one.
    """

    worlds: Sequence[np.ndarray]
    engine: str = "auto"
    mesh: Optional[Mesh] = None
    bucket_quantum: int = 64
    tile_hint: int = 512
    checkpoint_every: int = 0
    checkpoint_dir: Optional[str] = None
    keep_snapshots: int = 0
    telemetry_dir: Optional[str] = None
    run_id: Optional[str] = None
    compile_cache: Optional[str] = None
    restart_attempt: int = 0
    resume_info: Optional[dict] = None
    # Live metrics endpoint (--metrics-port; docs/OBSERVABILITY.md) —
    # same contract as GolRuntime: Prometheus text fed by the event
    # stream, requires telemetry.
    metrics_port: Optional[int] = None
    # Guarded batch runs (docs/RESILIENCE.md "Guard coverage"): audit
    # every world of every bucket each ``guard_every`` generations (one
    # vmapped fused reduce per bucket) and roll back ONLY the corrupted
    # world's bucket to its last audited-good stack — the other buckets
    # never replay.  ``guard_redundant`` recomputes each audited chunk
    # on the bucket's counterpart engine (dense checks packed buckets
    # and vice versa) and compares per-world fingerprints — the in-range
    # SDC detector, same contract as the single-world guard.
    guard_every: int = 0
    guard_max_restores: int = 3
    guard_redundant: bool = False
    guard_redundant_every: int = 1
    # Per-world completion callback ``(world_index, board, generation)``,
    # invoked for every world at the final host crop — the hook the serve
    # tier's continuous-batching scheduler generalizes into refilling a
    # freed slot the moment a world finishes (gol_tpu/serve/scheduler.py;
    # in a one-shot batch run all worlds share the final generation).
    on_world_complete: Optional[Callable[[int, np.ndarray, int], None]] = None

    def __post_init__(self) -> None:
        if self.engine == "ooc":
            raise ValueError(
                "engine 'ooc' streams one bigger-than-device board and "
                "has no batched tier; supported batch engines: "
                f"{batch_engines.BATCH_ENGINES}"
            )
        if self.engine not in batch_engines.BATCH_ENGINES:
            raise ValueError(
                f"unknown engine {self.engine!r}; expected "
                f"{batch_engines.BATCH_ENGINES}"
            )
        if not self.worlds:
            raise ValueError("batch runtime needs at least one world")
        boards = []
        for i, b in enumerate(self.worlds):
            b = np.asarray(b, np.uint8)
            if b.ndim != 2 or not b.size:
                raise ValueError(
                    f"world {i} must be a non-empty 2-D grid, got shape "
                    f"{b.shape}"
                )
            boards.append(b)
        self._boards: List[np.ndarray] = boards
        self._shapes = [b.shape for b in boards]
        if self.checkpoint_every and not self.checkpoint_dir:
            self.checkpoint_dir = "checkpoints"
        if self.keep_snapshots < 0:
            raise ValueError(
                f"keep_snapshots must be >= 0, got {self.keep_snapshots}"
            )
        if self.compile_cache:
            from gol_tpu.batch import cache as cache_mod

            cache_mod.enable_compile_cache(self.compile_cache)
        self.buckets: List[Bucket] = bucketize(
            self._shapes, self.bucket_quantum
        )
        self._engines = [
            resolve_bucket_engine(self.engine, bk, self._shapes)
            for bk in self.buckets
        ]
        if self.guard_every < 0:
            raise ValueError(
                f"guard_every must be >= 0, got {self.guard_every} "
                "(0 disables the guard)"
            )
        if self.guard_redundant and self.guard_every <= 0:
            raise ValueError(
                "guard_redundant audits chunks, so it requires "
                "guard_every > 0"
            )
        if self.guard_redundant_every != 1 and not self.guard_redundant:
            raise ValueError(
                "guard_redundant_every samples the redundancy audit, so "
                "it requires guard_redundant"
            )
        if self.guard_redundant:
            # Fail at construction, not mid-run: every bucket needs a
            # second bit-exact engine for the cross-engine recompute.
            for bucket_id in range(len(self.buckets)):
                self._checker_engine(bucket_id)
        # The last guarded run's report (None for unguarded runs).
        self.last_guard = None
        self.generation = 0
        self._ckpt_writer = None
        self._resume_source: Optional[str] = None
        # Checkpoint containment + live-events handle, same contract as
        # GolRuntime (docs/RESILIENCE.md "Retry and shed").
        self._ckpt_shed = False
        self._live_events = None
        if self.metrics_port is not None and not self.telemetry_dir:
            raise ValueError(
                "metrics_port serves the in-process event stream, so it "
                "requires telemetry_dir (--telemetry)"
            )
        self.last_metrics = None
        self._metrics_server = None

    # -- placement ---------------------------------------------------------
    def _bucket_mesh(self, bucket: Bucket) -> Optional[Mesh]:
        """The mesh a bucket shards over, or None (unsharded)."""
        if self.mesh is None:
            return None
        n = self.mesh.devices.size
        return self.mesh if bucket.batch % n == 0 else None

    def _stack(self, bucket: Bucket):
        """The bucket's padded device stack + true-extent vectors."""
        stack, hs, ws = stack_worlds(
            [self._boards[i] for i in bucket.indices], bucket.shape
        )
        mesh = self._bucket_mesh(bucket)
        if mesh is not None:
            sharding = batch_engines.batch_sharding(mesh)
            dev_stack = jax.device_put(stack, sharding)
            vec = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(batch_engines.WORLDS)
            )
            return dev_stack, jax.device_put(hs, vec), jax.device_put(ws, vec)
        return jax.device_put(stack), jax.device_put(hs), jax.device_put(ws)

    def _unstack(self, bucket: Bucket, stack) -> None:
        """Crop a stepped stack back into the per-world host boards."""
        host = np.asarray(stack)
        for k, i in enumerate(bucket.indices):
            h, w = self._shapes[i]
            self._boards[i] = host[k, :h, :w]

    # -- compile -----------------------------------------------------------
    def _evolver(self, bucket_id: int, take: int):
        """(jitted_fn, masked) for one bucket's chunk program."""
        bucket = self.buckets[bucket_id]
        name = self._engines[bucket_id]
        masked = bucket.masked
        fn = batch_engines.compiled_batch_evolver(
            name,
            take,
            masked,
            self.tile_hint,
            self._bucket_mesh(bucket),
        )
        return fn, masked

    def _checker_engine(self, bucket_id: int) -> str:
        """The redundant audit's second bit-exact engine for one bucket.

        Mirrors ``guard._checker_runtime``: dense buckets check on the
        bit-packed program (requires every member width to pack into
        whole words), packed/Pallas buckets check on dense — two
        independent programs a random flip cannot reproduce across.
        """
        bucket = self.buckets[bucket_id]
        if self._engines[bucket_id] != "dense":
            return "dense"
        packable = bucket.shape[1] % bitlife.BITS == 0 and all(
            self._shapes[i][1] % bitlife.BITS == 0 for i in bucket.indices
        )
        if not packable:
            raise ValueError(
                "the redundant audit needs a second engine, and the only "
                f"check for a dense bucket is bit-packed: bucket "
                f"{bucket.shape} has a world width that does not pack "
                f"into {bitlife.BITS}-bit words"
            )
        return "bitpack"

    def _checker_evolver(self, bucket_id: int, take: int):
        """(compiled, masked) — the checker's chunk program for one
        bucket (same call convention as the primary evolver)."""
        bucket = self.buckets[bucket_id]
        fn = batch_engines.compiled_batch_evolver(
            self._checker_engine(bucket_id),
            take,
            bucket.masked,
            self.tile_hint,
            self._bucket_mesh(bucket),
        )
        return fn, bucket.masked

    def compile_evolvers(self, schedule, events=None) -> dict:
        """AOT-compile one program per (bucket, distinct chunk size).

        Lowered from ShapeDtypeStructs — the warmup never steps a board —
        and recorded as ``compile`` telemetry events carrying the bucket
        block, so a persistent-cache hit is visible as a near-zero
        ``compile_s`` on the second invocation.  Returns
        ``{(bucket_id, take): (compiled, masked)}``.
        """
        import time as time_mod

        from gol_tpu import telemetry as telemetry_mod
        from gol_tpu.batch import cache as cache_mod

        evolvers = {}
        for bucket_id, bucket in enumerate(self.buckets):
            H, W = bucket.shape
            mesh = self._bucket_mesh(bucket)
            if mesh is not None:
                stack_spec = jax.ShapeDtypeStruct(
                    (bucket.batch, H, W),
                    CELL_DTYPE,
                    sharding=batch_engines.batch_sharding(mesh),
                )
                vec_sharding = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec(batch_engines.WORLDS)
                )
                vec_spec = jax.ShapeDtypeStruct(
                    (bucket.batch,), np.int32, sharding=vec_sharding
                )
            else:
                stack_spec = jax.ShapeDtypeStruct(
                    (bucket.batch, H, W), CELL_DTYPE
                )
                vec_spec = jax.ShapeDtypeStruct((bucket.batch,), np.int32)
            for take in sorted(set(schedule)):
                fn, masked = self._evolver(bucket_id, take)
                args = (stack_spec, vec_spec, vec_spec) if masked else (
                    stack_spec,
                )
                probe = cache_mod.CompileCacheProbe()
                with telemetry_mod.trace_annotation(
                    f"gol.batch.compile.{bucket_id}.{take}"
                ):
                    t0 = time_mod.perf_counter()
                    lowered = fn.lower(*args)
                    t1 = time_mod.perf_counter()
                    compiled = lowered.compile()
                    t2 = time_mod.perf_counter()
                evolvers[(bucket_id, take)] = (compiled, masked)
                if events is not None:
                    from gol_tpu.telemetry import stats as stats_mod

                    cache_hit, cache_key = probe.resolve()
                    events.compile_event(
                        take,
                        t1 - t0,
                        t2 - t1,
                        memory=stats_mod.compiled_memory(compiled),
                        batch=self._batch_block(bucket_id),
                        cache_hit=cache_hit,
                        cache_key=cache_key,
                    )
        return evolvers

    # -- telemetry ---------------------------------------------------------
    def _batch_block(self, bucket_id: int) -> dict:
        """The schema-v4 ``batch`` block for one bucket's events."""
        bucket = self.buckets[bucket_id]
        return dict(
            bucket=list(bucket.shape),
            B=bucket.batch,
            masked=bucket.masked,
            engine=self._engines[bucket_id],
        )

    def open_event_log(self):
        """A fresh EventLog with the batch run header, or None."""
        if not self.telemetry_dir:
            return None
        from gol_tpu import telemetry as telemetry_mod

        events = telemetry_mod.EventLog(self.telemetry_dir, run_id=self.run_id)
        # Arm the black box: dumps land next to the stream (unhandled
        # exception + fault-plane crash.exit triggers).
        telemetry_mod.blackbox.install(
            self.telemetry_dir,
            run_id=events.run_id,
            process_index=events.process_index,
        )
        if self.metrics_port is not None:
            # Single-process by CLI validation; attach before the header
            # emits so the registry sees every record.
            from gol_tpu.telemetry import metrics as metrics_mod

            self.last_metrics, self._metrics_server = (
                metrics_mod.serve_event_metrics(events, self.metrics_port)
            )
        events.run_header(
            dict(
                driver="batch",
                engine=self.engine,
                num_worlds=len(self._boards),
                buckets=[
                    dict(
                        shape=list(bk.shape),
                        B=bk.batch,
                        masked=bk.masked,
                        engine=self._engines[i],
                        sharded=self._bucket_mesh(bk) is not None,
                    )
                    for i, bk in enumerate(self.buckets)
                ],
                bucket_quantum=self.bucket_quantum,
                compile_cache=self.compile_cache,
                checkpoint_every=self.checkpoint_every,
            )
        )
        if self.restart_attempt > 0:
            events.restart_event(self.restart_attempt)
        if self.resume_info is not None and self.resume_info.get("path"):
            events.resume_event(
                generation=self.resume_info["generation"],
                path=self.resume_info["path"],
                fallback=bool(self.resume_info.get("fallback")),
                skipped=self.resume_info.get("skipped") or [],
            )
        return events

    # -- persistence --------------------------------------------------------
    def _world_cells(self) -> int:
        return sum(h * w for h, w in self._shapes)

    def _save_snapshot(self) -> None:
        from gol_tpu.resilience import degrade as degrade_mod
        from gol_tpu.utils.guard import fingerprint_np

        if self._ckpt_shed:
            return
        path = ckpt_mod.batch_checkpoint_path(
            self.checkpoint_dir, self.generation
        )
        boards = [b.copy() for b in self._boards]
        generation = self.generation
        fps = [fingerprint_np(b) for b in boards]

        def write():
            ok = degrade_mod.write_with_retry(
                lambda: ckpt_mod.save_batch(
                    path, boards, generation, fingerprints=fps
                ),
                generation=generation,
                shed_telemetry=self._shed_telemetry,
            )
            if not ok:
                self._ckpt_shed = True
                return
            if self.keep_snapshots > 0:
                from gol_tpu.resilience import retention

                retention.gc_snapshots(
                    self.checkpoint_dir,
                    self.keep_snapshots,
                    kind="batch",
                    protect=(self._resume_source,),
                )

        if self._ckpt_writer is not None:
            self._ckpt_writer.submit(write)
        else:
            write()

    def _shed_telemetry(self, reason: str) -> None:
        """Disk-full first sacrifice (docs/RESILIENCE.md): shed the
        event stream before giving up on checkpoints."""
        events = self._live_events
        if events is not None:
            events.request_shed("telemetry", reason)

    def _load_snapshot(self, resume: str) -> None:
        snap = ckpt_mod.load_batch(resume)
        if len(snap.boards) != len(self._boards):
            raise ValueError(
                f"batch checkpoint has {len(snap.boards)} worlds, run "
                f"configured for {len(self._boards)}"
            )
        for i, b in enumerate(snap.boards):
            if b.shape != self._shapes[i]:
                raise ValueError(
                    f"batch checkpoint world {i} is {b.shape}, run "
                    f"configured for {self._shapes[i]}"
                )
            self._boards[i] = b
        self.generation = snap.generation
        self._resume_source = resume

    def _guarded_bucket_chunk(
        self, i, take, bucket_id, stacks, last_good, evolvers, checkers,
        events, sc, sw, plan_on,
    ) -> None:
        """Step + audit + (rollback-replay) one bucket's chunk.

        The batched translation of :func:`gol_tpu.utils.guard.
        guarded_loop`'s body: the candidate stack is audited per world
        in one vmapped reduce; any corrupted world rolls THIS bucket
        back to its last audited-good stack (fingerprint-verified, like
        the single-world rollback base) and replays — sibling buckets
        never re-execute.  The redundancy audit recomputes the chunk
        from the same base on the bucket's counterpart engine and
        compares per-world fingerprints.  More than
        ``guard_max_restores`` consecutive failures raise
        :class:`~gol_tpu.utils.guard.GuardError` naming bucket + world.
        """
        import dataclasses as dc
        import time as time_mod

        from gol_tpu import telemetry as telemetry_mod
        from gol_tpu.resilience import faults as faults_mod
        from gol_tpu.utils import guard as guard_mod

        bucket = self.buckets[bucket_id]
        compiled, masked = evolvers[(bucket_id, take)]
        guard = self.last_guard
        gen_after = self.generation + take
        sampled = i % self.guard_redundant_every == 0
        restores = 0
        while True:
            stack, hs, ws = stacks[bucket_id]
            with telemetry_mod.step_annotation("gol.batch.guard.chunk", i):
                with sw.phase("total"):
                    t0 = time_mod.perf_counter()
                    candidate = (
                        compiled(stack, hs, ws) if masked else compiled(stack)
                    )
                    t1 = time_mod.perf_counter()
                    force_ready(candidate)
                    dt = time_mod.perf_counter() - t0
            if events is not None:
                sc.add("dispatch", t1 - t0)
                sc.add("ready", dt - (t1 - t0))
                cells = sum(
                    self._shapes[j][0] * self._shapes[j][1]
                    for j in bucket.indices
                )
                block = self._batch_block(bucket_id)
                block["per_world_updates_per_sec"] = (
                    cells * take / dt / bucket.batch if dt > 0 else 0.0
                )
                spans = sc.take()
                with sc.span("telemetry"):
                    events.chunk_event(
                        i, take, gen_after, dt, cells * take, None,
                        batch=block, spans=spans,
                        restores_this_chunk=restores,
                    )
            if plan_on:
                candidate = faults_mod.apply_board_faults(
                    candidate, gen_after, world_ids=bucket.indices
                )
            with sw.phase("audit"):
                audits = guard_mod.audit_worlds(candidate, gen_after)
            if checkers is not None and sampled and all(
                a.ok for a in audits
            ):
                # Cross-engine recompute from the same base: two
                # independent programs can only agree if neither run
                # was corrupted (the in-range-flip oracle).
                checker, cmasked = checkers[(bucket_id, take)]
                with sw.phase("redundant"):
                    base = guard_mod._device_copy(last_good[bucket_id][0])
                    reference = (
                        checker(base, hs, ws) if cmasked else checker(base)
                    )
                    ref_audits = guard_mod.audit_worlds(reference, gen_after)
                audits = [
                    dc.replace(
                        a,
                        ok=r.fingerprint == a.fingerprint,
                        redundant_fingerprint=r.fingerprint,
                    )
                    for a, r in zip(audits, ref_audits)
                ]
            guard.audits.extend(audits)
            if events is not None:
                with sc.span("telemetry"):
                    for k, a in enumerate(audits):
                        events.guard_event(
                            a, world=bucket.indices[k], bucket=bucket_id
                        )
            bad = [k for k, a in enumerate(audits) if not a.ok]
            if not bad:
                stacks[bucket_id] = (candidate, hs, ws)
                with sw.phase("snapshot"):
                    last_good[bucket_id] = (
                        guard_mod._device_copy(candidate),
                        [a.fingerprint for a in audits],
                    )
                return
            guard.failures += 1
            restores += 1
            if restores > self.guard_max_restores:
                a = audits[bad[0]]
                raise guard_mod.GuardError(
                    f"audit failed at generation {gen_after} for world "
                    f"{bucket.indices[bad[0]]} (bucket {bucket_id}, "
                    f"max cell {a.max_cell}, fingerprint "
                    f"{a.fingerprint:#010x}) and the restore budget "
                    f"({self.guard_max_restores}) is exhausted — "
                    "persistent fault"
                )
            guard.restores += 1
            with sw.phase("restore"):
                base_stack, base_fps = last_good[bucket_id]
                replay = guard_mod._device_copy(base_stack)
                base_audits = guard_mod.audit_worlds(
                    replay, self.generation
                )
                if [a.fingerprint for a in base_audits] != base_fps:
                    raise guard_mod.GuardError(
                        f"the rollback base of bucket {bucket_id} is "
                        f"itself corrupt at generation {self.generation}; "
                        "in-run recovery is impossible — resume from the "
                        "last checkpoint"
                    )
                stacks[bucket_id] = (replay, hs, ws)

    # -- main entry ----------------------------------------------------------
    def run(
        self, iterations: int, resume: Optional[str] = None
    ) -> Tuple[RunReport, List[np.ndarray]]:
        """Step every world ``iterations`` generations; return the worlds.

        Mirrors :meth:`gol_tpu.runtime.GolRuntime.run` phase for phase:
        init / compile / chunked total (device execution only, fenced) /
        checkpoint, with the preemption poll at chunk boundaries and the
        async snapshot writer overlapping checkpoint I/O.

        With ``guard_every`` set the loop is the guarded form: every
        bucket's chunk is audited per world (vmapped fused reduce), a
        corrupted world rolls back ONLY its bucket to the last
        audited-good stack and replays under the restore budget, and
        only audited boards ever reach a checkpoint.  ``last_guard``
        holds the :class:`~gol_tpu.utils.guard.GuardReport`.
        """
        import time as time_mod

        from gol_tpu import resilience
        from gol_tpu import telemetry as telemetry_mod
        from gol_tpu.resilience import degrade as degrade_mod
        from gol_tpu.resilience import faults as faults_mod

        plan_on = faults_mod.active() is not None
        self._ckpt_shed = False
        sw = Stopwatch()
        with sw.phase("init"):
            if resume:
                self._load_snapshot(resume)
            stacks = {}
            for bucket_id, bucket in enumerate(self.buckets):
                stacks[bucket_id] = self._stack(bucket)

        interval = (
            self.guard_every
            if self.guard_every > 0
            else (
                self.checkpoint_every
                if self.checkpoint_every > 0
                else iterations
            )
        )
        schedule = chunk_schedule(iterations, interval)
        events = self.open_event_log()
        self._live_events = events
        # Span attribution (schema v6): with several buckets per chunk
        # index, each bucket's event carries its own dispatch/ready and
        # the clock's accumulated boundary phases drain into whichever
        # event is emitted next — aggregate per-phase totals stay exact.
        sc = telemetry_mod.SpanClock() if events is not None else None

        def _drain_plane():
            if events is None:
                return
            for f in faults_mod.drain_fired():
                events.fault_event(**f)
            for d in degrade_mod.drain_reports():
                events.degraded_event(**d)
        try:
            with sw.phase("compile"):
                evolvers = self.compile_evolvers(schedule, events)
                checkers = None
                if self.guard_redundant:
                    checkers = {
                        (bucket_id, take): self._checker_evolver(
                            bucket_id, take
                        )
                        for bucket_id in range(len(self.buckets))
                        for take in sorted(set(schedule))
                    }
                for stack, _, _ in stacks.values():
                    force_ready(stack)

            writer = None
            if self.checkpoint_every > 0:
                writer = ckpt_mod.AsyncSnapshotWriter()
            self._ckpt_writer = writer
            guarded = self.guard_every > 0
            if guarded:
                from gol_tpu.utils import guard as guard_mod

                self.last_guard = guard_mod.GuardReport()
                # Rollback bases: one audited-good device stack + its
                # per-world fingerprints per bucket, resident like the
                # single-world guard's last_good board.
                last_good = {}
                for bucket_id, (stack, _, _) in stacks.items():
                    audits0 = guard_mod.audit_worlds(
                        stack, self.generation
                    )
                    last_good[bucket_id] = (
                        guard_mod._device_copy(stack),
                        [a.fingerprint for a in audits0],
                    )
            next_ckpt = (
                self.generation + self.checkpoint_every
                if guarded and self.checkpoint_every > 0
                else None
            )
            try:
                with telemetry_mod.trace_annotation("gol.batch.evolve"):
                    for i, take in enumerate(schedule):
                        with telemetry_mod.step_annotation("gol.batch.chunk", i):
                            for bucket_id, bucket in enumerate(self.buckets):
                                if guarded:
                                    self._guarded_bucket_chunk(
                                        i, take, bucket_id, stacks,
                                        last_good, evolvers, checkers,
                                        events, sc, sw, plan_on,
                                    )
                                    continue
                                compiled, masked = evolvers[(bucket_id, take)]
                                stack, hs, ws = stacks[bucket_id]
                                with sw.phase("total"):
                                    t0 = time_mod.perf_counter()
                                    if masked:
                                        stack = compiled(stack, hs, ws)
                                    else:
                                        stack = compiled(stack)
                                    t1 = time_mod.perf_counter()
                                    force_ready(stack)
                                    dt = time_mod.perf_counter() - t0
                                if plan_on:
                                    # Un-audited SDC injection: the
                                    # corruption this path must NOT
                                    # catch (guard-coverage teeth).
                                    stack = faults_mod.apply_board_faults(
                                        stack,
                                        self.generation + take,
                                        world_ids=bucket.indices,
                                    )
                                stacks[bucket_id] = (stack, hs, ws)
                                if events is not None:
                                    sc.add("dispatch", t1 - t0)
                                    sc.add("ready", dt - (t1 - t0))
                                    cells = sum(
                                        self._shapes[j][0] * self._shapes[j][1]
                                        for j in bucket.indices
                                    )
                                    block = self._batch_block(bucket_id)
                                    block["per_world_updates_per_sec"] = (
                                        cells * take / dt / bucket.batch
                                        if dt > 0
                                        else 0.0
                                    )
                                    spans = sc.take()
                                    with sc.span("telemetry"):
                                        events.chunk_event(
                                            i,
                                            take,
                                            self.generation + take,
                                            dt,
                                            cells * take,
                                            None,
                                            batch=block,
                                            spans=spans,
                                        )
                        self.generation += take
                        due = (
                            next_ckpt is not None
                            and self.generation >= next_ckpt
                        )
                        if due:
                            next_ckpt = (
                                self.generation + self.checkpoint_every
                            )
                        if (
                            self.checkpoint_every > 0
                            and not self._ckpt_shed
                            and (due or not guarded)
                        ):
                            with sw.phase("init"):
                                t0 = time_mod.perf_counter()
                                # Host crop of every stepped stack: the
                                # donation fence (the next chunk consumes
                                # the device buffers), outside 'total'.
                                for bucket_id, bucket in enumerate(
                                    self.buckets
                                ):
                                    self._unstack(
                                        bucket, stacks[bucket_id][0]
                                    )
                                    # The donated device stack survives
                                    # the fetch; rebuilding from host
                                    # would double-copy.
                                if sc is not None:
                                    sc.add(
                                        "host_fetch",
                                        time_mod.perf_counter() - t0,
                                    )
                            with telemetry_mod.trace_annotation(
                                "gol.checkpoint.save"
                            ):
                                with sw.phase("checkpoint"):
                                    t0 = time_mod.perf_counter()
                                    self._save_snapshot()
                                    dt = time_mod.perf_counter() - t0
                            if sc is not None:
                                sc.add("checkpoint", dt)
                            if events is not None:
                                with sc.span("telemetry"):
                                    events.checkpoint_event(
                                        self.generation,
                                        dt,
                                        self._world_cells(),
                                        overlapped=writer is not None,
                                    )
                        if plan_on:
                            faults_mod.crash_or_stall(self.generation)
                        _drain_plane()
                        if i < len(schedule) - 1:
                            if sc is None:
                                preempt_now = (
                                    resilience.agreed_preempt_requested()
                                )
                            else:
                                with sc.span("preempt_poll"):
                                    preempt_now = (
                                        resilience.agreed_preempt_requested()
                                    )
                            if preempt_now:
                                checkpointed = (
                                    self.checkpoint_every > 0
                                    and not self._ckpt_shed
                                )
                                if checkpointed and guarded and not due:
                                    # Guarded cadence: this boundary has
                                    # no snapshot yet — write one from
                                    # the audited stacks before exiting.
                                    with sw.phase("init"):
                                        for bid, bk in enumerate(
                                            self.buckets
                                        ):
                                            self._unstack(
                                                bk, stacks[bid][0]
                                            )
                                    with sw.phase("checkpoint"):
                                        self._save_snapshot()
                                if writer is not None and checkpointed:
                                    with sw.phase("checkpoint"):
                                        writer.flush()
                                if events is not None:
                                    events.preempt_event(
                                        self.generation,
                                        checkpointed=checkpointed,
                                    )
                                raise resilience.Preempted(
                                    self.generation,
                                    checkpoint_dir=self.checkpoint_dir
                                    if checkpointed
                                    else None,
                                )
                if writer is not None:
                    with sw.phase("checkpoint"):
                        writer.flush()
            finally:
                self._ckpt_writer = None
                if writer is not None:
                    writer.close()

            with sw.phase("init"):
                for bucket_id, bucket in enumerate(self.buckets):
                    self._unstack(bucket, stacks[bucket_id][0])
                if self.on_world_complete is not None:
                    for bucket in self.buckets:
                        for i in bucket.indices:
                            self.on_world_complete(
                                i, self._boards[i], self.generation
                            )
            _drain_plane()
            report = sw.report(self._world_cells() * iterations)
            if events is not None:
                events.summary(report)
        finally:
            self._live_events = None
            if events is not None:
                events.close()
        return report, list(self._boards)
