"""Compute ops: the TPU-native replacements for the CUDA kernel layer."""

from gol_tpu.ops import stencil

__all__ = ["stencil"]
