"""Compute ops: the TPU-native replacements for the CUDA kernel layer."""

from gol_tpu.ops import life3d, stencil

__all__ = ["life3d", "stencil"]
