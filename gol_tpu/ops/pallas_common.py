"""Shared scaffolding for the Pallas stencil kernels.

Both TPU kernels (:mod:`gol_tpu.ops.pallas_step`, dense uint8, and
:mod:`gol_tpu.ops.pallas_bitlife`, bit-packed int32) use the same plan: the
board lives in HBM, each grid step DMAs one row-tile plus two
alignment-sized halo blocks (mod-H source rows — the torus row wrap) into a
VMEM scratch, and the stencil runs fused over the tile.  This module holds
the plan's two shared pieces, parameterized on the dtype's Mosaic row
alignment and the kernel's VMEM bytes-per-board-row:

- :func:`pick_tile` — the validated replacement for the reference's
  unchecked ``blocksCount = W*H/threadsCount`` (gol-with-cuda.cu:272,
  bug B5): largest alignment-multiple divisor of the height that fits the
  VMEM budget and the caller's hint.
- :func:`load_tile_with_halo` — the 3-DMA scratch fill.  Single-row ghost
  DMAs at odd offsets fail Mosaic's tiling-divisibility proof, so each halo
  fetches a full alignment-sized block instead; the extra rows cost a
  little HBM bandwidth but keep every transfer aligned.
"""

from __future__ import annotations

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_BUDGET = 8 * 1024 * 1024


def pick_tile(
    height: int, width: int, hint: int, align: int, bytes_per_row: int
) -> int:
    """Largest divisor of ``height`` <= hint whose working set fits VMEM.

    ``bytes_per_row`` approximates the kernel's live VMEM bytes per board
    row of width ``width`` (scratch + output + widened temporaries).
    """
    if height % align != 0:
        raise ValueError(
            f"pallas engine needs board height divisible by {align}, "
            f"got {height}"
        )
    budget = max(align, _VMEM_BUDGET // max(1, bytes_per_row * width))
    cap = max(align, min(hint, height, budget))
    for tile in range(cap - cap % align, 0, -align):
        if height % tile == 0:
            return tile
    return align


def validate_tile(height: int, tile: int, align: int) -> None:
    """Reject tiles that don't divide the height or break DMA alignment."""
    if height % tile != 0 or tile % align != 0:
        raise ValueError(
            f"tile {tile} must divide board height {height} and be a "
            f"multiple of {align}"
        )


def tile_halo_copies(
    board_hbm, scratch, sems, i, *, tile, height, align, pad
):
    """The three async-copy descriptors filling ``scratch`` with
    [halo-pad | body tile | halo-pad] rows of window ``i``.

    Rank-agnostic: slices are taken on the leading axis only, so the same
    plan serves the 2-D kernels' [H, nw] row tiles and the 3-D kernel's
    [D, nw, H] plane tiles.  ``scratch``/``sems`` may be ``.at[slot]``
    views of a double-buffered pair — a caller prefetching window ``i+1``
    builds these descriptors twice (start on one slot, wait on the other);
    descriptors are cheap and must be *reconstructed identically* for the
    matching ``wait`` (the make_async_copy contract).

    Scratch layout (all DMA offsets ``align``-row aligned):

    - rows ``[0, pad)``: the block *ending* in the top halo row — source
      rows ``(start - pad) mod height`` (the torus row wrap; contiguous
      because ``pad <= tile``);
    - rows ``[pad, pad+tile)``: the body tile;
    - rows ``[pad+tile, pad+tile+pad)``: the block *starting* with the
      bottom halo row (``(start + tile) mod height``).

    A k-generation caller reads the step-``j`` stencil window as
    ``scratch[pad-(k-j) : pad+tile+(k-j)]``.
    """
    start = pl.multiple_of(i * tile, align)
    top = pl.multiple_of(
        jax.lax.rem(start - pad + height, height), align
    )
    bot = pl.multiple_of(jax.lax.rem(start + tile, height), align)
    return (
        pltpu.make_async_copy(
            board_hbm.at[pl.ds(start, tile)],
            scratch.at[pl.ds(pad, tile)],
            sems.at[0],
        ),
        pltpu.make_async_copy(
            board_hbm.at[pl.ds(top, pad)],
            scratch.at[pl.ds(0, pad)],
            sems.at[1],
        ),
        pltpu.make_async_copy(
            board_hbm.at[pl.ds(bot, pad)],
            scratch.at[pl.ds(pad + tile, pad)],
            sems.at[2],
        ),
    )


def load_window_double_buffered(copies, idx, nxt, slot, first, has_next):
    """The cross-grid-step DMA double-buffer protocol, shared by every
    prefetching kernel.

    ``copies(window_idx, slot)`` returns the async-copy descriptors
    filling scratch slot ``slot`` with that window (descriptors must be
    reconstructible — the wait rebuilds them, per the make_async_copy
    contract).  On the grid's first step (``first``) window ``idx`` is
    started serially; whenever ``has_next``, window ``nxt``'s copies are
    started into the *other* slot before this step's compute; then this
    window's copies are waited.  The caller computes from
    ``scratch[slot]`` and relies on the two-step slot reuse distance:
    the prefetch only ever writes the slot whose compute finished on the
    previous grid step.
    """

    @pl.when(first)
    def _():
        for c in copies(idx, slot):
            c.start()

    @pl.when(has_next)
    def _():
        for c in copies(nxt, 1 - slot):
            c.start()

    for c in copies(idx, slot):
        c.wait()


def load_tile_with_halo(
    board_hbm, scratch, sems, i, *, tile, height, align, pad=None
):
    """Serial form of :func:`tile_halo_copies`: start all three DMAs and
    block until they land."""
    if pad is None:
        pad = align
    copies = tile_halo_copies(
        board_hbm, scratch, sems, i,
        tile=tile, height=height, align=align, pad=pad,
    )
    for c in copies:
        c.start()
    for c in copies:
        c.wait()
