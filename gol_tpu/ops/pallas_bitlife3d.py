"""Pallas TPU kernel for bit-packed 3-D Life: fused plane adders in VMEM.

The XLA lowering of :mod:`gol_tpu.ops.bitlife3d` materializes the ~15
uint32 bit-plane temporaries between fusions; this kernel fuses the whole
x/h/d adder tree + rule matcher over VMEM-resident plane tiles.

**Layout is the key move.**  A packed volume ``[D, H, W/32]`` has only
``W/32`` words on the minor axis (16 at 512³) — far short of the 128-lane
Mosaic tiling, which would waste 8× of every vector op.  So the kernel
operates on the *transposed* layout ``[D, nw, H]``: lanes are the H axis
(512+, always lane-aligned for real volumes), the x word ring lives on the
sublane axis (carry bits via sublane-adjacent words — cheap slices), and
the plane axis is tiled with DMA'd mod-D halos exactly like the 2-D
kernel's row tiles (:mod:`gol_tpu.ops.pallas_common` plan).  Per
generation: 2 sublane shifts (x carries), 4 lane rolls (h neighbors),
plane slices (d), one fused adder tree, the bit-plane rule matcher — any
totalistic B/S rule, still branchless.

Temporal blocking (k generations per VMEM residency, the
:mod:`~gol_tpu.ops.pallas_bitlife` treatment) is supported but the kernel
is VPU-bound like its 2-D sibling, so gains are small.

At sizes where a whole ``(nw, H)`` word plane no longer fits the scoped-
VMEM window (1024³: 32×1024 words), the plane splits along the *word*
axis instead (:func:`multi_step_pallas_packed3d_wt`): word-chunk windows
ride the untiled leading axis of a ``[nw, D, H]`` layout (any slice
offset legal — no DMA alignment lost), carry one ghost word per side
whose 32-bit light cone supports k <= 32 in-VMEM generations, and keep H
whole so the h wrap stays a lane roll.  x/d wraps are XLA-pre-extended
ghost words/planes, one concat pair per k-generation launch.

Dispatch between the two kernels is by halo-recompute score (the
kernels are VPU-bound, so duplicated ghost compute decides); the
word-tiled kernel's window DMA is double-buffered across plane chunks,
the plane kernel's measured better serial (see :func:`_kernel`).

Measured on one v5e chip (Bays 4555, ×128-step runs so the ~130 ms
tunnel RPC doesn't dilute the rates; earlier round-2 notes used ×32 and
under-reported): **7.3e10 cell-updates/s at 512³** via the plane kernel
(XLA packed: 5.9e10), **1.78e11 at 768³** (wt kernel (48, 4), beating
both the plane kernel's 1.61e11 and XLA's 6.9e10 — 2.6×), and
**2.35e11 at 1024³** (wt (32, 4); XLA packed: 6.6e10 — 3.5×).
"""

from __future__ import annotations

import functools
from typing import FrozenSet

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import bitlife, bitlife3d
from gol_tpu.ops.life3d import BAYS_4555, Rule3D
from gol_tpu.ops.pallas_bitlife import _lsr, _pick_block
from gol_tpu.ops.pallas_common import (
    load_tile_with_halo,
    load_window_double_buffered,
    validate_tile,
)

_ALIGN = 8  # plane-axis DMA alignment for 32-bit data
_LANE = 128  # Mosaic lane tiling: H must fill whole lane tiles


def _one_generation(
    ext: jax.Array, birth: FrozenSet[int], survive: FrozenSet[int]
) -> jax.Array:
    """One generation over a plane-extended window ``ext[dp, nw, H]``.

    x wraps on the sublane word ring, h wraps via lane rolls, d consumes
    one plane layer per side (returns ``[dp-2, nw, H]``).
    """
    h = ext.shape[2]
    prev_w = jnp.concatenate([ext[:, -1:], ext[:, :-1]], axis=1)
    next_w = jnp.concatenate([ext[:, 1:], ext[:, :1]], axis=1)
    west = (ext << 1) | _lsr(prev_w, 31)
    east = _lsr(ext, 1) | (next_w << 31)
    s0, s1 = bitlife._full_add(west, ext, east)
    count9 = bitlife._sum3_2bit(
        (pltpu.roll(s0, 1, axis=2), pltpu.roll(s1, 1, axis=2)),
        (s0, s1),
        (pltpu.roll(s0, h - 1, axis=2), pltpu.roll(s1, h - 1, axis=2)),
    )
    count27 = bitlife3d._sum3_planes(
        tuple(p[:-2] for p in count9),
        tuple(p[1:-1] for p in count9),
        tuple(p[2:] for p in count9),
        width=5,
    )
    center = ext[1:-1]
    count26 = bitlife._sub_bit(count27, center)
    born = bitlife._match_counts(count26, birth)
    keep = bitlife._match_counts(count26, survive)
    return (~center & born) | (center & keep)


def _count9_plane(plane: jax.Array, wrap: bool = True):
    """In-plane count-of-9 bit planes for one ``[nw, H]`` word plane.

    The x/h stage of :func:`_one_generation` restricted to a single
    plane: the x word ring on the sublane axis (``wrap=True``: torus
    concats; ``wrap=False``: zero edge carries — word-extended planes
    whose outer ghost words accumulate light-cone garbage, the
    :func:`_one_generation_wt` contract), h neighbors via lane rolls.
    Returns the 4-bit-plane tuple ``_sum3_2bit`` produces.
    """
    h = plane.shape[1]
    if wrap:
        prev_w = jnp.concatenate([plane[-1:], plane[:-1]], axis=0)
        next_w = jnp.concatenate([plane[1:], plane[:1]], axis=0)
    else:
        zero = jnp.zeros_like(plane[:1])
        prev_w = jnp.concatenate([zero, plane[:-1]], axis=0)
        next_w = jnp.concatenate([plane[1:], zero], axis=0)
    west = (plane << 1) | _lsr(prev_w, 31)
    east = _lsr(plane, 1) | (next_w << 31)
    s0, s1 = bitlife._full_add(west, plane, east)
    return bitlife._sum3_2bit(
        (pltpu.roll(s0, 1, axis=1), pltpu.roll(s1, 1, axis=1)),
        (s0, s1),
        (pltpu.roll(s0, h - 1, axis=1), pltpu.roll(s1, h - 1, axis=1)),
    )


def _roll_generations(
    scratch, *, tile, k, pad, birth, survive, read=None, store=None,
    wrap=True,
):
    """The rolling kernels' shared k-generation loop over one window.

    Each generation is a plane-ascending ``fori_loop`` carrying the
    count-of-9 bit planes of the two planes below the write cursor,
    storing each output plane in place as soon as it is complete.
    In-place safety: storing plane ``p`` clobbers only data whose count9
    is already carried; ``center`` (plane ``p``) and the count9 of plane
    ``p+1`` are read through ``read`` BEFORE ``store`` runs.  The valid
    window shrinks one plane per side per generation.  ``read(p)`` /
    ``store(p, out)`` default to plain scratch access; the ghost-word
    kernel passes accessors that assemble ``[ghostL | body | ghostR]``
    planes and split the store — so the tricky invariants live here
    once, whatever the plane layout.
    """
    if read is None:
        read = lambda p: scratch[p]
    if store is None:
        def store(p, out):
            scratch[p] = out

    for j in range(k):
        lo = pad - (k - j)
        hi = pad + tile + (k - j)  # window [lo, hi); outputs [lo+1, hi-1)

        def body(p, carry, _birth=birth, _survive=survive):
            c9_prev, c9_cur = carry[:4], carry[4:]
            c9_next = _count9_plane(read(p + 1), wrap)
            count27 = bitlife3d._sum3_planes(
                c9_prev, c9_cur, c9_next, width=5
            )
            center = read(p)
            count26 = bitlife._sub_bit(count27, center)
            born = bitlife._match_counts(count26, _birth)
            keep = bitlife._match_counts(count26, _survive)
            store(p, (~center & born) | (center & keep))
            return (*c9_cur, *c9_next)

        carry = (
            *_count9_plane(read(lo), wrap),
            *_count9_plane(read(lo + 1), wrap),
        )
        jax.lax.fori_loop(lo + 1, hi - 1, body, carry)


def _kernel_roll(
    vol_hbm, out_ref, scratch, sems, *, tile, depth, k, pad, birth, survive
):
    """Plane-tiled kernel body, rolling per-plane generation (r4).

    Same windowing/DMA as :func:`_kernel`, but each generation runs as a
    plane-ascending ``fori_loop`` carrying the count-of-9 bit planes of
    the two planes below the write cursor, storing each output plane in
    place as soon as it is complete.  Peak VMEM is therefore ONE window
    plus ~a dozen plane-sized temporaries — not the ~9 whole-window live
    arrays the monolithic adder tree holds — so the plane tile can grow
    several-fold and the halo-recompute factor drops toward
    ``(tile + k + 1)/tile`` with NO word-ghost term at all (the r3
    verdict's 3-D ask: the wt kernel's word ghosts taxed 1024³ ×1.5).

    In-place safety: storing plane ``p`` clobbers only data whose count9
    is already carried; ``center`` (plane ``p``) and ``count9`` of plane
    ``p+1`` are read before the store.  Op count per useful word is
    identical to the monolithic kernel — the restructure moves memory,
    not arithmetic.
    """
    load_tile_with_halo(
        vol_hbm, scratch, sems, pl.program_id(0),
        tile=tile, height=depth, align=_ALIGN, pad=pad,
    )
    _roll_generations(
        scratch, tile=tile, k=k, pad=pad, birth=birth, survive=survive
    )
    # Manual output DMA instead of an out_specs VMEM block: pallas_call
    # double-buffers out blocks for its store pipeline, which at big
    # plane tiles costs 2*tile plane-buffers of VMEM — more than the
    # whole halo window.  The explicit copy keeps peak VMEM at ONE
    # window; the serial wait stalls only for an HBM write that is tiny
    # next to the k-generation VPU work.
    i = pl.program_id(0)
    store = pltpu.make_async_copy(
        scratch.at[pl.ds(pad, tile)],
        out_ref.at[pl.ds(pl.multiple_of(i * tile, _ALIGN), tile)],
        sems.at[3],
    )
    store.start()
    store.wait()


def multi_step_pallas_packed3d_roll(
    packed_t: jax.Array, tile: int, k: int, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """k fused rolling-plane generations on a transposed volume [D, nw, H].

    The big-window plane kernel: identical contract to
    :func:`multi_step_pallas_packed3d`, peak VMEM ~1 window (see
    :func:`_kernel_roll`), so it fits plane tiles the monolithic kernel
    cannot — at 1024³ a whole-(nw,H)-plane window of 64+ planes.
    """
    depth, nw, h = packed_t.shape
    validate_tile(depth, tile, _ALIGN)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    pad = -(-k // _ALIGN) * _ALIGN
    if pad > tile:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= tile {tile}"
        )
    return pl.pallas_call(
        functools.partial(
            _kernel_roll,
            tile=tile,
            depth=depth,
            k=k,
            pad=pad,
            birth=rule.birth,
            survive=rule.survive,
        ),
        grid=(depth // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(packed_t.shape, packed_t.dtype),
        scratch_shapes=[
            pltpu.VMEM((tile + 2 * pad, nw, h), packed_t.dtype),
            pltpu.SemaphoreType.DMA((4,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(packed_t)


def _kernel_roll_ext(
    ext_hbm, out_ref, scratch, sems, *, tile, k, pad, birth, survive
):
    """Rolling-plane kernel on a band-extended shard (sharded engine form).

    ``ext_hbm[band + 2*pad, nw, lanes]``: ghost planes from the ring band
    exchange on the leading axis (windows are contiguous aligned slices —
    one DMA, no mod arithmetic).  The x axis is the shard's FULL width
    (the sharded engine only takes this kernel on x-unsharded meshes), so
    x wraps locally exactly as in :func:`_kernel_roll`.  A word-extended
    variant was a measured dead end: ghost word columns put ``nw + 2``
    on the sublane axis, whose tiled HBM layout Mosaic cannot slice at
    unaligned extents (r4, memref_slice failure at 34-of-40 sublanes).
    """
    i = pl.program_id(0)
    cp = pltpu.make_async_copy(
        ext_hbm.at[pl.ds(pl.multiple_of(i * tile, _ALIGN), tile + 2 * pad)],
        scratch.at[:],
        sems.at[0],
    )
    cp.start()
    cp.wait()
    _roll_generations(
        scratch, tile=tile, k=k, pad=pad, birth=birth, survive=survive
    )
    store = pltpu.make_async_copy(
        scratch.at[pl.ds(pad, tile)],
        out_ref.at[pl.ds(pl.multiple_of(i * tile, _ALIGN), tile)],
        sems.at[1],
    )
    store.start()
    store.wait()


def multi_step_pallas_packed3d_roll_ext(
    ext: jax.Array, tile: int, k: int, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """k rolling generations on a band-extended shard volume.

    ``ext[band + 2*pad, nw, lanes]`` carries ring-ghost planes on the
    leading axis — the sharded 3-D engine's band-exchange product in the
    plane-leading layout, for meshes whose x axis is unsharded (the
    shard's local x wrap IS the torus).  Returns ``[band, nw, lanes]``.
    """
    pad = -(-k // _ALIGN) * _ALIGN
    band = ext.shape[0] - 2 * pad
    validate_tile(band, tile, _ALIGN)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    if pad > tile:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= tile {tile}"
        )
    return pl.pallas_call(
        functools.partial(
            _kernel_roll_ext,
            tile=tile,
            k=k,
            pad=pad,
            birth=rule.birth,
            survive=rule.survive,
        ),
        grid=(band // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(
            (band, ext.shape[1], ext.shape[2]), ext.dtype
        ),
        scratch_shapes=[
            pltpu.VMEM(
                (tile + 2 * pad, ext.shape[1], ext.shape[2]), ext.dtype
            ),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(ext)


def _kernel_roll_ext_g(
    ext_hbm, gh_hbm, out_ref, scratch, gscratch, sems, *, tile, k, pad,
    birth, survive,
):
    """Rolling-plane kernel with ghost word columns — the x-sharded form.

    The r4 fix for Mosaic's tiled-HBM constraint (a ``[*, nw+2, lanes]``
    array cannot be sliced at 34-of-40 sublanes): the two ghost word
    columns ride a SEPARATE ``[band+2*pad, 8, lanes]`` operand — slots 0
    (left) and 1 (right) real, 6 dead sublanes for alignment, costing
    DMA bytes but no compute.  Each rolling step concatenates
    ``[ghostL | body | ghostR]`` per plane (``nw+2`` words), evolves it
    with zero outer carries, and splits the store back — so the compute
    tax over the body is ``(nw+2)/nw``, replacing the wt kernel's
    ``(tw+2)/tw`` at tw=4 (×1.06 vs ×1.5 at a 32-word shard).
    """
    i = pl.program_id(0)
    base = pl.multiple_of(i * tile, _ALIGN)
    cp = pltpu.make_async_copy(
        ext_hbm.at[pl.ds(base, tile + 2 * pad)], scratch.at[:], sems.at[0]
    )
    gcp = pltpu.make_async_copy(
        gh_hbm.at[pl.ds(base, tile + 2 * pad)], gscratch.at[:], sems.at[1]
    )
    cp.start()
    gcp.start()
    cp.wait()
    gcp.wait()

    def read(p):
        return jnp.concatenate(
            [gscratch[p, 0:1], scratch[p], gscratch[p, 1:2]], axis=0
        )

    def split_store(p, out):
        scratch[p] = out[1:-1]
        gscratch[p, 0:1] = out[0:1]
        gscratch[p, 1:2] = out[-1:]

    _roll_generations(
        scratch, tile=tile, k=k, pad=pad, birth=birth, survive=survive,
        read=read, store=split_store, wrap=False,
    )
    store = pltpu.make_async_copy(
        scratch.at[pl.ds(pad, tile)],
        out_ref.at[pl.ds(base, tile)],
        sems.at[2],
    )
    store.start()
    store.wait()


GHOST_SLOTS = 8  # sublane-aligned ghost operand width (2 real + 6 dead)


def multi_step_pallas_packed3d_roll_ext_g(
    ext: jax.Array,
    ghosts: jax.Array,
    tile: int,
    k: int,
    rule: Rule3D = BAYS_4555,
) -> jax.Array:
    """k rolling generations of a band- AND word-extended shard.

    ``ext[band + 2*pad, nw, lanes]`` is the shard's own words behind the
    ring band exchange; ``ghosts[band + 2*pad, 8, lanes]`` carries the
    exchanged ghost word columns in sublane slots 0 (left) / 1 (right)
    (slots 2-7 ignored).  Returns the body ``[band, nw, lanes]`` — the
    evolved ghosts are NOT returned (the next chunk's exchange rebuilds
    them from the neighbors' bodies).  ``k <= 32``: one ghost word's bit
    light cone.
    """
    pad = -(-k // _ALIGN) * _ALIGN
    band = ext.shape[0] - 2 * pad
    validate_tile(band, tile, _ALIGN)
    if k < 1 or k > bitlife.BITS:
        raise ValueError(
            f"ghost-word rolling kernel supports 1 <= k <= {bitlife.BITS}, "
            f"got {k}"
        )
    if pad > tile:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= tile {tile}"
        )
    if ghosts.shape != (ext.shape[0], GHOST_SLOTS, ext.shape[2]):
        raise ValueError(
            f"ghosts must be {(ext.shape[0], GHOST_SLOTS, ext.shape[2])}, "
            f"got {ghosts.shape}"
        )
    return pl.pallas_call(
        functools.partial(
            _kernel_roll_ext_g,
            tile=tile,
            k=k,
            pad=pad,
            birth=rule.birth,
            survive=rule.survive,
        ),
        grid=(band // tile,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct(
            (band, ext.shape[1], ext.shape[2]), ext.dtype
        ),
        scratch_shapes=[
            pltpu.VMEM(
                (tile + 2 * pad, ext.shape[1], ext.shape[2]), ext.dtype
            ),
            pltpu.VMEM(
                (tile + 2 * pad, GHOST_SLOTS, ext.shape[2]), ext.dtype
            ),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(ext, ghosts)


def _kernel(
    vol_hbm, out_ref, scratch, sems, *, tile, depth, k, pad, birth, survive
):
    """Plane-tiled kernel body, serial window DMA.

    Measured negative result (v5e, 512³×128, same session): the
    cross-grid-step double-buffer that wins ~10% on the 2-D torus kernel
    *loses* ~9% here (6.6/6.7e10 vs 7.2/7.3e10 serial) — the dynamic
    scratch-slot indexing taxes the much larger 3-D windows more than the
    hidden fetch saves — so this kernel keeps the serial loader.  The
    word-tiled kernel (:func:`_kernel_wt`), whose windows are narrower,
    keeps its double-buffer (+5-11% at 768³/1024³).
    """
    load_tile_with_halo(
        vol_hbm, scratch, sems, pl.program_id(0),
        tile=tile, height=depth, align=_ALIGN, pad=pad,
    )
    for j in range(k):
        lo = pad - (k - j)
        hi = pad + tile + (k - j)
        scratch[lo + 1 : hi - 1] = _one_generation(
            scratch[lo:hi], birth, survive
        )
    out_ref[:] = scratch[pad : pad + tile]


def multi_step_pallas_packed3d(
    packed_t: jax.Array, tile: int, k: int, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """k fused torus generations on a transposed packed volume [D, nw, H]."""
    depth, nw, h = packed_t.shape
    validate_tile(depth, tile, _ALIGN)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    pad = -(-k // _ALIGN) * _ALIGN
    if pad > tile:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= tile {tile}"
        )
    return pl.pallas_call(
        functools.partial(
            _kernel,
            tile=tile,
            depth=depth,
            k=k,
            pad=pad,
            birth=rule.birth,
            survive=rule.survive,
        ),
        grid=(depth // tile,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tile, nw, h), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(packed_t.shape, packed_t.dtype),
        scratch_shapes=[
            pltpu.VMEM((tile + 2 * pad, nw, h), packed_t.dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(packed_t)


def _one_generation_wt(
    ext: jax.Array, birth: FrozenSet[int], survive: FrozenSet[int]
) -> jax.Array:
    """One generation over a word-leading window ``ext[tw+2, dp, H]``.

    The word-tiled layout's twin of :func:`_one_generation`: the x word
    ring lives on the *leading* (untiled) axis with zero-filled edge
    carries — the window's outer ghost words accumulate garbage one bit
    per generation (stencil light cone), which the caller's k <= 32 cap
    keeps inside the single ghost word per side.  d neighbors are sublane
    slices (shrink one plane layer per side), h wraps via lane rolls.
    Returns ``[tw+2, dp-2, H]``.
    """
    h = ext.shape[2]
    zero = jnp.zeros_like(ext[:1])
    prev_w = jnp.concatenate([zero, ext[:-1]], axis=0)
    next_w = jnp.concatenate([ext[1:], zero], axis=0)
    west = (ext << 1) | _lsr(prev_w, 31)
    east = _lsr(ext, 1) | (next_w << 31)
    s0, s1 = bitlife._full_add(west, ext, east)
    count9 = bitlife._sum3_2bit(
        (pltpu.roll(s0, 1, axis=2), pltpu.roll(s1, 1, axis=2)),
        (s0, s1),
        (pltpu.roll(s0, h - 1, axis=2), pltpu.roll(s1, h - 1, axis=2)),
    )
    count27 = bitlife3d._sum3_planes(
        tuple(p[:, :-2] for p in count9),
        tuple(p[:, 1:-1] for p in count9),
        tuple(p[:, 2:] for p in count9),
        width=5,
    )
    center = ext[:, 1:-1]
    count26 = bitlife._sub_bit(count27, center)
    born = bitlife._match_counts(count26, birth)
    keep = bitlife._match_counts(count26, survive)
    return (~center & born) | (center & keep)


def _kernel_wt(
    ext_hbm, out_ref, scratch, sems, *, tile_d, tile_w, k, pad, birth,
    survive,
):
    """Word-tiled kernel body: window = word chunk × plane chunk × full H.

    ``ext_hbm[nw+2, D+2*pad, H]`` is the XLA-pre-extended volume (x wrap
    words on the leading axis, d wrap planes on the sublane axis), so both
    window slices are plain in-bounds reads: the leading axis is untiled
    (any offset legal) and the plane slice stays 8-aligned — no mod
    arithmetic, one DMA.
    """
    j = pl.program_id(0)  # word chunk
    i = pl.program_id(1)  # plane chunk
    ni = pl.num_programs(1)
    # Double-buffered across the plane-chunk (inner) grid axis: window
    # (j, i+1) lands in the other slot under (j, i)'s adder tree.  The
    # first plane chunk of each word chunk loads serially (prefetching
    # across the word-chunk boundary would need j+1's window at i==ni-1;
    # the once-per-word-chunk stall is 1/ni of the fetches).
    step_lin = j * ni + i
    slot = jax.lax.rem(step_lin, 2)

    def copies(ii, s):
        return (
            pltpu.make_async_copy(
                ext_hbm.at[
                    pl.ds(j * tile_w, tile_w + 2),
                    pl.ds(
                        pl.multiple_of(ii * tile_d, _ALIGN),
                        tile_d + 2 * pad,
                    ),
                ],
                scratch.at[s],
                sems.at[s],
            ),
        )

    load_window_double_buffered(copies, i, i + 1, slot, i == 0, i + 1 < ni)
    for step in range(k):
        lo = pad - (k - step)
        hi = pad + tile_d + (k - step)
        scratch[slot, :, lo + 1 : hi - 1] = _one_generation_wt(
            scratch[slot, :, lo:hi], birth, survive
        )
    out_ref[:] = scratch[slot, 1:-1, pad : pad + tile_d]


def multi_step_pallas_packed3d_wt(
    packed_w: jax.Array,
    tile_d: int,
    tile_w: int,
    k: int,
    rule: Rule3D = BAYS_4555,
) -> jax.Array:
    """k fused torus generations on a word-leading packed volume [nw, D, H].

    The big-volume variant (VERDICT r1 #3): when a full ``(nw, H)`` word
    plane no longer fits the scoped-VMEM window (1024³: 32×1024 words),
    the plane is split along the *word* axis instead of the lane axis —
    word-chunk windows carry one ghost word per side whose 32-bit light
    cone supports k <= 32 in-VMEM generations, and word slices ride the
    untiled leading axis so no DMA alignment is lost.  H stays whole
    (lane rolls keep the h wrap); d halos are pre-extended wrap planes.
    """
    nw, depth, h = packed_w.shape
    validate_tile(depth, tile_d, _ALIGN)
    if nw % tile_w:
        raise ValueError(
            f"word tile {tile_w} must divide the packed width {nw}"
        )
    if k < 1 or k > bitlife.BITS:
        raise ValueError(
            f"word-tiled kernel supports 1 <= k <= {bitlife.BITS} (one "
            f"ghost word's bit light cone), got {k}"
        )
    pad = -(-k // _ALIGN) * _ALIGN
    if pad > tile_d:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= plane tile "
            f"{tile_d}"
        )
    ext = jnp.concatenate([packed_w[-1:], packed_w, packed_w[:1]], axis=0)
    ext = jnp.concatenate(
        [ext[:, -pad:], ext, ext[:, :pad]], axis=1
    )  # [nw+2, D+2*pad, H]
    return multi_step_pallas_packed3d_wt_ext(ext, tile_d, tile_w, k, rule)


def multi_step_pallas_packed3d_wt_ext(
    ext: jax.Array,
    tile_d: int,
    tile_w: int,
    k: int,
    rule: Rule3D = BAYS_4555,
) -> jax.Array:
    """Word-tiled kernel on a pre-extended volume ``[nw+2, D+2*pad, H]``.

    The extension's source is the caller's business: the single-device
    wrapper (:func:`multi_step_pallas_packed3d_wt`) concats torus wraps;
    the sharded engine (:func:`gol_tpu.parallel.sharded3d.
    compiled_evolve3d_pallas`) concats ``lax.ppermute`` ring ghosts —
    ghost word columns (x, one word per side: the 32-bit light cone
    covers k <= 32) and a ``pad``-plane band (d), with the word columns
    sliced from the already plane-extended array so the x/d corner data
    rides the second hop, exactly like the 2-D engine's two-phase
    exchange.  ``pad`` is inferred from the extension: ``(ext.shape[1] -
    D) / 2`` must equal ``ceil(k/8)*8``.
    """
    nw = ext.shape[0] - 2
    h = ext.shape[2]
    pad = -(-k // _ALIGN) * _ALIGN
    depth = ext.shape[1] - 2 * pad
    validate_tile(depth, tile_d, _ALIGN)
    if nw % tile_w:
        raise ValueError(
            f"word tile {tile_w} must divide the packed width {nw}"
        )
    if k < 1 or k > bitlife.BITS:
        raise ValueError(
            f"word-tiled kernel supports 1 <= k <= {bitlife.BITS}, got {k}"
        )
    if pad > tile_d:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= plane tile "
            f"{tile_d}"
        )
    return pl.pallas_call(
        functools.partial(
            _kernel_wt,
            tile_d=tile_d,
            tile_w=tile_w,
            k=k,
            pad=pad,
            birth=rule.birth,
            survive=rule.survive,
        ),
        grid=(nw // tile_w, depth // tile_d),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tile_w, tile_d, h), lambda j, i: (j, i, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((nw, depth, h), ext.dtype),
        scratch_shapes=[
            # Two slots for the cross-grid-step prefetch (see _kernel_wt).
            pltpu.VMEM(
                (2, tile_w + 2, tile_d + 2 * pad, h), ext.dtype
            ),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(ext)


# The wt kernel's own live-window factor: the 1024³ compile with a
# 9-window model overflowed scoped VMEM by 1.73 MB at a 1.77 MB window —
# the compiler's measured peak was 10.02 windows; 11 leaves margin, +1
# for the double-buffered scratch's second slot.
_LIVE_WINDOWS_WT = 12


def recompute_score(tile_d: int, tile_w: int, pad: int = _ALIGN) -> float:
    """Halo-recompute ratio of a kernel window: duplicated ghost compute
    per useful output cell.  ``tile_w`` counts ghost *words* (2 total,
    carried the whole way); the plane-axis factor is the *mean of the
    shrinking windows* — every kernel form evolves ``tile_d + 2*(pad-j)``
    planes at generation ``j``, so the per-generation mean is
    ``(tile_d + pad + 1) / tile_d``, exactly the basis of
    ``roofline.ops_3d_roll_per_useful_word`` / ``ops_3d_wt_per_useful_
    word`` — not the full first-window ``(tile_d + 2*pad) / tile_d``,
    which overweighted deep pads and could keep wt on near-tie shards
    where roll recomputes less (ADVICE r4).  The plane kernel is the
    ``tile_w -> inf`` special case (no word ghosts).  One definition
    shared by the wt tile picker and the dispatch sites (evolve3d,
    sharded3d), so the picker's objective and the dispatchers'
    comparisons cannot drift.
    """
    word_factor = (tile_w + 2) / tile_w if tile_w else 1.0
    return word_factor * ((tile_d + pad + 1) / tile_d)


def pick_tile3d_wt(depth: int, nw: int, h: int, pad: int = _ALIGN):
    """(tile_d, tile_w) for the word-tiled kernel, or None if nothing fits.

    Minimizes :func:`recompute_score` (the kernel is VPU-bound, so
    duplicated ghost compute is the cost that matters) over all feasible
    tiles under the scoped-VMEM window model; ties prefer the larger
    plane tile (fewer launches/DMAs).
    """
    budget = _SCOPED_LIMIT // (_LIVE_WINDOWS_WT * 4 * h)
    best = None
    best_score = None
    for tile_w in (w for w in (16, 8, 4, 2, 1) if nw % w == 0):
        cap = min(budget // (tile_w + 2) - 2 * pad, depth)
        if cap < _ALIGN:
            continue
        for tile_d in range(cap - cap % _ALIGN, 0, -_ALIGN):
            if depth % tile_d == 0:
                score = recompute_score(tile_d, tile_w, pad)
                if (
                    best is None
                    or score < best_score - 1e-12
                    or (abs(score - best_score) <= 1e-12 and tile_d > best[0])
                ):
                    best, best_score = (tile_d, tile_w), score
                break
    return best


# Benchmarked on v5e at 512³: blocking is marginal (VPU-bound) but k=8
# still wins slightly; the tile is VMEM-budget-limited.
_BLOCK = 8
# Scoped-VMEM feasibility model, calibrated against the compiler: ~9 live
# int32 arrays of the full halo-extended window at the scheduler's peak
# (the 1024³ failure measured 26.8 MB for a 24-plane window of 32×1024
# words — 9 × 24 × 32768 × 4 = 28 MB predicts it; 512³'s 48-plane window
# of 8192 words predicts 14 MB, which compiles).  Mosaic's hard scoped
# limit is 16 MB.
_SCOPED_LIMIT = 16 * 1024 * 1024
_LIVE_WINDOWS = 9


# The rolling kernel's VMEM model: ONE window (the scratch) plus
# plane-sized temporaries — three count9 sets in flight (12 bit planes),
# count27/count26/match intermediates, and slack for Mosaic's scheduling.
# Calibrated on-chip r4: tile 64 at 1024³ (80-plane window, 10 MB + temps)
# compiles; tile 128 (18 MB window alone) cannot.
_LIVE_PLANES_ROLL = 24


def pick_tile3d_roll(depth: int, nw: int, h: int, pad: int = _ALIGN) -> int:
    """Largest aligned divisor of ``depth`` whose window fits the rolling
    kernel's VMEM model (one window + ~24 plane-sized temps).

    Same contract as :func:`pick_tile3d`; returns 0 when nothing fits.
    The rolling kernel's restructured compute (per-plane ``fori_loop``
    with a count9 carry) is what shrinks the model from ~9 live windows
    to ~1 — see :func:`_kernel_roll`.
    """
    if depth % _ALIGN:
        raise ValueError(
            f"pallas 3-D engine needs volume depth divisible by {_ALIGN}, "
            f"got {depth}"
        )
    budget_planes = _SCOPED_LIMIT // (4 * nw * h) - _LIVE_PLANES_ROLL
    cap = min(budget_planes - 2 * pad, depth)
    if cap < _ALIGN:
        return 0
    for tile in range(cap - cap % _ALIGN, 0, -_ALIGN):
        if depth % tile == 0:
            return tile
    return 0


def pick_tile3d(depth: int, nw: int, h: int, pad: int = _ALIGN) -> int:
    """Largest _ALIGN-multiple divisor of ``depth`` whose halo-extended
    window (tile + 2*pad planes of nw×h words) fits scoped VMEM.

    Returns 0 when no tile fits — a single plane is too large (huge
    ``nw*h``); callers fall back to the XLA packed path.
    """
    if depth % _ALIGN:
        raise ValueError(
            f"pallas 3-D engine needs volume depth divisible by {_ALIGN}, "
            f"got {depth}"
        )
    max_window = _SCOPED_LIMIT // (_LIVE_WINDOWS * 4 * nw * h)
    cap = min(max_window - 2 * pad, depth)
    if cap < _ALIGN:
        return 0
    for tile in range(cap - cap % _ALIGN, 0, -_ALIGN):
        if depth % tile == 0:
            return tile
    return 0


@functools.partial(jax.jit, static_argnums=(1, 2, 3), donate_argnums=(0,))
def evolve3d(
    vol: jax.Array, steps: int, rule: Rule3D = BAYS_4555,
    strict: bool = False,
) -> jax.Array:
    """Dense uint8 in/out: pack, transpose, fused-evolve, restore.

    The transpose pair costs two XLA copies total — amortized over the
    whole generation loop, which runs as temporally-blocked Pallas
    launches (full k-blocks then one remainder).

    ``strict=True`` raises instead of taking the XLA fallback when no
    kernel window fits scoped VMEM — for callers who *explicitly* asked
    for the Pallas engine and must not have their benchmark silently
    relabeled (the cli3d ``--engine pallas`` contract); ``auto`` callers
    keep the silent substitution.
    """
    d, h, w = vol.shape
    nw = bitlife.packed_width(w)
    if jax.default_backend() == "tpu":
        if h % _LANE != 0:
            raise ValueError(
                "pallas 3-D engine needs the H axis to fill whole "
                f"{_LANE}-lane tiles on TPU: got H={h}"
            )
    # Three kernels, one objective: lowest halo-recompute score wins (the
    # kernels are VPU-bound, so duplicated ghost compute decides).  The
    # rolling kernel fits windows several times the monolithic plane
    # kernel's (one live window vs ~9), so it usually scores lowest and
    # is what retired the wt kernel's ×1.92 recompute at 1024³ — measured
    # same-session ×256 on v5e (BASELINE.md r4): roll(32/64) 4.8e11
    # cell-updates/s vs wt(32,4) 3.3e11.  On score ties prefer the
    # monolithic plane kernel (bigger fused ops, measured slightly ahead
    # at equal tile); the tie can only happen when both max out at the
    # full depth.
    tile = pick_tile3d(d, nw, h)
    wt = pick_tile3d_wt(d, nw, h)
    roll = pick_tile3d_roll(d, nw, h)
    cands = []
    if tile:
        cands.append((recompute_score(tile, 0), 0, "plane"))
    if roll:
        cands.append((recompute_score(roll, 0), 1, "roll"))
    if wt is not None:
        cands.append((recompute_score(wt[0], wt[1]), 2, "wt"))
    if not cands:
        # Not even a word-tiled window fits: take the XLA packed path —
        # same bit-exact result, still one compiled program.
        if strict:
            raise ValueError(
                f"the fused Pallas 3-D kernel cannot fit a volume of shape "
                f"{(d, h, w)} in scoped VMEM (neither whole, rolling, nor "
                "word-tiled plane windows); use engine 'auto' or 'bitpack'"
            )
        return bitlife3d.unpack3d(
            bitlife3d.run3d_packed(bitlife3d.pack3d(vol), steps, rule)
        )
    choice = min(cands)[2]
    if choice == "wt":
        tile_d, tile_w = wt
        packed_w = lax.bitcast_convert_type(
            bitlife3d.pack3d(vol), jnp.int32
        ).transpose(2, 0, 1)
        k = _pick_block(steps, tile_d, _BLOCK, _ALIGN)
        full, rem = divmod(steps, k)
        packed_w = lax.fori_loop(
            0,
            full,
            lambda _, p: multi_step_pallas_packed3d_wt(
                p, tile_d, tile_w, k, rule
            ),
            packed_w,
        )
        if rem:
            packed_w = multi_step_pallas_packed3d_wt(
                packed_w, tile_d, tile_w, rem, rule
            )
        return bitlife3d.unpack3d(
            lax.bitcast_convert_type(
                packed_w.transpose(1, 2, 0), jnp.uint32
            )
        )
    step_fn = (
        multi_step_pallas_packed3d
        if choice == "plane"
        else multi_step_pallas_packed3d_roll
    )
    t = tile if choice == "plane" else roll
    packed_t = lax.bitcast_convert_type(
        bitlife3d.pack3d(vol), jnp.int32
    ).transpose(0, 2, 1)
    k = _pick_block(steps, t, _BLOCK, _ALIGN)
    full, rem = divmod(steps, k)
    packed_t = lax.fori_loop(
        0,
        full,
        lambda _, p: step_fn(p, t, k, rule),
        packed_t,
    )
    if rem:
        packed_t = step_fn(packed_t, t, rem, rule)
    return bitlife3d.unpack3d(
        lax.bitcast_convert_type(packed_t.transpose(0, 2, 1), jnp.uint32)
    )
