"""Pallas TPU kernel over the bit-packed board: fused carry-save Life.

The top perf tier, composing the two fast paths:

- the **bit-packed** representation of :mod:`gol_tpu.ops.bitlife` (32
  cells/uint32 word, ~22 bitwise VPU ops per word per generation), and
- the **explicit VMEM tiling** of :mod:`gol_tpu.ops.pallas_step` (HBM-
  resident board, DMA'd row tiles with wrap halo rows).

The XLA lowering of the pure-jnp packed step materializes the bit-plane
temporaries between fusions, so it runs far below both VPU and HBM peak.
Here the entire adder tree + rule runs fused over one VMEM tile: per
generation the board words make exactly one HBM round trip (read + write =
2 × H·W/8 bytes — 8× less than even a perfectly-fused dense uint8 engine).
Measured on one v5e chip at 16384²: ~1.8e12 cell-updates/s device-side,
~4× the jnp packed engine, near HBM bandwidth bound.

Mosaic notes: compute is int32 (bit-identical to uint32 for the bitwise
adder ops — the adder/rule algebra itself is reused from
``bitlife._full_add`` / ``bitlife._rule_from_row_sums``); logical right
shifts are emulated with arithmetic shift + mask (``_lsr``); the word-ring
column wrap (gol-with-cuda.cu:210-211) is a ``pltpu.roll`` along lanes,
carry bits crossing words via shifts exactly as in ``bitlife._west_east``.
Row wrap is handled at DMA time with mod-H aligned halo fetches
(:func:`gol_tpu.ops.pallas_common.load_tile_with_halo`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import bitlife
from gol_tpu.ops.pallas_common import (
    load_tile_with_halo,
    pick_tile as _pick,
    validate_tile,
)

_ALIGN = 8  # TPU tiling for 32-bit data is (8, 128): 8-row DMA alignment
_LANE = 128  # Mosaic lane tiling for 32-bit data: packed width granularity
# ~12 live int32 [tile, nw] temporaries across the adder tree.
_BYTES_PER_ROW = 48


def pick_tile(height: int, packed_width: int, hint: int) -> int:
    """Largest divisor of ``height`` <= hint whose working set fits VMEM."""
    return _pick(height, packed_width, hint, _ALIGN, _BYTES_PER_ROW)


def _lsr(x: jax.Array, r: int) -> jax.Array:
    """Logical shift right on int32 lanes (mask off the sign extension)."""
    return (x >> r) & jnp.int32((1 << (32 - r)) - 1)


def _kernel(packed_hbm, out_ref, scratch, sems, *, tile: int, height: int):
    load_tile_with_halo(
        packed_hbm, scratch, sems, pl.program_id(0),
        tile=tile, height=height, align=_ALIGN,
    )
    ext = scratch[_ALIGN - 1 : _ALIGN + tile + 1, :]  # int32 [tile+2, nw]
    nw = ext.shape[1]

    # Per-row 3-cell horizontal sums, once per extended row (bit planes).
    prev_word = pltpu.roll(ext, 1, axis=1)
    next_word = pltpu.roll(ext, nw - 1, axis=1)  # roll by -1
    west = (ext << 1) | _lsr(prev_word, 31)
    east = _lsr(ext, 1) | (next_word << 31)
    s0, s1 = bitlife._full_add(west, ext, east)

    out_ref[:] = bitlife._rule_from_row_sums(
        ext[1:-1],
        (s0[:-2], s1[:-2]),
        (s0[1:-1], s1[1:-1]),
        (s0[2:], s1[2:]),
    )


def step_pallas_packed(packed_i32: jax.Array, tile: int) -> jax.Array:
    """One torus generation on an int32-bitcast packed board [H, W/32]."""
    height, nw = packed_i32.shape
    validate_tile(height, tile, _ALIGN)
    grid = height // tile
    return pl.pallas_call(
        functools.partial(_kernel, tile=tile, height=height),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tile, nw), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(packed_i32.shape, packed_i32.dtype),
        scratch_shapes=[
            pltpu.VMEM((tile + 2 * _ALIGN, nw), packed_i32.dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(packed_i32)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def evolve(board: jax.Array, steps: int, tile_hint: int = 512) -> jax.Array:
    """Dense uint8 in/out; pack, evolve fused-packed, unpack — one program."""
    nw = bitlife.packed_width(board.shape[1])
    if jax.default_backend() == "tpu" and nw % _LANE != 0:
        raise ValueError(
            "pallas bitpack engine needs the packed width to fill whole "
            f"{_LANE}-lane tiles on TPU: board width must be a multiple of "
            f"{_LANE * bitlife.BITS}, got {board.shape[1]}"
        )
    packed = bitlife.pack(board)
    packed_i32 = lax.bitcast_convert_type(packed, jnp.int32)
    tile = pick_tile(packed_i32.shape[0], packed_i32.shape[1], tile_hint)
    packed_i32 = lax.fori_loop(
        0, steps, lambda _, p: step_pallas_packed(p, tile), packed_i32
    )
    return bitlife.unpack(lax.bitcast_convert_type(packed_i32, jnp.uint32))
