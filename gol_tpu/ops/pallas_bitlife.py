"""Pallas TPU kernel over the bit-packed board: fused carry-save Life.

The top perf tier, composing the two fast paths:

- the **bit-packed** representation of :mod:`gol_tpu.ops.bitlife` (32
  cells/uint32 word, ~22 bitwise VPU ops per word per generation), and
- the **explicit VMEM tiling** of :mod:`gol_tpu.ops.pallas_step` (HBM-
  resident board, DMA'd row tiles with wrap halo rows).

The XLA lowering of the pure-jnp packed step materializes the bit-plane
temporaries between fusions, so it runs far below both VPU and HBM peak.
Here the entire adder tree + rule runs fused over one VMEM tile, and
generations are **temporally blocked**: each kernel launch loads its tile
with a k-deep halo pad and evolves k generations in VMEM (the valid window
shrinking one row per side per step), so k generations cost one HBM round
trip and one launch instead of k.  The window DMAs are **double-buffered
across grid steps** (tile i+1's three mod-H fetches issued into a second
scratch slot before tile i's adder tree): best-of-8 samples at
16384²×1024 measure 8.96/9.77e11 cell-updates/s vs 8.20/8.69e11 for the
serial-DMA form — ~10% from hiding the input fetch under the VPU work.
Earlier same-session sweep (k=16, tile=256, serial DMA): ~8.6e11 vs
~7.3e11 for the k=1 kernel (+17%); the kernel is VPU-bound (~22 bitwise
ops per 32-cell word), which is why deeper blocking saturates — the
recomputed halo bands add ~2k/tile extra compute.  A fully VMEM-resident
variant (no HBM traffic at all, row wrap via sublane rolls) measured 3×
*slower* per cell — sublane rolls beat slicing-with-halo-pad nowhere.

Mosaic notes: compute is int32 (bit-identical to uint32 for the bitwise
adder ops — the adder/rule algebra itself is reused from
``bitlife._full_add`` / ``bitlife._rule_from_row_sums``); logical right
shifts are emulated with arithmetic shift + mask (``_lsr``); the word-ring
column wrap (gol-with-cuda.cu:210-211) is a ``pltpu.roll`` along lanes,
carry bits crossing words via shifts exactly as in ``bitlife._west_east``.
Row wrap is handled at DMA time with mod-H aligned halo fetches
(:func:`gol_tpu.ops.pallas_common.tile_halo_copies` descriptors, started
and waited under the double-buffer protocol in :func:`_kernel` — the
wait must reconstruct the start's descriptors identically).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from gol_tpu.ops import bitlife
from gol_tpu.ops.pallas_common import (
    load_window_double_buffered,
    pick_tile as _pick,
    tile_halo_copies,
    validate_tile,
)

_ALIGN = 8  # TPU tiling for 32-bit data is (8, 128): 8-row DMA alignment
_LANE = 128  # Mosaic lane tiling for 32-bit data: packed width granularity
# ~12 live int32 [tile, nw] temporaries across the adder tree, plus the
# second scratch slot both double-buffered kernels carry (~1.1 more rows
# per body row at the torus kernel's pad=16).
_BYTES_PER_ROW = 57


def pick_tile(height: int, packed_width: int, hint: int) -> int:
    """Largest divisor of ``height`` <= hint whose working set fits VMEM."""
    return _pick(height, packed_width, hint, _ALIGN, _BYTES_PER_ROW)


def fold_factor(packed_width: int) -> int:
    """Smallest row-fold ``f`` making ``f * packed_width`` fill whole
    128-lane tiles.

    1 when the width already fills them.  A shard too narrow for the lane
    tiling (BASELINE config 3 on a 16×16 mesh: 1024-cell = 32-word shards)
    is evolved in a folded ``[h/f, f*nw]`` layout — ``f`` row groups side
    by side in lanes — by :func:`gol_tpu.parallel.packed.
    compiled_evolve_packed_pallas`; the kernel's group-local rolls
    (``_one_generation(groups=f)``) keep the fold exact, so only
    column-*sharded* meshes need their usual edge repair (folded to one
    column pair per group).
    """
    return _LANE // math.gcd(packed_width, _LANE)


def fold_feasible(
    shard_h: int, fold: int, overlap: bool, depth: int
) -> bool:
    """Geometric feasibility of evolving a fold-``f`` narrow shard.

    The ONE predicate behind the engine's trace-time check
    (``packed.local``), the runtime's up-front validation
    (``GolRuntime.__post_init__``), and the auto-resolution gate
    (``GolRuntime._resolve_auto``) — shared so the three sites cannot
    drift: the folded layout needs shard height divisible by
    ``fold * _ALIGN`` (every group an aligned row block), and overlap
    mode additionally needs the *folded* height to keep one aligned
    interior tile clear of both exchanged bands.
    """
    return shard_h % (fold * _ALIGN) == 0 and (
        not overlap or shard_h // fold >= 2 * depth + _ALIGN
    )


def _lsr(x: jax.Array, r: int) -> jax.Array:
    """Logical shift right on int32 lanes (mask off the sign extension)."""
    return (x >> r) & jnp.int32((1 << (32 - r)) - 1)


def _one_generation(ext: jax.Array, rule=None, groups: int = 1) -> jax.Array:
    """One packed generation over an extended row window (shrinks by 2 rows).

    Per-row 3-cell horizontal sums once per extended row (bit planes),
    column wrap via a lane roll with carry bits crossing words by shifts.
    ``rule=None`` runs the hard-wired B3/S23 tail (the reference's rule,
    two ops cheaper); a ``Rule2D`` runs the generic plane matcher on the
    count-of-9 with the +1 survive identity (see
    :func:`gol_tpu.ops.rules.step_rule_packed`).

    ``groups > 1`` is the lane-folded narrow-shard layout (``groups`` row
    groups side by side in lanes, :func:`gol_tpu.parallel.packed.
    fold_rows`): the word ring becomes **group-local** — each group's edge
    word takes its carry from its *own* group's opposite edge (two masked
    rolls), so the fold introduces no seam wrongness at all and the
    row-sharded engine needs no repair.  Cost: 2 extra rolls + 2 selects
    per extended row per generation (~18% on the ~22-op tree).
    """
    nw = ext.shape[1]
    if groups == 1:
        prev_word = pltpu.roll(ext, 1, axis=1)
        next_word = pltpu.roll(ext, nw - 1, axis=1)  # roll by -1
    else:
        gw = nw // groups
        # Masks via in-kernel iota (pallas_call forbids captured
        # constants); Mosaic CSEs the repeats across the unrolled k loop.
        lane = lax.rem(lax.broadcasted_iota(jnp.int32, (1, nw), 1), gw)
        first = lane == 0
        last = lane == gw - 1
        prev_word = jnp.where(
            first,
            # group-local wrap: lane g*gw reads its own group's last word
            pltpu.roll(ext, (nw - gw + 1) % nw, axis=1),
            pltpu.roll(ext, 1, axis=1),
        )
        next_word = jnp.where(
            last,
            pltpu.roll(ext, gw - 1, axis=1),
            pltpu.roll(ext, nw - 1, axis=1),
        )
    west = (ext << 1) | _lsr(prev_word, 31)
    east = _lsr(ext, 1) | (next_word << 31)
    s0, s1 = bitlife._full_add(west, ext, east)
    sa = (s0[:-2], s1[:-2])
    sc = (s0[1:-1], s1[1:-1])
    sb = (s0[2:], s1[2:])
    if rule is None:
        return bitlife._rule_from_row_sums(ext[1:-1], sa, sc, sb)
    from gol_tpu.ops.rules import _rule_from_count9

    return _rule_from_count9(
        ext[1:-1], bitlife._sum3_2bit(sa, sc, sb), rule
    )


def _kernel(
    packed_hbm, out_ref, scratch, sems, *, tile: int, height: int, k: int,
    pad: int, rule=None,
):
    """k torus generations per VMEM residency (temporal blocking).

    The tile is loaded with a k-deep halo pad on each side; generation j
    evolves the window ``[pad-(k-j), pad+tile+(k-j))`` in place, shrinking
    the valid region by one row per side per step, so after k steps the
    body tile is exact.  Neighboring tiles recompute the overlapping halo
    bands independently — the in-kernel analog of the sharded engines'
    ``--halo-depth`` temporal blocking, trading O(k²) duplicated edge rows
    for k× fewer HBM round trips and kernel launches.

    Like :func:`_kernel_ext`, the three window DMAs are double-buffered
    across grid steps: tile ``i+1``'s mod-H fetches are issued into the
    other scratch slot before tile ``i``'s adder tree runs.
    """
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    slot = jax.lax.rem(i, 2)

    def copies(j, s):
        return tile_halo_copies(
            packed_hbm, scratch.at[s], sems.at[s], j,
            tile=tile, height=height, align=_ALIGN, pad=pad,
        )

    load_window_double_buffered(copies, i, i + 1, slot, i == 0, i + 1 < nt)
    for j in range(k):
        a = pad - (k - j)
        b = pad + tile + (k - j)
        scratch[slot, a + 1 : b - 1] = _one_generation(
            scratch[slot, a:b], rule
        )
    out_ref[:] = scratch[slot, pad : pad + tile]


def multi_step_pallas_packed(
    packed_i32: jax.Array, tile: int, k: int, rule=None
) -> jax.Array:
    """k fused torus generations on an int32-bitcast packed board [H, W/32].

    ``rule`` (a :class:`gol_tpu.ops.rules.Rule2D`, hashable) switches the
    kernel tail to the generic plane matcher; None keeps hard-wired B3/S23.
    """
    height, nw = packed_i32.shape
    validate_tile(height, tile, _ALIGN)
    if k < 1:
        raise ValueError(f"k must be >= 1, got {k}")
    pad = -(-k // _ALIGN) * _ALIGN
    if pad > tile:
        raise ValueError(
            f"temporal block depth {k} needs halo pad {pad} <= tile {tile}"
        )
    grid = height // tile
    return pl.pallas_call(
        functools.partial(
            _kernel, tile=tile, height=height, k=k, pad=pad, rule=rule
        ),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tile, nw), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(packed_i32.shape, packed_i32.dtype),
        scratch_shapes=[
            # Two slots × (3 DMAs each): tile i computes from slot i%2
            # while tile i+1's mod-H window lands in the other.
            pltpu.VMEM((2, tile + 2 * pad, nw), packed_i32.dtype),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(packed_i32)


def step_pallas_packed(packed_i32: jax.Array, tile: int) -> jax.Array:
    """One torus generation on an int32-bitcast packed board [H, W/32]."""
    return multi_step_pallas_packed(packed_i32, tile, 1)


def _kernel_ext(*refs, tile: int, k: int, rule=None, groups: int = 1):
    """k generations of one tile of a halo-extended (no-wrap) board.

    The input already carries k ghost rows on each side (a sharded
    engine's ppermute exchange materialized them), so the window for tile
    ``i`` is the contiguous rows ``[i*tile, i*tile + tile + 2k)`` of the
    extended array — one aligned DMA, no mod-H arithmetic.  The DMA is
    **double-buffered across grid steps**: tile ``i+1``'s window is
    issued into the other scratch slot before tile ``i``'s adder tree
    runs, so the input fetch (~0.5 MB/tile at the 16384² shape) rides
    under the VPU work instead of serializing ahead of it (the output
    store is already pipelined by pallas_call's out_specs machinery).

    With an ``edges`` input (the 2-D-mesh sharded engine), the caller's
    pre-computed exact edge word-columns overwrite lanes ``0`` and
    ``nw-1`` during the same output store — the kernel's local column
    wrap is wrong in those words' outer k bits, and merging the fix here
    costs two masked lane stores instead of a separate full-lane-tile
    read-modify-write scatter pass over the output in HBM.
    """
    if len(refs) == 4:
        ext_hbm, out_ref, scratch, sems = refs
        edges_ref = None
    else:
        ext_hbm, edges_ref, out_ref, scratch, sems = refs
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    slot = jax.lax.rem(i, 2)

    def copies(j, s):
        start = pl.multiple_of(j * tile, _ALIGN)
        return (
            pltpu.make_async_copy(
                ext_hbm.at[pl.ds(start, tile + 2 * k)],
                scratch.at[s],
                sems.at[s],
            ),
        )

    load_window_double_buffered(copies, i, i + 1, slot, i == 0, i + 1 < nt)
    _evolve_window_and_store(
        scratch, slot, out_ref, edges_ref, tile, k, rule, groups
    )


def multi_step_pallas_packed_ext(
    ext_i32: jax.Array, tile: int, k: int, rule=None, edges_i32=None,
    groups: int = 1,
) -> jax.Array:
    """k fused generations on a k-deep row-halo-extended packed board.

    ``ext_i32[h + 2k, W/32]``: rows ``[0, k)`` and ``[h+k, h+2k)`` are
    ghost rows from the ring neighbors (fresh, by construction — the
    sharded engines build them with ``halo_extend`` inside the same traced
    program).  Columns wrap locally, so this is the 1-D row-decomposition
    kernel.  ``k`` must be a multiple of the DMA row alignment so every
    tile window stays aligned.  Returns the updated interior ``[h, W/32]``.

    ``edges_i32[h, 2]`` (optional, the 2-D-mesh path) holds the exact
    post-step left/right edge word-columns; they replace lanes 0 and nw-1
    of the output inside the kernel (see :func:`_kernel_ext`).
    """
    if k < 1 or k % _ALIGN:
        raise ValueError(
            f"extended kernel needs k to be a positive multiple of "
            f"{_ALIGN}, got {k}"
        )
    height = ext_i32.shape[0] - 2 * k
    nw = ext_i32.shape[1]
    validate_tile(height, tile, _ALIGN)
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)]
    operands = [ext_i32]
    _validate_groups(groups, nw)
    if edges_i32 is not None:
        _validate_edges(edges_i32, height, nw, groups)
        in_specs.append(
            pl.BlockSpec(
                (tile, edges_i32.shape[1]),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        )
        operands.append(edges_i32)
    return pl.pallas_call(
        functools.partial(_kernel_ext, tile=tile, k=k, rule=rule, groups=groups),
        grid=(height // tile,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (tile, nw), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((height, nw), ext_i32.dtype),
        scratch_shapes=[
            # Two slots: tile i computes from slot i%2 while tile i+1's
            # window lands in the other (see _kernel_ext).
            pltpu.VMEM((2, tile + 2 * k, nw), ext_i32.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(*operands)


def _evolve_window_and_store(
    scratch, slot, out_ref, edges_ref, tile: int, k: int, rule,
    groups: int = 1,
):
    """The ext kernels' shared compute tail: k in-place generations over
    the slot's window (shrinking one row per side per step), body store,
    and the optional exact-edge-word overwrite (see
    :func:`multi_step_pallas_packed_ext`)."""
    for j in range(k):
        a = j
        b = tile + 2 * k - j
        scratch[slot, a + 1 : b - 1] = _one_generation(
            scratch[slot, a:b], rule, groups
        )
    out_ref[:] = scratch[slot, k : k + tile]
    if edges_ref is not None:
        # One (left, right) exact column pair per lane-fold row group —
        # [tile, 2] unfolded, [tile, 2f] folded (group g's pair at columns
        # 2g, 2g+1; its words at lanes g*gw and (g+1)*gw - 1).
        nw = out_ref.shape[1]
        groups = edges_ref.shape[1] // 2
        gw = nw // groups
        for g in range(groups):
            out_ref[:, g * gw : g * gw + 1] = edges_ref[:, 2 * g : 2 * g + 1]
            out_ref[:, (g + 1) * gw - 1 : (g + 1) * gw] = edges_ref[
                :, 2 * g + 1 : 2 * g + 2
            ]


def _validate_groups(groups: int, nw: int) -> None:
    if groups < 1 or nw % groups:
        raise ValueError(
            f"groups ({groups}) must be >= 1 and divide the packed "
            f"width {nw}"
        )


def _validate_edges(edges, height: int, nw: int, groups: int) -> None:
    """Edges operand contract shared by the ext and banded kernels: one
    (left, right) exact column pair per row group, >= 2 words per group
    (so the two stores never collide)."""
    if edges.shape != (height, 2 * groups):
        raise ValueError(
            f"edges must be [height, 2*groups] = {(height, 2 * groups)}, "
            f"got {edges.shape}"
        )
    if nw // groups < 2:
        raise ValueError(
            f"edge repair needs >= 2 packed words per row group, got "
            f"{nw // groups}"
        )


def _kernel_ext_bands(*refs, tile: int, k: int, rule=None, groups: int = 1):
    """k generations of one tile, ghost band as a separate operand.

    Same compute as :func:`_kernel_ext`, but the k-row ghost bands arrive
    as their own ``[2k, nw]`` operand instead of pre-concatenated onto
    the block — so the sharded engine never materializes the
    ``[h+2k, nw]`` extended array (a full-board HBM copy per chunk, ~1/9
    of the chunk's traffic at k=8).  Each tile's window is assembled in
    VMEM from three fixed-size segments: a k-row top segment (the band's
    top half for tile 0, else block rows), the tile body, and a k-row
    bottom segment (block rows, or the band's bottom half for the last
    tile).  Segment source is resolved by ``pl.when`` pairs whose wait
    mirrors the start, and the whole plan is double-buffered across grid
    steps like the other kernels.
    """
    if len(refs) == 5:
        blk_hbm, bands_hbm, out_ref, scratch, sems = refs
        edges_ref = None
    else:
        blk_hbm, bands_hbm, edges_ref, out_ref, scratch, sems = refs
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    slot = jax.lax.rem(i, 2)

    def segs(j, s):
        """(predicate, descriptor) pairs for window j into slot s; the
        body descriptor's predicate is None (unconditional)."""
        base = pl.multiple_of(j * tile, _ALIGN)
        # Clamped so the never-started branch's descriptor stays in
        # bounds (the clamps are no-ops whenever the branch does start).
        top_blk = pl.multiple_of(jnp.maximum(base - k, 0), _ALIGN)
        bot_blk = pl.multiple_of(
            jnp.minimum(base + tile, blk_hbm.shape[0] - k), _ALIGN
        )
        mk = pltpu.make_async_copy
        return (
            (
                j == 0,
                mk(
                    bands_hbm.at[pl.ds(0, k)],
                    scratch.at[s, pl.ds(0, k)],
                    sems.at[s, 0],
                ),
            ),
            (
                j > 0,
                mk(
                    blk_hbm.at[pl.ds(top_blk, k)],
                    scratch.at[s, pl.ds(0, k)],
                    sems.at[s, 0],
                ),
            ),
            (
                None,
                mk(
                    blk_hbm.at[pl.ds(base, tile)],
                    scratch.at[s, pl.ds(k, tile)],
                    sems.at[s, 1],
                ),
            ),
            (
                j == nt - 1,
                mk(
                    bands_hbm.at[pl.ds(k, k)],
                    scratch.at[s, pl.ds(k + tile, k)],
                    sems.at[s, 2],
                ),
            ),
            (
                j < nt - 1,
                mk(
                    blk_hbm.at[pl.ds(bot_blk, k)],
                    scratch.at[s, pl.ds(k + tile, k)],
                    sems.at[s, 2],
                ),
            ),
        )

    def for_each_seg(j, s, action):
        for pred, desc in segs(j, s):
            if pred is None:
                action(desc)
            else:
                @pl.when(pred)
                def _(d=desc):
                    action(d)

    def start_all(j, s):
        for_each_seg(j, s, lambda d: d.start())

    def wait_all(j, s):
        for_each_seg(j, s, lambda d: d.wait())

    @pl.when(i == 0)
    def _():
        start_all(i, slot)

    @pl.when(i + 1 < nt)
    def _():
        start_all(i + 1, 1 - slot)

    wait_all(i, slot)
    _evolve_window_and_store(
        scratch, slot, out_ref, edges_ref, tile, k, rule, groups
    )


def multi_step_pallas_packed_bands(
    blk_i32: jax.Array,
    bands_i32: jax.Array,
    tile: int,
    k: int,
    rule=None,
    edges_i32=None,
    groups: int = 1,
) -> jax.Array:
    """k fused generations of a packed block with a separate ghost band.

    ``blk_i32[h, W/32]`` is the shard's own rows; ``bands_i32[2k, W/32]``
    stacks the k-row top and bottom ghost bands a ring exchange produced
    (fresh, same traced program).  Columns wrap locally; ``edges_i32``
    follows the :func:`multi_step_pallas_packed_ext` contract.  Returns
    the updated ``[h, W/32]``.
    """
    if k < 1 or k % _ALIGN:
        raise ValueError(
            f"banded kernel needs k to be a positive multiple of "
            f"{_ALIGN}, got {k}"
        )
    height, nw = blk_i32.shape
    if bands_i32.shape != (2 * k, nw):
        raise ValueError(
            f"bands must be [2k, nw] = {(2 * k, nw)}, got {bands_i32.shape}"
        )
    validate_tile(height, tile, _ALIGN)
    if tile < k:
        # An interior tile's k-row halo segments come from adjacent block
        # rows in ONE descriptor each; with tile < k the segment would
        # span more than one neighboring tile and the in-bounds clamps
        # would silently fetch the wrong rows.  Callers with tile < k use
        # the pre-extended kernel (multi_step_pallas_packed_ext) instead.
        raise ValueError(
            f"banded kernel needs tile ({tile}) >= band depth k ({k})"
        )
    if height < tile + k:
        # A single-tile block still needs k rows below the body for the
        # bot_blk descriptor's clamped source to stay in bounds; with
        # height == tile that descriptor is never started (j == nt-1) but
        # must still describe valid memory (tile >= k above keeps its
        # clamped start non-negative).
        if height != tile:
            raise ValueError(
                f"banded kernel needs block height {height} >= tile + k"
            )
    in_specs = [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]
    operands = [blk_i32, bands_i32]
    _validate_groups(groups, nw)
    if edges_i32 is not None:
        _validate_edges(edges_i32, height, nw, groups)
        in_specs.append(
            pl.BlockSpec(
                (tile, edges_i32.shape[1]),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        )
        operands.append(edges_i32)
    return pl.pallas_call(
        functools.partial(
            _kernel_ext_bands, tile=tile, k=k, rule=rule, groups=groups
        ),
        grid=(height // tile,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (tile, nw), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((height, nw), blk_i32.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, tile + 2 * k, nw), blk_i32.dtype),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(*operands)


# Benchmarked sweet spot on v5e at 16384² (see module docstring): deeper
# blocks win until the recomputed halo bands (~2k²/tile extra rows per k
# steps) eat the launch/HBM savings.  Round 3 re-measured at the
# RPC-amortized x10240 loop length: k=8 at tile 256 runs ~2.5% ahead of
# k=16 (1.87 vs 1.82e12 same-session sweep) — exactly the roofline's
# recompute-factor gap (1.035 vs 1.066); the deeper block's saved
# launches no longer pay once the loop is long enough to amortize them.
# Round 4 negative: tile 512 at k=8 (recompute x1.017) measures ~2.5%
# *behind* tile 256 (1.82 vs 1.87e12, interleaved best-of-5) — the
# larger window loses more to scheduling/DMA than the halved band
# recompute saves, so the cap stays.
_BLOCK = 8
_BLOCK_TILE = 256


def _pick_block(
    steps: int, tile: int, block: int = _BLOCK, align: int = _ALIGN
) -> int:
    """Largest supported temporal depth <= ``block`` for this tile.

    Shared with the 3-D kernel (which passes its own cap and alignment)."""
    k = min(block, steps, tile)
    while k > 1 and -(-k // align) * align > tile:
        k -= 1
    return max(1, k)


def blocking_plan(
    height: int, packed_width: int, steps: int, tile_hint: int
) -> tuple:
    """(tile, k) exactly as :func:`evolve` runs them — shared with the
    roofline attribution (utils/roofline.py) so the reported
    configuration cannot drift from the executed one."""
    cap = min(tile_hint, _BLOCK_TILE) if steps > 1 else tile_hint
    tile = pick_tile(height, packed_width, cap)
    return tile, _pick_block(steps, tile)


@functools.partial(jax.jit, static_argnums=(1, 2, 3), donate_argnums=(0,))
def evolve(
    board: jax.Array, steps: int, tile_hint: int = 512, rule=None
) -> jax.Array:
    """Dense uint8 in/out; pack, evolve fused-packed, unpack — one program.

    Generations run in temporally-blocked groups of up to ``_BLOCK`` per
    kernel launch (full groups first, then one remainder launch), cutting
    kernel launches and HBM round trips ~k-fold.
    """
    nw = bitlife.packed_width(board.shape[1])
    if jax.default_backend() == "tpu" and nw % _LANE != 0:
        raise ValueError(
            "pallas bitpack engine needs the packed width to fill whole "
            f"{_LANE}-lane tiles on TPU: board width must be a multiple of "
            f"{_LANE * bitlife.BITS}, got {board.shape[1]}"
        )
    packed = bitlife.pack(board)
    packed_i32 = lax.bitcast_convert_type(packed, jnp.int32)
    height = packed_i32.shape[0]
    # The blocked path prefers its own (smaller) tile: the k-deep scratch
    # plus temporaries must still fit VMEM.  Single-step runs keep the
    # caller's full hint — no pad, no reason to halve the tile.
    tile, k = blocking_plan(height, nw, steps, tile_hint)
    full, rem = divmod(steps, k)
    packed_i32 = lax.fori_loop(
        0,
        full,
        lambda _, p: multi_step_pallas_packed(p, tile, k, rule),
        packed_i32,
    )
    if rem:
        packed_i32 = multi_step_pallas_packed(packed_i32, tile, rem, rule)
    return bitlife.unpack(lax.bitcast_convert_type(packed_i32, jnp.uint32))
