"""In-graph chunk statistics: what the simulation *did*, not how fast.

The reference's only view of the evolving world is a full dump after the
run (``gol_printWorld``, gol-main.c:17-28); our telemetry (PR 2) added
per-chunk *timings* but still says nothing about the board without a
device→host grid pull.  This module owns the device-side reductions the
``--stats`` mode fuses onto each chunk program:

- **population** — live cells of the chunk-end board;
- **births / deaths / changed** — cells that flipped 0→1 / 1→0 across
  the whole chunk (``changed = births + deaths``), computed from the
  chunk-start board the compiled program still holds — the extinction /
  all-static-fixpoint watchdog inputs;
- **face_top/bottom/left/right** — live cells in the four boundary
  bands of depth ``band`` (what the next halo exchange ships), the
  boundary-flux signal for sharded runs.

Two tiers, mutually bit-equal (pinned by tests/test_stats.py):

- :func:`dense_chunk_stats` — plain ``jnp.sum`` reductions on the uint8
  board (the dense and Pallas-dense engines).
- :func:`packed_chunk_stats` — popcount-based: the boards are packed 32
  cells/word (:func:`gol_tpu.ops.bitlife.pack`) and every reduction runs
  ``lax.population_count`` over uint32 words, so the reduce tree sees
  1/32nd the elements and the flip planes (``new & ~prev``) are single
  bitwise ops — the bitpacked/folded tiers' native idiom.

Overflow discipline: scalars travel as **uint32 split accumulators**
``[hi, lo]`` with ``value = (hi << 16) + lo`` (:func:`pair_value`),
because jnp has no uint64 without the global x64 switch and a single
uint32 population wraps exactly at the 65536² whole-board scale in
BASELINE.md.  Row partial sums are exact for any width < 2³²; the split
accumulation is exact while ``rows ≤ 65536`` — one bound past every
geometry the repo runs, documented here so nobody "simplifies" it back
to one word.

Everything here is pure jnp/lax on device values — no host callbacks,
no collectives (the psum wiring for sharded runs lives in
:mod:`gol_tpu.parallel.stats`); the analysis suite's stats-purity check
traces these programs to prove it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from gol_tpu.ops import bitlife

# Scalar names of one chunk's stats, in emission order.  ``face_*`` are
# the four boundary bands; 3-D volumes report the first four only.
STATS_FIELDS = (
    "population",
    "births",
    "deaths",
    "changed",
    "face_top",
    "face_bottom",
    "face_left",
    "face_right",
)

_LO16 = np.uint32(0xFFFF)


def sum_pair(partials: jax.Array) -> jax.Array:
    """uint32 partial sums -> ``uint32[2]`` split accumulator [hi, lo].

    Exact while the partial count stays ≤ 2¹⁶ (see module docstring);
    reassembled by :func:`pair_value` on host or added pairwise on
    device (psum of pairs is a pair — carries resolve at reassembly).
    """
    partials = partials.astype(jnp.uint32).ravel()
    hi = jnp.sum(partials >> 16, dtype=jnp.uint32)
    lo = jnp.sum(partials & _LO16, dtype=jnp.uint32)
    return jnp.stack([hi, lo])


def pair_value(pair) -> int:
    """Host-side reassembly of a split accumulator (exact Python int)."""
    arr = np.asarray(pair, dtype=np.uint64)
    return (int(arr[0]) << 16) + int(arr[1])


def stats_values(stats: dict) -> dict:
    """Device stats dict (field -> uint32[2]) to plain Python ints."""
    return {k: pair_value(v) for k, v in stats.items()}


def _clamp_band(band: int, h: int, w: int) -> int:
    return max(1, min(band, h, w))


def flip_planes_dense(prev: jax.Array, new: jax.Array, n=None):
    """``(flips, born, died)`` uint32 planes of a dense chunk diff.

    The one place the dense flip algebra lives: the ``--stats`` reducers
    below and the activity tier's changed-tile mask
    (:func:`gol_tpu.sparse.mask.changed_tiles_dense`) both consume these
    planes, so the mask is a *byproduct* of the same expressions the
    stats already emit — not a second, divergent diff pass.  The ops are
    exactly the pre-refactor inline forms (the stats-on jaxpr identity
    is pinned by tests/test_sparse.py::test_stats_refactor_jaxpr_identical);
    ``n`` lets a caller that already widened ``new`` reuse that value so
    the emitted eqn sequence stays what the inline form produced.
    """
    if n is None:
        n = new.astype(jnp.uint32)
    flips = (prev ^ new).astype(jnp.uint32)
    born = flips * n  # changed and now alive
    died = flips - born
    return flips, born, died


def flip_planes_packed(p: jax.Array, n: jax.Array):
    """``(born, died)`` word planes of a packed chunk diff (see
    :func:`flip_planes_dense`; ``changed = born | died``).  ``p``/``n``
    are :func:`gol_tpu.ops.bitlife.pack`-layout uint32 boards."""
    born = n & ~p
    died = p & ~n
    return born, died


def dense_chunk_stats(prev: jax.Array, new: jax.Array, band: int) -> dict:
    """Chunk stats of a dense uint8 0/1 board pair (shard-local).

    ``prev`` is the chunk-start board, ``new`` the chunk-end board; both
    are values the compiled chunk program already holds, so the
    reductions fuse into it with no extra HBM round trip beyond keeping
    ``prev`` live (the one cost of ``--stats``: the chunk-start buffer
    cannot be donated to the evolution).
    """
    h, w = new.shape
    band = _clamp_band(band, h, w)
    n = new.astype(jnp.uint32)
    flips, born, died = flip_planes_dense(prev, new, n)

    def rows(x):
        return jnp.sum(x, axis=1, dtype=jnp.uint32)

    return {
        "population": sum_pair(rows(n)),
        "births": sum_pair(rows(born)),
        "deaths": sum_pair(rows(died)),
        "changed": sum_pair(rows(flips)),
        "face_top": sum_pair(rows(n[:band])),
        "face_bottom": sum_pair(rows(n[-band:])),
        "face_left": sum_pair(rows(n[:, :band])),
        "face_right": sum_pair(rows(n[:, -band:])),
    }


def _col_band_masks(nw: int, band: int):
    """uint32[nw] word masks selecting the left / right ``band`` columns.

    Bit j of word k is column ``32k + j`` (the :func:`bitlife.pack`
    layout), so the left band is the low bits of the leading words and
    the right band the high bits of the trailing ones.
    """
    left = np.zeros(nw, np.uint32)
    right = np.zeros(nw, np.uint32)
    full, rem = divmod(band, bitlife.BITS)
    left[:full] = np.uint32(0xFFFFFFFF)
    right[nw - full :] = np.uint32(0xFFFFFFFF)
    if rem:
        left[full] = np.uint32((1 << rem) - 1)
        right[nw - full - 1] = np.uint32(((1 << rem) - 1) << (bitlife.BITS - rem))
    return left, right


def packed_chunk_stats(prev: jax.Array, new: jax.Array, band: int) -> dict:
    """Popcount-based chunk stats for the bitpacked/folded tiers.

    Same contract and bit-identical values as :func:`dense_chunk_stats`
    (pinned by the tier-equality test); the boards are packed once and
    every count is ``lax.population_count`` over uint32 words, so the
    flip planes are single bitwise ops and the reduce tree is 32×
    shorter than the dense one.
    """
    h, w = new.shape
    band = _clamp_band(band, h, w)
    p = bitlife.pack(prev)
    n = bitlife.pack(new)
    born, died = flip_planes_packed(p, n)
    left_mask, right_mask = _col_band_masks(n.shape[1], band)

    def rows(words):
        return jnp.sum(
            lax.population_count(words).astype(jnp.uint32),
            axis=1,
            dtype=jnp.uint32,
        )

    return {
        "population": sum_pair(rows(n)),
        "births": sum_pair(rows(born)),
        "deaths": sum_pair(rows(died)),
        "changed": sum_pair(rows(born | died)),
        "face_top": sum_pair(rows(n[:band])),
        "face_bottom": sum_pair(rows(n[-band:])),
        "face_left": sum_pair(rows(n & left_mask[None, :])),
        "face_right": sum_pair(rows(n & right_mask[None, :])),
    }


_POPCOUNT8 = np.unpackbits(
    np.arange(256, dtype=np.uint8)[:, None], axis=1
).sum(axis=1).astype(np.uint32)


def popcount_words_np(words: np.ndarray) -> int:
    """Host-side popcount of a packed uint32 array (byte LUT) — the
    numpy twin of the ``lax.population_count`` reductions above, for
    boards that must never materialize on device (the OOC tier)."""
    return int(_POPCOUNT8[np.asarray(words).view(np.uint8)].sum(dtype=np.uint64))


def ooc_chunk_stats_np(
    prev: np.ndarray, new: np.ndarray, bands, width: int, band: int
) -> dict:
    """Fold per-band host-side partials into one chunk-stats dict.

    The OOC tier's ``--stats`` path: ``prev``/``new`` are the chunk-start
    and chunk-end *host* boards in the packed :func:`bitlife.pack`
    layout, ``bands`` the plan's ``(row_start, row_end)`` list.  Each
    band contributes an exact partial per field (flip planes are the
    same single bitwise ops as :func:`flip_planes_packed`; face bands
    intersect the band's row range); partials fold by integer addition,
    so the result is bit-identical to :func:`packed_chunk_stats` on the
    whole board (pinned by tests/test_ooc.py) without any device
    round-trip or split-accumulator bound — host ints are exact.
    Returns plain Python ints keyed by :data:`STATS_FIELDS`.
    """
    h = prev.shape[0]
    band = _clamp_band(band, h, width)
    left_mask, right_mask = _col_band_masks(prev.shape[1], band)
    totals = {f: 0 for f in STATS_FIELDS}
    for r0, r1 in bands:
        p, n = prev[r0:r1], new[r0:r1]
        born = n & ~p
        died = p & ~n
        totals["population"] += popcount_words_np(n)
        totals["births"] += popcount_words_np(born)
        totals["deaths"] += popcount_words_np(died)
        totals["changed"] += popcount_words_np(born | died)
        top_take = max(0, min(r1, band) - r0)
        if top_take:
            totals["face_top"] += popcount_words_np(n[:top_take])
        bot_lo = max(r0, h - band)
        if bot_lo < r1:
            totals["face_bottom"] += popcount_words_np(n[bot_lo - r0:])
        totals["face_left"] += popcount_words_np(n & left_mask[None, :])
        totals["face_right"] += popcount_words_np(n & right_mask[None, :])
    return totals


def dense_chunk_stats3d(prev: jax.Array, new: jax.Array) -> dict:
    """3-D volume counterpart (population/births/deaths/changed only —
    a volume has six faces and no driver consumes per-face flux yet).
    Per-plane uint32 partials (each < 2³²: a plane has size² cells) feed
    the same split accumulators."""
    n = new.astype(jnp.uint32)
    flips = (prev ^ new).astype(jnp.uint32)
    born = flips * n
    died = flips - born

    def planes(x):
        return jnp.sum(x, axis=(1, 2), dtype=jnp.uint32)

    return {
        "population": sum_pair(planes(n)),
        "births": sum_pair(planes(born)),
        "deaths": sum_pair(planes(died)),
        "changed": sum_pair(planes(flips)),
    }
