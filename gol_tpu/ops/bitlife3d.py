"""Bit-packed 3-D Life: 32 cells per uint32 word, any totalistic rule.

The performance tier for BASELINE.md config 5 (1024³ volumes): the dense
uint8 path of :mod:`gol_tpu.ops.life3d` moves 8× more HBM bytes than the
state needs, and 3-D stencils are even more bandwidth-hungry than 2-D
(27-point vs 9-point).  Volumes pack along the x axis exactly like 2-D
boards (:func:`gol_tpu.ops.bitlife.pack` semantics), and the 26-neighbor
count is built entirely from bit-plane adders:

1. per (d, h) row: the 3-cell x-sum as 2 planes — one full adder
   (:func:`bitlife._row_hsum`, torus) or the word-halo variant;
2. per (d) plane: three 2-bit row sums -> the 4-plane count-of-9 column
   sum (:func:`bitlife._sum3_2bit`, shared with the 2-D rule);
3. across planes: three 4-bit column sums -> the 5-plane count-of-27 via a
   carry-save layer + one ripple add; subtract the center bit with a
   borrow ripple for the count of 26 neighbors.

3-D rules are parameters (:class:`gol_tpu.ops.life3d.Rule3D` — there is no
canonical 3-D Conway), so the update is a bit-plane *matcher*: for each
count in the birth/survive sets, AND together the five planes or their
complements according to the count's bits, then OR the matches — still
branchless, still 32 cells per VPU op.  The same matcher powers the 2-D
generalized-rule engine (:mod:`gol_tpu.ops.rules`); only the default 2-D
path (:mod:`gol_tpu.ops.bitlife`) hard-wires B3/S23, mirroring the
reference's kernel (gol-with-cuda.cu:239-257).

~3 bitwise ops/cell per generation vs ~13 byte-wide ops/cell dense, at
1/8th the HBM traffic.  Measured on one v5e chip via the XLA lowering:
3.4e10 cell-updates/s at 512³ (~3× dense), 5.6e10 at 1024³ — XLA
materializes the plane temporaries between fusions, which the fused
kernel (:mod:`gol_tpu.ops.pallas_bitlife3d`) avoids where its plane
window fits VMEM.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from gol_tpu.models.state import CELL_DTYPE
from gol_tpu.ops import bitlife
from gol_tpu.ops.life3d import BAYS_4555, Rule3D

Planes = Tuple[jax.Array, ...]


def pack3d(vol: jax.Array) -> jax.Array:
    """uint8[D, H, W] 0/1 volume -> uint32[D, H, W//32] (x-axis packed)."""
    d, h, w = vol.shape
    nw = bitlife.packed_width(w)
    return bitlife.pack(vol.reshape(d * h, w)).reshape(d, h, nw)


def unpack3d(packed: jax.Array) -> jax.Array:
    """Inverse of :func:`pack3d`."""
    d, h, nw = packed.shape
    return bitlife.unpack(packed.reshape(d * h, nw)).reshape(
        d, h, nw * bitlife.BITS
    )


def _sum3_planes(a: Planes, b: Planes, c: Planes, width: int) -> Planes:
    """Bit-plane sum of three equal-width numbers, ``width`` output planes.

    One carry-save layer (a full adder per input plane) reduces the three
    numbers to two, then a ripple-carry add combines them.  All planes are
    packed words; every op advances 32 cells.
    """
    zero = jnp.zeros_like(a[0])
    sums, carries = [], [zero]  # carries are worth 2x: offset by one plane
    for ai, bi, ci in zip(a, b, c):
        s, cy = bitlife._full_add(ai, bi, ci)
        sums.append(s)
        carries.append(cy)
    out = []
    borrow = zero  # ripple carry between the two reduced numbers
    for i in range(width):
        ai = sums[i] if i < len(sums) else zero
        bi = carries[i] if i < len(carries) else zero
        s, borrow = bitlife._full_add(ai, bi, borrow)
        out.append(s)
    return tuple(out)


# Bit-plane subtraction / count matching live in bitlife (shared with the
# generalized-rule 2-D engine).
_sub_bit = bitlife._sub_bit
_match_counts = bitlife._match_counts


def _rule_packed(center: jax.Array, count26: Planes, rule: Rule3D) -> jax.Array:
    """Totalistic update on packed words: born where dead, kept where alive."""
    born = _match_counts(count26, rule.birth)
    keep = _match_counts(count26, rule.survive)
    return (~center & born) | (center & keep)


def step3d_packed(packed: jax.Array, rule: Rule3D = BAYS_4555) -> jax.Array:
    """One generation on a fully periodic packed volume uint32[D, H, W//32].

    The x stage wraps via the packed word ring (bitlife._west_east); the
    h and d stages reuse each stage's bit-planes through torus rolls, so
    every sum is computed exactly once per row/plane.
    """
    s = bitlife._row_hsum(packed)  # x: 2 planes per (d, h) row
    col9 = bitlife._sum3_2bit(
        tuple(jnp.roll(p, 1, axis=-2) for p in s),
        s,
        tuple(jnp.roll(p, -1, axis=-2) for p in s),
    )  # h: 4 planes, count-of-9 per (d, h)
    count27 = _sum3_planes(
        tuple(jnp.roll(p, 1, axis=-3) for p in col9),
        col9,
        tuple(jnp.roll(p, -1, axis=-3) for p in col9),
        width=5,
    )  # d: 5 planes, count-of-27
    return _rule_packed(packed, _sub_bit(count27, packed), rule)


def step3d_packed_halo_full(
    ext: jax.Array, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """One generation given a fully halo-extended packed volume.

    ``ext[d+2, h+2, nw+2]`` carries one ghost plane/row on each volume face
    and one ghost *word* column along x (edge and corner words included) —
    the packed analog of :func:`gol_tpu.ops.life3d.step3d_halo_full`.  No
    wrap is applied; the halo shell carries all periodicity.  Shrinks by
    one layer per axis, so it composes with depth-k
    :func:`gol_tpu.parallel.halo.halo_extend` for temporal blocking.
    """
    s = bitlife._row_hsum_ext(ext)  # x: planes [d+2, h+2, nw]
    col9 = bitlife._sum3_2bit(
        tuple(p[:, :-2] for p in s),
        tuple(p[:, 1:-1] for p in s),
        tuple(p[:, 2:] for p in s),
    )  # h: planes [d+2, h, nw]
    count27 = _sum3_planes(
        tuple(p[:-2] for p in col9),
        tuple(p[1:-1] for p in col9),
        tuple(p[2:] for p in col9),
        width=5,
    )  # d: planes [d, h, nw]
    center = ext[1:-1, 1:-1, 1:-1]
    return _rule_packed(center, _sub_bit(count27, center), rule)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def run3d_packed(
    packed: jax.Array, steps: int, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """Evolve a packed 3-torus volume ``steps`` gens in one compiled program."""
    return lax.fori_loop(0, steps, lambda _, p: step3d_packed(p, rule), packed)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def evolve3d_dense_io(
    vol: jax.Array, steps: int, rule: Rule3D = BAYS_4555
) -> jax.Array:
    """Dense uint8 in/out: pack, run packed, unpack — one compiled program."""
    if vol.dtype != CELL_DTYPE:
        vol = vol.astype(CELL_DTYPE)
    return unpack3d(run3d_packed(pack3d(vol), steps, rule))
