"""Hand-written Pallas TPU kernel: fused halo-load + count + rule.

The explicit-kernel tier of SURVEY §7 step 7 — the direct architectural
analog of the reference's ``__global__ gol_kernel``
(gol-with-cuda.cu:189-262) plus its launch configuration
(``threadsCount`` → our row-tile size, gol-main.c:52,
gol-with-cuda.cu:272-275), rebuilt for the TPU memory hierarchy instead of
SIMT:

- The board lives in HBM (``memory_space=ANY``); each grid step DMAs one
  row-tile *plus its two wrap halo rows* into a VMEM scratch buffer — the
  reference's ghost-row substitution (gol-with-cuda.cu:224-231) becomes
  two extra 1-row DMAs with mod-H source indices, so the row torus wrap is
  handled at load time and the compute is branch-free.
- Count + rule are fused over the VMEM tile on the VPU: a separable
  3-row/3-column sum (column wrap via lane rolls, the analog of
  gol-with-cuda.cu:210-211) and the branchless B3/S23 select
  (vs the if/else chain at gol-with-cuda.cu:239-257).

The XLA-stencil engine (:mod:`gol_tpu.ops.stencil`) usually matches this —
XLA fuses the roll-sums well — but the Pallas path pins down tiling and
VMEM residency explicitly, is the scaffold for kernel-level tuning, and is
where the CLI's ``threadsPerBlock`` argument gets a real meaning again.

Runs in interpreter mode automatically on non-TPU backends so the same
tests cover it everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

SUM_DTYPE = jnp.uint8  # neighbor counts fit (max 9)

# TPU tiling for 8-bit data is (32, 128): every DMA row offset must be a
# multiple of 32 or the transfer touches partial tiles (Mosaic's
# divisibility proof rejects some such cases outright; others have been
# observed to pass and rarely corrupt — keep everything 32-aligned).
_ALIGN = 32


def pick_tile(height: int, width: int, hint: int) -> int:
    """Largest divisor of ``height`` that is <= hint and fits VMEM.

    The validated replacement for the reference's unchecked
    ``blocksCount = W*H/threadsCount`` (gol-with-cuda.cu:272, bug B5).
    Per tile-row VMEM: uint8 scratch+out (~2B/cell) plus the widened
    int32 compute temporaries (~12B/cell across live values).
    """
    return _pick(height, width, hint, align=_ALIGN, bytes_per_row=16)


def _kernel(board_hbm, out_ref, scratch, sems, *, tile: int, height: int):
    i = pl.program_id(0)
    load_tile_with_halo(
        board_hbm, scratch, sems, i, tile=tile, height=height, align=_ALIGN
    )

    # Mosaic vector ops (roll in particular) need i32 lanes; the DMA'd
    # tile stays uint8 in VMEM (1 byte/cell of HBM traffic), compute
    # widens on the VPU.
    ext = scratch[_ALIGN - 1 : _ALIGN + tile + 1, :].astype(jnp.int32)
    width = ext.shape[1]
    rows3 = ext[:-2] + ext[1:-1] + ext[2:]  # [tile, W], vertical 3-sum
    west = pltpu.roll(rows3, 1, axis=1)  # column torus wrap
    east = pltpu.roll(rows3, width - 1, axis=1)  # roll by -1 (must be >= 0)
    center = ext[1:-1]
    neighbors = rows3 + west + east - center
    alive_next = (neighbors == 3) | ((center == 1) & (neighbors == 2))
    out_ref[:] = alive_next.astype(out_ref.dtype)


# Importing this module requires a jaxlib with Pallas/Mosaic; on one
# without it the ImportError propagates and the runtime reports the
# engine as unavailable (runtime._evolve_fn's guard).
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

from gol_tpu.ops.pallas_common import (  # noqa: E402
    load_tile_with_halo,
    pick_tile as _pick,
    validate_tile,
)


def step_pallas(board: jax.Array, tile: int) -> jax.Array:
    """One torus generation via the fused Pallas kernel."""
    height, width = board.shape
    validate_tile(height, tile, _ALIGN)
    grid = height // tile
    return pl.pallas_call(
        functools.partial(_kernel, tile=tile, height=height),
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(
            (tile, width), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(board.shape, board.dtype),
        scratch_shapes=[
            pltpu.VMEM((tile + 2 * _ALIGN, width), board.dtype),
            pltpu.SemaphoreType.DMA((3,)),
        ],
        interpret=jax.default_backend() != "tpu",
    )(board)


@functools.partial(jax.jit, static_argnums=(1, 2), donate_argnums=(0,))
def evolve(board: jax.Array, steps: int, tile_hint: int) -> jax.Array:
    """Evolve ``steps`` generations, whole loop in one compiled program.

    ``tile_hint`` is the CLI's ``threadsPerBlock``; it is clamped to a
    valid, VMEM-fitting divisor of the board height (fixing bug B5's
    silent no-op for out-of-range values).
    """
    tile = pick_tile(board.shape[0], board.shape[1], tile_hint)
    return lax.fori_loop(0, steps, lambda _, b: step_pallas(b, tile), board)
